"""Write-ahead mutation log + durable index store (ISSUE 7 tentpole).

Every ``extend``/``delete``/``compact`` on a :class:`DurableStore` is
logged BEFORE the in-memory mutation applies — the log-then-apply
discipline.  The mutations themselves (``neighbors.mutation``) are pure
deterministic functions of (index state, operands), so replaying the log
from a snapshot reproduces the live index bit-identically (values AND
ids); crash recovery is "latest valid snapshot + WAL tail", exactly the
classic database recipe, with the index pytree as the page store.

On-disk format (``wal.log``):

* file header: ``b"RTWL"`` + little-endian ``u32`` format version;
* records: ``u64 lsn | u32 crc32(payload) | u64 payload_len | payload``;
* payload: ``u32 jlen | json | <.npy stream per array>`` — json carries
  ``{"op", "arrays": [names...], "static": {...}}`` and the array
  streams follow in that order (``core.serialize.npy_bytes``).

LSNs are monotonic from 1.  The CRC + length prefix make torn tails
self-detecting: :func:`read_wal` stops at the first bad record and
reports the last good byte offset, so recovery can quarantine the torn
tail (copied aside, never parsed) and truncate.  Fsync policy is
group-commit: ``WalConfig.group_window_s`` bounds the durability lag —
0 (default) fsyncs every append, ``w > 0`` lets appends within ``w``
seconds share one fsync (higher mutation throughput, up to ``w`` seconds
of committed-to-page-cache records at risk on power loss; a clean
process crash loses nothing either way).

:class:`DurableStore` composes the log with crash-consistent snapshots
(``neighbors.serialize.save_index``: per-array CRC32s, write-to-temp +
fsync + atomic rename, manifest carrying the WAL LSN watermark) and
:func:`DurableStore.recover`: newest valid snapshot wins, corrupted ones
are quarantined and the previous good snapshot replays a longer tail.
``serve/faults.py`` sites (``wal_append``/``extend``/``snapshot``/
``rename``/``compact``) hook the exact crash windows the subprocess
driver in ``tests/test_durability.py`` exercises.
"""

from __future__ import annotations

import dataclasses
import io
import json
import os
import shutil
import struct
import time
import zlib
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..core.errors import expects
from ..core import lockdep, tracing
from ..core.serialize import (CorruptArtifact, deserialize_mdspan, fsync_dir,
                              npy_bytes)
from ..obs import spans as obs_spans
from .serialize import index_manifest, load_index, save_index, verify_index

__all__ = ["WalConfig", "WalRecord", "WriteAheadLog", "read_wal",
           "replay", "DurableStore"]

_MAGIC = b"RTWL"
_WAL_VERSION = 1
_FILE_HEADER = _MAGIC + struct.pack("<I", _WAL_VERSION)
_REC_HEADER = struct.Struct("<QIQ")  # lsn, crc32(payload), payload_len
_SNAP_PREFIX = "snap-"

#: mutation ops a record may carry (anything else fails replay loudly)
_OPS = ("extend", "delete", "compact")


@dataclasses.dataclass(frozen=True)
class WalConfig:
    """Durability knobs.

    ``group_window_s``: group-commit window — 0 fsyncs every append
    (no committed record is ever lost); ``w > 0`` amortizes fsyncs over
    all appends inside ``w`` seconds (bounded durability lag under power
    loss, nothing lost on a process crash).  ``retain_snapshots``: how
    many published snapshots :meth:`DurableStore.snapshot` keeps (older
    ones are pruned; ≥ 2 leaves a fallback when the newest is corrupt).
    """

    group_window_s: float = 0.0
    retain_snapshots: int = 2


@dataclasses.dataclass(frozen=True)
class WalRecord:
    """One decoded mutation record."""

    lsn: int
    op: str
    arrays: Dict[str, np.ndarray]
    static: Dict[str, Any]


def _encode_payload(op: str, arrays: Dict[str, Any],
                    static: Dict[str, Any]) -> bytes:
    names = sorted(arrays)
    j = json.dumps({"op": op, "arrays": names, "static": static},
                   sort_keys=True).encode()
    parts = [struct.pack("<I", len(j)), j]
    parts += [npy_bytes(arrays[name]) for name in names]
    return b"".join(parts)


def _decode_payload(lsn: int, payload: bytes) -> WalRecord:
    (jlen,) = struct.unpack_from("<I", payload)
    head = json.loads(payload[4:4 + jlen].decode())
    buf = io.BytesIO(payload[4 + jlen:])
    arrays = {name: deserialize_mdspan(buf) for name in head["arrays"]}
    return WalRecord(lsn, head["op"], arrays, head.get("static") or {})


class WriteAheadLog:
    """Append-only checksummed mutation log; thread-safe appends.

    Opening an existing log scans it (validating every CRC) to resume
    the LSN sequence; a torn/corrupt tail raises :class:`CorruptArtifact`
    — :meth:`DurableStore.recover` quarantines + truncates first, so a
    plain reopen never silently appends after garbage.

    Two locks split the append hot path from the durability wait:
    ``_lock`` covers the file write + LSN sequence (microseconds),
    ``_sync_lock`` serializes the fsync and its watermarks.  An append
    writes+flushes under ``_lock``, *releases it*, then settles
    durability via :meth:`_sync_to` — so while one thread waits on the
    disk, other appenders keep streaming into the page cache, and the
    ``_synced_lsn`` watermark lets one fsync retire every append that
    landed before it (group commit that actually amortizes under
    contention, not just under a timer)."""

    def __init__(self, path: str, config: Optional[WalConfig] = None, *,
                 clock=time.monotonic, _fsync=os.fsync) -> None:
        self.path = os.fspath(path)
        self.config = config or WalConfig()
        self._clock = clock
        self._fsync = _fsync
        # _lock: file writes + LSN; _sync_lock: fsync + its watermarks.
        # Order when nested (prune/close only): _lock -> _sync_lock.
        self._lock = lockdep.lock("WriteAheadLog._lock")
        self._sync_lock = lockdep.lock("WriteAheadLog._sync_lock")
        self._last_sync = float("-inf")  # guarded_by: _sync_lock
        self._synced_lsn = 0             # guarded_by: _sync_lock
        self.syncs = 0                   # guarded_by: _sync_lock
        fresh = not os.path.exists(self.path) \
            or os.path.getsize(self.path) == 0
        if fresh:
            self._lsn = 0  # guarded_by: _lock
            self._f = open(self.path, "ab")
            self._f.write(_FILE_HEADER)
            with self._sync_lock:
                self._sync_locked()
        else:
            records, good_end, problems = read_wal(self.path)
            if problems:
                raise CorruptArtifact(
                    f"{self.path}: torn/corrupt tail ({'; '.join(problems)})"
                    " — recover via DurableStore.recover, which quarantines"
                    " and truncates it")
            self._lsn = records[-1].lsn if records else 0
            self._synced_lsn = self._lsn  # on-disk records are the base
            self._f = open(self.path, "ab")

    @property
    def lsn(self) -> int:
        """LSN of the last appended record (0 = empty log)."""
        return self._lsn

    def append(self, op: str, arrays: Optional[Dict[str, Any]] = None,
               static: Optional[Dict[str, Any]] = None, *,
               defer_sync: bool = False) -> int:
        """Write one record and return its LSN.  The record is on disk
        (page cache) when this returns; it is *durable* per the group-
        commit policy (``WalConfig.group_window_s``).  ``defer_sync=True``
        skips the durability settle — the caller promises to call
        :meth:`commit` with the returned LSN after releasing its own
        locks (how :meth:`DurableStore._durable` keeps the fsync out of
        the store's critical section)."""
        expects(op in _OPS, f"unknown WAL op {op!r} ({_OPS})")
        payload = _encode_payload(op, arrays or {}, static or {})
        with self._lock:
            lsn = self._write(self._lsn + 1, payload)
        if not defer_sync:
            self._maybe_sync(lsn)
        return lsn

    def append_record(self, rec: "WalRecord", *,
                      defer_sync: bool = False) -> int:
        """Append an already-sequenced record (the replication apply
        path): ``rec.lsn`` must continue the local sequence; an empty log
        adopts it as the base (a standby bootstrapped from a snapshot at
        watermark W starts its log at W+1)."""
        expects(rec.op in _OPS, f"unknown WAL op {rec.op!r} ({_OPS})")
        payload = _encode_payload(rec.op, rec.arrays, rec.static)
        with self._lock:
            expects(self._lsn == 0 or rec.lsn == self._lsn + 1,
                    f"replicated lsn {rec.lsn} does not continue the "
                    f"local wal at {self._lsn}")
            lsn = self._write(rec.lsn, payload)
        if not defer_sync:
            self._maybe_sync(lsn)
        return lsn

    def _write(self, lsn: int, payload: bytes) -> int:
        # racelint: holds _lock
        self._f.write(_REC_HEADER.pack(lsn, zlib.crc32(payload),
                                       len(payload)))
        self._f.write(payload)
        self._f.flush()  # visible to the OS before _lock drops
        self._lsn = lsn
        return lsn

    def commit(self, lsn: int) -> None:
        """Settle durability for ``lsn`` per the group-commit policy —
        the deferred half of ``append(..., defer_sync=True)``.  Call it
        with no locks held: this is where the disk wait happens."""
        self._maybe_sync(lsn)

    def _maybe_sync(self, lsn: int) -> None:
        # _last_sync is read without _sync_lock: a stale read merely
        # shifts one fsync across the window boundary, and the settle
        # itself re-checks the watermark under _sync_lock
        w = self.config.group_window_s
        if w <= 0 or self._clock() - self._last_sync >= w:
            self._sync_to(lsn)

    def _sync_to(self, lsn: int) -> None:
        with self._sync_lock:
            if self._synced_lsn >= lsn:
                return  # a later append's fsync already covered us
            self._sync_locked()

    def prune(self, upto_lsn: int) -> int:
        """Atomically rewrite the log without records ``lsn <= upto_lsn``.
        The newest record is always retained so a later reopen can resume
        the LSN sequence from the file alone.  Returns the number of
        records discarded.  Callers own the safety floor —
        :meth:`DurableStore.prune_wal` clamps to the oldest retained
        snapshot watermark AND every registered follower's ack."""
        # maintenance path: both locks held for the whole rewrite —
        # appenders and fsyncs must not race a file swap
        with self._lock, self._sync_lock:
            self._sync_locked()
            records, _, problems = read_wal(self.path)
            if problems:
                raise CorruptArtifact(
                    f"{self.path}: refusing to prune a torn log "
                    f"({'; '.join(problems)})")
            upto = min(int(upto_lsn), self._lsn - 1)
            keep = [r for r in records if r.lsn > upto]
            dropped = len(records) - len(keep)
            if dropped <= 0:
                return 0
            tmp = f"{self.path}.prune-{os.getpid()}"
            with open(tmp, "wb") as f:
                f.write(_FILE_HEADER)
                for r in keep:
                    payload = _encode_payload(r.op, r.arrays, r.static)
                    f.write(_REC_HEADER.pack(r.lsn, zlib.crc32(payload),
                                             len(payload)))
                    f.write(payload)
                f.flush()
                self._fsync(f.fileno())  # racelint: disable=JX12 rare maintenance rewrite; the swap must be atomic w.r.t. appends, which never enter this path
            self._f.close()
            os.replace(tmp, self.path)
            fsync_dir(os.path.dirname(self.path) or ".")
            self._f = open(self.path, "ab")
            self._last_sync = self._clock()
            self._synced_lsn = self._lsn  # the rewrite is fully durable
            return dropped

    def _sync_locked(self) -> None:
        # racelint: holds _sync_lock
        # reading _lsn without _lock is deliberate: _write only advances
        # it AFTER the bytes are flushed to the OS, so any value read
        # here is covered by the fsync below — that is the group-commit
        # amortization (one disk wait retires every earlier append)
        target = self._lsn
        self._f.flush()
        self._fsync(self._f.fileno())  # racelint: disable=JX12 the fsync IS this path's job; it serializes on the dedicated _sync_lock while appends stream on under _lock
        self._last_sync = self._clock()
        self._synced_lsn = max(self._synced_lsn, target)
        self.syncs += 1

    def sync(self) -> None:
        """Force-fsync pending records (snapshot watermarks call this so
        the manifest never claims an LSN the disk doesn't hold).
        Unconditional: even a covered watermark re-settles, because the
        caller is about to write the LSN into a manifest."""
        with self._sync_lock:
            self._sync_locked()

    def close(self) -> None:
        with self._lock, self._sync_lock:
            if not self._f.closed:
                self._sync_locked()
                self._f.close()


def read_wal(path) -> Tuple[List[WalRecord], int, List[str]]:
    """Scan a WAL → ``(records, good_end, problems)``.

    ``records`` are every intact record in order; ``good_end`` is the
    byte offset just past the last intact record; ``problems`` is empty
    for a clean log and otherwise describes the torn/corrupt tail (bad
    magic, short header/payload, CRC mismatch, LSN discontinuity) —
    everything past ``good_end`` is garbage to quarantine + truncate."""
    path = os.fspath(path)
    with open(path, "rb") as f:
        blob = f.read()
    if blob[:len(_FILE_HEADER)] != _FILE_HEADER:
        return [], 0, [f"bad WAL header (want {_FILE_HEADER!r})"]
    records: List[WalRecord] = []
    off = len(_FILE_HEADER)
    problems: List[str] = []
    while off < len(blob):
        if off + _REC_HEADER.size > len(blob):
            problems.append(f"short record header at offset {off}")
            break
        lsn, crc, plen = _REC_HEADER.unpack_from(blob, off)
        start = off + _REC_HEADER.size
        payload = blob[start:start + plen]
        if len(payload) < plen:
            problems.append(f"short payload for lsn {lsn} at offset {off}")
            break
        if zlib.crc32(payload) != crc:
            problems.append(f"crc mismatch for lsn {lsn} at offset {off}")
            break
        if records:
            if lsn != records[-1].lsn + 1:
                problems.append(f"lsn discontinuity ({lsn}) at offset {off}")
                break
        elif lsn < 1:
            # the first record establishes the base: a pruned log starts
            # past 1, but lsn 0 is reserved for "empty"
            problems.append(f"bad base lsn ({lsn}) at offset {off}")
            break
        try:
            records.append(_decode_payload(lsn, payload))
        except Exception as exc:  # undecodable but checksummed: corrupt
            problems.append(f"undecodable payload for lsn {lsn}: {exc}")
            break
        off = start + plen
    # off never advances past the last intact record (breaks happen
    # before the advance), so it doubles as the truncation point
    return records, off, problems


def _apply(index, rec: WalRecord):
    """Apply one WAL record — the ONLY mutation path a DurableStore uses,
    live and during replay, so both are the same deterministic function."""
    from . import mutation

    if rec.op == "extend":
        return mutation.extend(
            index, rec.arrays["vectors"], rec.arrays.get("ids"),
            insert_chunk=int(rec.static.get("insert_chunk", 0)))
    if rec.op == "delete":
        return mutation.delete(index, rec.arrays["ids"],
                               id_space=int(rec.static.get("id_space", 0)))
    if rec.op == "compact":
        out = mutation.compact(index,
                               headroom=float(rec.static.get("headroom",
                                                             2.0)))
        n = int(rec.static.get("rewrap_bits", 0))
        if n:  # preserve the delete-headroom mask shape across compaction
            from ..core.bitset import Bitset
            from .mutation import Tombstoned

            out = Tombstoned(out, Bitset.create(n, True))
        return out
    raise CorruptArtifact(f"unknown WAL op {rec.op!r}")


def replay(index, records) -> Any:
    """Fold WAL records over ``index`` in LSN order.  Deterministic: the
    result is bit-identical to having applied the mutations live."""
    for rec in records:
        index = _apply(index, rec)
    return index


class DurableStore:
    """A mutable index + its durability machinery, rooted at one
    directory::

        root/wal.log            append-only mutation log
        root/snapshots/snap-<lsn>/   crash-consistent checkpoints
        root/quarantine/        corrupted artifacts, renamed aside

    Mutators are log-then-apply under one lock (the serve dispatch path
    never enters here — it reads registry generations).  ``faults`` is an
    optional ``serve.faults.FaultInjector`` whose ``wal_append`` /
    ``extend`` / ``snapshot`` / ``rename`` / ``compact`` sites bracket
    the crash windows; ``counters`` accumulates ``wal_appends`` /
    ``wal_replayed`` / ``quarantined_files`` / ``recoveries`` /
    ``snapshots`` and is mirrored into ``ServingMetrics`` when a server
    adopts the store (``SearchServer.recover``)."""

    def __init__(self, root, index=None, *,
                 config: Optional[WalConfig] = None, faults=None,
                 clock=time.monotonic, _fsync=os.fsync) -> None:
        self.root = os.fspath(root)
        self.snap_dir = os.path.join(self.root, "snapshots")
        self.quarantine_dir = os.path.join(self.root, "quarantine")
        for d in (self.root, self.snap_dir, self.quarantine_dir):
            os.makedirs(d, exist_ok=True)
        self.config = config or WalConfig()
        self.faults = faults
        self.index = index          # guarded_by: _lock
        self.counters: Dict[str, int] = {}  # guarded_by: _lock
        self.metrics = None  # ServingMetrics mirror once a server adopts us
        self.fence = None  # serve.replication.EpochFence once replicated
        # (lsn, op, arrays, static) hooks — invoked inside the commit
        # critical section so records enter the wire in LSN order
        self.on_commit: List[Any] = []  # called_under: _lock
        self._followers: Dict[str, int] = {}  # guarded_by: _follower_lock
        # followers get their own lock: the ack pump thread must be able
        # to record progress while a semi-sync commit holds _lock
        self._follower_lock = lockdep.lock("DurableStore._follower_lock")
        self._lock = lockdep.rlock("DurableStore._lock")
        self.wal = WriteAheadLog(os.path.join(self.root, "wal.log"),
                                 self.config, clock=clock, _fsync=_fsync)

    # -- bookkeeping --------------------------------------------------

    def _count(self, name: str, n: int = 1) -> None:
        # racelint: holds _lock  (construction-phase callers — recover,
        # follower ack bookkeeping — predate or sidestep sharing)
        self.counters[name] = self.counters.get(name, 0) + n
        if self.metrics is not None:
            self.metrics.count(name, n)

    def _fire(self, site: str, path: Optional[str] = None) -> None:
        if self.faults is not None:
            self.faults.fire(site, path=path)

    @property
    def wal_lsn(self) -> int:
        """Current WAL watermark (LSN of the last logged mutation)."""
        return self.wal.lsn

    # -- construction -------------------------------------------------

    @classmethod
    def create(cls, root, index, **kw) -> "DurableStore":
        """Initialize a fresh store: adopt ``index`` and publish the
        initial snapshot (the replay base — a WAL with no snapshot under
        it would be unreplayable)."""
        store = cls(root, index, **kw)
        store.snapshot()
        return store

    # -- durable mutators (log-then-apply) ----------------------------

    def extend(self, vectors, ids=None, *, insert_chunk: int = 0):
        """Durable insert: logged (and fsynced per policy) before the
        in-memory ``mutation.extend`` applies.  A crash after the append
        recovers WITH the insert (replayed); before it, without — never a
        half-applied state."""
        arrays = {"vectors": np.asarray(vectors)}
        static: Dict[str, Any] = {"insert_chunk": int(insert_chunk)}
        if ids is not None:
            arrays["ids"] = np.asarray(ids)
        return self._durable("extend", arrays, static, crash_site="extend")

    def delete(self, ids, *, id_space: int = 0):
        """Durable tombstone: same log-then-apply contract as
        :meth:`extend`."""
        return self._durable(
            "delete", {"ids": np.asarray(ids)},
            {"id_space": int(id_space)}, crash_site="extend")

    def compact(self, *, headroom: float = 2.0, rewrap: bool = True):
        """Durable compaction.  ``rewrap=True`` (the serving default)
        re-wraps the compacted index in a fresh all-live tombstone mask
        of the SAME bit width, so the searcher's mask operand keeps one
        shape across compactions (no recompile) and later deletes have
        their headroom back.  Logged first: a crash mid-compaction
        recovers to the old generation (append lost) or the new one
        (record replayed) — never a hybrid."""
        from .mutation import Tombstoned

        n_bits = self.index.keep.n_bits \
            if isinstance(self.index, Tombstoned) and rewrap else 0
        return self._durable(
            "compact", {},
            {"headroom": float(headroom), "rewrap_bits": int(n_bits)},
            crash_site="compact")

    def _durable(self, op, arrays, static, *, crash_site: str):
        """Log-then-apply under ``_lock``; the fsync settles AFTER the
        lock drops (``wal.commit``).  The write itself (page cache) and
        the in-memory apply stay atomic w.r.t. other mutators — LSN
        order is preserved — but the disk wait no longer serializes
        readers of the store lock behind the platter.  Power-loss
        durability is unchanged: ``_durable`` still returns only after
        the group-commit policy is settled for this LSN, and a *process*
        crash anywhere in between loses nothing (the bytes are in the
        OS page cache from the flush under the WAL lock)."""
        with self._lock, tracing.range("wal.durable(%s)", op):
            expects(self.index is not None, "store has no index (use "
                    "DurableStore.create or DurableStore.recover)")
            if self.fence is not None:  # a deposed primary must not write
                self.fence.check(crash_site, count=self._count)
            # corrupt-kind faults at this site byte-flip the existing log
            # (torn-tail drill); crash-kind ones lose the op entirely
            self._fire("wal_append", self.wal.path)
            lsn = self.wal.append(op, arrays, static, defer_sync=True)
            self._count("wal_appends")
            # crash here = committed but unapplied: replay restores it
            self._fire(crash_site)
            self.index = _apply(self.index, WalRecord(lsn, op, arrays,
                                                      static))
            for hook in self.on_commit:  # replication ship, in LSN order
                hook(lsn, op, arrays, static)
            out = self.index
        self.wal.commit(lsn)  # the disk wait, outside the store lock
        return out

    def apply_replicated(self, rec: WalRecord):
        """Standby-side ingest: append the primary's record at its
        ORIGINAL lsn, then apply it through the same :func:`_apply` fold
        every mutation takes — a promoted standby is bit-identical
        (values AND ids) to the primary by construction."""
        with self._lock, tracing.range("wal.apply_replicated(%s)", rec.op):
            expects(self.index is not None, "store has no index (use "
                    "DurableStore.create or DurableStore.recover)")
            self._fire("wal_append", self.wal.path)
            self.wal.append_record(rec, defer_sync=True)
            self._count("wal_appends")
            self._count("wal_replicated")
            self.index = _apply(self.index, rec)
            for hook in self.on_commit:  # chained replication fan-out
                hook(rec.lsn, rec.op, rec.arrays, rec.static)
            out = self.index
        self.wal.commit(rec.lsn)  # disk wait outside the store lock
        return out

    # -- follower watermarks (WAL retention floor) --------------------

    def register_follower(self, follower_id: str, ack_lsn: int = 0) -> None:
        """Track a replication follower's ack watermark:
        :meth:`prune_wal` never discards records past the slowest
        registered follower, so a catching-up standby is never
        stranded."""
        with self._follower_lock:
            self._followers[str(follower_id)] = max(
                int(ack_lsn), self._followers.get(str(follower_id), 0))

    def follower_acked(self, follower_id: str, lsn: int) -> None:
        """Advance a follower's durable watermark (monotonic)."""
        self.register_follower(follower_id, lsn)

    def drop_follower(self, follower_id: str) -> None:
        """Forget a decommissioned follower so it stops pinning the WAL."""
        with self._follower_lock:
            self._followers.pop(str(follower_id), None)

    def followers(self) -> Dict[str, int]:
        """Registered follower ack watermarks (snapshot copy)."""
        with self._follower_lock:
            return dict(self._followers)

    def follower_floor(self) -> Optional[int]:
        """Min ack watermark over registered followers (None if none)."""
        with self._follower_lock:
            return min(self._followers.values()) if self._followers else None

    def prune_wal(self) -> int:
        """Discard WAL records that are covered by BOTH the oldest
        retained snapshot (the local replay base) and every registered
        follower's ack watermark.  Returns the number of records
        dropped."""
        with self._lock:
            snaps = self.snapshots()
            expects(bool(snaps), "prune_wal needs a published snapshot "
                    "(the replay base)")
            floor = int(index_manifest(
                os.path.join(self.snap_dir, snaps[0])).get("wal_lsn", 0))
            follower = self.follower_floor()
            if follower is not None:
                floor = min(floor, follower)
            dropped = self.wal.prune(floor)
            if dropped:
                self._count("wal_pruned", dropped)
                obs_spans.recorder().event("wal.prune", dropped=dropped,
                                           floor=floor)
            return dropped

    # -- snapshots ----------------------------------------------------

    def snapshot(self) -> str:
        """Publish a crash-consistent checkpoint of the current index at
        the current WAL watermark.  Staged fully (checksummed + fsynced)
        in a temp directory, then one atomic rename — a crash at either
        armed site (``snapshot``: staged-but-unpublished; ``rename``:
        ditto) leaves the previous snapshot authoritative and recovery
        replays a longer WAL tail.  Prunes to
        ``WalConfig.retain_snapshots`` published snapshots."""
        with self._lock, tracing.range("wal.snapshot"):
            expects(self.index is not None, "store has no index")
            if self.fence is not None:  # deposed primaries publish nothing
                self.fence.check("snapshot", count=self._count)
            self.wal.sync()  # the manifest must never lead the disk
            lsn = self.wal.lsn
            final = os.path.join(self.snap_dir, f"{_SNAP_PREFIX}{lsn:020d}")
            tmp = f"{final}.tmp-{os.getpid()}"
            save_index(tmp, self.index, manifest={"wal_lsn": lsn},
                       atomic=False, fsync=True)
            self._fire("snapshot", tmp)
            self._fire("rename", final)
            if os.path.exists(final):  # re-snapshot at an unchanged lsn
                trash = f"{final}.old-{os.getpid()}"
                os.rename(final, trash)
                os.rename(tmp, final)
                shutil.rmtree(trash, ignore_errors=True)
            else:
                os.rename(tmp, final)
            fsync_dir(self.snap_dir)
            self._count("snapshots")
            self._prune_snapshots()
            return final

    def _prune_snapshots(self) -> None:
        keep = max(1, int(self.config.retain_snapshots))
        snaps = sorted(n for n in os.listdir(self.snap_dir)
                       if n.startswith(_SNAP_PREFIX) and "." not in n)
        for name in snaps[:-keep]:
            shutil.rmtree(os.path.join(self.snap_dir, name),
                          ignore_errors=True)

    def snapshots(self) -> List[str]:
        """Published snapshot directory names, oldest → newest."""
        return sorted(n for n in os.listdir(self.snap_dir)
                      if n.startswith(_SNAP_PREFIX) and "." not in n)

    # -- recovery -----------------------------------------------------

    def _quarantine(self, path: str, reason: str) -> None:
        base = os.path.basename(path)
        dest = os.path.join(self.quarantine_dir, base)
        i = 0
        while os.path.exists(dest):
            i += 1
            dest = os.path.join(self.quarantine_dir, f"{base}.{i}")
        os.rename(path, dest)
        with open(dest + ".reason", "w") as f:
            f.write(reason + "\n")
        self._count("quarantined_files")
        obs_spans.recorder().event("wal.quarantine", artifact=base,
                                   reason=reason)

    @classmethod
    def recover(cls, root, *, config: Optional[WalConfig] = None,
                faults=None, device: bool = True, clock=time.monotonic,
                _fsync=os.fsync) -> "DurableStore":
        """Restore a store after a crash: newest snapshot that passes
        ``verify_index`` wins (corrupted/incomplete ones are quarantined,
        never parsed), the WAL tail past its LSN watermark replays (a
        torn/corrupt tail is quarantined + truncated first), and the
        returned store is ready to mutate and snapshot again.  Raises
        :class:`CorruptArtifact` when no valid snapshot survives."""
        t_recover = obs_spans.recorder().clock_ns()
        self = cls.__new__(cls)
        self.root = os.fspath(root)
        self.snap_dir = os.path.join(self.root, "snapshots")
        self.quarantine_dir = os.path.join(self.root, "quarantine")
        for d in (self.root, self.snap_dir, self.quarantine_dir):
            os.makedirs(d, exist_ok=True)
        self.config = config or WalConfig()
        self.faults = faults
        self.index = None
        self.counters = {}
        self.metrics = None
        self.fence = None
        self.on_commit = []
        self._followers = {}
        self._follower_lock = lockdep.lock("DurableStore._follower_lock")
        self._lock = lockdep.rlock("DurableStore._lock")

        # 1) snapshots: quarantine strays (crashed-mid-publish temp dirs),
        #    then walk published ones newest-first until one verifies
        watermark = None
        for name in sorted(os.listdir(self.snap_dir)):
            if not name.startswith(_SNAP_PREFIX) or "." in name:
                self._quarantine(os.path.join(self.snap_dir, name),
                                 "incomplete snapshot (crash mid-publish)")
        for name in reversed(self.snapshots()):
            path = os.path.join(self.snap_dir, name)
            problems = verify_index(path)
            if problems:
                self._quarantine(path, "; ".join(problems))
                continue
            self.index = load_index(path, device=device)
            watermark = int(index_manifest(path).get("wal_lsn", 0))
            break
        if self.index is None:
            raise CorruptArtifact(
                f"{self.root}: no valid snapshot to recover from "
                f"(quarantined {self.counters.get('quarantined_files', 0)})")

        # 2) WAL: quarantine + truncate a torn tail, then replay past the
        #    snapshot watermark
        wal_path = os.path.join(self.root, "wal.log")
        if os.path.exists(wal_path) and os.path.getsize(wal_path) > 0:
            records, good_end, problems = read_wal(wal_path)
            if problems:
                tail_name = f"wal-tail-{good_end}.bin"
                dest = os.path.join(self.quarantine_dir, tail_name)
                with open(wal_path, "rb") as src, open(dest, "wb") as out:
                    src.seek(good_end)
                    shutil.copyfileobj(src, out)
                with open(dest + ".reason", "w") as f:
                    f.write("; ".join(problems) + "\n")
                self._count("quarantined_files")
                with open(wal_path, "r+b") as f:
                    f.truncate(good_end)
                    f.flush()
                    os.fsync(f.fileno())
            tail = [r for r in records if r.lsn > watermark]
            if tail and tail[0].lsn != watermark + 1:
                raise CorruptArtifact(
                    f"{wal_path}: WAL pruned past the snapshot watermark "
                    f"(first tail lsn {tail[0].lsn}, watermark {watermark})"
                    " — replay would silently skip mutations")
            self.index = replay(self.index, tail)
            self._count("wal_replayed", len(tail))
        self.wal = WriteAheadLog(wal_path, self.config, clock=clock,
                                 _fsync=_fsync)
        self._count("recoveries")
        rec = obs_spans.recorder()
        rec.record("wal.recover", t_recover, rec.clock_ns(),
                   replayed=self.counters.get("wal_replayed", 0),
                   quarantined=self.counters.get("quarantined_files", 0),
                   watermark=watermark)
        return self

    def close(self) -> None:
        self.wal.close()
