"""Mutable-index lifecycle: tombstone deletes, filtered views, compaction.

Production corpora mutate; the indexes here are built once.  This module
closes the gap without touching any search kernel:

* **insert** — the per-family ``extend()`` (ivf_flat/ivf_pq/ivf_rabitq)
  streams new rows through the slab-donating chunk step; :func:`extend`
  below adds a tombstone-preserving dispatch over the IVF families.
* **delete** — :func:`delete` records dead *source ids* in a
  ``core.Bitset`` keep-mask (True = live) and wraps the untouched index
  in a :class:`Tombstoned` view.  Every family's filtered-search path
  already consumes bitsets, so deletes cost one word-sized mask update —
  no slab rewrite, no recompile (the mask rides as a searcher operand of
  fixed shape).
* **compact** — :func:`compact` rewrites the slabs through the same
  device packer the chunked builder uses, dropping tombstoned/overfull
  rows and shrinking ``list_cap`` to the live maximum.

``Tombstoned`` is a pytree, so it serializes/shards like the index it
wraps.  The id space defaults to ``max stored id + 1``; serving loops
that interleave insert + delete should pass ``id_space=`` with headroom
so the mask keeps ONE shape across the whole lifecycle (a growing mask
is a new operand shape → a recompile).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core.bitset import Bitset
from ..core.errors import expects
from ._packing import (_max_source_id, as_keep_mask, host_rows, keep_lookup,
                       pack_lists)

__all__ = ["Tombstoned", "delete", "deleted_count", "extend", "search",
           "compact"]


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class Tombstoned:
    """An index plus its tombstone keep-mask (True = live source id).

    The wrapped ``index`` is never modified — deletes are O(mask) and a
    ``Tombstoned`` built from a live snapshot shares every slab with it.
    ``raft_tpu.serve`` unwraps this transparently (the mask becomes the
    searcher's shared prefilter operand)."""

    index: Any
    keep: Bitset

    @property
    def dim(self) -> int:
        return self.index.dim if hasattr(self.index, "dim") \
            else self.index.shape[1]

    @property
    def size(self) -> int:
        """Stored rows (tombstoned rows still occupy slots until
        :func:`compact`).  Brute databases are sized by rows — a raw
        array's ``.size`` attribute counts elements, not rows."""
        if getattr(self.index, "ndim", None) == 2:
            return int(self.index.shape[0])
        return int(self.index.size)


def _default_id_space(index) -> int:
    """The smallest keep-mask that covers every stored id."""
    ids = getattr(index, "ids", None)
    if ids is not None and getattr(ids, "ndim", 0) == 2:  # IVF slab ids
        return _max_source_id(ids) + 1
    if getattr(index, "ndim", None) == 2:  # brute database: row numbers
        return int(index.shape[0])
    expects(hasattr(index, "size"),
            "cannot infer an id space: expected an IVF index, a CagraIndex "
            "or a 2-D brute-force database")
    return int(index.size)  # cagra: positional row ids


def delete(index, ids, *, id_space: int = 0) -> Tombstoned:
    """Tombstone ``ids`` (source ids for IVF, row numbers for
    cagra/brute-force).  Returns a :class:`Tombstoned` view; compose
    freely — deleting from a ``Tombstoned`` accumulates into the same
    mask.  ``id_space`` fixes the mask size (serving: pick it once, with
    insert headroom, so the mask shape never changes); 0 infers the
    smallest cover.  Deleting an id twice is a no-op, not an error."""
    base, keep = (index.index, index.keep) if isinstance(index, Tombstoned) \
        else (index, None)
    idh = np.asarray(host_rows(ids), np.int64).reshape(-1)
    expects(idh.size >= 1, "no ids to delete")
    expects(int(idh.min()) >= 0, "ids must be >= 0 (−1 is the pad value)")
    if keep is None:
        keep = Bitset.create(int(id_space) or _default_id_space(base), True)
    elif id_space:
        expects(int(id_space) >= keep.n_bits,
                "id_space cannot shrink an existing tombstone mask")
        if int(id_space) > keep.n_bits:
            keep = keep.resize(int(id_space), True)
    expects(int(idh.max()) < keep.n_bits,
            f"id {int(idh.max())} outside id space {keep.n_bits} — pass "
            f"id_space= with headroom at the first delete")
    return Tombstoned(base, keep.set(jnp.asarray(idh, jnp.int32), False))


def deleted_count(t: Tombstoned) -> int:
    """Number of tombstoned ids (host int — one explicit transfer)."""
    return int(t.keep.n_bits - jax.device_get(t.keep.count()))  # jaxlint: disable=JX01 host-facing API scalar, not on the search path


def extend(index, new_vectors, new_ids=None, *, insert_chunk: int = 0):
    """Tombstone-preserving insert dispatch for the IVF families: extends
    the wrapped index and re-wraps with the same mask (grown — with live
    defaults — only if the new ids overflow it, which changes the mask
    shape; serving loops avoid that by sizing ``id_space`` up front)."""
    from . import ivf_flat, ivf_pq, ivf_rabitq

    base, keep = (index.index, index.keep) if isinstance(index, Tombstoned) \
        else (index, None)
    if isinstance(base, ivf_pq.IvfPqIndex):
        out = ivf_pq.extend(base, new_vectors, new_ids,
                            insert_chunk=insert_chunk)
    elif isinstance(base, ivf_rabitq.IvfRabitqIndex):
        out = ivf_rabitq.extend(base, new_vectors, new_ids,
                                insert_chunk=insert_chunk)
    else:
        expects(isinstance(base, ivf_flat.IvfFlatIndex),
                "online extend is an IVF-family operation (cagra/brute "
                "rebuild; see docs/mutability_guide.md)")
        out = ivf_flat.extend(base, new_vectors, new_ids,
                              insert_chunk=insert_chunk)
    if keep is None:
        return out
    top = _max_source_id(out.ids) + 1
    if top > keep.n_bits:
        keep = keep.resize(top, True)
    return Tombstoned(out, keep)


def _combined_keep(keep: Bitset, filter):
    """AND an extra caller filter into the tombstone mask (bool arrays —
    the per-call search path, not the fixed-operand serving path)."""
    if filter is None:
        return keep
    extra = as_keep_mask(filter)
    mask = keep.to_bool_array()
    expects(extra.shape[-1] == mask.shape[0],
            f"filter covers {extra.shape[-1]} ids, tombstone mask covers "
            f"{mask.shape[0]}")
    return extra & mask


def search(t: Tombstoned, queries, k: int, params=None, *, filter=None,
           **kw):
    """Family-dispatched search over a tombstoned view — deleted ids never
    appear in results (empty slots report id −1 / ±inf, the filtered-
    search contract).  An extra ``filter`` is ANDed with the mask."""
    from . import brute_force, cagra, ivf_flat, ivf_pq, ivf_rabitq

    expects(isinstance(t, Tombstoned), "search() takes a Tombstoned view")
    keep = _combined_keep(t.keep, filter)
    base = t.index
    if isinstance(base, ivf_flat.IvfFlatIndex):
        return ivf_flat.search(base, queries, k, params, filter=keep, **kw)
    if isinstance(base, ivf_pq.IvfPqIndex):
        return ivf_pq.search(base, queries, k, params, filter=keep, **kw)
    if isinstance(base, ivf_rabitq.IvfRabitqIndex):
        return ivf_rabitq.search(base, queries, k, params, filter=keep, **kw)
    if isinstance(base, cagra.CagraIndex):
        return cagra.search(base, queries, k, params, filter=keep, **kw)
    return brute_force.knn(queries, base, k, filter=keep, **kw)


def _compact_labels(ids, counts, cap: int, keep: Optional[Bitset]):
    """Per-slot destination list (its own list index) or −1 to drop: pad
    slots, −1 ids, and tombstoned ids all drop; survivors keep their slab
    order (``pack_lists``' stable sort preserves it)."""
    L = ids.shape[0]
    col = jnp.arange(cap, dtype=jnp.int32)[None, :]
    valid = (col < counts[:, None]) & (ids >= 0)
    if keep is not None:
        valid &= keep_lookup(as_keep_mask(keep), ids)
    labels = jnp.where(valid, jnp.arange(L, dtype=jnp.int32)[:, None], -1)
    return labels.reshape(-1), jnp.sum(valid, axis=1)


def compact(index, *, headroom: float = 2.0):
    """Rewrite an (optionally tombstoned) IVF index's slabs: drop dead
    rows, shrink ``list_cap`` to ``headroom ×`` the live per-list maximum
    (≥ the build-time ``list_cap_ratio`` default, so post-compact inserts
    have room).  Returns a PLAIN index — tombstones are consumed.  One
    device pass through the chunked builder's packer; derived IVF-PQ
    tiers (recon / ADC LUTs / 4-bit packing) are re-derived to match the
    input.

    A tombstoned **brute-force** database compacts too: dead rows drop
    into a fresh contiguous slab (ROADMAP item 5's reclaim story).  Brute
    ids are positional, so compaction renumbers survivors — new row ``i``
    is old row ``kept[i]`` with ``kept`` the sorted live row numbers
    (``headroom`` is meaningless, there are no lists).  Cagra has no slab
    to rewrite — rebuild it."""
    from . import ivf_flat, ivf_pq, ivf_rabitq

    base, keep = (index.index, index.keep) if isinstance(index, Tombstoned) \
        else (index, None)
    expects(headroom >= 1.0, "headroom must be >= 1.0")
    if getattr(base, "ndim", None) == 2:  # brute-force database
        if keep is None:
            return jnp.asarray(base)
        n = int(base.shape[0])
        # the kept-row gather index is a static shape: one explicit host
        # transfer per compaction, never on the search path
        mask = np.asarray(host_rows(keep.to_bool_array()))[:n]
        kept = np.flatnonzero(mask)
        expects(kept.size >= 1, "compact would drop every row")
        return jnp.asarray(base)[jnp.asarray(kept, jnp.int32)]
    is_pq = isinstance(base, ivf_pq.IvfPqIndex)
    is_rabitq = isinstance(base, ivf_rabitq.IvfRabitqIndex)
    expects(is_pq or is_rabitq or isinstance(base, ivf_flat.IvfFlatIndex),
            "compact is an IVF-family operation (plus tombstoned brute-"
            "force slabs): cagra stores rows positionally — rebuild it")
    was_packed = False
    if is_pq and base.packed:
        was_packed, base = True, base.with_unpacked_codes()
    L, cap = base.n_lists, base.list_cap
    labels, live = _compact_labels(base.ids, base.counts, cap, keep)
    # list_cap is a static slab shape: one explicit host transfer per
    # compaction, never on the search path
    new_cap = max(1, int(float(headroom) *
                         int(jax.device_get(jnp.max(live)))))  # jaxlint: disable=JX01 static slab shape: one explicit transfer per compaction, never on the search path
    if is_pq:
        flat = (base.codes.reshape(L * cap, -1),
                base.code_norms.reshape(L * cap),
                base.ids.reshape(L * cap))
        (codes, cnorms, ids), counts = pack_lists(
            labels, flat, n_lists=L, cap=new_cap, fills=(0, 0.0, -1))
        out = ivf_pq.IvfPqIndex(base.centroids, base.codebooks, codes,
                                cnorms, ids, counts, base.metric)
        if base.adc_norms is not None:
            out = out.with_adc_luts()
        if base.recon is not None:
            out = out.with_recon()
        return out.with_packed_codes() if was_packed else out
    if is_rabitq:
        # codes + correction scalars are per-row, centroid-relative — a
        # slot keeps them verbatim through the repack (no re-encode)
        flat = (base.codes.reshape(L * cap, -1),
                base.sabs.reshape(L * cap),
                base.res_norms.reshape(L * cap),
                base.code_cdots.reshape(L * cap),
                base.data.reshape(L * cap, -1),
                base.ids.reshape(L * cap))
        (codes, sabs, rn2, cs, data, ids), counts = pack_lists(
            labels, flat, n_lists=L, cap=new_cap,
            fills=(0, 0.0, 0.0, 0.0, 0.0, -1))
        return ivf_rabitq.IvfRabitqIndex(
            base.centroids, base.rotation,
            codes.reshape(L, new_cap, -1), sabs, rn2, cs,
            data.reshape(L, new_cap, base.dim), ids, counts, base.metric)
    flat = (base.data.reshape(L * cap, -1), base.ids.reshape(L * cap))
    (data, ids), counts = pack_lists(labels, flat, n_lists=L, cap=new_cap,
                                     fills=(0.0, -1))
    data = data.reshape(L, new_cap, base.dim)
    norms = jnp.sum(data.astype(jnp.float32) ** 2, axis=2)
    return ivf_flat.IvfFlatIndex(base.centroids, data, ids, counts, norms,
                                 base.metric)
