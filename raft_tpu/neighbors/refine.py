"""Exact candidate re-ranking — the cuVS ``refine`` stage.

Takes approximate candidates (e.g. IVF-PQ output oversampled at
``k·refine_ratio``) and recomputes exact distances against the original
dataset, returning the true top-k.  The gather of candidate vectors plus one
batched MXU dot is exactly how TPU-KNN (PAPERS.md) re-ranks, and it recovers
most of the recall PQ compression loses.
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp

from ..core.array import wrap_array
from ..core.errors import expects
from ..matrix.select_k import select_k

__all__ = ["refine"]


@partial(jax.jit, static_argnames=("k", "metric"))
def _refine_impl(dataset, queries, candidates, k: int, metric: str):
    nq, cand = candidates.shape
    safe = jnp.maximum(candidates, 0)
    vecs = dataset[safe]                          # [nq, cand, d]
    qf = queries.astype(jnp.float32)
    dots = jnp.einsum("qcd,qd->qc", vecs, qf,
                      preferred_element_type=jnp.float32,
                      precision=jax.lax.Precision.HIGHEST)
    if metric == "inner_product":
        dist = -dots
    else:
        from ..ops.blocked_scan import row_sq_norms

        vn = row_sq_norms(vecs.astype(jnp.float32))
        qn = row_sq_norms(qf)
        dist = jnp.maximum(vn - 2.0 * dots + qn[:, None], 0.0)
    dist = jnp.where(candidates >= 0, dist, jnp.inf)
    vals, idx = select_k(dist, k, in_idx=candidates, select_min=True)
    if metric == "euclidean":
        vals = jnp.sqrt(jnp.maximum(vals, 0.0))
    elif metric == "inner_product":
        vals = -vals
    return vals, idx


def refine(dataset, queries, candidates, k: int, *,
           metric: str = "sqeuclidean", res=None) -> Tuple[jax.Array, jax.Array]:
    """Re-rank ``candidates[nq, n_cand]`` (−1 = missing) with exact distances
    over ``dataset``; returns ``(distances, ids)`` of (nq, k)."""
    d = wrap_array(dataset, ndim=2, name="dataset")
    q = wrap_array(queries, ndim=2, name="queries")
    c = jnp.asarray(candidates, jnp.int32)
    expects(c.ndim == 2 and c.shape[0] == q.shape[0], "candidates shape mismatch")
    expects(k <= c.shape[1], "k exceeds candidate count")
    return _refine_impl(d, q, c, int(k), metric)
