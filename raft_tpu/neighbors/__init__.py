"""raft_tpu.neighbors — ANN index family (brute-force, IVF-Flat, IVF-PQ,
CAGRA), designed TPU-first from the north-star capability list
(``/root/repo/BASELINE.json``) and the TPU-KNN paper (``PAPERS.md``,
arXiv 2206.14286); the reference migrated these to cuVS so there is no
in-tree CUDA ancestor (SURVEY.md scope note).

Shared design rules:
* distance blocks ride the MXU (see ``raft_tpu.distance``),
* candidate selection is ``matrix.select_k``,
* index layouts are dense + padded (fixed list sizes / fixed graph degree) so
  search is static-shape and jit-compiles once,
* sharded (multi-chip) variants split the database over a mesh axis and merge
  per-shard top-k via ``all_gather`` — the moral equivalent of the
  reference's MNMG index shards over ``comms_t`` (SURVEY.md §5.7).
"""

from . import brute_force
from .brute_force import knn

__all__ = ["brute_force", "knn"]


def __getattr__(name):
    if name in ("ivf_flat", "ivf_pq", "ivf_rabitq", "ooc", "cagra",
                "refine", "serialize", "mutation", "wal", "health"):
        import importlib

        mod = importlib.import_module(f"raft_tpu.neighbors.{name}")
        globals()[name] = mod
        return mod
    if name in ("save_index", "load_index", "verify_index"):
        from . import serialize as _ser

        return getattr(_ser, name)
    raise AttributeError(f"module 'raft_tpu.neighbors' has no attribute {name!r}")
