"""CAGRA-class graph index — fixed-degree kNN graph + beam search.

No in-tree CUDA ancestor (cuVS migration); designed from the north-star
configs (``BASELINE.json``: cagra on DEEP-100M sharded) and the CAGRA idea
(build a high-quality fixed-out-degree proximity graph offline, search it
with a greedy multi-candidate descent).

TPU redesign:
* **Build**: a kNN graph (brute-force or IVF-sourced) is *optimized* by
  rank-based forward/reverse edge merging — every node keeps the
  best-ranked union of its out-edges and in-edges, deduplicated, truncated
  to ``graph_degree``.  This is the vectorizable core of CAGRA's
  detour-pruning heuristic: reverse edges give the connectivity the pruning
  step is after, rank interleaving approximates its edge ordering.  The
  whole optimization is numpy index arithmetic — no kernels.
* **Search**: breadth-limited greedy descent, frontier-blocked — per
  iteration the ``search_width`` best unexplored beam entries are expanded
  AS ONE BLOCK: a single [nq, width·deg] adjacency slab gather, one
  batch-dim MXU einsum (bit-invariant across width, the probe-block
  contract), a sorted-ring visited filter (beam ids kept sorted in the
  carry; membership is a ``searchsorted``, the XLA replacement for CAGRA's
  per-thread hash table), and one UNSORTED ``select_k`` fold — the single
  ranked selection happens at exit.  Converged queries become no-op lanes
  and the iteration cap is a device scalar, so one static-shape executable
  serves every iteration count ≤ the compiled scan length.  A per-parent
  reference engine (``search_impl="per_parent"``) is retained and pinned
  bit-identical.
* **Sharded**: database sharded over the mesh axis; each shard runs the same
  search program on its sub-graph and one ``all_gather`` + ``select_k``
  merges — identical pattern to IVF-Flat sharded (SURVEY.md §5.7).
"""

from __future__ import annotations

import dataclasses
from functools import lru_cache, partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from ..core import tracing
from ..core.array import wrap_array
from ..core.compat import shard_map
from ..core.errors import expects
from ..matrix.select_k import select_k
from ..utils.segment import within_group_rank as _within_group_rank

__all__ = [
    "CagraIndexParams",
    "CagraSearchParams",
    "CagraIndex",
    "build",
    "build_from_graph",
    "build_sharded",
    "extend",
    "optimize_graph",
    "refine_knn_graph",
    "resolved_search_params",
    "search",
    "searcher",
    "search_sharded",
    "ShardedCagraIndex",
]


@dataclasses.dataclass(frozen=True)
class CagraIndexParams:
    intermediate_graph_degree: int = 64
    graph_degree: int = 32
    metric: str = "sqeuclidean"
    build_algo: str = "brute_force"  # brute_force | ivf
    # entry-point table size (see _build_routers); 0 = auto ≈ 4·√n.  The
    # table must out-number the dataset's natural regions or recall caps
    # at the covered fraction REGARDLESS of search effort (a 300k-row
    # 300-cluster probe plateaued at 0.49 with 150 routers — beam search
    # can never enter an uncovered component)
    n_routers: int = 0
    seed: int = 0
    # accuracy of the intermediate kNN graph when build_algo="ivf": probes
    # per point during graph construction.  The optimize step can only
    # rank-merge edges the intermediate graph found, so this bounds final
    # recall at scale (build time grows ~linearly with it)
    build_n_probes: int = 16
    # NN-descent rounds over the intermediate graph before edge
    # optimization (0 = off): each round scores sampled
    # neighbors-of-neighbors and keeps the best edges by exact distance —
    # the cheap way to recover recall an approximate (IVF) build left out
    graph_refine_iters: int = 0


@dataclasses.dataclass(frozen=True)
class CagraSearchParams:
    itopk_size: int = 64      # beam width (internal top-k); 0 = auto (tuned table)
    search_width: int = 4     # parents expanded per iteration; 0 = auto
    max_iterations: int = 0   # 0 → auto from itopk/width
    n_seeds: int = 32         # random entry points
    # engine selector: "frontier" expands the whole frontier as one
    # [nq, width·deg] slab per iteration (production); "per_parent" is the
    # retained reference engine — same algorithm one parent at a time,
    # pinned bit-identical in tests/test_cagra_frontier.py
    search_impl: str = "frontier"


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class CagraIndex:
    dataset: jax.Array         # [n, d] — graph search recomputes exact distances
    graph: jax.Array           # [n, graph_degree] int32 adjacency
    router_centroids: jax.Array  # [R, d] coarse kmeans centroids
    router_nodes: jax.Array    # [R] nearest dataset node per centroid
    metric: str = dataclasses.field(metadata=dict(static=True))

    @property
    def size(self) -> int:
        return int(self.dataset.shape[0])

    @property
    def dim(self) -> int:
        return int(self.dataset.shape[1])

    @property
    def graph_degree(self) -> int:
        return int(self.graph.shape[1])


@partial(jax.jit, static_argnames=("graph_degree",))
def _optimize_graph_impl(knn_graph, graph_degree: int):
    """Device-side rank-merge graph optimization (see :func:`optimize_graph`).

    Phase 1 builds the rank-ordered *reverse* graph without a global edge
    sort: one pass per forward rank r scatters the in-edges arriving at
    that rank into each node's next free reverse slots (duplicate targets
    within a pass are serialized by a within-group rank).  Memory stays
    O(n·kk) — no 2·n·kk edge list, which at 10M×64 would be ~15 GB of
    sort working set.

    Phase 2 interleaves forward/reverse columns (rank 2r / 2r+1),
    deduplicates per row keeping the best rank, and compacts — all
    row-wise ops, chunked with ``lax.map`` so sorts never exceed a
    ~128k-row block.
    """
    n, kk = knn_graph.shape
    fwd = knn_graph.astype(jnp.int32)
    rev = _reverse_graph(fwd)  # phase 1 (shared with NN-descent)

    # phase 2: interleave, dedup (keep lowest rank), compact, truncate
    deg = graph_degree
    block = max(1, min(n, (1 << 24) // max(2 * kk, 1)))
    pad = (-n) % block

    def row_block(args):
        f, rv, base = args
        b = f.shape[0]
        self_id = base + jnp.arange(b, dtype=jnp.int32)
        f = jnp.where(f == self_id[:, None], -1, f)  # drop self-loops
        comb = jnp.stack([f, rv], axis=2).reshape(b, 2 * kk)
        pos = jnp.tile(jnp.arange(2 * kk, dtype=jnp.int32)[None, :], (b, 1))
        # stable sort by id keeps rank order within equal ids
        order = jnp.argsort(comb, axis=1, stable=True)
        i1 = jnp.take_along_axis(comb, order, axis=1)
        p1 = jnp.take_along_axis(pos, order, axis=1)
        dup = jnp.concatenate(
            [jnp.zeros((b, 1), bool), i1[:, 1:] == i1[:, :-1]], axis=1)
        keep = ~dup & (i1 >= 0)
        cnt = jnp.sum(keep.astype(jnp.int32), axis=1)
        # compact survivors back into rank order
        key = jnp.where(keep, p1, jnp.int32(2 * kk))
        order2 = jnp.argsort(key, axis=1, stable=True)
        ids = jnp.take_along_axis(i1, order2, axis=1)[:, :deg]
        # pad short rows cyclically with their own best edges (degenerate
        # rows with zero edges fall back to the node id itself)
        ccl = jnp.arange(deg, dtype=jnp.int32)[None, :] % jnp.maximum(
            jnp.minimum(cnt, deg), 1)[:, None]
        out = jnp.take_along_axis(ids, ccl, axis=1)
        return jnp.where(cnt[:, None] > 0, out, self_id[:, None])

    f_p = jnp.pad(fwd, ((0, pad), (0, 0)), constant_values=-1)
    r_p = jnp.pad(rev, ((0, pad), (0, 0)), constant_values=-1)
    bases = jnp.arange((n + pad) // block, dtype=jnp.int32) * block
    out = jax.lax.map(
        row_block,
        (f_p.reshape(-1, block, kk), r_p.reshape(-1, block, kk), bases),
    )
    return out.reshape(-1, deg)[:n]


def optimize_graph(knn_graph, graph_degree: int) -> jax.Array:
    """Rank-merge optimization: union of forward and reverse edges ordered by
    rank, deduplicated, truncated to ``graph_degree`` per node.

    Forward edge (u→v, rank r) contributes rank 2r and its reverse (v→u)
    rank 2r+1 — interleaving forward and reverse ranks like CAGRA's edge
    reordering.  Fully device-side (jitted segment ops + chunked row sorts;
    the r1 numpy/Python-loop version did not scale past ~10⁵ rows).
    """
    g = jnp.asarray(knn_graph)
    expects(g.ndim == 2, "knn_graph must be (n, k)")
    return _optimize_graph_impl(g, int(graph_degree))


@partial(jax.jit, static_argnames=())
def _reverse_graph(graph):
    """Per-node reverse edges ([n, kk], −1-padded, in arriving-rank order):
    u appears in row v when v ∈ graph[u].  One pass per forward rank
    scatters in-edges into each node's next free slots (duplicates within
    a pass serialized by a within-group rank; invalid edges rank in a
    spare group so they cannot inflate real positions).  Memory stays
    O(n·kk).  Shared by the graph optimizer's phase 1 and NN-descent."""
    n, kk = graph.shape
    src = jnp.arange(n, dtype=jnp.int32)

    def rev_step(r, carry):
        rev, rcount = carry
        dst = graph[:, r]
        ok_e = (dst != src) & (dst >= 0) & (dst < n)
        dst_safe = jnp.where(ok_e, dst, 0)
        pos = _within_group_rank(jnp.where(ok_e, dst_safe, n), src, n + 1)
        slot = rcount[dst_safe] + pos
        ok = ok_e & (slot < kk)
        dest = jnp.where(ok, dst_safe * kk + slot, n * kk)
        rev = rev.at[dest].set(src, mode="drop")
        rcount = rcount + jax.ops.segment_sum(
            ok.astype(jnp.int32), dst_safe, num_segments=n)
        return rev, rcount

    rev0 = jnp.full((n * kk,), -1, jnp.int32)
    rev, _ = jax.lax.fori_loop(
        0, kk, rev_step, (rev0, jnp.zeros((n,), jnp.int32)))
    return rev.reshape(n, kk)


@partial(jax.jit, static_argnames=("s", "block"))
def _nn_descent_round(x, graph, key, s: int, block: int):
    """One NN-descent round: every node scores ``s`` sampled candidates
    from the forward⋈reverse neighbor join against its current ``kk``
    edges and keeps the best ``kk`` by exact distance (ascending — the
    rank order ``optimize_graph`` expects).

    The classic kNN-graph improvement loop (NN-descent, Dong et al.;
    cuVS builds CAGRA graphs with it) recast for the MXU: candidate
    gathers + one batched einsum per row block, no per-node hash tables.
    The reverse half of the join is what makes it converge — a degraded
    edge is usually repaired by a node that LISTS you, not one you list.
    Row blocks bound peak memory at ``block·(kk+s)·d`` f32."""
    n, kk = graph.shape
    rev = _reverse_graph(graph)
    # unpopulated reverse slots fall back to the forward edge of the same
    # rank: every sampled (mid, cand) pair stays a real node pair instead
    # of a wasted −1 draw
    rev = jnp.where(rev < 0, graph, rev)
    comb = jnp.concatenate([graph, rev], axis=1)
    m2 = comb.shape[1]                                       # 2·kk
    kj, kr = jax.random.split(key)
    sj = max(1, s - s // 4)
    cols = jax.random.randint(kj, (n, sj), 0, m2 * m2)
    mid = jnp.take_along_axis(comb, cols // m2, axis=1)      # [n, sj]
    cand = comb[jnp.maximum(mid, 0), cols % m2]              # [n, sj]
    cand = jnp.where(mid < 0, -1, cand)
    # exploration term: a locally-consistent start (e.g. a 1-probe IVF
    # graph whose edges never leave their list) is a fixed point of the
    # pure join; uniform candidates seed cross-partition edges that the
    # join then propagates through the neighborhood
    rand = jax.random.randint(kr, (n, s - sj), 0, n, jnp.int32)
    allc = jnp.concatenate([graph, cand, rand], axis=1)      # [n, kk+s]
    self_id = jnp.arange(n, dtype=jnp.int32)
    allc = jnp.where(allc == self_id[:, None], -1, allc)

    pad = (-n) % block
    allc_p = jnp.pad(allc, ((0, pad), (0, 0)), constant_values=-1)
    x_p = jnp.pad(x, ((0, pad), (0, 0)))
    g_p = jnp.pad(graph, ((0, pad), (0, 0)), constant_values=-1)

    def score_block(args):
        xb, cb, gb = args
        vecs = x[jnp.maximum(cb, 0)]                         # [b, kk+s, d]
        from ..ops.blocked_scan import exact_gathered_dots

        dots = exact_gathered_dots("bcd,bd->bc", vecs, xb)
        vn = jnp.sum(vecs.astype(jnp.float32) ** 2, axis=2)
        xn = jnp.sum(xb.astype(jnp.float32) ** 2, axis=1)
        dist = jnp.maximum(vn - 2.0 * dots + xn[:, None], 0.0)
        # dedup by id + drop invalid, then best-kk ascending
        dist, ids = _dedup_by_id(jnp.where(cb < 0, jnp.inf, dist), cb)
        neg, pos = jax.lax.top_k(-dist, kk)
        sel = jnp.take_along_axis(ids, pos, axis=1)
        # degenerate rows (fewer than kk unique candidates) keep their
        # current edge at that rank instead of an invalidated slot
        return jnp.where(sel >= 0, sel, gb)

    out = jax.lax.map(score_block,
                      (x_p.reshape(-1, block, x.shape[1]),
                       allc_p.reshape(-1, block, kk + s),
                       g_p.reshape(-1, block, kk)))
    return out.reshape(-1, kk)[:n]


def refine_knn_graph(dataset, knn_graph, n_iters: int = 1, *,
                     sample: int = 0, seed: int = 0,
                     block: int = 65536) -> jax.Array:
    """NN-descent refinement of a kNN graph: ``n_iters`` rounds of
    neighbors-of-neighbors exploration, keeping each node's best edges by
    exact distance.  Lifts the recall of an approximately-built graph
    (e.g. the IVF-sourced intermediate graph at scale) without an exact
    kNN pass.  ``sample`` = candidates scored per node per round
    (default: 2× the graph degree, a quarter of which is uniform
    exploration — see ``_nn_descent_round``)."""
    x = wrap_array(dataset, ndim=2, name="dataset")
    g = jnp.asarray(knn_graph, jnp.int32)
    expects(g.ndim == 2 and g.shape[0] == x.shape[0],
            "knn_graph must be (n, kk) over the dataset rows")
    s = int(sample) if sample else 2 * int(g.shape[1])
    key = jax.random.PRNGKey(seed)
    for i in range(int(n_iters)):
        g = _nn_descent_round(x, g, jax.random.fold_in(key, i), s,
                              int(min(block, x.shape[0])))
    return g


@tracing.annotate("cagra.build")
def build(dataset, params: Optional[CagraIndexParams] = None, *,
          res=None) -> CagraIndex:
    """Build the optimized graph from scratch."""
    p = params or CagraIndexParams()
    x = wrap_array(dataset, ndim=2, name="dataset")
    n = x.shape[0]
    expects(p.build_n_probes >= 1,
            f"build_n_probes must be >= 1, got {p.build_n_probes}")
    kk = min(p.intermediate_graph_degree, n - 1)
    if p.build_algo == "ivf" and n >= 4096:
        from . import ivf_flat

        ip = ivf_flat.IvfFlatIndexParams(
            n_lists=max(16, int(np.sqrt(n))), metric=p.metric, seed=p.seed)
        index = ivf_flat.build(x, ip)
        _, nbrs = ivf_flat.search(
            index, x, kk + 1,
            ivf_flat.IvfFlatSearchParams(n_probes=p.build_n_probes))
    else:
        from . import brute_force

        _, nbrs = brute_force.knn(x, x, kk + 1, metric=p.metric)
    cleaned = _drop_self(jnp.asarray(nbrs), kk)
    if p.graph_refine_iters:
        # approximate intermediate graphs (IVF-sourced at scale) leave
        # recall on the table; NN-descent recovers it for ~one extra
        # gather+einsum pass per iteration
        cleaned = refine_knn_graph(x, cleaned, p.graph_refine_iters,
                                   seed=p.seed)
    graph = optimize_graph(cleaned, p.graph_degree)
    routers, router_nodes = _build_routers(x, _auto_routers(p.n_routers, n),
                                           p.seed)
    return CagraIndex(x, graph, routers, router_nodes, p.metric)


def _auto_routers(n_routers: int, n: int) -> int:
    """0 → ≈4·√n; every result is clamped to n (kmeans cannot make more
    clusters than rows).  The IVF n_lists heuristic (≈2·√n) undershoots
    here: routers must *cover* every natural region, and kmeans merges
    nearby regions when centroids are scarce (2·√8000 ≈ 179 entries over
    200 well-separated clusters caps coverage near 0.85 regardless of
    itopk).  Oversampling ~2× past the heuristic leaves headroom for
    those collisions; the 128 floor keeps small-n behavior unchanged."""
    if n_routers <= 0:
        return min(n, max(128, int(4 * np.sqrt(n))))
    return min(n_routers, n)


@partial(jax.jit, static_argnames=("kk",))
def _drop_self(nbrs, kk: int):
    """Remove each row's self match (if any) keeping neighbor order; returns
    the first ``kk`` of the remaining columns.  Shift-gather, no sort."""
    n = nbrs.shape[0]
    is_self = nbrs == jnp.arange(n, dtype=nbrs.dtype)[:, None]
    has_self = jnp.any(is_self, axis=1)
    self_pos = jnp.argmax(is_self, axis=1)
    cut = jnp.where(has_self, self_pos, nbrs.shape[1]).astype(jnp.int32)
    col = jnp.arange(kk, dtype=jnp.int32)[None, :]
    idx = col + (col >= cut[:, None]).astype(jnp.int32)
    return jnp.take_along_axis(nbrs, idx, axis=1).astype(jnp.int32)


def _build_routers(x, n_routers: int, seed: int):
    """Entry-point table: coarse kmeans centroids + their nearest dataset
    node.  Per-query seeds from this table reach every region of the dataset
    — graph search needs an entry in each connected component (random seeds
    alone miss components; this is the DiskANN-medoid idea, pluralized)."""
    from ..cluster.kmeans import KMeansParams, kmeans_fit
    from ..distance.fused import fused_l2_nn_argmin

    # kmeans++ init is load-bearing: random init leaves ~15% of well-
    # separated clusters router-less, which caps recall independently of
    # itopk (graph search can never enter an uncovered component)
    kp = KMeansParams(n_clusters=n_routers, max_iter=8, seed=seed, init="kmeans++")
    n = x.shape[0]
    sub = x[jax.random.permutation(jax.random.PRNGKey(seed), n)[: min(n, 50 * n_routers)]]
    centroids, _, _ = kmeans_fit(sub, kp)
    nodes = fused_l2_nn_argmin(centroids, x).astype(jnp.int32)  # [R]
    return centroids, nodes


def build_from_graph(dataset, knn_graph, graph_degree: int = 32,
                     metric: str = "sqeuclidean", n_routers: int = 0,
                     seed: int = 0) -> CagraIndex:
    """Build from a precomputed kNN graph (cuVS ``build`` overload parity).
    ``n_routers=0`` = auto (≈4·√n, see :func:`_auto_routers`)."""
    x = wrap_array(dataset, ndim=2, name="dataset")
    graph = optimize_graph(knn_graph, graph_degree)
    routers, router_nodes = _build_routers(
        x, _auto_routers(n_routers, x.shape[0]), seed)
    return CagraIndex(x, graph, routers, router_nodes, metric)


def extend(index: CagraIndex, new_vectors,
           params: Optional[CagraSearchParams] = None) -> CagraIndex:
    """Incrementally add nodes to the graph (cuVS CAGRA ``extend`` parity).

    Each new node's out-edges are its approximate nearest neighbors found
    by searching the EXISTING graph (beam search at the degree's width);
    reverse edges are spliced into the targets' adjacency rows by replacing
    those rows' last (worst-ranked) slots — the cheap half of the
    rank-merge optimize, keeping existing edge order intact.  Routers are
    untouched (they still cover the old data's regions; rebuild the index
    when additions change the distribution materially).
    """
    x = wrap_array(new_vectors, ndim=2, name="new_vectors")
    expects(x.shape[1] == index.dim, "vector dim mismatch")
    n_old = index.size
    n_new = int(x.shape[0])
    deg = index.graph_degree

    p = params or CagraSearchParams(itopk_size=max(64, 2 * deg))
    _, raw = search(index, x, deg, p)             # [n_new, deg] into old ids
    raw = jnp.asarray(raw, jnp.int32)
    # forward-edge fallback for -1 slots (tiny graphs): clamp to node 0
    nbrs = jnp.where(raw >= 0, raw, 0)

    dataset = jnp.concatenate([index.dataset, x.astype(index.dataset.dtype)],
                              axis=0)
    graph = jnp.concatenate([index.graph, nbrs], axis=0)
    # reverse edges: new node i is spliced into the tail slots of its top-R
    # old neighbors' rows (slot deg-1-j for the j-th neighbor).  R > 1 is
    # load-bearing: with a single reverse edge, new nodes sharing a best
    # old neighbor overwrite each other and the losers become unreachable
    # (~25% at a 15% add ratio); R slots make total orphaning ~(ratio)^R.
    # One combined scatter (not R eager passes — each would copy the whole
    # graph); -1 search slots are dropped, never written through to node 0.
    new_ids = jnp.arange(n_old, n_old + n_new, dtype=jnp.int32)
    n_rev = max(1, min(4, deg // 2))
    rows = raw[:, :n_rev]                          # [n_new, R], -1 = invalid
    slots = deg - 1 - jnp.arange(n_rev, dtype=jnp.int32)[None, :]
    dest = jnp.where(rows >= 0, rows * deg + slots,
                     (n_old + n_new) * deg)        # OOB → dropped
    flat = graph.reshape(-1).at[dest.reshape(-1)].set(
        jnp.tile(new_ids[:, None], (1, n_rev)).reshape(-1), mode="drop")
    graph = flat.reshape(graph.shape)
    return CagraIndex(dataset, graph, index.router_centroids,
                      index.router_nodes, index.metric)


def _batch_dists(dataset, q, qn, ids, metric: str):
    """Exact query→candidate distances: [nq, L] for ids [nq, L]."""
    vecs = dataset[jnp.maximum(ids, 0)]  # [nq, L, d]
    from ..ops.blocked_scan import exact_gathered_dots

    dots = exact_gathered_dots("qld,qd->ql", vecs, q)
    if metric == "inner_product":
        return -dots
    vn = jnp.sum(vecs.astype(jnp.float32) ** 2, axis=2)
    return jnp.maximum(vn - 2.0 * dots + qn[:, None], 0.0)


def _dedup_by_id(vals, ids):
    """Invalidate duplicate ids (keep best): sort by (id, val) via two stable
    argsorts, mask adjacent equals — the hash-table replacement.

    Duplicate slots are invalidated COMPLETELY: value → +inf AND id → −1.
    Keeping the loser's real id (the pre-fix behavior) let a downstream
    ``select_k(..., in_idx=...)`` fold resurrect the duplicate at its
    WORST distance whenever the selection had slack — and every +inf slot
    carrying id −1 is also what makes inf-tie selection indistinguishable
    between the frontier and per-parent search engines."""
    order = jnp.argsort(vals, axis=1, stable=True)
    v1 = jnp.take_along_axis(vals, order, axis=1)
    i1 = jnp.take_along_axis(ids, order, axis=1)
    order2 = jnp.argsort(i1, axis=1, stable=True)  # by id, best-val first in ties
    v2 = jnp.take_along_axis(v1, order2, axis=1)
    i2 = jnp.take_along_axis(i1, order2, axis=1)
    dup = jnp.concatenate(
        [jnp.zeros((ids.shape[0], 1), bool), i2[:, 1:] == i2[:, :-1]], axis=1
    )
    v2 = jnp.where(dup | (i2 < 0), jnp.inf, v2)
    i2 = jnp.where(dup, -1, i2)
    return v2, i2


def _expand_dists(dataset, q_score, qn, ids, metric: str):
    """Exact query→candidate distances for a ``[nq, w, deg]`` frontier
    slab, with ``w`` pinned into the einsum's *batch* dims.

    The frontier parity contract (mirroring the probe-block engine): each
    candidate's f32 accumulation over d is one independent ``(q, w, c)``
    lane, so a candidate's distance bits do not depend on how many parents
    were expanded alongside it — blocked (w = width) and per-parent
    (w = 1) expansion produce identical values.  Folding w into the
    candidate dimension would retile the reduction and break
    frontier == per-parent bit parity."""
    vecs = dataset[jnp.maximum(ids, 0)]            # [nq, w, deg, d]
    from ..ops.blocked_scan import slab_dots

    dots = slab_dots(vecs, q_score)
    if metric == "inner_product":
        return -dots
    vn = jnp.sum(vecs.astype(jnp.float32) ** 2, axis=3)
    return jnp.maximum(vn - 2.0 * dots + qn[:, None, None], 0.0)


def _seed_beam(dataset, routers, router_nodes, q, q_score, qn, key,
               itopk: int, n_seeds: int, metric: str):
    """Shared seed phase of both search engines: per-query nearest router
    entry nodes (covers every dataset region incl. disconnected
    components) + shared random extras, scored, deduped, ranked into the
    initial beam.  One implementation — the engines cannot drift here."""
    from ..distance.pairwise import sq_l2

    nq = q.shape[0]
    n = dataset.shape[0]
    rd = sq_l2(q, routers)                                  # [nq, R]
    n_route = min(n_seeds, routers.shape[0])
    _, rsel = jax.lax.top_k(-rd, n_route)
    seed_ids = router_nodes[rsel]                           # [nq, n_route]
    if n_seeds > n_route:
        extra = jax.random.choice(key, n, (n_seeds - n_route,),
                                  replace=False).astype(jnp.int32)
        seed_ids = jnp.concatenate(
            [seed_ids, jnp.tile(extra[None, :], (nq, 1))], axis=1
        )
    seed_vals = _batch_dists(dataset, q_score, qn, seed_ids, metric)
    seed_vals, seed_ids = _dedup_by_id(seed_vals, seed_ids)
    return select_k(seed_vals, itopk, in_idx=seed_ids, select_min=True)


def _select_parents(beam_val, beam_idx, explored, width: int):
    """Top-``width`` unexplored beam entries by ascending value — the
    per-iteration frontier, shared by both engines so they always expand
    the same parents in the same order.  Exhausted picks (no unexplored
    finite entry left) report ``live=False`` and expand nothing."""
    pv = jnp.where(explored, jnp.inf, beam_val)
    _, ppos = jax.lax.top_k(-pv, width)               # positions in beam
    parents = jnp.take_along_axis(beam_idx, ppos, axis=1)   # [nq, w]
    live = jnp.isfinite(jnp.take_along_axis(pv, ppos, axis=1))
    return parents, ppos, live


def _mask_slab_dups(vals, ids):
    """Invalidate repeats of an id within one expansion slab, keeping the
    first occurrence.  Copies of a candidate are bit-identical under the
    pinned accumulation contract (``_expand_dists``), so which copy
    survives is unobservable — only the multiplicity matters (the beam
    must never hold one node twice)."""
    nq, lanes = ids.shape
    pos = jnp.tile(jnp.arange(lanes, dtype=jnp.int32)[None, :], (nq, 1))
    order = jnp.argsort(ids, axis=1, stable=True)
    i1 = jnp.take_along_axis(ids, order, axis=1)
    p1 = jnp.take_along_axis(pos, order, axis=1)
    dup = jnp.concatenate(
        [jnp.zeros((nq, 1), bool), i1[:, 1:] == i1[:, :-1]], axis=1)
    mask = jnp.zeros_like(dup).at[jnp.arange(nq)[:, None], p1].set(dup)
    return jnp.where(mask, jnp.inf, vals), jnp.where(mask, -1, ids)


def _finish_search(beam_val, beam_idx, k: int, metric: str, keep):
    """Shared exit: result-stage filter mask + the ONE ranked selection of
    the whole search, then metric-space output transforms."""
    if keep is not None:
        # result-stage filter: the descent may pass through filtered
        # nodes, but they can never be returned (see search() docstring)
        from ._packing import keep_lookup

        beam_val = jnp.where(keep_lookup(keep, beam_idx) & (beam_idx >= 0),
                             beam_val, jnp.inf)
    out_val, pos = select_k(beam_val, k, select_min=True)
    out_idx = jnp.take_along_axis(beam_idx, pos, axis=1)
    if metric == "euclidean":
        out_val = jnp.sqrt(jnp.maximum(out_val, 0.0))
    elif metric == "inner_product":
        out_val = -out_val
    return out_val, out_idx


@partial(jax.jit, static_argnames=("k", "itopk", "width", "iters", "n_seeds",
                                   "metric"))
def _search_impl(dataset, graph, routers, router_nodes, q, key, iters_cap,
                 k: int, itopk: int, width: int, iters: int, n_seeds: int,
                 metric: str, keep=None):
    """Frontier-blocked beam search (the production engine).

    Each iteration expands ALL ``width`` frontier parents at once: one
    ``[nq, width·deg]`` slab gather, one batch-dim distance einsum
    (``_expand_dists`` — bit-invariant across ``width``), one unsorted
    ``select_k`` fold into the beam.  The beam carry is kept sorted by id
    (a "sorted ring"), so the visited test for every candidate is a
    ``searchsorted`` + one gather against the persistent carry instead of
    the per-iteration double argsort the per-parent engine pays; explored
    flags ride the fold as a payload instead of being rebuilt from an
    O(itopk²) membership product.  The only ranked selection happens once,
    at exit.

    ``iters`` is the static scan length; ``iters_cap`` is a DEVICE scalar
    — iterations past the cap, and queries whose frontier is exhausted,
    are no-op lanes (the carry is passed through unchanged), so one
    executable serves every ``max_iterations`` up to the compiled length.

    Bit-identical (values AND ids) to :func:`_search_impl_perop` at every
    ``width`` — pinned in tests/test_cagra_frontier.py."""
    nq, d = q.shape
    deg = graph.shape[1]
    qf = q.astype(jnp.float32)
    from ..ops.blocked_scan import row_sq_norms

    qn = row_sq_norms(qf)
    # beam scoring takes the RAW query when the 8-bit single-pass tier
    # applies (the f32 cast would silently disable it); one shared
    # eligibility rule keeps this in lockstep with the scorer
    from ..ops.blocked_scan import int8_tier_eligible

    q_score = q if int8_tier_eligible(dataset, q, d) else qf
    beam_val, beam_idx = _seed_beam(dataset, routers, router_nodes, q,
                                    q_score, qn, key, itopk, n_seeds, metric)
    explored = beam_idx < 0
    # sorted-ring layout: beam lanes ordered by id, so membership tests
    # against the carry are binary searches, not sorts
    order = jnp.argsort(beam_idx, axis=1)
    beam_val = jnp.take_along_axis(beam_val, order, axis=1)
    beam_idx = jnp.take_along_axis(beam_idx, order, axis=1)
    explored = jnp.take_along_axis(explored, order, axis=1)
    rows = jnp.arange(nq)[:, None]

    def step(carry, t):
        bv0, bi0, ex0 = carry
        # no-op lanes: a converged query (no unexplored finite entry) or
        # one past the dynamic cap keeps its carry bit-unchanged
        active = (jnp.any(~ex0 & jnp.isfinite(bv0), axis=1)
                  & (t < iters_cap))
        parents, ppos, live = _select_parents(bv0, bi0, ex0, width)
        live = live & active[:, None]
        explored2 = ex0.at[rows, ppos].set(True)
        # fused frontier expansion: one slab gather + one batched einsum
        nbrs = graph[jnp.maximum(parents, 0)]         # [nq, w, deg]
        nbrs = jnp.where(live[:, :, None], nbrs, -1)
        nvals = _expand_dists(dataset, q_score, qn, nbrs, metric)
        nids = nbrs.reshape(nq, width * deg)
        nvals = jnp.where(nids >= 0, nvals.reshape(nq, width * deg), jnp.inf)
        nvals, nids = _mask_slab_dups(nvals, nids)
        # sorted-ring visited filter: a candidate already in the beam is
        # dropped; its value folds into the resident entry by scatter-min
        # — the keep-min the per-parent dedup applies across the
        # seed/expansion accumulation boundary
        spos = jax.vmap(
            lambda a, v: jnp.searchsorted(a, v, method="sort"))(bi0, nids)
        spos = jnp.minimum(spos, itopk - 1)
        hit = jnp.take_along_axis(bi0, spos, axis=1) == nids
        beam_val = bv0.at[rows, jnp.where(hit, spos, itopk)].min(
            jnp.where(hit, nvals, jnp.inf), mode="drop")
        nvals = jnp.where(hit, jnp.inf, nvals)
        nids = jnp.where(hit, -1, nids)
        # unsorted fold: exact top-itopk *set*, no ranking pass — ids and
        # explored flags ride the fold as payloads
        from ..ops.blocked_scan import fold_topk_payload

        mv, mi, (mf,) = fold_topk_payload(
            beam_val, bi0, (explored2,), nvals, nids,
            (jnp.zeros_like(hit),), itopk)
        mi = jnp.where(jnp.isfinite(mv), mi, -1)  # empty slots are id −1
        mf = mf | (mi < 0)
        # rebuild the ring: ONE int argsort over itopk lanes (ties only
        # among identical (inf, −1, True) empties)
        order = jnp.argsort(mi, axis=1)
        a = active[:, None]
        new = tuple(jnp.take_along_axis(x, order, axis=1)
                    for x in (mv, mi, mf))
        return tuple(jnp.where(a, nw, od)
                     for nw, od in zip(new, (bv0, bi0, ex0))), None

    (beam_val, beam_idx, _), _ = jax.lax.scan(
        step, (beam_val, beam_idx, explored),
        jnp.arange(iters, dtype=jnp.int32))
    return _finish_search(beam_val, beam_idx, k, metric, keep)


@partial(jax.jit, static_argnames=("k", "itopk", "width", "iters", "n_seeds",
                                   "metric"))
def _search_impl_perop(dataset, graph, routers, router_nodes, q, key,
                       iters_cap, k: int, itopk: int, width: int, iters: int,
                       n_seeds: int, metric: str, keep=None):
    """Per-parent reference engine: the SAME frontier per iteration
    (``_select_parents`` once, like the frontier engine), expanded one
    parent at a time through the classic concat → ``_dedup_by_id`` →
    ranked-``select_k`` chain.  Kept as the parity oracle: width ranked
    merges + width dedup argsort chains per iteration against the
    frontier engine's single unsorted fold — the A/B in
    ``bench/CAGRA_FRONTIER_CPU.json`` measures exactly this gap.

    Explored flags are rebuilt once per iteration by membership against
    the iteration-start visited ids (parents included) — equivalent to
    the frontier engine's flags-ride-the-fold because surviving candidates
    can never collide with a visited id (the dedup keeps one copy and the
    visited copy's minimum value, exactly like the sorted-ring filter's
    scatter-min)."""
    nq, d = q.shape
    deg = graph.shape[1]
    qf = q.astype(jnp.float32)
    from ..ops.blocked_scan import int8_tier_eligible, row_sq_norms

    qn = row_sq_norms(qf)

    q_score = q if int8_tier_eligible(dataset, q, d) else qf
    beam_val, beam_idx = _seed_beam(dataset, routers, router_nodes, q,
                                    q_score, qn, key, itopk, n_seeds, metric)
    explored = beam_idx < 0
    rows = jnp.arange(nq)[:, None]

    def step(carry, t):
        bv0, bi0, ex0 = carry
        active = (jnp.any(~ex0 & jnp.isfinite(bv0), axis=1)
                  & (t < iters_cap))
        parents, ppos, live = _select_parents(bv0, bi0, ex0, width)
        live = live & active[:, None]
        ex_marked = ex0.at[rows, ppos].set(True)
        # the iteration's visited id set, frozen before any expansion
        vis_ids = jnp.where(ex_marked, bi0, -2)
        bv, bi = bv0, bi0
        for j in range(width):        # static unroll: one parent at a time
            nbrs = graph[jnp.maximum(parents[:, j:j + 1], 0)]  # [nq, 1, deg]
            nbrs = jnp.where(live[:, j:j + 1, None], nbrs, -1)
            nvals = _expand_dists(dataset, q_score, qn, nbrs, metric)
            nids = nbrs.reshape(nq, deg)
            nvals = jnp.where(nids >= 0, nvals.reshape(nq, deg), jnp.inf)
            dv, di = _dedup_by_id(jnp.concatenate([bv, nvals], axis=1),
                                  jnp.concatenate([bi, nids], axis=1))
            pos = jnp.tile(
                jnp.arange(dv.shape[1], dtype=jnp.int32)[None, :], (nq, 1))
            bv, mpos = select_k(dv, itopk, in_idx=pos, select_min=True)
            bi = jnp.take_along_axis(di, mpos, axis=1)
        # O(itopk²) membership product — the cost the frontier engine's
        # flag payload deletes
        mf = jnp.any(bi[:, :, None] == vis_ids[:, None, :], axis=2) | (bi < 0)
        a = active[:, None]
        return (jnp.where(a, bv, bv0), jnp.where(a, bi, bi0),
                jnp.where(a, mf, ex0)), None

    (beam_val, beam_idx, _), _ = jax.lax.scan(
        step, (beam_val, beam_idx, explored),
        jnp.arange(iters, dtype=jnp.int32))
    return _finish_search(beam_val, beam_idx, k, metric, keep)


_SEARCH_ENGINES = {"frontier": _search_impl, "per_parent": _search_impl_perop}


def _engine(name: str):
    expects(name in _SEARCH_ENGINES,
            f"unknown search_impl {name!r}; expected one of "
            f"{sorted(_SEARCH_ENGINES)}")
    return _SEARCH_ENGINES[name]


@lru_cache(maxsize=64)
def _iters_cap(cap: int):
    """Iteration cap as a memoized device scalar — an OPERAND, not a
    static: every ``max_iterations`` up to the compiled scan length shares
    one executable, and the memo keeps repeat searches free of implicit
    host→device transfers (the ``_search_key`` pattern)."""
    return jnp.asarray(int(cap), jnp.int32)


def _resolve_search(p: "CagraSearchParams", k: int, n: int):
    """Static search config from params: ``(itopk, width, iters, cap)``
    with ``iters`` the compiled scan length and ``cap`` the dynamic
    iteration bound (``iters`` ≥ the auto count so every
    ``max_iterations`` ≤ auto reuses one executable)."""
    from ._packing import resolve_cagra_search

    itopk, width = resolve_cagra_search(p.itopk_size, p.search_width,
                                        int(k), int(n))
    auto = max(1, (itopk + width - 1) // width)
    req = int(p.max_iterations)
    return itopk, width, max(auto, req), (req or auto)


def resolved_search_params(index, k: int,
                           params: Optional[CagraSearchParams] = None
                           ) -> CagraSearchParams:
    """Concrete search params for ``index``: 0-valued (auto) ``itopk_size``
    / ``search_width`` replaced by the tuned-table resolution ``search``
    itself would use.  The serve layer calls this BEFORE effort scaling,
    so degradation ladders scale the real beam width, not the sentinel."""
    p = params or CagraSearchParams()
    itopk, width, _, _ = _resolve_search(p, k, index.size)
    return dataclasses.replace(p, itopk_size=itopk, search_width=width)


@lru_cache(maxsize=16)
def _sharded_build_program(mesh: Mesh, axis: str, per: int, kk: int,
                           deg: int, n_routers: int, metric: str, seed: int,
                           tile: int):
    """Compile-once distributed CAGRA build: every device builds its
    sub-graph (local kNN graph → rank-merge optimize → router table) from
    ITS rows on ITS device — one shard_map program, S parallel builds,
    replacing the r2 sequential host loop (VERDICT r2 missing #2).
    SNMG model of ``core/device_resources_snmg.hpp:36``."""
    from ..cluster.kmeans import _fit_impl
    from ..distance.fused import _fused_l2_nn
    from .brute_force import _knn_impl

    def local(x_l):
        shard = jax.lax.axis_index(axis)
        key = jax.random.fold_in(jax.random.PRNGKey(seed), shard)
        _, nbrs = _knn_impl(x_l, x_l, kk + 1, metric, tile)
        cleaned = _drop_self(jnp.asarray(nbrs), kk)
        graph = _optimize_graph_impl(cleaned, deg)
        # router table on a subsample (the _build_routers recipe, traced)
        sub = x_l[jax.random.permutation(key, per)[: min(per, 50 * n_routers)]]
        # kmeans++ for coverage (see _build_routers)
        c, _, _, _ = _fit_impl(sub, key, n_routers, 8, 1e-4, "kmeans++")
        # router centroids keep the fit dtype (f32 for integer corpora)
        _, nodes = _fused_l2_nn(c, x_l, False, min(4096, per))
        return (x_l[None], graph[None], c[None],
                nodes.astype(jnp.int32)[None])

    return jax.jit(shard_map(
        local, mesh=mesh, in_specs=P(axis), out_specs=(P(axis),) * 4,
        check_vma=False,
    ))


def build_sharded(dataset, mesh: Mesh,
                  params: Optional[CagraIndexParams] = None, *,
                  axis: str = "shard") -> "ShardedCagraIndex":
    """Partition rows over the mesh axis and build one sub-graph per shard,
    **each on its own device** (one shard_map program — no sequential host
    loop, no post-hoc device_put).

    Each shard's graph indexes *local* row positions; global ids are
    ``shard * rows_per_shard + local`` (rows padded to divide evenly).
    The MNMG index-shard model of SURVEY.md §5.7 on ICI.  The graph source
    is the local brute-force kNN graph (the ``build_algo="brute_force"``
    path; per-shard rows make the quadratic tile scan tractable).
    """
    from ._packing import shard_rows

    p = params or CagraIndexParams()
    x_sh, n, per = shard_rows(dataset, mesh, axis)
    kk = min(p.intermediate_graph_degree, per - 1)
    prog = _sharded_build_program(
        mesh, axis, per, kk, p.graph_degree, _auto_routers(p.n_routers, per),
        p.metric, p.seed, min(8192, per))
    ds, graphs, rc, rn = prog(x_sh)
    return ShardedCagraIndex(ds, graphs, rc, rn, p.metric, n)


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class ShardedCagraIndex:
    datasets: jax.Array          # [S, per, d]
    graphs: jax.Array            # [S, per, deg]
    router_centroids: jax.Array  # [S, R, d]
    router_nodes: jax.Array      # [S, R]
    metric: str = dataclasses.field(metadata=dict(static=True))
    n_rows: int = dataclasses.field(metadata=dict(static=True))


@lru_cache(maxsize=32)
def _sharded_search_program(mesh: Mesh, axis: str, data_axis: Optional[str],
                            k: int, itopk: int, width: int, iters: int,
                            n_seeds: int, metric: str, per: int,
                            n_rows: int, keep_ndim: int = 0,
                            impl: str = "frontier"):
    """Compile-once sharded search (jit keyed on the static config — a
    per-call closure would re-trace every ``search_sharded`` call, which
    dominates pipelined QPS measurements)."""
    engine = _engine(impl)

    def local(ds, g, rc, rn, q_l, key, cap, keep_l):
        bv, bi = engine(ds[0], g[0], rc[0], rn[0], q_l, key, cap, k,
                        itopk, width, iters, n_seeds, metric)
        shard = jax.lax.axis_index(axis)
        bi = jnp.where(bi >= 0, bi + shard * per, bi)
        if metric == "inner_product":
            bv = -bv  # back to min-selectable before masking
        bv = jnp.where((bi >= 0) & (bi < n_rows), bv, jnp.inf)
        if keep_l is not None:
            # result-stage filter by GLOBAL source id (see search())
            from ._packing import keep_lookup

            bv = jnp.where(keep_lookup(keep_l, bi), bv, jnp.inf)
        av = jax.lax.all_gather(bv, axis)
        ai = jax.lax.all_gather(bi, axis)
        av = jnp.moveaxis(av, 0, 1).reshape(q_l.shape[0], -1)
        ai = jnp.moveaxis(ai, 0, 1).reshape(q_l.shape[0], -1)
        fv, fi = select_k(av, k, in_idx=ai, select_min=True)
        if metric == "inner_product":
            fv = -fv
        return fv, fi

    qspec = P(data_axis) if data_axis else P()
    # keep masks GLOBAL ids → replicated over the shard axis; bitmap rows
    # follow the query partitioning
    kspec = (P(data_axis) if (keep_ndim == 2 and data_axis) else P())
    return jax.jit(shard_map(
        local, mesh=mesh,
        in_specs=(P(axis), P(axis), P(axis), P(axis), qspec, P(), P(), kspec),
        out_specs=(qspec, qspec),
        check_vma=False,
    ))


@lru_cache(maxsize=64)
def _search_key(seed: int):
    """Seed -> PRNG key, memoized: building the key per call packs a host
    scalar onto device every search — an implicit h2d transfer the
    TraceGuard steady-state gate (tests/test_trace_guard.py) rejects."""
    return jax.random.PRNGKey(seed)


def search_sharded(index: ShardedCagraIndex, queries, k: int,
                   params: Optional[CagraSearchParams] = None, *,
                   mesh: Mesh, axis: str = "shard",
                   data_axis: Optional[str] = None, filter=None,
                   seed: int = 0
                   ) -> Tuple[jax.Array, jax.Array]:
    """Every shard searches its sub-graph with the same program; one
    all_gather + select_k merges the per-shard top-k (ids globalized).
    On a 2-D mesh, ``data_axis`` partitions the queries over that axis.

    ``filter``: bitset/bitmap over GLOBAL row numbering, result-stage
    semantics as in :func:`search`."""
    from ._packing import as_keep_mask, sentinel_filtered_ids

    p = params or CagraSearchParams()
    q = wrap_array(queries, ndim=2, name="queries")
    if data_axis is not None:
        expects(data_axis in mesh.axis_names, f"axis {data_axis!r} not in mesh")
        expects(q.shape[0] % int(mesh.shape[data_axis]) == 0,
                "queries not divisible by data axis")
    per = int(index.datasets.shape[1])
    itopk, width, iters, cap = _resolve_search(p, k, int(index.n_rows))
    keep = as_keep_mask(filter, n=int(index.n_rows), nq=q.shape[0])
    prog = _sharded_search_program(
        mesh, axis, data_axis, int(k), itopk, width, iters,
        int(min(p.n_seeds, per)), index.metric, per,
        int(index.n_rows), 0 if keep is None else keep.ndim, p.search_impl)
    dv, di = prog(index.datasets, index.graphs, index.router_centroids,
                  index.router_nodes, q, _search_key(int(seed)),
                  _iters_cap(cap), keep)
    if keep is not None:
        di = sentinel_filtered_ids(dv, di)
    return dv, di


@tracing.annotate("cagra.search")
def search(index: CagraIndex, queries, k: int,
           params: Optional[CagraSearchParams] = None, *, filter=None,
           seed: int = 0, res=None) -> Tuple[jax.Array, jax.Array]:
    """Graph beam search: returns ``(distances, ids)`` of (nq, k).

    ``filter``: optional prefilter, True = keep — shared
    ``core.Bitset``/(n,) bools or per-query ``core.Bitmap``/(nq, n) bools
    (cuVS filtered-CAGRA parity).  Graph-traversal semantics: the descent
    may route THROUGH filtered nodes (removing them would fragment the
    graph), but they never appear in results — filtering happens on the
    final beam, so size ``itopk_size`` ≥ ``k`` + the number of filtered
    nodes you expect near the query (raise it for dense filters).  Slots
    with no surviving candidate report id −1 with ±inf distance (−inf for
    ``inner_product``, which reports similarities) — ``id == -1`` is the
    reliable emptiness signal.
    """
    from ._packing import as_keep_mask, sentinel_filtered_ids

    p = params or CagraSearchParams()
    q = wrap_array(queries, ndim=2, name="queries")
    expects(q.shape[1] == index.dim, "query dim mismatch")
    keep = as_keep_mask(filter, n=index.size, nq=q.shape[0])
    itopk, width, iters, cap = _resolve_search(p, k, index.size)
    key = _search_key(int(seed))
    dv, di = _engine(p.search_impl)(
        index.dataset, index.graph, index.router_centroids,
        index.router_nodes, q, key, _iters_cap(cap), int(k), itopk, width,
        iters, int(min(p.n_seeds, index.size)), index.metric, keep)
    if keep is not None:
        di = sentinel_filtered_ids(dv, di)
    return dv, di


def searcher(index: CagraIndex, k: int,
             params: Optional[CagraSearchParams] = None, *, seed: int = 0,
             filter=None):
    """Uniform serving entry point (``raft_tpu.serve`` contract): returns
    ``(fn, operands)`` with ``fn(queries, *operands)`` equal to
    :func:`search` at the same ``seed``.  The PRNG key rides as an operand
    (the beam's random extra seeds are shared across queries, so padded
    serving batches stay row-identical to a direct call); dataset/graph
    and the dynamic iteration cap ride as operands so bucket executables
    share them (a ``max_iterations`` change within the compiled scan
    length never recompiles).

    ``filter``: optional shared prefilter (``core.Bitset`` / 1-D bools
    over row numbers, True = keep) with :func:`search`'s beam-stage
    semantics — rides as one more operand so tombstone deletes swap in a
    new mask without recompiling.  Per-query bitmaps can't ride a fixed
    operand across variable-row buckets and are rejected."""
    from ._packing import as_keep_mask, sentinel_filtered_ids

    p = params or CagraSearchParams()
    expects(k >= 1, "k must be >= 1")
    itopk, width, iters, cap = _resolve_search(p, k, index.size)
    n_seeds = int(min(p.n_seeds, index.size))
    metric = index.metric
    engine = _engine(p.search_impl)
    key = jax.random.PRNGKey(seed)
    keep = as_keep_mask(filter, n=index.size)
    if keep is not None:
        expects(keep.ndim == 1,
                "serving filters are shared bitsets (1-D); per-query "
                "bitmaps can't ride a fixed operand across buckets")

        def fn(q, dataset, graph, routers, router_nodes, kk, cap_dev, kp):
            dv, di = engine(dataset, graph, routers, router_nodes, q, kk,
                            cap_dev, int(k), itopk, width, iters, n_seeds,
                            metric, kp)
            return dv, sentinel_filtered_ids(dv, di)

        return fn, (index.dataset, index.graph, index.router_centroids,
                    index.router_nodes, key, _iters_cap(cap), keep)

    def fn(q, dataset, graph, routers, router_nodes, kk, cap_dev):
        return engine(dataset, graph, routers, router_nodes, q, kk,
                      cap_dev, int(k), itopk, width, iters, n_seeds, metric,
                      None)

    return fn, (index.dataset, index.graph, index.router_centroids,
                index.router_nodes, key, _iters_cap(cap))
