"""Index-health statistics — the structural quality of a built index.

Search quality degrades for two distinct reasons: the *query path*
spends less effort (admission ladder, kernel fallbacks — measured
online by :mod:`raft_tpu.obs.quality`), or the *index itself* got worse
(a skewed kmeans partition, tombstone bloat, a compaction that mangled
a graph).  This module measures the second kind, at the only moments it
can change — build / extend / compact / swap — so a bad generation is
visible in one scrape instead of a slow recall bleed.

:func:`index_health` extracts per-family structure stats as a flat
host dict; :func:`export_index_health` lands them in one registry gauge
family ``raft_index_health{stat,family,generation}`` and prunes retired
generations so the series set stays bounded.

Per family:

* **ivf_flat / ivf_pq** — list-occupancy balance: coefficient of
  variation and max-fraction of the per-list counts (imbalance = some
  lists carry hot spots → probe cost and recall both skew), fullest
  list / cap (the slab-growth trigger), fraction of empty lists.
  ``ivf_pq`` adds mean / p95 of the stored residual energy ``‖r̂‖²``
  (``code_norms``) over live slots — decoded-residual energy is the
  reconstruction-error proxy available without re-reading raw vectors,
  and its drift across generations tracks codebook staleness.
* **ivf_rabitq** — same occupancy stats, plus mean / p95 of the stored
  residual energy ``‖x−c‖²`` over live slots (the 1-bit estimator's
  error scale) — drift tracks centroid staleness.
* **cagra** — in-degree distribution of the fixed-out-degree graph
  (CV, max in-degree fraction, orphan fraction — orphans are
  unreachable except through seeds), self-loop fraction.
* **brute_force** — rows only (no structure to degrade).
* ``mutation.Tombstoned`` — wraps any of the above, adding ``dead`` /
  ``dead_fraction``.

All transfers are a handful of explicit host scalars at
build/swap/poll time, never on the search path.
"""

from __future__ import annotations

from typing import Optional

import jax
import numpy as np

__all__ = ["index_health", "export_index_health"]


def _occupancy_stats(counts: np.ndarray, cap: int) -> dict:
    n_lists = counts.shape[0]
    total = float(counts.sum())
    mean = total / n_lists if n_lists else 0.0
    cv = float(counts.std() / mean) if mean > 0 else 0.0
    return {
        "lists": float(n_lists),
        "list_cap": float(cap),
        "occupancy_cv": cv,
        "occupancy_max_fraction":
            float(counts.max()) / total if total > 0 else 0.0,
        "occupancy_max": float(counts.max()) / cap if cap else 0.0,
        "empty_lists_fraction":
            float((counts == 0).sum()) / n_lists if n_lists else 0.0,
    }


def index_health(index) -> dict:
    """Structure stats for ``index`` as a flat ``{stat: float}`` dict
    (plus ``family: str``).  Common keys: ``rows``, ``dead``,
    ``dead_fraction``; the rest are per-family (see module docstring)."""
    from .mutation import Tombstoned, deleted_count

    dead = 0.0
    if isinstance(index, Tombstoned):
        dead = float(deleted_count(index))
        index = index.index
    if getattr(index, "ndim", None) == 2:              # brute database
        rows = float(index.shape[0])
        out = {"family": "brute_force", "rows": rows}
    elif hasattr(index, "graph"):                      # cagra
        graph = np.asarray(jax.device_get(index.graph))  # jaxlint: disable=JX01 build/swap-time health poll, never on the search path
        n, deg = graph.shape
        in_deg = np.bincount(graph.reshape(-1), minlength=n)[:n]
        mean = float(in_deg.mean()) if n else 0.0
        out = {
            "family": "cagra",
            "rows": float(n),
            "graph_degree": float(deg),
            "in_degree_cv": float(in_deg.std() / mean) if mean > 0 else 0.0,
            "in_degree_max_fraction":
                float(in_deg.max()) / float(in_deg.sum())
                if n and in_deg.sum() else 0.0,
            "orphan_fraction": float((in_deg == 0).sum()) / n if n else 0.0,
            "self_loop_fraction":
                float((graph == np.arange(n)[:, None]).sum()) / graph.size
                if graph.size else 0.0,
        }
    elif hasattr(index, "store"):                      # ooc
        # the memory split IS this family's structural story: the codes
        # tier resident on device vs the raw rows host-side — plus the
        # same occupancy/residual stats as ivf_rabitq (same device half)
        counts = np.asarray(jax.device_get(index.counts))  # jaxlint: disable=JX01 build/swap-time health poll, never on the search path
        rn2 = np.asarray(jax.device_get(index.res_norms))  # jaxlint: disable=JX01 build/swap-time health poll, never on the search path
        ids = np.asarray(jax.device_get(index.ids))  # jaxlint: disable=JX01 build/swap-time health poll, never on the search path
        live = rn2[ids >= 0]
        out = {"family": "ooc", "rows": float(counts.sum())}
        out.update(_occupancy_stats(counts, index.list_cap))
        out["residual_energy_mean"] = float(live.mean()) if live.size else 0.0
        out["residual_energy_p95"] = \
            float(np.percentile(live, 95)) if live.size else 0.0
        out["resident_bytes"] = float(index.resident_bytes)
        out["host_bytes"] = float(index.host_bytes)
        from ..neighbors.ooc import transfer_stats

        out["rerank_fetch_bytes"] = float(transfer_stats()["fetch_bytes"])
    elif hasattr(index, "rotation"):                   # ivf_rabitq
        counts = np.asarray(jax.device_get(index.counts))  # jaxlint: disable=JX01 build/swap-time health poll, never on the search path
        rn2 = np.asarray(jax.device_get(index.res_norms))  # jaxlint: disable=JX01 build/swap-time health poll, never on the search path
        ids = np.asarray(jax.device_get(index.ids))  # jaxlint: disable=JX01 build/swap-time health poll, never on the search path
        live = rn2[ids >= 0]
        out = {"family": "ivf_rabitq", "rows": float(counts.sum())}
        out.update(_occupancy_stats(counts, index.list_cap))
        # ‖x−c‖² over live slots: the estimator's error scale is
        # proportional to residual energy, so drift across generations
        # tracks centroid staleness exactly like ivf_pq's decoded proxy
        out["residual_energy_mean"] = float(live.mean()) if live.size else 0.0
        out["residual_energy_p95"] = \
            float(np.percentile(live, 95)) if live.size else 0.0
    elif hasattr(index, "codes"):                      # ivf_pq
        counts = np.asarray(jax.device_get(index.counts))  # jaxlint: disable=JX01 build/swap-time health poll, never on the search path
        norms = np.asarray(jax.device_get(index.code_norms))  # jaxlint: disable=JX01 build/swap-time health poll, never on the search path
        ids = np.asarray(jax.device_get(index.ids))  # jaxlint: disable=JX01 build/swap-time health poll, never on the search path
        live = norms[ids >= 0]
        out = {"family": "ivf_pq", "rows": float(counts.sum())}
        out.update(_occupancy_stats(counts, index.list_cap))
        out["residual_energy_mean"] = float(live.mean()) if live.size else 0.0
        out["residual_energy_p95"] = \
            float(np.percentile(live, 95)) if live.size else 0.0
    elif hasattr(index, "data"):                       # ivf_flat
        counts = np.asarray(jax.device_get(index.counts))  # jaxlint: disable=JX01 build/swap-time health poll, never on the search path
        out = {"family": "ivf_flat", "rows": float(counts.sum())}
        out.update(_occupancy_stats(counts, index.list_cap))
    else:
        raise TypeError(f"no health stats for {type(index).__name__}")
    out["dead"] = dead
    out["dead_fraction"] = dead / out["rows"] if out["rows"] else 0.0
    return out


def export_index_health(registry, index, *, generation: Optional[int] = None,
                        keep_generations: int = 4) -> dict:
    """Compute :func:`index_health` and land every stat in the registry
    gauge family ``raft_index_health{stat,family,generation}``.

    One gauge family (not one per stat) keeps the exposition's shape
    fixed as families come and go across swaps.  Generations older than
    the newest ``keep_generations`` are pruned from the family — the
    point of per-generation labels is comparing a swap against its
    predecessor, not unbounded history.  Returns the stats dict."""
    stats = index_health(index)
    gen = str(0 if generation is None else int(generation))
    family = stats["family"]
    g = registry.gauge(
        "raft_index_health",
        "per-generation index structure stats (see neighbors.health)")
    for stat, value in stats.items():
        if stat == "family":
            continue
        g.set(value, stat=stat, family=family, generation=gen)
    gens = sorted({int(labels["generation"])
                   for labels, _ in g.samples()
                   if labels.get("generation", "").lstrip("-").isdigit()})
    for old in gens[:-keep_generations] if keep_generations > 0 else gens:
        for labels, _ in g.samples():
            if labels.get("generation") == str(old):
                g.remove(**labels)
    return stats
