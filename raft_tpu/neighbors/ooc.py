"""Out-of-core cooperative search tier — codes in device memory, raw
rows on host, only survivors cross the bus.

The FusionANNS split (PAPERS.md) on top of the PR 13 RaBitQ tier: the
device keeps ONLY the packed 1-bit code slabs + centroids (~20 B/vec at
d=64, ~13× under the raw f32 slab), while the full-precision vectors
live host-side as an mmap-backed sharded store
(:class:`raft_tpu.io.shards.ShardedVectorStore`).  Search is a
three-phase cooperative loop per query chunk:

1. **estimate** (device) — the probe-blocked RaBitQ estimator scan
   (:func:`~raft_tpu.neighbors.ivf_rabitq._estimate_survivors`, shared
   code, bit-identical candidates) keeps the top-``rerank_k`` survivor
   ids;
2. **fetch** (host) — survivor rows gather from the sharded store
   grouped by shard (native threaded pread / mmap fallback), staged
   through :class:`~raft_tpu.core.host_memory.HostBufferPool` buffers so
   the steady-state loop allocates nothing;
3. **rerank** (device) — ONE explicit ``device_put`` of the
   ``[chunk, rerank_k, d]`` slab (never the full database), then the
   exact re-score through :func:`~raft_tpu.ops.blocked_scan.l2_rescorer`
   in brute accumulation order — ``rerank_k = n`` is bit-identical
   (values AND ids) to ``brute_force.knn``, the same contract ivf_rabitq
   pinned (tests/test_ooc.py).

With ``overlap=True`` (default) the chunks ride
:func:`~raft_tpu.core.double_buffer.device_prefetch`: chunk t+1's
estimate + host fetch + slab put run while chunk t's rerank executes, so
the bus transfer hides behind device compute (TPU-KNN's overlapped
model).  Peak device memory is ``codes_bytes + slab_budget`` by
construction — every transfer funnels through one accounting seam
(:func:`transfer_stats`), which the tier-1 boundedness test audits under
``jax.transfer_guard("disallow")``.

Build streams chunks through the PR 4 pipelined path and writes the
shard store as it goes: peak host memory is bounded by ``chunk_rows``
(+ the fixed OS page cache behind the mmaps), peak device memory by the
code slabs + two staged chunks.  ``save()`` / ``open()`` persist a
format-v5 manifest-directory layout (device bundle with per-array CRCs
+ the shard store with per-shard CRCs); ``open()`` maps shards lazily,
so opening a store costs metadata only.
"""

from __future__ import annotations

import dataclasses
import json
import os
import shutil
from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..core import lockdep, tracing
from ..core.array import wrap_array
from ..core.double_buffer import device_prefetch
from ..core.errors import expects
from ..core.host_memory import default_host_pool
from ..distance.pairwise import sq_l2
from ..io.shards import ShardedVectorStore, ShardWriter
from ..ops import blocked_scan as _scan
from . import ivf_rabitq as _rq

__all__ = [
    "OocIndexParams",
    "OocSearchParams",
    "OocIndex",
    "build",
    "build_chunked",
    "search",
    "searcher",
    "save",
    "open",
    "verify",
    "transfer_stats",
    "reset_transfer_stats",
]

_META = "meta.json"
_DEVICE_DIR = "device"
_SHARDS_DIR = "shards"
_FORMAT_VERSION = 5
_ARRAY_FIELDS = ("centroids", "rotation", "codes", "sabs", "res_norms",
                 "code_cdots", "ids", "counts")


@dataclasses.dataclass(frozen=True)
class OocIndexParams:
    """Build configuration.  The device-side knobs mirror
    :class:`~raft_tpu.neighbors.ivf_rabitq.IvfRabitqIndexParams` (same
    coarse quantizer, same encoder); ``rows_per_shard`` fixes the
    host-store shard granularity (global row id = shard·rows_per_shard
    + local row, so the store IS the id space)."""

    n_lists: int = 1024
    metric: str = "sqeuclidean"  # sqeuclidean | euclidean | inner_product
    kmeans_n_iters: int = 20
    kmeans_trainset_fraction: float = 0.1
    list_cap_ratio: float = 2.0
    rows_per_shard: int = 1 << 20
    seed: int = 0


@dataclasses.dataclass(frozen=True)
class OocSearchParams:
    n_probes: int = 32
    # exact-rerank candidate count (estimator survivors fetched from the
    # host store per query).  0 = auto (the rabitq recall-gated tuned
    # table, else heuristic).  rerank_k = index.size is bit-identical to
    # brute force.  Unlike ivf_rabitq this is ALSO the bus knob: each
    # query moves rerank_k · d · itemsize bytes up per search.
    rerank_k: int = 0
    query_chunk: int = 1024   # queries per cooperative step (cap)
    probe_block: int = 0      # 0 = auto; bit-identical for every value
    scan_kernel: str = "auto"  # "auto" | "xla" | "fused" (counted fallback)
    # hard cap on ONE staged rerank slab, in bytes: the query chunk
    # shrinks until chunk · rerank_k · d · itemsize fits.  Peak device
    # memory = codes slabs + (overlap+1) slabs under this budget — the
    # knob that makes "fits on device" a configuration, not an accident.
    slab_budget: int = 256 << 20
    # host-store read granularity: survivor gathers fetch dense spans up
    # to fetch_batch rows through one threaded pread (pooled staging
    # buffers are keyed by this, so it also fixes the pool working set)
    fetch_batch: int = 8192
    # double-buffer the cooperative loop: stage chunk t+1 (estimate +
    # host fetch + slab put) while chunk t reranks on device.  Results
    # are bit-identical either way — pure overlap knob (the A/B in
    # bench/ooc_bench.py)
    overlap: bool = True


@dataclasses.dataclass(frozen=True)
class OocIndex:
    """Device half = RaBitQ code slabs (+ centroids, rotation, per-vector
    scalars, ids, counts); host half = the sharded raw-row store.  NOT a
    pytree — the store is host state; the search loop passes the device
    fields into jit explicitly."""

    centroids: jax.Array    # [L, d]
    rotation: jax.Array     # [d, d] f32 orthonormal
    codes: jax.Array        # [L, cap, ceil(d/8)] uint8 packed signs
    sabs: jax.Array         # [L, cap] f32
    res_norms: jax.Array    # [L, cap] f32
    code_cdots: jax.Array   # [L, cap] f32
    ids: jax.Array          # [L, cap] int32 (== store row; -1 pad)
    counts: jax.Array       # [L] int32
    store: ShardedVectorStore
    metric: str = "sqeuclidean"

    @property
    def n_lists(self) -> int:
        return int(self.codes.shape[0])

    @property
    def list_cap(self) -> int:
        return int(self.codes.shape[1])

    @property
    def dim(self) -> int:
        return int(self.rotation.shape[0])

    @property
    def size(self) -> int:
        return int(jnp.sum(self.counts))  # jaxlint: disable=JX01 size is a host-facing API scalar, not on the search path

    @property
    def resident_bytes(self) -> int:
        """Device-resident bytes: everything search keeps in accelerator
        memory (the codes tier — NOT the raw rows)."""
        return sum(int(np.dtype(getattr(self, f).dtype).itemsize)
                   * int(np.prod(getattr(self, f).shape))
                   for f in _ARRAY_FIELDS)

    @property
    def host_bytes(self) -> int:
        """Host-side bytes of the full-precision row store."""
        return int(self.store.nbytes)


# ---------------------------------------------------------------------------
# Transfer accounting — the boundedness seam.  EVERY host→device transfer
# the search loop performs goes through _stage_to_device, so a test (or a
# budget audit) can assert that no staged put ever exceeds slab_budget —
# i.e. there is no hidden full-slab device_put anywhere in the tier.
# ---------------------------------------------------------------------------

_transfer_lock = lockdep.lock("ooc._transfer_lock")
_transfer = {"puts": 0, "put_bytes": 0, "max_put_bytes": 0,  # guarded_by: _transfer_lock
             "fetch_bytes": 0}


def transfer_stats() -> dict:
    """Snapshot of the search loop's transfer accounting: ``puts`` /
    ``put_bytes`` / ``max_put_bytes`` (host→device stages) and
    ``fetch_bytes`` (host store → staging buffers)."""
    with _transfer_lock:
        return dict(_transfer)


def reset_transfer_stats() -> None:
    with _transfer_lock:
        for k in _transfer:
            _transfer[k] = 0


def _stage_to_device(host_arr):
    """The ONLY host→device path in the search loop: explicit
    ``device_put`` (guard-clean under ``transfer_guard("disallow")``)
    plus byte accounting."""
    nb = int(host_arr.nbytes)
    with _transfer_lock:
        _transfer["puts"] += 1
        _transfer["put_bytes"] += nb
        _transfer["max_put_bytes"] = max(_transfer["max_put_bytes"], nb)
    return jax.device_put(host_arr)


def _note_fetch(nbytes: int) -> None:
    with _transfer_lock:
        _transfer["fetch_bytes"] += int(nbytes)
    from ..obs.metrics import registry

    registry().counter(
        "raft_ooc_rerank_fetch_bytes_total",
        "bytes gathered from the host shard store for exact rerank",
    ).inc(n=float(nbytes))


# ---------------------------------------------------------------------------
# Build — the PR 4 pipelined streaming path, writing shards as it goes.
# ---------------------------------------------------------------------------


def _ooc_step_impl(slabs, counts, centroids, rotation, rotc, xc, idc, *,
                   n_lists: int, cap: int):
    """The ivf_rabitq chunk step minus the raw-data slab: masked capped
    assignment + RaBitQ encode + scatter-append over FIVE payload slabs
    (codes, sabs, rn2, cs, ids).  The raw rows never touch the device on
    the packing side — they stream to the shard store host-side."""
    from ..cluster.kmeans import _capped_assign_impl
    from ._packing import _scatter_append_impl

    valid = idc >= 0
    labels, _ = _capped_assign_impl(xc, centroids, cap - counts, valid)
    codes, sabs, rn2, cs = _rq._encode(xc, labels, centroids, rotation,
                                       rotc)
    return _scatter_append_impl(slabs, counts, labels,
                                (codes, sabs, rn2, cs, idc),
                                n_lists=n_lists, cap=cap)


_ooc_chunk_step = partial(jax.jit, static_argnames=("n_lists", "cap"),
                          donate_argnums=(0, 1))(_ooc_step_impl)


def _empty_slabs(n_lists: int, cap: int, d: int):
    from ._packing import device_full

    db = -(-d // 8)
    return (device_full((n_lists, cap, db), 0, jnp.uint8),
            device_full((n_lists, cap), 0.0, jnp.float32),
            device_full((n_lists, cap), 0.0, jnp.float32),
            device_full((n_lists, cap), 0.0, jnp.float32),
            device_full((n_lists, cap), -1, jnp.int32))


def _stream_pipelined(dataset, centroids, rotation, p: OocIndexParams,
                      n: int, cap: int, chunk_rows: int, writer, dtype,
                      heartbeat=None):
    """Pipelined chunk engine: background host reads
    (:func:`~._packing.prefetch_chunks`), the shard write on the staging
    thread (so disk IO overlaps device compute), fixed-shape padded
    device staging one chunk ahead, and the fused donated chunk step —
    one executable, one dispatch per chunk."""
    from ._packing import device_full, prefetch_chunks

    d = dataset.shape[1]
    slabs = _empty_slabs(p.n_lists, cap, d)
    counts = device_full((p.n_lists,), 0, jnp.int32)
    rotc = _rq._rotated_centroids(centroids, rotation)
    store_dtype = writer.dtype if writer is not None else None

    def stage(item):
        lo, hi, xc_h, idc_h = item
        xc_h = np.asarray(xc_h)
        if dtype is not None:
            xc_h = xc_h.astype(np.dtype(str(dtype)), copy=False)
        if writer is not None:
            writer.append(xc_h.astype(store_dtype, copy=False))
        idc_h = np.asarray(idc_h, np.int32)
        rows = hi - lo
        if rows < chunk_rows:  # pad the tail to the one fixed shape
            xp = np.zeros((chunk_rows, d), xc_h.dtype)
            xp[:rows] = xc_h
            ip = np.full((chunk_rows,), -1, np.int32)
            ip[:rows] = idc_h
            xc_h, idc_h = xp, ip
        return lo, hi, jax.device_put(xc_h), jax.device_put(idc_h)

    for lo, hi, xc, idc in device_prefetch(
            prefetch_chunks(dataset, chunk_rows, None), stage):
        slabs, counts = _ooc_chunk_step(slabs, counts, centroids, rotation,
                                        rotc, xc, idc, n_lists=p.n_lists,
                                        cap=cap)
        if heartbeat is not None:
            heartbeat(hi)
    return slabs, counts


def _stream_perop(dataset, centroids, rotation, p: OocIndexParams, n: int,
                  cap: int, chunk_rows: int, writer, dtype):
    """Reference per-op chunk loop (blocking H2D, separate dispatches,
    sequential shard writes) — the bit-parity oracle for the pipelined
    engine and the A/B baseline of ``bench/build_throughput.py``."""
    from ..cluster.kmeans import capped_assign_room
    from ._packing import prefetch_chunks, scatter_append

    d = dataset.shape[1]
    db = -(-d // 8)
    slabs = (jnp.zeros((p.n_lists, cap, db), jnp.uint8),
             jnp.zeros((p.n_lists, cap), jnp.float32),
             jnp.zeros((p.n_lists, cap), jnp.float32),
             jnp.zeros((p.n_lists, cap), jnp.float32),
             jnp.full((p.n_lists, cap), -1, jnp.int32))
    counts = jnp.zeros((p.n_lists,), jnp.int32)
    rotc = _rq._rotated_centroids(centroids, rotation)
    for lo, hi, xc_h, idc_h in prefetch_chunks(dataset, chunk_rows, None):
        if writer is not None:
            writer.append(np.asarray(xc_h).astype(writer.dtype, copy=False))
        xc = jnp.asarray(xc_h, dtype)
        idc = jnp.asarray(idc_h, jnp.int32)
        labels, _ = capped_assign_room(xc, centroids, cap - counts)
        codes, sabs, rn2, cs = _rq._encode(xc, labels, centroids, rotation,
                                           rotc)
        slabs, counts = scatter_append(
            slabs, counts, labels, (codes, sabs, rn2, cs, idc),
            n_lists=p.n_lists, cap=cap)
    return slabs, counts


def _build_with(stream, dataset, params, store_path, chunk_rows):
    from .ivf_flat import _coarse_train_chunked
    from ._packing import build_heartbeat, resolve_chunk_rows

    p = params or OocIndexParams()
    n, d = dataset.shape
    expects(p.n_lists >= 1 and p.n_lists <= n, "n_lists out of range")
    expects(p.metric in ("sqeuclidean", "euclidean", "inner_product"),
            f"unsupported metric {p.metric!r}")
    cap = max(1, int(np.ceil(p.list_cap_ratio * n / p.n_lists)))
    dtype = jnp.asarray(np.asarray(dataset[:1])).dtype
    chunk_rows = resolve_chunk_rows(chunk_rows, n, d, "ivf_rabitq")

    centroids = _coarse_train_chunked(dataset, p, n)
    rotation = _rq._rotation(d, p.seed)
    writer = ShardWriter(store_path, d, np.dtype(str(dtype)),
                         p.rows_per_shard)
    hb = (build_heartbeat("ooc.build_chunked", n)
          if stream is _stream_pipelined else None)
    kwargs = {"heartbeat": hb} if hb is not None else {}
    (codes, sabs, rn2, cs, ids_slab), counts = stream(
        dataset, centroids, rotation, p, n, cap, chunk_rows, writer, dtype,
        **kwargs)
    store = writer.close()
    return OocIndex(centroids, rotation, codes, sabs, rn2, cs, ids_slab,
                    counts, store, p.metric)


@tracing.annotate("ooc.build_chunked")
def build_chunked(dataset, params: Optional[OocIndexParams] = None, *,
                  store_path: str, chunk_rows: int = 0,
                  res=None) -> OocIndex:
    """Streaming out-of-core build: the dataset flows once through the
    fused slab-donating chunk step (device side: assign + encode +
    scatter over the FIVE code slabs) while the same host chunks append
    to the shard store at ``store_path``.  Peak host memory is bounded
    by ``chunk_rows``; peak device memory by the code slabs + two staged
    chunks — the raw rows never materialize on device.  Store row ids
    are positional (row ``i`` of ``dataset`` = store row ``i``), which
    is the id space the search tier's survivors address."""
    return _build_with(_stream_pipelined, dataset, params, store_path,
                       chunk_rows)


@tracing.annotate("ooc.build")
def build(dataset, params: Optional[OocIndexParams] = None, *,
          store_path: str, chunk_rows: int = 0, res=None) -> OocIndex:
    """Alias of :func:`build_chunked` — the out-of-core family has no
    resident one-shot path by design (an index whose point is not
    holding the rows should not require holding the rows to build)."""
    return build_chunked(dataset, params, store_path=store_path,
                         chunk_rows=chunk_rows, res=res)


def _build_chunked_perop(dataset, params: Optional[OocIndexParams] = None,
                         *, store_path: str,
                         chunk_rows: int = 0) -> OocIndex:
    """:func:`build_chunked` on the reference per-op loop — parity
    oracle / A/B baseline; not public API."""
    return _build_with(_stream_perop, dataset, params, store_path,
                       chunk_rows)


# ---------------------------------------------------------------------------
# Search — the three-phase cooperative loop.
# ---------------------------------------------------------------------------


@partial(jax.jit, static_argnames=("n_probes", "rerank_k", "metric",
                                   "probe_block", "scan_kernel"))
def _survivors_impl(centroids, rotation, codes, sabs, res_norms,
                    code_cdots, ids, counts, q, n_probes: int,
                    rerank_k: int, metric: str, keep=None,
                    probe_block: int = 1, scan_kernel: str = "xla"):
    # scan_kernel rides the static signature exactly like ivf_rabitq's
    # _search_impl; both arms dispatch the XLA estimator scan today (the
    # "fused" request is counted at resolve time)
    del scan_kernel
    qf = q.astype(jnp.float32)
    cd = sq_l2(q, centroids)
    _, probes = jax.lax.top_k(-cd, n_probes)
    bv, bi, _ = _rq._estimate_survivors(qf, cd, centroids, rotation, codes,
                                        sabs, res_norms, code_cdots, ids,
                                        counts, probes, rerank_k, metric,
                                        keep, probe_block)
    return bv, bi


def _rerank_core(slab, bv, bi, q, k: int, metric: str):
    """Exact re-score of the fetched survivor slab in brute accumulation
    order: the slab flattens to ``[nq·rerank_k, d]`` and re-scores
    through the SAME stored-norm-free :func:`~raft_tpu.ops.blocked_scan
    .l2_rescorer` seam ivf_rabitq uses — identical algebra on identical
    row values, which is what carries the ``rerank_k = n`` bitwise
    contract across the host round-trip."""
    nq, rk = bi.shape
    flat = slab.reshape(nq * rk, slab.shape[-1])
    qf = q.astype(jnp.float32)
    qn = _scan.row_sq_norms(qf)
    rescore = _scan.l2_rescorer(flat, None, q, qn, metric)
    ptr = jnp.arange(nq * rk, dtype=jnp.int32).reshape(nq, rk)
    dist = rescore(ptr, bi)
    dist = jnp.where(jnp.isfinite(bv) & (bi >= 0), dist, jnp.inf)
    dv, di = _scan.ranked_finish(dist, bi, k)
    if metric == "euclidean":
        dv = jnp.sqrt(jnp.maximum(dv, 0.0))
    elif metric == "inner_product":
        dv = -dv
    return dv, di


_rerank_impl = partial(jax.jit, static_argnames=("k", "metric"))(
    _rerank_core)


def _resolved_static(index: OocIndex, k: int, p: OocSearchParams):
    """(n_probes, probe_block, rerank_k, scan_kernel) — ivf_rabitq's
    resolution verbatim (same estimator scan, same tuned table), with
    the "fused" request counted through the gate fallback counter."""
    return _rq._resolved_static(index, k, p)


def _resolve_query_chunk(p: OocSearchParams, nq: int, rerank_k: int,
                         d: int, itemsize: int) -> int:
    """Queries per cooperative step: ``query_chunk`` capped so one
    staged rerank slab (``chunk · rerank_k · d · itemsize``) fits
    ``slab_budget``.  Host-int arithmetic only."""
    per_q = int(rerank_k) * int(d) * int(itemsize)
    expects(int(p.slab_budget) >= per_q,
            f"slab_budget ({int(p.slab_budget)} B) is below one query's "
            f"rerank slab ({per_q} B = rerank_k·d·itemsize); raise "
            "slab_budget or lower rerank_k")
    budget_rows = int(p.slab_budget) // per_q
    return max(1, min(int(nq), int(p.query_chunk) or 1024, budget_rows))


@tracing.annotate("ooc.search")
def search(index: OocIndex, queries, k: int,
           params: Optional[OocSearchParams] = None, *, filter=None,
           res=None) -> Tuple[jax.Array, jax.Array]:
    """Out-of-core kNN with EXACT returned values.  Per query chunk:
    device estimator scan → host gather of the top-``rerank_k`` survivor
    rows from the shard store → one bounded slab put → exact rerank.
    ``filter``: optional shared (1-D) bitset prefilter by source id —
    per-query bitmaps don't ride the cooperative loop's fixed operand
    shapes."""
    from ._packing import (as_keep_mask, check_filter_covers_ids,
                           sentinel_filtered_ids)
    from ..obs.spans import recorder

    p = params or OocSearchParams()
    expects(k >= 1, "k must be >= 1")
    q = wrap_array(queries, ndim=2, name="queries")
    expects(q.shape[1] == index.dim, "query dim mismatch")
    n_probes, probe_block, rerank_k, scan_kernel = _resolved_static(
        index, k, p)
    keep = as_keep_mask(filter)
    if keep is not None:
        expects(keep.ndim == 1,
                "ooc filters are shared bitsets (1-D); per-query bitmaps "
                "can't ride the cooperative loop's fixed operand shapes")
        check_filter_covers_ids(keep, index.ids)
    store = index.store
    d = index.dim
    itemsize = store.dtype.itemsize
    nq = int(q.shape[0])
    chunk = _resolve_query_chunk(p, nq, rerank_k, d, itemsize)
    pool = default_host_pool(res)
    rec = recorder()
    qh = np.asarray(jax.device_get(q))  # jaxlint: disable=JX01 the cooperative tier is host-driven by design: queries chunk host-side, survivors fetch host-side
    bounds = [(lo, min(nq, lo + chunk)) for lo in range(0, nq, chunk)]
    metric = index.metric

    def stage(b):
        lo, hi = b
        rows = hi - lo
        qbuf = pool.acquire((chunk, d), qh.dtype)
        qbuf[:rows] = qh[lo:hi]
        if rows < chunk:
            qbuf[rows:] = 0
        qd = _stage_to_device(qbuf)
        with rec.span("ooc.estimate", nq=rows):
            bv, bi = _survivors_impl(
                index.centroids, index.rotation, index.codes, index.sabs,
                index.res_norms, index.code_cdots, index.ids, index.counts,
                qd, n_probes, rerank_k, metric, keep, probe_block,
                scan_kernel)
            bi_h = np.asarray(jax.device_get(bi))  # jaxlint: disable=JX01 phase boundary: survivor ids must reach the host to address the shard store
        with rec.span("ooc.fetch", rows=int(bi_h.size)):
            slab_h = pool.acquire((chunk, rerank_k, d), store.dtype)
            store.gather(bi_h, out=slab_h.reshape(chunk * rerank_k, d),
                         fetch_batch=int(p.fetch_batch), pool=pool)
            _note_fetch(slab_h.nbytes)
        slab = _stage_to_device(slab_h)
        return lo, hi, qd, slab, bv, bi, (qbuf, slab_h)

    it = (device_prefetch(bounds, stage) if p.overlap and len(bounds) > 1
          else (stage(b) for b in bounds))
    outs = []
    pending = None  # (prev chunk's dv, its pooled host buffers)
    for lo, hi, qd, slab, bv, bi, bufs in it:
        with rec.span("ooc.rerank", nq=hi - lo):
            dv, di = _rerank_impl(slab, bv, bi, qd, k=int(k),
                                  metric=metric)
        outs.append((lo, hi, dv, di))
        if pending is not None:
            pdv, pbufs = pending
            # the previous rerank consumed its staged buffers once this
            # is ready — only then may the pool hand them out again
            jax.block_until_ready(pdv)  # jaxlint: disable=JX05 pool-buffer lifetime barrier: device_put may alias host memory, so the staging buffers return to the pool only after the rerank that read them completes
            for buf in pbufs:
                pool.release(buf)
        pending = (dv, bufs)
    if pending is not None:
        pdv, pbufs = pending
        jax.block_until_ready(pdv)  # jaxlint: disable=JX05 final pool-buffer lifetime barrier (see above): last chunk's staging buffers return only after its rerank completes
        for buf in pbufs:
            pool.release(buf)
    if len(outs) == 1:
        lo, hi, dv, di = outs[0]
        dv, di = dv[:hi - lo], di[:hi - lo]
    else:
        dv = jnp.concatenate([o[2][:o[1] - o[0]] for o in outs], axis=0)
        di = jnp.concatenate([o[3][:o[1] - o[0]] for o in outs], axis=0)
    if keep is not None:  # sub-k survivors: sentinel tail, not real ids
        di = sentinel_filtered_ids(dv, di)
    from ..core.host_memory import export_host_pool_metrics

    export_host_pool_metrics(pool)
    return dv, di


def searcher(index: OocIndex, k: int,
             params: Optional[OocSearchParams] = None, *, filter=None):
    """Uniform serving entry point (``raft_tpu.serve`` contract):
    ``(fn, operands)`` with ``fn(queries, *operands)`` equal to
    :func:`search` for batches up to the resolved query chunk.  The
    host gather rides INSIDE the traced function as a
    ``jax.pure_callback`` with a static ``[nq, rerank_k, d]`` result
    shape, so the searcher AOT-compiles through the serve executable
    cache like every resident family — the callback closure holds the
    shard store, the device slabs ride as operands."""
    from ._packing import (as_keep_mask, check_filter_covers_ids,
                           sentinel_filtered_ids)

    p = params or OocSearchParams()
    expects(k >= 1, "k must be >= 1")
    n_probes, probe_block, rerank_k, scan_kernel = _resolved_static(
        index, k, p)
    metric = index.metric
    store = index.store
    d = index.dim
    fetch_batch = int(p.fetch_batch)
    keep = as_keep_mask(filter)
    if keep is not None:
        expects(keep.ndim == 1,
                "serving filters are shared bitsets (1-D)")
        check_filter_covers_ids(keep, index.ids)

    def fetch_host(bi):
        bi = np.asarray(bi)
        out = np.empty((bi.size, d), store.dtype)
        store.gather(bi, out=out, fetch_batch=fetch_batch)
        _note_fetch(out.nbytes)
        return out.reshape(bi.shape[0], rerank_k, d)

    def core(q, centroids, rotation, codes, sabs, res_norms, code_cdots,
             ids, counts, kp):
        bv, bi = _survivors_impl(centroids, rotation, codes, sabs,
                                 res_norms, code_cdots, ids, counts, q,
                                 n_probes, rerank_k, metric, kp,
                                 probe_block, scan_kernel)
        slab = jax.pure_callback(
            fetch_host,
            jax.ShapeDtypeStruct((q.shape[0], rerank_k, d), store.dtype),
            bi)
        return _rerank_core(slab, bv, bi, q, int(k), metric)

    if keep is not None:
        def fn(q, centroids, rotation, codes, sabs, res_norms, code_cdots,
               ids, counts, kp):
            dv, di = core(q, centroids, rotation, codes, sabs, res_norms,
                          code_cdots, ids, counts, kp)
            return dv, sentinel_filtered_ids(dv, di)

        return fn, (index.centroids, index.rotation, index.codes,
                    index.sabs, index.res_norms, index.code_cdots,
                    index.ids, index.counts, keep)

    def fn(q, centroids, rotation, codes, sabs, res_norms, code_cdots,
           ids, counts):
        return core(q, centroids, rotation, codes, sabs, res_norms,
                    code_cdots, ids, counts, None)

    return fn, (index.centroids, index.rotation, index.codes, index.sabs,
                index.res_norms, index.code_cdots, index.ids, index.counts)


# ---------------------------------------------------------------------------
# Persistence — format v5: a manifest directory wrapping a device bundle
# (per-array CRCs) and the shard store (per-shard CRCs).
# ---------------------------------------------------------------------------


def save(path, index: OocIndex, *, manifest: Optional[dict] = None,
         fsync: bool = True) -> None:
    """Persist to a v5 manifest directory::

        path/meta.json   index_type/format_version/static + manifest
        path/device/     the eight device arrays (save_arrays bundle,
                         atomic, per-array CRC32)
        path/shards/     the raw-row store (copied in unless the index
                         already built it here; per-shard CRC32)

    ``meta.json`` publishes LAST, so a reader (or :func:`verify`) never
    sees a half-written artifact as openable."""
    from ..core.serialize import fsync_dir, save_arrays, write_text_atomic

    path = os.fspath(path)
    os.makedirs(path, exist_ok=True)
    arrays = {f: np.asarray(jax.device_get(getattr(index, f)))  # jaxlint: disable=JX01 checkpoint write, off the search path
              for f in _ARRAY_FIELDS}
    save_arrays(os.path.join(path, _DEVICE_DIR), arrays,
                metadata={"index_type": "OocIndex"},
                atomic=True, fsync=fsync)
    dst = os.path.join(path, _SHARDS_DIR)
    src = os.path.abspath(index.store.path)
    if src != os.path.abspath(dst):
        if os.path.exists(dst):
            shutil.rmtree(dst)
        shutil.copytree(src, dst)
    meta = {
        "index_type": "OocIndex",
        "format_version": _FORMAT_VERSION,
        "static": {"metric": index.metric},
        "store": _SHARDS_DIR,
        "manifest": dict(manifest or {}),
    }
    write_text_atomic(os.path.join(path, _META), json.dumps(meta, indent=1))
    if fsync:
        fsync_dir(path)


def _read_meta(path: str) -> dict:
    import pathlib

    mp = pathlib.Path(path) / _META
    expects(mp.exists(), f"ooc.open: no {_META} under {path!r}")
    meta = json.loads(mp.read_text())
    expects(meta.get("index_type") == "OocIndex",
            f"{path!r} is not an OocIndex artifact "
            f"(index_type={meta.get('index_type')!r})")
    if meta.get("format_version", 0) > _FORMAT_VERSION:
        raise ValueError(
            f"{path!r}: format_version {meta['format_version']} is newer "
            f"than supported {_FORMAT_VERSION}")
    return meta


def open(path, *, verify: bool = False, res=None) -> OocIndex:
    """Open a :func:`save` artifact.  The device bundle loads (and
    ``device_put``s) the code tier; the shard store maps LAZILY — no
    shard is read until a survivor gather touches it, so opening a
    TB-scale store costs metadata only.  ``verify=True`` checks the
    device arrays' CRCs first (shard CRCs are a :func:`verify` sweep —
    re-reading terabytes at open time would defeat the layout)."""
    from ..core.serialize import load_arrays

    path = os.fspath(path)
    meta = _read_meta(path)
    arrays, _ = load_arrays(os.path.join(path, _DEVICE_DIR), verify=verify)
    store = ShardedVectorStore.open(
        os.path.join(path, meta.get("store", _SHARDS_DIR)))
    fields = {f: jax.device_put(arrays[f]) for f in _ARRAY_FIELDS}
    return OocIndex(store=store,
                    metric=meta.get("static", {}).get("metric",
                                                      "sqeuclidean"),
                    **fields)


def verify(path) -> list:
    """Integrity-check a :func:`save` artifact without opening it:
    meta.json well-formed, device bundle CRCs, per-shard store CRCs.
    Returns a list of problems (empty = intact)."""
    from ..core.serialize import verify_arrays

    path = os.fspath(path)
    problems = []
    try:
        meta = _read_meta(path)
    except Exception as exc:
        return [str(exc)]
    problems.extend(verify_arrays(os.path.join(path, _DEVICE_DIR)))
    store_dir = os.path.join(path, meta.get("store", _SHARDS_DIR))
    try:
        store = ShardedVectorStore.open(store_dir)
        problems.extend(store.verify())
    except Exception as exc:
        problems.append(f"shard store unreadable: {exc}")
    return problems
