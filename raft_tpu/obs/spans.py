"""Structured spans + the always-on flight recorder.

A :class:`Span` is a named monotonic-clock interval with attributes,
a ``trace_id`` grouping one logical operation (e.g. one serve request)
and a ``parent_id`` forming the tree.  A :class:`SpanRecorder` retains
finished spans in **per-thread ring buffers** — appends touch only the
calling thread's ring (no lock on the record path; ring registration
takes the lock once per thread), so the recorder can stay enabled on the
serve hot path as a *flight recorder*: when something wedges, the last
``capacity_per_thread`` spans of every thread are still in memory and
can be dumped (:mod:`raft_tpu.obs.perfetto`,
:mod:`raft_tpu.obs.watchdog`).

Span times come from ``time.monotonic_ns`` (injectable), never the wall
clock — the recorder must keep working while a fake-clock server is
driven deterministically, and interval math must survive NTP steps.

Parentage is resolved three ways, in order: an explicit ``parent=``
(a :class:`Span` — the cross-thread case: the serve dispatch thread
parents its spans under the client thread's request span), else the
innermost open span **on the calling thread** (``with recorder.span()``
nesting), else the span roots a fresh trace.

The process-wide default recorder (:func:`recorder` /
:func:`set_recorder`) is what :mod:`raft_tpu.core.tracing` and the
serving runtime write into; ``RAFT_OBS_SPANS=0`` starts it disabled and
``RAFT_OBS_RING`` sizes its rings.
"""

from __future__ import annotations

import contextlib
import itertools
import os
import threading
import time
from typing import Dict, Iterator, List, Optional, Union

__all__ = ["Span", "SpanRecorder", "recorder", "set_recorder"]

# itertools.count.__next__ is atomic under the GIL — ids are unique
# across threads without a lock.
_ids = itertools.count(1)


class Span:
    """One named interval.  ``t_end_ns == 0`` while still open; ``attrs``
    is a plain dict the owner may extend until :meth:`SpanRecorder.finish`
    (instant events have ``t_end_ns == t_start_ns``)."""

    __slots__ = ("name", "t_start_ns", "t_end_ns", "trace_id", "span_id",
                 "parent_id", "tid", "thread_name", "attrs")

    def __init__(self, name: str, t_start_ns: int, trace_id: int,
                 span_id: int, parent_id: Optional[int], tid: int,
                 thread_name: str, attrs: Dict) -> None:
        self.name = name
        self.t_start_ns = t_start_ns
        self.t_end_ns = 0
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.tid = tid
        self.thread_name = thread_name
        self.attrs = attrs

    @property
    def duration_ns(self) -> int:
        return max(0, self.t_end_ns - self.t_start_ns)

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return (f"Span({self.name!r}, id={self.span_id}, "
                f"parent={self.parent_id}, trace={self.trace_id}, "
                f"dur={self.duration_ns / 1e6:.3f}ms, attrs={self.attrs})")


class _Ring:
    """Fixed-capacity overwrite-oldest buffer, owned by ONE thread.

    Appends are unlocked (only the owner writes); ``snapshot`` copies the
    list reference under the recorder lock and re-orders by append index,
    tolerating a concurrent append (worst case one torn slot, never a
    crash — list reads/writes are atomic under the GIL)."""

    __slots__ = ("cap", "buf", "n")

    def __init__(self, cap: int) -> None:
        self.cap = cap
        self.buf: List[Span] = []
        self.n = 0

    def append(self, span: Span) -> None:
        if len(self.buf) < self.cap:
            self.buf.append(span)
        else:
            self.buf[self.n % self.cap] = span
        self.n += 1

    def snapshot(self) -> List[Span]:
        if self.n <= self.cap:
            return list(self.buf)
        cut = self.n % self.cap
        return self.buf[cut:] + self.buf[:cut]


class SpanRecorder:
    """Low-overhead span sink with per-thread flight-recorder rings.

    ``enabled=False`` turns every record call into an early return (the
    compile-it-out story, like ``RAFT_TPU_TRACING=0``); flipping
    :attr:`enabled` at runtime is safe — open spans still finish, they
    are just not retained."""

    def __init__(self, capacity_per_thread: int = 4096, *,
                 clock_ns=time.monotonic_ns, enabled: bool = True) -> None:
        from ..core import lockdep
        from ..core.errors import expects

        expects(capacity_per_thread >= 1,
                "capacity_per_thread must be >= 1")
        self.capacity_per_thread = int(capacity_per_thread)
        self.clock_ns = clock_ns
        self.enabled = bool(enabled)
        self._lock = lockdep.lock("SpanRecorder._lock")
        self._rings: Dict[int, _Ring] = {}      # guarded_by: _lock  tid -> ring
        self._tls = threading.local()

    # -- per-thread state ---------------------------------------------------

    def _ring(self) -> _Ring:
        ring = getattr(self._tls, "ring", None)
        if ring is None:
            ring = _Ring(self.capacity_per_thread)
            self._tls.ring = ring
            t = threading.current_thread()
            self._tls.tid = t.ident or 0
            self._tls.tname = t.name
            with self._lock:
                self._rings[self._tls.tid] = ring
        return ring

    def _stack(self) -> List[Span]:
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = []
            self._tls.stack = stack
        return stack

    def current(self) -> Optional[Span]:
        """The innermost open ``with``-span on the calling thread."""
        stack = self._stack()
        return stack[-1] if stack else None

    def _lineage(self, parent: Union[Span, int, None]):
        if parent is None:
            parent = self.current()
        if parent is None:
            span_id = next(_ids)
            return span_id, span_id, None       # fresh trace, self-rooted
        if isinstance(parent, Span):
            return next(_ids), parent.trace_id, parent.span_id
        return next(_ids), int(parent), int(parent)

    # -- recording ----------------------------------------------------------

    def start(self, name: str, parent: Union[Span, int, None] = None,
              **attrs) -> Optional[Span]:
        """Open a span WITHOUT pushing it on the thread's nesting stack —
        the handle-passing form for spans that end on another thread
        (e.g. a serve request: opened at ``submit()`` on the client
        thread, finished by the dispatch thread at reply)."""
        if not self.enabled:
            return None
        self._ring()  # bind tid/tname before reading them
        span_id, trace_id, parent_id = self._lineage(parent)
        return Span(name, self.clock_ns(), trace_id, span_id, parent_id,
                    self._tls.tid, self._tls.tname, attrs)

    def finish(self, span: Optional[Span], **attrs) -> None:
        """Close ``span`` and retain it in the *finishing* thread's ring.
        Idempotent — a second finish (e.g. the parts of a split request
        sharing one root) updates attrs but does not re-append; ``None``
        (from a disabled :meth:`start`) is a no-op."""
        if span is None or not self.enabled:
            return
        if attrs:
            span.attrs.update(attrs)
        if span.t_end_ns != 0:
            return
        span.t_end_ns = self.clock_ns()
        self._ring().append(span)

    @contextlib.contextmanager
    def span(self, name: str, parent: Union[Span, int, None] = None,
             **attrs) -> Iterator[Optional[Span]]:
        """RAII span, pushed on the thread's nesting stack so inner spans
        auto-parent to it.  Exception-safe: the span finishes (and the
        stack pops) even when the body raises, recording ``error=``."""
        if not self.enabled:
            yield None
            return
        sp = self.start(name, parent, **attrs)
        stack = self._stack()
        stack.append(sp)
        try:
            yield sp
        except BaseException as exc:
            sp.attrs["error"] = type(exc).__name__
            raise
        finally:
            if stack and stack[-1] is sp:
                stack.pop()
            elif sp in stack:       # tolerate interleaved manual pops
                stack.remove(sp)
            self.finish(sp)

    def record(self, name: str, t_start_ns: int, t_end_ns: int,
               parent: Union[Span, int, None] = None,
               **attrs) -> Optional[Span]:
        """Retain an already-measured interval (post-hoc recording: the
        caller timed a region under its own lock and records the span
        after releasing it, keeping the recorder off the critical
        section)."""
        if not self.enabled:
            return None
        self._ring()
        span_id, trace_id, parent_id = self._lineage(parent)
        sp = Span(name, int(t_start_ns), trace_id, span_id, parent_id,
                  self._tls.tid, self._tls.tname, attrs)
        sp.t_end_ns = int(t_end_ns)
        self._ring().append(sp)
        return sp

    def event(self, name: str, parent: Union[Span, int, None] = None,
              **attrs) -> Optional[Span]:
        """Zero-duration marker (a counted occurrence with context —
        e.g. a gate fallback, a quarantined file)."""
        if not self.enabled:
            return None
        now = self.clock_ns()
        return self.record(name, now, now, parent, **attrs)

    # -- draining -----------------------------------------------------------

    def snapshot(self) -> List[Span]:
        """Every retained span across all threads, oldest first (the
        flight-recorder dump).  Never blocks recorders: rings are copied,
        not locked."""
        with self._lock:
            rings = list(self._rings.values())
        spans: List[Span] = []
        for ring in rings:
            spans.extend(ring.snapshot())
        spans.sort(key=lambda s: (s.t_start_ns, s.span_id))
        return spans

    def clear(self) -> None:
        """Drop retained spans (rings stay registered; open spans keep
        their handles and will re-enter fresh rings on finish)."""
        with self._lock:
            for ring in self._rings.values():
                ring.buf = []
                ring.n = 0

    def stats(self) -> dict:
        """Recorder gauges: retained spans, total recorded, threads."""
        with self._lock:
            rings = list(self._rings.items())
        return {
            "threads": len(rings),
            "retained": sum(len(r.buf) for _, r in rings),
            "recorded": sum(r.n for _, r in rings),
            "capacity_per_thread": self.capacity_per_thread,
            "enabled": self.enabled,
        }


_default: Optional[SpanRecorder] = None  # guarded_by: _default_lock
_default_lock = threading.Lock()


def recorder() -> SpanRecorder:
    """The process-wide flight recorder (created on first use;
    ``RAFT_OBS_SPANS=0`` starts it disabled, ``RAFT_OBS_RING`` sizes the
    per-thread rings, default 4096)."""
    global _default
    with _default_lock:
        if _default is None:
            _default = SpanRecorder(
                int(os.environ.get("RAFT_OBS_RING", "4096")),
                enabled=os.environ.get("RAFT_OBS_SPANS", "1") != "0")
        return _default


def set_recorder(rec: SpanRecorder) -> SpanRecorder:
    """Swap the process-wide recorder (tests; embedding hosts that own
    their telemetry wiring).  Returns the previous one."""
    global _default
    with _default_lock:
        prev, _default = _default, rec
        return prev if prev is not None else rec
