"""Query-distribution drift — a streaming PSI sketch vs a build baseline.

An ANN index is tuned to the query distribution it was built and
calibrated against: IVF probe counts assume queries land near the same
centroids the corpus clustered into, CAGRA's router seeds assume the
same regions stay hot.  When the *live* query distribution walks away
from the build-time baseline, recall degrades even though nothing in
the serving stack changed — the drift is invisible to latency metrics
and only shows up in the online recall estimate after the damage.

:class:`DriftDetector` makes drift a first-class metric.  The sketch is
the classic monitoring one: a scalar *summary statistic* per query —
its squared distance to the nearest index reference point (IVF / CAGRA
centroids; a row subsample for brute databases) — histogrammed into
quantile buckets fitted on the **baseline** distribution, then compared
against the live window with the Population Stability Index

    PSI = Σ_i (q_i − p_i) · ln(q_i / p_i)

(p = baseline fraction, q = live fraction per bucket; ε-smoothed).  PSI
is symmetric-KL-flavored, zero iff the distributions match, and has
industry-standard alert thresholds: < 0.1 stable, 0.1–0.25 moderate
shift, ≥ 0.25 shifted.  Observations are fed from the quality
estimator's shadow-sample worker, so the sketch costs nothing on the
hot path and sees exactly the sampled traffic.

Pure stdlib + numpy at call time; jax only to pull reference points out
of device-resident indexes.
"""

from __future__ import annotations

from collections import deque

__all__ = ["DriftDetector", "PSI_MODERATE", "PSI_SHIFTED",
           "centroid_distances", "reference_points"]

PSI_MODERATE = 0.1
PSI_SHIFTED = 0.25
_EPS = 1e-4


def reference_points(index, m: int = 256, seed: int = 0):
    """Reference points the drift statistic measures distance to:
    coarse centroids for the IVF families, router centroids for CAGRA,
    a seeded ``m``-row subsample for a brute database.  Returns a numpy
    ``[r, d]`` f32 array."""
    import numpy as np

    import jax

    from ..neighbors.mutation import Tombstoned

    if isinstance(index, Tombstoned):
        index = index.index
    if hasattr(index, "centroids"):                    # ivf_flat / ivf_pq
        pts = index.centroids
    elif hasattr(index, "graph"):                      # cagra
        pts = index.router_centroids
    elif getattr(index, "ndim", None) == 2:            # brute database
        arr = np.asarray(jax.device_get(index), dtype=np.float32)  # jaxlint: disable=JX01 one-time baseline extraction, never on the search path
        rows = np.random.default_rng(seed).choice(
            arr.shape[0], size=min(m, arr.shape[0]), replace=False)
        return arr[np.sort(rows)]
    else:
        raise TypeError(f"no reference points for {type(index).__name__}")
    return np.asarray(jax.device_get(pts), dtype=np.float32)  # jaxlint: disable=JX01 one-time baseline extraction, never on the search path


def centroid_distances(points, queries):
    """Squared L2 distance from each query to its nearest reference
    point — the per-query drift statistic.  Plain numpy (runs on the
    oracle worker, not under jit)."""
    import numpy as np

    q = np.asarray(queries, dtype=np.float32)
    p = np.asarray(points, dtype=np.float32)
    d2 = ((q * q).sum(axis=1)[:, None] - 2.0 * (q @ p.T)
          + (p * p).sum(axis=1)[None, :])
    return np.maximum(d2.min(axis=1), 0.0)


class DriftDetector:
    """Streaming PSI of a scalar statistic vs its baseline distribution.

    ``baseline_values`` (1-D) fits the bucket boundaries (baseline
    quantiles, so every baseline bucket holds equal mass — the PSI
    binning with maximum sensitivity) and the baseline fractions; live
    values stream through :meth:`observe` into a bounded window.
    Attach ``points`` (or build via :meth:`from_index`) to enable
    :meth:`observe_queries`, the hook the quality estimator's worker
    calls with each shadow-sampled query batch.

    Sampling bias: even with NO drift, a finite live window reads
    E[PSI] ≈ (buckets − 1) / window — keep the window an order of
    magnitude above the bucket count (the defaults are 8 buckets /
    1024 window → bias ≈ 0.007, far under the 0.1 alert line)."""

    def __init__(self, baseline_values, *, n_buckets: int = 8,
                 window: int = 1024, points=None, registry=None) -> None:
        import numpy as np

        from ..core.errors import expects
        from .metrics import registry as default_registry

        base = np.asarray(baseline_values, dtype=np.float32).reshape(-1)
        expects(base.size >= 2, "drift baseline needs >= 2 values")
        expects(n_buckets >= 2, "n_buckets must be >= 2")
        expects(window >= 1, "window must be >= 1")
        # interior quantile cuts; dedup because a spiky baseline can
        # repeat a quantile, and boundaries must increase strictly
        qs = np.linspace(0.0, 1.0, n_buckets + 1)[1:-1]
        cuts = np.unique(np.quantile(base, qs))
        self.boundaries = tuple(float(c) for c in cuts)
        counts = np.histogram(base, bins=self._edges())[0]
        self._baseline_frac = counts / counts.sum()
        self.window = int(window)
        self._live: deque = deque(maxlen=self.window)
        self.points = points
        self.registry = registry if registry is not None \
            else default_registry()
        self._g_psi = self.registry.gauge(
            "raft_quality_drift_psi",
            "PSI of live query-to-centroid distances vs build baseline")
        self._g_n = self.registry.gauge(
            "raft_quality_drift_window", "live observations in the window")
        self._g_psi.set(0.0)
        self._g_n.set(0)

    @classmethod
    def from_index(cls, index, baseline_queries, *, m: int = 256,
                   seed: int = 0, **kw) -> "DriftDetector":
        """Fit a detector for ``index``: reference points from the index,
        baseline distances from a representative query sample (e.g. the
        tuning/calibration query set)."""
        pts = reference_points(index, m=m, seed=seed)
        return cls(centroid_distances(pts, baseline_queries),
                   points=pts, **kw)

    def _edges(self):
        import numpy as np

        return np.concatenate(([-np.inf], self.boundaries, [np.inf]))

    # -- streaming ----------------------------------------------------------

    def observe(self, values) -> None:
        """Fold scalar statistic values into the live window and refresh
        the exported PSI gauge."""
        import numpy as np

        for v in np.asarray(values, dtype=np.float32).reshape(-1):
            self._live.append(float(v))
        self._g_psi.set(self.psi())
        self._g_n.set(len(self._live))

    def observe_queries(self, queries, *, generation: int = 0) -> None:
        """Fold a raw query batch (distance-to-nearest-reference computed
        here) — the quality-worker hook.  Requires ``points``."""
        from ..core.errors import expects

        expects(self.points is not None,
                "observe_queries needs reference points — build with "
                "from_index() or pass points=")
        del generation  # one live window; labels would split the sketch
        self.observe(centroid_distances(self.points, queries))

    # -- scoring ------------------------------------------------------------

    def psi(self) -> float:
        """Population Stability Index of the live window vs the baseline
        (0.0 while the window is empty)."""
        import numpy as np

        if not self._live:
            return 0.0
        live = np.histogram(np.asarray(self._live), bins=self._edges())[0]
        q = (live + _EPS) / (live.sum() + _EPS * live.size)
        p = (self._baseline_frac * 1.0 + _EPS) \
            / (1.0 + _EPS * live.size)
        return float(((q - p) * np.log(q / p)).sum())

    def status(self) -> str:
        """``stable`` / ``moderate`` / ``shifted`` per the standard PSI
        thresholds (0.1 / 0.25)."""
        v = self.psi()
        if v >= PSI_SHIFTED:
            return "shifted"
        if v >= PSI_MODERATE:
            return "moderate"
        return "stable"

    def stats(self) -> dict:
        return {"psi": self.psi(), "status": self.status(),
                "window": len(self._live), "buckets": len(self.boundaries) + 1}
