"""raft_tpu.obs — unified telemetry: spans, metrics, exporters, watchdog.

The system's self-knowledge used to be fragmented — flat JSON counters
in ``serve.metrics``, uncollected profiler annotations in
``core/tracing``, gate fallbacks lost in the log stream, wedged-TPU
failures (BENCH_r04/r05) leaving no evidence.  This package is the one
substrate they all report through:

* **spans** (:mod:`.spans`) — monotonic-clock :class:`Span` trees with
  attributes, recorded into lock-cheap per-thread ring buffers: an
  always-on **flight recorder**.  ``core/tracing`` ranges feed it, the
  serve request lifecycle (enqueue → batch-form → dispatch →
  device-exec → reply) threads explicit parents through it, and WAL /
  snapshot / recovery / compaction annotate into it.
* **metrics** (:mod:`.metrics`) — counters, gauges and fixed-boundary
  **mergeable** histograms in a :class:`MetricRegistry`; the
  process-global :func:`registry` collects library-level events such as
  Pallas gate fallbacks (counted, with ``kernel``/``reason`` labels,
  instead of log lines).
* **exporters** — Prometheus text exposition (:mod:`.prometheus`) and
  Chrome-trace/Perfetto JSON of the flight recorder (:mod:`.perfetto`);
  the serving JSON schema (``SearchServer.metrics_snapshot``) is
  unchanged and now derivable from the same registry.
* **watchdog** (:mod:`.watchdog`) — :class:`StallWatchdog` detects a
  wedged device dispatch, dumps flight recorder + ``jax.profiler``
  capture to a quarantine directory (retained newest-K), and counts
  ``stalls`` instead of hanging silently.
* **quality** (:mod:`.quality`) — :class:`RecallEstimator`
  shadow-samples live requests and re-scores them against an exact
  blocked-scan oracle off the hot path: online recall@k with Wilson
  CIs, labeled by degradation level / scan kernel / generation.
* **drift** (:mod:`.drift`) — :class:`DriftDetector`, a streaming PSI
  sketch of the query-to-centroid distance distribution vs the
  build-time baseline.
* **slo** (:mod:`.slo`) — :class:`SloEvaluator`, multi-window burn
  rates over latency / availability / recall, and the ``quality_guard``
  the server's degradation ladder consults before entering a level.

Everything except the profiler capture is pure stdlib: importable
without jax, zero device interaction, safe on any host.  See
``docs/observability_guide.md`` for the span API, exporter endpoints and
the stall runbook.

>>> from raft_tpu import obs
>>> rec = obs.SpanRecorder(capacity_per_thread=8)
>>> with rec.span("request", rows=2) as root:
...     with rec.span("dispatch"):
...         pass
>>> [s.name for s in rec.snapshot()]
['request', 'dispatch']
>>> rec.snapshot()[1].parent_id == root.span_id
True
"""

from .drift import DriftDetector
from .metrics import (DEFAULT_LATENCY_BOUNDARIES_MS, Counter, Gauge,
                      Histogram, MetricRegistry, registry, set_registry)
from .perfetto import chrome_trace, export_chrome_trace
from .prometheus import parse_text, render, render_labeled
from .quality import (QualityConfig, RecallEstimate, RecallEstimator,
                      wilson_interval)
from .slo import SloEvaluator, SloPolicy
from .spans import Span, SpanRecorder, recorder, set_recorder
from .watchdog import StallWatchdog

__all__ = [
    "Span",
    "SpanRecorder",
    "recorder",
    "set_recorder",
    "Counter",
    "Gauge",
    "Histogram",
    "MetricRegistry",
    "registry",
    "set_registry",
    "DEFAULT_LATENCY_BOUNDARIES_MS",
    "render",
    "render_labeled",
    "parse_text",
    "chrome_trace",
    "export_chrome_trace",
    "StallWatchdog",
    "QualityConfig",
    "RecallEstimate",
    "RecallEstimator",
    "wilson_interval",
    "DriftDetector",
    "SloEvaluator",
    "SloPolicy",
]
