"""Metric registry — counters, gauges, fixed-boundary histograms.

The aggregation substrate under the exporters: every metric is a named
*family* holding one value per label-set, registered in a
:class:`MetricRegistry` so :mod:`raft_tpu.obs.prometheus` can walk and
render everything uniformly.

Histograms use **fixed bucket boundaries** (upper bounds, exclusive of
``+Inf``) chosen at registration.  Unlike the serving reservoir's exact
window percentiles, fixed-boundary counts are *mergeable*: summing the
per-replica bucket vectors yields the fleet histogram, which is how
pod-scale percentiles must be computed (ROADMAP item 4 — reservoirs
cannot merge).  :meth:`Histogram.quantile` returns the conservative
upper edge of the bucket containing the quantile, so it can disagree
with an exact percentile by at most one bucket width — the invariant
``tests/test_obs.py`` pins against the serving snapshot.

Everything here is pure stdlib (no jax import) so lint/CI tooling and
the exporters stay accelerator-free.
"""

from __future__ import annotations

import bisect
import threading
from typing import Dict, List, Optional, Sequence, Tuple

__all__ = ["Counter", "Gauge", "Histogram", "MetricRegistry",
           "registry", "set_registry", "DEFAULT_LATENCY_BOUNDARIES_MS"]

#: Default latency ladder (ms): ~2× steps from sub-ms dispatches to the
#: multi-second wedge regime.  Mergeable across replicas by construction.
DEFAULT_LATENCY_BOUNDARIES_MS = (
    0.5, 1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0, 256.0, 512.0,
    1000.0, 2000.0, 4000.0, 8000.0)


def _label_key(labels: Dict[str, str]) -> Tuple[Tuple[str, str], ...]:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


class _Family:
    """Shared label-set plumbing (one value slot per label combination)."""

    kind = "untyped"

    def __init__(self, name: str, help: str = "") -> None:
        self.name = name
        self.help = help
        # plain Lock on purpose: the metrics surface is the leaf lockdep
        # itself reports into — instrumenting it would recurse
        self._lock = threading.Lock()
        self._vals: Dict[Tuple, float] = {}  # guarded_by: _lock

    def samples(self) -> List[Tuple[Dict[str, str], float]]:
        """``[(labels_dict, value), ...]`` sorted by label key."""
        with self._lock:
            items = sorted(self._vals.items())
        return [(dict(k), v) for k, v in items]

    def value(self, **labels) -> float:
        with self._lock:
            return self._vals.get(_label_key(labels), 0.0)

    def remove(self, **labels) -> bool:
        """Drop one label-set (e.g. a retired index generation) so
        bounded-cardinality exporters don't accumulate dead series.
        Returns whether the label-set existed."""
        with self._lock:
            return self._vals.pop(_label_key(labels), None) is not None


class Counter(_Family):
    """Monotonic count, optionally labelled:
    ``c.inc(kernel="fused", reason="stale")``."""

    kind = "counter"

    def inc(self, n: float = 1, **labels) -> None:
        from ..core.errors import expects

        expects(n >= 0, f"counter {self.name} cannot decrease (n={n})")
        key = _label_key(labels)
        with self._lock:
            self._vals[key] = self._vals.get(key, 0.0) + n


class Gauge(_Family):
    """Point-in-time value (queue depth, ring occupancy)."""

    kind = "gauge"

    def set(self, v: float, **labels) -> None:
        with self._lock:
            self._vals[_label_key(labels)] = float(v)


class Histogram:
    """Fixed-boundary histogram family (cumulative on export).

    Per label-set state: one count per bucket (+ the ``+Inf`` overflow),
    the running sum, and the total count — exactly the Prometheus
    histogram data model, and the mergeable replacement for reservoir
    percentiles."""

    kind = "histogram"

    def __init__(self, name: str, help: str = "",
                 boundaries: Sequence[float] =
                 DEFAULT_LATENCY_BOUNDARIES_MS) -> None:
        from ..core.errors import expects

        bounds = tuple(float(b) for b in boundaries)
        expects(len(bounds) >= 1, f"histogram {name} needs >= 1 boundary")
        expects(all(a < b for a, b in zip(bounds, bounds[1:])),
                f"histogram {name} boundaries must increase strictly")
        self.name = name
        self.help = help
        self.boundaries = bounds
        self._lock = threading.Lock()  # plain on purpose — lockdep reports into histograms
        self._counts: Dict[Tuple, List[int]] = {}  # guarded_by: _lock
        self._sums: Dict[Tuple, float] = {}        # guarded_by: _lock

    def observe(self, v: float, **labels) -> None:
        i = bisect.bisect_left(self.boundaries, float(v))
        key = _label_key(labels)
        with self._lock:
            counts = self._counts.get(key)
            if counts is None:
                counts = [0] * (len(self.boundaries) + 1)
                self._counts[key] = counts
                self._sums[key] = 0.0
            counts[i] += 1
            self._sums[key] += float(v)

    def samples(self) -> List[Tuple[Dict[str, str], List[int], float]]:
        """``[(labels, bucket_counts_incl_inf, sum)]`` per label-set."""
        with self._lock:
            items = sorted((k, list(c), self._sums[k])
                           for k, c in self._counts.items())
        return [(dict(k), c, s) for k, c, s in items]

    def count(self, **labels) -> int:
        with self._lock:
            return sum(self._counts.get(_label_key(labels), ()))

    def quantile(self, q: float, *, interpolate: bool = False,
                 **labels) -> float:
        """Bucketed quantile (0 < q <= 1).  The default is the
        *conservative* estimate — the upper boundary of the bucket where
        the cumulative count reaches ``q`` — which never understates and
        differs from an exact percentile over the same observations by
        at most one bucket width.  ``interpolate=True`` instead places
        the quantile linearly *within* that bucket (the Prometheus
        ``histogram_quantile`` convention): usually closer to the exact
        value, but it can land on either side of it.  Both estimates lie
        in the same bucket.  Returns the top finite boundary for
        overflow quantiles and 0.0 when empty."""
        from ..core.errors import expects

        expects(0.0 < q <= 1.0, "quantile q must lie in (0, 1]")
        with self._lock:
            counts = list(self._counts.get(_label_key(labels), ()))
        total = sum(counts)
        if total == 0:
            return 0.0
        need = q * total
        cum = 0
        for i, c in enumerate(counts[:-1]):
            if cum + c >= need:
                hi = self.boundaries[i]
                if not interpolate:
                    return hi
                lo = self.boundaries[i - 1] if i > 0 else 0.0
                return lo + (need - cum) / c * (hi - lo)
            cum += c
        return self.boundaries[-1]

    def remove(self, **labels) -> bool:
        """Drop one label-set's buckets (see :meth:`_Family.remove`)."""
        key = _label_key(labels)
        with self._lock:
            existed = self._counts.pop(key, None) is not None
            self._sums.pop(key, None)
        return existed

    def bucket_width(self, v: float) -> float:
        """Width of the bucket containing ``v`` — the exporter-vs-exact
        agreement tolerance (overflow bucket reports the top span)."""
        i = bisect.bisect_left(self.boundaries, float(v))
        if i >= len(self.boundaries):
            i = len(self.boundaries) - 1
        lo = self.boundaries[i - 1] if i > 0 else 0.0
        return self.boundaries[i] - lo


class MetricRegistry:
    """Ordered name -> metric map with idempotent typed registration:
    re-registering an existing name returns the existing family (so call
    sites need no globals), re-registering under a different type is an
    error."""

    def __init__(self) -> None:
        self._lock = threading.Lock()  # plain on purpose — see _Family
        self._metrics: Dict[str, object] = {}  # guarded_by: _lock

    def _get(self, name: str, kind, factory):
        from ..core.errors import expects

        with self._lock:
            hit = self._metrics.get(name)
            if hit is not None:
                expects(isinstance(hit, kind),
                        f"metric {name!r} already registered as "
                        f"{type(hit).__name__}, not {kind.__name__}")
                return hit
            m = factory()
            self._metrics[name] = m
            return m

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get(name, Counter, lambda: Counter(name, help))

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._get(name, Gauge, lambda: Gauge(name, help))

    def histogram(self, name: str, help: str = "",
                  boundaries: Sequence[float] =
                  DEFAULT_LATENCY_BOUNDARIES_MS) -> Histogram:
        return self._get(name, Histogram,
                         lambda: Histogram(name, help, boundaries))

    def collect(self) -> List[object]:
        """Registration-ordered metric families (dicts preserve insertion
        order — exposition output is deterministic)."""
        with self._lock:
            return list(self._metrics.values())

    def get(self, name: str) -> Optional[object]:
        with self._lock:
            return self._metrics.get(name)


_default: Optional[MetricRegistry] = None
_default_lock = threading.Lock()


def registry() -> MetricRegistry:
    """The process-wide registry — library-level events (Pallas gate
    fallbacks, tracing diagnostics) land here so one exposition covers
    code that has no server handle."""
    global _default
    with _default_lock:
        if _default is None:
            _default = MetricRegistry()
        return _default


def set_registry(reg: MetricRegistry) -> MetricRegistry:
    """Swap the process-wide registry (tests).  Returns the previous."""
    global _default
    with _default_lock:
        prev, _default = _default, reg
        return prev if prev is not None else reg
