"""Chrome-trace / Perfetto JSON export of the flight recorder.

Renders a list of :class:`~raft_tpu.obs.spans.Span` to the Trace Event
Format (the ``traceEvents`` JSON that both ``chrome://tracing`` and
https://ui.perfetto.dev open directly): one complete (``"ph": "X"``)
event per span on its recording thread's track, thread-name metadata
events, and the span/parent/trace ids in ``args`` so tooling (and the
acceptance test) can rebuild the exact tree even where parent and child
ran on different threads — Perfetto's own nesting view is per-track;
the cross-thread request lineage additionally gets flow events
(``"ph": "s"/"f"``) drawn as arrows from parent to child track.

This is the *flight-recorder* view (host-side spans: queue wait,
batch-form, dispatch, device-exec, WAL, compaction).  Device-internal
timelines still come from ``jax.profiler`` captures — the watchdog dumps
both side by side for a wedged dispatch.
"""

from __future__ import annotations

import json
import os
from typing import Dict, Iterable, List

from .spans import Span

__all__ = ["chrome_trace", "export_chrome_trace"]


def chrome_trace(spans: Iterable[Span], *,
                 process_name: str = "raft_tpu") -> Dict:
    """Trace Event Format dict for ``spans`` (open spans are skipped —
    a flight-recorder dump happens mid-flight by definition)."""
    pid = os.getpid()
    spans = [s for s in spans if s.t_end_ns]
    events: List[Dict] = [{
        "name": "process_name", "ph": "M", "pid": pid, "tid": 0,
        "args": {"name": process_name},
    }]
    named: set = set()
    for s in spans:
        if s.tid not in named:
            named.add(s.tid)
            events.append({"name": "thread_name", "ph": "M", "pid": pid,
                           "tid": s.tid, "args": {"name": s.thread_name}})
    by_id = {s.span_id: s for s in spans}
    for s in spans:
        ts_us = s.t_start_ns / 1e3
        events.append({
            "name": s.name, "ph": "X", "pid": pid, "tid": s.tid,
            "ts": ts_us,
            # sub-us floor keeps instant events visible as slivers
            "dur": max(s.duration_ns / 1e3, 0.001),
            "args": {"span_id": s.span_id, "parent_id": s.parent_id,
                     "trace_id": s.trace_id,
                     **{k: _jsonable(v) for k, v in s.attrs.items()}},
        })
        parent = by_id.get(s.parent_id)
        if parent is not None and parent.tid != s.tid:
            # flow arrow from the parent's track to the child's: the
            # cross-thread request lineage stays visible in the UI
            mid_us = parent.t_start_ns / 1e3 + \
                max(parent.duration_ns / 2e3, 0.001)
            events.append({"name": s.name, "cat": "flow", "ph": "s",
                           "id": s.span_id, "pid": pid, "tid": parent.tid,
                           "ts": mid_us})
            events.append({"name": s.name, "cat": "flow", "ph": "f",
                           "bp": "e", "id": s.span_id, "pid": pid,
                           "tid": s.tid, "ts": ts_us})
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def _jsonable(v):
    if isinstance(v, (str, int, float, bool)) or v is None:
        return v
    if isinstance(v, (list, tuple)):
        return [_jsonable(x) for x in v]
    return repr(v)


def export_chrome_trace(path, spans: Iterable[Span], *,
                        process_name: str = "raft_tpu") -> str:
    """Write :func:`chrome_trace` as JSON via the crash-consistent
    temp + fsync + rename discipline (a stall dump must never itself be
    a torn file).  Returns ``path``."""
    from ..core.serialize import write_text_atomic

    doc = chrome_trace(spans, process_name=process_name)
    write_text_atomic(path, json.dumps(doc) + "\n")
    return os.fspath(path)
