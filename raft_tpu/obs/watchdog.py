"""Stall-triggered flight-recorder + profiler capture.

The recurring production failure mode (BENCH_r04/r05) is a *wedged*
device dispatch: the call into the accelerator neither returns nor
raises, the serve queue backs up, and — before this module — the only
evidence was a bench timeout hours later.  :class:`StallWatchdog`
watches the server's in-flight dispatch marker
(``SearchServer.dispatch_inflight()``) from its own daemon thread; when
one dispatch has been in flight longer than ``stall_timeout_s`` it

1. counts a ``stalls`` metric (``raft_serve_stalls_total`` on the
   Prometheus surface) — the alertable signal,
2. dumps the flight recorder (Chrome-trace JSON) + the live metrics
   snapshot into a fresh ``stall-<n>-<site>/`` directory under
   ``quarantine_dir`` (same quarantine discipline as corrupt WAL
   artifacts: evidence is renamed aside, never overwritten), and
3. attempts a short ``jax.profiler`` capture beside them — if the
   runtime can still trace, the device timeline of the wedge lands in
   ``profile/``; if the profiler itself is wedged the failure is
   recorded in ``capture.json`` instead of hanging the watchdog.

One dump per stall *episode*: the marker's start time latches, so a
600 s wedge produces one directory, not 600.  ``check()`` is the
deterministic inline surface (fake clocks welcome); ``start()`` runs the
same check on a daemon poll loop for real deployments.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Optional

__all__ = ["StallWatchdog"]


class StallWatchdog:
    """Watch one server's dispatch thread for wedged device calls.

    ``server`` needs ``dispatch_inflight()``, ``clock``, ``metrics`` and
    ``metrics_snapshot()`` (duck-typed —
    :class:`raft_tpu.serve.SearchServer` and the tests' fakes both
    qualify).  ``capture_s`` bounds the profiler capture; 0 disables it
    (flight recorder + metrics still dump).

    ``max_dumps`` is the quarantine retention policy: after each dump,
    only the newest ``max_dumps`` ``stall-*`` directories are kept and
    the rest are pruned (counted — ``stall_dumps_pruned``).  A flapping
    wedge used to fill the disk with one directory per episode; the
    newest dumps are the ones being debugged.  0 disables pruning."""

    def __init__(self, server, quarantine_dir, *,
                 stall_timeout_s: float = 30.0,
                 poll_interval_s: float = 1.0,
                 capture_s: float = 0.25,
                 max_dumps: int = 16,
                 recorder=None, clock=None, sleep=time.sleep) -> None:
        from ..core.errors import expects

        expects(stall_timeout_s > 0, "stall_timeout_s must be > 0")
        expects(poll_interval_s > 0, "poll_interval_s must be > 0")
        expects(capture_s >= 0, "capture_s must be >= 0")
        expects(max_dumps >= 0, "max_dumps must be >= 0")
        self.server = server
        self.quarantine_dir = os.fspath(quarantine_dir)
        self.stall_timeout_s = float(stall_timeout_s)
        self.poll_interval_s = float(poll_interval_s)
        self.capture_s = float(capture_s)
        self.max_dumps = int(max_dumps)
        self.clock = clock if clock is not None else server.clock
        self._sleep = sleep
        if recorder is None:
            from .spans import recorder as default_recorder

            recorder = default_recorder()
        self.recorder = recorder
        self.stalls_detected = 0
        self.pruned_total = 0
        self.dumps: list = []          # dump dir paths, oldest first
        self._latched_t0: Optional[float] = None
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    # -- detection ----------------------------------------------------------

    def check(self, now: Optional[float] = None) -> Optional[str]:
        """One poll: returns the new dump directory when a *fresh* stall
        episode was detected, else ``None``.  Safe to drive inline with a
        fake clock (no thread required)."""
        inflight = self.server.dispatch_inflight()
        if inflight is None:
            self._latched_t0 = None       # episode over; re-arm
            return None
        site, t0 = inflight
        now = self.clock() if now is None else now
        if now - t0 < self.stall_timeout_s:
            return None
        if self._latched_t0 == t0:
            return None                   # already dumped this episode
        self._latched_t0 = t0
        self.stalls_detected += 1
        self.server.metrics.count("stalls")
        self.recorder.event("obs.stall_detected", site=site,
                            stalled_s=round(now - t0, 3))
        path = self._dump(site, now - t0)
        self.dumps.append(path)
        self._prune()
        return path

    # -- evidence -----------------------------------------------------------

    def _dump(self, site: str, stalled_s: float) -> str:
        from ..core.logging import default_logger
        from ..core.serialize import write_text_atomic
        from .perfetto import export_chrome_trace

        os.makedirs(self.quarantine_dir, exist_ok=True)
        n = self.stalls_detected
        out = os.path.join(self.quarantine_dir, f"stall-{n:03d}-{site}")
        suffix = 0
        while os.path.exists(out):        # never overwrite evidence
            suffix += 1
            out = os.path.join(self.quarantine_dir,
                               f"stall-{n:03d}-{site}.{suffix}")
        os.makedirs(out)
        export_chrome_trace(os.path.join(out, "flight.trace.json"),
                            self.recorder.snapshot())
        write_text_atomic(
            os.path.join(out, "metrics.json"),
            json.dumps(self.server.metrics_snapshot(), indent=2,
                       sort_keys=True, default=repr) + "\n")
        capture = {"requested_s": self.capture_s}
        if self.capture_s > 0:
            capture.update(self._profiler_capture(
                os.path.join(out, "profile")))
        write_text_atomic(os.path.join(out, "capture.json"),
                          json.dumps(capture, indent=2) + "\n")
        default_logger().error(
            "stall watchdog: dispatch at %r in flight for %.1fs "
            "(timeout %.1fs) — flight recorder + profiler capture dumped "
            "to %s", site, stalled_s, self.stall_timeout_s, out)
        return out

    def _prune(self) -> int:
        """Apply the retention policy: drop the oldest ``stall-*``
        directories beyond ``max_dumps``.  Ordered by the zero-padded
        episode number in the name (stall-001 < stall-002 < ...), so
        retention is deterministic and independent of filesystem
        timestamps; directories from a previous process count too —
        retention is a property of the quarantine dir, not this run."""
        if self.max_dumps <= 0:
            return 0
        import shutil

        try:
            entries = sorted(
                e for e in os.listdir(self.quarantine_dir)
                if e.startswith("stall-")
                and os.path.isdir(os.path.join(self.quarantine_dir, e)))
        except OSError:
            return 0
        pruned = 0
        for name in entries[:-self.max_dumps]:
            path = os.path.join(self.quarantine_dir, name)
            try:
                shutil.rmtree(path)
            except OSError:
                continue                  # busy/foreign dir: keep it
            pruned += 1
            if path in self.dumps:
                self.dumps.remove(path)
        if pruned:
            self.pruned_total += pruned
            try:
                self.server.metrics.count("stall_dumps_pruned", pruned)
            except Exception:  # noqa: BLE001 — fakes without the counter
                pass
            self.recorder.event("obs.stall_dumps_pruned", n=pruned,
                                kept=self.max_dumps)
        return pruned

    def _profiler_capture(self, logdir: str) -> dict:
        """Best-effort ``jax.profiler`` capture.  The profiler runs on
        *this* thread — a wedge that blocks the dispatch thread usually
        leaves the runtime traceable; when it does not, the error string
        is the evidence."""
        try:
            import jax

            jax.profiler.start_trace(logdir)
            try:
                self._sleep(self.capture_s)
            finally:
                jax.profiler.stop_trace()
            return {"ok": True, "logdir": logdir}
        except Exception as exc:  # noqa: BLE001 - evidence, not control flow
            return {"ok": False, "error": repr(exc)}

    # -- daemon loop --------------------------------------------------------

    def start(self) -> "StallWatchdog":
        from ..core.errors import expects

        expects(self._thread is None, "watchdog already started")
        self._stop.clear()
        self._thread = threading.Thread(  # racelint: disable=JX14 the watchdog's only jax touch is the profiler capture on the stall path — collecting that evidence is its whole job
            target=self._loop, daemon=True, name="raft-tpu-stall-watchdog")
        self._thread.start()
        return self

    def stop(self, timeout: Optional[float] = 5.0) -> None:
        if self._thread is None:
            return
        self._stop.set()
        self._thread.join(timeout)
        self._thread = None

    def __enter__(self) -> "StallWatchdog":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    def _loop(self) -> None:
        while not self._stop.wait(self.poll_interval_s):
            try:
                self.check()
            except Exception:  # noqa: BLE001 - the watchdog must outlive
                from ..core.logging import default_logger

                default_logger().exception("stall watchdog check failed")
