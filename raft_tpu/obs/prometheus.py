"""Prometheus text exposition (version 0.0.4) over a MetricRegistry.

:func:`render` walks one or more registries and emits the scrapeable
text format: ``# HELP`` / ``# TYPE`` headers, labelled samples,
histograms as cumulative ``_bucket{le=...}`` series plus ``_sum`` /
``_count``.  Because the histograms carry fixed boundaries
(:mod:`raft_tpu.obs.metrics`), the emitted series are mergeable across
replicas — ``histogram_quantile()`` over a fleet sum is exact to one
bucket width, which reservoir p95s can never promise.

:func:`parse_text` is the inverse for the subset this module emits —
enough for tests and runbooks to assert on a scrape without a Prometheus
install (it is NOT a general exposition parser).

No HTTP server is shipped on purpose: serving one GET is three lines of
stdlib (see ``docs/observability_guide.md``) and every deployment
already has an opinion about its HTTP stack.
"""

from __future__ import annotations

import math
import re
from typing import Dict, Iterable, List, Tuple, Union

from .metrics import Counter, Gauge, Histogram, MetricRegistry

__all__ = ["render", "render_labeled", "parse_text"]

_NAME_OK = re.compile(r"[a-zA-Z_:][a-zA-Z0-9_:]*$")


def _fmt_value(v: float) -> str:
    if v == math.inf:
        return "+Inf"
    f = float(v)
    return str(int(f)) if f == int(f) else repr(f)


def _fmt_labels(labels: Dict[str, str], extra: str = "") -> str:
    parts = [f'{k}="{_escape(v)}"' for k, v in sorted(labels.items())]
    if extra:
        parts.append(extra)
    return "{" + ",".join(parts) + "}" if parts else ""


def _escape(v: str) -> str:
    return str(v).replace("\\", "\\\\").replace('"', '\\"') \
                 .replace("\n", "\\n")


def render(registries: Union[MetricRegistry,
                             Iterable[MetricRegistry]]) -> str:
    """One scrape body over ``registries`` (a registry or an iterable —
    e.g. a server's own registry plus the process-global one).  Duplicate
    family names across registries keep the first occurrence: the caller
    ordered them by precedence."""
    if isinstance(registries, MetricRegistry):
        registries = (registries,)
    out: List[str] = []
    seen: set = set()
    for reg in registries:
        for metric in reg.collect():
            if metric.name in seen:
                continue
            seen.add(metric.name)
            name = metric.name
            if not _NAME_OK.match(name):  # pragma: no cover - registration bug
                continue
            if metric.help:
                out.append(f"# HELP {name} {_escape(metric.help)}")
            out.append(f"# TYPE {name} {metric.kind}")
            if isinstance(metric, Histogram):
                for labels, counts, total in metric.samples():
                    cum = 0
                    for bound, c in zip(metric.boundaries, counts):
                        cum += c
                        le = f'le="{_fmt_value(bound)}"'
                        out.append(f"{name}_bucket{_fmt_labels(labels, le)}"
                                   f" {cum}")
                    cum += counts[-1]
                    inf_label = 'le="+Inf"'
                    out.append(f"{name}_bucket{_fmt_labels(labels, inf_label)}"
                               f" {cum}")
                    out.append(f"{name}_sum{_fmt_labels(labels)}"
                               f" {_fmt_value(total)}")
                    out.append(f"{name}_count{_fmt_labels(labels)} {cum}")
            elif isinstance(metric, (Counter, Gauge)):
                for labels, v in metric.samples():
                    out.append(f"{name}{_fmt_labels(labels)} {_fmt_value(v)}")
                if not metric.samples():
                    # a registered-but-never-incremented unlabelled family
                    # still exposes 0 so absence is distinguishable from
                    # a scrape miss
                    out.append(f"{name} 0")
    return "\n".join(out) + "\n"


def render_labeled(registries_by_label: Dict[str, MetricRegistry], *,
                   label: str = "replica") -> str:
    """One scrape body over many same-shaped registries, disambiguated by
    an injected label (e.g. the per-replica registries of a fleet, keyed
    by replica name → every sample gains ``replica="r0"``).

    :func:`render` keeps only the FIRST occurrence of a duplicate family
    name, so feeding N replica registries through it would silently drop
    N−1 replicas' series.  Here identical families are expected — they
    merge under one HELP/TYPE header and each sample carries the
    distinguishing label, which is exactly the shape
    ``histogram_quantile()``/``sum by (replica)`` expect fleet-side."""
    out: List[str] = []
    headered: set = set()
    for key in sorted(registries_by_label):
        reg = registries_by_label[key]
        inject = {label: str(key)}
        for metric in reg.collect():
            name = metric.name
            if not _NAME_OK.match(name):  # pragma: no cover
                continue
            if name not in headered:
                headered.add(name)
                if metric.help:
                    out.append(f"# HELP {name} {_escape(metric.help)}")
                out.append(f"# TYPE {name} {metric.kind}")
            if isinstance(metric, Histogram):
                for labels, counts, total in metric.samples():
                    labels = {**labels, **inject}
                    cum = 0
                    for bound, c in zip(metric.boundaries, counts):
                        cum += c
                        le = f'le="{_fmt_value(bound)}"'
                        out.append(f"{name}_bucket{_fmt_labels(labels, le)}"
                                   f" {cum}")
                    cum += counts[-1]
                    inf_label = 'le="+Inf"'
                    out.append(f"{name}_bucket"
                               f"{_fmt_labels(labels, inf_label)} {cum}")
                    out.append(f"{name}_sum{_fmt_labels(labels)}"
                               f" {_fmt_value(total)}")
                    out.append(f"{name}_count{_fmt_labels(labels)} {cum}")
            elif isinstance(metric, (Counter, Gauge)):
                samples = metric.samples()
                for labels, v in samples:
                    out.append(f"{name}{_fmt_labels({**labels, **inject})}"
                               f" {_fmt_value(v)}")
                if not samples:
                    out.append(f"{name}{_fmt_labels(inject)} 0")
    return "\n".join(out) + "\n"


_SAMPLE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{(.*)\})?\s+(\S+)$")
_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')


def _unescape(v: str) -> str:
    # single pass so '\\n' stays a literal backslash-n, not a newline
    return re.sub(r"\\(.)",
                  lambda m: "\n" if m.group(1) == "n" else m.group(1), v)


def parse_text(text: str) -> Dict[str, List[Tuple[Dict[str, str], float]]]:
    """Parse exposition text back to
    ``{sample_name: [(labels, value), ...]}`` (sample names include the
    ``_bucket``/``_sum``/``_count`` suffixes).  Raises ``ValueError`` on
    a line that is neither a comment nor a well-formed sample — the
    "exposition parses" acceptance check."""
    samples: Dict[str, List[Tuple[Dict[str, str], float]]] = {}
    for lineno, line in enumerate(text.splitlines(), 1):
        if not line.strip() or line.startswith("#"):
            continue
        m = _SAMPLE_RE.match(line)
        if not m:
            raise ValueError(f"line {lineno}: unparseable sample {line!r}")
        name, _, labelstr, value = m.groups()
        labels = {k: _unescape(v)
                  for k, v in _LABEL_RE.findall(labelstr or "")}
        v = math.inf if value == "+Inf" else float(value)
        samples.setdefault(name, []).append((labels, v))
    return samples
