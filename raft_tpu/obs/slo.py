"""SLO evaluation — multi-window burn rates and the recall quality guard.

Raw metrics answer "what is happening"; an SLO answers "is it bad
enough to act".  This module implements the standard SRE error-budget
machinery over three serving SLOs and wires the recall one back into
the control loop that can violate it:

* **latency** — fraction of requests answering within
  ``SloPolicy.latency_ms``, read from the mergeable latency histogram
  (bucketed: requests in the bucket straddling the target count as bad,
  the conservative side);
* **availability** — answered vs rejected/faulted, from the serving
  counters;
* **recall** — shadow-sampled requests at or above
  ``SloPolicy.recall_floor``, from :class:`raft_tpu.obs.quality.
  RecallEstimator`'s cumulative feed.

Each SLO is tracked with **multi-window burn rates**: the error budget
(``1 − target``) spent per unit, measured over a short and a long
window simultaneously — the long window filters blips, the short window
makes alerts reset promptly once the problem stops.  Both must exceed
the threshold to alert (page at ``burn_page``×, warn at
``burn_warn``×).  Windows are *event-counted*, not wall-clock, so a
fake-clock test drives the exact same math as production.

The **quality guard** closes the loop: ``quality_guard(level)`` returns
the deepest degradation level at or below the requested one whose
measured recall CI does not sit below the floor — the server asks it
before entering a ladder level, so a level that demonstrably breaks the
recall SLO is refused (counted, as ``quality_guard_overrides``) while
levels with no evidence yet stay allowed (the ladder must still work
cold).  Level 0 is always allowed: full effort is the best the index
can do, and the load ladder must have a floor.

Pure stdlib, like the rest of :mod:`raft_tpu.obs`.
"""

from __future__ import annotations

import bisect
import dataclasses
from collections import deque
from typing import Dict, Optional, Tuple

__all__ = ["SloPolicy", "SloEvaluator"]

_STATES = {"ok": 0, "warn": 1, "page": 2}


@dataclasses.dataclass(frozen=True)
class SloPolicy:
    """Targets + burn thresholds for :class:`SloEvaluator`.

    ``latency_ms`` / ``latency_budget``: requests slower than the target
    may consume at most ``latency_budget`` of traffic; ``availability``:
    answered fraction target; ``recall_floor`` / ``recall_bad_budget``:
    sampled requests below the floor may consume at most the budget.
    ``short_window`` / ``long_window`` are event counts (see module
    docstring); ``min_samples`` gates both alerting and the guard — an
    estimate with fewer sampled requests is *unknown*, not bad."""

    latency_ms: float = 64.0
    latency_budget: float = 0.05
    availability: float = 0.999
    recall_floor: float = 0.9
    recall_bad_budget: float = 0.10
    short_window: int = 32
    long_window: int = 256
    burn_warn: float = 2.0
    burn_page: float = 8.0
    min_samples: int = 8

    def __post_init__(self):
        from ..core.errors import expects

        expects(self.latency_ms > 0, "latency_ms must be > 0")
        expects(0.0 < self.latency_budget < 1.0,
                "latency_budget must lie in (0, 1)")
        expects(0.0 < self.availability < 1.0,
                "availability must lie in (0, 1)")
        expects(0.0 < self.recall_floor <= 1.0,
                "recall_floor must lie in (0, 1]")
        expects(0.0 < self.recall_bad_budget < 1.0,
                "recall_bad_budget must lie in (0, 1)")
        expects(1 <= self.short_window <= self.long_window,
                "need 1 <= short_window <= long_window")
        expects(0.0 < self.burn_warn <= self.burn_page,
                "need 0 < burn_warn <= burn_page")
        expects(self.min_samples >= 1, "min_samples must be >= 1")


class _BudgetTrack:
    """One SLO's event history: cumulative (total, bad) deltas per
    ``evaluate()`` call, walked backwards to form event-counted
    windows."""

    def __init__(self, budget: float, maxlen: int = 4096) -> None:
        self.budget = float(budget)
        self._last: Tuple[float, float] = (0.0, 0.0)
        self._hist: deque = deque(maxlen=maxlen)

    def push(self, total: float, bad: float) -> None:
        lt, lb = self._last
        if total < lt or bad < lb:        # counter reset (fresh metrics)
            lt, lb = 0.0, 0.0
        self._hist.append((total - lt, bad - lb))
        self._last = (total, bad)

    def burn(self, window_events: int) -> float:
        """Budget-normalized bad fraction over the newest ``window_events``
        events (0.0 while no events): 1.0 = burning exactly the budget."""
        total = bad = 0.0
        for dt, db in reversed(self._hist):
            total += dt
            bad += db
            if total >= window_events:
                break
        if total <= 0:
            return 0.0
        return (bad / total) / self.budget


class SloEvaluator:
    """Periodically fold serving + quality metrics into burn rates,
    alert states, and the degradation quality guard.

    ``metrics`` is the server's :class:`raft_tpu.serve.ServingMetrics`;
    ``estimator`` the optional :class:`~raft_tpu.obs.quality.
    RecallEstimator` (without one the recall SLO reads as empty).
    Gauges/counters land in ``registry`` (default: the metrics' own, so
    one scrape carries everything): ``raft_slo_burn_rate{slo,window}``,
    ``raft_slo_state{slo}`` (0 ok / 1 warn / 2 page), and
    ``raft_slo_alerts_total{slo,severity}`` counted on each transition
    into warn/page.  Drive :meth:`evaluate` on whatever cadence suits —
    per scrape, per N requests, or inline in deterministic tests."""

    def __init__(self, metrics, estimator=None,
                 policy: Optional[SloPolicy] = None, *,
                 registry=None, recorder=None) -> None:
        from .spans import recorder as default_recorder

        self.metrics = metrics
        self.estimator = estimator
        self.policy = policy or SloPolicy()
        self.registry = registry if registry is not None \
            else metrics.registry
        self.recorder = recorder if recorder is not None \
            else default_recorder()
        p = self.policy
        self._tracks: Dict[str, _BudgetTrack] = {
            "latency": _BudgetTrack(p.latency_budget),
            "availability": _BudgetTrack(1.0 - p.availability),
            "recall": _BudgetTrack(p.recall_bad_budget),
        }
        self.states: Dict[str, str] = {s: "ok" for s in self._tracks}
        self.overrides = 0          # guard refusals (cumulative)
        self._g_burn = self.registry.gauge(
            "raft_slo_burn_rate",
            "error-budget burn rate per SLO and window (1.0 = on budget)")
        self._g_state = self.registry.gauge(
            "raft_slo_state", "per-SLO alert state (0 ok, 1 warn, 2 page)")
        self._c_alerts = self.registry.counter(
            "raft_slo_alerts_total", "transitions into warn/page per SLO")
        if estimator is not None:
            estimator.track_floor(p.recall_floor)
        for slo in self._tracks:
            self._g_state.set(0, slo=slo)

    # -- cumulative feeds ---------------------------------------------------

    def _latency_events(self) -> Tuple[float, float]:
        hist = self.metrics.latency_hist
        samples = hist.samples()
        if not samples:
            return 0.0, 0.0
        counts = samples[0][1]
        idx = bisect.bisect_right(hist.boundaries, self.policy.latency_ms)
        total = float(sum(counts))
        return total, total - float(sum(counts[:idx]))

    def _availability_events(self) -> Tuple[float, float]:
        m = self.metrics
        bad = float(m.counter_value("rejected_queue_full")
                    + m.counter_value("rejected_deadline")
                    + m.counter_value("faulted_batches"))
        return float(m.counter_value("completed")) + bad, bad

    def _recall_events(self) -> Tuple[float, float]:
        if self.estimator is None:
            return 0.0, 0.0
        return (float(self.estimator.samples_total),
                float(self.estimator.samples_below_floor))

    # -- evaluation ---------------------------------------------------------

    def evaluate(self) -> Dict[str, dict]:
        """One evaluation pass: pull cumulative events, refresh burn
        windows, update states/gauges, count transitions.  Returns
        ``{slo: {burn_short, burn_long, state}}``."""
        p = self.policy
        feeds = {"latency": self._latency_events(),
                 "availability": self._availability_events(),
                 "recall": self._recall_events()}
        out: Dict[str, dict] = {}
        for slo, (total, bad) in feeds.items():
            track = self._tracks[slo]
            track.push(total, bad)
            short = track.burn(p.short_window)
            long_ = track.burn(p.long_window)
            # both windows must agree: the long window proves it is
            # sustained, the short window proves it is still happening
            floor = min(short, long_)
            state = "page" if floor >= p.burn_page else \
                "warn" if floor >= p.burn_warn else "ok"
            prev = self.states[slo]
            if state != prev:
                self.states[slo] = state
                if _STATES[state] > _STATES[prev]:
                    self._c_alerts.inc(slo=slo, severity=state)
                    self.recorder.event("obs.slo_alert", slo=slo,
                                        severity=state,
                                        burn_short=round(short, 3),
                                        burn_long=round(long_, 3))
            self._g_burn.set(short, slo=slo, window="short")
            self._g_burn.set(long_, slo=slo, window="long")
            self._g_state.set(_STATES[state], slo=slo)
            out[slo] = {"burn_short": short, "burn_long": long_,
                        "state": state}
        return out

    # -- the guard ----------------------------------------------------------

    def quality_guard(self, level: int) -> int:
        """The deepest allowed degradation level <= ``level``: a level is
        refused when its windowed recall estimate has at least
        ``min_samples`` sampled requests AND its Wilson CI lies entirely
        below ``recall_floor`` (``ci_high < floor`` — the measured upper
        bound cannot reach the SLO).  Unknown levels pass: refusing
        unmeasured levels would deadlock a cold ladder."""
        lvl = int(level)
        if self.estimator is None:
            return lvl
        p = self.policy
        while lvl > 0:
            est = self.estimator.estimate(lvl)
            if est.samples < p.min_samples or est.ci_high >= p.recall_floor:
                return lvl
            lvl -= 1
        return lvl

    def stats(self) -> dict:
        """JSON-ready snapshot for ``metrics_snapshot()['slo']``."""
        p = self.policy
        return {
            "states": dict(self.states),
            "overrides": self.overrides,
            "burn": {slo: {"short": t.burn(p.short_window),
                           "long": t.burn(p.long_window)}
                     for slo, t in self._tracks.items()},
        }
