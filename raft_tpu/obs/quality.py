"""Search-quality telemetry — shadow-sampled online recall (ISSUE 11).

PR 9 made the serving stack's *performance* observable; this module
makes its *quality* observable.  The serving runtime silently trades
recall for latency in three places — the admission ladder scales effort
down under load, the Pallas gate swaps scan kernels, and
compaction/swap rewrite indexes — and none of them used to measure what
they did to result quality.

:class:`RecallEstimator` closes the loop with the FusionANNS trick: the
cheap way to hold quality online is to re-rank a *small sampled subset*
exactly.  A deterministic, seeded hash over the request sequence number
picks ``sample_fraction`` of live requests on the hot path (one integer
multiply per request, no RNG state, replayable); sampled requests are
copied onto a **bounded work queue** (full queue = drop and count — the
oracle must never backpressure serving) and an off-hot-path worker
re-scores them against an **exact brute-force oracle** built from the
serving index's stored vectors via the shared
:mod:`raft_tpu.ops.blocked_scan` core.  Per-request recall@k streams
into registry metrics labeled by degradation level / scan kernel /
index generation, with Wilson confidence intervals per level — the
signal :mod:`raft_tpu.obs.slo`'s quality guard consumes.

The oracle is *ground truth for the stored representation*: IVF-Flat /
CAGRA / brute oracles scan the exact stored vectors; the IVF-PQ oracle
scans the reconstruction slab, so it measures candidate-selection loss
(probes/beam/kernel effects) rather than quantization loss — exactly
the part the degradation ladder and kernel dispatch can change.

Pure stdlib at import time (the jax/numpy oracle machinery loads
lazily), like the rest of :mod:`raft_tpu.obs`.
"""

from __future__ import annotations

import dataclasses
import math
import queue
import threading
from collections import deque
from typing import Dict, NamedTuple, Optional

__all__ = ["QualityConfig", "RecallEstimate", "RecallEstimator",
           "oracle_database", "wilson_interval"]

#: recall@k lives in [0, 1]; the ladder resolves the interesting top end
#: (0.9 / 0.95 / 0.99) where production floors sit.
RECALL_BOUNDARIES = (0.1, 0.25, 0.5, 0.7, 0.8, 0.9, 0.95, 0.99, 1.0)

_HASH_MULT = 0x9E3779B1        # Fibonacci hashing multiplier (Knuth)


def wilson_interval(successes: float, total: float,
                    z: float = 1.96) -> tuple:
    """Wilson score interval for a binomial proportion — the CI that
    stays honest at small n and extreme p (a plain normal interval
    collapses to a point at recall 1.0, claiming false certainty).
    Returns ``(low, high)``; ``(0, 1)`` when there is no data."""
    if total <= 0:
        return (0.0, 1.0)
    n = float(total)
    p = float(successes) / n
    zz = z * z
    denom = 1.0 + zz / n
    center = (p + zz / (2.0 * n)) / denom
    half = z * math.sqrt(p * (1.0 - p) / n + zz / (4.0 * n * n)) / denom
    return (max(0.0, center - half), min(1.0, center + half))


@dataclasses.dataclass(frozen=True)
class QualityConfig:
    """Knobs for :class:`RecallEstimator` (see
    ``docs/observability_guide.md`` for sizing guidance).

    ``sample_fraction``: fraction of requests shadow-sampled (the hash
    threshold — deterministic given ``seed`` and the request sequence);
    ``window``: per-degradation-level rolling window of sampled requests
    the CI is computed over (quality moves with load, so the estimate
    must age out); ``queue_max``: bound on the oracle work queue —
    overflow is dropped and counted, never blocks ``submit``;
    ``rows_cap``: sampled requests are truncated to this many query rows
    and padded to exactly this many, so ONE oracle executable serves
    every sample (zero steady-state recompiles); ``oracle_block``: rows
    per blocked-scan step of the oracle."""

    sample_fraction: float = 0.01
    seed: int = 0
    window: int = 256
    queue_max: int = 64
    rows_cap: int = 8
    oracle_block: int = 4096
    z: float = 1.96

    def __post_init__(self):
        from ..core.errors import expects

        expects(0.0 < self.sample_fraction <= 1.0,
                "sample_fraction must lie in (0, 1]")
        expects(self.window >= 1, "window must be >= 1")
        expects(self.queue_max >= 1, "queue_max must be >= 1")
        expects(self.rows_cap >= 1, "rows_cap must be >= 1")
        expects(self.oracle_block >= 1, "oracle_block must be >= 1")
        expects(self.z > 0, "z must be > 0")


class RecallEstimate(NamedTuple):
    """Windowed recall@k estimate for one degradation level."""

    mean: float        # sampled neighbor slots recovered / slots total
    ci_low: float      # Wilson interval over the window's slots
    ci_high: float
    samples: int       # sampled requests in the window
    slots: int         # neighbor slots (rows × k) in the window


class _Sample(NamedTuple):
    queries: object    # np [rows<=rows_cap, d] f32 copy
    ids: object        # np [rows, k] served neighbor ids
    level: int
    generation: int
    scan_kernel: str


def oracle_database(index):
    """Extract ``(vectors [n, d] f32, ids [n] int64)`` numpy arrays — the
    exact-scan corpus for ``index``'s oracle.

    * brute (2-D array) — the array itself, ids = row numbers;
    * ``ivf_flat`` — the list slabs, flattened, pad slots dropped;
    * ``ivf_pq`` — the bf16 reconstruction slab (materialized on demand),
      so the oracle is exact over the stored representation;
    * ``ivf_rabitq`` — the raw rerank slab (rerank returns exact
      distances, so the oracle corpus is the raw vectors);
    * ``ooc`` — the raw rows gathered from the host shard store (the
      device half holds only codes);
    * ``cagra`` — the dataset, ids = row numbers;
    * ``mutation.Tombstoned`` — the wrapped index's corpus with deleted
      source ids removed (a tombstoned id must never count as a miss
      against results that correctly exclude it).
    """
    import numpy as np

    import jax

    from ..neighbors.mutation import Tombstoned

    keep = None
    if isinstance(index, Tombstoned):
        keep = np.asarray(jax.device_get(index.keep.to_bool_array()))  # jaxlint: disable=JX01 one-time oracle corpus extraction, off the hot path
        index = index.index
    if getattr(index, "ndim", None) == 2:              # brute database
        vecs = np.asarray(jax.device_get(index), dtype=np.float32)  # jaxlint: disable=JX01 one-time oracle corpus extraction, off the hot path
        ids = np.arange(vecs.shape[0], dtype=np.int64)
    elif hasattr(index, "graph"):                      # cagra
        vecs = np.asarray(jax.device_get(index.dataset), dtype=np.float32)  # jaxlint: disable=JX01 one-time oracle corpus extraction, off the hot path
        ids = np.arange(vecs.shape[0], dtype=np.int64)
    elif hasattr(index, "store"):                      # ooc
        # the raw rows live host-side: gather every live slot's row from
        # the shard store (shadow-sample scale — the oracle corpus is
        # bounded by the sampled index, not re-read per query)
        ids = np.asarray(jax.device_get(index.ids), dtype=np.int64).reshape(-1)  # jaxlint: disable=JX01 one-time oracle corpus extraction, off the hot path
        vecs = np.asarray(index.store.gather(ids), dtype=np.float32)
    elif hasattr(index, "rotation"):                   # ivf_rabitq
        # rerank is exact over the raw slab, so the oracle corpus is the
        # raw vectors (not the 1-bit codes) — same shape as ivf_flat
        vecs = np.asarray(jax.device_get(index.data),  # jaxlint: disable=JX01 one-time oracle corpus extraction, off the hot path
                          dtype=np.float32).reshape(-1, index.dim)
        ids = np.asarray(jax.device_get(index.ids), dtype=np.int64).reshape(-1)  # jaxlint: disable=JX01 one-time oracle corpus extraction, off the hot path
    elif hasattr(index, "codes"):                      # ivf_pq
        idx = index.with_recon() if index.recon is None else index
        vecs = np.asarray(jax.device_get(idx.recon),  # jaxlint: disable=JX01 one-time oracle corpus extraction, off the hot path
                          dtype=np.float32).reshape(-1, idx.dim)
        ids = np.asarray(jax.device_get(idx.ids), dtype=np.int64).reshape(-1)  # jaxlint: disable=JX01 one-time oracle corpus extraction, off the hot path
    elif hasattr(index, "data"):                       # ivf_flat
        vecs = np.asarray(jax.device_get(index.data),  # jaxlint: disable=JX01 one-time oracle corpus extraction, off the hot path
                          dtype=np.float32).reshape(-1, index.dim)
        ids = np.asarray(jax.device_get(index.ids), dtype=np.int64).reshape(-1)  # jaxlint: disable=JX01 one-time oracle corpus extraction, off the hot path
    else:
        raise TypeError(f"no oracle corpus for {type(index).__name__}")
    valid = ids >= 0
    vecs, ids = vecs[valid], ids[valid]
    if keep is not None:
        live = keep[np.clip(ids, 0, keep.shape[0] - 1)] & (ids < keep.shape[0])
        vecs, ids = vecs[live], ids[live]
    return vecs, ids


class RecallEstimator:
    """Shadow-sample live requests and measure recall@k against an exact
    oracle, off the hot path.

    Hot-path surface: :meth:`maybe_sample` — one hash per request;
    sampled requests are copied onto the bounded queue (overflow =
    drop + count).  Oracle surface: :meth:`drain` processes queued
    samples inline (the deterministic test mode); :meth:`start` runs the
    same drain on a daemon worker for real deployments.

    ``registry`` receives the streamed metrics (histogram
    ``raft_quality_recall{level,scan_kernel,generation}``, per-level
    mean/CI gauges, sample/drop counters); ``metrics`` (optional
    :class:`raft_tpu.serve.ServingMetrics`) additionally carries the
    ``quality_samples`` / ``quality_sample_drops`` counters into the
    serving JSON schema."""

    def __init__(self, index, k: int, config: Optional[QualityConfig] = None,
                 *, metric: Optional[str] = None, registry=None,
                 metrics=None, recorder=None) -> None:
        from ..core.errors import expects
        from .metrics import registry as default_registry
        from .spans import recorder as default_recorder

        self.config = config or QualityConfig()
        self.k = int(k)
        expects(self.k >= 1, "k must be >= 1")
        self.metric = metric if metric is not None \
            else getattr(index, "metric", "sqeuclidean")
        self.registry = registry if registry is not None \
            else default_registry()
        self.metrics = metrics
        self.recorder = recorder if recorder is not None \
            else default_recorder()
        self.drift = None          # optional obs.drift.DriftDetector
        self._index = index        # corpus extracted lazily, off hot path
        self._oracle = None        # (fn, device operands) once built
        from ..core import lockdep
        self._seq = 0  # guarded_by: _seq_lock
        self._seq_lock = lockdep.lock("RecallEstimator._seq_lock")
        self._queue: "queue.Queue[_Sample]" = queue.Queue(
            maxsize=self.config.queue_max)
        self._state_lock = lockdep.lock("RecallEstimator._state_lock")
        self._windows: Dict[int, deque] = {}   # guarded_by: _state_lock
        self.samples_total = 0       # guarded_by: _state_lock
        self.samples_below_floor = 0  # guarded_by: _state_lock
        self._floor: Optional[float] = None    # guarded_by: _state_lock
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        # registry families (idempotent getters)
        self._hist = self.registry.histogram(
            "raft_quality_recall",
            "sampled online recall@k vs the exact oracle",
            RECALL_BOUNDARIES)
        self._g_mean = self.registry.gauge(
            "raft_quality_recall_mean", "windowed mean recall per level")
        self._g_lo = self.registry.gauge(
            "raft_quality_recall_ci_low",
            "Wilson CI lower bound of windowed recall per level")
        self._g_hi = self.registry.gauge(
            "raft_quality_recall_ci_high",
            "Wilson CI upper bound of windowed recall per level")
        self._g_n = self.registry.gauge(
            "raft_quality_recall_window",
            "sampled requests in the per-level window")
        self._c_sampled = self.registry.counter(
            "raft_quality_samples_total", "requests shadow-sampled")
        self._c_dropped = self.registry.counter(
            "raft_quality_sample_dropped_total",
            "samples dropped at the bounded oracle queue")
        self._c_errors = self.registry.counter(
            "raft_quality_oracle_errors_total",
            "oracle evaluations that raised (sample discarded)")

    # -- hot path -----------------------------------------------------------

    def _selected(self, seq: int) -> bool:
        """Deterministic seeded membership: Fibonacci-hash the sequence
        number into [0, 1) and threshold — replayable, no RNG state, and
        uniform enough that 1% means 1% at every window size."""
        h = ((seq ^ (self.config.seed * 0x85EBCA6B)) * _HASH_MULT) \
            & 0xFFFFFFFF
        return h < self.config.sample_fraction * 4294967296.0

    def maybe_sample(self, queries, ids, *, level: int, generation: int = 0,
                     scan_kernel: str = "xla") -> bool:
        """Hot-path hook: consider one answered request for shadow
        sampling.  ``queries`` [rows, d], ``ids`` [rows, k] (numpy, the
        reply the client saw).  Returns True when the request was
        enqueued for oracle scoring."""
        with self._seq_lock:
            seq = self._seq
            self._seq += 1
        if not self._selected(seq):
            return False
        import numpy as np

        cap = self.config.rows_cap
        sample = _Sample(np.array(queries[:cap], dtype=np.float32, copy=True),
                         np.array(ids[:cap], copy=True),
                         int(level), int(generation), str(scan_kernel))
        try:
            self._queue.put_nowait(sample)
        except queue.Full:
            # drop-and-count backpressure: the oracle must never block
            # or slow the serving path it is measuring
            self._c_dropped.inc()
            if self.metrics is not None:
                self.metrics.count("quality_sample_drops")
            return False
        self._c_sampled.inc(level=str(int(level)))
        if self.metrics is not None:
            self.metrics.count("quality_samples")
        return True

    # -- oracle -------------------------------------------------------------

    def _build_oracle(self):
        """Jit ONE fixed-shape executable over the corpus (queries padded
        to ``rows_cap``), routed through the shared blocked-scan core."""
        from functools import partial

        import numpy as np

        import jax
        import jax.numpy as jnp

        from ..ops import blocked_scan as bs

        vecs, ids = oracle_database(self._index)
        n, d = vecs.shape
        block = min(self.config.oracle_block, max(1, n))
        nb = -(-n // block)
        pad = nb * block - n
        vecs = np.pad(vecs, ((0, pad), (0, 0)))
        pids = np.pad(ids.astype(np.int32), (0, pad), constant_values=-1)
        norms = (vecs * vecs).sum(axis=1).astype(np.float32)
        blocks = jax.device_put(vecs.reshape(nb, block, d))
        bids = jax.device_put(pids.reshape(nb, block))
        bnorms = jax.device_put(norms.reshape(nb, block))
        metric = "inner_product" if self.metric == "inner_product" \
            else "sqeuclidean"

        @partial(jax.jit, static_argnames=("k",))
        def oracle(q, blocks, bids, bnorms, k):
            def score_step(inp):
                bvecs, bvids, bvnorms = inp
                dots = bs.exact_gathered_dots("cd,qd->qc", bvecs, q)
                dist = -dots if metric == "inner_product" \
                    else bvnorms[None, :] - 2.0 * dots
                dist = jnp.where(bvids[None, :] >= 0, dist, jnp.inf)
                return dist, jnp.broadcast_to(bvids[None, :], dist.shape)

            return bs.scan_topk(score_step, (blocks, bids, bnorms),
                                q.shape[0], k)

        self._oracle = (oracle, blocks, bids, bnorms)

    def oracle_ids(self, queries):
        """Exact top-k ids for ``queries`` (any row count ≤ ``rows_cap``;
        rows are padded to the cap so the jit runs one executable)."""
        import numpy as np

        import jax

        if self._oracle is None:
            self._build_oracle()
        fn, blocks, bids, bnorms = self._oracle
        q = np.asarray(queries, dtype=np.float32)
        rows = q.shape[0]
        cap = self.config.rows_cap
        if rows < cap:
            q = np.pad(q, ((0, cap - rows), (0, 0)))
        _, oids = fn(jax.device_put(q[:cap]), blocks, bids, bnorms,
                     k=self.k)
        return np.asarray(jax.device_get(oids))[:rows]  # jaxlint: disable=JX01 oracle worker result fetch, off the hot path

    # -- scoring ------------------------------------------------------------

    def _score(self, s: _Sample) -> None:
        import numpy as np

        oids = self.oracle_ids(s.queries)
        served = np.asarray(s.ids)[:, :self.k]
        hits = 0
        slots = 0
        for row in range(served.shape[0]):
            truth = set(int(v) for v in oids[row] if v >= 0)
            if not truth:
                continue
            got = sum(1 for v in served[row] if int(v) in truth)
            hits += got
            slots += len(truth)
        if slots == 0:
            return
        recall = hits / slots
        labels = dict(level=str(s.level), scan_kernel=s.scan_kernel,
                      generation=str(s.generation))
        self._hist.observe(recall, **labels)
        with self._state_lock:
            win = self._windows.get(s.level)
            if win is None:
                win = deque(maxlen=self.config.window)
                self._windows[s.level] = win
            win.append((hits, slots))
            self.samples_total += 1
            if self._floor is not None and recall < self._floor:
                self.samples_below_floor += 1
        est = self.estimate(s.level)
        lab = dict(level=str(s.level))
        self._g_mean.set(est.mean, **lab)
        self._g_lo.set(est.ci_low, **lab)
        self._g_hi.set(est.ci_high, **lab)
        self._g_n.set(est.samples, **lab)
        if self.drift is not None:
            self.drift.observe_queries(s.queries, generation=s.generation)

    # -- worker -------------------------------------------------------------

    def drain(self, max_items: Optional[int] = None) -> int:
        """Process queued samples inline; returns the number scored.
        The deterministic surface the drill tests drive (no thread)."""
        done = 0
        while max_items is None or done < max_items:
            try:
                s = self._queue.get_nowait()
            except queue.Empty:
                return done
            try:
                with self.recorder.span("obs.quality_oracle",
                                        level=s.level,
                                        generation=s.generation):
                    self._score(s)
            except Exception as exc:  # noqa: BLE001 — oracle must not kill the worker
                self._c_errors.inc()
                self.recorder.event("obs.quality_oracle_error",
                                    error=type(exc).__name__)
            done += 1
        return done

    def start(self) -> "RecallEstimator":
        """Run :meth:`drain` on a daemon worker (real deployments)."""
        from ..core.errors import expects

        expects(self._thread is None, "estimator already started")
        self._stop.clear()
        self._thread = threading.Thread(  # racelint: disable=JX14 the oracle worker owns its compiled exact-scan executable; it was built through the gated searcher path before start()
            target=self._loop, daemon=True, name="raft-tpu-quality")
        self._thread.start()
        return self

    def stop(self, timeout: Optional[float] = 5.0) -> None:
        if self._thread is None:
            return
        self._stop.set()
        self._thread.join(timeout)
        self._thread = None

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                s = self._queue.get(timeout=0.05)
            except queue.Empty:
                continue
            try:
                with self.recorder.span("obs.quality_oracle",
                                        level=s.level,
                                        generation=s.generation):
                    self._score(s)
            except Exception as exc:  # noqa: BLE001
                self._c_errors.inc()
                self.recorder.event("obs.quality_oracle_error",
                                    error=type(exc).__name__)

    def __enter__(self) -> "RecallEstimator":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- estimates ----------------------------------------------------------

    def track_floor(self, floor: float) -> None:
        """Record the recall floor (set by the SLO evaluator) so the
        cumulative below-floor counter the burn-rate windows consume is
        maintained at scoring time."""
        with self._state_lock:
            self._floor = float(floor)

    def estimate(self, level: int = 0) -> RecallEstimate:
        """Windowed recall estimate (+ Wilson CI over neighbor slots)
        for one degradation level; all-zero slots → the vacuous
        ``(0, [0, 1])`` estimate, which the guard treats as *unknown*."""
        with self._state_lock:
            win = list(self._windows.get(int(level), ()))
        hits = sum(h for h, _ in win)
        slots = sum(s for _, s in win)
        if slots == 0:
            return RecallEstimate(0.0, 0.0, 1.0, 0, 0)
        lo, hi = wilson_interval(hits, slots, self.config.z)
        return RecallEstimate(hits / slots, lo, hi, len(win), slots)

    def levels(self):
        """Degradation levels with at least one scored sample."""
        with self._state_lock:
            return sorted(self._windows)

    def stats(self) -> dict:
        """JSON-ready snapshot (per-level estimates + queue/counter
        state) for ``metrics_snapshot()['quality']``."""
        with self._state_lock:
            pending = self._queue.qsize()
        return {
            "sample_fraction": self.config.sample_fraction,
            "pending": pending,
            "samples_total": self.samples_total,
            "samples_below_floor": self.samples_below_floor,
            "levels": {
                str(lvl): dict(self.estimate(lvl)._asdict())
                for lvl in self.levels()
            },
        }
