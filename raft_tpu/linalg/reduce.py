"""Reduction family — parity with ``cpp/include/raft/linalg/reduce.cuh:63,148``,
``coalesced_reduction.cuh``, ``strided_reduction.cuh``, ``map_reduce.cuh``,
``reduce_rows_by_key.cuh``, ``reduce_cols_by_key.cuh``,
``mean_squared_error.cuh``.

The reference dispatches on (layout × reduction direction) into
thin/medium/thick tiled kernels (``detail/coalesced_reduction-inl.cuh:22``).
On TPU a reduction lowers to an XLA ``reduce`` the compiler tiles onto the VPU
— the policy machinery disappears; what's kept is the op algebra
(``main_op`` elementwise transform → ``reduce_op`` associative combine →
``final_op`` epilogue) and the ``Apply`` direction enum.
"""

from __future__ import annotations

import enum
from typing import Callable

import jax
import jax.numpy as jnp

from ..core.array import wrap_array
from ..core.errors import expects

__all__ = [
    "Apply",
    "reduce",
    "coalesced_reduction",
    "strided_reduction",
    "map_reduce",
    "reduce_rows_by_key",
    "reduce_cols_by_key",
    "mean_squared_error",
]


class Apply(enum.Enum):
    """Reduction direction (``linalg_types.hpp`` ``Apply``)."""

    ALONG_ROWS = "along_rows"
    ALONG_COLUMNS = "along_columns"


def _identity(x):
    return x


def reduce(
    data,
    *,
    apply: Apply = Apply.ALONG_ROWS,
    init=0,
    main_op: Callable = _identity,
    reduce_op: Callable = jnp.add,
    final_op: Callable = _identity,
):
    """General row/col reduction (``linalg::reduce``, ``reduce.cuh:148``).

    ``ALONG_ROWS`` reduces each row to a scalar (output length = n_rows),
    matching the reference's row-major/along-rows coalesced path.
    """
    data = wrap_array(data, ndim=2)
    axis = 1 if apply == Apply.ALONG_ROWS else 0
    mapped = main_op(data)
    if reduce_op in (jnp.add, jnp.sum):
        acc = jnp.sum(mapped, axis=axis)
    elif reduce_op in (jnp.minimum, jnp.min):
        acc = jnp.min(mapped, axis=axis)
    elif reduce_op in (jnp.maximum, jnp.max):
        acc = jnp.max(mapped, axis=axis)
    else:  # arbitrary associative functor: let XLA build the reduction
        acc = jax.lax.reduce(mapped, jnp.asarray(init, mapped.dtype), lambda a, b: reduce_op(a, b), (axis,))
        return final_op(acc)
    if init != 0:
        acc = reduce_op(acc, jnp.asarray(init, acc.dtype))
    return final_op(acc)


def coalesced_reduction(data, **kwargs):
    """Reduce along the contiguous (last) dimension
    (``coalesced_reduction.cuh``)."""
    return reduce(data, apply=Apply.ALONG_ROWS, **kwargs)


def strided_reduction(data, **kwargs):
    """Reduce along the strided (first) dimension (``strided_reduction.cuh``)."""
    return reduce(data, apply=Apply.ALONG_COLUMNS, **kwargs)


def map_reduce(fn: Callable, reduce_op: Callable, *arrays, init=0):
    """Fused map→reduce over flat arrays (``map_reduce.cuh``)."""
    arrays = [wrap_array(a) for a in arrays]
    mapped = fn(*arrays)
    flat = mapped.reshape(-1)
    if reduce_op in (jnp.add, jnp.sum):
        return jnp.sum(flat) + jnp.asarray(init, flat.dtype)
    return jax.lax.reduce(flat, jnp.asarray(init, flat.dtype), lambda a, b: reduce_op(a, b), (0,))


def reduce_rows_by_key(data, keys, n_unique_keys: int, weights=None):
    """Sum rows sharing a key (``reduce_rows_by_key.cuh``): out[k] = Σ rows
    with keys[i]==k.  Segment-sum formulation (TPU-friendly scatter-add)."""
    data = wrap_array(data, ndim=2)
    keys = wrap_array(keys, ndim=1)
    expects(keys.shape[0] == data.shape[0], "one key per row required")
    if weights is not None:
        data = data * wrap_array(weights, ndim=1)[:, None]
    return jax.ops.segment_sum(data, keys, num_segments=n_unique_keys)


def reduce_cols_by_key(data, keys, n_unique_keys: int):
    """Sum columns sharing a key (``reduce_cols_by_key.cuh``)."""
    data = wrap_array(data, ndim=2)
    keys = wrap_array(keys, ndim=1)
    expects(keys.shape[0] == data.shape[1], "one key per column required")
    return jax.ops.segment_sum(data.T, keys, num_segments=n_unique_keys).T


def mean_squared_error(a, b, weight: float = 1.0):
    """Weighted MSE (``mean_squared_error.cuh``)."""
    a, b = wrap_array(a), wrap_array(b)
    diff = a - b
    return weight * jnp.mean(diff * diff)
