"""Norms / normalization / broadcast ops — parity with
``cpp/include/raft/linalg/norm.cuh`` (+``norm_types.hpp``), ``normalize.cuh``,
``matrix_vector_op.cuh``, ``matrix_vector.cuh``.
"""

from __future__ import annotations

import enum
from typing import Callable

import jax.numpy as jnp

from ..core.array import wrap_array
from ..core.errors import expects
from .reduce import Apply

__all__ = [
    "NormType",
    "norm",
    "row_norm",
    "col_norm",
    "normalize",
    "row_normalize",
    "matrix_vector_op",
    "binary_mult_skip_zero",
    "binary_div_skip_zero",
]


class NormType(enum.Enum):
    """``raft::linalg::NormType`` (``norm_types.hpp``)."""

    L1Norm = "l1"
    L2Norm = "l2"          # sum of squares (NOT rooted), as in the reference
    LinfNorm = "linf"


def norm(
    data,
    norm_type: NormType = NormType.L2Norm,
    apply: Apply = Apply.ALONG_ROWS,
    root: bool = False,
):
    """Row/col norms (``linalg::norm``, ``norm.cuh``).  Note the reference's
    L2 norm is the *sum of squares*; pass ``root=True`` for sqrt epilogue
    (the reference's ``fin_op=sqrt_op``)."""
    data = wrap_array(data, ndim=2)
    axis = 1 if apply == Apply.ALONG_ROWS else 0
    if norm_type == NormType.L1Norm:
        out = jnp.sum(jnp.abs(data), axis=axis)
    elif norm_type == NormType.L2Norm:
        out = jnp.sum(data * data, axis=axis)
    else:
        out = jnp.max(jnp.abs(data), axis=axis)
    return jnp.sqrt(out) if (root and norm_type == NormType.L2Norm) else out


def row_norm(data, norm_type: NormType = NormType.L2Norm, root: bool = False):
    return norm(data, norm_type, Apply.ALONG_ROWS, root)


def col_norm(data, norm_type: NormType = NormType.L2Norm, root: bool = False):
    return norm(data, norm_type, Apply.ALONG_COLUMNS, root)


def normalize(data, norm_type: NormType = NormType.L2Norm, eps: float = 1e-10):
    """Row-normalize (``linalg::normalize``/``row_normalize``,
    ``normalize.cuh``).  L2 uses the rooted norm, as the reference does."""
    data = wrap_array(data, ndim=2)
    if norm_type == NormType.L2Norm:
        denom = jnp.sqrt(jnp.sum(data * data, axis=1, keepdims=True))
    elif norm_type == NormType.L1Norm:
        denom = jnp.sum(jnp.abs(data), axis=1, keepdims=True)
    else:
        denom = jnp.max(jnp.abs(data), axis=1, keepdims=True)
    return jnp.where(denom > eps, data / denom, data)


row_normalize = normalize


def matrix_vector_op(matrix, vector, op: Callable = jnp.add, along_rows: bool = True):
    """Broadcast a vector across a matrix (``matrix_vector_op.cuh``).

    ``along_rows=True``: vector has length n_cols and is applied to every row
    (the reference's ``bcastAlongRows``).
    """
    matrix = wrap_array(matrix, ndim=2)
    vector = wrap_array(vector, ndim=1)
    if along_rows:
        expects(vector.shape[0] == matrix.shape[1], "vector length must equal n_cols")
        return op(matrix, vector[None, :])
    expects(vector.shape[0] == matrix.shape[0], "vector length must equal n_rows")
    return op(matrix, vector[:, None])


def binary_mult_skip_zero(matrix, vector, along_rows: bool = True):
    """``matrix_vector.cuh`` helper: multiply, treating 0 entries as 1."""
    safe = jnp.where(wrap_array(vector, ndim=1) == 0, 1, vector)
    return matrix_vector_op(matrix, safe, jnp.multiply, along_rows)


def binary_div_skip_zero(matrix, vector, along_rows: bool = True, return_zero: bool = False):
    """``matrix_vector.cuh`` helper: divide, skipping zero divisors.

    ``return_zero=True`` zeroes the output where the divisor is ~0 (the
    reference's ``bcastAlongRows`` variant used by kmeans centroid updates).
    """
    vector = wrap_array(vector, ndim=1)
    safe = jnp.where(vector == 0, 1, vector)
    out = matrix_vector_op(matrix, safe, jnp.divide, along_rows)
    if return_zero:
        mask = (vector == 0)[None, :] if along_rows else (vector == 0)[:, None]
        out = jnp.where(mask, 0, out)
    return out
