"""raft_tpu.linalg — dense linear algebra primitives.

TPU-native analog of ``cpp/include/raft/linalg`` (SURVEY.md §2.3): the map /
reduce / norm families lower to fused XLA VPU loops; BLAS-class ops to MXU
``dot_general``; decompositions to ``lax.linalg`` plus hand-rolled Jacobi
variants.
"""

from .elementwise import (
    map, map_offset, unary_op, binary_op, ternary_op,
    add, add_scalar, subtract, subtract_scalar, multiply, multiply_scalar,
    divide, divide_scalar, power, power_scalar, sqrt,
)
from .reduce import (
    Apply, reduce, coalesced_reduction, strided_reduction, map_reduce,
    reduce_rows_by_key, reduce_cols_by_key, mean_squared_error,
)
from .norm import (
    NormType, norm, row_norm, col_norm, normalize, row_normalize,
    matrix_vector_op, binary_mult_skip_zero, binary_div_skip_zero,
)
from .blas import gemm, gemv, axpy, dot, transpose, init_eye
from .decomp import (
    eig_dc, eig_dc_selective, eig_jacobi, qr_get_q, qr_get_qr,
    svd_qr, svd_eig, svd_jacobi, rsvd_fixed_rank,
    lstsq_svd_qr, lstsq_eig, lstsq_qr, cholesky_r1_update,
)
from .pca import (
    PcaSolver, PcaParams, PcaModel, pca_fit, pca_transform,
    pca_fit_transform, pca_inverse_transform,
)

__all__ = ["map", "map_offset", "unary_op", "binary_op", "ternary_op", "add",
    "add_scalar", "subtract", "subtract_scalar", "multiply", "multiply_scalar",
    "divide", "divide_scalar", "power", "power_scalar", "sqrt", "Apply",
    "reduce", "coalesced_reduction", "strided_reduction", "map_reduce",
    "reduce_rows_by_key", "reduce_cols_by_key", "mean_squared_error",
    "NormType", "norm", "row_norm", "col_norm", "normalize", "row_normalize",
    "matrix_vector_op", "binary_mult_skip_zero", "binary_div_skip_zero",
    "gemm", "gemv", "axpy", "dot", "transpose", "init_eye", "eig_dc",
    "eig_dc_selective", "eig_jacobi", "qr_get_q", "qr_get_qr", "svd_qr",
    "svd_eig", "svd_jacobi", "rsvd_fixed_rank", "lstsq_svd_qr", "lstsq_eig",
    "lstsq_qr", "cholesky_r1_update", "PcaSolver", "PcaParams", "PcaModel",
    "pca_fit", "pca_transform", "pca_fit_transform", "pca_inverse_transform"]
