"""BLAS-class ops — parity with ``cpp/include/raft/linalg/gemm.cuh:51-221``,
``gemv.cuh``, ``axpy.cuh``, ``dot.cuh``, ``init.cuh``, ``transpose.cuh``.

The reference routes these to cuBLAS/cuBLASLt; the TPU-native path is a single
``jax.lax.dot_general`` that XLA tiles onto the MXU.  The knob that matters on
TPU is the accumulation dtype: every matmul here takes
``preferred_element_type`` (default f32) so bf16 inputs hit the MXU at full
rate while accumulating in f32 — the moral equivalent of cuBLASLt's compute
type selection in ``detail/cublaslt_wrappers.hpp``.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

from ..core.array import wrap_array
from ..core.errors import expects

__all__ = ["gemm", "gemv", "axpy", "dot", "transpose", "init_eye"]


def gemm(
    a,
    b,
    *,
    trans_a: bool = False,
    trans_b: bool = False,
    alpha: float = 1.0,
    beta: float = 0.0,
    c=None,
    preferred_element_type=jnp.float32,
):
    """C = alpha·op(A)·op(B) + beta·C (``linalg::gemm``, ``gemm.cuh:51``)."""
    a = wrap_array(a, ndim=2)
    b = wrap_array(b, ndim=2)
    if trans_a:
        a = a.T
    if trans_b:
        b = b.T
    expects(a.shape[1] == b.shape[0], f"gemm inner dims mismatch: {a.shape} x {b.shape}")
    out = jnp.matmul(a, b, preferred_element_type=preferred_element_type)
    if alpha != 1.0:
        out = alpha * out
    if beta != 0.0:
        expects(c is not None, "beta != 0 requires C")
        out = out + beta * wrap_array(c, ndim=2)
    return out.astype(preferred_element_type if preferred_element_type is not None else out.dtype)


def gemv(a, x, *, trans: bool = False, alpha: float = 1.0, beta: float = 0.0, y=None):
    """y = alpha·op(A)·x + beta·y (``gemv.cuh``)."""
    a = wrap_array(a, ndim=2)
    x = wrap_array(x, ndim=1)
    if trans:
        a = a.T
    out = alpha * jnp.matmul(a, x, preferred_element_type=jnp.float32)
    if beta != 0.0:
        expects(y is not None, "beta != 0 requires y")
        out = out + beta * wrap_array(y, ndim=1)
    return out


def axpy(alpha: float, x, y):
    """y ← alpha·x + y (``axpy.cuh``)."""
    return alpha * wrap_array(x) + wrap_array(y)


def dot(x, y):
    """Inner product (``dot.cuh``)."""
    x, y = wrap_array(x, ndim=1), wrap_array(y, ndim=1)
    return jnp.dot(x, y, preferred_element_type=jnp.float32)


def transpose(a):
    """Out-of-place transpose (``transpose.cuh``; XLA fuses the layout swap)."""
    return wrap_array(a, ndim=2).T


def init_eye(n: int, m: Optional[int] = None, dtype=jnp.float32):
    """Identity init (``init.cuh``)."""
    return jnp.eye(n, m, dtype=dtype)
