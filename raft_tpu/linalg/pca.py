"""PCA — parity with ``cpp/include/raft/linalg/pca.cuh:42,87`` (+
``pca_types.hpp``), newly promoted into RAFT from cuML.

Covariance + eigendecomposition path: center, cov = XᵀX/(n−1), eigh, project.
Everything is MXU matmuls + one small eigh.
"""

from __future__ import annotations

import enum
from typing import NamedTuple

import jax
import jax.numpy as jnp

from ..core.array import wrap_array

__all__ = ["PcaSolver", "PcaParams", "PcaModel", "pca_fit", "pca_transform", "pca_fit_transform", "pca_inverse_transform"]


class PcaSolver(enum.Enum):
    """``pca_types.hpp`` solver enum (COV_EIG_DQ / COV_EIG_JACOBI)."""

    COV_EIG_DQ = "eig_dc"
    COV_EIG_JACOBI = "eig_jacobi"


class PcaParams(NamedTuple):
    n_components: int
    solver: PcaSolver = PcaSolver.COV_EIG_DQ
    whiten: bool = False


class PcaModel(NamedTuple):
    components: jax.Array        # (n_components, n_features)
    explained_variance: jax.Array
    explained_variance_ratio: jax.Array
    singular_values: jax.Array
    mean: jax.Array
    noise_variance: jax.Array


def pca_fit(data, params: PcaParams) -> PcaModel:
    """Fit PCA (``pca_fit``, ``pca.cuh:42``)."""
    x = wrap_array(data, ndim=2)
    n, d = x.shape
    mean = jnp.mean(x, axis=0)
    xc = x - mean[None, :]
    cov = jnp.matmul(xc.T, xc, preferred_element_type=jnp.float32) / (n - 1)
    if params.solver == PcaSolver.COV_EIG_JACOBI:
        from .decomp import eig_jacobi

        vals, vecs = eig_jacobi(cov)
    else:
        vals, vecs = jnp.linalg.eigh(cov)
    vals = jnp.maximum(vals[::-1], 0.0)  # descending
    vecs = vecs[:, ::-1]
    k = params.n_components
    total_var = jnp.sum(vals)
    noise = jnp.mean(vals[k:]) if k < d else jnp.asarray(0.0, vals.dtype)
    return PcaModel(
        components=vecs[:, :k].T,
        explained_variance=vals[:k],
        explained_variance_ratio=vals[:k] / jnp.where(total_var > 0, total_var, 1.0),
        singular_values=jnp.sqrt(vals[:k] * (n - 1)),
        mean=mean,
        noise_variance=noise,
    )


def pca_transform(data, model: PcaModel, params: PcaParams):
    x = wrap_array(data, ndim=2)
    proj = jnp.matmul(x - model.mean[None, :], model.components.T, preferred_element_type=jnp.float32)
    if params.whiten:
        proj = proj / jnp.sqrt(jnp.where(model.explained_variance > 0, model.explained_variance, 1.0))[None, :]
    return proj


def pca_fit_transform(data, params: PcaParams):
    """``pca_fit_transform`` (``pca.cuh:87``)."""
    model = pca_fit(data, params)
    return pca_transform(data, model, params), model


def pca_inverse_transform(proj, model: PcaModel, params: PcaParams):
    proj = wrap_array(proj, ndim=2)
    if params.whiten:
        proj = proj * jnp.sqrt(jnp.where(model.explained_variance > 0, model.explained_variance, 1.0))[None, :]
    return jnp.matmul(proj, model.components, preferred_element_type=jnp.float32) + model.mean[None, :]
