"""Dense decompositions — parity with ``cpp/include/raft/linalg/eig.cuh:121-190``
(eig_dc / eig_dc_selective / eig_jacobi), ``svd.cuh:195-332`` (svd_qr /
svd_eig), ``qr.cuh:73,95``, ``lstsq.cuh:31-127``, ``rsvd.cuh:158``,
``cholesky_r1_update.cuh``.

The reference calls cuSOLVER (syevd/syevj/gesvd/geqrf/potrf); on TPU these map
to ``jnp.linalg`` / ``lax.linalg`` (XLA-native QR/eigh/SVD) plus a hand-rolled
one-sided Jacobi for the ``*_jacobi`` variants — Jacobi sweeps are
batched-rotation friendly and keep everything on the MXU.
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp

from ..core.array import wrap_array
from ..core.errors import expects

__all__ = [
    "eig_dc",
    "eig_dc_selective",
    "eig_jacobi",
    "qr_get_q",
    "qr_get_qr",
    "svd_qr",
    "svd_eig",
    "svd_jacobi",
    "rsvd_fixed_rank",
    "lstsq_svd_qr",
    "lstsq_eig",
    "lstsq_qr",
    "cholesky_r1_update",
]


def eig_dc(matrix) -> Tuple[jax.Array, jax.Array]:
    """Symmetric eigendecomposition (``eig_dc``, ``eig.cuh:121`` → cuSOLVER
    syevd).  Returns (eigenvalues ascending, eigenvectors as columns)."""
    matrix = wrap_array(matrix, ndim=2)
    vals, vecs = jnp.linalg.eigh(matrix)
    return vals, vecs


def eig_dc_selective(matrix, n_eig_vals: int, which: str = "largest"):
    """Partial eigendecomposition (``eig_dc_selective``, ``eig.cuh:152`` →
    syevdx).  XLA has no partial syevdx; computes full eigh and slices —
    correct, and for the sizes RAFT uses this for (covariance matrices) the
    full solve is MXU-cheap."""
    vals, vecs = eig_dc(matrix)
    if which == "largest":
        return vals[-n_eig_vals:], vecs[:, -n_eig_vals:]
    return vals[:n_eig_vals], vecs[:, :n_eig_vals]


@partial(jax.jit, static_argnames=("sweeps",))
def eig_jacobi(matrix, tol: float = 1e-7, sweeps: int = 15):
    """Two-sided cyclic Jacobi eigensolver (``eig_jacobi``, ``eig.cuh:190`` →
    cuSOLVER syevj).  Runs fixed ``sweeps`` of full cyclic rotation sets with
    a tolerance-based early-freeze per rotation — compiler-friendly control
    flow (``lax.fori_loop``; no data-dependent shapes)."""
    a = wrap_array(matrix, ndim=2).astype(jnp.float32)
    n = a.shape[0]
    expects(a.shape[0] == a.shape[1], "eig_jacobi requires a square matrix")
    v = jnp.eye(n, dtype=a.dtype)

    idx_i, idx_j = jnp.tril_indices(n, -1)
    n_pairs = idx_i.shape[0]
    if n_pairs == 0:  # 1×1: nothing to rotate
        return jnp.diag(a), v

    def rotate(carry, pair_idx):
        a, v = carry
        p = idx_j[pair_idx]  # p < q
        q = idx_i[pair_idx]
        apq = a[p, q]
        app = a[p, p]
        aqq = a[q, q]
        # Jacobi rotation angle; skip (theta=0) when |apq| below tol.
        active = jnp.abs(apq) > tol
        tau = (aqq - app) / (2.0 * jnp.where(active, apq, 1.0))
        # sign(0) must be +1 here (Golub & Van Loan 8.4): tau==0 (equal
        # diagonal entries) still requires a 45-degree rotation.
        sign_tau = jnp.where(tau >= 0, 1.0, -1.0)
        t = sign_tau / (jnp.abs(tau) + jnp.sqrt(1.0 + tau * tau))
        t = jnp.where(active, t, 0.0)
        c = 1.0 / jnp.sqrt(1.0 + t * t)
        s = t * c
        # Apply G(p,q,theta) on both sides via row/col updates.
        row_p = a[p, :]
        row_q = a[q, :]
        a = a.at[p, :].set(c * row_p - s * row_q)
        a = a.at[q, :].set(s * row_p + c * row_q)
        col_p = a[:, p]
        col_q = a[:, q]
        a = a.at[:, p].set(c * col_p - s * col_q)
        a = a.at[:, q].set(s * col_p + c * col_q)
        vp = v[:, p]
        vq = v[:, q]
        v = v.at[:, p].set(c * vp - s * vq)
        v = v.at[:, q].set(s * vp + c * vq)
        return (a, v), None

    def sweep(_, carry):
        (a, v), _ = jax.lax.scan(rotate, carry, jnp.arange(n_pairs))
        return (a, v)

    a, v = jax.lax.fori_loop(0, sweeps, sweep, (a, v))
    vals = jnp.diag(a)
    order = jnp.argsort(vals)
    return vals[order], v[:, order]


def qr_get_q(matrix) -> jax.Array:
    """Q factor (``qr_get_q``, ``qr.cuh:73`` → geqrf/orgqr)."""
    q, _ = jnp.linalg.qr(wrap_array(matrix, ndim=2), mode="reduced")
    return q


def qr_get_qr(matrix) -> Tuple[jax.Array, jax.Array]:
    """(Q, R) (``qr_get_qr``, ``qr.cuh:95``)."""
    return jnp.linalg.qr(wrap_array(matrix, ndim=2), mode="reduced")


def svd_qr(matrix, gen_u: bool = True, gen_v: bool = True):
    """SVD via the QR-iteration path (``svd_qr``, ``svd.cuh:195`` → gesvd).

    Returns (U, S, V) with V as columns (reference convention: right singular
    vectors in a n×k matrix, not Vᵀ).
    """
    matrix = wrap_array(matrix, ndim=2)
    u, s, vt = jnp.linalg.svd(matrix, full_matrices=False)
    return (u if gen_u else None), s, (vt.T if gen_v else None)


def svd_eig(matrix):
    """SVD via eigendecomposition of the Gram matrix (``svd_eig``,
    ``svd.cuh:332``): eigh(AᵀA) → V, S; U = A V S⁻¹.  Faster for tall-skinny
    A on the MXU (one n×k gram matmul + small eigh)."""
    a = wrap_array(matrix, ndim=2)
    gram = jnp.matmul(a.T, a, preferred_element_type=jnp.float32)
    vals, vecs = jnp.linalg.eigh(gram)
    # descending order, clamp tiny negatives from roundoff
    vals = jnp.maximum(vals[::-1], 0.0)
    vecs = vecs[:, ::-1]
    s = jnp.sqrt(vals)
    u = jnp.matmul(a, vecs, preferred_element_type=jnp.float32) / jnp.where(s > 0, s, 1.0)[None, :]
    return u, s, vecs


def svd_jacobi(matrix, max_sweeps: int = 15, tol: float = 1e-7):
    """One-sided Jacobi SVD (``svd.cuh`` gesvdj parity) built on
    :func:`eig_jacobi` of the Gram matrix."""
    a = wrap_array(matrix, ndim=2)
    gram = jnp.matmul(a.T, a, preferred_element_type=jnp.float32)
    vals, vecs = eig_jacobi(gram, tol=tol, sweeps=max_sweeps)
    vals = jnp.maximum(vals[::-1], 0.0)
    vecs = vecs[:, ::-1]
    s = jnp.sqrt(vals)
    u = jnp.matmul(a, vecs, preferred_element_type=jnp.float32) / jnp.where(s > 0, s, 1.0)[None, :]
    return u, s, vecs


def rsvd_fixed_rank(matrix, k: int, p: int = 10, n_iters: int = 2, key=None):
    """Randomized SVD at fixed rank (``rsvd_fixed_rank``, ``rsvd.cuh:158``).

    Halko-Martinsson-Tropp range finder with power iterations — all matmuls,
    ideal for the MXU: Y = (A Aᵀ)^q A Ω, QR(Y), SVD of QᵀA.
    """
    a = wrap_array(matrix, ndim=2)
    m, n = a.shape
    ell = min(k + p, min(m, n))
    if key is None:
        key = jax.random.PRNGKey(0)
    omega = jax.random.normal(key, (n, ell), dtype=a.dtype)
    y = jnp.matmul(a, omega, preferred_element_type=jnp.float32)
    for _ in range(n_iters):
        q, _ = jnp.linalg.qr(y)
        z = jnp.matmul(a.T, q, preferred_element_type=jnp.float32)
        q, _ = jnp.linalg.qr(z)
        y = jnp.matmul(a, q, preferred_element_type=jnp.float32)
    q, _ = jnp.linalg.qr(y)
    b = jnp.matmul(q.T, a, preferred_element_type=jnp.float32)
    ub, s, vt = jnp.linalg.svd(b, full_matrices=False)
    u = jnp.matmul(q, ub, preferred_element_type=jnp.float32)
    return u[:, :k], s[:k], vt[:k, :].T


def lstsq_svd_qr(a, b):
    """min ‖Ax − b‖ via SVD (``lstsqSvdQR``, ``lstsq.cuh:31``)."""
    a = wrap_array(a, ndim=2)
    b = wrap_array(b)
    return jnp.linalg.lstsq(a, b)[0]


def lstsq_eig(a, b):
    """Least squares via normal equations + eigh (``lstsqEig``,
    ``lstsq.cuh:72``): (AᵀA)x = Aᵀb."""
    a = wrap_array(a, ndim=2)
    b = wrap_array(b)
    gram = jnp.matmul(a.T, a, preferred_element_type=jnp.float32)
    rhs = jnp.matmul(a.T, b, preferred_element_type=jnp.float32)
    vals, vecs = jnp.linalg.eigh(gram)
    inv_vals = jnp.where(vals > 1e-10 * vals[-1], 1.0 / vals, 0.0)
    proj = vecs.T @ rhs
    scaled = inv_vals[:, None] * proj if proj.ndim == 2 else inv_vals * proj
    return vecs @ scaled


def lstsq_qr(a, b):
    """Least squares via QR (``lstsqQR``, ``lstsq.cuh:98``)."""
    a = wrap_array(a, ndim=2)
    b = wrap_array(b)
    q, r = jnp.linalg.qr(a, mode="reduced")
    return jax.scipy.linalg.solve_triangular(r, q.T @ b, lower=False)


def cholesky_r1_update(chol_lower, new_col):
    """Rank-1 Cholesky extension (``cholesky_r1_update.cuh``): given L for the
    leading (n−1)×(n−1) block and the new row/col vector [b; c], return the
    n×n lower factor.  Used by incremental solvers downstream."""
    L = wrap_array(chol_lower, ndim=2)
    v = wrap_array(new_col, ndim=1)
    n = L.shape[0] + 1
    expects(v.shape[0] == n, "new_col must have length n (existing + 1)")
    b, c = v[:-1], v[-1]
    # Solve L y = b, then d = sqrt(c - yᵀy)
    y = jax.scipy.linalg.solve_triangular(L, b, lower=True)
    d = jnp.sqrt(jnp.maximum(c - jnp.dot(y, y), 0.0))
    out = jnp.zeros((n, n), dtype=L.dtype)
    out = out.at[:-1, :-1].set(L)
    out = out.at[-1, :-1].set(y)
    out = out.at[-1, -1].set(d)
    return out
