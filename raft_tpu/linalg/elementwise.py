"""Elementwise map family — parity with ``cpp/include/raft/linalg``'s
``map.cuh`` / ``add.cuh`` / ``subtract.cuh`` / ``divide.cuh`` / ``multiply.cuh``
/ ``power.cuh`` / ``sqrt.cuh`` / ``eltwise.cuh`` / ``unary_op.cuh`` /
``binary_op.cuh`` / ``ternary_op.cuh``.

The reference funnels all of these into one fused vectorized kernel
(``linalg/detail/map.cuh``).  On TPU, XLA fuses chains of elementwise ops into
a single VPU loop automatically, so these are thin wrappers whose value is API
parity + dtype/shape validation; ``map`` accepts arbitrary Python callables
(traced once, fused by XLA — same effect as the reference's functor template).
"""

from __future__ import annotations

import math
from typing import Callable

import jax.numpy as jnp

from ..core.array import check_same_shape, wrap_array

__all__ = [
    "map",
    "map_offset",
    "unary_op",
    "binary_op",
    "ternary_op",
    "add",
    "add_scalar",
    "subtract",
    "subtract_scalar",
    "multiply",
    "multiply_scalar",
    "divide",
    "divide_scalar",
    "power",
    "power_scalar",
    "sqrt",
]


def map(fn: Callable, *arrays):
    """Apply an n-ary elementwise functor (``linalg::map``, ``map.cuh``)."""
    arrays = [wrap_array(a) for a in arrays]
    for a in arrays[1:]:
        check_same_shape(arrays[0], a)
    return fn(*arrays)


def map_offset(fn: Callable, shape, dtype=jnp.int32):
    """Map over flat element offsets (``linalg::map_offset``): ``fn(idx)``
    evaluated for each linear index of ``shape``."""
    # shape is a host tuple: size it on the host (the former
    # jnp.prod(jnp.asarray(shape)) round-tripped a static value through
    # the device just to int() it back)
    idx = jnp.arange(math.prod(int(s) for s in shape), dtype=dtype)
    return fn(idx).reshape(shape)


def unary_op(fn, x):
    return map(fn, x)


def binary_op(fn, x, y):
    return map(fn, x, y)


def ternary_op(fn, x, y, z):
    return map(fn, x, y, z)


def add(x, y):
    return map(jnp.add, x, y)


def add_scalar(x, scalar):
    return wrap_array(x) + scalar


def subtract(x, y):
    return map(jnp.subtract, x, y)


def subtract_scalar(x, scalar):
    return wrap_array(x) - scalar


def multiply(x, y):
    return map(jnp.multiply, x, y)


def multiply_scalar(x, scalar):
    return wrap_array(x) * scalar


def divide(x, y):
    return map(jnp.divide, x, y)


def divide_scalar(x, scalar):
    return wrap_array(x) / scalar


def power(x, y):
    return map(jnp.power, x, y)


def power_scalar(x, scalar):
    return wrap_array(x) ** scalar


def sqrt(x):
    return jnp.sqrt(wrap_array(x))
