"""Label utilities.

Parity: ``label/classlabels.cuh`` (``getUniquelabels:31``, ``getOvrlabels:55``,
``make_monotonic:81``) and ``label/merge_labels.cuh:47`` (iterative-hooking
union of two labellings — the CUDA kernel loop becomes pointer-jumping gathers).
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["get_unique_labels", "get_ovr_labels", "make_monotonic", "merge_labels"]


def get_unique_labels(y) -> jax.Array:
    """Sorted unique labels (``getUniquelabels:31``).  Host-eager — the
    reference also returns a host count; output size is data-dependent."""
    return jnp.asarray(np.unique(np.asarray(y)))


def get_ovr_labels(y, y_unique, idx: int, dtype=None) -> jax.Array:
    """One-vs-rest ±1 labels (``getOvrlabels:55``):
    out = (y == y_unique[idx]) ? +1 : -1."""
    y = jnp.asarray(y)
    target = jnp.asarray(y_unique)[idx]
    out = jnp.where(y == target, 1, -1)
    return out.astype(dtype or y.dtype)


def make_monotonic(
    y,
    *,
    filter_op: Optional[Callable] = None,
    zero_based: bool = True,
) -> jax.Array:
    """Map labels onto a monotonically increasing set (``make_monotonic:81``).

    ``filter_op(label) -> bool`` excludes labels from remapping (they pass
    through unchanged), matching the reference's Lambda filter.
    ``zero_based=False`` starts at 1 like the reference's default.
    """
    y = jnp.asarray(y)
    yn = np.asarray(y)  # jaxlint: disable=JX01 host LUT build: filter_op is an arbitrary Python predicate, values must be concrete
    if filter_op is not None:
        keep = np.asarray([bool(filter_op(v)) for v in yn.tolist()])
    else:
        keep = np.ones(yn.shape, bool)
    uniq = np.unique(yn[keep])
    base = 0 if zero_based else 1
    lut = {v: i + base for i, v in enumerate(uniq.tolist())}
    out = np.asarray([lut[v] if k else v for v, k in zip(yn.tolist(), keep.tolist())])
    return jnp.asarray(out, y.dtype)


def merge_labels(labels_a, labels_b, mask) -> jax.Array:
    """Merge two labellings by connected components (``merge_labels.cuh:47``).

    Points where ``mask`` is true act as "core" points: if a core point has
    label i in A and j in B, groups i and j are merged.  Non-core points keep
    their A-label unless their group was merged.  Labels follow the
    reference's convention: the representative is the *minimum* label of the
    merged group.  Iterative hooking + pointer jumping, log rounds.
    """
    a = jnp.asarray(labels_a, jnp.int32)
    b = jnp.asarray(labels_b, jnp.int32)
    mask = jnp.asarray(mask, bool)
    # union-find domain: label values (bounded by n+1 per the contract)
    m = int(max(int(jnp.max(a)), int(jnp.max(b))) + 1)  # jaxlint: disable=JX01 union-find domain bound sizes a static-shape parent array; must be a host int
    parent = jnp.arange(m, dtype=jnp.int32)

    rounds = max(1, int(np.ceil(np.log2(max(m, 2)))) + 1)
    for _ in range(rounds):
        # hook: for each core point, link max(parent of a, parent of b) to min
        ra = parent[a]
        rb = parent[b]
        lo = jnp.minimum(ra, rb)
        hi = jnp.maximum(ra, rb)
        upd = jnp.where(mask, lo, parent[jnp.clip(hi, 0, m - 1)])
        parent = parent.at[jnp.clip(hi, 0, m - 1)].min(
            jnp.where(mask, upd, m)
        )
        # pointer jumping
        for _ in range(rounds):
            parent = parent[parent]
    return parent[a]
