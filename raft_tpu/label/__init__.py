"""Label utilities — ``raft/label`` parity (SURVEY.md §2.8)."""

from .labels import (
    get_ovr_labels,
    get_unique_labels,
    make_monotonic,
    merge_labels,
)

__all__ = [
    "get_unique_labels",
    "get_ovr_labels",
    "make_monotonic",
    "merge_labels",
]
