"""Compatibility layers for users migrating from the reference stack.

``raft_tpu.compat.pylibraft`` mirrors the pylibraft package layout
(``python/pylibraft/pylibraft``) so existing call sites keep working::

    from raft_tpu.compat import pylibraft
    from raft_tpu.compat.pylibraft.sparse.linalg import eigsh
"""
