"""``pylibraft.distance`` parity (the pre-cuVS surface the reference's
README now delegates — ``README.md:96-119``)."""

from __future__ import annotations


__all__ = ["pairwise_distance", "DISTANCE_TYPES"]


def _distance_types():
    # derived from the backing alias table so the advertised list can
    # never drift from what _as_metric actually accepts
    from raft_tpu.distance.pairwise import _ALIASES

    return sorted(_ALIASES)


DISTANCE_TYPES = _distance_types()


def pairwise_distance(X, Y=None, out=None, metric="euclidean", p=2.0,
                      handle=None):
    """Upstream convention: optional preallocated ``out`` is filled and
    returned; otherwise a new array comes back.

    >>> import numpy as np
    >>> x = np.random.default_rng(0).standard_normal((4, 3)).astype(np.float32)
    >>> d = pairwise_distance(x, metric="euclidean")
    >>> d.shape == (4, 4) and abs(float(np.asarray(d)[0, 0])) < 1e-5
    True
    """
    from raft_tpu.distance.pairwise import pairwise_distance as _pd

    from ..common import fill_out
    from ..common.outputs import auto_convert_output

    @auto_convert_output
    def _run():  # honors config.set_output_as; filled `out` passes through
        dist = _pd(X, Y, metric, p=float(p))
        return fill_out(out, dist) if out is not None else dist

    return _run()
