"""``pylibraft.config`` parity: process-wide output-conversion policy
(``python/pylibraft/pylibraft/config.py``).

Upstream lets callers pick what device arrays come back as
(``set_output_as("cupy"|"torch"|callable)``).  The TPU analog converts
``jax.Array`` outputs: ``"raft"`` (default — committed ``jax.Array``),
``"numpy"`` (host copy), ``"torch"`` (CPU torch tensor), or any callable
``jax.Array -> anything``.

>>> set_output_as("numpy")
>>> get_output_as()
'numpy'
>>> set_output_as("raft")
"""

from __future__ import annotations

from typing import Callable, Union

__all__ = ["set_output_as", "get_output_as", "SUPPORTED_OUTPUT_TYPES"]

SUPPORTED_OUTPUT_TYPES = ("raft", "numpy", "torch")

output_as_: Union[str, Callable] = "raft"


def set_output_as(output: Union[str, Callable]) -> None:
    """Set the global output conversion (upstream ``config.set_output_as``)."""
    if not callable(output) and output not in SUPPORTED_OUTPUT_TYPES:
        raise ValueError(
            f"output_as must be callable or one of {SUPPORTED_OUTPUT_TYPES}, "
            f"got {output!r}")
    global output_as_
    output_as_ = output


def get_output_as() -> Union[str, Callable]:
    return output_as_
