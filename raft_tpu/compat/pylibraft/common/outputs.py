"""``pylibraft.common.outputs`` parity: the ``auto_convert_output``
decorator (``common/outputs.py``) honoring :mod:`..config`'s policy.

Wrapped functions may return a ``jax.Array``, a tuple/list of them, or
anything else (passed through untouched — e.g. a preallocated ``out``
that was filled in place).

>>> from raft_tpu.compat.pylibraft import config
>>> import jax.numpy as jnp, numpy as np
>>> @auto_convert_output
... def f():
...     return jnp.arange(3), "tag"
>>> config.set_output_as("numpy")
>>> out, tag = f()
>>> type(out).__name__, tag
('ndarray', 'tag')
>>> config.set_output_as("raft")
"""

from __future__ import annotations

import functools

import numpy as np

from .. import config

__all__ = ["auto_convert_output"]


def _convert_leaf(x):
    import jax

    if not isinstance(x, jax.Array):
        return x
    policy = config.output_as_
    if callable(policy):
        return policy(x)
    if policy == "raft":
        return x
    if policy == "numpy":
        return np.asarray(x)
    if policy == "torch":
        import torch

        # copy: np.asarray(jax.Array) aliases JAX's read-only host cache,
        # and an in-place torch op on that buffer would corrupt it
        return torch.from_numpy(np.asarray(x).copy())
    raise ValueError(f"unknown output_as policy {policy!r}")


def auto_convert_output(f):
    """Convert ``jax.Array`` results per the global policy (upstream
    ``@auto_convert_output``)."""

    @functools.wraps(f)
    def wrapper(*args, **kwargs):
        ret = f(*args, **kwargs)
        if isinstance(ret, tuple) and hasattr(ret, "_fields"):  # namedtuple
            return type(ret)(*(_convert_leaf(v) for v in ret))
        if isinstance(ret, (tuple, list)):
            return type(ret)(_convert_leaf(v) for v in ret)
        return _convert_leaf(ret)

    return wrapper
