"""``pylibraft.common`` parity: handles and the owning device array.

``Handle``/``DeviceResources`` (``common/handle.pyx:21,125``) map to the
framework's :class:`raft_tpu.core.DeviceResources`; ``device_ndarray``
(``common/device_ndarray.py:10``) wraps a committed ``jax.Array`` with the
same factory/accessor surface minus ``__cuda_array_interface__``.
"""

from __future__ import annotations

import numpy as np

from raft_tpu.core import DeviceResources

from . import interruptible, outputs  # noqa: F401  (upstream submodules)
from .outputs import auto_convert_output

__all__ = ["Handle", "DeviceResources", "device_ndarray", "fill_out",
           "auto_convert_output", "interruptible", "outputs"]

# the core handle already carries sync(*arrays) (resources.py:150)
Handle = DeviceResources  # deprecated alias, as upstream


def fill_out(out, values):
    """Honor an upstream out-parameter: fill ``out`` in place and return
    it.  numpy arrays are written directly; :class:`device_ndarray`
    rebinds its device buffer (np.asarray(out) would write a throwaway
    host copy and silently lose the result)."""
    if isinstance(out, np.ndarray):
        out[...] = np.asarray(values).astype(out.dtype, copy=False)
        return out
    if isinstance(out, device_ndarray):
        import jax.numpy as jnp

        out._array = jnp.asarray(values, dtype=out.dtype)
        return out
    raise TypeError(
        f"out must be np.ndarray or device_ndarray, got {type(out).__name__}")


class device_ndarray:
    """Owning device array (``common/device_ndarray.py:10`` parity).

    Note: 64-bit dtypes follow JAX's dtype policy — without
    ``jax_enable_x64`` they are stored as their 32-bit counterparts (TPUs
    have no f64 units; the reference's CUDA arrays keep f64).

    >>> import numpy as np
    >>> a = device_ndarray(np.arange(6, dtype=np.float32).reshape(2, 3))
    >>> a.shape, a.dtype.name, a.c_contiguous
    ((2, 3), 'float32', True)
    >>> bool((a.copy_to_host() == np.arange(6).reshape(2, 3)).all())
    True
    """

    def __init__(self, np_ndarray):
        import jax.numpy as jnp

        self._array = jnp.asarray(np_ndarray)

    @classmethod
    def empty(cls, shape, dtype=np.float32, order="C"):
        if order != "C":
            # XLA storage is row-major; silently accepting 'F' would make
            # the contiguity flags lie to layout-branching call sites
            raise ValueError("device_ndarray only supports order='C' "
                             "(XLA layout); use core.copy for F-order host "
                             "views")
        return cls(np.zeros(shape, dtype=dtype, order=order))

    @property
    def c_contiguous(self):
        return True  # XLA arrays are logically row-major

    @property
    def f_contiguous(self):
        return False

    @property
    def dtype(self):
        return np.dtype(self._array.dtype)

    @property
    def shape(self):
        return tuple(self._array.shape)

    def copy_to_host(self):
        return np.asarray(self._array)

    def __array__(self, dtype=None):
        h = self.copy_to_host()
        return h.astype(dtype) if dtype is not None else h
