"""``pylibraft.common.interruptible`` parity (``common/interruptible.pyx``):
the ``synchronize``/``cancel`` pair with SIGINT → cooperative cancellation,
backed by :mod:`raft_tpu.core.interruptible`.

>>> import jax.numpy as jnp
>>> _ = synchronize(jnp.ones((2,)))      # completes; no pending cancel
>>> cancel()                             # flag the process
>>> try:
...     _ = synchronize(jnp.ones((2,)))
... except InterruptedException:
...     print("cancelled")
cancelled
"""

from __future__ import annotations

from raft_tpu.core.interruptible import (  # noqa: F401
    InterruptedException,
    cancel,
    clear,
    install_sigint_handler,
    synchronize,
    yield_now,
)

__all__ = ["InterruptedException", "cancel", "clear",
           "install_sigint_handler", "synchronize", "yield_now"]
