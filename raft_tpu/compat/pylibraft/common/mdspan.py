"""``pylibraft.common.mdspan`` parity — the Python-callable surface of
``common/mdspan.pyx``: the serializer roundtrip helper its tests use
(``mdspan.pyx:40``).  The Cython view-construction plumbing has no TPU
role (``jax.Array`` IS the view); serialization delegates to
:mod:`raft_tpu.core.serialize` (the ``serialize.hpp`` analog, numpy
``.npy`` framing on both sides).
"""

from __future__ import annotations

import io

import numpy as np

from raft_tpu.core.serialize import deserialize_mdspan, serialize_mdspan

__all__ = ["run_roundtrip_test_for_mdspan", "serialize_mdspan",
           "deserialize_mdspan"]


def run_roundtrip_test_for_mdspan(X, fortran_order: bool = False) -> None:
    """Serialize ``X`` to the ``.npy`` wire format and back; raise unless
    values, dtype, and memory order survive (upstream's roundtrip check).

    >>> run_roundtrip_test_for_mdspan(np.arange(6, dtype=np.int32).reshape(2, 3))
    >>> run_roundtrip_test_for_mdspan(
    ...     np.asfortranarray(np.eye(3, dtype=np.float32)), fortran_order=True)
    """
    arr = np.asarray(X)
    if fortran_order:
        arr = np.asfortranarray(arr)
    buf = io.BytesIO()
    serialize_mdspan(buf, arr)
    buf.seek(0)
    back = deserialize_mdspan(buf)
    np.testing.assert_array_equal(back, arr)
    if back.dtype != arr.dtype:
        raise AssertionError(f"dtype changed: {arr.dtype} -> {back.dtype}")
    if fortran_order and not back.flags.f_contiguous:
        raise AssertionError("fortran order not preserved")
