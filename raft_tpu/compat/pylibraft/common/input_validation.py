"""``pylibraft.common.input_validation`` parity — array cross-checks.

Upstream operates on ``__cuda_array_interface__`` dicts
(``common/input_validation.py:13-63``); the TPU translation accepts
anything with a shape/dtype (``jax.Array``, numpy, ``device_ndarray``)
and reads the same facts through numpy semantics.  C-contiguity for a
``jax.Array`` is definitionally true (XLA arrays export row-major).
"""

from __future__ import annotations

import numpy as np

__all__ = ["do_dtypes_match", "do_rows_match", "do_cols_match",
           "do_shapes_match", "is_c_contiguous"]


def _shape(a):
    return tuple(a.shape)


def _dtype(a):
    return np.dtype(a.dtype).str


def do_dtypes_match(*arrays) -> bool:
    """True when every array shares one dtype.

    >>> do_dtypes_match(np.zeros(2, np.float32), np.ones((3, 4), np.float32))
    True
    >>> do_dtypes_match(np.zeros(2, np.float32), np.zeros(2, np.int32))
    False
    """
    return len({_dtype(a) for a in arrays}) == 1


def do_rows_match(*arrays) -> bool:
    """True when every array has the same leading dimension."""
    return len({_shape(a)[0] for a in arrays}) == 1


def do_cols_match(*arrays) -> bool:
    """True when every array has the same second dimension."""
    return len({_shape(a)[1] for a in arrays}) == 1


def do_shapes_match(*arrays) -> bool:
    """True when every array has exactly the same shape.

    >>> do_shapes_match(np.zeros((2, 3)), np.ones((2, 3)))
    True
    """
    return len({_shape(a) for a in arrays}) == 1


def is_c_contiguous(a) -> bool:
    """Row-major contiguity.  numpy answers from its flags; committed
    ``jax.Array``s (and the compat ``device_ndarray``) are always exported
    row-major, so anything without flags answers True.

    >>> is_c_contiguous(np.zeros((4, 4)))
    True
    >>> is_c_contiguous(np.asfortranarray(np.zeros((4, 4))))
    False
    >>> is_c_contiguous(np.zeros((4, 1)))  # degenerate strides still count
    True
    """
    flags = getattr(a, "flags", None)
    if flags is not None:
        return bool(flags["C_CONTIGUOUS"] if not hasattr(flags, "c_contiguous")
                    else flags.c_contiguous)
    return True
