"""``pylibraft.sparse.linalg`` parity: ``eigsh`` / ``svds`` with the
upstream call conventions (``sparse/linalg/lanczos.pyx:100``,
``sparse/linalg/svds.pyx:73``) — scipy.sparse / dense / raft CSR inputs
all accepted."""

from __future__ import annotations

import numpy as np

__all__ = ["eigsh", "svds"]


def _as_csr(a):
    from raft_tpu.sparse.types import COO, CSR

    if isinstance(a, (CSR, COO)):
        return a
    if hasattr(a, "tocsr"):  # scipy.sparse (any format)
        sp = a.tocsr()
        return CSR.from_arrays(sp.indptr, sp.indices, sp.data, sp.shape)
    return CSR.from_dense(np.asarray(a))


def eigsh(A, k=6, which="LM", v0=None, ncv=None, maxiter=None,
          tol=0, seed=None, handle=None):
    """Thick-restart Lanczos, upstream signature (``lanczos.pyx:100``).
    Returns ``(eigenvalues, eigenvectors)``."""
    from raft_tpu.sparse.solver.lanczos import eigsh as _eigsh

    return _eigsh(
        _as_csr(A), int(k), which=which, ncv=ncv,
        maxiter=1000 if maxiter is None else int(maxiter),
        tol=float(tol), v0=v0, seed=42 if seed is None else int(seed))


def svds(a, k=6, *, p=10, n_iters=4, seed=None, handle=None):
    """Randomized sparse SVD, upstream signature (``svds.pyx:73``).
    Returns ``(U, S, V)``."""
    from raft_tpu.sparse.solver.randomized_svd import svds as _svds

    return _svds(_as_csr(a), int(k), p=int(p), n_iters=int(n_iters),
                 seed=42 if seed is None else int(seed))
