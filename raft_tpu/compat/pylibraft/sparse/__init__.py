"""``pylibraft.sparse`` parity."""

from . import linalg  # noqa: F401

__all__ = ["linalg"]
