"""``pylibraft.sparse`` parity."""

from . import linalg  # noqa: F401
