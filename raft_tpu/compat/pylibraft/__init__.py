"""pylibraft-shaped API over raft_tpu — module paths, entry-point names,
and call conventions of ``python/pylibraft/pylibraft`` (the north star's
"expose everything through pylibraft unchanged"), backed by the TPU-native
implementations.  CUDA-specific surfaces (streams, __cuda_array_interface__)
have no TPU meaning and are represented by host/device-array equivalents.
"""

from . import common, config, distance, neighbors, random, sparse  # noqa: F401

__version__ = "26.08.00+tpu"

__all__ = ["common", "config", "distance", "neighbors", "random", "sparse"]
