"""``pylibraft.neighbors.ivf_flat`` parity: params-first build/search/extend."""

from __future__ import annotations

import dataclasses

from ..common.outputs import auto_convert_output

__all__ = ["IndexParams", "SearchParams", "build", "search", "extend"]


@dataclasses.dataclass(frozen=True)
class IndexParams:
    """Upstream field names.  ``adaptive_centers`` accepted-but-fixed
    (TPU builds re-fit centers); ``add_data_on_build=False`` trains the
    quantizer on the dataset but leaves the lists empty for ``extend``.
    """

    n_lists: int = 1024
    metric: str = "sqeuclidean"
    kmeans_n_iters: int = 20
    kmeans_trainset_fraction: float = 0.5
    add_data_on_build: bool = True
    adaptive_centers: bool = False


@dataclasses.dataclass(frozen=True)
class SearchParams:
    n_probes: int = 20


def _native_params(p: IndexParams):
    from raft_tpu.neighbors.ivf_flat import IvfFlatIndexParams

    return IvfFlatIndexParams(
        n_lists=p.n_lists, metric=p.metric, kmeans_n_iters=p.kmeans_n_iters,
        kmeans_trainset_fraction=min(1.0, p.kmeans_trainset_fraction))


def build(index_params: IndexParams, dataset, handle=None):
    """``build(IndexParams, dataset)`` → index (upstream argument order).

    >>> import numpy as np
    >>> x = np.random.default_rng(0).standard_normal((256, 8)).astype(np.float32)
    >>> idx = build(IndexParams(n_lists=8), x)
    >>> d, i = search(SearchParams(n_probes=8), idx, x[:4], 3)
    >>> bool((np.asarray(i)[:, 0] == np.arange(4)).all())
    True
    """
    from raft_tpu.neighbors import ivf_flat as _native

    idx = _native.build(dataset, _native_params(index_params))
    if not index_params.add_data_on_build:
        idx = _clear_lists(idx)
    return idx


def _clear_lists(idx):
    """Train-only build: zero the occupancy (counts/ids) so stale rows
    can never surface (search validity masks on both) and ``extend``
    starts from an empty index with a trained quantizer."""
    import dataclasses as _dc

    import jax.numpy as jnp

    return _dc.replace(idx, counts=jnp.zeros_like(idx.counts),
                       ids=jnp.full_like(idx.ids, -1))


@auto_convert_output
def search(search_params: SearchParams, index, queries, k, handle=None):
    from raft_tpu.neighbors import ivf_flat as _native

    return _native.search(
        index, queries, int(k),
        _native.IvfFlatSearchParams(n_probes=int(search_params.n_probes)))


def extend(index, new_vectors, new_indices=None, handle=None):
    from raft_tpu.neighbors import ivf_flat as _native

    return _native.extend(index, new_vectors, new_indices)
