"""``pylibraft.neighbors.ivf_pq`` parity: params-first build/search/extend."""

from __future__ import annotations

import dataclasses

from ..common.outputs import auto_convert_output

__all__ = ["IndexParams", "SearchParams", "build", "search", "extend"]


@dataclasses.dataclass(frozen=True)
class IndexParams:
    """Upstream field names.  ``codebook_kind`` is accepted for signature
    parity; the TPU build trains per-subspace codebooks (the
    ``per_subspace`` kind).  ``add_data_on_build=False`` trains the
    quantizer+codebooks but leaves the lists empty for ``extend``."""

    n_lists: int = 1024
    metric: str = "sqeuclidean"
    kmeans_n_iters: int = 20
    kmeans_trainset_fraction: float = 0.5
    pq_bits: int = 8
    pq_dim: int = 0
    codebook_kind: str = "subspace"
    add_data_on_build: bool = True


@dataclasses.dataclass(frozen=True)
class SearchParams:
    """``lut_dtype`` selects the search tier: a reduced-precision LUT
    request routes to the code-resident LUT tier; the default takes the
    bf16 reconstruction tier.  ``internal_distance_dtype`` is accepted
    for signature parity only — the recon tier already accumulates in
    f32 over bf16 operands, which is what float16 internals ask for."""

    n_probes: int = 20
    lut_dtype: str = "float32"
    internal_distance_dtype: str = "float32"


def _native_params(p: IndexParams):
    from raft_tpu.neighbors.ivf_pq import IvfPqIndexParams

    return IvfPqIndexParams(
        n_lists=p.n_lists, metric=p.metric, kmeans_n_iters=p.kmeans_n_iters,
        kmeans_trainset_fraction=min(1.0, p.kmeans_trainset_fraction),
        pq_bits=p.pq_bits, pq_dim=p.pq_dim)


def build(index_params: IndexParams, dataset, handle=None):
    """``build(IndexParams, dataset)`` → index (upstream argument order).

    >>> import numpy as np
    >>> x = np.random.default_rng(0).standard_normal((512, 16)).astype(np.float32)
    >>> idx = build(IndexParams(n_lists=8, pq_dim=8), x)
    >>> d, i = search(SearchParams(n_probes=8), idx, x[:4], 3)
    >>> bool((np.asarray(i)[:, 0] == np.arange(4)).all())
    True
    """
    from raft_tpu.neighbors import ivf_pq as _native

    idx = _native.build(dataset, _native_params(index_params))
    if not index_params.add_data_on_build:
        from .ivf_flat import _clear_lists

        if idx.recon is not None:
            # drop-then-rebuild: ``with_recon`` is an idempotent no-op on an
            # index that still holds the stale full-dataset slab, so force
            # re-derivation from the cleared lists (cleared ids decode to
            # +inf recon_norms, masking every slot in recon-mode search)
            idx = _clear_lists(idx).without_recon().with_recon()
        else:
            idx = _clear_lists(idx)
    return idx


@auto_convert_output
def search(search_params: SearchParams, index, queries, k, handle=None):
    from raft_tpu.neighbors import ivf_pq as _native

    mode = "auto"
    if search_params.lut_dtype != "float32":
        mode = "lut"  # reduced-precision LUT request → code-resident tier
    return _native.search(
        index, queries, int(k),
        _native.IvfPqSearchParams(n_probes=int(search_params.n_probes),
                                  mode=mode))


def extend(index, new_vectors, new_indices=None, handle=None):
    from raft_tpu.neighbors import ivf_pq as _native

    return _native.extend(index, new_vectors, new_indices)
