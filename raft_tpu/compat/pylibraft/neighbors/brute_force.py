"""``pylibraft.neighbors.brute_force`` parity: the ``knn()`` entry point."""

from __future__ import annotations

from ..common.outputs import auto_convert_output

__all__ = ["knn"]


def knn(dataset, queries, k, indices=None, distances=None,
        metric="sqeuclidean", metric_arg=2.0, global_id_offset=0,
        handle=None):
    """Exact brute-force kNN, upstream argument order (dataset first;
    optional preallocated ``indices``/``distances`` outputs are filled
    and returned).

    >>> import numpy as np
    >>> x = np.random.default_rng(0).standard_normal((100, 8)).astype(np.float32)
    >>> d, i = knn(x, x[:5], 3)
    >>> bool((np.asarray(i)[:, 0] == np.arange(5)).all())
    True
    """
    from raft_tpu.neighbors.brute_force import knn as _knn

    from ..common import fill_out

    d, i = _knn(queries, dataset, int(k), metric=metric)
    if global_id_offset:
        i = i + int(global_id_offset)
    return _finish_out(d, i, distances, indices, fill_out)


def _finish_out(d, i, distances, indices, fill_out):
    """Upstream out-parameter contract shared by knn/refine: fill the
    preallocated buffers when given, else honor the output policy."""
    if distances is not None:
        d = fill_out(distances, d)
    if indices is not None:
        i = fill_out(indices, i)
    if distances is None and indices is None:
        return auto_convert_output(lambda: (d, i))()
    return d, i
