"""Thin serving adapter over :mod:`raft_tpu.serve` in the pylibraft call
convention (params-first, ``handle=`` accepted for signature parity).

Upstream has no serving surface — cuVS/pylibraft stop at one-shot
``search()`` — so this module is additive: it lets code already holding a
compat-built index and compat ``SearchParams`` stand up the online
runtime without translating params by hand::

    from raft_tpu.compat.pylibraft.neighbors import ivf_flat, serving
    index = ivf_flat.build(ivf_flat.IndexParams(n_lists=64), dataset)
    with serving.Server(ivf_flat.SearchParams(n_probes=8), index, k=10) as s:
        dist, ids = s.search(queries)
"""

from __future__ import annotations

__all__ = ["Server"]


def _native_search_params(search_params, index):
    """Translate a compat SearchParams (any family) to the native params
    struct ``raft_tpu.serve`` expects; native params pass through."""
    if search_params is None:
        return None
    from . import cagra as _cagra
    from . import ivf_flat as _ivf_flat
    from . import ivf_pq as _ivf_pq

    if isinstance(search_params, _ivf_flat.SearchParams):
        from raft_tpu.neighbors.ivf_flat import IvfFlatSearchParams

        return IvfFlatSearchParams(n_probes=int(search_params.n_probes))
    if isinstance(search_params, _ivf_pq.SearchParams):
        from raft_tpu.neighbors.ivf_pq import IvfPqSearchParams

        mode = "lut" if search_params.lut_dtype != "float32" else "auto"
        return IvfPqSearchParams(n_probes=int(search_params.n_probes),
                                 mode=mode)
    if isinstance(search_params, _cagra.SearchParams):
        from raft_tpu.neighbors.cagra import CagraSearchParams

        return CagraSearchParams(
            itopk_size=int(search_params.itopk_size),
            search_width=max(1, int(search_params.search_width)),
            max_iterations=int(search_params.max_iterations),
            n_seeds=32 * max(1, int(search_params.num_random_samplings)))
    return search_params  # native params (or brute-force) pass through


class Server:
    """``Server(SearchParams, index, k)`` — pylibraft-ordered wrapper
    around :class:`raft_tpu.serve.SearchServer`.

    Accepts every compat index (they *are* the native index objects) and
    compat or native SearchParams; ``config`` takes a
    :class:`raft_tpu.serve.ServerConfig` for the serving knobs.  Context
    manager: enter starts (and warms) the dispatch thread, exit drains
    and stops it.
    """

    def __init__(self, search_params, index, k, *, config=None,
                 handle=None) -> None:
        from raft_tpu.serve import SearchServer

        self._server = SearchServer(
            index, k=int(k),
            params=_native_search_params(search_params, index),
            config=config)

    def __enter__(self) -> "Server":
        self._server.start()
        return self

    def __exit__(self, *exc) -> None:
        self._server.stop()

    def search(self, queries, k=None, deadline_ms=None):
        """Synchronous serve: ``(distances, indices)`` numpy arrays."""
        return self._server.search(queries, k, deadline_ms)

    def submit(self, queries, k=None, deadline_ms=None):
        """Async serve: a ``concurrent.futures.Future``."""
        return self._server.submit(queries, k, deadline_ms)

    def metrics(self) -> dict:
        return self._server.metrics_snapshot()

    @property
    def native(self):
        """The underlying :class:`raft_tpu.serve.SearchServer`."""
        return self._server
