"""``pylibraft.neighbors.refine`` parity: exact re-ranking of candidates."""

from __future__ import annotations

__all__ = ["refine"]


def refine(dataset, queries, candidates, k, indices=None, distances=None,
           metric="sqeuclidean", handle=None):
    """Re-rank ``candidates`` (nq, n_cand) exactly against ``dataset``;
    upstream argument order with optional preallocated
    ``indices``/``distances`` outputs.

    >>> import numpy as np
    >>> from raft_tpu.compat.pylibraft.neighbors import brute_force
    >>> x = np.random.default_rng(0).standard_normal((100, 8)).astype(np.float32)
    >>> _, cand = brute_force.knn(x, x[:4], 10)
    >>> d, i = refine(x, x[:4], cand, 3)
    >>> bool((np.asarray(i)[:, 0] == np.arange(4)).all())
    True
    """
    from raft_tpu.neighbors.refine import refine as _refine

    from ..common import fill_out
    from .brute_force import _finish_out

    d, i = _refine(dataset, queries, candidates, int(k), metric=metric)
    return _finish_out(d, i, distances, indices, fill_out)
