"""``pylibraft.neighbors`` parity — the pre-cuVS upstream surface
(``python/pylibraft/pylibraft/neighbors`` in reference history; the
north star's "expose everything through pylibraft unchanged").

Upstream call convention, kept verbatim::

    from raft_tpu.compat.pylibraft.neighbors import ivf_pq
    index = ivf_pq.build(ivf_pq.IndexParams(n_lists=1024), dataset)
    dist, ids = ivf_pq.search(ivf_pq.SearchParams(n_probes=32),
                              index, queries, k=10)

i.e. ``build(IndexParams, dataset)`` / ``search(SearchParams, index,
queries, k)`` — params-first argument order, upstream metric naming
(``"sqeuclidean"``/``"euclidean"``/``"inner_product"``), optional
``handle=`` accepted everywhere (the TPU handle carries no streams, so
it is accepted for signature parity and unused).
"""

from . import brute_force, cagra, ivf_flat, ivf_pq, serving  # noqa: F401
from .refine import refine  # noqa: F401

__all__ = ["brute_force", "cagra", "ivf_flat", "ivf_pq", "refine",
           "serving"]
