"""``pylibraft.neighbors.cagra`` parity: params-first build/search."""

from __future__ import annotations

import dataclasses

from ..common.outputs import auto_convert_output

__all__ = ["IndexParams", "SearchParams", "build", "search"]


@dataclasses.dataclass(frozen=True)
class IndexParams:
    """Upstream field names; ``build_algo`` keeps the upstream vocabulary
    (``"ivf_pq"`` selects the IVF-sourced graph build, ``"nn_descent"``
    maps to the brute-force kNN-graph build — exact, which dominates
    NN-descent quality at TPU matmul rates)."""

    metric: str = "sqeuclidean"
    intermediate_graph_degree: int = 128
    graph_degree: int = 64
    build_algo: str = "ivf_pq"


@dataclasses.dataclass(frozen=True)
class SearchParams:
    """``num_random_samplings`` scales the native entry-seed count
    (``n_seeds = 32 · num_random_samplings``).  ``max_queries`` is
    accepted for parity; XLA batches any query count without a cap."""

    max_queries: int = 0
    itopk_size: int = 64
    max_iterations: int = 0
    search_width: int = 1
    num_random_samplings: int = 1


def build(index_params: IndexParams, dataset, handle=None):
    """``build(IndexParams, dataset)`` → index (upstream argument order).

    >>> import numpy as np
    >>> x = np.random.default_rng(0).standard_normal((400, 16)).astype(np.float32)
    >>> idx = build(IndexParams(intermediate_graph_degree=16, graph_degree=8,
    ...                         build_algo="nn_descent"), x)
    >>> d, i = search(SearchParams(itopk_size=32, search_width=4),
    ...               idx, x[:4], 3)
    >>> bool((np.asarray(i)[:, 0] == np.arange(4)).all())
    True
    """
    from raft_tpu.neighbors import cagra as _native

    algo = "ivf" if index_params.build_algo == "ivf_pq" else "brute_force"
    return _native.build(dataset, _native.CagraIndexParams(
        metric=index_params.metric,
        intermediate_graph_degree=index_params.intermediate_graph_degree,
        graph_degree=index_params.graph_degree,
        build_algo=algo))


@auto_convert_output
def search(search_params: SearchParams, index, queries, k, handle=None):
    from raft_tpu.neighbors import cagra as _native

    return _native.search(
        index, queries, int(k),
        _native.CagraSearchParams(
            itopk_size=int(search_params.itopk_size),
            search_width=max(1, int(search_params.search_width)),
            max_iterations=int(search_params.max_iterations),
            n_seeds=32 * max(1, int(search_params.num_random_samplings))))
