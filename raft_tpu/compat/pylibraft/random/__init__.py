"""``pylibraft.random`` parity: the RMAT generator with the upstream
out-parameter convention (``random/rmat_rectangular_generator.pyx:69``)."""

from __future__ import annotations

import numpy as np

__all__ = ["rmat"]


def rmat(out, theta, r_scale, c_scale, seed=12345, handle=None):
    """Fill ``out`` (n_edges, 2) with RMAT edges; also returns it.

    >>> import numpy as np
    >>> out = np.zeros((100, 2), np.int64)
    >>> _ = rmat(out, np.array([0.57, 0.19, 0.19, 0.05] * 4, np.float32), 4, 4)
    >>> bool((out >= 0).all() and (out < 16).all())
    True
    """
    from raft_tpu.random import RngState
    from raft_tpu.random.rmat import rmat as _rmat

    from ..common import fill_out

    if len(out.shape) != 2 or out.shape[1] != 2:
        raise ValueError("out must be (n_edges, 2)")
    edges = _rmat(RngState(int(seed)), int(out.shape[0]), np.asarray(theta),
                  int(r_scale), int(c_scale))
    return fill_out(out, edges)
