"""raft_tpu — a TPU-native rebuild of RAPIDS RAFT's capability surface.

JAX/XLA/Pallas implementation of the primitives + infrastructure layer that
vector-search and ML libraries build on: handle/resources, dense & sparse
linear algebra, matrix ops (select_k top-k), random generation, stats/metrics,
solvers, an injectable collective-communication layer over device meshes, and
the ANN index family (brute-force / IVF-Flat / IVF-PQ / CAGRA) plus kmeans.

Design (see SURVEY.md §7): not a port — view-first functional APIs over
``jax.Array``, SPMD via ``shard_map`` over named meshes, Pallas kernels for the
hot ops, counter-based RNG, and an optional injectable ``Resources`` handle
mirroring ``raft::resources`` (``cpp/include/raft/core/resources.hpp:47``).
"""

__version__ = "0.1.0"

from . import core
from .core import Resources, DeviceResources, default_resources

_SUBMODULES = (
    "linalg", "matrix", "random", "stats", "distance", "neighbors",
    "cluster", "comms", "sparse", "solver", "spectral", "label", "utils",
    "io", "ops", "serve",
)


def __getattr__(name):
    # Lazy submodule import keeps `import raft_tpu` light.
    if name in _SUBMODULES:
        import importlib

        mod = importlib.import_module(f"raft_tpu.{name}")
        globals()[name] = mod
        return mod
    raise AttributeError(f"module 'raft_tpu' has no attribute {name!r}")

__all__ = ["core", "Resources", "DeviceResources", "default_resources"]
