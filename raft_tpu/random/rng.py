"""RNG state + distributions — parity with ``cpp/include/raft/random/rng.cuh:43-503``
and ``rng_state.hpp:19`` (``RngState``, ``GeneratorType{GenPhilox,GenPC}``).

RAFT's generators are counter-based and stateless per call (``detail/rng_device.cuh``)
— exactly JAX's PRNG model, so ``RngState`` maps to a key plus a split counter
and every distribution is a pure function of (key, shape).  Philox is JAX's
default threefry-family generator; the PCG option maps to ``rbg`` when needed.
"""

from __future__ import annotations

import enum

import jax
import jax.numpy as jnp

from ..core.array import wrap_array
from ..core.errors import expects

__all__ = [
    "GeneratorType",
    "RngState",
    "uniform",
    "uniform_int",
    "normal",
    "normal_int",
    "normal_table",
    "fill",
    "bernoulli",
    "scaled_bernoulli",
    "gumbel",
    "lognormal",
    "logistic",
    "exponential",
    "rayleigh",
    "laplace",
    "discrete",
    "sample_without_replacement",
    "excess_subsample",
]


class GeneratorType(enum.Enum):
    """``rng_state.hpp:29``."""

    GenPhilox = "philox"
    GenPC = "pcg"


class RngState:
    """Seed + stream counter (``RngState``, ``rng_state.hpp:19``).

    ``next_key()`` advances the subsequence, giving each kernel call its own
    independent counter-based stream like the reference's per-call
    ``rng_state.advance()``.
    """

    def __init__(self, seed: int = 0, generator: GeneratorType = GeneratorType.GenPhilox):
        self.seed = int(seed)
        self.generator = generator
        self._subseq = 0
        impl = "threefry2x32" if generator == GeneratorType.GenPhilox else "rbg"
        self._base = jax.random.key(self.seed, impl=impl)

    def next_key(self) -> jax.Array:
        self._subseq += 1
        return jax.random.fold_in(self._base, self._subseq)

    def advance(self, n: int = 1) -> None:
        self._subseq += n


def _key_of(rng) -> jax.Array:
    if isinstance(rng, RngState):
        return rng.next_key()
    return rng  # assume a jax PRNG key


def uniform(rng, shape, low=0.0, high=1.0, dtype=jnp.float32):
    """U[low, high) (``rng.cuh`` ``uniform``)."""
    return jax.random.uniform(_key_of(rng), shape, dtype=dtype, minval=low, maxval=high)


def uniform_int(rng, shape, low: int, high: int, dtype=jnp.int32):
    """Uniform integers in [low, high) (``uniformInt``)."""
    return jax.random.randint(_key_of(rng), shape, low, high, dtype=dtype)


def normal(rng, shape, mu=0.0, sigma=1.0, dtype=jnp.float32):
    return mu + sigma * jax.random.normal(_key_of(rng), shape, dtype=dtype)


def normal_int(rng, shape, mu: int, sigma: int, dtype=jnp.int32):
    """Rounded normal (``normalInt``)."""
    return jnp.round(normal(rng, shape, float(mu), float(sigma))).astype(dtype)


def normal_table(rng, n_rows: int, mu_vec, sigma_vec=None, sigma: float = 1.0, dtype=jnp.float32):
    """Rows drawn with per-column mu/sigma (``normalTable``)."""
    mu_vec = wrap_array(mu_vec, ndim=1)
    n_cols = mu_vec.shape[0]
    sig = wrap_array(sigma_vec, ndim=1) if sigma_vec is not None else jnp.full((n_cols,), sigma)
    z = jax.random.normal(_key_of(rng), (n_rows, n_cols), dtype=dtype)
    return mu_vec[None, :] + sig[None, :] * z


def fill(rng, shape, value, dtype=jnp.float32):
    """``rng.cuh`` ``fill`` (kept for API parity; not actually random)."""
    del rng
    return jnp.full(shape, value, dtype=dtype)


def bernoulli(rng, shape, prob: float):
    return jax.random.bernoulli(_key_of(rng), prob, shape)


def scaled_bernoulli(rng, shape, prob: float, scale: float, dtype=jnp.float32):
    """±scale with probability flip (``scaledBernoulli``)."""
    b = jax.random.bernoulli(_key_of(rng), prob, shape)
    return jnp.where(b, jnp.asarray(scale, dtype), jnp.asarray(-scale, dtype))


def gumbel(rng, shape, mu=0.0, beta=1.0, dtype=jnp.float32):
    return mu + beta * jax.random.gumbel(_key_of(rng), shape, dtype=dtype)


def lognormal(rng, shape, mu=0.0, sigma=1.0, dtype=jnp.float32):
    return jnp.exp(normal(rng, shape, mu, sigma, dtype))


def logistic(rng, shape, mu=0.0, scale=1.0, dtype=jnp.float32):
    return mu + scale * jax.random.logistic(_key_of(rng), shape, dtype=dtype)


def exponential(rng, shape, lam=1.0, dtype=jnp.float32):
    return jax.random.exponential(_key_of(rng), shape, dtype=dtype) / lam


def rayleigh(rng, shape, sigma=1.0, dtype=jnp.float32):
    u = jax.random.uniform(_key_of(rng), shape, dtype=dtype, minval=1e-12, maxval=1.0)
    return sigma * jnp.sqrt(-2.0 * jnp.log(u))


def laplace(rng, shape, mu=0.0, scale=1.0, dtype=jnp.float32):
    return jax.random.laplace(_key_of(rng), shape, dtype=dtype) * scale + mu


def discrete(rng, shape, weights):
    """Sample indices proportional to weights (``discrete``)."""
    weights = wrap_array(weights, ndim=1)
    logits = jnp.log(jnp.maximum(weights, 1e-38))
    return jax.random.categorical(_key_of(rng), logits, shape=shape)


def sample_without_replacement(rng, population: int, n_samples: int, weights=None):
    """Weighted sampling without replacement (``rng.cuh``
    ``sample_without_replacement``) via the Gumbel top-k trick — one fused
    top_k instead of sequential draws."""
    expects(n_samples <= population, "cannot sample more than population")
    key = _key_of(rng)
    g = jax.random.gumbel(key, (population,))
    if weights is not None:
        g = g + jnp.log(jnp.maximum(wrap_array(weights, ndim=1), 1e-38))
    _, idx = jax.lax.top_k(g, n_samples)
    return idx


def excess_subsample(rng, population: int, n_samples: int):
    """Uniform subsample via excess-draw (``detail/rng_impl.cuh``
    ``excess_subsample``): functionally identical to unweighted
    :func:`sample_without_replacement`."""
    return sample_without_replacement(rng, population, n_samples)
