"""raft_tpu.random — counter-based RNG, distributions, data + graph generators.

TPU-native analog of ``cpp/include/raft/random`` (SURVEY.md §2.6).  JAX's
stateless key-based PRNG is the natural match for RAFT's counter-based
Philox/PCG design.
"""

from .rng import (
    GeneratorType, RngState,
    uniform, uniform_int, normal, normal_int, normal_table, fill,
    bernoulli, scaled_bernoulli, gumbel, lognormal, logistic,
    exponential, rayleigh, laplace, discrete,
    sample_without_replacement, excess_subsample,
)
from .datagen import make_blobs, make_regression, multi_variable_gaussian, permute
from .rmat import rmat_rectangular_gen, rmat

__all__ = ["GeneratorType", "RngState", "uniform", "uniform_int", "normal",
    "normal_int", "normal_table", "fill", "bernoulli", "scaled_bernoulli",
    "gumbel", "lognormal", "logistic", "exponential", "rayleigh", "laplace",
    "discrete", "sample_without_replacement", "excess_subsample", "make_blobs",
    "make_regression", "multi_variable_gaussian", "permute",
    "rmat_rectangular_gen", "rmat"]
