"""Synthetic data generation — parity with
``cpp/include/raft/random/make_blobs.cuh:58,126`` (GMM cluster generator),
``make_regression.cuh``, ``multi_variable_gaussian.cuh``, ``permute.cuh``.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from ..core.array import wrap_array
from .rng import _key_of

__all__ = ["make_blobs", "make_regression", "multi_variable_gaussian", "permute"]


def make_blobs(
    rng,
    n_samples: int,
    n_features: int,
    n_clusters: int = 5,
    cluster_std: float = 1.0,
    center_box: Tuple[float, float] = (-10.0, 10.0),
    centers=None,
    shuffle: bool = True,
    dtype=jnp.float32,
) -> Tuple[jax.Array, jax.Array]:
    """Gaussian-mixture blobs → (X, labels) (``make_blobs.cuh:58``)."""
    key = _key_of(rng)
    k_centers, k_assign, k_noise, k_shuffle = jax.random.split(key, 4)
    if centers is None:
        centers = jax.random.uniform(
            k_centers, (n_clusters, n_features), dtype=dtype,
            minval=center_box[0], maxval=center_box[1],
        )
    else:
        centers = wrap_array(centers, ndim=2, dtype=dtype)
        n_clusters = centers.shape[0]
    labels = jax.random.randint(k_assign, (n_samples,), 0, n_clusters)
    noise = cluster_std * jax.random.normal(k_noise, (n_samples, n_features), dtype=dtype)
    x = jnp.take(centers, labels, axis=0) + noise
    if shuffle:
        perm = jax.random.permutation(k_shuffle, n_samples)
        x, labels = x[perm], labels[perm]
    return x, labels.astype(jnp.int32)


def make_regression(
    rng,
    n_samples: int,
    n_features: int,
    n_informative: Optional[int] = None,
    n_targets: int = 1,
    bias: float = 0.0,
    noise: float = 0.0,
    shuffle: bool = True,
    dtype=jnp.float32,
):
    """Linear-model regression data → (X, y, coef) (``make_regression.cuh``)."""
    n_informative = n_features if n_informative is None else min(n_informative, n_features)
    key = _key_of(rng)
    k_x, k_w, k_n, k_s = jax.random.split(key, 4)
    x = jax.random.normal(k_x, (n_samples, n_features), dtype=dtype)
    coef = jnp.zeros((n_features, n_targets), dtype=dtype)
    w = 100.0 * jax.random.uniform(k_w, (n_informative, n_targets), dtype=dtype)
    coef = coef.at[:n_informative].set(w)
    y = jnp.matmul(x, coef, preferred_element_type=jnp.float32).astype(dtype) + bias
    if noise > 0:
        y = y + noise * jax.random.normal(k_n, y.shape, dtype=dtype)
    if shuffle:
        perm = jax.random.permutation(k_s, n_samples)
        x, y = x[perm], y[perm]
    return x, y.squeeze(-1) if n_targets == 1 else y, coef


def multi_variable_gaussian(rng, n_samples: int, mean, cov):
    """Samples from N(mean, cov) (``multi_variable_gaussian.cuh`` — the
    reference factors cov with cuSOLVER potrf; here ``jax.random`` does the
    Cholesky internally)."""
    mean = wrap_array(mean, ndim=1)
    cov = wrap_array(cov, ndim=2)
    return jax.random.multivariate_normal(_key_of(rng), mean, cov, (n_samples,), dtype=mean.dtype)


def permute(rng, array_or_n, rows: bool = True):
    """Random permutation of rows (or an index permutation)
    (``random/permute.cuh``)."""
    key = _key_of(rng)
    if isinstance(array_or_n, int):
        return jax.random.permutation(key, array_or_n)
    arr = wrap_array(array_or_n)
    axis = 0 if rows else 1
    perm = jax.random.permutation(key, arr.shape[axis])
    return jnp.take(arr, perm, axis=axis), perm
