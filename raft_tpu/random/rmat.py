"""R-MAT rectangular graph generator — parity with
``cpp/include/raft/random/rmat_rectangular_generator.cuh`` (kernel
``detail/rmat_rectangular_generator.cuh:67``: one thread per edge, per-thread
generator stream, quadrant descent over the scale levels) and the pylibraft
binding ``random/rmat_rectangular_generator.pyx:69``.

TPU formulation: the quadrant descent is vectorized over all edges at once —
``max(r_scale, c_scale)`` rounds of a 4-way categorical pick, each round
appending one bit to the row/col ids.  No per-edge loop; one (n_edges × levels)
uniform tensor drives everything.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

from ..core.array import wrap_array
from ..core.errors import expects
from .rng import _key_of

__all__ = ["rmat_rectangular_gen", "rmat"]


def rmat_rectangular_gen(
    rng,
    n_edges: int,
    theta,
    r_scale: int,
    c_scale: int,
) -> Tuple[jax.Array, jax.Array]:
    """Generate (src, dst) of an R-MAT graph with 2^r_scale × 2^c_scale
    adjacency.  ``theta`` is ``(max_scale, 4)`` (or flat ``4*max_scale``)
    per-level quadrant probabilities [a, b, c, d], exactly the reference's
    layout."""
    max_scale = max(r_scale, c_scale)
    theta = wrap_array(theta).reshape(max_scale, 4).astype(jnp.float32)
    # Normalize each level (the reference requires caller-normalized theta;
    # we tolerate unnormalized input).
    theta = theta / jnp.sum(theta, axis=1, keepdims=True)

    key = _key_of(rng)
    u = jax.random.uniform(key, (n_edges, max_scale))

    # Per level: cumulative [a, a+b, a+b+c] thresholds → quadrant in {0,1,2,3}
    cum = jnp.cumsum(theta, axis=1)  # (L, 4)
    q = (u[:, :, None] > cum[None, :, :3]).sum(axis=2)  # (n_edges, L) in 0..3

    # Quadrant bits: row bit = q >> 1, col bit = q & 1 (a=00, b=01, c=10, d=11)
    # int32 ids: scales beyond 31 bits would need jax_enable_x64.
    expects(max_scale <= 31, "rmat scales > 31 require 64-bit ids (enable jax x64)")
    row_bits = (q >> 1).astype(jnp.int32)
    col_bits = (q & 1).astype(jnp.int32)

    # For rectangular output, only the last r_scale (c_scale) levels contribute
    # bits to rows (cols), matching detail/rmat_rectangular_generator.cuh:67.
    levels = jnp.arange(max_scale)
    r_shift = jnp.where(levels >= max_scale - r_scale, (max_scale - 1 - levels), -1)
    c_shift = jnp.where(levels >= max_scale - c_scale, (max_scale - 1 - levels), -1)
    src = jnp.sum(jnp.where(r_shift >= 0, row_bits << jnp.maximum(r_shift, 0), 0), axis=1)
    dst = jnp.sum(jnp.where(c_shift >= 0, col_bits << jnp.maximum(c_shift, 0), 0), axis=1)
    return src, dst


def rmat(rng, n_edges: int, theta, r_scale: int, c_scale: int) -> jax.Array:
    """pylibraft-style entry (``rmat_rectangular_generator.pyx:69``): returns
    an ``(n_edges, 2)`` int64 edge list."""
    src, dst = rmat_rectangular_gen(rng, n_edges, theta, r_scale, c_scale)
    return jnp.stack([src, dst], axis=1)
