"""Combinatorial solvers — ``raft/solver`` parity (SURVEY.md §2.8)."""

from .lap import LinearAssignmentProblem, lap_solve

__all__ = ["LinearAssignmentProblem", "lap_solve"]
