"""Linear assignment — ``solver/linear_assignment.cuh:60``
``LinearAssignmentProblem`` parity (``solve():125``; kernels
``solver/detail/lap_kernels.cuh``).

The reference ports Date & Nagi's GPU alternating-tree Hungarian algorithm —
a data-parallel but deeply branchy method.  The TPU-native replacement is the
**auction algorithm** (Bertsekas) with ε-scaling: every bidding round is a
dense, branch-free batch of row-max/scatter-max ops (VPU-shaped), the whole
solve is one ``lax.while_loop`` per ε-phase, and batching over problem
instances is ``vmap`` — matching the reference's ``batchsize`` dimension.
Auction with final ε < gap/n yields the optimal assignment; costs are scaled
so the default ε tolerance matches the reference's ``epsilon_`` role.
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp

from ..core.errors import expects

__all__ = ["LinearAssignmentProblem", "lap_solve"]


def _auction_phase(benefit, prices, eps: float, max_iters: int):
    """One ε-phase of forward auction on a single [n, n] benefit matrix.

    Returns (person→object assignment, prices).  All persons start
    unassigned; prices persist across phases (ε-scaling warm start).
    """
    n = benefit.shape[0]
    NEG = jnp.asarray(-jnp.inf, benefit.dtype)

    def cond(state):
        person_obj, obj_person, prices, it = state
        return (jnp.any(person_obj < 0)) & (it < max_iters)

    def body(state):
        person_obj, obj_person, prices, it = state
        unassigned = person_obj < 0  # [n]
        value = benefit - prices[None, :]  # [n, n]
        v1 = jnp.max(value, axis=1)
        j1 = jnp.argmax(value, axis=1)
        masked = value.at[jnp.arange(n), j1].set(NEG)
        v2 = jnp.max(masked, axis=1)
        # bid increment; v2=-inf (n==1 case) falls back to eps only
        incr = jnp.where(jnp.isfinite(v2), v1 - v2, 0.0) + eps
        bid = prices[j1] + incr

        # per-object winner: max bid, ties to smallest person index
        obj_bid = jnp.full((n,), NEG, benefit.dtype)
        obj_bid = obj_bid.at[j1].max(jnp.where(unassigned, bid, NEG))
        is_win = unassigned & (bid >= obj_bid[j1]) & jnp.isfinite(obj_bid[j1])
        winner = jnp.full((n,), n, jnp.int32)
        winner = winner.at[j1].min(
            jnp.where(is_win, jnp.arange(n, dtype=jnp.int32), n)
        )
        has_winner = winner < n

        # evict previous owners of re-priced objects
        evicted_owner = jnp.where(has_winner, obj_person, -1)  # [n] person ids
        person_obj = jnp.where(
            jnp.isin(jnp.arange(n), jnp.where(evicted_owner >= 0, evicted_owner, -2)),
            -1,
            person_obj,
        )
        # assign winners; sentinel index n drops non-winning objects so stale
        # reads can never clobber a concurrent winner write
        won_obj = jnp.full((n,), -1, jnp.int32)
        won_obj = won_obj.at[jnp.where(has_winner, winner, n)].set(
            jnp.arange(n, dtype=jnp.int32), mode="drop"
        )
        person_obj = jnp.where(won_obj >= 0, won_obj, person_obj)
        obj_person = jnp.where(has_winner, winner, obj_person)
        prices = jnp.where(has_winner, obj_bid, prices)
        return person_obj, obj_person, prices, it + 1

    person_obj = jnp.full((n,), -1, jnp.int32)
    obj_person = jnp.full((n,), -1, jnp.int32)
    state = (person_obj, obj_person, prices, jnp.int32(0))
    person_obj, obj_person, prices, _ = jax.lax.while_loop(cond, body, state)
    return person_obj, obj_person, prices


@partial(jax.jit, static_argnames=("max_iters", "n_phases"))
def _solve_single(cost, eps_final: float, max_iters: int, n_phases: int):
    n = cost.shape[0]
    benefit = -cost  # minimization → maximization
    span = jnp.maximum(jnp.max(jnp.abs(benefit)), 1.0)
    prices = jnp.zeros((n,), cost.dtype)
    person_obj = jnp.full((n,), -1, jnp.int32)
    obj_person = jnp.full((n,), -1, jnp.int32)
    # ε-scaling: eps_0 = span/2, divide by 5 each phase down to eps_final
    for p in range(n_phases):
        eps = jnp.maximum(span / 2.0 / (5.0 ** p), eps_final)
        person_obj, obj_person, prices = _auction_phase(
            benefit, prices, eps, max_iters
        )
    return person_obj, obj_person


class LinearAssignmentProblem:
    """Batched LAP solver (``linear_assignment.cuh:60``).

    ``solve(cost[batch, n, n])`` → ``(row_assignment, col_assignment)`` of
    ``[batch, n]`` each, plus primal objective accessors mirroring
    ``getPrimalObjectiveValue``.
    """

    def __init__(self, size: int, batchsize: int = 1, epsilon: float = 1e-6):
        expects(size >= 1, "size must be positive")
        self.size = size
        self.batchsize = batchsize
        self.epsilon = float(epsilon)
        self._row_assign = None
        self._col_assign = None
        self._cost = None

    def solve(self, cost) -> Tuple[jax.Array, jax.Array]:
        cost = jnp.asarray(cost)
        if cost.ndim == 2:
            cost = cost[None]
        expects(cost.shape[1] == cost.shape[2] == self.size, "cost shape mismatch")
        n = self.size
        # enough phases to reach epsilon, enough rounds to settle each phase
        import math

        span_bound = 10.0  # phases computed for worst case via static count
        n_phases = max(3, int(math.ceil(math.log(max(span_bound / self.epsilon, 10.0)) / math.log(5.0))) + 1)
        max_iters = 60 * n * n_phases
        row, col = jax.vmap(
            lambda c: _solve_single(c, self.epsilon, max_iters, n_phases)
        )(cost)
        self._row_assign, self._col_assign, self._cost = row, col, cost
        return row, col

    def get_primal_objective(self) -> jax.Array:
        """Assignment cost per batch (``getPrimalObjectiveValue`` parity)."""
        expects(self._row_assign is not None, "call solve() first")
        b = jnp.arange(self._cost.shape[0])[:, None]
        i = jnp.arange(self.size)[None, :]
        return jnp.sum(self._cost[b, i, self._row_assign], axis=1)


def lap_solve(cost, epsilon: float = 1e-6) -> Tuple[jax.Array, jax.Array]:
    """Functional single/batched driver: returns (row_assignment, col_assignment)."""
    cost = jnp.asarray(cost)
    squeeze = cost.ndim == 2
    lap = LinearAssignmentProblem(cost.shape[-1],
                                  1 if squeeze else cost.shape[0], epsilon)
    row, col = lap.solve(cost)
    return (row[0], col[0]) if squeeze else (row, col)
