"""Logging — parity with ``cpp/include/raft/core/logger.hpp``.

The reference wraps rapids_logger with a default logger, compile-time level,
and an env-var-controlled file sink (``RAFT_DEBUG_LOG_FILE``,
``core/logger.hpp:27``).  Here we wrap :mod:`logging` the same way: one default
logger named ``raft_tpu``, level from ``RAFT_TPU_LOG_LEVEL``, optional file
sink from ``RAFT_TPU_DEBUG_LOG_FILE``.
"""

from __future__ import annotations

import logging
import os
from typing import Sequence

__all__ = ["default_logger", "log_trace_vec"]

_LOGGER_NAME = "raft_tpu"
_configured = False


def default_logger() -> logging.Logger:
    """The process-wide logger (``raft::default_logger()``, ``core/logger.hpp:46``)."""
    global _configured
    logger = logging.getLogger(_LOGGER_NAME)
    if not _configured:
        level = os.environ.get("RAFT_TPU_LOG_LEVEL", "INFO").upper()
        logger.setLevel(getattr(logging, level, logging.INFO))
        if not logger.handlers:
            handler = logging.StreamHandler()
            handler.setFormatter(logging.Formatter("[%(levelname)s] [%(name)s] %(message)s"))
            logger.addHandler(handler)
        log_file = os.environ.get("RAFT_TPU_DEBUG_LOG_FILE")
        if log_file:
            fh = logging.FileHandler(log_file)
            fh.setLevel(logging.DEBUG)
            logger.addHandler(fh)
            logger.setLevel(logging.DEBUG)
        _configured = True
    return logger


def log_trace_vec(name: str, values: Sequence, limit: int = 16) -> None:
    """``RAFT_LOG_TRACE_VEC`` parity (``core/logger.hpp:58``): trace-log a
    bounded prefix of a vector."""
    vals = list(values[:limit])
    default_logger().debug("%s: %s%s", name, vals, "..." if len(values) > limit else "")
