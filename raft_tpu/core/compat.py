"""Version-compat aliases for JAX APIs that moved between releases.

The library targets the current public surface (``jax.shard_map`` with
``check_vma``); older runtimes still in the fleet carry it under
``jax.experimental.shard_map`` with the ``check_rep`` spelling.  Call
sites import the alias from here instead of branching per-version.
"""

from __future__ import annotations

import jax

__all__ = ["axis_size", "shard_map"]

try:
    shard_map = jax.shard_map
except AttributeError:  # pre-0.6 runtimes: experimental namespace
    from jax.experimental.shard_map import shard_map as _legacy_shard_map

    def shard_map(f, *, mesh, in_specs, out_specs, check_vma=True):
        # the old kwarg is check_rep; semantics (disable the replication
        # checker) are the same for every use in this tree
        return _legacy_shard_map(f, mesh=mesh, in_specs=in_specs,
                                 out_specs=out_specs, check_rep=check_vma)

try:
    axis_size = jax.lax.axis_size
except AttributeError:  # pre-0.5: the size hangs off the axis env
    def axis_size(axis):
        from jax.core import axis_frame

        return axis_frame(axis)  # returns the mapped axis size (an int)
