"""lockdep — instrumented locks with runtime lock-order tracking.

The static arm (:mod:`raft_tpu.analysis.racelint`) proves lock
discipline *within* a file: guarded attributes are written under their
declared guard, no blocking call sits under a lock, acquisition order is
consistent method-to-method.  What it cannot see is the cross-module
composition at runtime — a ``DurableStore`` commit hook calling into a
``LogShipper`` that takes its own condition, a compaction daemon
swapping an index through the server's registry.  This module is that
runtime arm: drop-in ``Lock``/``RLock``/``Condition`` wrappers that

* record every *nested* acquisition as an edge in a process-global
  lock-order graph (``A held while acquiring B`` → edge A→B),
* detect **inversions** at acquisition time — acquiring B while a path
  B→…→A already exists for some held A means two threads can deadlock;
  the event is recorded (thread names, both orders) and counted as
  ``raft_lockdep_inversions_total`` rather than raised, so production
  keeps serving while the graph evidence lands in metrics,
* measure hold times into the obs :class:`~raft_tpu.obs.metrics.
  MetricRegistry` (``raft_lockdep_hold_seconds{lock=}`` histogram), and
* flag **blocking-under-lock** dynamically: a hold longer than
  ``RAFT_LOCKDEP_HOLD_S`` (default 0.1 s) counts
  ``raft_lockdep_blocking_holds_total{lock=}`` — the runtime mirror of
  racelint's JX12.

The wrappers are constructed unconditionally (``lockdep.lock("name")``
everywhere a ``threading.Lock()`` used to be) but instrumentation is
**off by default**: a disabled acquire is one attribute load + branch on
top of the raw lock, so the serving hot path pays nothing.  Tests arm it
via the ``lockdep_enabled`` fixture (``tests/conftest.py``); production
arms it with ``RAFT_LOCKDEP=1`` (and ``RAFT_LOCKDEP_REPORT=<path>``
makes the test session write the edge/inversion census on exit — the
zero-inversion gate ``tests/test_lockdep.py`` runs over the threaded
suites).

Pure standard library; the obs registry import is lazy and the
registry's own internal locks stay *plain* ``threading.Lock`` — the
metrics surface is a leaf the instrumentation reports into, never
through.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Dict, List, Optional, Tuple

__all__ = ["lock", "rlock", "condition", "enable", "disable", "enabled",
           "reset", "inversions", "edges", "held", "report",
           "hold_threshold_s"]

# module state below is guarded by _state_lock (a raw lock: lockdep must
# not instrument itself); the _enabled flag is a bare bool read on every
# acquire — torn reads are impossible for a Python bool and a stale read
# only delays arming by one acquisition
_state_lock = threading.Lock()
_edges: Dict[Tuple[str, str], Tuple[str, str]] = {}  # (a,b) -> (thread, where)
_inversions: List[dict] = []
_enabled = os.environ.get("RAFT_LOCKDEP", "") == "1"
_hold_threshold_s = float(os.environ.get("RAFT_LOCKDEP_HOLD_S", "0.1"))

_tls = threading.local()


def _held_stack() -> List["_Instrumented"]:
    stack = getattr(_tls, "held", None)
    if stack is None:
        stack = _tls.held = []
    return stack


def enable() -> None:
    """Arm instrumentation process-wide (all existing wrappers included)."""
    global _enabled
    _enabled = True


def disable() -> None:
    global _enabled
    _enabled = False


def enabled() -> bool:
    return _enabled


def hold_threshold_s(value: Optional[float] = None) -> float:
    """Get (and with ``value`` set) the blocking-hold flag threshold."""
    global _hold_threshold_s
    if value is not None:
        _hold_threshold_s = float(value)
    return _hold_threshold_s


def reset() -> None:
    """Clear the order graph + inversion log (test isolation)."""
    with _state_lock:
        _edges.clear()
        del _inversions[:]


def edges() -> Dict[Tuple[str, str], Tuple[str, str]]:
    """Snapshot of the observed lock-order graph."""
    with _state_lock:
        return dict(_edges)


def inversions() -> List[dict]:
    """Snapshot of recorded lock-order inversions (potential deadlocks)."""
    with _state_lock:
        return list(_inversions)


def held() -> List[str]:
    """Names of locks the *calling* thread currently holds, outermost
    first."""
    return [lk.name for lk in _held_stack()]


def report() -> dict:
    """JSON-able census: the artifact ``RAFT_LOCKDEP_REPORT`` writes."""
    with _state_lock:
        return {
            "tool": "lockdep",
            "enabled": _enabled,
            "edges": sorted(f"{a} -> {b}" for a, b in _edges),
            "inversions": list(_inversions),
            "inversion_total": len(_inversions),
        }


def _path_exists(src: str, dst: str) -> bool:
    """DFS over _edges: is there an order path src → … → dst?  Caller
    holds _state_lock."""
    seen = {src}
    stack = [src]
    while stack:
        node = stack.pop()
        if node == dst:
            return True
        for (a, b) in _edges:
            if a == node and b not in seen:
                seen.add(b)
                stack.append(b)
    return False


def _metrics():
    """The obs registry, or None when the obs package is unavailable
    (lockdep must work from a bare interpreter)."""
    try:
        from ..obs.metrics import registry
        return registry()
    except Exception:  # pragma: no cover - obs is part of this package
        return None


def _observe_hold(name: str, dt: float) -> None:
    reg = _metrics()
    if reg is None:
        return
    reg.histogram(
        "raft_lockdep_hold_seconds",
        "lock hold time in seconds (lockdep instrumentation)",
        boundaries=(1e-5, 1e-4, 1e-3, 1e-2, 0.1, 1.0, 10.0),
    ).observe(dt, lock=name)
    if dt >= _hold_threshold_s:
        reg.counter(
            "raft_lockdep_blocking_holds_total",
            "holds exceeding RAFT_LOCKDEP_HOLD_S — blocking under a lock",
        ).inc(lock=name)


def _count_inversion() -> None:
    reg = _metrics()
    if reg is not None:
        reg.counter(
            "raft_lockdep_inversions_total",
            "lock-order inversions observed at acquisition time",
        ).inc()


class _Instrumented:
    """Shared acquire/release bookkeeping over a raw primitive.

    Subclasses set ``_raw``; RLock re-entry is detected via the held
    stack (an inner re-acquire adds no edge and keeps the outer hold
    timer running)."""

    def __init__(self, name: str, raw) -> None:
        self.name = name
        self._raw = raw

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"<lockdep {type(self).__name__} {self.name!r}>"

    # -- bookkeeping ---------------------------------------------------

    def _note_acquired(self) -> None:
        stack = _held_stack()
        if any(e is self for e in stack):  # RLock re-entry: no new edge
            stack.append(self)
            return
        if stack:
            top_names = [e.name for e in stack if e.name != self.name]
            where = threading.current_thread().name
            new_inversions = 0
            with _state_lock:
                for a in top_names:
                    if (a, self.name) not in _edges:
                        if _path_exists(self.name, a):
                            _inversions.append({
                                "acquiring": self.name,
                                "while_holding": a,
                                "thread": where,
                                "established": _edges.get(
                                    (self.name, a), ("?", "?"))[0],
                            })
                            new_inversions += 1
                        _edges[(a, self.name)] = (where, "runtime")
            for _ in range(new_inversions):
                _count_inversion()
        stack.append(self)
        self._t0 = time.monotonic()

    def _note_released(self) -> None:
        stack = _held_stack()
        for i in range(len(stack) - 1, -1, -1):
            if stack[i] is self:
                del stack[i]
                break
        if not any(e is self for e in stack):  # outermost release
            t0 = getattr(self, "_t0", None)
            if t0 is not None:
                self._t0 = None
                _observe_hold(self.name, time.monotonic() - t0)

    # -- lock protocol -------------------------------------------------

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        ok = self._raw.acquire(blocking, timeout)
        if ok and _enabled:
            self._note_acquired()
        return ok

    def release(self) -> None:
        if _enabled:
            self._note_released()
        self._raw.release()

    def locked(self) -> bool:
        return self._raw.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()


class _InstrumentedLock(_Instrumented):
    def __init__(self, name: str) -> None:
        super().__init__(name, threading.Lock())


class _InstrumentedRLock(_Instrumented):
    def __init__(self, name: str) -> None:
        super().__init__(name, threading.RLock())


class _InstrumentedCondition(_Instrumented):
    """Condition over an instrumented (R)Lock.  ``wait`` releases the
    lock for its duration — the held stack and hold timer mirror that,
    so a 30 s ``wait`` does not read as a 30 s hold."""

    def __init__(self, name: str, lock=None) -> None:
        raw = threading.Condition(
            lock._raw if isinstance(lock, _Instrumented) else lock)
        super().__init__(name, raw)

    def wait(self, timeout: Optional[float] = None) -> bool:
        if _enabled:
            self._note_released()
        try:
            return self._raw.wait(timeout)
        finally:
            if _enabled:
                self._note_acquired()

    def wait_for(self, predicate, timeout: Optional[float] = None):
        if _enabled:
            self._note_released()
        try:
            return self._raw.wait_for(predicate, timeout)
        finally:
            if _enabled:
                self._note_acquired()

    def notify(self, n: int = 1) -> None:
        self._raw.notify(n)

    def notify_all(self) -> None:
        self._raw.notify_all()

    def locked(self) -> bool:  # pragma: no cover - parity with Lock API
        return self._raw._lock.locked()


def lock(name: str) -> _InstrumentedLock:
    """A ``threading.Lock`` with lockdep instrumentation (off until
    :func:`enable`).  ``name`` keys the order graph and the metric
    label — use ``Class._attr`` / ``module:_name`` so graph nodes read
    like the source."""
    return _InstrumentedLock(name)


def rlock(name: str) -> _InstrumentedRLock:
    """Instrumented ``threading.RLock`` (re-entry adds no edges)."""
    return _InstrumentedRLock(name)


def condition(name: str, lock=None) -> _InstrumentedCondition:
    """Instrumented ``threading.Condition`` (wait releases the hold)."""
    return _InstrumentedCondition(name, lock)
