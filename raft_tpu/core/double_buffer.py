"""Host→device double buffering for streaming pipelines.

The streaming index builds (``neighbors.*.build_chunked``) consume a
sequence of host chunks.  Feeding them naively puts the H2D copy on the
critical path: the device sits idle while chunk t+1 is copied in.  JAX's
``jax.device_put`` is *asynchronous* — it returns a handle immediately
and the copy proceeds in the background (TPU-KNN's overlapped-transfer
model, PAPERS.md) — so issuing the put for chunk t+1 while the device
computes on chunk t takes the copy off the critical path entirely.

:func:`device_prefetch` is the one shared home of that pattern: it maps a
staging function (typically ending in ``jax.device_put``) over an
iterable, keeping ``depth`` staged items in flight ahead of the consumer.
``device_put`` is an *explicit* transfer, so pipelines fed this way stay
clean under ``jax.transfer_guard("disallow")`` (:class:`.TraceGuard`).

On the CPU backend the transfer is zero-copy and the overlap is free but
empty; on TPU it hides the PCIe/DMA latency of each chunk.
"""

from __future__ import annotations

from collections import deque
from typing import Callable, Iterable, Iterator, TypeVar

__all__ = ["device_prefetch"]

T = TypeVar("T")
S = TypeVar("S")


def device_prefetch(items: Iterable[T], stage: Callable[[T], S],
                    depth: int = 1) -> Iterator[S]:
    """Yield ``stage(item)`` for each item, staying ``depth`` staged items
    ahead of the consumer.

    ``stage`` runs on the consumer thread (no locking needed) but is
    called for item t+1 *before* the consumer's loop body runs for item
    t — with an async ``jax.device_put`` inside ``stage``, the H2D copy
    of the next chunk overlaps the device compute on the current one.

    ``depth=1`` (classic double buffering) is right for the build loops:
    deeper pipelines only add host-memory pressure unless the producer
    is bursty.
    """
    if depth < 1:
        raise ValueError(f"depth must be >= 1, got {depth}")
    it = iter(items)
    buf: deque = deque()
    exhausted = False
    while True:
        while not exhausted and len(buf) < depth + 1:
            try:
                buf.append(stage(next(it)))
            except StopIteration:
                exhausted = True
        if not buf:
            return
        yield buf.popleft()
