"""Lazy, type-indexed resource container — the TPU-native analog of RAFT's handle.

Reference parity: ``cpp/include/raft/core/resources.hpp:47`` (``class resources``:
a vector of lazily-constructed resource cells keyed by a resource-type enum) and
``cpp/include/raft/core/device_resources.hpp:51`` (convenience facade).

On TPU there are no cuBLAS/cuSOLVER/stream handles to manage; the resources a
primitive needs are instead:

* the **device set / mesh** the computation is sharded over,
* a **PRNG key stream** (JAX's counter-based keys match RAFT's stateless
  Philox/PCG design, ``random/rng_state.hpp:19``),
* an injected **communicator** (``resource::set_comms`` parity,
  ``core/resource/comms.hpp``),
* memory / donation policy knobs and a workspace byte limit,
* a logger.

Like the reference, accessors lazily install a default factory on first use
(``resources::ensure_default_factory``, ``core/resources.hpp:100``), copies of
the container share resource cells, and user code can override any slot before
first use.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Sequence

import jax
import numpy as np

from . import lockdep
from .errors import RaftError, expects

__all__ = [
    "Resources",
    "DeviceResources",
    "default_resources",
    "set_default_resources",
    # accessor namespace (resource.get_* parity)
    "get_mesh",
    "get_devices",
    "get_rng_key",
    "get_comms",
    "set_comms",
    "get_workspace_limit",
]


class Resources:
    """Type-indexed lazy resource registry (``core/resources.hpp:47``).

    Slots are keyed by string (the Python analog of the 22-entry
    ``resource_type`` enum in ``core/resource/resource_types.hpp:24``).  A slot
    holds either a realized resource or a factory; factories run at most once,
    on first access, under a lock — mirroring the thread-safety contract of
    ``core/resources.hpp:27-35``.
    """

    # Well-known slot names (enum parity).
    DEVICES = "devices"
    MESH = "mesh"
    RNG_SEED = "rng_seed"
    RNG_COUNTER = "rng_counter"
    COMMS = "comms"
    SUB_COMMS = "sub_comms"
    WORKSPACE_LIMIT = "workspace_limit"
    LOGGER = "logger"
    DEFAULT_DTYPE = "default_dtype"
    DONATE = "donate"
    HOST_POOL = "host_pool"

    def __init__(self, **overrides: Any) -> None:
        self._lock = lockdep.rlock("Resources._lock")
        self._cells: Dict[str, Any] = {}  # guarded_by: _lock
        self._factories: Dict[str, Callable[["Resources"], Any]] = {}  # guarded_by: _lock
        self._install_default_factories()
        for name, value in overrides.items():
            self.set_resource(name, value)

    # -- factory / cell protocol (resource_types.hpp:58-97 parity) ---------
    def add_resource_factory(self, name: str, factory: Callable[["Resources"], Any]) -> None:
        """Register/replace the factory for ``name`` (``resources.hpp:81``)."""
        with self._lock:
            self._factories[name] = factory
            self._cells.pop(name, None)

    def set_resource(self, name: str, value: Any) -> None:
        """Directly install a realized resource into a slot."""
        with self._lock:
            self._cells[name] = value

    def has_resource_factory(self, name: str) -> bool:
        with self._lock:
            return name in self._factories or name in self._cells

    def get_resource(self, name: str) -> Any:
        """Fetch a resource, lazily running its factory (``resources.hpp:120``)."""
        with self._lock:
            if name not in self._cells:
                factory = self._factories.get(name)
                if factory is None:
                    raise RaftError(f"no resource or factory registered for {name!r}")
                self._cells[name] = factory(self)
            return self._cells[name]

    def copy(self) -> "Resources":
        """A copy *shares* realized resource cells (``resources.hpp`` copy ctor)."""
        other = Resources.__new__(Resources)
        other._lock = lockdep.rlock("Resources._lock")
        with self._lock:
            other._cells = dict(self._cells)
            other._factories = dict(self._factories)
        return other

    # -- defaults ----------------------------------------------------------
    def _install_default_factories(self) -> None:
        self.add_resource_factory(self.DEVICES, lambda _res: tuple(jax.devices()))
        self.add_resource_factory(self.MESH, _default_mesh_factory)
        self.add_resource_factory(self.RNG_SEED, lambda _res: 0)
        self.add_resource_factory(self.RNG_COUNTER, lambda _res: _Counter())
        self.add_resource_factory(self.WORKSPACE_LIMIT, lambda _res: None)
        self.add_resource_factory(self.DEFAULT_DTYPE, lambda _res: np.float32)
        self.add_resource_factory(self.DONATE, lambda _res: False)
        self.add_resource_factory(self.HOST_POOL, _default_host_pool_factory)
        self.add_resource_factory(self.LOGGER, _default_logger_factory)

    # -- convenience properties -------------------------------------------
    @property
    def devices(self) -> Sequence[jax.Device]:
        return self.get_resource(self.DEVICES)

    @property
    def mesh(self) -> jax.sharding.Mesh:
        return self.get_resource(self.MESH)

    @property
    def logger(self):
        return self.get_resource(self.LOGGER)

    def rng_key(self, advance: bool = True) -> jax.Array:
        """A fresh PRNG key from the handle's key stream.

        RAFT parity: ``RngState`` seed+subsequence (``random/rng_state.hpp:19``)
        — counter-based, so successive calls yield independent streams without
        mutable device state.
        """
        seed = self.get_resource(self.RNG_SEED)
        counter: _Counter = self.get_resource(self.RNG_COUNTER)
        sub = counter.next() if advance else counter.peek()
        return jax.random.fold_in(jax.random.PRNGKey(seed), sub)

    def sync(self, *arrays) -> None:
        """Wait for device work (``device_resources::sync_stream`` parity).

        Pass the arrays you need completed — PJRT orders completion per
        buffer, not per device, so only ``block_until_ready`` on a value
        gives a hard guarantee.  With no arguments this drains pending JAX
        effects (``jax.effects_barrier``), a best-effort global barrier.
        """
        if arrays:
            jax.block_until_ready(arrays)
        else:
            jax.effects_barrier()


class _Counter:
    def __init__(self) -> None:
        self._lock = lockdep.lock("resources._Counter._lock")
        self._v = 0  # guarded_by: _lock

    def next(self) -> int:
        with self._lock:
            self._v += 1
            return self._v

    def peek(self) -> int:
        with self._lock:
            return self._v


def _default_mesh_factory(res: Resources) -> jax.sharding.Mesh:
    devices = np.asarray(res.get_resource(Resources.DEVICES))
    return jax.sharding.Mesh(devices.reshape(-1), ("data",))


def _default_logger_factory(_res: Resources):
    from . import logging as raft_logging

    return raft_logging.default_logger()


def _default_host_pool_factory(_res: Resources):
    from .host_memory import HostBufferPool

    return HostBufferPool()


class DeviceResources(Resources):
    """Convenience facade preconfigured for the local device set.

    Parity: ``raft::device_resources`` (``core/device_resources.hpp:51``).
    Accepts an explicit mesh (the TPU analog of choosing device id + streams).
    """

    def __init__(
        self,
        mesh: Optional[jax.sharding.Mesh] = None,
        seed: Optional[int] = None,
        **overrides: Any,
    ) -> None:
        super().__init__(**overrides)
        if mesh is not None:
            self.set_resource(Resources.MESH, mesh)
            self.set_resource(Resources.DEVICES, tuple(mesh.devices.flat))
        if seed is not None:
            self.set_resource(Resources.RNG_SEED, seed)


_default: Optional[Resources] = None  # guarded_by: _default_lock
_default_lock = lockdep.lock("resources._default_lock")


def default_resources() -> Resources:
    """Process-wide default handle (``device_resources_manager`` parity,
    ``core/device_resources_manager.hpp:75``)."""
    global _default
    with _default_lock:
        if _default is None:
            _default = DeviceResources()
        return _default


def set_default_resources(res: Resources) -> None:
    global _default
    with _default_lock:
        _default = res


def _resolve(res: Optional[Resources]) -> Resources:
    return res if res is not None else default_resources()


# -- accessor functions (raft::resource::get_* parity) ---------------------

def get_mesh(res: Optional[Resources] = None) -> jax.sharding.Mesh:
    return _resolve(res).mesh


def get_devices(res: Optional[Resources] = None) -> Sequence[jax.Device]:
    return _resolve(res).devices


def get_rng_key(res: Optional[Resources] = None) -> jax.Array:
    return _resolve(res).rng_key()


def get_comms(res: Optional[Resources] = None):
    """Fetch the injected communicator (``resource::get_comms`` parity).

    Raises if none was injected, like the reference's
    ``RAFT_EXPECTS(has_resource_factory(...), "comms not initialized")``.
    """
    r = _resolve(res)
    expects(r.has_resource_factory(Resources.COMMS), "communicator not initialized on this handle")
    return r.get_resource(Resources.COMMS)


def set_comms(res: Resources, comms) -> None:
    """Inject a communicator (``resource::set_comms``, ``core/resource/comms.hpp``)."""
    res.set_resource(Resources.COMMS, comms)


def get_workspace_limit(res: Optional[Resources] = None) -> Optional[int]:
    return _resolve(res).get_resource(Resources.WORKSPACE_LIMIT)


def get_host_pool(res: Optional[Resources] = None):
    """The host staging-buffer pool (pinned-MR analog —
    :mod:`raft_tpu.core.host_memory`)."""
    return _resolve(res).get_resource(Resources.HOST_POOL)
