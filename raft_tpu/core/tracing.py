"""Tracing ranges — parity with ``cpp/include/raft/core/nvtx.hpp``.

RAFT provides RAII NVTX ranges (``common::nvtx::range``, ``core/nvtx.hpp:14-57``)
compiled out unless ``RAFT_NVTX`` is on.  The TPU analog is
``jax.profiler.TraceAnnotation`` (shows up in XProf/Perfetto timelines) plus
``jax.named_scope`` so the annotation also lands in HLO names.  Enabled by
default; set ``RAFT_TPU_TRACING=0`` to compile it out to a no-op.

Unified with :mod:`raft_tpu.obs` (ISSUE 9): every range additionally
records a structured span into the process flight recorder
(:func:`raft_tpu.obs.spans.recorder`), auto-parented by the calling
thread's open ranges — so engine/build/serve annotations that used to be
profiler-only are retained in the always-on ring buffer and come out in
stall dumps and Perfetto exports.  ``RAFT_OBS_SPANS=0`` disables just
the recording half; ``RAFT_TPU_TRACING=0`` disables both.

Push/pop discipline (satellite of ISSUE 9): :func:`pop_range` is safe on
an empty per-thread stack (returns ``False`` and counts
``raft_tracing_unbalanced_pops_total`` instead of raising or silently
hiding the imbalance) and is exception-safe — the obs span always
finishes and the stack entry always pops, even when the underlying
annotation's ``__exit__`` raises.
"""

from __future__ import annotations

import contextlib
import os
import threading
from functools import wraps

import jax

__all__ = ["range", "annotate", "push_range", "pop_range", "stack_depth"]

_ENABLED = os.environ.get("RAFT_TPU_TRACING", "1") != "0"
_tls = threading.local()


def _stack() -> list:
    # Per-thread like NVTX push/pop: annotations must not cross threads.
    if not hasattr(_tls, "stack"):
        _tls.stack = []
    return _tls.stack


def _recorder():
    from ..obs.spans import recorder

    return recorder()


@contextlib.contextmanager
def range(fmt: str, *args):
    """RAII-style range (``nvtx::range`` parity). Usage::

        with tracing.range("select_k(batch=%d,k=%d)", batch, k):
            ...

    Emits the profiler annotation + HLO scope AND a flight-recorder span
    (auto-parented to the innermost open range/span on this thread).
    """
    if not _ENABLED:
        yield
        return
    name = (fmt % args) if args else fmt
    with jax.profiler.TraceAnnotation(name), jax.named_scope(name), \
            _recorder().span(name):
        yield


def push_range(fmt: str, *args) -> None:
    """Explicit push (``nvtx::push_range``); pair with :func:`pop_range`."""
    if not _ENABLED:
        return
    name = (fmt % args) if args else fmt
    cm = jax.profiler.TraceAnnotation(name)
    cm.__enter__()
    span = _recorder().start(name)
    _stack().append((cm, span))


def pop_range() -> bool:
    """Pop the innermost pushed range.  Returns ``True`` when a range was
    popped; an unbalanced pop (empty stack) is a counted no-op — see the
    module docstring.  The flight-recorder span finishes even when the
    annotation's ``__exit__`` raises."""
    if not _ENABLED:
        return False
    stack = _stack()
    if not stack:
        from ..obs.metrics import registry

        registry().counter(
            "raft_tracing_unbalanced_pops_total",
            "pop_range() calls with no matching push_range()").inc()
        return False
    cm, span = stack.pop()
    try:
        cm.__exit__(None, None, None)
    finally:
        _recorder().finish(span)
    return True


def stack_depth() -> int:
    """Open pushed ranges on the calling thread (test/debug surface)."""
    return len(_stack())


def annotate(name: str = None):
    """Decorator form: annotate a whole function as a range."""

    def deco(fn):
        label = name or fn.__qualname__

        @wraps(fn)
        def wrapper(*args, **kwargs):
            with range(label):
                return fn(*args, **kwargs)

        return wrapper

    return deco
