"""Tracing ranges — parity with ``cpp/include/raft/core/nvtx.hpp``.

RAFT provides RAII NVTX ranges (``common::nvtx::range``, ``core/nvtx.hpp:14-57``)
compiled out unless ``RAFT_NVTX`` is on.  The TPU analog is
``jax.profiler.TraceAnnotation`` (shows up in XProf/Perfetto timelines) plus
``jax.named_scope`` so the annotation also lands in HLO names.  Enabled by
default; set ``RAFT_TPU_TRACING=0`` to compile it out to a no-op.
"""

from __future__ import annotations

import contextlib
import os
import threading
from functools import wraps

import jax

__all__ = ["range", "annotate", "push_range", "pop_range"]

_ENABLED = os.environ.get("RAFT_TPU_TRACING", "1") != "0"
_tls = threading.local()


def _stack() -> list:
    # Per-thread like NVTX push/pop: annotations must not cross threads.
    if not hasattr(_tls, "stack"):
        _tls.stack = []
    return _tls.stack


@contextlib.contextmanager
def range(fmt: str, *args):
    """RAII-style range (``nvtx::range`` parity). Usage::

        with tracing.range("select_k(batch=%d,k=%d)", batch, k):
            ...
    """
    if not _ENABLED:
        yield
        return
    name = (fmt % args) if args else fmt
    with jax.profiler.TraceAnnotation(name), jax.named_scope(name):
        yield


def push_range(fmt: str, *args) -> None:
    """Explicit push (``nvtx::push_range``); pair with :func:`pop_range`."""
    if not _ENABLED:
        return
    name = (fmt % args) if args else fmt
    cm = jax.profiler.TraceAnnotation(name)
    cm.__enter__()
    _stack().append(cm)


def pop_range() -> None:
    if not _ENABLED:
        return
    stack = _stack()
    if stack:
        stack.pop().__exit__(None, None, None)


def annotate(name: str = None):
    """Decorator form: annotate a whole function as a range."""

    def deco(fn):
        label = name or fn.__qualname__

        @wraps(fn)
        def wrapper(*args, **kwargs):
            with range(label):
                return fn(*args, **kwargs)

        return wrapper

    return deco
