"""Memory observability — parity with the reference's accounting stack:
``core/memory_stats_resources.hpp:75`` (allocation-counting handle wrapper,
incl. dry-run mode), ``mr/statistics_adaptor.hpp:25`` and
``mr/resource_monitor.hpp:42`` (sampled usage, trace-correlated).

TPU translation: XLA owns the allocator, so accounting hooks at two levels —

* **static analysis** (the dry-run analog): a jitted program's compiled
  ``memory_analysis`` reports argument/output/temp/peak bytes *without
  executing* — strictly stronger than the reference's dry-run counter,
  which must replay an allocation trace;
* **runtime sampling**: ``device_memory_stats`` (PJRT allocator counters)
  and ``MemoryTracker`` (live-buffer delta + peak across a scope).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, Optional

import jax

__all__ = [
    "MemoryAnalysis",
    "analyze_memory",
    "device_memory_stats",
    "live_bytes",
    "MemoryTracker",
]


@dataclasses.dataclass(frozen=True)
class MemoryAnalysis:
    """Compiled-program memory footprint (bytes)."""

    argument_size: int
    output_size: int
    temp_size: int
    alias_size: int
    generated_code_size: int

    @property
    def peak_estimate(self) -> int:
        return self.argument_size + self.output_size + self.temp_size


def analyze_memory(fn: Callable, *args, static_argnames=(), **kwargs) -> MemoryAnalysis:
    """Dry-run memory accounting (``memory_stats_resources`` dry-run parity):
    lower + compile ``fn`` for the given arguments and report XLA's memory
    analysis without running it."""
    jitted = fn if hasattr(fn, "lower") else jax.jit(fn, static_argnames=static_argnames)
    ma = jitted.lower(*args, **kwargs).compile().memory_analysis()

    def _get(*names: str) -> int:
        for n in names:
            v = getattr(ma, n, None)
            if v is not None:
                return int(v)
        return 0

    return MemoryAnalysis(
        argument_size=_get("argument_size_in_bytes"),
        output_size=_get("output_size_in_bytes"),
        temp_size=_get("temp_size_in_bytes"),
        alias_size=_get("alias_size_in_bytes"),
        generated_code_size=_get("generated_code_size_in_bytes"),
    )


def device_memory_stats(device: Optional[jax.Device] = None) -> Dict[str, Any]:
    """Allocator counters for one device (``mr/statistics_adaptor`` parity):
    ``bytes_in_use``, ``peak_bytes_in_use``, … — empty dict on backends that
    don't expose stats (CPU)."""
    dev = device if device is not None else jax.local_devices()[0]
    try:
        return dict(dev.memory_stats() or {})
    except (RuntimeError, AttributeError):
        return {}


def live_bytes(platform: Optional[str] = None) -> int:
    """Total bytes of live ``jax.Array`` buffers (tracking-MR parity,
    ``core/memory_tracking_resources.hpp``)."""
    total = 0
    for arr in jax.live_arrays(platform):
        try:
            total += arr.nbytes
        except Exception:  # deleted/donated buffers
            pass
    return total


class MemoryTracker:
    """Scope-based usage tracker (``mr::resource_monitor`` parity).

    >>> with MemoryTracker() as mt:
    ...     _ = jax.numpy.zeros((256, 256))
    >>> mt.allocated_delta >= 0
    True
    """

    def __init__(self, device: Optional[jax.Device] = None) -> None:
        self._device = device
        self.start_live = 0
        self.end_live = 0
        self.start_stats: Dict[str, Any] = {}
        self.end_stats: Dict[str, Any] = {}

    def __enter__(self) -> "MemoryTracker":
        self.start_live = live_bytes()
        self.start_stats = device_memory_stats(self._device)
        return self

    def __exit__(self, *exc) -> None:
        self.end_live = live_bytes()
        self.end_stats = device_memory_stats(self._device)

    @property
    def allocated_delta(self) -> int:
        """Live-buffer byte growth across the scope."""
        return self.end_live - self.start_live

    @property
    def peak_bytes(self) -> Optional[int]:
        """Peak allocation attributable to this scope, when the backend
        reports allocator statistics.

        ``peak_bytes_in_use`` is a process-lifetime high-water mark, so a
        peak reached *before* the scope would otherwise be reported
        unchanged.  Subtracting the bytes already in use at entry bounds the
        value to growth the scope could have caused; when the lifetime peak
        predates the scope entirely the result is clamped to the scope's
        live-byte growth (≥ 0).
        """
        peak = self.end_stats.get("peak_bytes_in_use")
        if peak is None:
            return None
        start_in_use = self.start_stats.get("bytes_in_use")
        start_peak = self.start_stats.get("peak_bytes_in_use")
        if start_in_use is None or start_peak is None:
            return int(peak)
        if int(peak) <= int(start_peak):  # peak predates the scope
            return max(self.allocated_delta, 0)
        return int(peak) - int(start_in_use)
