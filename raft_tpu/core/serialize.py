"""Array serialization — parity with ``cpp/include/raft/core/serialize.hpp``.

The reference serializes mdspans to the NumPy ``.npy`` format
(``serialize_mdspan``/``deserialize_mdspan``, ``core/serialize.hpp:26,73``;
writer in ``core/detail/mdspan_numpy_serializer.hpp``), used downstream for ANN
index persistence.  Here the on-disk format is the same ``.npy`` stream, so
artifacts interoperate with NumPy directly; scalars get the same header-framed
encoding (``serialize_scalar``).  Index objects serialize as a directory of
``.npy`` files plus a JSON metadata header (orbax-style layout, but zero-dep).
"""

from __future__ import annotations

import json
import os
from typing import Any, BinaryIO, Dict, Union

import jax
import numpy as np

__all__ = [
    "serialize_mdspan",
    "deserialize_mdspan",
    "serialize_scalar",
    "deserialize_scalar",
    "save_arrays",
    "load_arrays",
]


def serialize_mdspan(stream: BinaryIO, array: Union[np.ndarray, jax.Array]) -> None:
    """Write an array to ``stream`` in ``.npy`` format (``serialize.hpp:26``)."""
    np.save(stream, np.asarray(array), allow_pickle=False)


def deserialize_mdspan(stream: BinaryIO) -> np.ndarray:
    """Read one ``.npy``-framed array from ``stream`` (``serialize.hpp:73``)."""
    return np.load(stream, allow_pickle=False)


def serialize_scalar(stream: BinaryIO, value: Any, dtype=None) -> None:
    """Scalar with self-describing framing (``serialize_scalar`` parity)."""
    arr = np.asarray(value, dtype=dtype)
    np.save(stream, arr.reshape(()), allow_pickle=False)


def deserialize_scalar(stream: BinaryIO) -> Any:
    arr = np.load(stream, allow_pickle=False)
    return arr[()]


def save_arrays(path: Union[str, os.PathLike], arrays: Dict[str, Any], metadata: Dict[str, Any] = None) -> None:
    """Persist a named bundle of arrays + JSON metadata under ``path``.

    Layout: ``path/meta.json`` + one ``path/<name>.npy`` per array.  This is
    the checkpoint/resume surface for index objects (the reference's
    downstream use of ``serialize_mdspan``).
    """
    path = os.fspath(path)
    os.makedirs(path, exist_ok=True)
    names = sorted(arrays)
    with open(os.path.join(path, "meta.json"), "w") as f:
        json.dump({"arrays": names, "metadata": metadata or {}}, f, indent=1)
    for name in names:
        with open(os.path.join(path, f"{name}.npy"), "wb") as f:
            serialize_mdspan(f, arrays[name])


def load_arrays(path: Union[str, os.PathLike]):
    """Inverse of :func:`save_arrays` → ``(arrays_dict, metadata_dict)``.

    Uses the native threaded reader from :mod:`raft_tpu.io` when the
    extension is built, else ``np.load``.
    """
    from .. import io as rio

    path = os.fspath(path)
    with open(os.path.join(path, "meta.json")) as f:
        meta = json.load(f)
    arrays = {}
    for name in meta["arrays"]:
        arrays[name] = rio.read_npy(os.path.join(path, f"{name}.npy"))
    return arrays, meta.get("metadata", {})
