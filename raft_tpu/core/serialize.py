"""Array serialization — parity with ``cpp/include/raft/core/serialize.hpp``.

The reference serializes mdspans to the NumPy ``.npy`` format
(``serialize_mdspan``/``deserialize_mdspan``, ``core/serialize.hpp:26,73``;
writer in ``core/detail/mdspan_numpy_serializer.hpp``), used downstream for ANN
index persistence.  Here the on-disk format is the same ``.npy`` stream, so
artifacts interoperate with NumPy directly; scalars get the same header-framed
encoding (``serialize_scalar``).  Index objects serialize as a directory of
``.npy`` files plus a JSON metadata header (orbax-style layout, but zero-dep).

Durability tier (ISSUE 7): every array carries a CRC32 in ``meta.json``
(``checksums``), writers can stage into a temp directory and publish with
one atomic rename (``atomic=True``) after fsyncing every file, and
:func:`verify_arrays` detects truncation and bit-flips without loading
arrays into JAX — the building blocks for crash-consistent snapshots
(``neighbors.serialize`` / ``neighbors.wal``).
"""

from __future__ import annotations

import json
import os
import zlib
from typing import Any, BinaryIO, Dict, List, Optional, Union

import jax
import numpy as np

__all__ = [
    "serialize_mdspan",
    "deserialize_mdspan",
    "serialize_scalar",
    "deserialize_scalar",
    "save_arrays",
    "load_arrays",
    "verify_arrays",
    "CorruptArtifact",
    "fsync_dir",
    "write_text_atomic",
]


class CorruptArtifact(ValueError):
    """An on-disk artifact failed its integrity checks (truncated file,
    checksum mismatch, unreadable metadata)."""


def serialize_mdspan(stream: BinaryIO, array: Union[np.ndarray, jax.Array]) -> None:
    """Write an array to ``stream`` in ``.npy`` format (``serialize.hpp:26``)."""
    np.save(stream, np.asarray(array), allow_pickle=False)


def deserialize_mdspan(stream: BinaryIO) -> np.ndarray:
    """Read one ``.npy``-framed array from ``stream`` (``serialize.hpp:73``)."""
    return np.load(stream, allow_pickle=False)


def serialize_scalar(stream: BinaryIO, value: Any, dtype=None) -> None:
    """Scalar with self-describing framing (``serialize_scalar`` parity)."""
    arr = np.asarray(value, dtype=dtype)
    np.save(stream, arr.reshape(()), allow_pickle=False)


def deserialize_scalar(stream: BinaryIO) -> Any:
    arr = np.load(stream, allow_pickle=False)
    return arr[()]


def npy_bytes(array) -> bytes:
    """The exact ``.npy`` stream for ``array`` (header + data) — the unit
    both the checksummed writers and the WAL frame records around."""
    import io

    buf = io.BytesIO()
    serialize_mdspan(buf, array)
    return buf.getvalue()


def fsync_dir(path: Union[str, os.PathLike]) -> None:
    """fsync a DIRECTORY so a just-renamed entry survives power loss (the
    rename itself is atomic; its durability needs the parent synced)."""
    fd = os.open(os.fspath(path), os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _write_file(path: str, data: bytes, fsync: bool) -> None:
    with open(path, "wb") as f:
        f.write(data)
        if fsync:
            f.flush()
            os.fsync(f.fileno())


def write_text_atomic(path: Union[str, os.PathLike], text: str) -> str:
    """Publish a small text artifact (metrics snapshot, trace dump) with
    the crash-consistent single-file discipline: write to a sibling temp
    file, fsync it, ``os.replace`` onto ``path`` (atomic on POSIX), then
    fsync the directory.  A crash at any point leaves either the old
    complete file or the new complete file — never a torn one.  Returns
    ``path``."""
    path = os.fspath(path)
    tmp = f"{path}.tmp-{os.getpid()}"
    try:
        _write_file(tmp, text.encode(), fsync=True)
        os.replace(tmp, path)
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise
    fsync_dir(os.path.dirname(os.path.abspath(path)) or ".")
    return path


def save_arrays(path: Union[str, os.PathLike], arrays: Dict[str, Any],
                metadata: Dict[str, Any] = None, *, fsync: bool = False,
                atomic: bool = False) -> None:
    """Persist a named bundle of arrays + JSON metadata under ``path``.

    Layout: ``path/meta.json`` + one ``path/<name>.npy`` per array.  This is
    the checkpoint/resume surface for index objects (the reference's
    downstream use of ``serialize_mdspan``).

    ``meta.json`` carries a CRC32 per array (over the full ``.npy`` stream,
    header included) so readers can detect truncation and bit-flips
    (:func:`verify_arrays`).  ``fsync=True`` syncs every file (and the
    directory) before returning; ``atomic=True`` stages the bundle in a
    sibling temp directory and publishes it with one rename, so a crash
    mid-write never leaves a half-written bundle at ``path`` (the
    crash-consistent snapshot discipline — implies ``fsync``).
    """
    path = os.fspath(path)
    if atomic:
        fsync = True
        final, path = path, f"{path}.tmp-{os.getpid()}"
        if os.path.exists(path):
            import shutil

            shutil.rmtree(path)
    os.makedirs(path, exist_ok=True)
    names = sorted(arrays)
    blobs = {name: npy_bytes(arrays[name]) for name in names}
    meta = {
        "arrays": names,
        "metadata": metadata or {},
        "checksums": {name: zlib.crc32(blob) for name, blob in blobs.items()},
    }
    for name in names:
        _write_file(os.path.join(path, f"{name}.npy"), blobs[name], fsync)
    # meta last: its presence marks a complete bundle even without atomic=
    _write_file(os.path.join(path, "meta.json"),
                json.dumps(meta, indent=1).encode(), fsync)
    if fsync:
        fsync_dir(path)
    if atomic:
        if os.path.exists(final):  # refresh-in-place: swap, drop the old
            import shutil

            trash = f"{final}.old-{os.getpid()}"
            os.rename(final, trash)
            os.rename(path, final)
            shutil.rmtree(trash, ignore_errors=True)
        else:
            os.rename(path, final)
        fsync_dir(os.path.dirname(os.path.abspath(final)) or ".")


def load_arrays(path: Union[str, os.PathLike], *, verify: bool = False):
    """Inverse of :func:`save_arrays` → ``(arrays_dict, metadata_dict)``.

    Uses the native threaded reader from :mod:`raft_tpu.io` when the
    extension is built, else ``np.load``.  ``verify=True`` checks every
    array's CRC32 before returning (one extra read per file; artifacts
    written before checksums existed pass unchecked).
    """
    from .. import io as rio

    path = os.fspath(path)
    with open(os.path.join(path, "meta.json")) as f:
        meta = json.load(f)
    if verify:
        problems = verify_arrays(path)
        if problems:
            raise CorruptArtifact(f"{path}: " + "; ".join(problems))
    arrays = {}
    for name in meta["arrays"]:
        arrays[name] = rio.read_npy(os.path.join(path, f"{name}.npy"))
    return arrays, meta.get("metadata", {})


def verify_arrays(path: Union[str, os.PathLike]) -> List[str]:
    """Integrity-check a :func:`save_arrays` bundle without loading it into
    JAX.  Returns a list of problems (empty = intact): unreadable/absent
    ``meta.json``, missing array files, CRC32 mismatches (bit-flips AND
    truncation — the checksum covers the whole ``.npy`` stream).  Arrays
    not covered by a checksum (pre-durability artifacts) are only checked
    for existence."""
    path = os.fspath(path)
    problems: List[str] = []
    try:
        with open(os.path.join(path, "meta.json")) as f:
            meta = json.load(f)
    except (OSError, ValueError) as exc:
        return [f"meta.json unreadable: {exc}"]
    checksums = meta.get("checksums") or {}
    for name in meta.get("arrays", ()):
        fpath = os.path.join(path, f"{name}.npy")
        try:
            with open(fpath, "rb") as f:
                blob = f.read()
        except OSError as exc:
            problems.append(f"{name}.npy unreadable: {exc}")
            continue
        want = checksums.get(name)
        if want is not None and zlib.crc32(blob) != want:
            problems.append(f"{name}.npy checksum mismatch "
                            f"(bit-flip or truncation)")
    return problems


def checksum_file(path: Union[str, os.PathLike],
                  chunk: int = 1 << 20) -> Optional[int]:
    """CRC32 of a whole file (streamed), or None if unreadable."""
    crc = 0
    try:
        with open(os.fspath(path), "rb") as f:
            while True:
                block = f.read(chunk)
                if not block:
                    return crc
                crc = zlib.crc32(block, crc)
    except OSError:
        return None
