"""Runtime trace-guard harness: assert a code region is steady-state.

A "hot" region — a serve loop after warmup, a search path after its first
call — must neither re-trace/recompile (jit cache misses rebuild XLA
executables, a multi-second stall on TPU) nor move data between host and
device (each transfer is a blocking sync that drains the dispatch
pipeline).  Both hazards are invisible in unit tests on CPU: everything
still *passes*, just slower, and the cost only lands once the code runs
against a real TPU.  :class:`TraceGuard` makes them assertable::

    srv.warmup()
    with TraceGuard() as tg:
        for q in queries:
            srv.search(q)
    tg.assert_steady_state()      # zero traces, zero compiles

How it counts: :mod:`jax.monitoring` fires a duration event on every
jaxpr trace (``/jax/core/compile/jaxpr_trace_duration``) and every
backend compile (``/jax/core/compile/backend_compile_duration``) — and
nothing on a jit-cache hit — so the event count over a region is an
exact census of cache misses.  ``jax.monitoring`` has no public
unregister, so ONE module-level listener is registered lazily and
dispatches to whatever guards are currently active (nesting is fine:
every active guard sees every event).

Transfers ride :func:`jax.transfer_guard`: ``"disallow"`` raises on any
implicit host<->device movement inside the region.  Caveat: on the CPU
backend transfers are zero-copy and the guard never fires — so tests
assert the trace/compile counters (backend-independent) and merely run
clean under ``"disallow"``, which becomes a real tripwire on TPU.

The static analyzer (:mod:`raft_tpu.analysis.jaxlint`, JX01/JX02) finds
these hazards in source; this harness proves their absence at runtime.
Both gates ship in the same PR on purpose — see docs/jax_hygiene.md.
"""

from __future__ import annotations

from typing import List, Optional

import jax

from . import lockdep

__all__ = ["TraceGuard", "SteadyStateError"]

_TRACE_EVENT = "/jax/core/compile/jaxpr_trace_duration"
_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"

_lock = lockdep.lock("trace_guard._lock")
_active: List["TraceGuard"] = []  # guarded_by: _lock
_listener_registered = False


class SteadyStateError(AssertionError):
    """A guarded region traced, compiled, or transferred when it must not."""


def _on_event(event: str, duration: float, **kwargs) -> None:
    if event != _TRACE_EVENT and event != _COMPILE_EVENT:
        return
    with _lock:
        guards = list(_active)
    for g in guards:
        g._record(event, kwargs)


def _ensure_listener() -> None:
    # jax.monitoring exposes register but not unregister: install exactly
    # one permanent listener, route through the active-guard list.
    global _listener_registered
    with _lock:
        if _listener_registered:
            return
        _listener_registered = True
    jax.monitoring.register_event_duration_secs_listener(_on_event)


class TraceGuard:
    """Context manager counting jit cache misses and guarding transfers.

    Parameters
    ----------
    transfer : str
        ``jax.transfer_guard`` mode for the region: ``"disallow"``
        (default) raises on implicit transfers, ``"log"`` reports them,
        ``"allow"`` disables the transfer gate (counters still run).

    Attributes (valid during and after the ``with`` block)
    ------------------------------------------------------
    traces : int
        Jaxpr traces observed — the jit cache-miss count.
    compiles : int
        Backend (XLA) compiles observed.  ``compiles <= traces``: a
        trace whose jaxpr hits the persistent compilation cache still
        counts as a miss of the in-process jit cache.
    events : list of (event, description) tuples for diagnostics.
    """

    def __init__(self, transfer: str = "disallow"):
        self.transfer = transfer
        self.traces = 0
        self.compiles = 0
        self.events: List[tuple] = []
        self._cm: Optional[object] = None

    # -- listener callback -------------------------------------------------
    def _record(self, event: str, kwargs: dict) -> None:
        with _lock:
            if event == _TRACE_EVENT:
                self.traces += 1
            else:
                self.compiles += 1
            desc = kwargs.get("fun_name") or kwargs.get("event") or ""
            self.events.append((event, str(desc)))

    # -- context protocol --------------------------------------------------
    def __enter__(self) -> "TraceGuard":
        _ensure_listener()
        with _lock:
            _active.append(self)
        self._cm = jax.transfer_guard(self.transfer)
        self._cm.__enter__()
        return self

    def __exit__(self, exc_type, exc, tb):
        cm, self._cm = self._cm, None
        with _lock:
            if self in _active:
                _active.remove(self)
        return cm.__exit__(exc_type, exc, tb)

    # -- assertions --------------------------------------------------------
    def assert_steady_state(self, max_traces: int = 0,
                            max_compiles: int = 0) -> None:
        """Raise :class:`SteadyStateError` if the region exceeded the
        allowed trace/compile budget (both default to zero)."""
        if self.traces > max_traces or self.compiles > max_compiles:
            detail = "; ".join(f"{e.rsplit('/', 1)[-1]}:{d}"
                               for e, d in self.events[:8])
            raise SteadyStateError(
                f"guarded region not steady-state: {self.traces} trace(s) "
                f"(allowed {max_traces}), {self.compiles} compile(s) "
                f"(allowed {max_compiles}) [{detail}]")

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return (f"TraceGuard(transfer={self.transfer!r}, "
                f"traces={self.traces}, compiles={self.compiles})")
