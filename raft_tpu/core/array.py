"""Array ingestion/validation — the role of pylibraft's ``cai_wrapper`` /
``ai_wrapper`` (``python/pylibraft/pylibraft/common/cai_wrapper.py:10,32``) and
the mdspan conversion layer (``common/mdspan.pyx:40``).

Anything array-like (numpy, jax.Array, torch CPU tensor, lists, objects with
``__array__``/``__dlpack__``) normalizes to a ``jax.Array`` with validated
rank/dtype.  Output conversion (``auto_convert_output`` parity,
``common/outputs.py``) returns numpy on request.
"""

from __future__ import annotations

from typing import Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np

from .errors import expects

__all__ = ["wrap_array", "check_rank", "check_same_shape", "check_dtype", "to_numpy"]

ArrayLike = Union[jax.Array, np.ndarray, Sequence]


def wrap_array(
    x: ArrayLike,
    dtype=None,
    ndim: Optional[int] = None,
    name: str = "array",
) -> jax.Array:
    """Normalize any array-like to ``jax.Array`` (``wrap_array`` parity)."""
    if hasattr(x, "__dlpack__") and not isinstance(x, (jax.Array, np.ndarray)):
        try:  # torch / cupy style producers
            x = jnp.from_dlpack(x)
        except Exception:
            x = np.asarray(x)
    arr = jnp.asarray(x, dtype=dtype)
    if ndim is not None:
        check_rank(arr, ndim, name)
    return arr


def check_rank(x, ndim: int, name: str = "array") -> None:
    expects(x.ndim == ndim, f"{name}: expected rank {ndim}, got {x.ndim}")


def check_same_shape(a, b, name: str = "arrays") -> None:
    expects(tuple(a.shape) == tuple(b.shape), f"{name}: shape mismatch {a.shape} vs {b.shape}")


def check_dtype(x, dtypes, name: str = "array") -> None:
    if not isinstance(dtypes, (tuple, list)):
        dtypes = (dtypes,)
    expects(
        any(x.dtype == np.dtype(d) for d in dtypes),
        f"{name}: dtype {x.dtype} not in {[np.dtype(d).name for d in dtypes]}",
    )


def to_numpy(x) -> np.ndarray:
    """Host copy (``auto_convert_output`` role)."""
    return np.asarray(x)
