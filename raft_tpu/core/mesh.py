"""Device-mesh construction helpers — the TPU replacement for RAFT's
stream/device plumbing and the SNMG/MNMG handle variants.

Reference parity: ``core/device_resources_snmg.hpp:36`` (single-node multi-GPU
clique) maps to a single-process mesh over the local devices;
``raft_dask.common.Comms`` bootstrap (``common/comms.py:161``) maps to
``jax.distributed.initialize`` + a global mesh.  Axis-name conventions used
throughout the framework:

* ``"data"`` — batch/query-parallel axis (DP; rides DCN when multi-host),
* ``"shard"`` — database/index-shard axis (the MNMG index-shard model of
  §2.9/§5.7 of the survey; rides ICI).
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np

from .errors import expects

__all__ = [
    "make_mesh",
    "make_1d_mesh",
    "make_hybrid_mesh",
    "local_mesh",
    "distributed_init",
    "DATA_AXIS",
    "SHARD_AXIS",
]

DATA_AXIS = "data"
SHARD_AXIS = "shard"


def make_mesh(
    shape: Sequence[int],
    axis_names: Sequence[str],
    devices: Optional[Sequence[jax.Device]] = None,
) -> jax.sharding.Mesh:
    """Build a named mesh of the given logical shape over ``devices``.

    Uses ``jax.experimental.mesh_utils`` device ordering when available so the
    innermost axis maps to ICI neighbors (collectives ride ICI, not DCN).
    """
    if devices is None:
        try:
            from jax.experimental import mesh_utils

            dev_array = mesh_utils.create_device_mesh(tuple(shape))
            return jax.sharding.Mesh(dev_array, tuple(axis_names))
        except Exception:
            devices = jax.devices()
    dev = np.asarray(devices)
    expects(dev.size == int(np.prod(shape)), f"need {int(np.prod(shape))} devices, have {dev.size}")
    return jax.sharding.Mesh(dev.reshape(tuple(shape)), tuple(axis_names))


def make_hybrid_mesh(
    dcn_axis: str = DATA_AXIS,
    ici_axis: str = SHARD_AXIS,
    dcn_size: Optional[int] = None,
) -> jax.sharding.Mesh:
    """Two-level mesh for multi-pod/multi-slice deployments: the outer axis
    spans slices over **DCN** (data-center network), the inner axis spans
    each slice's chips over **ICI**.

    This is the topology-correct layout for the framework's sharded
    indexes: put the index-shard axis (heavy all-gather/ppermute merges)
    on ICI and the query/data-parallel axis (rare, small collectives) on
    DCN — the mesh-axis-ordering recipe of SURVEY.md §5.8, replacing the
    reference's NCCL-ring-over-IB assumptions
    (``comms/std_comms.hpp:60``).

    ``dcn_size`` defaults to ``jax.process_count()`` (one slice per
    process); uses ``mesh_utils.create_hybrid_device_mesh`` when the
    runtime exposes slice topology, falling back to a process-major
    reshape (valid because ``jax.devices()`` orders by process).
    """
    n = len(jax.devices())
    dcn = dcn_size or max(1, jax.process_count())
    expects(n % dcn == 0, f"{n} devices not divisible by dcn size {dcn}")
    try:
        from jax.experimental import mesh_utils

        dev_array = mesh_utils.create_hybrid_device_mesh(
            mesh_shape=(1, n // dcn), dcn_mesh_shape=(dcn, 1))
        dev_array = np.asarray(dev_array).reshape(dcn, n // dcn)
    except Exception:
        # the process-major reshape fallback is only topology-safe when the
        # requested dcn grouping matches process boundaries (or everything
        # is one process — CPU simulation); anything else would silently
        # put the "ICI" axis across slices, the exact pathology this
        # function exists to prevent
        expects(jax.process_count() in (1, dcn),
                f"runtime cannot form a hybrid mesh with dcn={dcn} over "
                f"{jax.process_count()} processes; pass dcn_size="
                f"{jax.process_count()} or build the mesh explicitly")
        dev_array = np.asarray(jax.devices()).reshape(dcn, n // dcn)
    return jax.sharding.Mesh(dev_array, (dcn_axis, ici_axis))


def make_1d_mesh(axis_name: str = SHARD_AXIS, devices=None) -> jax.sharding.Mesh:
    devices = jax.devices() if devices is None else list(devices)
    return jax.sharding.Mesh(np.asarray(devices), (axis_name,))


def local_mesh(axis_name: str = SHARD_AXIS) -> jax.sharding.Mesh:
    """SNMG parity (``device_resources_snmg.hpp``): mesh over local devices."""
    return jax.sharding.Mesh(np.asarray(jax.local_devices()), (axis_name,))


def distributed_init(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Multi-host bootstrap — replaces the entire NCCL-uniqueId/Dask-RPC dance
    of ``raft_dask.common.Comms.init()`` (``common/comms.py:161``) with JAX's
    built-in coordinator.  No-op when already initialized or single-process.
    """
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
    except (RuntimeError, ValueError):
        pass  # already initialized or single-process defaults unavailable
