"""Device-mesh construction helpers — the TPU replacement for RAFT's
stream/device plumbing and the SNMG/MNMG handle variants.

Reference parity: ``core/device_resources_snmg.hpp:36`` (single-node multi-GPU
clique) maps to a single-process mesh over the local devices;
``raft_dask.common.Comms`` bootstrap (``common/comms.py:161``) maps to
``jax.distributed.initialize`` + a global mesh.  Axis-name conventions used
throughout the framework:

* ``"data"`` — batch/query-parallel axis (DP; rides DCN when multi-host),
* ``"shard"`` — database/index-shard axis (the MNMG index-shard model of
  §2.9/§5.7 of the survey; rides ICI).
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import numpy as np

from .errors import expects

__all__ = [
    "make_mesh",
    "make_1d_mesh",
    "local_mesh",
    "distributed_init",
    "DATA_AXIS",
    "SHARD_AXIS",
]

DATA_AXIS = "data"
SHARD_AXIS = "shard"


def make_mesh(
    shape: Sequence[int],
    axis_names: Sequence[str],
    devices: Optional[Sequence[jax.Device]] = None,
) -> jax.sharding.Mesh:
    """Build a named mesh of the given logical shape over ``devices``.

    Uses ``jax.experimental.mesh_utils`` device ordering when available so the
    innermost axis maps to ICI neighbors (collectives ride ICI, not DCN).
    """
    if devices is None:
        try:
            from jax.experimental import mesh_utils

            dev_array = mesh_utils.create_device_mesh(tuple(shape))
            return jax.sharding.Mesh(dev_array, tuple(axis_names))
        except Exception:
            devices = jax.devices()
    dev = np.asarray(devices)
    expects(dev.size == int(np.prod(shape)), f"need {int(np.prod(shape))} devices, have {dev.size}")
    return jax.sharding.Mesh(dev.reshape(tuple(shape)), tuple(axis_names))


def make_1d_mesh(axis_name: str = SHARD_AXIS, devices=None) -> jax.sharding.Mesh:
    devices = jax.devices() if devices is None else list(devices)
    return jax.sharding.Mesh(np.asarray(devices), (axis_name,))


def local_mesh(axis_name: str = SHARD_AXIS) -> jax.sharding.Mesh:
    """SNMG parity (``device_resources_snmg.hpp``): mesh over local devices."""
    return jax.sharding.Mesh(np.asarray(jax.local_devices()), (axis_name,))


def distributed_init(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Multi-host bootstrap — replaces the entire NCCL-uniqueId/Dask-RPC dance
    of ``raft_dask.common.Comms.init()`` (``common/comms.py:161``) with JAX's
    built-in coordinator.  No-op when already initialized or single-process.
    """
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
    except (RuntimeError, ValueError):
        pass  # already initialized or single-process defaults unavailable
