"""Host staging memory — the host/pinned memory-resource role of the
reference's MR stack (``mr/host/*`` resources and the pinned container
policies of ``core/host_mdarray.hpp``; accounting counterpart in
:mod:`raft_tpu.core.memory`).

TPU translation: PJRT owns the device allocator *and* the pinned staging
under ``device_put`` — a Python framework cannot (and should not) manage
device pages.  What it can own is the host side of every transfer: the
numpy buffers that disk readers fill and ``device_put`` drains.  Steady-
state streaming (out-of-core builds, ``io.BatchLoader``) re-reads
same-shaped chunks thousands of times; allocating a fresh multi-hundred-MB
array per chunk costs page faults + zeroing and defeats the OS page-cache
warmth that makes the native reader fast.  :class:`HostBufferPool` is the
pinned-pool analog: shape/dtype-keyed reuse of staging buffers with a byte
bound, so the hot loop allocates nothing after the first lap.

Safety contract: a pooled buffer returned by :meth:`HostBufferPool.acquire`
is exclusively the caller's until :meth:`~HostBufferPool.release`; consumers
of APIs that *lend* pooled buffers (``BatchLoader(reuse_buffers=True)``)
must treat each batch as valid only until the next iteration — exactly the
lifetime a double-buffered pinned staging ring gives in the reference.
"""

from __future__ import annotations

import contextlib
from typing import Dict, List, Tuple

import numpy as np

from . import lockdep

__all__ = ["HostBufferPool", "default_host_pool",
           "export_host_pool_metrics"]


class HostBufferPool:
    """Shape/dtype-keyed free-list of host staging buffers.

    ``limit_bytes`` bounds the *idle* bytes held in free lists (buffers out
    on loan are the caller's problem); releases past the bound simply drop
    the buffer.  Thread-safe — readers release from worker threads.

    >>> pool = HostBufferPool()
    >>> a = pool.acquire((4, 3), np.float32)
    >>> pool.release(a)
    >>> b = pool.acquire((4, 3), np.float32)
    >>> b is a  # steady state allocates nothing
    True
    >>> pool.stats()["hits"], pool.stats()["misses"]
    (1, 1)
    """

    def __init__(self, limit_bytes: int = 1 << 31):
        self._lock = lockdep.lock("HostBufferPool._lock")
        self._free: Dict[Tuple[Tuple[int, ...], str], List[np.ndarray]] = {}  # guarded_by: _lock
        self._limit = int(limit_bytes)
        self._held = 0    # guarded_by: _lock
        self._hits = 0    # guarded_by: _lock
        self._misses = 0  # guarded_by: _lock

    @staticmethod
    def _key(shape, dtype):
        return (tuple(int(s) for s in shape), np.dtype(dtype).str)

    def acquire(self, shape, dtype) -> np.ndarray:
        """A C-contiguous buffer of exactly ``(shape, dtype)`` — reused when
        a matching one is free, freshly allocated otherwise.  Contents are
        undefined (the caller fills it)."""
        key = self._key(shape, dtype)
        with self._lock:
            lst = self._free.get(key)
            if lst:
                buf = lst.pop()
                self._held -= buf.nbytes
                self._hits += 1
                return buf
            self._misses += 1
        return np.empty(key[0], dtype=np.dtype(key[1]))

    def release(self, buf: np.ndarray) -> None:
        """Return a buffer to the pool (dropped when over ``limit_bytes`` or
        not a plain C-contiguous array we could hand out again)."""
        if not isinstance(buf, np.ndarray) or not buf.flags.c_contiguous \
                or buf.base is not None:
            return
        key = self._key(buf.shape, buf.dtype)
        with self._lock:
            if self._held + buf.nbytes > self._limit:
                return
            self._free.setdefault(key, []).append(buf)
            self._held += buf.nbytes

    @contextlib.contextmanager
    def borrow(self, shape, dtype):
        """``with pool.borrow((n, d), np.float32) as buf: …`` — scoped
        acquire/release."""
        buf = self.acquire(shape, dtype)
        try:
            yield buf
        finally:
            self.release(buf)

    def trim(self) -> None:
        """Drop every idle buffer (e.g. before a big device allocation)."""
        with self._lock:
            self._free.clear()
            self._held = 0

    def stats(self) -> dict:
        with self._lock:
            return {"hits": self._hits, "misses": self._misses,
                    "held_bytes": self._held,
                    "free_buffers": sum(map(len, self._free.values()))}


def export_host_pool_metrics(pool: HostBufferPool = None,
                             registry=None) -> dict:
    """Land the pool's occupancy/hit-rate in registry gauges —
    ``raft_host_pool_{idle_bytes,hits,misses}`` — and return the stats
    snapshot.  A climbing ``misses`` series after warmup means some hot
    loop is acquiring shapes the pool has never seen (a chunk-shape
    regression); ``idle_bytes`` is the standing host-memory cost of the
    reuse.  Called by the out-of-core search loop after each query batch
    and by ``serve``'s ``metrics_snapshot()``."""
    from ..obs.metrics import registry as _registry

    pool = pool if pool is not None else default_host_pool()
    reg = registry if registry is not None else _registry()
    s = pool.stats()
    reg.gauge("raft_host_pool_idle_bytes",
              "bytes held idle in the host staging buffer pool").set(
                  float(s["held_bytes"]))
    reg.gauge("raft_host_pool_hits",
              "host pool acquires served from the free list").set(
                  float(s["hits"]))
    reg.gauge("raft_host_pool_misses",
              "host pool acquires that allocated fresh buffers").set(
                  float(s["misses"]))
    return s


def default_host_pool(res=None) -> HostBufferPool:
    """The process-default pool, one lazy cell on ``Resources``
    (``resource_types.hpp`` slot parity — see
    :data:`raft_tpu.core.resources.Resources.HOST_POOL`)."""
    from .resources import Resources, _resolve

    return _resolve(res).get_resource(Resources.HOST_POOL)
