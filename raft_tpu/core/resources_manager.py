"""Process-wide per-device handle pool — parity with
``core/device_resources_manager.hpp:75`` (``struct device_resources_manager``:
a lazily-built pool of per-device ``device_resources`` with settings that must
be fixed before first use).

The CUDA knobs (streams per device, pool sizes, memory limits) map to their
TPU analogs: default mesh layout over the local devices, RNG seed policy, and
the handle's workspace byte limit.  Settings changed *after* a handle has been
vended log a warning and are ignored for already-built handles, exactly like
the reference (``device_resources_manager.hpp`` "should be called before the
first get_device_resources").
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import jax

from . import lockdep
from .resources import DeviceResources, Resources

__all__ = ["DeviceResourcesManager", "get_device_resources"]


class DeviceResourcesManager:
    """Singleton pool: one ``DeviceResources`` per local device (plus one
    all-device handle), built lazily, shared across threads."""

    def __init__(self) -> None:
        self._lock = lockdep.lock("DeviceResourcesManager._lock")
        self._handles: Dict[Optional[int], DeviceResources] = {}  # guarded_by: _lock
        self._seed = 0
        self._workspace_limit: Optional[int] = None
        self._mesh_axes: Tuple[str, ...] = ("data",)
        self._touched = False

    # -- pre-use configuration (setter-before-first-get contract) ----------
    def set_seed(self, seed: int) -> None:
        self._warn_if_touched("set_seed")
        self._seed = int(seed)

    def set_workspace_limit(self, nbytes: Optional[int]) -> None:
        self._warn_if_touched("set_workspace_limit")
        self._workspace_limit = nbytes

    def set_mesh_axes(self, axes: Tuple[str, ...]) -> None:
        self._warn_if_touched("set_mesh_axes")
        self._mesh_axes = tuple(axes)

    def _warn_if_touched(self, what: str) -> None:
        if self._touched:
            from .logging import default_logger

            default_logger().warning(
                "%s called after get_device_resources; existing handles keep "
                "their old settings (device_resources_manager.hpp contract)",
                what,
            )

    # -- handle vending ----------------------------------------------------
    def get_device_resources(self, device_index: Optional[int] = None) -> DeviceResources:
        """The pooled handle for one local device (or the all-device handle
        when ``device_index`` is None)."""
        with self._lock:
            self._touched = True
            h = self._handles.get(device_index)
            if h is None:
                h = self._build(device_index)
                self._handles[device_index] = h
            return h

    def _build(self, device_index: Optional[int]) -> DeviceResources:
        import numpy as np

        if device_index is None:
            devices = np.asarray(jax.local_devices())
            seed = self._seed
        else:
            devices = np.asarray([jax.local_devices()[device_index]])
            seed = self._seed + 1 + device_index  # distinct streams per device
        if len(self._mesh_axes) == 1:
            mesh = jax.sharding.Mesh(devices, self._mesh_axes)
        else:  # trailing axis absorbs the device count
            shape = (1,) * (len(self._mesh_axes) - 1) + (len(devices),)
            mesh = jax.sharding.Mesh(devices.reshape(shape), self._mesh_axes)
        h = DeviceResources(mesh=mesh, seed=seed)
        h.set_resource(Resources.WORKSPACE_LIMIT, self._workspace_limit)
        return h

    def reset(self) -> None:
        """Drop all pooled handles (test hook; not in the reference API)."""
        with self._lock:
            self._handles.clear()
            self._touched = False


_manager = DeviceResourcesManager()


def get_device_resources(device_index: Optional[int] = None) -> DeviceResources:
    """Module-level accessor mirroring
    ``device_resources_manager::get_device_resources()``."""
    return _manager.get_device_resources(device_index)
