"""raft_tpu.core — runtime layer (handle/resources, mesh, errors, tracing, IO).

TPU-native analog of ``cpp/include/raft/core`` (see SURVEY.md §2.1).
"""

from .errors import RaftError, LogicError, expects, fail
from .resources import (
    Resources,
    DeviceResources,
    default_resources,
    set_default_resources,
    get_mesh,
    get_devices,
    get_rng_key,
    get_comms,
    set_comms,
    get_workspace_limit,
    get_host_pool,
)
from .host_memory import HostBufferPool, default_host_pool
from .mesh import (make_mesh, make_1d_mesh, make_hybrid_mesh, local_mesh,
                   distributed_init, DATA_AXIS, SHARD_AXIS)
from .array import wrap_array, check_rank, check_same_shape, check_dtype, to_numpy
from .copy import copy
from .bitset import Bitset, Bitmap, popc
from .buffer import MDBuffer, memory_type, memory_type_dispatcher
from .memory import MemoryTracker, analyze_memory, device_memory_stats, live_bytes
from .resources_manager import DeviceResourcesManager, get_device_resources
from .serialize import (
    serialize_mdspan,
    deserialize_mdspan,
    serialize_scalar,
    deserialize_scalar,
    save_arrays,
    load_arrays,
)
from .trace_guard import TraceGuard, SteadyStateError
from .double_buffer import device_prefetch
from . import interruptible, tracing, logging

__all__ = [
    "RaftError", "LogicError", "expects", "fail",
    "Resources", "DeviceResources", "default_resources", "set_default_resources",
    "get_mesh", "get_devices", "get_rng_key", "get_comms", "set_comms", "get_workspace_limit",
    "get_host_pool", "HostBufferPool", "default_host_pool",
    "make_mesh", "make_1d_mesh", "make_hybrid_mesh", "local_mesh",
    "distributed_init", "DATA_AXIS", "SHARD_AXIS",
    "wrap_array", "check_rank", "check_same_shape", "check_dtype", "to_numpy",
    "copy",
    "Bitset", "Bitmap", "popc",
    "MDBuffer", "memory_type", "memory_type_dispatcher",
    "MemoryTracker", "analyze_memory", "device_memory_stats", "live_bytes",
    "DeviceResourcesManager", "get_device_resources",
    "serialize_mdspan", "deserialize_mdspan", "serialize_scalar", "deserialize_scalar",
    "save_arrays", "load_arrays",
    "TraceGuard", "SteadyStateError",
    "device_prefetch",
    "interruptible", "tracing", "logging",
]
