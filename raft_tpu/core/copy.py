"""Layout- and memory-space-aware N-D copy — parity with ``raft::copy``
(``cpp/include/raft/core/copy.hpp``, kernels ``core/detail/copy.hpp``): one
entry point that moves a logical array between memory spaces (host↔device)
and storage layouts (row-major "C" / column-major "F"), converting dtype on
the way, copying only when something actually changes.

TPU mapping of the reference's axes of variation:

* **memory space** — ``"host"`` (NumPy) vs ``"device"`` (committed
  ``jax.Array``), same split as :mod:`raft_tpu.core.buffer`.
* **layout** — observable only on the host side: XLA owns device layout
  (row-major logical indexing, physical tiling chosen by the compiler), so
  a device-resident array has no user-visible F-order.  ``copy`` therefore
  honors ``layout=`` for host outputs (``np.ascontiguousarray`` /
  ``np.asfortranarray`` — the layout-transposing copy of
  ``core/detail/copy.hpp``) and *ingests* F-order host arrays correctly on
  the way to device (logical values preserved; XLA re-lays them out).
* **dtype** — converted in the same pass when requested.

>>> import numpy as np
>>> f = np.asfortranarray(np.arange(6, dtype=np.float32).reshape(2, 3))
>>> d = copy(f, memory="device")              # F-host → device, values kept
>>> bool((np.asarray(d) == f).all())
True
>>> h = copy(d, memory="host", layout="F")    # device → F-order host
>>> h.flags.f_contiguous
True
"""

from __future__ import annotations

from typing import Optional

import jax
import numpy as np

from .buffer import memory_type
from .errors import expects

__all__ = ["copy"]


def copy(src, *, memory: Optional[str] = None, layout: Optional[str] = None,
         dtype=None):
    """Copy ``src`` into the requested memory space / layout / dtype.

    Parameters mirror the degrees of freedom of ``raft::copy``
    (``core/copy.hpp``): any of ``memory`` (``"host"``/``"device"``),
    ``layout`` (``"C"``/``"F"``; host outputs only — device layout is
    XLA-managed and ``"F"`` there is rejected), and ``dtype`` may be given;
    omitted ones keep the source's property.  Returns ``np.ndarray`` for
    host results, ``jax.Array`` for device results.  When nothing changes,
    the source is returned as-is (the reference's no-copy fast path).
    """
    expects(memory in (None, "host", "device"), f"unknown memory {memory!r}")
    expects(layout in (None, "C", "F"), f"unknown layout {layout!r}")
    src_mem = memory_type(src)
    memory = memory or src_mem

    if memory == "device":
        expects(layout in (None, "C"),
                "device arrays are always row-major under XLA; copy to "
                "memory='host' for an F-order view")
        # np.asarray on the host side normalizes any stride pattern
        # (F-order, sliced, broadcast) before the transfer
        arr = src if src_mem == "device" else np.asarray(src)
        if dtype is not None and np.dtype(jax.numpy.result_type(arr)) != np.dtype(dtype):
            return jax.numpy.asarray(arr, dtype=dtype)
        if src_mem == "device":
            return src
        return jax.numpy.asarray(arr)

    # host output: device sources fetch once, then layout/dtype in numpy
    arr = np.asarray(src)
    if dtype is not None:
        arr = arr.astype(dtype, copy=False)
    if layout == "F":
        return np.asfortranarray(arr)
    if layout == "C":
        return np.ascontiguousarray(arr)
    return arr
