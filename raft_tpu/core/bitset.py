"""Bitset / bitmap — parity with ``cpp/include/raft/core/bitset.hpp:33,279`` and
``core/bitmap.hpp:34``.

RAFT's device bitset packs bits into 32-bit words and offers test / set / flip /
count plus conversion helpers (``util/popc.cuh`` for counting).  The TPU version
is a functional pytree: ops return new bitsets (XLA turns the copies into
in-place updates under donation).  A bitmap is the 2-D (rows × cols) view used
for sample filtering in ANN search.
"""

from __future__ import annotations


import jax
import jax.numpy as jnp

from .errors import expects

__all__ = ["Bitset", "Bitmap", "popc"]

_WORD_BITS = 32


def _n_words(n_bits: int) -> int:
    return (n_bits + _WORD_BITS - 1) // _WORD_BITS


def popc(words: jax.Array) -> jax.Array:
    """Population count over a word array (``util/popc.cuh`` parity)."""
    return jnp.sum(jax.lax.population_count(words.astype(jnp.uint32)), dtype=jnp.int64
                   if jax.config.jax_enable_x64 else jnp.int32)


@jax.tree_util.register_pytree_node_class
class Bitset:
    """Packed device bitset (``raft::core::bitset``, ``core/bitset.hpp:279``)."""

    def __init__(self, words: jax.Array, n_bits: int):
        self.words = words
        self.n_bits = n_bits

    def _with_words(self, words: jax.Array) -> "Bitset":
        """Rebuild preserving the concrete type (Bitmap keeps rows/cols)."""
        leaves, treedef = jax.tree_util.tree_flatten(self)
        del leaves
        return jax.tree_util.tree_unflatten(treedef, (words,))

    # -- construction ------------------------------------------------------
    @classmethod
    def create(cls, n_bits: int, default_value: bool = True) -> "Bitset":
        fill = jnp.uint32(0xFFFFFFFF) if default_value else jnp.uint32(0)
        words = jnp.full((_n_words(n_bits),), fill, dtype=jnp.uint32)
        return cls(words, n_bits)._mask_tail()

    @classmethod
    def from_bool_array(cls, mask) -> "Bitset":
        mask = jnp.asarray(mask, dtype=bool).reshape(-1)
        n = mask.shape[0]
        pad = _n_words(n) * _WORD_BITS - n
        bits = jnp.concatenate([mask, jnp.zeros((pad,), bool)]).reshape(-1, _WORD_BITS)
        weights = (jnp.uint32(1) << jnp.arange(_WORD_BITS, dtype=jnp.uint32))
        words = jnp.sum(jnp.where(bits, weights[None, :], jnp.uint32(0)), axis=1, dtype=jnp.uint32)
        return cls(words, n)

    def _mask_tail(self) -> "Bitset":
        tail = self.n_bits % _WORD_BITS
        if tail == 0:
            return self
        mask = jnp.uint32((1 << tail) - 1)
        return self._with_words(self.words.at[-1].set(self.words[-1] & mask))

    # -- queries -----------------------------------------------------------
    def test(self, idx) -> jax.Array:
        """Test bit(s) at ``idx`` (scalar or array) → bool array."""
        idx = jnp.asarray(idx)
        word = self.words[idx // _WORD_BITS]
        return ((word >> (idx % _WORD_BITS).astype(jnp.uint32)) & 1).astype(bool)

    def count(self) -> jax.Array:
        """Number of set bits (``bitset::count``; uses popc)."""
        return popc(self.words)

    def to_bool_array(self) -> jax.Array:
        shifts = jnp.arange(_WORD_BITS, dtype=jnp.uint32)
        bits = ((self.words[:, None] >> shifts[None, :]) & 1).astype(bool)
        return bits.reshape(-1)[: self.n_bits]

    # -- mutation (functional) --------------------------------------------
    def set(self, idx, value: bool = True) -> "Bitset":
        # Build a per-word OR mask first: several indices can land in the same
        # word, so a plain scatter-set would drop all but one (the CUDA version
        # uses atomicOr; the XLA version uses add-scatter over deduplicated bits).
        idx = jnp.asarray(idx).reshape(-1)
        order = jnp.argsort(idx)
        sidx = idx[order]
        first = jnp.concatenate([jnp.ones((1,), bool), sidx[1:] != sidx[:-1]])
        bit = jnp.where(first, jnp.uint32(1) << (sidx % _WORD_BITS).astype(jnp.uint32), jnp.uint32(0))
        mask = jnp.zeros_like(self.words).at[sidx // _WORD_BITS].add(bit)
        return self._with_words((self.words | mask) if value else (self.words & ~mask))

    def flip(self) -> "Bitset":
        return self._with_words(~self.words)._mask_tail()

    def reset(self, default_value: bool = True) -> "Bitset":
        fill = jnp.uint32(0xFFFFFFFF) if default_value else jnp.uint32(0)
        return self._with_words(jnp.full_like(self.words, fill))._mask_tail()

    def resize(self, new_n_bits: int, default_value: bool = True) -> "Bitset":
        """Grow (new bits take ``default_value``) or truncate — the
        ``bitset::resize`` role (``core/bitset.hpp:357``)."""
        expects(new_n_bits >= 0, "new_n_bits must be >= 0")
        nw_new = _n_words(new_n_bits)
        nw_old = self.words.shape[0]
        # branch on BITS, not words: growth within the same tail word
        # (33→40) still creates new bits that must take the default
        if new_n_bits <= self.n_bits:
            out = Bitset(self.words[:nw_new], new_n_bits)
            return out._mask_tail()
        fill = jnp.uint32(0xFFFFFFFF) if default_value else jnp.uint32(0)
        grown = (self.words if nw_new == nw_old else jnp.concatenate(
            [self.words, jnp.full((nw_new - nw_old,), fill, jnp.uint32)]))
        if default_value and self.n_bits % _WORD_BITS:
            # the old tail word's masked-off bits become REAL bits now —
            # they must take the default, not stay zero
            tail = self.n_bits // _WORD_BITS
            high = jnp.uint32(0xFFFFFFFF) << jnp.uint32(
                self.n_bits % _WORD_BITS)
            grown = grown.at[tail].set(grown[tail] | high)
        return Bitset(grown, new_n_bits)._mask_tail()

    def any(self) -> jax.Array:
        """True if at least one bit is set (``bitset::any`` role)."""
        return self.count() > 0

    def all(self) -> jax.Array:
        """True if every bit is set."""
        return self.count() == self.n_bits

    def none(self) -> jax.Array:
        """True if no bit is set."""
        return self.count() == 0

    def __and__(self, other: "Bitset") -> "Bitset":
        expects(self.n_bits == other.n_bits, "bitset size mismatch")
        return self._with_words(self.words & other.words)

    def __or__(self, other: "Bitset") -> "Bitset":
        expects(self.n_bits == other.n_bits, "bitset size mismatch")
        return self._with_words(self.words | other.words)

    # -- pytree ------------------------------------------------------------
    def tree_flatten(self):
        return (self.words,), self.n_bits

    @classmethod
    def tree_unflatten(cls, n_bits, children):
        return cls(children[0], n_bits)


@jax.tree_util.register_pytree_node_class
class Bitmap(Bitset):
    """2-D bit view: ``rows × cols`` (``core/bitmap.hpp:34``)."""

    def __init__(self, words: jax.Array, rows: int, cols: int):
        super().__init__(words, rows * cols)
        self.rows = rows
        self.cols = cols

    @classmethod
    def create_2d(cls, rows: int, cols: int, default_value: bool = True) -> "Bitmap":
        base = Bitset.create(rows * cols, default_value)
        return cls(base.words, rows, cols)

    def test2(self, row, col) -> jax.Array:
        return self.test(jnp.asarray(row) * self.cols + jnp.asarray(col))

    def set2(self, row, col, value: bool = True) -> "Bitmap":
        return self.set(jnp.asarray(row) * self.cols + jnp.asarray(col), value)

    def tree_flatten(self):
        return (self.words,), (self.rows, self.cols)

    @classmethod
    def tree_unflatten(cls, aux, children):
        rows, cols = aux
        return cls(children[0], rows, cols)
