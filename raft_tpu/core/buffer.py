"""Memory-type-generic buffers — parity with ``mdbuffer``
(``core/mdbuffer.cuh:391``: view-or-own across memory types, copying only
when needed) and ``util/memory_type_dispatcher.cuh:107`` (run the right
overload for where the data lives).

TPU memory types: ``host`` (NumPy) and ``device`` (committed ``jax.Array``).
The CUDA managed/pinned tiers have no TPU equivalent; like ``mdbuffer``, a
conversion happens at most once and is cached for the buffer's lifetime.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional

import jax
import numpy as np

__all__ = ["memory_type", "MDBuffer", "memory_type_dispatcher"]


def memory_type(x: Any) -> str:
    """``"host"`` for NumPy/buffer-protocol data, ``"device"`` for
    ``jax.Array`` (``core/memory_type.hpp:21`` parity)."""
    return "device" if isinstance(x, jax.Array) else "host"


class MDBuffer:
    """Hold one logical array; serve it in whichever memory type a consumer
    asks for, converting lazily and at most once (``mdbuffer.cuh:391``).

    >>> buf = MDBuffer(np.arange(4, dtype=np.float32))
    >>> buf.memory_type
    'host'
    >>> dev = buf.device()        # copies host→device once
    >>> buf.device() is dev       # second ask: cached, no copy
    True
    >>> host = buf.host()         # original view — never copied
    >>> host.dtype.name
    'float32'
    """

    def __init__(self, array: Any, *, sharding: Optional[jax.sharding.Sharding] = None):
        self._origin = memory_type(array)
        self._views: Dict[str, Any] = {self._origin: array}
        self._sharding = sharding

    @property
    def memory_type(self) -> str:
        """Where the buffer's *original* data lives."""
        return self._origin

    def host(self) -> np.ndarray:
        """Host view (device→host copy on first ask only)."""
        if "host" not in self._views:
            self._views["host"] = np.asarray(self._views["device"])
        v = self._views["host"]
        return v if isinstance(v, np.ndarray) else np.asarray(v)

    def device(self) -> jax.Array:
        """Device view (host→device transfer on first ask only); honors the
        sharding given at construction."""
        if "device" not in self._views:
            src = self._views["host"]
            self._views["device"] = (
                jax.device_put(src, self._sharding) if self._sharding is not None
                else jax.device_put(src)
            )
        return self._views["device"]

    def view(self, mt: str) -> Any:
        """Generic access — the ``mdbuffer`` visitor surface."""
        if mt == "host":
            return self.host()
        if mt == "device":
            return self.device()
        raise ValueError(f"unknown memory type {mt!r}")


def memory_type_dispatcher(
    host_fn: Callable[[Any], Any],
    device_fn: Callable[[Any], Any],
    x: Any,
    *,
    prefer: Optional[str] = None,
) -> Any:
    """Run the overload matching where ``x`` lives
    (``util/memory_type_dispatcher.cuh:107``): no copy when an overload
    exists for the data's current type; ``prefer`` forces a conversion
    first (the dispatcher's mdbuffer-conversion path)."""
    buf = x if isinstance(x, MDBuffer) else MDBuffer(x)
    mt = prefer or buf.memory_type
    return host_fn(buf.host()) if mt == "host" else device_fn(buf.device())
