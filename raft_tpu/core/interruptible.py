"""Cooperative cancellation — parity with ``cpp/include/raft/core/interruptible.hpp:64``.

RAFT lets long-running host loops be cancelled at stream-sync points
(``interruptible::synchronize`` / ``yield`` / ``cancel``).  The TPU analog:
driver loops (kmeans iterations, index build batches, Lanczos restarts) call
:func:`yield_now` between device dispatches; another thread (or a SIGINT
handler installed via :func:`install_sigint_handler`) flags cancellation, and
the loop raises :class:`InterruptedException` at the next check.
"""

from __future__ import annotations

import signal
import threading

__all__ = [
    "InterruptedException",
    "cancel",
    "clear",
    "yield_now",
    "synchronize",
    "install_sigint_handler",
]


class InterruptedException(RuntimeError):
    """Raised at a yield point after :func:`cancel` (``raft::interrupted_exception``)."""


_state = threading.local()
_global_cancel = threading.Event()


def cancel(thread: threading.Thread = None) -> None:
    """Request cancellation (``interruptible::cancel``). Global: flags every
    yield point in the process (per-thread token granularity is not needed on
    a single dispatch thread)."""
    _global_cancel.set()


def clear() -> None:
    _global_cancel.clear()


def yield_now() -> None:
    """Throw if cancelled (``interruptible::yield``)."""
    if _global_cancel.is_set():
        _global_cancel.clear()
        raise InterruptedException("raft_tpu computation cancelled")


def synchronize(x=None):
    """Cancellable device sync (``interruptible::synchronize``): check, block
    on ``x`` (or a trivial transfer), check again."""
    import jax

    yield_now()
    if x is None:
        x = jax.device_put(0)
    out = jax.block_until_ready(x)
    yield_now()
    return out


def install_sigint_handler() -> None:
    """Route SIGINT to :func:`cancel` (parity with pylibraft's
    ``common/interruptible.pyx`` SIGINT→cancel bridge)."""
    prev = signal.getsignal(signal.SIGINT)
    # Chain only to user-installed handlers: chaining to the default handler
    # would re-raise KeyboardInterrupt immediately, defeating the whole point
    # of deferring cancellation to the next yield point.
    chain = callable(prev) and prev is not signal.default_int_handler

    def handler(signum, frame):
        cancel()
        if chain:
            prev(signum, frame)

    signal.signal(signal.SIGINT, handler)
