"""Error hierarchy + check macros — parity with ``cpp/include/raft/core/error.hpp``.

RAFT exposes ``raft::exception`` / ``raft::logic_error`` plus the ``RAFT_EXPECTS``
and ``RAFT_FAIL`` macros; we keep the same verbs as plain functions.  The CUDA /
cublas / cusolver status macros have no TPU analog — XLA raises Python
exceptions directly.
"""

from __future__ import annotations

__all__ = ["RaftError", "LogicError", "expects", "fail"]


class RaftError(RuntimeError):
    """Base exception (``raft::exception``, ``core/error.hpp``)."""


class LogicError(RaftError):
    """Invalid API usage (``raft::logic_error``)."""


def expects(condition: bool, message: str = "condition violated") -> None:
    """``RAFT_EXPECTS`` parity: raise :class:`LogicError` unless ``condition``."""
    if not condition:
        raise LogicError(message)


def fail(message: str) -> None:
    """``RAFT_FAIL`` parity: unconditional raise."""
    raise LogicError(message)
