"""Built-in comms verification — ``comms/comms_test.hpp:23-155`` parity.

The reference ships self-test kernels inside the comms layer itself
(``test_collective_allreduce`` … ``test_pointToPoint_device_multicast_sendrecv``,
``test_commsplit``), which Python merely orchestrates
(``common/comms_utils.pyx:68+``, ``raft-dask/tests/test_comms.py:62-110``).
Same discipline here: each function takes a :class:`Comms`, runs a known
pattern through the real collective path, and returns ``bool``.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from .comms import Comms, Op

__all__ = [
    "test_collective_allreduce",
    "test_collective_broadcast",
    "test_collective_reduce",
    "test_collective_allgather",
    "test_collective_allgatherv",
    "test_collective_gather",
    "test_collective_gatherv",
    "test_collective_reducescatter",
    "test_pointToPoint_device_send_or_recv",
    "test_pointToPoint_device_sendrecv",
    "test_pointToPoint_device_multicast_sendrecv",
    "test_commsplit",
    "run_all",
]


def _ranks(comms: Comms):
    n = comms.get_size()
    return n, jnp.arange(n, dtype=jnp.float32)


def test_collective_allreduce(comms: Comms) -> bool:
    """Each rank contributes 1; result must equal size (comms_test.hpp:23)."""
    n = comms.get_size()
    out = comms.allreduce(jnp.ones((n, 1), jnp.float32), Op.SUM)
    return bool(np.all(np.asarray(out) == n))


def test_collective_broadcast(comms: Comms) -> bool:
    n = comms.get_size()
    vals = jnp.where(jnp.arange(n) == 0, 42.0, -1.0).astype(jnp.float32)[:, None]
    out = comms.bcast(vals, root=0)
    return bool(np.all(np.asarray(out) == 42.0))


def test_collective_reduce(comms: Comms) -> bool:
    n, r = _ranks(comms)
    out = np.asarray(comms.reduce(r[:, None], Op.SUM, root=0))
    want_root = n * (n - 1) / 2
    return bool(out[0, 0] == want_root and np.all(out[1:] == 0))


def test_collective_allgather(comms: Comms) -> bool:
    n, r = _ranks(comms)
    out = np.asarray(comms.allgather(r[:, None]))  # [n, n]
    return bool(np.all(out == np.arange(n)[None, :]))


def test_collective_allgatherv(comms: Comms) -> bool:
    n = comms.get_size()
    counts = [(r % 2) + 1 for r in range(n)]
    pad = max(counts)
    buf = np.zeros((n, pad), np.float32)
    want = []
    for r in range(n):
        for i in range(counts[r]):
            buf[r, i] = 10 * r + i
            want.append(10 * r + i)
    out = np.asarray(comms.allgatherv(jnp.asarray(buf), counts))
    return bool(out.shape[1] == len(want) and np.all(out == np.asarray(want)[None, :]))


def test_collective_gather(comms: Comms) -> bool:
    n, r = _ranks(comms)
    out = np.asarray(comms.gather(r[:, None], root=0))
    return bool(np.all(out[0] == np.arange(n)) and np.all(out[1:] == 0))


def test_collective_gatherv(comms: Comms) -> bool:
    n = comms.get_size()
    counts = [(r % 3) + 1 for r in range(n)]
    pad = max(counts)
    buf = np.zeros((n, pad), np.float32)
    want = []
    for r in range(n):
        for i in range(counts[r]):
            buf[r, i] = 100 * r + i
            want.append(100 * r + i)
    out = np.asarray(comms.gatherv(jnp.asarray(buf), counts, root=0))
    return bool(np.all(out[0] == np.asarray(want)) and np.all(out[1:] == 0))


def test_collective_reducescatter(comms: Comms) -> bool:
    n = comms.get_size()
    data = jnp.ones((n, n), jnp.float32)  # each rank sends ones[n]
    out = np.asarray(comms.reducescatter(data, Op.SUM))  # each rank gets [1]
    return bool(np.all(out == n))


def test_pointToPoint_device_send_or_recv(comms: Comms) -> bool:
    """Ring shift by 1 — device_send/device_recv parity (comms_test.hpp)."""
    n, r = _ranks(comms)
    out = np.asarray(comms.ring_shift(r[:, None], 1))
    want = (np.arange(n) - 1) % n  # rank r receives from r-1
    return bool(np.all(out[:, 0] == want))


def test_pointToPoint_device_sendrecv(comms: Comms) -> bool:
    n, r = _ranks(comms)
    perm = [(s, (s + 2) % n) for s in range(n)]
    out = np.asarray(comms.sendrecv(r[:, None], perm))
    want = (np.arange(n) - 2) % n
    return bool(np.all(out[:, 0] == want))


def test_pointToPoint_device_multicast_sendrecv(comms: Comms) -> bool:
    n, r = _ranks(comms)
    # Every rank multicasts to both neighbors.
    sends = [[(s + 1) % n, (s - 1) % n] for s in range(n)]
    out = np.asarray(comms.multicast_sendrecv(r[:, None], sends))  # [n, n, 1]
    ok = True
    for dst in range(n):
        for src in ((dst + 1) % n, (dst - 1) % n):
            ok = ok and out[dst, src, 0] == src
    return bool(ok)


def test_commsplit(comms: Comms, n_colors: int = 2) -> bool:
    """Grouped allreduce after split (comms_test.hpp:~140 test_commsplit)."""
    n = comms.get_size()
    if n < n_colors:
        return True
    color = [r % n_colors for r in range(n)]
    split = comms.comm_split(color)
    out = np.asarray(split.allreduce(jnp.ones((n, 1), jnp.float32), Op.SUM))
    want = np.asarray([len(split.group_ranks[r]) for r in range(n)], np.float32)
    return bool(np.all(out[:, 0] == want))


def run_all(comms: Comms) -> dict:
    """Run every self-test; returns {name: bool}."""
    tests = {
        name: fn
        for name, fn in globals().items()
        if name.startswith("test_") and callable(fn)
    }
    return {name: fn(comms) for name, fn in tests.items()}
