"""Distributed bootstrap — the raft-dask ``Comms`` analog.

The reference bootstraps MNMG in five Dask/RPC/NCCL steps
(``raft-dask/raft_dask/common/comms.py:161`` ``init``: worker ranks → NCCL
uniqueId broadcast → per-worker ``ncclCommInitRank`` → optional UCX endpoint
mesh → handle injection, SURVEY.md §3.2).  On TPU the whole stack collapses:
``jax.distributed.initialize`` performs rank/coordination bootstrap, the mesh
*is* the communicator topology, and "injection" is setting the comms slot on a
:class:`~raft_tpu.core.resources.Resources`.
"""

from __future__ import annotations

import math
from typing import Optional, Sequence

import jax

from ..core import resources as res_mod
from ..core.errors import expects
from ..core.mesh import make_mesh
from .comms import Comms

__all__ = ["init_distributed", "inject_comms_on_resources",
           "verify_comms"]


def init_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    *,
    axis_names: Sequence[str] = ("shard",),
    axis_shape: Optional[Sequence[int]] = None,
    res: Optional[res_mod.Resources] = None,
) -> Comms:
    """Bootstrap a (possibly multi-host) communicator and inject it.

    Single-process: uses local devices directly (LocalCUDACluster-style tests,
    ``raft-dask/tests/conftest.py:14-49`` parity).  Multi-process: forwards to
    ``jax.distributed.initialize`` (the ``ncclCommInitRank`` +
    ``create_nccl_uniqueid`` replacement — coordination service instead of a
    Dask RPC'd uniqueId).
    """
    if num_processes is not None and num_processes > 1:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
    devices = jax.devices()
    if axis_shape is None:
        if len(axis_names) != 1:
            raise ValueError("axis_shape required for multi-axis meshes")
        axis_shape = (len(devices),)
    else:
        # a shape that doesn't tile the device set used to slip through
        # and make_mesh silently meshed SOME of jax.devices() — a fleet
        # bootstrapped that way shards an index over a sub-pod while the
        # rest idles (or make_mesh raises an opaque reshape error).
        # Validate here, where the operator's intent (axis_shape) and
        # the runtime reality (visible devices) first meet.
        expects(len(axis_shape) == len(axis_names),
                f"axis_shape {tuple(axis_shape)} has {len(axis_shape)} "
                f"axes but axis_names {tuple(axis_names)} has "
                f"{len(axis_names)}")
        want = math.prod(int(s) for s in axis_shape)
        if want != len(devices):
            raise ValueError(
                f"axis_shape {tuple(axis_shape)} covers {want} devices "
                f"but this process sees {len(devices)} "
                f"({jax.default_backend()} backend) — the mesh must use "
                "every visible device; pass an axis_shape whose product "
                f"is {len(devices)}, or restrict visible devices first")
    mesh = make_mesh(tuple(axis_shape), tuple(axis_names))
    comms = Comms(mesh)
    target = res_mod._resolve(res)
    inject_comms_on_resources(target, comms)
    return comms


def verify_comms(comms: Comms) -> dict:
    """Run the :mod:`.selftest` battery over a bootstrapped communicator
    and raise with the failing verb names if any collective is broken —
    the fleet-startup gate (``FleetServer`` refuses to serve over a mesh
    whose collectives disagree with the single-device reference).
    Returns the full ``{test_name: bool}`` map on success."""
    from . import selftest

    results = selftest.run_all(comms)
    failed = sorted(name for name, ok in results.items() if not ok)
    if failed:
        raise RuntimeError(
            f"comms selftest failed on {comms.mesh.shape} mesh: "
            f"{', '.join(failed)} — refusing to serve over a broken "
            "collective (check device topology / runtime version)")
    return results


def inject_comms_on_resources(res: res_mod.Resources, comms: Comms) -> None:
    """``inject_comms_on_handle`` parity (``common/comms_utils.pyx:248,278``):
    construct-and-set collapses to setting the comms slot; the mesh slot is
    aligned so primitives see a consistent topology."""
    res_mod.set_comms(res, comms)
    res.set_resource("mesh", comms.mesh)
