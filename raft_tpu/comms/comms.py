"""Communicator verbs over XLA collectives.

The reference's ``comms_iface`` (``core/comms.hpp:114-226``) is an imperative,
buffer-oriented verb set bound to NCCL (``comms/detail/std_comms.hpp:54``).  On
TPU the native shape is different: collectives are *traced ops* that XLA lowers
onto ICI/DCN links, and the "communicator" is a mesh axis.  This module keeps
the reference's verb *names and semantics* but exposes them in two forms:

1. **Traced verbs** — free functions taking ``axis`` — callable inside a
   ``shard_map``-decorated program.  This is the production path: XLA sees the
   collective and schedules/overlaps it (the NCCL-launch role of
   ``std_comms.hpp`` ``allreduce``→``ncclAllReduce`` collapses into tracing).
2. **Eager verbs** — methods on :class:`Comms` — run a one-off ``shard_map``
   over per-rank data stacked on a leading axis.  These serve tests and
   host-driven orchestration, mirroring how the reference's verbs are invoked
   from host code on device buffers.

Rank/size live on the mesh: ``lax.axis_index(axis)`` inside a traced program
(the ``get_rank()`` of ``core/comms.hpp:131``), ``mesh.shape[axis]`` outside.

Variable-count verbs (``allgatherv``/``gatherv``, ``core/comms.hpp:165-186``)
take *static* per-rank counts — XLA requires static shapes, so ragged inputs
are carried padded to the max count and the counts list compiles into the
gather/concat plan (same information the reference passes as ``recvcounts`` /
``displs`` arrays).

``comm_split`` (``core/comms.hpp:122``) is provided on :class:`Comms` for
meshes whose axis factors into sub-axes, plus a mask-based grouped-collective
fallback for arbitrary static colors.
"""

from __future__ import annotations

import enum
from typing import Callable, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ..core.compat import axis_size, shard_map
from ..core.errors import expects

__all__ = [
    "Op",
    "Comms",
    "build_comms",
    "allreduce",
    "reduce",
    "bcast",
    "allgather",
    "allgatherv",
    "gather",
    "gatherv",
    "reducescatter",
    "alltoall",
    "sendrecv",
    "ring_shift",
    "multicast_sendrecv",
    "barrier",
]


class Op(enum.Enum):
    """Reduction op — ``op_t`` parity (``core/comms.hpp:70-77``)."""

    SUM = "sum"
    PROD = "prod"
    MIN = "min"
    MAX = "max"


# ---------------------------------------------------------------------------
# Traced verbs: call inside shard_map over `axis`.
# ---------------------------------------------------------------------------


def _axis_reduce(x, op: Op, axis: str):
    if op == Op.SUM:
        return lax.psum(x, axis)
    if op == Op.MAX:
        return lax.pmax(x, axis)
    if op == Op.MIN:
        return lax.pmin(x, axis)
    # No native pprod: gather and fold. XLA still keeps this on ICI.
    gathered = lax.all_gather(x, axis)
    return jnp.prod(gathered, axis=0)


def allreduce(x, op: Op = Op.SUM, *, axis: str):
    """Elementwise reduction across ranks, result on all ranks.

    ``comms_iface::allreduce`` (``core/comms.hpp:134``) → ``lax.psum`` family.
    """
    return _axis_reduce(x, op, axis)


def reduce(x, op: Op = Op.SUM, root: int = 0, *, axis: str):
    """Reduction delivered to ``root``; other ranks get zeros.

    ``comms_iface::reduce`` (``core/comms.hpp:151``).  NCCL leaves non-root
    buffers undefined; we define them as zeros for determinism.
    """
    full = _axis_reduce(x, op, axis)
    rank = lax.axis_index(axis)
    return jnp.where(rank == root, full, jnp.zeros_like(full))


def bcast(x, root: int = 0, *, axis: str):
    """Broadcast ``root``'s value to all ranks.

    ``comms_iface::bcast`` (``core/comms.hpp:141``).  One-hot mask + psum —
    a single ICI collective, no host round-trip.
    """
    rank = lax.axis_index(axis)
    mask = (rank == root).astype(x.dtype)
    return lax.psum(x * mask, axis)


def allgather(x, *, axis: str, tiled: bool = True):
    """Concatenate each rank's buffer along dim 0, result on all ranks.

    ``comms_iface::allgather`` (``core/comms.hpp:159``).  ``tiled=True``
    matches NCCL's flat concatenation; ``tiled=False`` stacks a new leading
    rank dimension.
    """
    return lax.all_gather(x, axis, tiled=tiled)


def allgatherv(x_padded, counts: Sequence[int], *, axis: str):
    """Variable-count allgather (``core/comms.hpp:165``).

    Each rank contributes ``counts[rank]`` rows carried in a buffer padded to
    ``max(counts)``.  Counts are static (XLA static shapes); the result is the
    dense concatenation of the valid prefixes, on every rank.
    """
    counts = [int(c) for c in counts]
    pad = max(counts)
    expects(x_padded.shape[0] == pad, "allgatherv: buffer must be padded to max(counts)")
    stacked = lax.all_gather(x_padded, axis)  # [size, pad, ...]
    pieces = [stacked[r, : counts[r]] for r in range(len(counts))]
    return jnp.concatenate(pieces, axis=0)


def gather(x, root: int = 0, *, axis: str):
    """Gather to root (``core/comms.hpp:172``); non-root ranks get zeros."""
    full = lax.all_gather(x, axis, tiled=True)
    rank = lax.axis_index(axis)
    return jnp.where(rank == root, full, jnp.zeros_like(full))


def gatherv(x_padded, counts: Sequence[int], root: int = 0, *, axis: str):
    """Variable-count gather to root (``core/comms.hpp:179``)."""
    full = allgatherv(x_padded, counts, axis=axis)
    rank = lax.axis_index(axis)
    return jnp.where(rank == root, full, jnp.zeros_like(full))


def reducescatter(x, op: Op = Op.SUM, *, axis: str):
    """Reduce then scatter equal chunks (``core/comms.hpp:188``).

    SUM rides ``lax.psum_scatter`` (a native ICI reduce-scatter); MIN/MAX/PROD
    fold an all_gather then slice — rarely used, correctness over speed.
    """
    if op == Op.SUM:
        return lax.psum_scatter(x, axis, scatter_dimension=0, tiled=True)
    size = lax.psum(1, axis)
    full = _axis_reduce(x, op, axis)
    chunk = x.shape[0] // size
    rank = lax.axis_index(axis)
    return lax.dynamic_slice_in_dim(full, rank * chunk, chunk, axis=0)


def alltoall(x, *, axis: str):
    """Each rank scatters dim-0 chunks to peers and concatenates received ones.

    No direct reference verb — NCCL exposes this via grouped p2p
    (``device_multicast_sendrecv``, ``core/comms.hpp:209``); on TPU it is the
    native ``lax.all_to_all`` and the backbone of sharded top-k exchange.
    """
    return lax.all_to_all(x, axis, split_axis=0, concat_axis=0, tiled=True)


def sendrecv(x, perm: Sequence[Tuple[int, int]], *, axis: str):
    """Point-to-point exchange along static (src, dst) pairs.

    ``comms_iface::device_sendrecv`` (``core/comms.hpp:203``).  XLA requires a
    static communication pattern, so the per-rank ``dest``/``source`` ints of
    the reference become a permutation list; ranks not named as a destination
    receive zeros (NCCL leaves them untouched — zeros keep tracing pure).
    """
    return lax.ppermute(x, axis, perm=list(perm))


def ring_shift(x, offset: int = 1, *, axis: str):
    """Ring ppermute: rank r sends to (r+offset) mod size.

    The building block of ring pipelines (sharded kNN merge, ring attention);
    plays the role of the reference's UCX ring p2p in e.g. cuML's MNMG loops.
    """
    size = _static_axis_size(axis)
    perm = [(r, (r + offset) % size) for r in range(size)]
    return lax.ppermute(x, axis, perm=perm)


def multicast_sendrecv(x, sends: Sequence[Sequence[int]], *, axis: str):
    """One buffer per rank multicast to static destination lists.

    ``comms_iface::device_multicast_sendrecv`` (``core/comms.hpp:209``).
    ``sends[r]`` lists the destination ranks of rank ``r``.  Scheduled as
    ppermute rounds (each destination appears at most once per round) — the
    grouped-NCCL-call analog of ``group_start``/``group_end``
    (``core/comms.hpp:221-223``).  Returns ``[size, ...]`` where row ``s``
    holds the buffer received from rank ``s`` (zeros where nothing was sent).
    """
    size = _static_axis_size(axis)
    expects(len(sends) == size, "multicast_sendrecv: need one dest list per rank")
    # Greedy round scheduling: a round is a partial permutation.
    pending = [(src, dst) for src, dsts in enumerate(sends) for dst in dsts]
    out = jnp.zeros((size,) + x.shape, x.dtype)
    while pending:
        round_pairs: List[Tuple[int, int]] = []
        used_dst, used_src = set(), set()
        rest = []
        for src, dst in pending:
            if dst not in used_dst and src not in used_src:
                round_pairs.append((src, dst))
                used_dst.add(dst)
                used_src.add(src)
            else:
                rest.append((src, dst))
        pending = rest
        received = lax.ppermute(x, axis, perm=round_pairs)
        # Scatter this round's payload into the per-source slot.
        rank = lax.axis_index(axis)
        src_of = np.full((size,), -1, np.int32)
        for src, dst in round_pairs:
            src_of[dst] = src
        my_src = jnp.asarray(src_of)[rank]
        slot = jnp.where(my_src >= 0, my_src, 0)
        update = jnp.where(my_src >= 0, received, out[slot])
        out = out.at[slot].set(update)
    return out


def barrier(*, axis: str):
    """Synchronization point (``core/comms.hpp:124``): a trivial psum.

    Inside a traced program every collective is already a synchronization
    edge; this exists for verb-set parity and host-driven orchestration.
    """
    return lax.psum(jnp.ones((), jnp.int32), axis)


def _static_axis_size(axis: str) -> int:
    try:
        return axis_size(axis)  # available in tracing context
    except Exception:
        raise ValueError(f"axis {axis!r} not bound; call inside shard_map") from None


# ---------------------------------------------------------------------------
# Comms object: mesh-bound communicator, injectable into Resources.
# ---------------------------------------------------------------------------


class Comms:
    """Mesh-axis communicator — ``comms_t`` parity (``core/comms.hpp:234``).

    Wraps a ``Mesh`` + axis name.  ``get_size``/``get_rank`` mirror
    ``core/comms.hpp:128-131`` (rank = this process's first device position on
    the axis; inside traced code use ``lax.axis_index``).  The eager verb
    methods run the traced verbs through a cached ``shard_map`` over per-rank
    data stacked on a leading rank dimension.
    """

    def __init__(self, mesh: Mesh, axis: Optional[str] = None):
        expects(isinstance(mesh, Mesh), "Comms requires a jax.sharding.Mesh")
        self.mesh = mesh
        self.axis = axis if axis is not None else mesh.axis_names[0]
        expects(self.axis in mesh.axis_names, f"axis {self.axis!r} not in mesh")
        # jitted shard_map programs keyed by (verb, static-params,
        # out_replicated, n_args); jax.jit's own cache then handles
        # shape/dtype specialization — so repeated eager verbs re-trace
        # only on new (verb, shape) combinations, not every call.
        self._programs: dict = {}

    # -- introspection ------------------------------------------------------
    def get_size(self) -> int:
        return int(self.mesh.shape[self.axis])

    def get_rank(self) -> int:
        # Host-side rank: position of this process's first addressable device
        # along the axis (multi-host: one controller per process).
        local = set(d.id for d in jax.local_devices())
        axis_idx = self.mesh.axis_names.index(self.axis)
        arr = np.asarray(self.mesh.devices)
        for idx in np.ndindex(arr.shape):
            if arr[idx].id in local:
                return int(idx[axis_idx])
        return 0

    def sync_stream(self) -> None:
        """``comms_iface::sync_stream`` (``core/comms.hpp:126``) — on TPU a
        barrier over async dispatch, not a CUDA stream."""
        jax.effects_barrier()

    # -- eager collectives --------------------------------------------------
    def _run(self, key, fn: Callable, *arrays, out_replicated: bool = False):
        """shard_map `fn` over per-rank-stacked inputs [size, ...].

        ``key`` identifies the verb + its static parameters; the jitted
        shard_map program is built once per key and cached, so calling the
        same verb repeatedly hits jax.jit's dispatch cache instead of
        rebuilding (and re-tracing) a fresh program every call.
        """
        size = self.get_size()
        for a in arrays:
            expects(a.shape[0] == size, f"leading dim must equal comm size {size}")
        cache_key = (key, out_replicated, len(arrays))
        prog = self._programs.get(cache_key)
        if prog is None:
            specs = tuple(P(self.axis) for _ in arrays)
            out_spec = P() if out_replicated else P(self.axis)
            prog = jax.jit(shard_map(
                fn,
                mesh=self.mesh,
                in_specs=specs,
                out_specs=out_spec,
                check_vma=False,
            ))
            self._programs[cache_key] = prog
        return prog(*arrays)

    def allreduce(self, x, op: Op = Op.SUM):
        """Per-rank rows ``x[size, ...]`` → reduced row replicated to all."""
        return self._run(
            ("allreduce", op),
            lambda v: allreduce(v[0], op, axis=self.axis)[None],
            x,
        )

    def reduce(self, x, op: Op = Op.SUM, root: int = 0):
        return self._run(("reduce", op, root),
                         lambda v: reduce(v[0], op, root, axis=self.axis)[None], x)

    def bcast(self, x, root: int = 0):
        return self._run(("bcast", root),
                         lambda v: bcast(v[0], root, axis=self.axis)[None], x)

    def allgather(self, x):
        """x[size, n, ...] → [size, size*n, ...]: flat concat on all ranks
        (NCCL allgather concatenation semantics)."""
        return self._run(("allgather",),
                         lambda v: allgather(v[0], axis=self.axis, tiled=True)[None], x)

    def allgatherv(self, x, counts: Sequence[int]):
        counts = tuple(int(c) for c in counts)
        return self._run(("allgatherv", counts),
                         lambda v: allgatherv(v[0], counts, axis=self.axis)[None], x)

    def gather(self, x, root: int = 0):
        return self._run(("gather", root),
                         lambda v: gather(v[0], root, axis=self.axis)[None], x)

    def gatherv(self, x, counts: Sequence[int], root: int = 0):
        counts = tuple(int(c) for c in counts)
        return self._run(("gatherv", counts, root),
                         lambda v: gatherv(v[0], counts, root, axis=self.axis)[None], x)

    def reducescatter(self, x, op: Op = Op.SUM):
        return self._run(("reducescatter", op),
                         lambda v: reducescatter(v[0], op, axis=self.axis)[None], x)

    def alltoall(self, x):
        return self._run(("alltoall",),
                         lambda v: alltoall(v[0], axis=self.axis)[None], x)

    def sendrecv(self, x, perm: Sequence[Tuple[int, int]]):
        perm = tuple((int(a), int(b)) for a, b in perm)
        return self._run(("sendrecv", perm),
                         lambda v: sendrecv(v[0], perm, axis=self.axis)[None], x)

    def ring_shift(self, x, offset: int = 1):
        return self._run(("ring_shift", offset),
                         lambda v: ring_shift(v[0], offset, axis=self.axis)[None], x)

    def multicast_sendrecv(self, x, sends: Sequence[Sequence[int]]):
        sends = tuple(tuple(int(d) for d in row) for row in sends)
        return self._run(
            ("multicast_sendrecv", sends),
            lambda v: multicast_sendrecv(v[0], sends, axis=self.axis)[None], x
        )

    def barrier(self):
        size = self.get_size()
        self._run(
            ("barrier",),
            lambda v: (barrier(axis=self.axis) * 0 + v[0])[None],
            jnp.zeros((size,), jnp.int32),
        )
        jax.effects_barrier()

    # -- comm_split ---------------------------------------------------------
    def comm_split(self, color: Sequence[int], key: Optional[Sequence[int]] = None) -> "SplitComms":
        """Static-color communicator split (``core/comms.hpp:122``).

        The reference re-bootstraps NCCL from an allgather of colors/keys
        (``comms/detail/std_comms.hpp`` comm_split).  Here colors are static
        host values, and the split communicator implements grouped collectives
        by masking within the parent axis — no re-bootstrap needed.
        """
        size = self.get_size()
        color = [int(c) for c in color]
        expects(len(color) == size, "comm_split: need a color per rank")
        if key is None:
            key = list(range(size))
        return SplitComms(self, color, [int(k) for k in key])


class SplitComms:
    """Grouped collectives inside a parent communicator (comm_split result).

    Membership/order are static: group of rank r = ranks with ``color[r]``,
    ordered by ``key``.  Collectives are parent-axis collectives with one-hot
    group masks — semantically NCCL's comm_split'd communicator
    (``comms/detail/std_comms.hpp`` comm_split → new std_comms).
    """

    def __init__(self, parent: Comms, color: List[int], key: List[int]):
        self.parent = parent
        self.axis = parent.axis
        self.color = color
        self.key = key
        size = parent.get_size()
        # group_ranks[r] = ordered member list of r's group
        self.group_ranks = []
        for r in range(size):
            members = [q for q in range(size) if color[q] == color[r]]
            members.sort(key=lambda q: (key[q], q))
            self.group_ranks.append(members)
        # new_rank[r] = r's rank inside its group
        self.new_rank = [self.group_ranks[r].index(r) for r in range(size)]

    def get_size_of(self, rank: int) -> int:
        return len(self.group_ranks[rank])

    def get_rank_of(self, rank: int) -> int:
        return self.new_rank[rank]

    # Traced grouped verbs -------------------------------------------------
    def _group_mask(self):
        """[size] bools: my group's members (traced; parent-axis context)."""
        size = self.parent.get_size()
        rank = lax.axis_index(self.axis)
        same = np.zeros((size, size), bool)
        for r in range(size):
            for q in self.group_ranks[r]:
                same[r, q] = True
        return jnp.asarray(same)[rank]

    def t_allreduce(self, x, op: Op = Op.SUM):
        """Traced grouped allreduce (call inside shard_map on parent axis)."""
        size = self.parent.get_size()
        gathered = lax.all_gather(x, self.axis)  # [size, ...]
        mask = self._group_mask()
        shaped = mask.reshape((size,) + (1,) * (gathered.ndim - 1))
        if op == Op.SUM:
            return jnp.sum(jnp.where(shaped, gathered, 0), axis=0)
        if op == Op.MAX:
            neg = jnp.finfo(x.dtype).min if jnp.issubdtype(x.dtype, jnp.floating) else jnp.iinfo(x.dtype).min
            return jnp.max(jnp.where(shaped, gathered, neg), axis=0)
        if op == Op.MIN:
            pos = jnp.finfo(x.dtype).max if jnp.issubdtype(x.dtype, jnp.floating) else jnp.iinfo(x.dtype).max
            return jnp.min(jnp.where(shaped, gathered, pos), axis=0)
        return jnp.prod(jnp.where(shaped, gathered, 1), axis=0)

    def _group_root(self, root: int) -> np.ndarray:
        """[size] parent rank of each rank's group ``root`` (group-local,
        key-ordered).  ``root`` is validated against every group's size —
        an out-of-range root is an error, as in MPI/NCCL."""
        size = self.parent.get_size()
        expects(0 <= root < min(len(g) for g in self.group_ranks),
                f"root {root} out of range for the smallest group")
        src = np.zeros((size,), np.int32)
        for r in range(size):
            src[r] = self.group_ranks[r][root]
        return src

    def t_bcast(self, x, root: int = 0):
        """Traced grouped bcast: every rank receives its group's ``root``-th
        member's value (root indexes *within* the group, by key order)."""
        rank = lax.axis_index(self.axis)
        gathered = lax.all_gather(x, self.axis)  # [size, ...]
        return gathered[jnp.asarray(self._group_root(root))[rank]]

    def t_reduce(self, x, op: Op = Op.SUM, root: int = 0):
        """Traced grouped reduce: the group root gets the reduction, other
        ranks get zeros — same non-root contract as the parent-axis
        :func:`reduce` (the reference leaves them undefined)."""
        rank = lax.axis_index(self.axis)
        red = self.t_allreduce(x, op)
        src = jnp.asarray(self._group_root(root))[rank]
        return jnp.where(rank == src, red, jnp.zeros_like(red))

    def t_allgather(self, x):
        """Traced grouped allgather: [max_group_size, ...] per rank, rows
        ordered by group key; groups smaller than the largest repeat their
        last member (defined-prefix contract — read the first
        ``get_size_of(rank)`` rows, like allgatherv)."""
        size = self.parent.get_size()
        rank = lax.axis_index(self.axis)
        gathered = lax.all_gather(x, self.axis)  # [size, ...]
        gmax = max(len(g) for g in self.group_ranks)
        members = np.zeros((size, gmax), np.int32)
        for r in range(size):
            g = self.group_ranks[r]
            members[r] = [g[min(i, len(g) - 1)] for i in range(gmax)]
        return gathered[jnp.asarray(members)[rank]]

    # Eager wrappers (parent-cached programs) ------------------------------
    def _key(self, verb, *extra):
        return ("split_" + verb, tuple(self.color), tuple(self.key)) + extra

    def allreduce(self, x, op: Op = Op.SUM):
        return self.parent._run(
            self._key("allreduce", op),
            lambda v: self.t_allreduce(v[0], op)[None], x)

    def bcast(self, x, root: int = 0):
        return self.parent._run(
            self._key("bcast", root),
            lambda v: self.t_bcast(v[0], root)[None], x)

    def reduce(self, x, op: Op = Op.SUM, root: int = 0):
        return self.parent._run(
            self._key("reduce", op, root),
            lambda v: self.t_reduce(v[0], op, root)[None], x)

    def allgather(self, x):
        return self.parent._run(
            self._key("allgather"),
            lambda v: self.t_allgather(v[0])[None], x)


def build_comms(mesh: Mesh, axis: Optional[str] = None) -> Comms:
    """Factory — ``build_comms_nccl_only`` parity (``comms/std_comms.hpp:60``).

    NCCL/UCX bootstrap collapses to binding a mesh axis; for multi-host use
    :func:`raft_tpu.comms.bootstrap.init_distributed` first.
    """
    return Comms(mesh, axis)
