"""Ring pipelines over ICI — the candidate-exchange analog of ring
attention (SURVEY.md §5.7: "ring-style ppermute/all_to_all pipelines over
ICI for candidate exchange — the moral equivalent of ring attention
applied to top-k merging").

The all_gather merge (``neighbors.brute_force.knn_sharded``) materializes
``S·k`` candidates per query on every shard before one wide select.  The
ring formulation keeps memory constant: each of ``S−1`` steps ppermutes a
``(m, k)`` buffer one hop around the ring and folds it into the running
best via a ``2k``-wide merge — bandwidth-optimal on a torus ring, peak
memory ``O(m·k)`` instead of ``O(m·S·k)``, and each hop's transfer
overlaps the previous hop's merge under XLA's scheduler.

Must be called inside ``shard_map`` over the named axis.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

from ..core.compat import axis_size

__all__ = ["ring_topk_merge"]


def ring_topk_merge(vals: jax.Array, idx: jax.Array, k: int, axis: str,
                    *, select_min: bool = True) -> Tuple[jax.Array, jax.Array]:
    """Global top-k across shards of per-shard ``(m, k)`` candidates.

    Every shard circulates its candidate buffer around the ring; after
    ``S−1`` hops each shard has folded every other shard's candidates into
    its running best, so the result is replicated (exact merges are
    order-independent).  ``vals`` must be min-ordered when ``select_min``
    (negate beforehand otherwise).
    """
    size = axis_size(axis)
    perm = [(j, (j + 1) % size) for j in range(size)]

    def hop(carry, _):
        best_v, best_i, cur_v, cur_i = carry
        cur_v = jax.lax.ppermute(cur_v, axis, perm)
        cur_i = jax.lax.ppermute(cur_i, axis, perm)
        cat_v = jnp.concatenate([best_v, cur_v], axis=1)
        cat_i = jnp.concatenate([best_i, cur_i], axis=1)
        sign = 1.0 if select_min else -1.0
        neg, pos = jax.lax.top_k(-sign * cat_v, k)
        return (sign * -neg, jnp.take_along_axis(cat_i, pos, axis=1),
                cur_v, cur_i), None

    (best_v, best_i, _, _), _ = jax.lax.scan(
        hop, (vals, idx, vals, idx), None, length=size - 1)
    return best_v, best_i
