"""Distributed communication layer — TPU-native analog of ``raft/comms``.

Reference parity map (SURVEY.md §2.9):

* ``core/comms.hpp:114`` ``comms_iface`` verb set  → :mod:`raft_tpu.comms.comms`
  (traced verbs over ``jax.lax`` collectives inside ``shard_map``).
* ``comms/std_comms.hpp:60,108`` NCCL/UCX factories → :func:`build_comms` /
  :func:`raft_tpu.comms.bootstrap.init_distributed` (bootstrap collapses to
  ``jax.distributed.initialize`` + mesh construction).
* ``comms/comms_test.hpp:23-155`` self-test kernels  → :mod:`raft_tpu.comms.selftest`.
* ``core/resource/comms.hpp`` handle injection       → ``resources.set_comms``.
"""

from .comms import (
    Comms,
    Op,
    build_comms,
    allreduce,
    reduce,
    bcast,
    allgather,
    allgatherv,
    gather,
    gatherv,
    reducescatter,
    alltoall,
    sendrecv,
    ring_shift,
    multicast_sendrecv,
    barrier,
)
from .bootstrap import (init_distributed, inject_comms_on_resources,
                        verify_comms)
from .ring import ring_topk_merge
from . import selftest

__all__ = [
    "Comms",
    "Op",
    "build_comms",
    "allreduce",
    "reduce",
    "bcast",
    "allgather",
    "allgatherv",
    "gather",
    "gatherv",
    "reducescatter",
    "alltoall",
    "sendrecv",
    "ring_shift",
    "ring_topk_merge",
    "multicast_sendrecv",
    "barrier",
    "init_distributed",
    "inject_comms_on_resources",
    "verify_comms",
    "selftest",
]
