"""raft_tpu.cluster — kmeans / kmeans_balanced (north-star config #3).

The reference's kmeans migrated to cuVS; capability is rebuilt TPU-first:
assignment is the fused L2 argmin (MXU gemm, ``distance.fused_l2_nn``),
centroid update is a segment-sum (scatter-add), and everything is a
``lax.scan``/``while_loop`` over static shapes so the whole fit jit-compiles
to one XLA program.  Sharded fit = per-shard partial sums + ``psum`` over the
mesh axis (the MNMG kmeans pattern of SURVEY.md §2.9 item 4).
"""

from .kmeans import (
    capped_assign,
    KMeansParams,
    kmeans_fit,
    kmeans_predict,
    kmeans_fit_predict,
    kmeans_transform,
    kmeans_balanced_fit,
    kmeans_balanced_predict,
    kmeans_balanced_fit_predict,
    kmeans_plus_plus_init,
)

__all__ = [
    "capped_assign",
    "KMeansParams",
    "kmeans_fit",
    "kmeans_predict",
    "kmeans_fit_predict",
    "kmeans_transform",
    "kmeans_balanced_fit",
    "kmeans_balanced_predict",
    "kmeans_balanced_fit_predict",
    "kmeans_plus_plus_init",
]
