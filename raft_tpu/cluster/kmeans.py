"""KMeans (Lloyd + k-means++ init) and balanced KMeans, TPU-native.

Capability parity targets (no in-tree CUDA ancestor — migrated to cuVS):
``cluster::kmeans`` fit/predict/transform and ``cluster::kmeans_balanced``
(the IVF coarse quantizer; north-star config #3).  Design:

* assignment  — fused L2 argmin (`distance.fused_l2_nn`): one MXU gemm per
  database tile, never materializing (n, k) unless k is tiny.
* update      — `segment_sum` scatter-add of points into centroids.
* fit loop    — `lax.while_loop` on (centroids, inertia, iter): the entire
  fit is ONE compiled XLA program.
* sharded fit — rows sharded over a mesh axis; each shard computes partial
  (sums, counts, inertia) and a `psum` merges them — the SPMD analog of the
  reference's MNMG kmeans-over-comms_t pattern (SURVEY.md §2.9.4).
* balanced    — Lloyd with a size-penalty term folded into the assignment
  cost, yielding near-uniform list sizes for IVF layouts.
"""

from __future__ import annotations

import dataclasses
import functools as _functools
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ..core.array import wrap_array
from ..core.compat import shard_map
from ..core.errors import expects
from ..distance.fused import _fused_l2_nn
from ..distance.pairwise import sq_l2
from ..utils.segment import within_group_rank as _within_group_rank

__all__ = [
    "KMeansParams",
    "capped_assign",
    "capped_assign_room",
    "kmeans_plus_plus_init",
    "kmeans_fit",
    "kmeans_predict",
    "kmeans_fit_predict",
    "kmeans_transform",
    "kmeans_balanced_fit",
    "kmeans_balanced_predict",
    "kmeans_balanced_fit_predict",
]


@dataclasses.dataclass(frozen=True)
class KMeansParams:
    """Fit configuration (per-call parameter struct, the reference's config
    idiom — SURVEY.md §5.6b)."""

    n_clusters: int = 8
    max_iter: int = 20
    tol: float = 1e-4
    seed: int = 0
    init: str = "kmeans++"  # "kmeans++" | "random"
    balanced_penalty: float = 1.0   # soft size penalty during balanced training
    balanced_max_ratio: float = 2.0  # hard cap = ratio · n/k for balanced lists
    # "highest" = exact 3-pass gemm for training assignments (default);
    # "bf16" = single-pass MXU gemm (~3x assignment rate) for the balanced
    # TRAINING loop only — the final capped assignment and the returned
    # inertia always use the exact gemm, so the hard size bound and the
    # reported quality are precision-independent
    balanced_assign_precision: str = "highest"  # "highest" | "bf16"


def _centroid_dtype(x):
    """Centroids are continuous quantities: float inputs keep their dtype
    (bf16 stays bf16), integer corpora (uint8/int8 SIFT-class) get f32 —
    rounding means back to uint8 would wrap residuals and quantize the
    probe routing (the reference's kmeans also emits float centroids for
    integer data)."""
    return x.dtype if jnp.issubdtype(x.dtype, jnp.floating) else jnp.float32


def _assign(x, centroids, tile: int = 4096):
    """(labels, sq_dists) for each row of x against centroids."""
    d, i = _fused_l2_nn(x, centroids, False, min(tile, centroids.shape[0]))
    return i, d


def _update(x, labels, k: int, w=None):
    xf = x.astype(jnp.float32)
    if w is None:
        sums = jax.ops.segment_sum(xf, labels, num_segments=k)
        counts = jax.ops.segment_sum(jnp.ones((x.shape[0],), jnp.float32),
                                     labels, num_segments=k)
    else:  # weighted centroid update: Σ wᵢxᵢ / Σ wᵢ
        sums = jax.ops.segment_sum(xf * w[:, None], labels, num_segments=k)
        counts = jax.ops.segment_sum(w, labels, num_segments=k)
    return sums, counts


def _new_centroids(sums, counts, old):
    # divide by the actual (possibly fractional, with sample_weight) mass;
    # clamping to 1.0 would leave sub-unit-weight clusters unnormalized
    safe = jnp.where(counts[:, None] > 0, counts[:, None], 1.0)
    fresh = sums / safe
    # empty clusters keep their previous position (reference keeps/reseeds)
    return jnp.where(counts[:, None] > 0, fresh, old)


def kmeans_plus_plus_init(key, x, k: int, *, tile: int = 4096,
                          sample_weight=None) -> jax.Array:
    """k-means++ seeding: (w·D²)-weighted sequential sampling, one lax.scan."""
    x = jnp.asarray(x)
    n = x.shape[0]
    k0, key = jax.random.split(key)
    w = None if sample_weight is None else jnp.asarray(sample_weight,
                                                       jnp.float32)
    if w is None:
        first = x[jax.random.randint(k0, (), 0, n)]
    else:  # the first center is weight-sampled too
        first = x[jax.random.choice(k0, n, p=w / jnp.maximum(jnp.sum(w),
                                                             1e-30))]
    xf = x.astype(jnp.float32)

    def d2_to(c):
        diff = xf - c[None, :].astype(jnp.float32)
        return jnp.sum(diff * diff, axis=1)

    def step(carry, sk):
        mind2 = carry
        score = mind2 if w is None else mind2 * w
        p = score / jnp.maximum(jnp.sum(score), 1e-30)
        idx = jax.random.choice(sk, n, p=p)
        c = x[idx]
        mind2 = jnp.minimum(mind2, d2_to(c))
        return mind2, c

    keys = jax.random.split(key, k - 1)
    _, rest = jax.lax.scan(step, d2_to(first), keys)
    return jnp.concatenate([first[None, :], rest], axis=0).astype(x.dtype)


@partial(jax.jit, static_argnames=("k", "max_iter", "init"))
def _fit_impl(x, key, k: int, max_iter: int, tol: float, init: str, w=None):
    if init == "kmeans++":
        c0 = kmeans_plus_plus_init(key, x, k, sample_weight=w)
    else:
        idx = jax.random.choice(key, x.shape[0], (k,), replace=False)
        c0 = x[idx]

    def inertia_of(d2):
        return jnp.sum(d2) if w is None else jnp.sum(d2 * w)

    def cond(state):
        _, prev_inertia, inertia, it = state
        return (it < max_iter) & (
            jnp.abs(prev_inertia - inertia) > tol * jnp.maximum(inertia, 1e-30)
        )

    def body(state):
        c, _, inertia, it = state
        labels, d2 = _assign(x, c)
        sums, counts = _update(x, labels, k, w)
        c2 = _new_centroids(sums, counts, c)
        return c2, inertia, inertia_of(d2), it + 1

    # one warmup Lloyd step so `inertia` holds a real value entering the loop
    c0 = c0.astype(jnp.float32)
    labels, d2 = _assign(x, c0)
    sums, counts = _update(x, labels, k, w)
    state = (_new_centroids(sums, counts, c0), jnp.float32(jnp.inf),
             inertia_of(d2), jnp.int32(1))
    c, _, inertia, n_iter = jax.lax.while_loop(cond, body, state)
    labels, d2 = _assign(x, c)
    return c.astype(_centroid_dtype(x)), labels, inertia_of(d2), n_iter


def kmeans_fit(
    x,
    params: Optional[KMeansParams] = None,
    *,
    sample_weight=None,
    mesh: Optional[Mesh] = None,
    axis: str = "shard",
    res=None,
):
    """Fit centroids. Returns ``(centroids, inertia, n_iter)``.

    ``sample_weight``: optional (n,) per-row weights (classic
    ``cluster::kmeans`` sample_weights parity) — weighted centroid
    updates, weighted inertia, and (w·D²)-weighted k-means++ seeding.

    With ``mesh``, rows are sharded over ``axis`` and each Lloyd step psums
    partial statistics over ICI (multi-chip data-parallel fit).
    ``sample_weight`` is single-device-only for now (the sharded program
    rejects it rather than silently ignoring the weights).
    """
    p = params or KMeansParams()
    x = wrap_array(x, ndim=2, name="x")
    expects(p.n_clusters <= x.shape[0], "n_clusters exceeds n_rows")
    # balanced-only knob (its name says so): reject rather than silently
    # run the plain fit at a precision the caller didn't get
    expects(p.balanced_assign_precision == "highest",
            "balanced_assign_precision applies to kmeans_balanced_fit* "
            "only; the plain fit always assigns at Precision.HIGHEST")
    w = None
    if sample_weight is not None:
        w = jnp.asarray(sample_weight, jnp.float32)
        expects(w.shape == (x.shape[0],),
                f"sample_weight shape {w.shape} != ({x.shape[0]},)")
    key = jax.random.PRNGKey(p.seed)
    if mesh is None:
        c, _, inertia, n_iter = _fit_impl(x, key, p.n_clusters, p.max_iter,
                                          p.tol, p.init, w)
        return c, inertia, n_iter
    expects(w is None, "sample_weight with mesh= is not supported yet; "
                       "fit per-shard weights via the single-device path")
    return _fit_sharded(x, key, p, mesh, axis)


@_functools.lru_cache(maxsize=64)
def _sharded_fit_program(mesh: Mesh, axis: str, k: int, max_iter: int, tol: float):
    """Compile-once sharded Lloyd loop (jit keyed on the static config, not a
    per-call closure — otherwise every kmeans_fit(mesh=...) call re-traces)."""

    def step_fn(c, xs):
        # xs: local (n/nsh, d) rows; c replicated
        labels, d2 = _assign(xs, c)
        sums, counts = _update(xs, labels, k)
        sums = jax.lax.psum(sums, axis)
        counts = jax.lax.psum(counts, axis)
        inertia = jax.lax.psum(jnp.sum(d2), axis)
        return _new_centroids(sums, counts, c), inertia

    def fit(xs, c0):
        def cond(carry):
            _, prev, inertia, it = carry
            return (it < max_iter) & (
                jnp.abs(prev - inertia) > tol * jnp.maximum(inertia, 1e-30)
            )

        def body(carry):
            c, _, inertia, it = carry
            c2, new_inertia = step_fn(c, xs)
            return c2, inertia, new_inertia, it + 1

        c, inertia0 = step_fn(c0, xs)
        c, _, inertia, it = jax.lax.while_loop(
            cond, body, (c, jnp.float32(jnp.inf), inertia0, jnp.int32(1))
        )
        return c, inertia, it

    return jax.jit(
        shard_map(
            fit, mesh=mesh, in_specs=(P(axis), P()), out_specs=(P(), P(), P()),
            check_vma=False,
        )
    )


def _fit_sharded(x, key, p: KMeansParams, mesh: Mesh, axis: str):
    nsh = mesh.shape[axis]
    n, d = x.shape
    expects(n % nsh == 0, f"rows {n} not divisible by shards {nsh}")
    k = p.n_clusters

    if p.init == "kmeans++":
        # k++ on a subsample (the reference trains coarse centroids on a
        # subsample too); full-data k++ would serialize n steps
        sub = x[:: max(1, n // (k * 32))]
        c0 = kmeans_plus_plus_init(key, sub, k).astype(jnp.float32)
    else:
        idx = jax.random.choice(key, n, (k,), replace=False)
        c0 = x[idx].astype(jnp.float32)

    fit = _sharded_fit_program(mesh, axis, k, p.max_iter, float(p.tol))
    c, inertia, n_iter = fit(x, c0)
    return c.astype(_centroid_dtype(x)), inertia, n_iter


def kmeans_predict(x, centroids, *, res=None) -> jax.Array:
    x = wrap_array(x, ndim=2, name="x")
    centroids = wrap_array(centroids, ndim=2, name="centroids")
    return _assign(x, centroids)[0]


def kmeans_fit_predict(x, params: Optional[KMeansParams] = None, **kw):
    c, inertia, n_iter = kmeans_fit(x, params, **kw)
    return c, kmeans_predict(x, c), inertia, n_iter


def kmeans_transform(x, centroids, *, res=None) -> jax.Array:
    """Distance from every row to every centroid (n, k) — L2."""
    from ..distance.pairwise import pairwise_distance

    return pairwise_distance(x, centroids, "euclidean")


# --------------------------------------------------------------------------
# Balanced variant — the IVF coarse quantizer.
# --------------------------------------------------------------------------

def _assign_balanced(x, c, counts, penalty, n_per,
                     precision=jax.lax.Precision.HIGHEST):
    """Assignment with multiplicative size penalty:
    ``cost = d² · (1 + λ·size/target)``.

    Multiplicative scaling keeps the penalty proportional to the local
    distance scale: points well inside a cluster stay put, boundary points
    migrate to less-crowded neighbors — additive penalties either do nothing
    (scale too small) or shuffle points across unrelated clusters (too
    large)."""
    d2 = sq_l2(x, c, precision=precision)
    cost = d2 * (1.0 + penalty * counts[None, :] / jnp.maximum(n_per, 1.0))
    labels = jnp.argmin(cost, axis=1)
    real = jnp.take_along_axis(d2, labels[:, None], axis=1)[:, 0]
    return labels, real


def _capped_assign_impl(x, centroids, room, valid=None):
    """Shared core of :func:`capped_assign` / :func:`capped_assign_room`:
    ``room`` is a traced per-cluster capacity vector (k,) int32.

    ``valid``: optional (n,) bool row mask — invalid rows never request a
    cluster, never consume capacity, and keep label −1 (the pipelined
    chunked builds pad the tail chunk to a fixed shape and mask the pads
    here).  With ``valid=None`` (or all-True) the computation is
    bit-identical to the unmasked form: masked rows only ever add
    +inf-distance requests, which :func:`~raft_tpu.utils.segment.
    within_group_rank` ranks after every finite (real) request, so real
    rows' ranks — and therefore acceptance — are unchanged.
    """
    n = x.shape[0]
    k = centroids.shape[0]
    d2 = sq_l2(x, centroids)
    INF = jnp.float32(jnp.inf)
    if valid is None:
        valid = jnp.ones((n,), bool)

    def pending(labels):
        return jnp.sum(((labels < 0) & valid).astype(jnp.int32))

    def cond(carry):
        labels, counts, prev_left = carry
        left = pending(labels)
        return (left > 0) & (left != prev_left)

    def round_fn(carry):
        labels, counts, _ = carry
        prev_left = pending(labels)
        unassigned = (labels < 0) & valid
        full = counts >= room
        cost = jnp.where(full[None, :], INF, d2)
        cand = jnp.argmin(cost, axis=1).astype(jnp.int32)
        req_d2 = jnp.where(unassigned, jnp.take_along_axis(d2, cand[:, None], 1)[:, 0], INF)
        rank = _within_group_rank(cand, req_d2, k)
        left_room = (room - counts)[cand]
        accept = unassigned & (rank < left_room)
        labels = jnp.where(accept, cand, labels)
        counts = counts + jax.ops.segment_sum(
            accept.astype(jnp.int32), cand, num_segments=k
        )
        return labels, counts, prev_left

    labels0 = jnp.full((n,), -1, jnp.int32)
    counts0 = jnp.zeros((k,), jnp.int32)
    labels, counts, _ = jax.lax.while_loop(
        cond, round_fn, (labels0, counts0, jnp.int32(-1))
    )
    return labels, counts


@partial(jax.jit, static_argnames=("cap",))
def capped_assign(x, centroids, cap: int):
    """Capacity-constrained nearest-centroid assignment.

    Every cluster receives at most ``cap`` points; overflow spills to the
    next-nearest cluster with room.  Per round: each unassigned point
    requests its nearest non-full cluster, requests are ranked by distance
    within each cluster, and the closest ``capacity_left`` are accepted.
    Deterministic, O(rounds · n log n), and the workhorse behind balanced
    IVF list layouts (dense padded lists need a hard size bound).

    Runs until every point is placed or no progress is possible (all
    remaining capacity exhausted — only when ``cap·k < n``); leftover points
    then keep label -1.  While capacity remains, each round accepts at least
    one point, so termination ≡ completion.
    """
    k = centroids.shape[0]
    return _capped_assign_impl(x, centroids, jnp.full((k,), cap, jnp.int32))


@jax.jit
def capped_assign_room(x, centroids, room, valid=None):
    """:func:`capped_assign` against a traced per-cluster ``room`` vector
    (k,) — the streaming-build variant: chunked index builds pass the
    *remaining* capacity of each list (``cap - counts_so_far``) so a chunk
    can never overflow lists filled by earlier chunks.  ``valid``: optional
    (n,) bool row mask (padded fixed-shape chunks); masked rows keep
    label −1 and consume no capacity."""
    return _capped_assign_impl(x, centroids, jnp.asarray(room, jnp.int32),
                               valid)


@partial(jax.jit, static_argnames=("k", "max_iter", "cap", "precision"))
def _balanced_fit_impl(x, key, k: int, max_iter: int, penalty: float, cap: int,
                       precision=jax.lax.Precision.HIGHEST):
    n = x.shape[0]
    n_per = jnp.float32(n / k)
    c0 = kmeans_plus_plus_init(key, x, k).astype(jnp.float32)
    counts0 = jnp.zeros((k,), jnp.float32)

    def body(it, carry):
        c, counts_s, _ = carry
        labels, d2 = _assign_balanced(x, c, counts_s, penalty, n_per,
                                      precision)
        sums, cnts = _update(x, labels, k)
        c2 = _new_centroids(sums, cnts, c)
        # revive genuinely empty clusters (otherwise frozen forever): slot
        # j-th empty centroid onto the j-th worst-assigned point
        empty = cnts == 0
        _, worst = jax.lax.top_k(d2, k)
        slot = jnp.clip(jnp.cumsum(empty.astype(jnp.int32)) - 1, 0, k - 1)
        c2 = jnp.where(empty[:, None], x[worst[slot]].astype(jnp.float32), c2)
        # smoothed counts damp the penalty feedback loop (no oscillation)
        return c2, 0.5 * counts_s + 0.5 * cnts, jnp.sum(d2)

    c, _, _ = jax.lax.fori_loop(0, max_iter, body, (c0, counts0, jnp.float32(0)))
    # final assignment is capacity-constrained — a hard size bound, which the
    # soft penalty alone cannot give (winner-take-all between co-located
    # centroids); one more Lloyd update from the capped labels re-centers.
    labels, counts = capped_assign(x, c, cap)
    safe = jnp.maximum(labels, 0)
    assigned = (labels >= 0).astype(jnp.float32)
    sums = jax.ops.segment_sum(x.astype(jnp.float32) * assigned[:, None], safe, num_segments=k)
    cnts = jax.ops.segment_sum(assigned, safe, num_segments=k)
    c = _new_centroids(sums, cnts, c)
    # inertia measured against the RETURNED centroids and labels (a stale
    # training-loop value would mislead seed/penalty sweeps)
    d2_final = sq_l2(x, c)
    real = jnp.take_along_axis(d2_final, safe[:, None], axis=1)[:, 0]
    inertia = jnp.sum(real * assigned)
    return c.astype(_centroid_dtype(x)), labels, counts, inertia


def _balanced_cap(p: KMeansParams, n: int) -> int:
    return int(-(-p.balanced_max_ratio * n // p.n_clusters))


def kmeans_balanced_fit_predict(x, params: Optional[KMeansParams] = None, *, res=None):
    """Returns ``(centroids, capped_labels, cluster_sizes, inertia)`` — the
    labels respect the hard bound ``balanced_max_ratio · n/k`` (what an IVF
    build consumes).  ``balanced_max_ratio`` must be ≥ 1: below that total
    capacity cannot hold the dataset and points would be dropped."""
    p = params or KMeansParams()
    x = wrap_array(x, ndim=2, name="x")
    expects(p.n_clusters <= x.shape[0], "n_clusters exceeds n_rows")
    expects(
        p.balanced_max_ratio >= 1.0,
        f"balanced_max_ratio={p.balanced_max_ratio} < 1 cannot hold all points",
    )
    expects(p.balanced_assign_precision in ("highest", "bf16"),
            f"balanced_assign_precision={p.balanced_assign_precision!r} (want highest|bf16)")
    key = jax.random.PRNGKey(p.seed)
    precision = (jax.lax.Precision.DEFAULT if p.balanced_assign_precision == "bf16"
                 else jax.lax.Precision.HIGHEST)
    return _balanced_fit_impl(
        x, key, p.n_clusters, p.max_iter, p.balanced_penalty,
        _balanced_cap(p, x.shape[0]), precision=precision
    )


def kmeans_balanced_fit(x, params: Optional[KMeansParams] = None, *, res=None):
    """Balanced fit → ``(centroids, cluster_sizes, inertia)``; see
    :func:`kmeans_balanced_fit_predict` for the size-bound contract."""
    c, _, counts, inertia = kmeans_balanced_fit_predict(x, params, res=res)
    return c, counts, inertia


def kmeans_balanced_predict(x, centroids, *, res=None) -> jax.Array:
    """Plain nearest-centroid labels (the cap only shapes the build)."""
    return kmeans_predict(x, centroids)
