"""KMeans (Lloyd + k-means++ init) and balanced KMeans, TPU-native.

Capability parity targets (no in-tree CUDA ancestor — migrated to cuVS):
``cluster::kmeans`` fit/predict/transform and ``cluster::kmeans_balanced``
(the IVF coarse quantizer; north-star config #3).  Design:

* assignment  — fused L2 argmin (`distance.fused_l2_nn`): one MXU gemm per
  database tile, never materializing (n, k) unless k is tiny.
* update      — `segment_sum` scatter-add of points into centroids.
* fit loop    — `lax.while_loop` on (centroids, inertia, iter): the entire
  fit is ONE compiled XLA program.
* sharded fit — rows sharded over a mesh axis; each shard computes partial
  (sums, counts, inertia) and a `psum` merges them — the SPMD analog of the
  reference's MNMG kmeans-over-comms_t pattern (SURVEY.md §2.9.4).
* balanced    — Lloyd with a size-penalty term folded into the assignment
  cost, yielding near-uniform list sizes for IVF layouts.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ..core.array import wrap_array
from ..core.errors import expects
from ..distance.fused import _fused_l2_nn

__all__ = [
    "KMeansParams",
    "kmeans_plus_plus_init",
    "kmeans_fit",
    "kmeans_predict",
    "kmeans_fit_predict",
    "kmeans_transform",
    "kmeans_balanced_fit",
    "kmeans_balanced_predict",
    "kmeans_balanced_fit_predict",
]


@dataclasses.dataclass(frozen=True)
class KMeansParams:
    """Fit configuration (per-call parameter struct, the reference's config
    idiom — SURVEY.md §5.6b)."""

    n_clusters: int = 8
    max_iter: int = 20
    tol: float = 1e-4
    seed: int = 0
    init: str = "kmeans++"  # "kmeans++" | "random"
    balanced_penalty: float = 1.0  # only used by balanced variant


def _assign(x, centroids, tile: int = 4096):
    """(labels, sq_dists) for each row of x against centroids."""
    d, i = _fused_l2_nn(x, centroids, False, min(tile, centroids.shape[0]))
    return i, d


def _update(x, labels, k: int):
    sums = jax.ops.segment_sum(x.astype(jnp.float32), labels, num_segments=k)
    counts = jax.ops.segment_sum(jnp.ones((x.shape[0],), jnp.float32), labels, num_segments=k)
    return sums, counts


def _new_centroids(sums, counts, old):
    safe = jnp.maximum(counts[:, None], 1.0)
    fresh = sums / safe
    # empty clusters keep their previous position (reference keeps/reseeds)
    return jnp.where(counts[:, None] > 0, fresh, old)


def kmeans_plus_plus_init(key, x, k: int, *, tile: int = 4096) -> jax.Array:
    """k-means++ seeding: D²-weighted sequential sampling, as one lax.scan."""
    x = jnp.asarray(x)
    n = x.shape[0]
    k0, key = jax.random.split(key)
    first = x[jax.random.randint(k0, (), 0, n)]
    xf = x.astype(jnp.float32)

    def d2_to(c):
        diff = xf - c[None, :].astype(jnp.float32)
        return jnp.sum(diff * diff, axis=1)

    def step(carry, sk):
        mind2 = carry
        p = mind2 / jnp.maximum(jnp.sum(mind2), 1e-30)
        idx = jax.random.choice(sk, n, p=p)
        c = x[idx]
        mind2 = jnp.minimum(mind2, d2_to(c))
        return mind2, c

    keys = jax.random.split(key, k - 1)
    _, rest = jax.lax.scan(step, d2_to(first), keys)
    return jnp.concatenate([first[None, :], rest], axis=0).astype(x.dtype)


@partial(jax.jit, static_argnames=("k", "max_iter", "init"))
def _fit_impl(x, key, k: int, max_iter: int, tol: float, init: str):
    if init == "kmeans++":
        c0 = kmeans_plus_plus_init(key, x, k)
    else:
        idx = jax.random.choice(key, x.shape[0], (k,), replace=False)
        c0 = x[idx]

    def cond(state):
        _, prev_inertia, inertia, it = state
        return (it < max_iter) & (
            jnp.abs(prev_inertia - inertia) > tol * jnp.maximum(inertia, 1e-30)
        )

    def body(state):
        c, _, inertia, it = state
        labels, d2 = _assign(x, c)
        sums, counts = _update(x, labels, k)
        c2 = _new_centroids(sums, counts, c)
        return c2, inertia, jnp.sum(d2), it + 1

    # one warmup Lloyd step so `inertia` holds a real value entering the loop
    c0 = c0.astype(jnp.float32)
    labels, d2 = _assign(x, c0)
    sums, counts = _update(x, labels, k)
    state = (_new_centroids(sums, counts, c0), jnp.float32(jnp.inf), jnp.sum(d2), jnp.int32(1))
    c, _, inertia, n_iter = jax.lax.while_loop(cond, body, state)
    labels, d2 = _assign(x, c)
    return c.astype(x.dtype), labels, jnp.sum(d2), n_iter


def kmeans_fit(
    x,
    params: Optional[KMeansParams] = None,
    *,
    mesh: Optional[Mesh] = None,
    axis: str = "shard",
    res=None,
):
    """Fit centroids. Returns ``(centroids, inertia, n_iter)``.

    With ``mesh``, rows are sharded over ``axis`` and each Lloyd step psums
    partial statistics over ICI (multi-chip data-parallel fit).
    """
    p = params or KMeansParams()
    x = wrap_array(x, ndim=2, name="x")
    expects(p.n_clusters <= x.shape[0], "n_clusters exceeds n_rows")
    key = jax.random.PRNGKey(p.seed)
    if mesh is None:
        c, _, inertia, n_iter = _fit_impl(x, key, p.n_clusters, p.max_iter, p.tol, p.init)
        return c, inertia, n_iter
    return _fit_sharded(x, key, p, mesh, axis)


def _fit_sharded(x, key, p: KMeansParams, mesh: Mesh, axis: str):
    nsh = mesh.shape[axis]
    n, d = x.shape
    expects(n % nsh == 0, f"rows {n} not divisible by shards {nsh}")
    k = p.n_clusters

    # init on replicated data view (cheap: k++ on a subsample)
    sub = x[:: max(1, n // (k * 32))]
    c0 = kmeans_plus_plus_init(key, sub, k).astype(jnp.float32)

    def step_fn(c, xs):
        # xs: local (n/nsh, d) rows; c replicated
        labels, d2 = _assign(xs, c)
        sums, counts = _update(xs, labels, k)
        sums = jax.lax.psum(sums, axis)
        counts = jax.lax.psum(counts, axis)
        inertia = jax.lax.psum(jnp.sum(d2), axis)
        return _new_centroids(sums, counts, c), inertia

    def fit(xs, c0):
        def body(it, carry):
            c, _ = carry
            return step_fn(c, xs)

        c, inertia = jax.lax.fori_loop(0, p.max_iter, body, (c0, jnp.float32(jnp.inf)))
        return c, inertia

    fit_sharded = jax.jit(
        jax.shard_map(
            fit, mesh=mesh, in_specs=(P(axis), P()), out_specs=(P(), P()),
            check_vma=False,
        )
    )
    c, inertia = fit_sharded(x, c0)
    return c.astype(x.dtype), inertia, jnp.int32(p.max_iter)


def kmeans_predict(x, centroids, *, res=None) -> jax.Array:
    x = wrap_array(x, ndim=2, name="x")
    centroids = wrap_array(centroids, ndim=2, name="centroids")
    return _assign(x, centroids)[0]


def kmeans_fit_predict(x, params: Optional[KMeansParams] = None, **kw):
    c, inertia, n_iter = kmeans_fit(x, params, **kw)
    return c, kmeans_predict(x, c), inertia, n_iter


def kmeans_transform(x, centroids, *, res=None) -> jax.Array:
    """Distance from every row to every centroid (n, k) — L2."""
    from ..distance.pairwise import pairwise_distance

    return pairwise_distance(x, centroids, "euclidean")


# --------------------------------------------------------------------------
# Balanced variant — the IVF coarse quantizer.
# --------------------------------------------------------------------------

def _assign_balanced(x, c, counts, penalty, n_per):
    """Assignment with additive size penalty: cost = d² + λ·q·(size/target),
    where q is the mean quantization error (mean distance to nearest
    centroid) — the natural scale so the penalty competes with real
    distances, not with inter-cluster separation."""
    xf = x.astype(jnp.float32)
    xn = jnp.sum(xf * xf, axis=1)
    cf = c.astype(jnp.float32)
    cn = jnp.sum(cf * cf, axis=1)
    d2 = jnp.maximum(xn[:, None] + cn[None, :] - 2.0 * jnp.dot(xf, cf.T), 0.0)
    scale = jnp.mean(jnp.min(d2, axis=1)) + 1e-12
    cost = d2 + penalty * scale * (counts[None, :] / jnp.maximum(n_per, 1.0))
    labels = jnp.argmin(cost, axis=1)
    real = jnp.take_along_axis(d2, labels[:, None], axis=1)[:, 0]
    return labels, real


@partial(jax.jit, static_argnames=("k", "max_iter"))
def _balanced_fit_impl(x, key, k: int, max_iter: int, penalty: float):
    n = x.shape[0]
    n_per = jnp.float32(n / k)
    c0 = kmeans_plus_plus_init(key, x, k).astype(jnp.float32)
    counts0 = jnp.zeros((k,), jnp.float32)

    def body(it, carry):
        c, counts_s, _ = carry
        labels, d2 = _assign_balanced(x, c, counts_s, penalty, n_per)
        sums, cnts = _update(x, labels, k)
        c2 = _new_centroids(sums, cnts, c)
        # reseed any empty cluster at one of the worst-assigned points
        # (slot j empty → j-th farthest point), preventing permanent collapse
        _, worst_idx = jax.lax.top_k(d2, k)
        empty = cnts == 0
        slot = jnp.clip(jnp.cumsum(empty.astype(jnp.int32)) - 1, 0, k - 1)
        repl = x[worst_idx].astype(jnp.float32)  # (k, d)
        c2 = jnp.where(empty[:, None], repl[slot], c2)
        # smoothed counts damp the penalty feedback loop (no oscillation)
        counts_s = 0.5 * counts_s + 0.5 * cnts
        return c2, counts_s, jnp.sum(d2)

    c, counts_s, inertia = jax.lax.fori_loop(0, max_iter, body, (c0, counts0, jnp.float32(0)))
    # final hard assignment (with steady-state penalty) gives the list sizes
    labels, d2 = _assign_balanced(x, c, counts_s, penalty, n_per)
    _, counts = _update(x, labels, k)
    return c.astype(x.dtype), counts, jnp.sum(d2)


def kmeans_balanced_fit(x, params: Optional[KMeansParams] = None, *, res=None):
    """Balanced fit → ``(centroids, cluster_sizes, inertia)``."""
    p = params or KMeansParams()
    x = wrap_array(x, ndim=2, name="x")
    expects(p.n_clusters <= x.shape[0], "n_clusters exceeds n_rows")
    key = jax.random.PRNGKey(p.seed)
    return _balanced_fit_impl(x, key, p.n_clusters, p.max_iter, p.balanced_penalty)


def kmeans_balanced_predict(x, centroids, *, res=None) -> jax.Array:
    """Plain nearest-centroid labels (the penalty only shapes training)."""
    return kmeans_predict(x, centroids)


def kmeans_balanced_fit_predict(x, params: Optional[KMeansParams] = None, *, res=None):
    c, sizes, inertia = kmeans_balanced_fit(x, params)
    return c, kmeans_balanced_predict(x, c), sizes, inertia
