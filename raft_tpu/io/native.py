"""ctypes binding for the native IO library (``cpp/raft_tpu_io.cpp``).

Loads ``libraft_tpu_io.so`` (built by ``make -C cpp``; attempted once,
automatically, on first use).  Every entry point has a pure-NumPy
fallback, so the package works without a toolchain — the native path is
the performance tier (threaded pread, GIL-free), matching the
reference's native-by-necessity host IO
(``core/detail/mdspan_numpy_serializer.hpp``, raft-ann-bench loaders).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Optional

_LIB_NAME = "libraft_tpu_io.so"
_lib: Optional[ctypes.CDLL] = None
_tried = False


def _reset_for_tests(lib="unset") -> None:
    """Clear (or force) the load-once state so tests can exercise both
    the native and the fallback paths in one process.  ``lib=None``
    pins the fallback (sets ``_tried`` so no build is attempted);
    default re-arms a fresh ``_load()`` attempt."""
    global _lib, _tried
    if lib == "unset":
        _lib, _tried = None, False
    else:
        _lib, _tried = lib, True


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    _tried = True
    here = os.path.dirname(os.path.abspath(__file__))
    path = os.path.join(here, _LIB_NAME)
    if not os.path.exists(path):
        cpp = os.path.join(here, "..", "..", "cpp")
        # Auto-building on first IO call is surprising in library code
        # (sandboxes pay a doomed subprocess attempt); opt out with
        # RAFT_TPU_BUILD_NATIVE=0.  The attempt happens at most once per
        # process (guarded by _tried) with a short timeout, and only when a
        # toolchain is plausibly present.
        import shutil

        want_build = os.environ.get("RAFT_TPU_BUILD_NATIVE", "1") != "0"
        cxx = os.environ.get("CXX", "g++")  # the Makefile honors $CXX
        have_cxx = shutil.which(cxx) or shutil.which("g++") or shutil.which("clang++")
        if (want_build and os.path.exists(os.path.join(cpp, "Makefile"))
                and shutil.which("make") and have_cxx):
            # serialize concurrent builders (pytest-xdist, parallel jobs):
            # only the flock holder runs make; losers wait, then re-check
            try:
                import fcntl

                with open(os.path.join(here, ".build.lock"), "w") as lk:
                    fcntl.flock(lk, fcntl.LOCK_EX)
                    if not os.path.exists(path):
                        subprocess.run(["make", "-C", cpp], capture_output=True,
                                       timeout=60, check=True)
            except (OSError, subprocess.SubprocessError, ImportError):
                return None
    if not os.path.exists(path):
        return None
    try:
        lib = ctypes.CDLL(path)
    except OSError:
        return None
    lib.rt_npy_header.argtypes = [
        ctypes.c_char_p, ctypes.c_char_p, ctypes.c_int,
        ctypes.POINTER(ctypes.c_int), ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(ctypes.c_int), ctypes.POINTER(ctypes.c_int64)]
    lib.rt_npy_header.restype = ctypes.c_int
    lib.rt_mmap.argtypes = [ctypes.c_char_p, ctypes.POINTER(ctypes.c_void_p),
                            ctypes.POINTER(ctypes.c_int64)]
    lib.rt_mmap.restype = ctypes.c_int
    lib.rt_munmap.argtypes = [ctypes.c_void_p, ctypes.c_int64]
    lib.rt_munmap.restype = ctypes.c_int
    lib.rt_vecs_info.argtypes = [ctypes.c_char_p, ctypes.c_int,
                                 ctypes.POINTER(ctypes.c_int64),
                                 ctypes.POINTER(ctypes.c_int64)]
    lib.rt_vecs_info.restype = ctypes.c_int
    lib.rt_vecs_read.argtypes = [ctypes.c_char_p, ctypes.c_int, ctypes.c_int64,
                                 ctypes.c_int64, ctypes.c_int64,
                                 ctypes.c_void_p, ctypes.c_int]
    lib.rt_vecs_read.restype = ctypes.c_int
    lib.rt_pread_dense.argtypes = [ctypes.c_char_p, ctypes.c_int64,
                                   ctypes.c_int64, ctypes.c_void_p, ctypes.c_int]
    lib.rt_pread_dense.restype = ctypes.c_int
    _lib = lib
    return _lib


def available() -> bool:
    """True when the native library loaded (building it on demand)."""
    return _load() is not None


def npy_header(path: str):
    """(dtype_descr, shape, fortran, data_offset) of a .npy file, or None
    if the native library is unavailable."""
    lib = _load()
    if lib is None:
        return None
    descr = ctypes.create_string_buffer(32)
    ndim = ctypes.c_int()
    shape = (ctypes.c_int64 * 8)()
    fortran = ctypes.c_int()
    off = ctypes.c_int64()
    rc = lib.rt_npy_header(path.encode(), descr, 32, ctypes.byref(ndim),
                           shape, ctypes.byref(fortran), ctypes.byref(off))
    if rc != 0:
        raise OSError(-rc, f"rt_npy_header({path!r}) failed", path)
    return (descr.value.decode(), tuple(shape[i] for i in range(ndim.value)),
            bool(fortran.value), off.value)


def vecs_info(path: str, elem_size: int):
    lib = _load()
    if lib is None:
        return None
    rows = ctypes.c_int64()
    dim = ctypes.c_int64()
    rc = lib.rt_vecs_info(path.encode(), elem_size, ctypes.byref(rows),
                          ctypes.byref(dim))
    if rc != 0:
        raise OSError(-rc, f"rt_vecs_info({path!r}) failed", path)
    return rows.value, dim.value


def vecs_read_into(path: str, elem_size: int, dim: int, row_start: int,
                   n_rows: int, out, threads: int = 8) -> bool:
    """Threaded strided read into a preallocated C-contiguous array.
    Returns False when the native library is unavailable."""
    lib = _load()
    if lib is None:
        return False
    rc = lib.rt_vecs_read(path.encode(), elem_size, dim, row_start, n_rows,
                          out.ctypes.data_as(ctypes.c_void_p), threads)
    if rc != 0:
        raise OSError(-rc, f"rt_vecs_read({path!r}) failed", path)
    return True


def pread_dense_into(path: str, offset: int, out, threads: int = 8) -> bool:
    """Threaded dense read of ``out.nbytes`` bytes at ``offset`` into a
    preallocated buffer.  Returns False when unavailable."""
    lib = _load()
    if lib is None:
        return False
    rc = lib.rt_pread_dense(path.encode(), offset, out.nbytes,
                            out.ctypes.data_as(ctypes.c_void_p), threads)
    if rc != 0:
        raise OSError(-rc, f"rt_pread_dense({path!r}) failed", path)
    return True
