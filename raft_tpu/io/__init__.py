"""Dataset + array IO — host-side feeding layer for the TPU pipeline.

Covers the reference's native IO surface: ``.npy`` persistence
(``core/serialize.hpp:26,73``, reader parity with
``core/detail/mdspan_numpy_serializer.hpp``) and the TexMex
``.fvecs/.bvecs/.ivecs`` dataset formats used by the ANN benchmarks
(SIFT-1M, DEEP, GIST — raft-ann-bench's loaders, removed upstream with
the cuVS migration).  A native C++ backend (``cpp/raft_tpu_io.cpp``,
threaded ``pread`` off the GIL) accelerates bulk reads when built;
everything degrades to pure NumPy transparently.
"""

from __future__ import annotations

import os
from typing import Iterator, Optional, Tuple

import numpy as np

from . import native
from .shards import ShardedVectorStore, ShardWriter

__all__ = [
    "read_npy",
    "read_fvecs",
    "read_bvecs",
    "read_ivecs",
    "vecs_shape",
    "BatchLoader",
    "ShardWriter",
    "ShardedVectorStore",
]

_VECS_DTYPES = {".fvecs": (np.float32, 4), ".bvecs": (np.uint8, 1),
                ".ivecs": (np.int32, 4)}


def read_npy(path: str, *, mmap: bool = False, threads: int = 8,
             out: Optional[np.ndarray] = None) -> np.ndarray:
    """Load a ``.npy`` file.  ``mmap=True`` returns a zero-copy
    memory-mapped view; otherwise the data section is read with the
    native threaded reader when available (several GB/s from page cache
    vs. single-stream ``np.load``).

    ``out``: optional preallocated destination (e.g. from
    ``core.HostBufferPool`` — the pinned staging-reuse pattern); shape,
    dtype, and memory order must match the file exactly."""
    if mmap:
        if out is not None:
            raise ValueError("out= and mmap=True are mutually exclusive")
        return np.load(path, mmap_mode="r", allow_pickle=False)
    try:
        # files the C parser can't express (structured dtypes, ndim > 8)
        # must still load — fall back rather than surface the native error
        hdr = native.npy_header(path) if native.available() else None
    except OSError:
        hdr = None
    if hdr is None:
        data = np.load(path, allow_pickle=False)
        if out is None:
            return data
        if out.shape != data.shape or out.dtype != data.dtype:
            raise ValueError(f"out {out.shape}/{out.dtype} does not match "
                             f"file {data.shape}/{data.dtype}")
        np.copyto(out, data)
        return out
    descr, shape, fortran, offset = hdr
    dt = np.dtype(descr)
    if dt.hasobject:
        # object dtypes hold pickle bytes, not raw data — filling a
        # PyObject* array from disk would segfault; np.load raises the
        # proper allow_pickle error instead
        return np.load(path, allow_pickle=False)
    if out is not None:
        want_order = "F" if fortran else "C"
        ok = (out.shape == tuple(shape) and out.dtype == dt
              and (out.flags.f_contiguous if fortran
                   else out.flags.c_contiguous))
        if not ok:
            raise ValueError(f"out must be {want_order}-contiguous "
                             f"{tuple(shape)}/{dt}, got "
                             f"{out.shape}/{out.dtype}")
    else:
        out = np.empty(shape, dtype=dt, order="F" if fortran else "C")
    if not native.pread_dense_into(path, offset, out, threads=threads):
        data = np.load(path, allow_pickle=False)
        np.copyto(out, data)
    return out


def vecs_shape(path: str) -> Tuple[int, int]:
    """(rows, dim) of a TexMex vecs file without reading the data."""
    dt, esz = _vecs_meta(path)
    info = native.vecs_info(path, esz) if native.available() else None
    if info is not None:
        return info
    dim = int(np.fromfile(path, dtype=np.int32, count=1)[0])
    row_bytes = 4 + dim * esz
    size = os.path.getsize(path)
    if dim <= 0 or size % row_bytes:
        raise ValueError(f"{path}: not a valid vecs file")
    return size // row_bytes, dim


def _vecs_meta(path: str):
    ext = os.path.splitext(path)[1]
    if ext not in _VECS_DTYPES:
        raise ValueError(f"unknown vecs extension {ext!r}")
    return _VECS_DTYPES[ext]


def _read_vecs(path: str, start: int, count: Optional[int], threads: int,
               geometry: Optional[Tuple[int, int]] = None,
               out: Optional[np.ndarray] = None) -> np.ndarray:
    dt, esz = _vecs_meta(path)
    rows, dim = geometry if geometry is not None else vecs_shape(path)
    if count is None:
        count = rows - start
    if start < 0 or start + count > rows:
        raise ValueError(f"rows [{start}, {start + count}) out of range {rows}")
    if out is not None:
        if out.shape != (count, dim) or out.dtype != dt \
                or not out.flags.c_contiguous:
            raise ValueError(f"out must be C-contiguous ({count}, {dim})/"
                             f"{np.dtype(dt)}, got {out.shape}/{out.dtype}")
    else:
        out = np.empty((count, dim), dtype=dt)
    if native.available() and native.vecs_read_into(
            path, esz, dim, start, count, out, threads=threads):
        return out
    row_bytes = 4 + dim * esz
    raw = np.memmap(path, dtype=np.uint8, mode="r",
                    offset=start * row_bytes, shape=(count * row_bytes,))
    mat = raw.reshape(count, row_bytes)[:, 4:]
    np.copyto(out, mat.view(dt).reshape(count, dim))
    return out


def read_fvecs(path: str, start: int = 0, count: Optional[int] = None,
               *, threads: int = 8) -> np.ndarray:
    """Read float32 vectors from a ``.fvecs`` file (SIFT/GIST format)."""
    return _read_vecs(path, start, count, threads)


def read_bvecs(path: str, start: int = 0, count: Optional[int] = None,
               *, threads: int = 8) -> np.ndarray:
    """Read uint8 vectors from a ``.bvecs`` file (DEEP/SIFT-1B format)."""
    return _read_vecs(path, start, count, threads)


def read_ivecs(path: str, start: int = 0, count: Optional[int] = None,
               *, threads: int = 8) -> np.ndarray:
    """Read int32 vectors (ground-truth neighbor lists) from ``.ivecs``."""
    return _read_vecs(path, start, count, threads)


class BatchLoader:
    """Double-buffered background batch reader: while the TPU consumes
    batch *i*, a worker thread reads batch *i+1* (native threaded pread
    underneath).  The host-side analog of the reference's stream-pool
    copy/compute overlap (``core/resource/cuda_stream_pool.hpp``).

    ``reuse_buffers=True`` stages batches through the host pool
    (``core.HostBufferPool``, the pinned-MR analog): the steady-state
    loop allocates nothing, cycling two staging buffers.  The contract
    is the standard staging-ring one: **each yielded batch is valid only
    until the next iteration** — copy it (or finish converting it to a
    device array) before advancing."""

    def __init__(self, path: str, batch_rows: int, *, start: int = 0,
                 stop: Optional[int] = None, threads: int = 8,
                 reuse_buffers: bool = False, host_pool=None):
        self._path = path
        self._batch = int(batch_rows)
        self._rows, self._dim = vecs_shape(path)
        self._stop = self._rows if stop is None else min(stop, self._rows)
        self._start = start
        self._threads = threads
        self._pool = None
        if reuse_buffers or host_pool is not None:
            # an explicit pool IS the reuse request — silently ignoring it
            # would allocate fresh buffers the caller thought were pooled
            from ..core.host_memory import default_host_pool

            self._pool = host_pool or default_host_pool()

    @property
    def dim(self) -> int:
        return self._dim

    def __len__(self) -> int:
        return -(-(self._stop - self._start) // self._batch)

    def __iter__(self) -> Iterator[np.ndarray]:
        import concurrent.futures as cf

        dt, _ = _vecs_meta(self._path)

        def submit(workers, lo, n):
            buf = (self._pool.acquire((n, self._dim), dt)
                   if self._pool is not None else None)
            return workers.submit(_read_vecs, self._path, lo, n,
                                  self._threads, geom, buf)

        with cf.ThreadPoolExecutor(max_workers=1) as workers:
            nxt = None
            prev = None
            geom = (self._rows, self._dim)
            for lo in range(self._start, self._stop, self._batch):
                n = min(self._batch, self._stop - lo)
                if nxt is None:
                    nxt = submit(workers, lo, n)
                cur = nxt.result()
                if prev is not None and self._pool is not None:
                    # the consumer advanced past ``prev`` (the lending
                    # contract) and the worker is idle here — releasing
                    # before the next submit closes the two-buffer ring:
                    # the worker refills ``prev`` while the consumer
                    # holds ``cur``
                    self._pool.release(prev)
                hi = lo + self._batch
                if hi < self._stop:
                    nn = min(self._batch, self._stop - hi)
                    nxt = submit(workers, hi, nn)
                else:
                    nxt = None
                prev = cur
                yield cur
            if prev is not None and self._pool is not None:
                self._pool.release(prev)
