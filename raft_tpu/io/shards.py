"""Mmap-backed sharded vector store for the out-of-core search tier.

The FusionANNS split (PAPERS.md): accelerator memory holds only compact
codes, the full-precision rows live host-side, and only top-ranked
candidates cross the bus.  This module is the host half — a directory of
fixed-row ``.npy`` shards plus a JSON manifest with per-shard CRCs:

    store/
      manifest.json          {"rows", "dim", "descr", "rows_per_shard",
                              "shards": [{"file", "rows", "crc32"}, ...]}
      shard-00000.npy        exactly rows_per_shard rows each ...
      shard-00042.npy        ... except the last, which may be short

Global row ``i`` lives in shard ``i // rows_per_shard`` at local row
``i % rows_per_shard`` — the store IS the id space, so the search tier's
survivor ids address it directly with no translation table.

* :class:`ShardWriter` streams a build's chunks straight to disk —
  incremental appends into an open shard file (never buffering a whole
  shard), so the build's peak host memory stays bounded by the chunk
  size, not the dataset or shard size.
* :class:`ShardedVectorStore` opens shards lazily (``np.load(mmap_mode=
  "r")`` on first touch) and gathers arbitrary row sets grouped by
  shard.  Dense-ish runs go through :func:`raft_tpu.io.native`'s
  threaded pread into a pooled staging buffer
  (:class:`~raft_tpu.core.host_memory.HostBufferPool`, fixed
  ``fetch_batch``-row key so the hot loop allocates nothing after
  warmup); sparse runs fall back to mmap fancy-indexing, which is also
  the complete pure-NumPy path when the native library is absent.
"""

from __future__ import annotations

import io as _io
import json
import os
import time
from typing import List, Optional, Tuple

import numpy as np

from . import native
from ..core.errors import expects

_MANIFEST = "manifest.json"
_FORMAT = "raft_tpu.shards/v1"

#: bounded retries for transient gather read failures (EINTR, EIO, short
#: reads surfacing as OSError) before the error propagates
_READ_RETRIES = 3


def _retry_counter():
    from ..obs.metrics import registry

    return registry().counter(
        "raft_ooc_shard_read_retries_total",
        "transient shard read failures retried (EINTR / EIO / short read)")


def _shard_name(i: int) -> str:
    return f"shard-{i:05d}.npy"


def _npy_header_bytes(shape: Tuple[int, ...], dtype) -> bytes:
    """The exact v1 .npy header for (shape, dtype) — what np.save would
    write.  Used to stream shard bytes behind a pre-written header."""
    from numpy.lib import format as npfmt

    bio = _io.BytesIO()
    npfmt.write_array_header_1_0(bio, {
        "descr": npfmt.dtype_to_descr(np.dtype(dtype)),
        "fortran_order": False,
        "shape": tuple(int(s) for s in shape),
    })
    return bio.getvalue()


def _npy_data_offset(path: str) -> int:
    """Byte offset of the data payload in a .npy file (header-aware;
    native fast path with a pure-NumPy fallback)."""
    if native.available():
        hdr = native.npy_header(path)
        if hdr is not None:
            return int(hdr[3])
    from numpy.lib import format as npfmt

    with open(path, "rb") as f:
        version = npfmt.read_magic(f)
        npfmt._check_version(version)
        npfmt._read_array_header(f, version)
        return f.tell()


class ShardWriter:
    """Streaming writer: ``append()`` arbitrary row chunks, ``close()``
    publishes the manifest.  Rows are written incrementally into the
    open shard file (header first, payload streamed), so peak memory is
    one append chunk — a build can stream a 100M-row dataset through
    ``chunk_rows``-sized pieces without ever holding a shard.

    Every non-final shard has exactly ``rows_per_shard`` rows.  The open
    shard's header is written for the full shape up front; if the final
    shard comes up short, the header is rewritten in place for the real
    row count (same byte length for any row count — numpy pads v1
    headers to a fixed 64-byte boundary — with a full rewrite fallback
    if that ever fails to hold).
    """

    def __init__(self, path: str, dim: int, dtype, rows_per_shard: int):
        expects(int(dim) > 0, "ShardWriter: dim must be positive")
        expects(int(rows_per_shard) > 0,
                "ShardWriter: rows_per_shard must be positive")
        self.path = os.fspath(path)
        self.dim = int(dim)
        self.dtype = np.dtype(dtype)
        self.rows_per_shard = int(rows_per_shard)
        self.rows = 0
        self._shards: List[dict] = []
        self._f = None          # open shard file handle
        self._shard_rows = 0    # rows written into the open shard
        self._header_len = 0
        self._closed = False
        os.makedirs(self.path, exist_ok=True)

    # -- internals ---------------------------------------------------

    def _open_shard(self) -> None:
        name = _shard_name(len(self._shards))
        self._f = open(os.path.join(self.path, name), "wb")
        header = _npy_header_bytes((self.rows_per_shard, self.dim),
                                   self.dtype)
        self._f.write(header)
        self._header_len = len(header)
        self._shard_rows = 0

    def _close_shard(self) -> None:
        from ..core.serialize import checksum_file

        name = _shard_name(len(self._shards))
        full = os.path.join(self.path, name)
        if self._shard_rows != self.rows_per_shard:
            header = _npy_header_bytes((self._shard_rows, self.dim),
                                       self.dtype)
            if len(header) == self._header_len:
                self._f.seek(0)
                self._f.write(header)
            else:  # pragma: no cover - numpy header padding makes this rare
                self._f.flush()
                self._f.close()
                data = np.fromfile(
                    full, dtype=self.dtype, offset=self._header_len,
                ).reshape(self._shard_rows, self.dim)
                self._f = open(full, "wb")
                self._f.write(header)
                data.tofile(self._f)
        self._f.flush()
        os.fsync(self._f.fileno())
        self._f.close()
        self._f = None
        self._shards.append({
            "file": name,
            "rows": int(self._shard_rows),
            "crc32": checksum_file(full),
        })

    # -- public API --------------------------------------------------

    def append(self, rows) -> None:
        """Append ``rows: [r, dim]`` (host array) to the store."""
        expects(not self._closed, "ShardWriter: append after close")
        rows = np.ascontiguousarray(rows, dtype=self.dtype)
        expects(rows.ndim == 2 and rows.shape[1] == self.dim,
                f"ShardWriter: expected [r, {self.dim}] rows, "
                f"got {rows.shape}")
        lo = 0
        while lo < rows.shape[0]:
            if self._f is None:
                self._open_shard()
            room = self.rows_per_shard - self._shard_rows
            take = min(room, rows.shape[0] - lo)
            self._f.write(rows[lo:lo + take].tobytes())
            self._shard_rows += take
            self.rows += take
            lo += take
            if self._shard_rows == self.rows_per_shard:
                self._close_shard()

    def close(self) -> "ShardedVectorStore":
        """Finish the open shard, publish ``manifest.json`` atomically,
        and return the opened store."""
        from ..core.serialize import fsync_dir, write_text_atomic

        expects(not self._closed, "ShardWriter: close called twice")
        self._closed = True
        if self._f is not None:
            self._close_shard()
        manifest = {
            "format": _FORMAT,
            "rows": int(self.rows),
            "dim": int(self.dim),
            "descr": np.lib.format.dtype_to_descr(self.dtype),
            "rows_per_shard": int(self.rows_per_shard),
            "shards": self._shards,
        }
        write_text_atomic(os.path.join(self.path, _MANIFEST),
                          json.dumps(manifest, indent=1))
        fsync_dir(self.path)
        return ShardedVectorStore.open(self.path)


class ShardedVectorStore:
    """Read side: lazy per-shard mmaps + grouped gather.

    ``open()`` reads only the manifest — a shard's ``np.load(mmap_mode=
    "r")`` happens on first touch, so opening a TB-scale store is O(1)
    and search only maps the shards its survivors actually hit.
    """

    def __init__(self, path: str, manifest: dict, *,
                 verify_on_gather: bool = False):
        self.path = os.fspath(path)
        self._m = manifest
        n = len(manifest["shards"])
        self._maps: List[Optional[np.memmap]] = [None] * n
        self._offsets: List[Optional[int]] = [None] * n
        self.verify_on_gather = bool(verify_on_gather)
        self._verified = [False] * n

    # -- lifecycle ---------------------------------------------------

    @classmethod
    def open(cls, path: str, *,
             verify_on_gather: Optional[bool] = None) -> "ShardedVectorStore":
        """Open a store.  ``verify_on_gather=True`` (or env
        ``RAFT_TPU_SHARD_VERIFY=1``) CRC-checks each shard against the
        manifest on its first read — bit-rot surfaces as a loud
        :class:`~raft_tpu.core.serialize.CorruptArtifact` at the gather
        that would have served it, instead of as silently wrong
        reranks."""
        path = os.fspath(path)
        mf = os.path.join(path, _MANIFEST)
        expects(os.path.exists(mf),
                f"ShardedVectorStore: no {_MANIFEST} under {path!r}")
        with open(mf) as f:
            manifest = json.load(f)
        expects(manifest.get("format") == _FORMAT,
                f"ShardedVectorStore: unrecognised manifest format "
                f"{manifest.get('format')!r}")
        if verify_on_gather is None:
            verify_on_gather = \
                os.environ.get("RAFT_TPU_SHARD_VERIFY", "0") == "1"
        return cls(path, manifest, verify_on_gather=bool(verify_on_gather))

    # -- shape/metadata ----------------------------------------------

    @property
    def rows(self) -> int:
        return int(self._m["rows"])

    @property
    def dim(self) -> int:
        return int(self._m["dim"])

    @property
    def dtype(self) -> np.dtype:
        return np.dtype(self._m["descr"])

    @property
    def rows_per_shard(self) -> int:
        return int(self._m["rows_per_shard"])

    @property
    def row_bytes(self) -> int:
        return self.dim * self.dtype.itemsize

    @property
    def nbytes(self) -> int:
        """Host-side bytes of the full-precision rows (the slab the
        out-of-core tier keeps OFF the device)."""
        return self.rows * self.row_bytes

    @property
    def n_shards(self) -> int:
        return len(self._m["shards"])

    def __len__(self) -> int:
        return self.rows

    # -- reads -------------------------------------------------------

    def _shard_path(self, s: int) -> str:
        return os.path.join(self.path, self._m["shards"][s]["file"])

    def _shard_map(self, s: int) -> np.memmap:
        if self._maps[s] is None:
            self._maps[s] = np.load(self._shard_path(s), mmap_mode="r")
        return self._maps[s]

    def _shard_offset(self, s: int) -> int:
        if self._offsets[s] is None:
            self._offsets[s] = _npy_data_offset(self._shard_path(s))
        return self._offsets[s]

    def _check_shard(self, s: int) -> None:
        """First-touch CRC verify (``verify_on_gather`` mode only)."""
        if not self.verify_on_gather or self._verified[s]:
            return
        from ..core.serialize import CorruptArtifact, checksum_file

        entry = self._m["shards"][s]
        want = entry.get("crc32")
        got = checksum_file(self._shard_path(s))
        if want is not None and got is not None and got != want:
            raise CorruptArtifact(
                f"shard {entry['file']} checksum mismatch "
                f"({got} != manifest {want}) — refusing to serve "
                "corrupt rows")
        self._verified[s] = True

    def _read_with_retry(self, what: str, fn):
        """Run ``fn`` with bounded retry on transient OSErrors (EINTR /
        EIO / short reads).  Each retry counts toward the global
        ``raft_ooc_shard_read_retries_total``; exhausted retries
        propagate — the OOC tier degrades loudly, never silently."""
        delay_s = 0.001
        for attempt in range(_READ_RETRIES + 1):
            try:
                return fn()
            except OSError:
                if attempt >= _READ_RETRIES:
                    raise
                _retry_counter().inc()
                from ..obs import spans as obs_spans

                obs_spans.recorder().event("ooc.shard_read_retry",
                                           what=what, attempt=attempt + 1)
                time.sleep(delay_s)
                delay_s *= 2

    def read_rows(self, lo: int, hi: int, out: Optional[np.ndarray] = None,
                  *, threads: int = 8) -> np.ndarray:
        """Dense read of global rows [lo, hi) (native pread when
        available, mmap copy otherwise)."""
        expects(0 <= lo <= hi <= self.rows,
                f"read_rows: [{lo}, {hi}) out of range for {self.rows} rows")
        if out is None:
            out = np.empty((hi - lo, self.dim), self.dtype)
        expects(out.shape == (hi - lo, self.dim) and out.dtype == self.dtype,
                "read_rows: out buffer shape/dtype mismatch")
        rps = self.rows_per_shard
        pos = 0
        while lo < hi:
            s, local = lo // rps, lo % rps
            take = min(hi - lo, rps - local)
            dst = out[pos:pos + take]
            self._check_shard(s)

            def _read(s=s, local=local, take=take, dst=dst):
                done = False
                if native.available() and dst.flags.c_contiguous:
                    off = self._shard_offset(s) + local * self.row_bytes
                    done = native.pread_dense_into(self._shard_path(s), off,
                                                   dst, threads=threads)
                if not done:
                    np.copyto(dst, self._shard_map(s)[local:local + take])

            self._read_with_retry(f"read_rows:shard{s}", _read)
            lo += take
            pos += take
        return out

    def gather(self, ids, out: Optional[np.ndarray] = None, *,
               fetch_batch: int = 8192, threads: int = 8,
               pool=None) -> np.ndarray:
        """Gather rows for ``ids`` (any shape; clipped to the valid row
        range, so sentinel ``-1`` ids read row 0 — callers mask those
        lanes downstream) into ``out: [ids.size, dim]``.

        Requests are sorted and grouped by shard; within a shard,
        ``fetch_batch``-row windows that are dense enough (requested
        rows ≥ span/4) are fetched with one threaded pread into a pooled
        staging buffer, everything else fancy-indexes the shard's mmap.
        Staging buffers are keyed by the fixed ``(fetch_batch, dim)``
        shape, so steady-state gathers allocate nothing.
        """
        ids_flat = np.asarray(ids).reshape(-1)
        expects(ids_flat.dtype.kind in "iu",
                "gather: ids must be an integer array")
        n = ids_flat.size
        if out is None:
            out = np.empty((n, self.dim), self.dtype)
        expects(out.shape == (n, self.dim) and out.dtype == self.dtype,
                f"gather: out must be [{n}, {self.dim}] {self.dtype}, "
                f"got {out.shape} {out.dtype}")
        if n == 0:
            return out
        clipped = np.clip(ids_flat, 0, self.rows - 1).astype(np.int64)
        order = np.argsort(clipped, kind="stable")
        sorted_ids = clipped[order]
        rps = self.rows_per_shard
        use_native = native.available()
        if pool is None:
            from ..core.host_memory import default_host_pool

            pool = default_host_pool()
        i = 0
        while i < n:
            base = sorted_ids[i]
            s = int(base // rps)
            shard_rows = int(self._m["shards"][s]["rows"])
            shard_end = s * rps + shard_rows
            # all ids in one fetch window, within this shard
            win_end = min(base + fetch_batch, shard_end)
            j = int(np.searchsorted(sorted_ids, win_end, side="left"))
            window = sorted_ids[i:j] - s * rps
            pos = order[i:j]
            span = int(window[-1] - window[0]) + 1
            self._check_shard(s)

            def _fetch(s=s, window=window, pos=pos, span=span):
                if use_native and 4 * (j - i) >= span:
                    # dense-ish: one threaded pread of the covering span,
                    # then scatter from the pooled staging buffer
                    with pool.borrow((fetch_batch, self.dim),
                                     self.dtype) as buf:
                        dst = buf[:span]
                        off = (self._shard_offset(s)
                               + int(window[0]) * self.row_bytes)
                        if native.pread_dense_into(self._shard_path(s), off,
                                                   dst, threads=threads):
                            out[pos] = dst[window - window[0]]
                        else:  # native raced away; mmap fallback
                            out[pos] = self._shard_map(s)[window]
                else:
                    out[pos] = self._shard_map(s)[window]

            self._read_with_retry(f"gather:shard{s}", _fetch)
            i = j
        return out

    # -- integrity ---------------------------------------------------

    def verify(self) -> List[str]:
        """Re-checksum every shard against the manifest; returns a list
        of problems (empty = intact)."""
        from ..core.serialize import checksum_file

        problems = []
        total = 0
        for s, entry in enumerate(self._m["shards"]):
            path = self._shard_path(s)
            if not os.path.exists(path):
                problems.append(f"missing shard {entry['file']}")
                continue
            total += int(entry["rows"])
            want = entry.get("crc32")
            got = checksum_file(path)
            if want is not None and got is not None and got != want:
                problems.append(
                    f"checksum mismatch for {entry['file']}: "
                    f"{got} != {want}")
        if total != self.rows:
            problems.append(
                f"manifest rows {self.rows} != shard total {total}")
        return problems


def write_store(path: str, data, *, rows_per_shard: int = 1 << 20,
                chunk_rows: int = 1 << 16) -> ShardedVectorStore:
    """One-shot convenience: stream ``data: [n, d]`` into a new store at
    ``path`` in ``chunk_rows`` pieces (bounded peak memory for mmap /
    lazy sources)."""
    data_shape = data.shape
    expects(len(data_shape) == 2, "write_store: data must be [n, d]")
    w = ShardWriter(path, data_shape[1], np.asarray(data[:1]).dtype,
                    rows_per_shard)
    for lo in range(0, data_shape[0], chunk_rows):
        w.append(np.asarray(data[lo:lo + chunk_rows]))
    return w.close()


__all__ = ["ShardWriter", "ShardedVectorStore", "write_store"]
