"""One blocked-scan core for every neighbors engine.

The probe-blocked IVF engines (PR 3), the frontier-blocked CAGRA engine
(PR 5), and the tiled brute-force scan all share one shape:

    slab gather → batch-dim distance einsum → select_k(sorted=False) fold
    per block → ONE ranked selection at exit

but until this module each engine carried its own copy of the fold/carry
boilerplate, so there was no single place to land a fused kernel.  This
module owns the contract:

* :func:`slab_dots` — the batch-dim scoring einsum with the **pinned
  per-candidate accumulation shape**: the block axis stays a *batch*
  dimension (``"qbcd,qbd->qbc"``), so the inner ``[cap, d]·[d]`` f32
  accumulation order is identical for every block size.  Folding the
  block axis into the candidate axis would retile the reduction and break
  the PR 3/5 bit-invariance contract (blocked results bit-identical to
  the per-item reference engines for ANY block size).
* :func:`fold_topk` / :func:`fold_topk_payload` — the
  ``select_k(sorted=False)`` fold, without and with payload lanes
  (CAGRA's explored flags, the fused path's slab pointers).
* :func:`scan_topk` — the ``scan(carry, slab) -> carry`` driver: carry
  init, per-block fold, ranked exit selection.
* :func:`scan_topk_fused` — the same contract with the distance tile and
  an approximate partial top-k fused into ONE Pallas kernel
  (``ops/pallas/fused_scan.py``, TPU-KNN's PartialReduce scheme), plus an
  exact re-score of the k finalists so reported distances stay f32-exact.
  Approximate-partial: the candidate *set* is recall-gated, not
  bit-pinned (a true neighbor is shed only on a ≥3-way lane-bucket
  collision within one slab block).

Quantized-scan sub-API
----------------------

The scan core also owns the *quantized* scoring tier — the packed-code
helpers every compressed engine shares, promoted here from private
``ivf_pq``/``_packing`` homes so 4-bit PQ codes and 1-bit RaBitQ codes
go through one documented seam:

* :func:`int8_tier_eligible` — the ONE eligibility rule for the exact
  single-pass bf16 MXU tier over 8-bit operands.
* :func:`exact_gathered_dots` — the tiered gathered-dots einsum itself.
* :func:`pack_codes4` / :func:`unpack_codes4` — 4-bit sub-quantizer
  codes packed two-per-byte (IVF-PQ's storage tier; HBM reads halve,
  codes unpack AFTER the gather).
* :func:`pack_sign_bits` / :func:`unpack_sign_bits` — 1-bit sign codes
  packed eight-per-byte (IVF-RaBitQ's storage tier; HBM reads shrink
  8× vs int8, 32× vs f32).
* :func:`packed_sign_dots` — the packed-binary scoring path:
  ``⟨sign(r), q8⟩`` computed as ``2·⟨bits, q8⟩ − Σq8`` with the bits
  unpacked post-gather and the dot taken on the int8 MXU tier
  (popcount-as-int8-einsum; exact, see the function doc).
  :func:`slab_dots` dispatches here via ``packed_sign=True``.

:func:`exact_gathered_dots` and :func:`int8_tier_eligible` originally
moved here from ``neighbors/_packing.py``: the scoring-tier rule is
owned by the scan core, and ``ops`` must not import from ``neighbors``.
"""

from __future__ import annotations

import json
import os
from functools import lru_cache
from typing import Callable, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

__all__ = ["int8_tier_eligible", "exact_gathered_dots", "slab_dots",
           "pack_codes4", "unpack_codes4", "pack_sign_bits",
           "unpack_sign_bits", "packed_sign_dots",
           "row_sq_norms",
           "fold_topk", "fold_topk_payload", "topk_carry", "ranked_finish",
           "scan_topk", "scan_topk_fused", "list_slab_ptr", "l2_rescorer",
           "resolve_scan_kernel", "scan_kernel_sha"]


def int8_tier_eligible(a, b, d: int) -> bool:
    """True when the single-pass bf16 scoring tier is EXACT for a·b dots
    over contraction length ``d`` — the ONE home of the eligibility rule
    (every call site must agree or a raw integer query silently reverts a
    path to the 6× slower HIGHEST einsum).

    Exactness needs every f32 partial sum to stay an exact integer
    (< 2²⁴): uint8 products reach 255² ⇒ d ≤ 256; int8 reach 128² ⇒
    d ≤ 1024.  Beyond the bound integer dot gaps of 1 could round away —
    HIGHEST was exact there, so the tier must not regress it."""
    kinds = (jnp.uint8, jnp.int8)
    if a.dtype not in kinds or b.dtype not in kinds:
        return False
    lim = 256 if jnp.uint8 in (a.dtype, b.dtype) else 1024
    return d <= lim


def exact_gathered_dots(subscripts: str, vecs, q):
    """Query·candidate dots for gathered rows — the shared scoring einsum
    of the IVF-Flat probe scan, the CAGRA beam step, and the brute-force
    exact/refine paths.

    Eligible 8-bit corpora (:func:`int8_tier_eligible`) take ONE bf16 MXU
    pass: the values are bf16-exact and the MXU accumulates products in
    f32, so the result matches the f32 path exactly at ~6× the MXU rate of
    ``Precision.HIGHEST``.  Everything else keeps the bf16x6 HIGHEST
    passes — a single pass would genuinely lose ranking precision there."""
    if int8_tier_eligible(vecs, q, int(vecs.shape[-1])):
        return jnp.einsum(subscripts, vecs.astype(jnp.bfloat16),
                          q.astype(jnp.bfloat16),
                          preferred_element_type=jnp.float32)
    return jnp.einsum(subscripts, vecs, q,
                      preferred_element_type=jnp.float32,
                      precision=jax.lax.Precision.HIGHEST)


def pack_codes4(codes):
    """Pack 4-bit sub-quantizer codes two-per-byte along the last axis:
    ``[..., m] uint8 (values < 16) → [..., ⌈m/2⌉] uint8`` with the even
    sub-quantizer in the low nibble.  Odd ``m`` pads one zero nibble —
    :func:`unpack_codes4` takes ``m`` to strip it.  The IVF-PQ packed
    storage tier (``ivf_pq.with_packed_codes``) stores this form; codes
    unpack AFTER the probe gather so HBM reads move half the bytes."""
    m = codes.shape[-1]
    if m % 2:
        codes = jnp.pad(codes, [(0, 0)] * (codes.ndim - 1) + [(0, 1)])
    return (codes[..., 0::2] | (codes[..., 1::2] << 4)).astype(jnp.uint8)


def unpack_codes4(packed, m: int):
    """Inverse of :func:`pack_codes4`: ``[..., ⌈m/2⌉] → [..., m] uint8``
    (low nibble first, pad nibble dropped)."""
    lo = packed & 0xF
    hi = packed >> 4
    inter = jnp.stack([lo, hi], axis=-1).reshape(*packed.shape[:-1], -1)
    return inter[..., :m].astype(jnp.uint8)


def pack_sign_bits(x):
    """Sign codes packed eight-per-byte along the last axis:
    ``[..., d] → [..., ⌈d/8⌉] uint8`` with bit ``i % 8`` of byte
    ``i // 8`` set iff ``x[..., i] >= 0`` (little bit order).  The
    IVF-RaBitQ storage tier: one byte stores eight dimensions, so the
    estimator scan's HBM traffic is 32× below the f32 slab's."""
    bits = (x >= 0).astype(jnp.uint8)
    return jnp.packbits(bits, axis=-1, bitorder="little")


def unpack_sign_bits(packed, d: int):
    """Inverse of :func:`pack_sign_bits`: ``[..., ⌈d/8⌉] uint8 →
    [..., d]`` int8 in {0, 1} (pad bits dropped).  int8 output feeds the
    int8 MXU tier of :func:`exact_gathered_dots` directly."""
    return jnp.unpackbits(packed, axis=-1, count=d,
                          bitorder="little").astype(jnp.int8)


def packed_sign_dots(packed, q8):
    """Packed-binary slab scoring: ``[nq, B, C, ⌈d/8⌉] uint8 ·
    [nq, d] int8 → [nq, B, C] f32`` = ``⟨sign(r), q8⟩`` where
    ``sign(r) ∈ {−1, +1}`` is the stored code and ``q8`` the int8-
    quantized rotated query.

    The popcount-as-int8-einsum formulation: with bits ``b ∈ {0, 1}``,
    ``⟨2b − 1, q8⟩ = 2·⟨b, q8⟩ − Σq8``, so the scan unpacks the gathered
    bytes to {0, 1} int8 **after** the gather (HBM moved only packed
    bytes) and takes ONE bf16 MXU pass via :func:`exact_gathered_dots` —
    exact, because every product is an integer ≤ 127 and every partial
    sum stays < 2²⁴.  The block axis ``B`` stays a batch dimension
    (:func:`slab_dots` pinned-shape contract)."""
    nq, b = packed.shape[0], packed.shape[1]
    d = q8.shape[-1]
    bits = unpack_sign_bits(packed, d)
    qb = jnp.broadcast_to(q8[:, None, :], (nq, b, d))
    dots = exact_gathered_dots("qbcd,qbd->qbc", bits, qb)
    q8sum = jnp.sum(q8.astype(jnp.float32), axis=-1)
    return 2.0 * dots - q8sum[:, None, None]


def slab_dots(vecs, q, *, exact: bool = True, packed_sign: bool = False):
    """Score one gathered slab: ``[nq, B, C, d] · [nq, d] → [nq, B, C]``.

    This is THE blocked-scan distance einsum — the single insertion point
    every engine routes through — with the block axis ``B`` pinned as a
    batch dimension (bit-invariance across block sizes, see module doc).

    ``exact=True`` (IVF-Flat, CAGRA, brute-force refine) dispatches via
    :func:`exact_gathered_dots`; ``exact=False`` is the IVF-PQ recon
    tier's contract — ONE bf16 MXU pass with f32 accumulation over
    already-lossy reconstructions, where HIGHEST would triple the cost for
    precision the codes don't carry.  ``packed_sign=True`` is the 1-bit
    scoring path: ``vecs`` holds packed sign bytes and ``q`` the int8
    rotated query — dispatches to :func:`packed_sign_dots` (exact
    ``⟨sign, q8⟩``; the estimator algebra lives with the engine)."""
    if packed_sign:
        return packed_sign_dots(vecs, q)
    nq, b = vecs.shape[0], vecs.shape[1]
    qb = jnp.broadcast_to(q[:, None, :], (nq, b, q.shape[-1]))
    if exact:
        return exact_gathered_dots("qbcd,qbd->qbc", vecs, qb)
    return jnp.einsum("qbcd,qbd->qbc", vecs, qb,
                      preferred_element_type=jnp.float32)


def row_sq_norms(qf):
    """Squared L2 norms over the last axis ``[..., d] → [...]`` as a
    batched dot contraction, NOT ``jnp.sum(qf * qf, axis=-1)``.

    These norms land in every served distance (``qn + yn − 2·dots``), so
    the fleet fan-out's bit-identity contract needs them to round the
    same way in the single-device executable and the shard_map'd SPMD
    executable.  Elementwise IEEE ops are deterministic per element, and
    a ``dot_general`` contraction's accumulation order is fixed by its
    shape — but a mul+``reduce`` lowering's association order is a
    per-module codegen choice, and the two programs were observed to
    round query norms one ulp apart on CPU.  Routing every norm that
    reaches a reported distance through the same dot machinery as the
    candidate scores pins it."""
    flat = qf.reshape(-1, qf.shape[-1])
    out = jax.lax.dot_general(
        flat, flat, (((1,), (1,)), ((0,), (0,))),
        precision=jax.lax.Precision.HIGHEST)
    return out.reshape(qf.shape[:-1])


def fold_topk(best_val, best_idx, tile_val, tile_idx, k: int, *,
              sorted: bool = True):
    """Merge a new candidate block into the running (m, k) best buffers via
    ``matrix.select_k`` — one selection primitive owns all top-k tuning.

    ``sorted=False`` keeps the carry an unordered top-k set (exact values
    and ids, unspecified row order) — the right form for intermediate scan
    carries, where only the FINAL merge needs ranked output."""
    from ..matrix.select_k import select_k

    vals = jnp.concatenate([best_val, tile_val], axis=1)
    idxs = jnp.concatenate([best_idx, tile_idx], axis=1)
    return select_k(vals, k, in_idx=idxs, select_min=True, sorted=sorted)


def fold_topk_payload(best_val, best_idx, best_payload: Sequence,
                      tile_val, tile_idx, tile_payload: Sequence, k: int):
    """:func:`fold_topk` with payload lanes riding the selection (CAGRA's
    explored flags, the fused path's slab pointers, build's counts).

    Selects by *concat position*, then gathers ids and every payload lane
    through the winning positions — bit-identical to the direct
    ``in_idx=ids`` fold (``select_k`` picks positions internally either
    way), which is what lets the payload-free engines share the same
    selection primitive.  Unsorted carry form (``sorted=False``)."""
    from ..matrix.select_k import select_k

    cat_val = jnp.concatenate([best_val, tile_val], axis=1)
    cat_idx = jnp.concatenate([best_idx, tile_idx], axis=1)
    cpos = jnp.tile(jnp.arange(cat_val.shape[1], dtype=jnp.int32)[None, :],
                    (cat_val.shape[0], 1))
    mv, mpos = select_k(cat_val, k, in_idx=cpos, select_min=True,
                        sorted=False)
    mi = jnp.take_along_axis(cat_idx, mpos, axis=1)
    out = tuple(
        jnp.take_along_axis(jnp.concatenate([bp, tp], axis=1), mpos, axis=1)
        for bp, tp in zip(best_payload, tile_payload))
    return mv, mi, out


def topk_carry(nq: int, k: int, *, id_fill: int = -1):
    """Fresh (values, ids) scan carry: +inf distances, ``id_fill`` ids
    (brute-force historically fills 0, the IVF engines −1 — preserved so
    the refactor stays bit-identical in the ids of sub-k result rows)."""
    return (jnp.full((nq, k), jnp.inf, jnp.float32),
            jnp.full((nq, k), id_fill, jnp.int32))


def ranked_finish(vals, ids, k: int):
    """The ONE ranked selection at scan exit: intermediate carries are
    unordered top-k sets; rank once here."""
    from ..matrix.select_k import select_k

    return select_k(vals, k, in_idx=ids, select_min=True)


def scan_topk(score_step: Callable, xs, nq: int, k: int, *,
              id_fill: int = -1) -> Tuple[jax.Array, jax.Array]:
    """The shared blocked-scan driver (XLA path).

    ``score_step(slab_inputs) -> (dist [nq, L], ids [nq, L])`` owns the
    engine-specific slab gather + scoring + validity masking (invalid
    lanes must carry ``+inf``); this driver owns the carry init, the
    per-block :func:`fold_topk` (unsorted), and the ranked exit — the
    ``scan(carry, slab) -> carry`` contract in one place."""

    def step(carry, inp):
        bv, bi = carry
        dist, ids = score_step(inp)
        return fold_topk(bv, bi, dist, ids, k, sorted=False), None

    (bv, bi), _ = jax.lax.scan(step, topk_carry(nq, k, id_fill=id_fill), xs)
    return ranked_finish(bv, bi, k)


def scan_topk_fused(q, slab_step: Callable, xs, rescore: Callable,
                    nq: int, k: int, *, shortlist_block: int = 512,
                    interpret: Optional[bool] = None
                    ) -> Tuple[jax.Array, jax.Array]:
    """Fused-kernel blocked scan: each block's distance tile and an
    approximate partial top-k run INSIDE one Pallas kernel
    (:func:`raft_tpu.ops.pallas.fused_scan.fused_slab_topk`), so the
    ``[nq, L]`` distance block never materializes in HBM.

    ``slab_step(slab_inputs) -> (vecs [nq, C, d], base [nq, C],
    vids [nq, C], ptr [nq, C])`` gathers the slab and computes the
    surrogate base (``‖y‖²``-like per-candidate offset; invalid lanes
    ``+inf``); the kernel scores ``base − 2·⟨q, vec⟩``.  ``ptr`` is an
    engine-defined storage pointer payload lane carried through the fold
    so ``rescore(ptr [nq, k], vids [nq, k]) -> dist [nq, k]`` can re-gather
    the k finalists and re-score them exactly — reported values match the
    engine's exact metric; only the candidate *set* is approximate
    (recall-gated, not bit-pinned)."""

    def step(carry, inp):
        from .pallas.fused_scan import fused_slab_topk

        bv, bi, bp = carry
        vecs, base, vids, ptr = slab_step(inp)
        sv, spos = fused_slab_topk(vecs, base, q, bn=shortlist_block,
                                   interpret=interpret)
        svids = jnp.take_along_axis(vids, spos, axis=1)
        sptr = jnp.take_along_axis(ptr, spos, axis=1)
        mv, mi, (mp,) = fold_topk_payload(bv, bi, (bp,), sv, svids, (sptr,), k)
        return (mv, mi, mp), None

    bv0, bi0 = topk_carry(nq, k)
    bp0 = jnp.zeros((nq, k), jnp.int32)
    (bv, bi, bp), _ = jax.lax.scan(step, (bv0, bi0, bp0), xs)
    dist = rescore(bp, bi)
    dist = jnp.where(jnp.isfinite(bv) & (bi >= 0), dist, jnp.inf)
    return ranked_finish(dist, bi, k)


def list_slab_ptr(lists, cap: int):
    """Storage pointers for a gathered ``[nq, B]`` list block over a
    ``[L, cap, …]`` slab: flat row ``list·cap + slot``, shaped
    ``[nq, B·cap]`` to match the block's candidate lanes — the payload
    lane :func:`scan_topk_fused` carries so ``rescore`` can re-gather
    finalists from the flattened slab."""
    nq, b = lists.shape
    slot = jnp.arange(cap, dtype=jnp.int32)
    return (lists[:, :, None].astype(jnp.int32) * cap
            + slot[None, None, :]).reshape(nq, b * cap)


def l2_rescorer(data, norms, q, qn, metric: str, *, exact: bool = True,
                clamp: bool = True) -> Callable:
    """Build the ``rescore(ptr, vids)`` closure for an IVF-style fused
    scan: re-gather the k finalist rows from the flattened ``[L·cap, d]``
    slab and re-score them with the engine's exact metric algebra
    (``exact=True`` → :func:`exact_gathered_dots` tiering; ``exact=False``
    → the recon tier's single bf16 MXU pass).  ``clamp`` matches each
    engine's squared-L2 floor convention (IVF-Flat clamps at 0, the recon
    tier does not).

    ``norms=None`` is the stored-norm-free form (the RaBitQ exact-rerank
    tier keeps no norm slab): the squared norms recompute from the
    gathered rows and the algebra runs in ``brute_force``'s accumulation
    order (``qn + yn − 2·dots``, clamped) — f32 addition is not
    associative, and matching the oracle's order is what lets a
    rerank-everything search bit-match ``brute_force.knn``."""
    flat_data = data.reshape(-1, data.shape[-1])
    flat_norms = norms.reshape(-1) if norms is not None else None

    def rescore(ptr, _vids):
        rows = flat_data[ptr]                     # [nq, k, d] finalists
        if exact:
            dots = exact_gathered_dots("qkd,qd->qk", rows, q)
        else:
            dots = jnp.einsum("qkd,qd->qk", rows, q,
                              preferred_element_type=jnp.float32)
        if metric == "inner_product":
            return -dots
        if flat_norms is None:  # brute-force order, see docstring
            rf = rows.astype(jnp.float32)
            yn = row_sq_norms(rf)
            dist = qn[:, None] + yn - 2.0 * dots
        else:
            dist = flat_norms[ptr] - 2.0 * dots + qn[:, None]
        return jnp.maximum(dist, 0.0) if clamp else dist

    return rescore


def scan_kernel_sha() -> str:
    """Hash of the fused-path sources — scopes the tuned scan-kernel table
    (``bench/tune_select_k.py`` writes it, :func:`resolve_scan_kernel`
    rejects a table whose sha no longer matches the kernels it measured)."""
    import hashlib

    root = os.path.dirname(os.path.abspath(__file__))
    h = hashlib.sha256()
    for rel in ("blocked_scan.py", os.path.join("pallas", "fused_scan.py"),
                os.path.join("pallas", "gate.py")):
        with open(os.path.join(root, rel), "rb") as f:
            h.update(f.read())
    return h.hexdigest()[:16]


@lru_cache(maxsize=1)
def _scan_kernel_table():
    """Measured xla-vs-fused table written by the ``bench/tune_select_k.py``
    fused arm.  Canonical name first; a ``.{backend}.json`` suffix holds
    off-TPU measurements.  A table whose ``kernel_sha`` doesn't match the
    current fused-path sources is stale and ignored."""
    base = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "_scan_kernel_table.json")
    cands = [base]
    try:
        cands.append(base.replace(".json", f".{jax.default_backend()}.json"))
    except Exception:  # pragma: no cover - backend probe failure
        pass
    for path in cands:
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            continue
        if doc.get("kernel_sha") != scan_kernel_sha():
            from ..core.logging import default_logger

            default_logger().info(
                "scan-kernel table %s is sha-stale (table %s, sources %s); "
                "auto keeps the XLA path", os.path.basename(path),
                doc.get("kernel_sha"), scan_kernel_sha())
            continue
        return doc.get("entries", {})
    return {}


def resolve_scan_kernel(requested: str, family: str, n_candidates: int,
                        k: int) -> str:
    """Resolve the engine ``scan_kernel`` knob to ``"xla"`` or ``"fused"``.

    ``"auto"`` picks fused only when the Mosaic hardware gate is open
    (validated ``bench/MOSAIC_CHECK.json``, see ``ops/pallas/gate.py``)
    AND the sha-scoped tuned table says fused wins for this
    ``family : candidates-per-block : k`` bucket — off-TPU auto therefore
    always resolves to the XLA path (interpret-mode Pallas is a parity
    tool, not a fast path)."""
    from ..core.errors import expects

    expects(requested in ("auto", "xla", "fused"),
            f"scan_kernel must be auto|xla|fused, got {requested!r}")
    if requested != "auto":
        return requested
    from .pallas.gate import mosaic_gate

    ok, _ = mosaic_gate("fused_scan")
    if not ok:
        return "xla"
    key = f"{family}:{int(n_candidates).bit_length()}:{int(k).bit_length()}"
    return _scan_kernel_table().get(key, "xla")
