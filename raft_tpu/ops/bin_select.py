"""Threshold-refinement top-k ("bin select") — the TPU analog of the
reference's radix select (``matrix/detail/select_radix.cuh``).

The CUDA radix kernel repeatedly histograms the high bits of the keys and
narrows to the bucket containing the k-th element.  The same idea expressed in
XLA-friendly form: iterate a *fixed* number of rounds, each maintaining
per-row scalar bounds ``(lo, hi)`` on the k-th value; bucket values into B
equal-width bins inside the bounds, prefix-sum bucket counts to find the bin
holding rank k, and tighten the bounds.  After the rounds, values below the
lower bound are definitely selected; ties at the boundary are resolved with
one masked ``top_k`` over only the boundary band — avoiding any full-length
sort.  Everything is dense vectorized compare+sum on the VPU with static
shapes.
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp

__all__ = ["bin_select_k"]


@partial(jax.jit, static_argnames=("k", "select_min", "n_bins", "n_rounds",
                                   "sorted"))
def bin_select_k(
    in_val: jax.Array,
    k: int,
    *,
    select_min: bool = True,
    n_bins: int = 32,
    n_rounds: int = 3,
    sorted: bool = True,
) -> Tuple[jax.Array, jax.Array]:
    """Select k smallest/largest per row via iterative bin refinement.

    ``sorted=False`` skips the final ranked ``top_k`` over the boundary
    band: ties are still resolved exactly, but via ``argpartition``, so the
    returned k pairs come back in unspecified order."""
    x = in_val if select_min else -in_val
    x = x.astype(jnp.float32)
    batch, length = x.shape

    # Bounds from FINITE values only: masked/sentinel rows carry +inf
    # (e.g. filtered search), and an inf hi would freeze width at inf so
    # the rounds never tighten — the kernel would silently degrade to a
    # full top_k with three wasted histogram passes.
    finite = jnp.isfinite(x)
    lo = jnp.min(jnp.where(finite, x, jnp.inf), axis=1)   # (batch,)
    hi_f = jnp.max(jnp.where(finite, x, -jnp.inf), axis=1)
    hi = jnp.where(jnp.isfinite(hi_f), hi_f, lo)

    def round_fn(_, carry):
        lo, hi = carry
        width = (hi - lo) / n_bins
        width = jnp.where(width > 0, width, 1.0)
        # bin index of every in-bounds element, clamped; out-of-bounds
        # values (incl. +inf sentinels) are excluded from the histogram so
        # bin counts are exact ranks within [lo, hi]
        inb = x <= hi[:, None]
        b = jnp.clip(((x - lo[:, None]) / width[:, None]).astype(jnp.int32), 0, n_bins - 1)
        onehot = jax.nn.one_hot(b, n_bins, dtype=jnp.int32)          # (batch, len, B)
        counts = jnp.sum(onehot * inb[:, :, None], axis=1)            # (batch, B)
        cum = jnp.cumsum(counts, axis=1)
        # first bin where cumulative count reaches k
        target = jnp.argmax(cum >= k, axis=1)                         # (batch,)
        new_lo = lo + target.astype(jnp.float32) * width
        new_hi = lo + (target.astype(jnp.float32) + 1.0) * width
        # tighten ONLY when the k-th value provably lies within [lo, hi]
        # (fewer than k in-bounds entries means the k-th sits outside —
        # e.g. < k finite values in a masked row)
        found = cum[:, -1] >= k
        return (jnp.where(found, jnp.maximum(lo, new_lo), lo),
                jnp.where(found, jnp.minimum(hi, new_hi), hi))

    lo, hi = jax.lax.fori_loop(0, n_rounds, round_fn, (lo, hi))

    # The band [lo, hi] now contains the k-th value: masking everything above
    # hi to +inf leaves ~k candidates, so top_k runs over a mostly-degenerate
    # key set (cheap) while returning exactly the k smallest originals.
    surrogate = jnp.where(x <= hi[:, None], x, jnp.inf)
    if sorted:
        neg_vals, idx = jax.lax.top_k(-surrogate, k)
        vals = -neg_vals
    else:  # exact selection without the final ordering pass
        idx = jnp.argpartition(surrogate, k - 1, axis=1)[:, :k]
        vals = jnp.take_along_axis(surrogate, idx, axis=1)
    if not select_min:
        vals = -vals
    return vals.astype(in_val.dtype), idx
