"""Hardware dispatch gate shared by every Pallas kernel.

Mosaic lowering is only trusted after ``scripts/mosaic_check.py`` has
validated the kernels on the actual hardware and stamped
``bench/MOSAIC_CHECK.json``.  Before this module, each kernel decided
dispatch with a bare ``jax.default_backend() != "tpu"`` and callers were
expected to pre-check the artifact — which fails exactly in the live
failure mode (BENCH_r04/r05): a wedged TPU tunnel where the platform
probe hangs, or a stale artifact from an older kernel source tree.

The gate centralizes three decisions, each with a *logged reason* AND a
counted event so a fallback is observable instead of silent — every
non-mosaic resolution a TPU caller would care about increments
``raft_pallas_gate_fallback_total{kernel,reason}`` in the process-global
:func:`raft_tpu.obs.registry` (scraped via any server's
``prometheus_text()``) and drops a marker event into the flight
recorder, so fleet dashboards can alert on "replicas silently serving
from stock XLA" without grepping logs:

* :func:`probe_backend` — ``jax.default_backend()`` behind a daemon-thread
  timeout (``RAFT_PLATFORM_PROBE_TIMEOUT`` seconds, default 60).  A wedged
  probe returns ``None`` instead of hanging the dispatch site.
* :func:`mosaic_gate` — is the hardware stamp trustworthy?  Requires a
  readable artifact with ``ok: true``, ``backend: "tpu"``, and a
  ``kernel_sha`` matching the current kernel sources
  (:func:`pallas_kernel_sha`); anything else is *stale*.
* :func:`dispatch_mode` — the per-call-site resolution:
  ``"mosaic"`` (compile for real), ``"interpret"`` (off-TPU parity mode,
  the CPU test mesh), or ``"xla"`` (clean fallback: on-TPU but the gate is
  closed or the probe wedged — kernels must take their stock-XLA path).

``RAFT_MOSAIC_GATE=off`` bypasses the artifact check (backend probe still
decides mosaic-vs-interpret) — ``scripts/mosaic_check.py`` sets it so the
validation run itself can exercise Mosaic before the artifact exists.
"""

from __future__ import annotations

import json
import os
import threading

from ...core import lockdep
from typing import Optional, Tuple

__all__ = ["probe_backend", "mosaic_gate", "dispatch_mode",
           "pallas_kernel_sha", "reset_gate"]

_ARTIFACT = os.path.normpath(os.path.join(
    os.path.dirname(os.path.abspath(__file__)),
    "..", "..", "..", "bench", "MOSAIC_CHECK.json"))

_lock = lockdep.lock("pallas_gate._lock")
_cache: dict = {}    # guarded_by: _lock
_logged: set = set()  # guarded_by: _lock


def reset_gate() -> None:
    """Drop every memoized decision (tests; after re-running the checker)."""
    with _lock:
        _cache.clear()
        _logged.clear()


def _log_once(key: str, msg: str, *args) -> None:
    with _lock:
        if key in _logged:
            return
        _logged.add(key)
    from ...core.logging import default_logger

    default_logger().warning(msg, *args)


#: reason-string prefix -> the label value the fallback counter carries
#: (free-text reasons stay in logs/events; labels must be low-cardinality)
_REASON_CLASSES = (
    ("platform probe", "probe_wedged"),
    ("backend is", "backend_not_tpu"),
    ("missing", "artifact_missing"),
    ("unreadable", "artifact_unreadable"),
    ("stamp, not a", "artifact_not_hardware"),
    ("failed checks", "artifact_failed_checks"),
    ("stale", "artifact_stale"),
)


def _reason_class(reason: str) -> str:
    for needle, cls in _REASON_CLASSES:
        if needle in reason:
            return cls
    return "other"


def _count_fallback(kernel: str, reason: str) -> None:
    """A gate-closed resolution is a *counted event*, not just a log
    line: labelled counter in the global registry + flight-recorder
    marker carrying the full free-text reason."""
    from ...obs.metrics import registry
    from ...obs.spans import recorder

    registry().counter(
        "raft_pallas_gate_fallback_total",
        "Pallas dispatches resolved to stock XLA with the gate closed",
    ).inc(kernel=kernel, reason=_reason_class(reason))
    recorder().event("pallas.gate_fallback", kernel=kernel, reason=reason)


def probe_backend(timeout_s: Optional[float] = None) -> Optional[str]:
    """``jax.default_backend()`` that cannot wedge the caller.

    The first call runs the probe on a daemon thread and joins with a
    timeout; ``None`` means the probe hung or raised (the BENCH_r04/r05
    tunnel wedge) and the process should stay off the device-initializing
    paths.  The verdict — including ``None`` — is memoized: retrying a
    wedged probe at every dispatch would stack up doomed threads."""
    with _lock:
        if "backend" in _cache:
            return _cache["backend"]
    if timeout_s is None:
        timeout_s = float(os.environ.get("RAFT_PLATFORM_PROBE_TIMEOUT", "60"))
    result: dict = {}

    def work():
        try:
            import jax

            result["backend"] = jax.default_backend()
        except Exception as e:  # pragma: no cover - init failure path
            result["error"] = repr(e)

    t = threading.Thread(target=work, daemon=True,
                         name="raft-tpu-platform-probe")
    t.start()
    t.join(timeout_s)
    backend = result.get("backend")
    if backend is None:
        from ...obs.metrics import registry

        registry().counter(
            "raft_pallas_probe_failures_total",
            "platform probes that wedged or raised (BENCH_r04/r05 mode)",
        ).inc(outcome="raised" if "error" in result else "timeout")
        _log_once("probe", "platform probe %s after %.0fs — treating the "
                  "backend as unavailable; Pallas dispatch falls back to "
                  "stock XLA paths",
                  "raised " + result["error"] if "error" in result
                  else "did not return", timeout_s)
    with _lock:
        _cache["backend"] = backend
    return backend


def pallas_kernel_sha() -> str:
    """Hash of the kernel sources the hardware stamp vouches for — an
    artifact whose sha differs was validated against different code and
    counts as stale."""
    import hashlib

    here = os.path.dirname(os.path.abspath(__file__))
    h = hashlib.sha256()
    for rel in ("select_k.py", "fused_l2_topk.py", "fused_scan.py",
                os.path.join("..", "bin_select.py")):
        try:
            with open(os.path.join(here, rel), "rb") as f:
                h.update(f.read())
        except OSError:
            h.update(b"<absent>")
    return h.hexdigest()[:16]


def mosaic_gate(kernel: str = "*") -> Tuple[bool, str]:
    """Is Mosaic dispatch trustworthy here?  Returns ``(ok, reason)``.

    ``ok`` requires: backend probe returned ``"tpu"``, and
    ``bench/MOSAIC_CHECK.json`` is a hardware stamp (``backend: "tpu"``)
    with ``ok: true`` and a ``kernel_sha`` matching the current sources.
    The reason string names the first failed condition."""
    if os.environ.get("RAFT_MOSAIC_GATE") == "off":
        return True, "gate bypassed (RAFT_MOSAIC_GATE=off)"
    backend = probe_backend()
    if backend is None:
        return False, "platform probe wedged or failed"
    if backend != "tpu":
        return False, f"backend is {backend!r}, not tpu"
    try:
        with open(_ARTIFACT) as f:
            doc = json.load(f)
    except OSError:
        return False, f"{os.path.basename(_ARTIFACT)} missing — run " \
                      f"scripts/mosaic_check.py on this host"
    except ValueError:
        return False, f"{os.path.basename(_ARTIFACT)} unreadable"
    if doc.get("backend") != "tpu":
        return False, f"artifact is a {doc.get('backend')!r} stamp, not a " \
                      f"hardware validation"
    if not doc.get("ok"):
        return False, "artifact records failed checks"
    sha = pallas_kernel_sha()
    if doc.get("kernel_sha") != sha:
        return False, f"artifact kernel_sha {doc.get('kernel_sha')} is " \
                      f"stale (sources are {sha})"
    return True, "validated"


def dispatch_mode(kernel: str) -> str:
    """Resolve one kernel call site to ``"mosaic"`` / ``"interpret"`` /
    ``"xla"``, memoized per kernel name, logging the reason once on any
    non-mosaic resolution that a TPU caller would care about."""
    with _lock:
        if kernel in _cache:
            return _cache[kernel]
    backend = probe_backend()
    if backend is None:
        mode = "xla"  # reason already logged by the probe
        _count_fallback(kernel, "platform probe wedged or failed")
    elif backend != "tpu":
        mode = "interpret"   # off-TPU parity mode is normal, not a fallback
    else:
        ok, reason = mosaic_gate(kernel)
        mode = "mosaic" if ok else "xla"
        if not ok:
            _count_fallback(kernel, reason)
            _log_once(f"gate:{kernel}",
                      "Mosaic gate closed for %s (%s); falling back to the "
                      "stock XLA path", kernel, reason)
    with _lock:
        _cache[kernel] = mode
    return mode
