"""Exact batched top-k as a Pallas kernel — the TPU replacement for the
reference's warpsort select (``matrix/detail/select_warpsort.cuh``).

The CUDA kernel keeps per-warp bitonic priority queues in registers and
merges them at the end.  Registers/warps don't transplant to TPU; the
VMEM-native formulation used here:

* the input row is streamed block-by-block through VMEM (grid over
  ``(row_blocks, col_blocks)``, columns innermost),
* each step concatenates the running ``(BM, KPAD)`` best buffer with the
  new ``(BM, BN)`` block and runs **k min-extraction passes** (min +
  argmin + mask-out) entirely in VMEM — ``2k`` VPU passes per element
  instead of a full sort, which beats ``lax.top_k``'s O(n log n) sort for
  small k over long rows,
* the best buffer lives in the *output* refs, revisited across the column
  grid (Pallas TPU executes the innermost grid dimension sequentially, so
  accumulation in out-refs is well-defined).

Exact (not approximate): every element is compared against the running
k-th best.  Output arrives sorted ascending by construction.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

try:  # pre-0.6 runtimes carry the old TPUCompilerParams spelling
    _CompilerParams = pltpu.CompilerParams
except AttributeError:
    _CompilerParams = pltpu.TPUCompilerParams

__all__ = ["select_k_pallas"]

_LANES = 128  # TPU lane width: pad k to a full lane tile


def _kernel(x_ref, val_ref, idx_ref, *, k: int, kpad: int, bn: int, length: int):
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        val_ref[:] = jnp.full_like(val_ref, jnp.inf)
        idx_ref[:] = jnp.full_like(idx_ref, -1)

    bm = x_ref.shape[0]
    block = x_ref[:].astype(jnp.float32)                      # (BM, BN)
    col = j * bn + jax.lax.broadcasted_iota(jnp.int32, (bm, bn), 1)
    # mask padded tail columns so they never win a min
    block = jnp.where(col < length, block, jnp.inf)

    cat_val = jnp.concatenate([val_ref[:], block], axis=1)    # (BM, KPAD+BN)
    cat_idx = jnp.concatenate([idx_ref[:], col], axis=1)
    width = kpad + bn
    lane = jax.lax.broadcasted_iota(jnp.int32, (bm, width), 1)

    kslot = jax.lax.broadcasted_iota(jnp.int32, (bm, kpad), 1)

    # rolled (not Python-unrolled) min-extraction: k unrolled passes blow
    # up the Mosaic program at k ≳ 16 over wide blocks (the tuner observed
    # compile failures at k=32, cols ≥ 16384); a fori_loop keeps the
    # program size O(1) in k
    def pass_s(s, carry):
        cat_val, new_val, new_idx = carry
        m = jnp.min(cat_val, axis=1)                          # (BM,)
        am = jnp.argmin(cat_val, axis=1)                      # (BM,)
        hit = lane == am[:, None]                             # exactly one per row
        mi = jnp.sum(jnp.where(hit, cat_idx, 0), axis=1)      # gather-free pick
        new_val = jnp.where(kslot == s, m[:, None], new_val)
        new_idx = jnp.where(kslot == s, mi[:, None], new_idx)
        cat_val = jnp.where(hit, jnp.inf, cat_val)
        return cat_val, new_val, new_idx

    _, new_val, new_idx = jax.lax.fori_loop(
        0, k, pass_s,
        (cat_val,
         jnp.full((bm, kpad), jnp.inf, jnp.float32),
         jnp.full((bm, kpad), -1, jnp.int32)))
    val_ref[:] = new_val
    idx_ref[:] = new_idx


@functools.partial(jax.jit, static_argnames=("k", "bm", "bn", "interpret"))
def _call(x, k: int, bm: int, bn: int, interpret: bool):
    batch, length = x.shape
    kpad = max(_LANES, ((k + _LANES - 1) // _LANES) * _LANES)
    grid = (pl.cdiv(batch, bm), pl.cdiv(length, bn))
    val, idx = pl.pallas_call(
        functools.partial(_kernel, k=k, kpad=kpad, bn=bn, length=length),
        grid=grid,
        in_specs=[
            pl.BlockSpec((bm, bn), lambda i, j: (i, j), memory_space=pltpu.VMEM),
        ],
        out_specs=(
            pl.BlockSpec((bm, kpad), lambda i, j: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((bm, kpad), lambda i, j: (i, 0), memory_space=pltpu.VMEM),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((grid[0] * bm, kpad), jnp.float32),
            jax.ShapeDtypeStruct((grid[0] * bm, kpad), jnp.int32),
        ),
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(x)
    return val[:batch, :k], idx[:batch, :k]


def select_k_pallas(
    in_val: jax.Array,
    k: int,
    *,
    select_min: bool = True,
    sorted: bool = True,
    bm: int = 256,
    bn: int = 2048,
) -> Tuple[jax.Array, jax.Array]:
    """Exact top-k (smallest or largest) per row, sorted best-first.

    Designed for small k (≤ ~64) over long rows; cost grows linearly with
    k (k min-extract passes), so large k should use ``lax.top_k`` instead
    (the ``SelectAlgo.kAuto`` heuristic handles this).

    ``sorted=False`` accepts the relaxed unsorted-fold contract that
    ``matrix.select_k`` plumbs through for intermediate merges (the
    probe-block and CAGRA frontier folds): this kernel's min-extraction
    passes emit ascending order anyway — a valid refinement, at no extra
    cost, since the ranking falls out of the extraction rather than a
    separate pass — so the flag only keeps the fold call signature uniform
    across dispatch targets.
    """
    del sorted  # ordered output is a refinement of the unsorted contract
    batch, length = in_val.shape
    bn = min(bn, max(_LANES, length))
    bm = min(bm, max(8, batch))
    x = in_val if select_min else -in_val
    # dispatch through the Mosaic gate: on-TPU with a stale MOSAIC_CHECK
    # stamp or a wedged platform probe this call must NOT attempt Mosaic
    # lowering — fall back to lax.top_k here (reason logged by the gate)
    # instead of relying on every caller to pre-check the artifact
    from .gate import dispatch_mode

    mode = dispatch_mode("select_k")
    if mode == "xla":
        neg, idx = jax.lax.top_k(-x, int(k))
        val = -neg
        if not select_min:
            val = -val
        return val.astype(in_val.dtype), idx
    val, idx = _call(x, int(k), bm, bn, mode != "mosaic")
    if not select_min:
        val = -val
    return val.astype(in_val.dtype), idx
