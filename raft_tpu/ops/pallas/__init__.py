"""Pallas TPU kernels for the hot ops.

These are the TPU analogs of the reference's hand-written CUDA kernels
(``matrix/detail/select_radix.cuh``, ``select_warpsort.cuh``, and the
tiled contraction engine ``linalg/detail/contractions.cuh``): where XLA's
stock lowering leaves performance on the table, the op is expressed as an
explicit grid over VMEM-resident blocks.

Dispatch is centralized in :mod:`.gate`: Mosaic only behind a validated
``bench/MOSAIC_CHECK.json`` hardware stamp, ``interpret=True`` off-TPU so
the same code paths are exercised by the CPU test mesh (SURVEY.md §4's
LocalCUDACluster analog), and logged stock-XLA fallbacks when the stamp
is stale or the platform probe wedges.
"""

from .gate import dispatch_mode, mosaic_gate, pallas_kernel_sha, reset_gate
from .select_k import select_k_pallas
from .fused_l2_topk import fused_shortlist
from .fused_scan import fused_slab_topk

__all__ = ["select_k_pallas", "fused_shortlist", "fused_slab_topk",
           "dispatch_mode", "mosaic_gate", "pallas_kernel_sha", "reset_gate"]
