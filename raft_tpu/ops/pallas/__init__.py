"""Pallas TPU kernels for the hot ops.

These are the TPU analogs of the reference's hand-written CUDA kernels
(``matrix/detail/select_radix.cuh``, ``select_warpsort.cuh``, and the
tiled contraction engine ``linalg/detail/contractions.cuh``): where XLA's
stock lowering leaves performance on the table, the op is expressed as an
explicit grid over VMEM-resident blocks.

Kernels fall back to ``interpret=True`` off-TPU so the same code paths are
exercised by the CPU test mesh (SURVEY.md §4's LocalCUDACluster analog).
"""

from .select_k import select_k_pallas
from .fused_l2_topk import fused_shortlist

__all__ = ["select_k_pallas", "fused_shortlist"]
