"""Fused per-query slab distance + partial top-k — the blocked-scan kernel.

:func:`fused_l2_topk.fused_shortlist` fuses the *shared-database* matmul
(every query scores the same rows).  The blocked engines are different:
each query gathers its OWN candidate slab (its probed lists, its frontier
neighborhood), so the distance tile is a batched ``[C, d] · [d]``
contraction — the pinned accumulation shape of
``ops/blocked_scan.slab_dots``.  This kernel fuses that tile with an
in-register approximate partial top-k per TPU-KNN's PartialReduce scheme
(PAPERS.md, arXiv 2206.14286):

* grid ``(q_blocks, c_blocks)``, candidate dimension innermost; each step
  scores a ``(BM, BN)`` block of ``base − 2·⟨q, vec⟩`` via a batched
  ``dot_general`` (bf16 inputs, f32 accumulation) without the ``[nq, C]``
  distance block ever reaching HBM,
* every lane position is a shortlist bucket keeping its branch-free
  **running top-2** (value + int32 c-block id) in VMEM-resident output
  refs — the same 2-deep per-bucket queue as ``fused_l2_topk``, so a true
  neighbor is shed only when ≥ 3 of a query's top-k collide in one of the
  BN buckets within a single slab,
* the caller (``ops/blocked_scan.scan_topk_fused``) folds the
  ``(nq, 2·BN)`` shortlist into the scan carry and exactly re-scores the
  k finalists, so values stay f32-exact and only the candidate *set* is
  approximate (recall-gated).

Dispatch rides :mod:`ops.pallas.gate`: Mosaic on validated TPU,
``interpret=True`` parity off-TPU, and a stock-XLA shortlist fallback
(with the gate's logged reason) when the hardware stamp is stale or the
platform probe wedges.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

try:  # pre-0.6 runtimes carry the old TPUCompilerParams spelling
    _CompilerParams = pltpu.CompilerParams
except AttributeError:
    _CompilerParams = pltpu.TPUCompilerParams

__all__ = ["fused_slab_topk"]


def _kernel(q_ref, v_ref, b_ref, v1_ref, i1_ref, v2_ref, i2_ref):
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        v1_ref[:] = jnp.full_like(v1_ref, jnp.inf)
        i1_ref[:] = jnp.full_like(i1_ref, -1)
        v2_ref[:] = jnp.full_like(v2_ref, jnp.inf)
        i2_ref[:] = jnp.full_like(i2_ref, -1)

    # batched [BN, d] · [d] contraction — one query row against its own
    # slab block, f32 accumulation (the slab_dots accumulation shape)
    dots = jax.lax.dot_general(
        q_ref[:], v_ref[:],
        dimension_numbers=(((1,), (2,)), ((0,), (0,))),
        preferred_element_type=jnp.float32)               # (BM, BN)
    dist = b_ref[:] - 2.0 * dots
    # a bucket's winning candidate ≡ its lane position (mod BN): the int32
    # c-block id alone identifies the slab position
    blk = j.astype(jnp.int32)

    # branch-free running top-2 merge per lane bucket
    r1, r2 = v1_ref[:], v2_ref[:]
    first = dist < r1
    loser = jnp.where(first, r1, dist)                    # max(dist, r1)
    li = jnp.where(first, i1_ref[:], blk)
    v1_ref[:] = jnp.where(first, dist, r1)
    i1_ref[:] = jnp.where(first, blk, i1_ref[:])
    second = loser < r2
    v2_ref[:] = jnp.where(second, loser, r2)
    i2_ref[:] = jnp.where(second, li, i2_ref[:])


@functools.partial(jax.jit, static_argnames=("bm", "bn", "interpret"))
def _call(q, vecs, base, bm, bn, interpret):
    nq, c, d = vecs.shape
    grid = (pl.cdiv(nq, bm), c // bn)
    out_spec = pl.BlockSpec((bm, bn), lambda i, j: (i, 0),
                            memory_space=pltpu.VMEM)
    out_shape = jax.ShapeDtypeStruct((grid[0] * bm, bn), jnp.float32)
    idx_shape = jax.ShapeDtypeStruct((grid[0] * bm, bn), jnp.int32)
    v1, i1, v2, i2 = pl.pallas_call(
        _kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((bm, d), lambda i, j: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((bm, bn, d), lambda i, j: (i, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((bm, bn), lambda i, j: (i, j),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=(out_spec, out_spec, out_spec, out_spec),
        out_shape=(out_shape, idx_shape, out_shape, idx_shape),
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(q, vecs, base)
    # reconstruct slab positions: pos = c_block_id * BN + lane position
    lane = jax.lax.broadcasted_iota(jnp.int32, (nq, bn), 1)
    vals = jnp.concatenate([v1[:nq], v2[:nq]], axis=1)
    pos = jnp.concatenate([i1[:nq] * bn + lane, i2[:nq] * bn + lane], axis=1)
    # unfilled buckets carry block id -1 and +inf values: clamp so
    # downstream gathers stay in-bounds (+inf keeps them out of any top-k)
    return vals, jnp.maximum(pos, 0)


@functools.partial(jax.jit, static_argnames=("bn",))
def _xla_fallback(q, vecs, base, bn):
    # gate-closed path: same shortlist contract from stock XLA ops — the
    # exact top-2·BN (a superset of anything the bucketed kernel keeps)
    dots = jnp.einsum("qcd,qd->qc", vecs, q,
                      preferred_element_type=jnp.float32)
    dist = base - 2.0 * dots
    width = min(2 * bn, dist.shape[1])
    neg, pos = jax.lax.top_k(-dist, width)
    pad = 2 * bn - width
    if pad:
        neg = jnp.pad(neg, ((0, 0), (0, pad)), constant_values=-jnp.inf)
        pos = jnp.pad(pos, ((0, 0), (0, pad)))
    return -neg, pos


def fused_slab_topk(
    vecs: jax.Array,
    base: jax.Array,
    q: jax.Array,
    *,
    bm: int = 8,
    bn: int = 512,
    interpret: Optional[bool] = None,
) -> Tuple[jax.Array, jax.Array]:
    """Per-query shortlist of ``2*bn`` best slab positions by
    ``base − 2·⟨q, vec⟩`` (monotone in L2 for ``base = ‖vec‖²``; use
    ``base = 0`` for inner product, where the surrogate is ``−2·dots``).

    ``vecs`` is the gathered ``(nq, C, d)`` slab, ``base`` the f32
    ``(nq, C)`` per-candidate offset — invalid/padded lanes must carry
    ``base = +inf`` so they never surface.  Inputs are cast to bf16 for
    the MXU pass (f32 accumulation): this is the *approximate-partial*
    arm — the caller re-scores survivors exactly.  Returns
    ``(values, slab_positions)`` of shape ``(nq, 2*bn)``, unsorted.

    ``interpret=None`` resolves dispatch through the Mosaic gate
    (``ops/pallas/gate.dispatch_mode``); pass ``True`` to force
    interpret-mode (CPU parity tests).
    """
    from ...core.errors import expects

    nq, c, d = vecs.shape
    expects(base.shape == (nq, c), f"base shape {base.shape} != ({nq}, {c})")
    expects(q.shape == (nq, d), f"q shape {q.shape} != ({nq}, {d})")
    if interpret is None:
        from .gate import dispatch_mode

        mode = dispatch_mode("fused_scan")
        if mode == "xla":
            return _xla_fallback(q.astype(jnp.bfloat16),
                                 vecs.astype(jnp.bfloat16),
                                 base.astype(jnp.float32), bn)
        interpret = mode != "mosaic"
    bn = min(bn, ((max(c, 1) + 127) // 128) * 128)  # keep lane alignment
    dpad = (-d) % 128
    if dpad:  # lane-width pad (zeros don't change dots)
        vecs = jnp.pad(vecs, ((0, 0), (0, 0), (0, dpad)))
        q = jnp.pad(q, ((0, 0), (0, dpad)))
    cpad = (-c) % bn
    if cpad:
        vecs = jnp.pad(vecs, ((0, 0), (0, cpad), (0, 0)))
        base = jnp.pad(base, ((0, 0), (0, cpad)), constant_values=jnp.inf)
    bm = min(bm, max(1, nq))
    return _call(q.astype(jnp.bfloat16), vecs.astype(jnp.bfloat16),
                 base.astype(jnp.float32), bm, bn, interpret)
