"""Fused L2-distance + lane-bucketed shortlist — the flagship kNN kernel.

TPU-KNN (PAPERS.md, arXiv 2206.14286) reaches peak FLOP/s by folding
top-k selection into the distance matmul's epilogue so the ``(m, n)``
distance matrix never touches HBM.  This kernel is that design in Pallas:

* grid ``(m_blocks, n_blocks)`` with the database dimension innermost;
  each step computes a ``(BM, BN)`` block of ``‖y‖² − 2·x·yᵀ`` on the MXU
  (bf16 inputs, f32 accumulation),
* every *lane position* ``p ∈ [0, BN)`` is a shortlist bucket holding the
  columns ``{p, p+BN, p+2BN, …}``; the kernel keeps each bucket's
  **running top-2** (value + column id) in VMEM-resident output refs.
  The update is branch-free elementwise compare/select on the VPU — no
  argmin, no cross-lane reduction (that was measured 3× slower), the
  PartialReduce trick from the TPU-KNN paper with a 2-deep per-bucket
  queue,
* a true neighbor is missed only when ≥ 3 of the query's top-k collide
  in one of the BN buckets: P ≈ C(k,3)/BN² per query (< 3e-5 for k=10,
  BN = 2048), so the ``(m, 2·BN)`` shortlist is effectively exact; the
  caller (``neighbors.brute_force``) re-scores it in f32, removing bf16
  rounding from the final ranking.

HBM traffic: x and y are read (y: ``⌈m/BM⌉`` times), the distance matrix
itself never leaves VMEM.  Compare ``matrix/detail/select_radix.cuh`` +
``linalg/detail/contractions.cuh`` for the reference's (separate) CUDA
kernels.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

try:  # pre-0.6 runtimes carry the old TPUCompilerParams spelling
    _CompilerParams = pltpu.CompilerParams
except AttributeError:
    _CompilerParams = pltpu.TPUCompilerParams

__all__ = ["fused_shortlist"]


def _kernel(x_ref, y_ref, yn_ref, v1_ref, i1_ref, v2_ref, i2_ref):
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        v1_ref[:] = jnp.full_like(v1_ref, jnp.inf)
        i1_ref[:] = jnp.full_like(i1_ref, -1)
        v2_ref[:] = jnp.full_like(v2_ref, jnp.inf)
        i2_ref[:] = jnp.full_like(i2_ref, -1)

    if x_ref.dtype == jnp.int8:
        # int8 MXU pass (2x bf16 rate, int32 accumulation — exact)
        dots = jnp.dot(x_ref[:], y_ref[:].T,
                       preferred_element_type=jnp.int32).astype(jnp.float32)
    else:
        dots = jnp.dot(x_ref[:], y_ref[:].T,
                       preferred_element_type=jnp.float32)
    dist = yn_ref[:] - 2.0 * dots                     # (BM, BN); ‖x‖² added later
    # a bucket's winning column ≡ its lane position (mod BN): storing the
    # int16 n-block id alone identifies the column — no per-lane iota pass
    blk = j.astype(jnp.int16)

    # branch-free running top-2 merge per lane bucket
    r1, r2 = v1_ref[:], v2_ref[:]
    first = dist < r1
    loser = jnp.where(first, r1, dist)                # max(dist, r1)
    li = jnp.where(first, i1_ref[:], blk)
    v1_ref[:] = jnp.where(first, dist, r1)
    i1_ref[:] = jnp.where(first, blk, i1_ref[:])
    second = loser < r2
    v2_ref[:] = jnp.where(second, loser, r2)
    i2_ref[:] = jnp.where(second, li, i2_ref[:])


@functools.partial(jax.jit, static_argnames=("bm", "bn", "interpret"))
def _call(xb, yb, yn, bm, bn, interpret):
    m = xb.shape[0]
    n = yb.shape[0]
    grid = (pl.cdiv(m, bm), pl.cdiv(n, bn))
    out_spec = pl.BlockSpec((bm, bn), lambda i, j: (i, 0), memory_space=pltpu.VMEM)
    out_shape = jax.ShapeDtypeStruct((grid[0] * bm, bn), jnp.float32)
    idx_shape = jax.ShapeDtypeStruct((grid[0] * bm, bn), jnp.int16)
    v1, i1, v2, i2 = pl.pallas_call(
        _kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((bm, xb.shape[1]), lambda i, j: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((bn, yb.shape[1]), lambda i, j: (j, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bn), lambda i, j: (0, j), memory_space=pltpu.VMEM),
        ],
        out_specs=(out_spec, out_spec, out_spec, out_spec),
        out_shape=(out_shape, idx_shape, out_shape, idx_shape),
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(xb, yb, yn)
    # reconstruct column ids: col = block_id * BN + lane position
    lane = jax.lax.broadcasted_iota(jnp.int32, (m, bn), 1)
    vals = jnp.concatenate([v1[:m], v2[:m]], axis=1)
    idx = jnp.concatenate(
        [i1[:m].astype(jnp.int32) * bn + lane, i2[:m].astype(jnp.int32) * bn + lane],
        axis=1,
    )
    # unfilled buckets (possible when n < bn) carry block id -1 and +inf
    # values: clamp the id so downstream gathers stay in-bounds (the +inf
    # value keeps them out of every top-k)
    return vals, jnp.maximum(idx, 0)


def fused_shortlist(
    x: jax.Array,
    y: jax.Array,
    yn: jax.Array,
    *,
    bm: int = 256,
    bn: int = 2048,
) -> Tuple[jax.Array, jax.Array]:
    """Per-query shortlist of ``2*bn`` nearest candidates by
    ``yn − 2·x·yᵀ`` (monotone in L2 distance for fixed query when ``yn``
    is ``‖y‖²`` — or any per-column offset with the same property).

    Float inputs are cast to bf16 for the MXU pass.  **int8 inputs run an
    int8 MXU pass** (2× the bf16 rate, exact int32 accumulation) —
    ``uint8`` corpora (SIFT/bigann-style) are centered to int8 with the
    correction folded into ``yn`` (see :func:`int8_surrogate_norms`; the
    per-*query* correction term is constant within a row and drops out of
    the ranking).  ``yn`` must be f32.  Returns ``(values, column_ids)``
    of shape ``(m, 2*bn)`` — *unsorted*; exact re-scoring is the caller's
    job.  Padded database rows get ``yn = +inf`` so they never surface.

    The int16 block-id encoding bounds the database at ``32767 * bn`` rows
    (~67M at the default ``bn``) per call; shard larger databases.
    """
    from ...core.errors import expects

    m, d = x.shape
    n = y.shape[0]
    expects(n <= 32767 * bn,
            f"database rows {n} exceed int16 block-id range ({32767 * bn}) "
            f"at bn={bn}; shard the database or raise bn")
    expects(x.dtype == y.dtype, f"x/y dtype mismatch {x.dtype} vs {y.dtype}")
    if x.dtype == jnp.uint8:
        # center to int8 BEFORE padding (pad zeros must stay zeros)
        x = center_int8(x)
        y = center_int8(y)
    # pad feature dim to lane width for the MXU (zeros don't change dots)
    dpad = (-d) % 128
    if dpad:
        x = jnp.pad(x, ((0, 0), (0, dpad)))
        y = jnp.pad(y, ((0, 0), (0, dpad)))
    npad = (-n) % bn
    if npad:
        y = jnp.pad(y, ((0, npad), (0, 0)))
        yn = jnp.pad(yn, (0, npad), constant_values=jnp.inf)
    bm = min(bm, max(8, m))
    if x.dtype != jnp.int8:
        x = x.astype(jnp.bfloat16)
        y = y.astype(jnp.bfloat16)
    yn = yn.reshape(1, -1).astype(jnp.float32)
    # Mosaic gate (see gate.py): stale hardware stamp / wedged probe on a
    # TPU host → same shortlist contract from stock XLA ops, reason logged
    from .gate import dispatch_mode

    mode = dispatch_mode("fused_l2_topk")
    if mode == "xla":
        if x.dtype == jnp.int8:
            dots = jnp.matmul(x.astype(jnp.int32), y.T.astype(jnp.int32)
                              ).astype(jnp.float32)
        else:
            dots = jnp.matmul(x, y.T, preferred_element_type=jnp.float32)
        dist = yn - 2.0 * dots
        width = min(2 * bn, dist.shape[1])
        neg, idx = jax.lax.top_k(-dist, width)
        pad = 2 * bn - width
        if pad:
            neg = jnp.pad(neg, ((0, 0), (0, pad)),
                          constant_values=-jnp.inf)
            idx = jnp.pad(idx, ((0, 0), (0, pad)))
        return -neg, idx
    return _call(x, y, yn, bm, bn, mode != "mosaic")


def center_int8(a: jax.Array) -> jax.Array:
    """``uint8 → int8`` zero-point shift (``a − 128``) — THE centering the
    int8 kernel path scores; :func:`int8_surrogate_norms` is its paired
    ``yn`` convention.  int8 passes through unchanged."""
    if a.dtype == jnp.uint8:
        return (a.astype(jnp.int16) - 128).astype(jnp.int8)
    return a


def int8_surrogate_norms(y: jax.Array) -> jax.Array:
    """The ``yn`` vector for integer datasets fed to :func:`fused_shortlist`.

    For ``int8`` rows this is plainly ``‖y‖²``.  For ``uint8`` rows the
    kernel scores centered values ``y' = y − 128``, so the surrogate
    needs ``yn' = ‖y‖² − 256·Σy``: with ``x' = x − 128``,

    ``‖y‖² − 2·x·y = (‖y‖² − 256·Σy) − 2·x'·y' − 256·Σx' − 32768·d``

    and the last two terms are constant per *query*, leaving the per-row
    ranking unchanged.  Exact in f32 (both terms ≤ 2²³ for d ≤ 128).
    """
    yf = y.astype(jnp.float32)
    yn = jnp.sum(yf * yf, axis=1)
    if y.dtype == jnp.uint8:
        return yn - 256.0 * jnp.sum(yf, axis=1)
    return yn
