"""raft_tpu.ops — kernel-level implementations (Pallas + XLA formulations)
backing the public primitives.  Analog of the reference's ``detail/`` layer."""
