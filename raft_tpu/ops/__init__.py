"""raft_tpu.ops — kernel-level implementations (Pallas + XLA formulations)
backing the public primitives.  Analog of the reference's ``detail/`` layer.

``ops.blocked_scan`` is the shared blocked-scan core every neighbors
engine routes through (slab scoring einsum, ``select_k(sorted=False)``
fold, fused-kernel dispatch); ``ops.pallas`` holds the Mosaic kernels and
their hardware gate.  Submodules import lazily — ``import raft_tpu.ops``
alone must not initialize a backend."""
