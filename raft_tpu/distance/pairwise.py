"""Pairwise distances between row sets — the cuVS ``pairwise_distance``
capability (reference delegates there post-migration; metric list mirrors the
classic RAFT ``distance::DistanceType`` enum).

Two execution shapes:

* **expanded** — metrics decomposable as ``f(||x||, ||y||, x.y)`` are computed
  from a single ``X @ Y.T`` (MXU) plus per-row norm corrections: sqeuclidean,
  euclidean, cosine, inner product, correlation.
* **tiled unexpanded** — elementwise-difference metrics (L1, Linf, Canberra,
  Minkowski, Hamming, Hellinger, JensenShannon, KL, RusselRao, BrayCurtis,
  Dice, Jaccard) scan over database tiles so the ``(m, tile, d)`` broadcast
  stays bounded; static shapes keep everything jit-friendly.

APIs are functional (no handle mutation); pass ``Resources`` only if you need
a non-default mesh downstream.
"""

from __future__ import annotations

import enum
from functools import partial

import jax
import jax.numpy as jnp

from ..core.array import wrap_array
from ..core.errors import expects

__all__ = ["DistanceType", "pairwise_distance"]


class DistanceType(enum.Enum):
    """Metric enum — parity with RAFT's classic ``distance::DistanceType``."""

    L2Expanded = "sqeuclidean"          # ||x-y||^2 via gemm
    L2SqrtExpanded = "euclidean"        # ||x-y|| via gemm
    L2Unexpanded = "sqeuclidean_unexp"  # ||x-y||^2 via diff
    L2SqrtUnexpanded = "euclidean_unexp"
    CosineExpanded = "cosine"
    InnerProduct = "inner_product"
    CorrelationExpanded = "correlation"
    L1 = "l1"                            # cityblock
    Linf = "chebyshev"
    Canberra = "canberra"
    LpUnexpanded = "minkowski"
    HammingUnexpanded = "hamming"
    HellingerExpanded = "hellinger"
    JensenShannon = "jensenshannon"
    KLDivergence = "kldivergence"
    RusselRaoExpanded = "russelrao"
    BrayCurtis = "braycurtis"
    JaccardExpanded = "jaccard"
    DiceExpanded = "dice"


# String aliases accepted by the public API (pylibraft accepted scipy-style
# metric names; keep that ergonomic surface).
_ALIASES = {
    "sqeuclidean": DistanceType.L2Expanded,
    "euclidean": DistanceType.L2SqrtExpanded,
    "l2": DistanceType.L2SqrtExpanded,
    "cosine": DistanceType.CosineExpanded,
    "inner_product": DistanceType.InnerProduct,
    "correlation": DistanceType.CorrelationExpanded,
    "l1": DistanceType.L1,
    "cityblock": DistanceType.L1,
    "manhattan": DistanceType.L1,
    "taxicab": DistanceType.L1,
    "chebyshev": DistanceType.Linf,
    "linf": DistanceType.Linf,
    "canberra": DistanceType.Canberra,
    "minkowski": DistanceType.LpUnexpanded,
    "lp": DistanceType.LpUnexpanded,
    "hamming": DistanceType.HammingUnexpanded,
    "hellinger": DistanceType.HellingerExpanded,
    "jensenshannon": DistanceType.JensenShannon,
    "kldivergence": DistanceType.KLDivergence,
    "kl_divergence": DistanceType.KLDivergence,
    "russelrao": DistanceType.RusselRaoExpanded,
    "braycurtis": DistanceType.BrayCurtis,
    "jaccard": DistanceType.JaccardExpanded,
    "dice": DistanceType.DiceExpanded,
}


def _as_metric(metric) -> DistanceType:
    if isinstance(metric, DistanceType):
        return metric
    m = str(metric).lower()
    expects(m in _ALIASES, f"unknown metric {metric!r}")
    return _ALIASES[m]


_EXPANDED = {
    DistanceType.L2Expanded,
    DistanceType.L2SqrtExpanded,
    DistanceType.CosineExpanded,
    DistanceType.InnerProduct,
    DistanceType.CorrelationExpanded,
}


def sq_norm_rows(x: jax.Array) -> jax.Array:
    return jnp.sum(x * x, axis=-1)


def sq_l2(x: jax.Array, y: jax.Array, *,
          precision=jax.lax.Precision.HIGHEST) -> jax.Array:
    """Squared-L2 matrix (m, n) in f32 — THE shared recipe.

    One place owns the distance gemm: f32 accumulation + a cancellation
    clamp, at ``Precision.HIGHEST`` by default (single bf16 MXU passes are
    coarser than neighbor/centroid gaps).  Everything needing raw squared
    distances (kmeans assignment, capacity assignment, IVF) must call
    this, not re-derive it.  ``precision=Precision.DEFAULT`` opts a caller
    into the ~3× faster single-pass bf16 MXU gemm where only an argmin
    over well-separated alternatives is consumed (kmeans *training*
    assignments — never final/capped assignments or k-NN ranking).
    """
    xf = x.astype(jnp.float32)
    yf = y.astype(jnp.float32)
    dots = jnp.dot(
        x, y.T, preferred_element_type=jnp.float32,
        precision=precision,
    )
    return jnp.maximum(
        sq_norm_rows(xf)[:, None] + sq_norm_rows(yf)[None, :] - 2.0 * dots, 0.0
    )


def _expanded_distance(x, y, metric: DistanceType):
    """Distance from one MXU gemm + rank-1 norm corrections.

    Accumulate in f32 regardless of input dtype.  Precision.HIGHEST matters
    on TPU: the default MXU path multiplies in bf16 whose ~8-bit mantissa is
    coarser than intra-cluster distance gaps, silently wrecking neighbor
    ranking (observed recall@10 0.67 vs 1.0).  HIGHEST selects the multi-pass
    f32-equivalent MXU algorithm.
    """
    dots = jnp.dot(
        x, y.T, preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.HIGHEST,
    )
    if metric is DistanceType.InnerProduct:
        return dots
    if metric in (DistanceType.L2Expanded, DistanceType.L2SqrtExpanded):
        xn = sq_norm_rows(x.astype(jnp.float32))
        yn = sq_norm_rows(y.astype(jnp.float32))
        d2 = xn[:, None] + yn[None, :] - 2.0 * dots
        d2 = jnp.maximum(d2, 0.0)  # clamp catastrophic cancellation
        if metric is DistanceType.L2SqrtExpanded:
            return jnp.sqrt(d2)
        return d2
    if metric is DistanceType.CosineExpanded:
        xn = jnp.sqrt(sq_norm_rows(x.astype(jnp.float32)))
        yn = jnp.sqrt(sq_norm_rows(y.astype(jnp.float32)))
        denom = jnp.maximum(xn[:, None] * yn[None, :], 1e-30)
        return 1.0 - dots / denom
    if metric is DistanceType.CorrelationExpanded:
        xf = x.astype(jnp.float32)
        yf = y.astype(jnp.float32)
        xc = xf - jnp.mean(xf, axis=1, keepdims=True)
        yc = yf - jnp.mean(yf, axis=1, keepdims=True)
        return _expanded_distance(xc, yc, DistanceType.CosineExpanded)
    raise AssertionError(metric)


def _elementwise_tile(xs, yt, metric: DistanceType, p: float):
    """Distances between x tile (m,d) and y tile (t,d) via broadcast diff."""
    xb = xs[:, None, :]  # (m, 1, d)
    yb = yt[None, :, :]  # (1, t, d)
    if metric in (DistanceType.L2Unexpanded, DistanceType.L2SqrtUnexpanded):
        d = jnp.sum((xb - yb) ** 2, axis=-1)
        return jnp.sqrt(d) if metric is DistanceType.L2SqrtUnexpanded else d
    if metric is DistanceType.L1:
        return jnp.sum(jnp.abs(xb - yb), axis=-1)
    if metric is DistanceType.Linf:
        return jnp.max(jnp.abs(xb - yb), axis=-1)
    if metric is DistanceType.Canberra:
        num = jnp.abs(xb - yb)
        den = jnp.abs(xb) + jnp.abs(yb)
        return jnp.sum(jnp.where(den > 0, num / jnp.where(den > 0, den, 1.0), 0.0), axis=-1)
    if metric is DistanceType.LpUnexpanded:
        return jnp.sum(jnp.abs(xb - yb) ** p, axis=-1) ** (1.0 / p)
    if metric is DistanceType.HammingUnexpanded:
        return jnp.mean((xb != yb).astype(jnp.float32), axis=-1)
    if metric is DistanceType.HellingerExpanded:
        # sqrt(1 - sum(sqrt(x*y))) — inputs assumed non-negative
        s = jnp.sum(jnp.sqrt(jnp.maximum(xb * yb, 0.0)), axis=-1)
        return jnp.sqrt(jnp.maximum(1.0 - s, 0.0))
    if metric is DistanceType.JensenShannon:
        m = 0.5 * (xb + yb)

        def kldiv(a, b):
            ratio = jnp.where((a > 0) & (b > 0), a / jnp.where(b > 0, b, 1.0), 1.0)
            return jnp.sum(jnp.where(a > 0, a * jnp.log(ratio), 0.0), axis=-1)

        return jnp.sqrt(jnp.maximum(0.5 * (kldiv(xb, m) + kldiv(yb, m)), 0.0))
    if metric is DistanceType.KLDivergence:
        ratio = jnp.where((xb > 0) & (yb > 0), xb / jnp.where(yb > 0, yb, 1.0), 1.0)
        return jnp.sum(jnp.where(xb > 0, xb * jnp.log(ratio), 0.0), axis=-1)
    if metric is DistanceType.RusselRaoExpanded:
        d = xs.shape[-1]
        both = jnp.sum((xb != 0) & (yb != 0), axis=-1).astype(jnp.float32)
        return (d - both) / d
    if metric is DistanceType.BrayCurtis:
        num = jnp.sum(jnp.abs(xb - yb), axis=-1)
        den = jnp.sum(jnp.abs(xb + yb), axis=-1)
        return jnp.where(den > 0, num / jnp.where(den > 0, den, 1.0), 0.0)
    if metric in (DistanceType.JaccardExpanded, DistanceType.DiceExpanded):
        xnz = xb != 0
        ynz = yb != 0
        inter = jnp.sum(xnz & ynz, axis=-1).astype(jnp.float32)
        union = jnp.sum(xnz | ynz, axis=-1).astype(jnp.float32)
        if metric is DistanceType.JaccardExpanded:
            return jnp.where(union > 0, 1.0 - inter / jnp.where(union > 0, union, 1.0), 0.0)
        tot = jnp.sum(xnz, axis=-1) + jnp.sum(ynz, axis=-1)
        return jnp.where(tot > 0, 1.0 - 2.0 * inter / jnp.where(tot > 0, tot, 1.0), 0.0)
    raise AssertionError(metric)


def _pad_rows(a: jax.Array, multiple: int):
    n = a.shape[0]
    pad = (-n) % multiple
    if pad:
        a = jnp.concatenate([a, jnp.zeros((pad,) + a.shape[1:], a.dtype)], axis=0)
    return a, n


@partial(jax.jit, static_argnames=("metric", "p", "tile"))
def _tiled_unexpanded(x, y, metric: DistanceType, p: float, tile: int):
    """Scan y in tiles of ``tile`` rows; output (m, n_padded)."""
    ypad, _ = _pad_rows(y, tile)
    ytiles = ypad.reshape(-1, tile, y.shape[1])

    def step(_, yt):
        return None, _elementwise_tile(x, yt, metric, p)

    _, out = jax.lax.scan(step, None, ytiles)  # (ntiles, m, tile)
    return jnp.moveaxis(out, 0, 1).reshape(x.shape[0], -1)  # caller slices padding


def pairwise_distance(
    x,
    y=None,
    metric="euclidean",
    *,
    p: float = 2.0,
    tile: int = 2048,
    res=None,
) -> jax.Array:
    """All-pairs distance matrix ``(x.shape[0], y.shape[0])``.

    Parity surface: ``pylibraft``-era ``distance.pairwise_distance`` (the
    reference now routes to cuVS — ``README.md:108-119``).  ``x``/``y`` are
    any array-likes; ``y=None`` means ``y=x``.  ``p`` is the Minkowski order.
    """
    x = wrap_array(x, ndim=2, name="x")
    y = x if y is None else wrap_array(y, ndim=2, name="y")
    expects(x.shape[1] == y.shape[1], f"dim mismatch {x.shape} vs {y.shape}")
    m = _as_metric(metric)
    if m in _EXPANDED:
        return _expanded_distance(x, y, m)
    out = _tiled_unexpanded(x, y, m, float(p), int(min(tile, max(y.shape[0], 1))))
    return out[:, : y.shape[0]]
