"""Fused L2 nearest-neighbor — capability parity with RAFT's ``fusedL2NN``
(named in the north star; descended from the tiled contraction engine
``cpp/include/raft/linalg/detail/contractions.cuh:16``).

For each query row, find the single nearest database row without ever
materializing the full (m, n) distance matrix: scan database tiles, compute a
(m, tile) distance block on the MXU, and fold a running (min_val, min_idx)
pair.  This is the inner loop of kmeans assignment and 1-NN, so it must be
pure gemm + elementwise — XLA fuses the correction and min into the matmul
epilogue.
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp

from ..core.array import wrap_array
from ..core.errors import expects

__all__ = ["fused_l2_nn", "fused_l2_nn_argmin"]


@partial(jax.jit, static_argnames=("sqrt", "tile"))
def _fused_l2_nn(x, y, sqrt: bool, tile: int) -> Tuple[jax.Array, jax.Array]:
    m, d = x.shape
    n = y.shape[0]
    pad = (-n) % tile
    INF = jnp.float32(jnp.inf)
    if pad:
        y = jnp.concatenate([y, jnp.zeros((pad, d), y.dtype)], axis=0)
    ytiles = y.reshape(-1, tile, d)
    xf = x.astype(jnp.float32)
    xn = jnp.sum(xf * xf, axis=1)  # (m,)

    def step(carry, inp):
        best_val, best_idx = carry
        t, yt = inp
        ytf = yt.astype(jnp.float32)
        yn = jnp.sum(ytf * ytf, axis=1)  # (tile,)
        # HIGHEST: default bf16 MXU passes are coarser than neighbor gaps
        dots = jnp.dot(
            x, yt.T, preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.HIGHEST,
        )
        d2 = xn[:, None] + yn[None, :] - 2.0 * dots
        d2 = jnp.maximum(d2, 0.0)
        # mask padded rows of the final tile
        col = t * tile + jnp.arange(tile)
        d2 = jnp.where(col[None, :] < n, d2, INF)
        loc = jnp.argmin(d2, axis=1)
        val = jnp.take_along_axis(d2, loc[:, None], axis=1)[:, 0]
        idx = t * tile + loc
        upd = val < best_val
        return (jnp.where(upd, val, best_val), jnp.where(upd, idx, best_idx)), None

    init = (jnp.full((m,), INF), jnp.zeros((m,), jnp.int32))
    (best_val, best_idx), _ = jax.lax.scan(
        step, init, (jnp.arange(ytiles.shape[0], dtype=jnp.int32), ytiles)
    )
    if sqrt:
        best_val = jnp.sqrt(best_val)
    return best_val, best_idx


def fused_l2_nn(x, y, *, sqrt: bool = False, tile: int = 4096, res=None):
    """``(min_dist, argmin)`` of L2 distance from each x row to y rows."""
    x = wrap_array(x, ndim=2, name="x")
    y = wrap_array(y, ndim=2, name="y")
    expects(x.shape[1] == y.shape[1], f"dim mismatch {x.shape} vs {y.shape}")
    return _fused_l2_nn(x, y, bool(sqrt), int(min(tile, max(y.shape[0], 1))))


def fused_l2_nn_argmin(x, y, *, tile: int = 4096, res=None) -> jax.Array:
    """Argmin only (the ``fusedL2NNMinReduce`` out_idx path)."""
    return fused_l2_nn(x, y, sqrt=False, tile=tile, res=res)[1]
