"""raft_tpu.distance — pairwise distance metrics, TPU-native.

Capability parity with the RAFT/cuVS pairwise-distance layer the reference
delegates to (``/root/reference/README.md:96-119`` shows the cuVS API the
reference now points users at; the in-tree ancestor is the tiled contraction
engine ``cpp/include/raft/linalg/detail/contractions.cuh:16``).  TPU design:
expanded metrics (L2/cosine/inner-product/correlation) ride the MXU as one
``X @ Y.T`` plus rank-1 corrections; unexpanded metrics (L1, Chebyshev,
Canberra, ...) use a database-tiled ``lax.scan`` so the broadcast difference
tensor never exceeds one tile.
"""

from .pairwise import DistanceType, pairwise_distance
from .fused import fused_l2_nn, fused_l2_nn_argmin

__all__ = [
    "DistanceType",
    "pairwise_distance",
    "fused_l2_nn",
    "fused_l2_nn_argmin",
]
