"""Regression / classification metrics — parity with ``cpp/include/raft/stats``:
``accuracy.cuh``, ``r2_score.cuh``, ``regression_metrics.cuh``,
``contingency_matrix.cuh``.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp

from ..core.array import wrap_array
from ..core.errors import expects

__all__ = ["accuracy", "r2_score", "RegressionMetrics", "regression_metrics", "contingency_matrix"]


def accuracy(predictions, ref_predictions):
    """Fraction of matching labels (``accuracy.cuh``)."""
    p = wrap_array(predictions, ndim=1)
    r = wrap_array(ref_predictions, ndim=1)
    expects(p.shape == r.shape, "prediction length mismatch")
    return jnp.mean((p == r).astype(jnp.float32))


def r2_score(y, y_hat):
    """Coefficient of determination (``r2_score.cuh``)."""
    y = wrap_array(y, ndim=1)
    y_hat = wrap_array(y_hat, ndim=1)
    mu = jnp.mean(y)
    ss_tot = jnp.sum((y - mu) ** 2)
    ss_res = jnp.sum((y - y_hat) ** 2)
    return 1.0 - ss_res / ss_tot


class RegressionMetrics(NamedTuple):
    mean_abs_error: jax.Array
    mean_squared_error: jax.Array
    median_abs_error: jax.Array


def regression_metrics(predictions, ref_predictions) -> RegressionMetrics:
    """MAE / MSE / median-AE (``regression_metrics.cuh``)."""
    p = wrap_array(predictions, ndim=1)
    r = wrap_array(ref_predictions, ndim=1)
    err = jnp.abs(p - r)
    return RegressionMetrics(
        mean_abs_error=jnp.mean(err),
        mean_squared_error=jnp.mean((p - r) ** 2),
        median_abs_error=jnp.median(err),
    )


def contingency_matrix(ground_truth, predicted, n_classes: Optional[int] = None):
    """Label contingency matrix (``contingency_matrix.cuh``).  Segment-sum of
    one-hot outer products → a single scatter-add."""
    gt = wrap_array(ground_truth, ndim=1).astype(jnp.int32)
    pr = wrap_array(predicted, ndim=1).astype(jnp.int32)
    expects(gt.shape == pr.shape, "label length mismatch")
    if n_classes is None:
        n_classes = int(jnp.maximum(jnp.max(gt), jnp.max(pr))) + 1  # jaxlint: disable=JX01 output sizing needs a concrete bound; pass n_classes to stay async
    flat = gt * n_classes + pr
    counts = jnp.zeros((n_classes * n_classes,), jnp.int32).at[flat].add(1)
    return counts.reshape(n_classes, n_classes)
