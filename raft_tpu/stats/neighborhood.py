"""Embedding / ANN quality metrics — parity with
``cpp/include/raft/stats/trustworthiness_score.cuh`` and
``stats/neighborhood_recall.cuh:77`` (the metric behind the north-star
QPS@recall target).
"""

from __future__ import annotations


import jax
import jax.numpy as jnp

from ..core.array import wrap_array
from ..core.errors import expects

__all__ = ["neighborhood_recall", "trustworthiness_score"]


def neighborhood_recall(indices, ref_indices, distances=None, ref_distances=None, eps: float = 1e-6):
    """Recall@k of ANN results against ground truth
    (``neighborhood_recall.cuh:77``).

    Counts, per query, how many returned ids appear in the reference top-k;
    like the reference, an id mismatch still counts when the *distances* match
    within ``eps`` (duplicate-distance ties).
    """
    idx = wrap_array(indices, ndim=2)
    ref = wrap_array(ref_indices, ndim=2)
    expects(idx.shape == ref.shape, "indices/ref_indices shape mismatch")
    match = (idx[:, :, None] == ref[:, None, :]).any(axis=2)
    if distances is not None and ref_distances is not None:
        d = wrap_array(distances, ndim=2)
        rd = wrap_array(ref_distances, ndim=2)
        tie = (jnp.abs(d[:, :, None] - rd[:, None, :]) <= eps).any(axis=2)
        match = match | tie
    return jnp.mean(match.astype(jnp.float32))


def trustworthiness_score(x, x_embedded, n_neighbors: int, batch_size: int = 512):
    """Trustworthiness of an embedding (``trustworthiness_score.cuh``).

    T = 1 − 2/(n·k·(2n−3k−1)) · Σ_i Σ_{j∈U_i^k} (r(i,j) − k) where r(i,j) is
    the rank of j among i's original-space neighbors and U_i^k the embedded
    k-NN not among the original k-NN.

    Tiled over query batches of ``batch_size`` (like the reference's batched
    pairwise-distance driver): peak memory is O(batch_size · n), never n².
    Ranks are computed by *counting* points closer than each selected
    neighbor — no n×n argsort materialization.
    """
    x = wrap_array(x, ndim=2)
    e = wrap_array(x_embedded, ndim=2)
    n, k = x.shape[0], n_neighbors
    expects(n == e.shape[0], "row count mismatch")

    x_sq = jnp.sum(x * x, axis=1)
    e_sq = jnp.sum(e * e, axis=1)

    batch_size = min(batch_size, n)
    n_tiles = (n + batch_size - 1) // batch_size
    pad = n_tiles * batch_size - n
    # pad the *query* side only; the database stays exactly n points and
    # padded query rows are masked out by `valid`
    x_pad = jnp.concatenate([x, jnp.zeros((pad, x.shape[1]), x.dtype)]) if pad else x
    e_pad = jnp.concatenate([e, jnp.zeros((pad, e.shape[1]), e.dtype)]) if pad else e

    def tile_penalty(start):
        rows_x = jax.lax.dynamic_slice_in_dim(x_pad, start, batch_size, 0)
        rows_e = jax.lax.dynamic_slice_in_dim(e_pad, start, batch_size, 0)
        row_ids = start + jnp.arange(batch_size)
        valid = (row_ids < n)[:, None]
        self_mask = row_ids[:, None] == jnp.arange(n)[None, :]

        d_o = jnp.maximum(
            jnp.sum(rows_x * rows_x, 1)[:, None] + x_sq[None, :]
            - 2.0 * jnp.matmul(rows_x, x.T, preferred_element_type=jnp.float32), 0.0)
        d_e = jnp.maximum(
            jnp.sum(rows_e * rows_e, 1)[:, None] + e_sq[None, :]
            - 2.0 * jnp.matmul(rows_e, e.T, preferred_element_type=jnp.float32), 0.0)
        d_o = jnp.where(self_mask, jnp.inf, d_o)
        d_e = jnp.where(self_mask, jnp.inf, d_e)

        _, emb_nn = jax.lax.top_k(-d_e, k)                      # (b, k)
        d_sel = jnp.take_along_axis(d_o, emb_nn, axis=1)        # (b, k)
        # rank(i, j) = #points strictly closer to i than j in original space
        r = jnp.sum((d_o[:, None, :] < d_sel[:, :, None]) & jnp.isfinite(d_o)[:, None, :],
                    axis=2).astype(jnp.float32)
        pen = jnp.maximum(r - (k - 1), 0.0) * (r >= k)
        return jnp.sum(jnp.where(valid, pen, 0.0))

    starts = jnp.arange(n_tiles) * batch_size
    penalty = jnp.sum(jax.lax.map(tile_penalty, starts))
    return 1.0 - 2.0 / (n * k * (2.0 * n - 3.0 * k - 1.0)) * penalty
