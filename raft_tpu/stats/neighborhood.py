"""Embedding / ANN quality metrics — parity with
``cpp/include/raft/stats/trustworthiness_score.cuh`` and
``stats/neighborhood_recall.cuh:77`` (the metric behind the north-star
QPS@recall target).
"""

from __future__ import annotations


import jax
import jax.numpy as jnp

from ..core.array import wrap_array
from ..core.errors import expects

__all__ = ["neighborhood_recall", "trustworthiness_score"]


def neighborhood_recall(indices, ref_indices, distances=None, ref_distances=None, eps: float = 1e-6):
    """Recall@k of ANN results against ground truth
    (``neighborhood_recall.cuh:77``).

    Counts, per query, how many returned ids appear in the reference top-k;
    like the reference, an id mismatch still counts when the *distances* match
    within ``eps`` (duplicate-distance ties).
    """
    idx = wrap_array(indices, ndim=2)
    ref = wrap_array(ref_indices, ndim=2)
    expects(idx.shape == ref.shape, "indices/ref_indices shape mismatch")
    match = (idx[:, :, None] == ref[:, None, :]).any(axis=2)
    if distances is not None and ref_distances is not None:
        d = wrap_array(distances, ndim=2)
        rd = wrap_array(ref_distances, ndim=2)
        tie = (jnp.abs(d[:, :, None] - rd[:, None, :]) <= eps).any(axis=2)
        match = match | tie
    return jnp.mean(match.astype(jnp.float32))


def trustworthiness_score(x, x_embedded, n_neighbors: int, batch_size: int = 512,
                          col_batch_size=None):
    """Trustworthiness of an embedding (``trustworthiness_score.cuh``).

    T = 1 − 2/(n·k·(2n−3k−1)) · Σ_i Σ_{j∈U_i^k} (r(i,j) − k) where r(i,j) is
    the rank of j among i's original-space neighbors and U_i^k the embedded
    k-NN not among the original k-NN.

    Tiled over query batches of ``batch_size`` (like the reference's batched
    pairwise-distance driver): peak memory is O(batch_size · n), never n².
    Ranks are computed by *counting* points closer than each selected
    neighbor — no n×n argsort materialization.

    ``col_batch_size`` additionally streams the database axis (the
    ``detail/batched`` double-chunk discipline, VERDICT r4 weak #6): the
    embedded k-NN come from a running top-k merge over column chunks and
    ranks accumulate per chunk, so peak memory drops to
    O(batch_size · col_batch_size) — for corpora where even one
    (batch, n) row strip is too large.
    """
    x = wrap_array(x, ndim=2)
    e = wrap_array(x_embedded, ndim=2)
    n, k = x.shape[0], n_neighbors
    expects(n == e.shape[0], "row count mismatch")
    if col_batch_size is not None and col_batch_size < n:
        return _trustworthiness_colchunked(x, e, k, batch_size, col_batch_size)

    x_sq = jnp.sum(x * x, axis=1)
    e_sq = jnp.sum(e * e, axis=1)

    batch_size = min(batch_size, n)
    n_tiles = (n + batch_size - 1) // batch_size
    pad = n_tiles * batch_size - n
    # pad the *query* side only; the database stays exactly n points and
    # padded query rows are masked out by `valid`
    x_pad = jnp.concatenate([x, jnp.zeros((pad, x.shape[1]), x.dtype)]) if pad else x
    e_pad = jnp.concatenate([e, jnp.zeros((pad, e.shape[1]), e.dtype)]) if pad else e

    def tile_penalty(start):
        rows_x = jax.lax.dynamic_slice_in_dim(x_pad, start, batch_size, 0)
        rows_e = jax.lax.dynamic_slice_in_dim(e_pad, start, batch_size, 0)
        row_ids = start + jnp.arange(batch_size)
        valid = (row_ids < n)[:, None]
        self_mask = row_ids[:, None] == jnp.arange(n)[None, :]

        d_o = jnp.maximum(
            jnp.sum(rows_x * rows_x, 1)[:, None] + x_sq[None, :]
            - 2.0 * jnp.matmul(rows_x, x.T, preferred_element_type=jnp.float32), 0.0)
        d_e = jnp.maximum(
            jnp.sum(rows_e * rows_e, 1)[:, None] + e_sq[None, :]
            - 2.0 * jnp.matmul(rows_e, e.T, preferred_element_type=jnp.float32), 0.0)
        d_o = jnp.where(self_mask, jnp.inf, d_o)
        d_e = jnp.where(self_mask, jnp.inf, d_e)

        _, emb_nn = jax.lax.top_k(-d_e, k)                      # (b, k)
        d_sel = jnp.take_along_axis(d_o, emb_nn, axis=1)        # (b, k)
        # rank(i, j) = #points strictly closer to i than j in original space
        r = jnp.sum((d_o[:, None, :] < d_sel[:, :, None]) & jnp.isfinite(d_o)[:, None, :],
                    axis=2).astype(jnp.float32)
        pen = jnp.maximum(r - (k - 1), 0.0) * (r >= k)
        return jnp.sum(jnp.where(valid, pen, 0.0))

    starts = jnp.arange(n_tiles) * batch_size
    penalty = jnp.sum(jax.lax.map(tile_penalty, starts))
    return 1.0 - 2.0 / (n * k * (2.0 * n - 3.0 * k - 1.0)) * penalty


def _trustworthiness_colchunked(x, e, k, batch_size, col_batch_size):
    """Double-chunked trustworthiness: O(b·c) working set.

    Per query tile: (1) a scan over database chunks keeps a running
    embedded-space top-k (concat + ``lax.top_k`` merge — the warpsort-merge
    role), (2) ``d_sel`` comes from gathering the k selected rows directly,
    (3) a second scan counts, per chunk, the points strictly closer in
    original space than each selected neighbor.
    """
    n, dim_x = x.shape
    b = min(batch_size, n)
    c = min(col_batch_size, n)

    # pad the database axis once for both spaces; padded columns are
    # excluded by the col_id < n masks below
    padc = (-n) % c
    xc = jnp.concatenate([x, jnp.zeros((padc, dim_x), x.dtype)]) if padc else x
    ec = jnp.concatenate([e, jnp.zeros((padc, e.shape[1]), e.dtype)]) if padc else e
    xt = xc.reshape(-1, c, dim_x)                                 # (C, c, dx)
    et = ec.reshape(-1, c, e.shape[1])                            # (C, c, de)
    xnt = jnp.sum(xt * xt, axis=2)                                # (C, c)
    ent = jnp.sum(et * et, axis=2)
    col0 = jnp.arange(c)

    padb = (-n) % b
    xq = jnp.concatenate([x, jnp.zeros((padb, dim_x), x.dtype)]) if padb else x
    eq = jnp.concatenate([e, jnp.zeros((padb, e.shape[1]), e.dtype)]) if padb else e

    def tile_penalty(start):
        rows_x = jax.lax.dynamic_slice_in_dim(xq, start, b, 0)
        rows_e = jax.lax.dynamic_slice_in_dim(eq, start, b, 0)
        rows_xn = jnp.sum(rows_x * rows_x, axis=1)
        rows_en = jnp.sum(rows_e * rows_e, axis=1)
        row_ids = start + jnp.arange(b)
        valid = row_ids < n

        def emb_topk_step(carry, col):
            best_d, best_i = carry
            ci, eb, ebn = col
            cols = ci * c + col0
            d = rows_en[:, None] + ebn[None, :] \
                - 2.0 * jnp.matmul(rows_e, eb.T,
                                   preferred_element_type=jnp.float32)
            d = jnp.where((cols[None, :] == row_ids[:, None])
                          | (cols[None, :] >= n), jnp.inf, d)
            cat_d = jnp.concatenate([best_d, d], axis=1)
            cat_i = jnp.concatenate([best_i, jnp.broadcast_to(cols, d.shape)],
                                    axis=1)
            neg, pos = jax.lax.top_k(-cat_d, k)
            return (-neg, jnp.take_along_axis(cat_i, pos, axis=1)), None

        (_, emb_nn), _ = jax.lax.scan(
            emb_topk_step,
            (jnp.full((b, k), jnp.inf, jnp.float32),
             jnp.full((b, k), -1, jnp.int32)),
            (jnp.arange(xt.shape[0]), et, ent))

        def orig_chunk_d(col):
            """One (b, c) original-space distance chunk — shared by the
            d_sel extraction AND the rank count below.  d_sel MUST come
            from the identical arithmetic as the comparison distances: a
            separately-evaluated gather/einsum d_sel differs by ~1e-6 in
            f32, which makes selected neighbors count *themselves* as
            'closer' and systematically inflates ranks (measured: 289
            off-by-ones over a 333-row corpus)."""
            ci, xb, xbn = col
            cols = ci * c + col0
            d = jnp.maximum(
                rows_xn[:, None] + xbn[None, :]
                - 2.0 * jnp.matmul(rows_x, xb.T,
                                   preferred_element_type=jnp.float32), 0.0)
            return d, cols

        def dsel_step(acc, col):
            d, cols = orig_chunk_d(col)
            hit = emb_nn[:, :, None] == cols[None, None, :]       # (b, k, c)
            return acc + jnp.sum(jnp.where(hit, d[:, None, :], 0.0),
                                 axis=2), None

        cols_axes = (jnp.arange(xt.shape[0]), xt, xnt)
        d_sel, _ = jax.lax.scan(dsel_step, jnp.zeros((b, k), jnp.float32),
                                cols_axes)

        def rank_step(r, col):
            d, cols = orig_chunk_d(col)
            live = (cols[None, :] != row_ids[:, None]) & (cols[None, :] < n)
            closer = (d[:, None, :] < d_sel[:, :, None]) & live[:, None, :]
            return r + jnp.sum(closer, axis=2).astype(jnp.float32), None

        r, _ = jax.lax.scan(rank_step, jnp.zeros((b, k), jnp.float32),
                            cols_axes)
        pen = jnp.maximum(r - (k - 1), 0.0) * (r >= k)
        return jnp.sum(jnp.where(valid[:, None], pen, 0.0))

    starts = jnp.arange((n + b - 1) // b) * b
    penalty = jnp.sum(jax.lax.map(tile_penalty, starts))
    return 1.0 - 2.0 / (n * k * (2.0 * n - 3.0 * k - 1.0)) * penalty
