"""raft_tpu.stats — summary statistics + clustering/regression/ANN metrics.

TPU-native analog of ``cpp/include/raft/stats`` (SURVEY.md §2.7).
"""

from .summary import (
    mean, stddev, sum, meanvar, mean_center, mean_add,
    minmax, cov, weighted_mean, row_weighted_mean, col_weighted_mean,
    histogram, dispersion,
)
from .metrics import accuracy, r2_score, RegressionMetrics, regression_metrics, contingency_matrix
from .clustering import (
    adjusted_rand_index, rand_index, mutual_info_score, entropy,
    homogeneity_score, completeness_score, v_measure, kl_divergence,
    silhouette_score, IC_Type, information_criterion_batched,
)
from .neighborhood import neighborhood_recall, trustworthiness_score

__all__ = ["mean", "stddev", "sum", "meanvar", "mean_center", "mean_add",
    "minmax", "cov", "weighted_mean", "row_weighted_mean", "col_weighted_mean",
    "histogram", "dispersion", "accuracy", "r2_score", "RegressionMetrics",
    "regression_metrics", "contingency_matrix", "adjusted_rand_index",
    "rand_index", "mutual_info_score", "entropy", "homogeneity_score",
    "completeness_score", "v_measure", "kl_divergence", "silhouette_score",
    "IC_Type", "information_criterion_batched", "neighborhood_recall",
    "trustworthiness_score"]
