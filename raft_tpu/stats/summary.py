"""Summary statistics — parity with ``cpp/include/raft/stats``: ``mean.cuh:37``,
``stddev.cuh``, ``sum.cuh``, ``meanvar.cuh``, ``mean_center.cuh``,
``minmax.cuh``, ``cov.cuh``, ``weighted_mean.cuh``, ``histogram.cuh``
(multi-strategy kernel ``detail/histogram.cuh``), ``dispersion.cuh``.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

from ..core.array import wrap_array
from ..core.errors import expects

__all__ = [
    "mean", "stddev", "sum", "meanvar", "mean_center", "mean_add",
    "minmax", "cov", "weighted_mean", "row_weighted_mean", "col_weighted_mean",
    "histogram", "dispersion",
]


def mean(data, sample: bool = False, along_rows: bool = True):
    """Column means of a row-major matrix (``stats::mean``, ``mean.cuh:37``).

    ``sample`` selects the (n−1) divisor like the reference.
    """
    data = wrap_array(data, ndim=2)
    axis = 0 if along_rows else 1
    n = data.shape[axis]
    s = jnp.sum(data, axis=axis)
    return s / (n - 1 if sample else n)


def stddev(data, mu=None, sample: bool = True):
    """Column standard deviations (``stddev.cuh``)."""
    data = wrap_array(data, ndim=2)
    if mu is None:
        mu = jnp.mean(data, axis=0)
    n = data.shape[0]
    var = jnp.sum((data - mu[None, :]) ** 2, axis=0) / (n - 1 if sample else n)
    return jnp.sqrt(var)


def sum(data, along_rows: bool = True):
    """Column (or row) sums (``sum.cuh``)."""
    return jnp.sum(wrap_array(data, ndim=2), axis=0 if along_rows else 1)


def meanvar(data, sample: bool = True) -> Tuple[jax.Array, jax.Array]:
    """Fused mean+variance (``meanvar.cuh``)."""
    data = wrap_array(data, ndim=2)
    n = data.shape[0]
    mu = jnp.mean(data, axis=0)
    var = jnp.sum((data - mu[None, :]) ** 2, axis=0) / (n - 1 if sample else n)
    return mu, var


def mean_center(data, mu=None):
    """Subtract column means (``mean_center.cuh``)."""
    data = wrap_array(data, ndim=2)
    if mu is None:
        mu = jnp.mean(data, axis=0)
    return data - wrap_array(mu, ndim=1)[None, :]


def mean_add(data, mu):
    """Add column means back (``mean_center.cuh`` ``meanAdd``)."""
    return wrap_array(data, ndim=2) + wrap_array(mu, ndim=1)[None, :]


def minmax(data) -> Tuple[jax.Array, jax.Array]:
    """Per-column (min, max) (``minmax.cuh``)."""
    data = wrap_array(data, ndim=2)
    return jnp.min(data, axis=0), jnp.max(data, axis=0)


def cov(data, mu=None, sample: bool = True, stable: bool = True):
    """Covariance matrix (``cov.cuh``).  One MXU gram matmul."""
    data = wrap_array(data, ndim=2)
    if mu is None:
        mu = jnp.mean(data, axis=0)
    centered = data - wrap_array(mu, ndim=1)[None, :]
    n = data.shape[0]
    return jnp.matmul(centered.T, centered, preferred_element_type=jnp.float32) / (
        n - 1 if sample else n
    )


def weighted_mean(data, weights, along_rows: bool = True):
    """Weighted mean (``weighted_mean.cuh``)."""
    data = wrap_array(data, ndim=2)
    weights = wrap_array(weights, ndim=1)
    if along_rows:  # weight per row, average over rows → per-column result
        expects(weights.shape[0] == data.shape[0], "need one weight per row")
        return jnp.sum(data * weights[:, None], axis=0) / jnp.sum(weights)
    expects(weights.shape[0] == data.shape[1], "need one weight per column")
    return jnp.sum(data * weights[None, :], axis=1) / jnp.sum(weights)


def row_weighted_mean(data, weights):
    return weighted_mean(data, weights, along_rows=False)


def col_weighted_mean(data, weights):
    return weighted_mean(data, weights, along_rows=True)


def histogram(data, n_bins: int, lower: float = None, upper: float = None):
    """Per-column histograms (``histogram.cuh``).  The reference picks among
    smem/gmem atomic strategies; XLA lowers the one-hot sum onto the VPU."""
    data = wrap_array(data)
    if data.ndim == 1:
        data = data[:, None]
    lo = jnp.min(data) if lower is None else lower
    hi = jnp.max(data) if upper is None else upper
    width = jnp.where((hi - lo) > 0, (hi - lo) / n_bins, 1.0)
    bins = jnp.clip(((data - lo) / width).astype(jnp.int32), 0, n_bins - 1)
    onehot = jax.nn.one_hot(bins, n_bins, dtype=jnp.int32, axis=0)  # (n_bins, n, cols)
    return jnp.sum(onehot, axis=1)


def dispersion(centroids, cluster_sizes, global_centroid=None, n_points: int = None):
    """Between-cluster dispersion (``dispersion.cuh``)."""
    centroids = wrap_array(centroids, ndim=2)
    sizes = wrap_array(cluster_sizes, ndim=1)
    n = jnp.sum(sizes) if n_points is None else n_points
    if global_centroid is None:
        global_centroid = jnp.sum(centroids * sizes[:, None], axis=0) / n
    d2 = jnp.sum((centroids - global_centroid[None, :]) ** 2, axis=1)
    return jnp.sqrt(jnp.sum(d2 * sizes))
