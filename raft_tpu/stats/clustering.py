"""Clustering-quality metrics — parity with ``cpp/include/raft/stats``:
``adjusted_rand_index.cuh``, ``rand_index.cuh``, ``mutual_info_score.cuh``,
``entropy.cuh``, ``homogeneity_score.cuh``, ``completeness_score.cuh``,
``v_measure.cuh``, ``kl_divergence.cuh``, ``silhouette_score.cuh``
(+ ``detail/batched``), ``information_criterion.cuh``.

All are formulated over the contingency matrix (one scatter-add) + reductions.
"""

from __future__ import annotations

import enum
import math
from typing import Optional

import jax
import jax.numpy as jnp

from ..core.array import wrap_array
from ..core.errors import expects
from .metrics import contingency_matrix

__all__ = [
    "adjusted_rand_index", "rand_index", "mutual_info_score", "entropy",
    "homogeneity_score", "completeness_score", "v_measure", "kl_divergence",
    "silhouette_score", "IC_Type", "information_criterion_batched",
]


def _comb2(x):
    return x * (x - 1) / 2.0


def adjusted_rand_index(first, second, n_classes: Optional[int] = None):
    """ARI (``adjusted_rand_index.cuh``)."""
    c = contingency_matrix(first, second, n_classes).astype(jnp.float64 if jax.config.jax_enable_x64 else jnp.float32)
    n = jnp.sum(c)
    sum_comb_cells = jnp.sum(_comb2(c))
    a = jnp.sum(c, axis=1)
    b = jnp.sum(c, axis=0)
    sum_comb_a = jnp.sum(_comb2(a))
    sum_comb_b = jnp.sum(_comb2(b))
    expected = sum_comb_a * sum_comb_b / _comb2(n)
    max_index = 0.5 * (sum_comb_a + sum_comb_b)
    return (sum_comb_cells - expected) / (max_index - expected)


def rand_index(first, second):
    """Unadjusted Rand index (``rand_index.cuh``)."""
    a = wrap_array(first, ndim=1)
    b = wrap_array(second, ndim=1)
    same_a = a[:, None] == a[None, :]
    same_b = b[:, None] == b[None, :]
    n = a.shape[0]
    agree = jnp.sum((same_a == same_b).astype(jnp.float32)) - n  # drop diagonal
    return agree / (n * (n - 1))


def entropy(labels, n_classes: Optional[int] = None):
    """Shannon entropy of a label set, in nats (``entropy.cuh``)."""
    y = wrap_array(labels, ndim=1).astype(jnp.int32)
    if n_classes is None:
        n_classes = int(jnp.max(y)) + 1  # jaxlint: disable=JX01 output sizing needs a concrete bound; pass n_classes to stay async
    counts = jnp.zeros((n_classes,), jnp.float32).at[y].add(1.0)
    p = counts / y.shape[0]
    return -jnp.sum(jnp.where(p > 0, p * jnp.log(p), 0.0))


def mutual_info_score(first, second, n_classes: Optional[int] = None):
    """MI over the contingency matrix (``mutual_info_score.cuh``)."""
    c = contingency_matrix(first, second, n_classes).astype(jnp.float32)
    n = jnp.sum(c)
    pij = c / n
    pi = jnp.sum(pij, axis=1, keepdims=True)
    pj = jnp.sum(pij, axis=0, keepdims=True)
    ratio = pij / jnp.where(pi * pj > 0, pi * pj, 1.0)
    return jnp.sum(jnp.where(pij > 0, pij * jnp.log(jnp.where(ratio > 0, ratio, 1.0)), 0.0))


def homogeneity_score(truth, predicted, n_classes: Optional[int] = None):
    """(``homogeneity_score.cuh``): 1 − H(C|K)/H(C) via MI/entropy."""
    mi = mutual_info_score(truth, predicted, n_classes)
    h = entropy(truth, n_classes)
    return jnp.where(h > 0, mi / h, 1.0)


def completeness_score(truth, predicted, n_classes: Optional[int] = None):
    """(``completeness_score.cuh``)."""
    mi = mutual_info_score(truth, predicted, n_classes)
    h = entropy(predicted, n_classes)
    return jnp.where(h > 0, mi / h, 1.0)


def v_measure(truth, predicted, n_classes: Optional[int] = None, beta: float = 1.0):
    """(``v_measure.cuh``)."""
    h = homogeneity_score(truth, predicted, n_classes)
    c = completeness_score(truth, predicted, n_classes)
    denom = beta * h + c
    return jnp.where(denom > 0, (1 + beta) * h * c / denom, 0.0)


def kl_divergence(p, q):
    """KL(P‖Q) over densities (``kl_divergence.cuh``)."""
    p = wrap_array(p)
    q = wrap_array(q)
    return jnp.sum(jnp.where(p > 0, p * jnp.log(p / jnp.where(q > 0, q, 1.0)), 0.0))


def silhouette_score(x, labels, n_clusters: Optional[int] = None, batch_size: Optional[int] = None,
                     cluster_reduce: str = "auto"):
    """Mean silhouette coefficient (``silhouette_score.cuh`` + batched variant).

    Per-sample mean distance to each cluster via pairwise-distance tiles
    folded into per-cluster sums.  With ``batch_size`` the distance
    matrix is chunked along **both** axes (the ``detail/batched/
    silhouette_score.cuh:214-227`` double loop): each ``(c, c)`` tile is
    reduced to ``(c, n_clusters)`` cluster sums before the next tile is
    formed, so peak memory is ``O(c² + c·k)`` — never ``O(c·n)`` — and 1M-row
    corpora stream through a fixed-size working set.

    ``cluster_reduce`` picks how a distance tile becomes cluster sums:
    ``"matmul"`` multiplies by a dense one-hot (cost ∝ ``n_clusters``;
    on TPU the FLOPs ride the MXU), ``"segment"`` uses a ``segment_sum``
    scatter-add (k-independent, but scatter throughput is poor on
    matmul-oriented backends).  ``"auto"``: matmul on TPU always; on
    other backends matmul until ``n_clusters ≥ 512``, the measured CPU
    crossover (100k×96, c=4096: matmul 51 s vs segment 149 s at k=100;
    297 s vs 174 s at k=1000 — at 1M×96/k=1000 the one-hot matmul alone
    would add ~14 h single-core).
    """
    expects(cluster_reduce in ("auto", "matmul", "segment"),
            f"cluster_reduce={cluster_reduce!r} (want auto|matmul|segment)")
    x = wrap_array(x, ndim=2)
    y = wrap_array(labels, ndim=1).astype(jnp.int32)
    n, dim = x.shape
    if n_clusters is None:
        n_clusters = int(jnp.max(y)) + 1  # jaxlint: disable=JX01 output sizing needs a concrete bound; pass n_clusters to stay async
    if cluster_reduce == "auto":
        # decide from where x actually lives when knowable (a CPU-pinned
        # run on a TPU host must not land in the k-scaled matmul regime);
        # under tracing fall back to the default backend
        try:
            platform = next(iter(x.devices())).platform
        except Exception:  # noqa: BLE001 — tracer or uncommitted input
            platform = jax.default_backend()
        cluster_reduce = ("matmul" if platform == "tpu"
                          or n_clusters < 512 else "segment")
    counts = jnp.zeros((n_clusters,), jnp.float32).at[y].add(1.0)

    def cluster_sums(d, yb):
        """(rows, cols) distance block → (rows, k) per-cluster sums, where
        ``yb`` labels the COLUMN points (out-of-range labels — padding —
        contribute nothing in either formulation)."""
        if cluster_reduce == "matmul":
            oh = jax.nn.one_hot(yb, n_clusters, dtype=jnp.float32)
            return jnp.matmul(d, oh, preferred_element_type=jnp.float32)
        from ..linalg.reduce import reduce_cols_by_key

        return reduce_cols_by_key(d, yb, n_clusters)

    def per_sample_s(cluster_dist, yb):
        """Silhouette per row from its (rows, k) cluster distance sums."""
        own = counts[yb]
        own_dist = jnp.take_along_axis(cluster_dist, yb[:, None], axis=1)[:, 0]
        a = jnp.where(own > 1, own_dist / jnp.maximum(own - 1, 1.0), 0.0)
        mean_other = cluster_dist / jnp.maximum(counts[None, :], 1.0)
        mean_other = jnp.where(jax.nn.one_hot(yb, n_clusters, dtype=bool),
                               jnp.inf, mean_other)
        b = jnp.min(mean_other, axis=1)
        return jnp.where(own > 1,
                         (b - a) / jnp.maximum(jnp.maximum(a, b), 1e-12), 0.0)

    if batch_size is None or batch_size >= n:
        sq = jnp.sum(x * x, axis=1, keepdims=True) + jnp.sum(x * x, axis=1)[None, :] \
             - 2.0 * jnp.matmul(x, x.T, preferred_element_type=jnp.float32)
        d = jnp.sqrt(jnp.maximum(sq, 0.0))
        return jnp.mean(per_sample_s(cluster_sums(d, y), y))

    c = batch_size
    pad = (-n) % c
    xp = jnp.concatenate([x, jnp.zeros((pad, dim), x.dtype)])
    # padded points carry label == n_clusters: one_hot maps it to an
    # all-zero row, so they contribute nothing as columns; as rows they
    # are masked out of the mean below
    yp = jnp.concatenate([y, jnp.full((pad,), n_clusters, jnp.int32)])
    xt = xp.reshape(-1, c, dim)                                   # (T, c, d)
    nt = jnp.sum(xt * xt, axis=2)                                 # (T, c)
    yt = yp.reshape(-1, c)

    def row_tile(args):
        xb, xbn, yb = args

        def col_step(acc, col):
            xc, xcn, yc = col
            sq = xbn[:, None] + xcn[None, :] \
                 - 2.0 * jnp.matmul(xb, xc.T,
                                    preferred_element_type=jnp.float32)
            d = jnp.sqrt(jnp.maximum(sq, 0.0))                    # (c, c)
            # reduction built per column tile: an up-front (n, k) one-hot
            # would be the O(n·k) allocation this path exists to avoid
            return acc + cluster_sums(d, yc), None

        acc, _ = jax.lax.scan(
            col_step, jnp.zeros((c, n_clusters), jnp.float32), (xt, nt, yt))
        valid = yb < n_clusters
        s = per_sample_s(acc, jnp.minimum(yb, n_clusters - 1))
        return jnp.sum(jnp.where(valid, s, 0.0))

    return jnp.sum(jax.lax.map(row_tile, (xt, nt, yt))) / n


class IC_Type(enum.Enum):
    """``information_criterion.cuh`` (AIC / AICc / BIC)."""

    AIC = "aic"
    AICc = "aicc"
    BIC = "bic"


def information_criterion_batched(log_likelihood, ic_type: IC_Type, n_params: int, n_samples: int):
    """Batched information criterion (``information_criterion.cuh``)."""
    ll = wrap_array(log_likelihood)
    if ic_type == IC_Type.AIC:
        penalty = 2.0 * n_params
    elif ic_type == IC_Type.AICc:
        penalty = 2.0 * n_params + 2.0 * n_params * (n_params + 1) / max(n_samples - n_params - 1, 1)
    else:
        # n_samples is a host int: log it on the host — the former
        # jnp.log(jnp.asarray(float(n_samples))) dispatched a device op
        # (and an h2d transfer) for a static scalar, and its weak-f32
        # rounding of log(n) was pure loss
        penalty = math.log(n_samples) * n_params
    return -2.0 * ll + penalty
