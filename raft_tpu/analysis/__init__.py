"""raft_tpu.analysis — static hazard analysis for the library's hot paths.

The reference keeps itself honest with compile-time discipline (every
header compiled in every consumption mode, ``cpp/tests/CMakeLists.txt``
ext_headers).  Our equivalent failure class is JAX-specific: silent host
syncs, per-call recompilation, and dtype leaks that CPU-pinned tests
never see.  :mod:`.jaxlint` is the AST pass that gates them; the runtime
side (``raft_tpu.core.trace_guard``) asserts the same properties on live
dispatches.  Rule catalog: ``docs/jax_hygiene.md``.

:mod:`.racelint` is the concurrency sibling: guarded-attribute writes,
lock-order consistency, blocking calls under locks, and daemon threads
touching jax dispatch (JX10..JX14).  Its runtime arm is
:mod:`raft_tpu.core.lockdep` — instrumented locks that record the
cross-module lock-order graph the AST pass cannot see.

This package imports only the standard library (no jax) so lint tooling
can load it without touching an accelerator backend.
"""

from . import racelint
from .jaxlint import (
    ALL_RULES,
    Finding,
    Report,
    scan_file,
    scan_source,
    scan_tree,
)

__all__ = [
    "ALL_RULES",
    "Finding",
    "Report",
    "racelint",
    "scan_file",
    "scan_source",
    "scan_tree",
]
