"""racelint — AST rules for the concurrency hazards the threaded serving
stack can hide from single-process tests.

The fleet tier left the tree with ~50 lock/thread primitives across ~20
files; :mod:`.jaxlint` (JX01–JX05) gates JAX hygiene but says nothing
about thread safety.  These five rules do, driven by lightweight
source-comment annotations (catalog with bad/good snippets:
``docs/jax_hygiene.md``):

* **JX10** shared-attribute write outside its declared guard — an
  attribute declared ``# guarded_by: _lock`` is written (assigned,
  augmented, subscript-stored, or mutated via ``append``/``update``/…)
  in a method that neither holds ``with self._lock:`` lexically nor is
  annotated ``# racelint: holds _lock``.  ``__init__``/``__new__`` are
  exempt (objects under construction are thread-private, and classmethod
  constructors building via ``cls.__new__`` are invisible to the rule by
  construction — their writes target a local, not the first parameter).
* **JX11** inconsistent lock-acquisition order — within one file, if
  some code path acquires B while holding A and another acquires A while
  holding B, both inner acquisitions are flagged: two threads on those
  paths deadlock.  Cross-file composition is the runtime arm's job
  (:mod:`raft_tpu.core.lockdep` watches the live order graph).
* **JX12** blocking call while holding a lock — ``sleep``, ``fsync``/
  ``fdatasync``, socket ``send``/``sendall``/``recv``/``accept``/
  ``connect``, ``block_until_ready``, ``device_get`` under a held lock
  serializes every other thread behind a device round-trip or disk/
  network wait.  Matching strips leading underscores, so an injected
  ``self._fsync(...)`` seam counts.  (``join`` is deliberately absent:
  ``str.join``/``os.path.join`` drown the signal — lockdep's hold-time
  flag covers thread joins dynamically.)
* **JX13** callback invoked under an undocumented lock — calling a
  hook-shaped attribute (``on_*``, ``*_hook(s)``, ``*_callback(s)``),
  directly or via ``for h in self.on_x:``, while a lock is held, unless
  the hook list's declaration documents it with ``# called_under:
  _lock``.  Undocumented reentrancy is how callback deadlocks are born;
  documented reentrancy is a contract callees can read.
* **JX14** daemon thread touching JAX dispatch — a ``threading.Thread``
  whose target (including same-class helpers it calls) references
  ``jax``/``jnp``, outside the pallas gate module.  Background dispatch
  must either go through the gate or own its compiled executable; the
  waiver's reason is where that ownership gets written down.

Annotations::

    self._pending = []        # guarded_by: _cond
    self.on_commit = []       # called_under: _lock ships in LSN order
    def _write(self, ...):    # racelint: holds _lock

Per-line waivers, jaxlint-style (reason mandatory — a bare ``disable=``
is itself a finding, **JXW1**, not waivable)::

    self._fsync(fd)  # racelint: disable=JX12 maintenance path, appends go lock-free

Pure standard library (``ast``); importable without jax.  Entry point:
``python scripts/mini_lint.py --race raft_tpu``; census artifact:
``bench/RACELINT.json``.
"""

from __future__ import annotations

import ast
import dataclasses
import os
import re
from typing import Dict, List, Optional, Set, Tuple

__all__ = ["ALL_RULES", "Finding", "Report", "scan_source", "scan_file",
           "scan_tree"]

ALL_RULES: Dict[str, str] = {
    "JX10": "shared-attribute write outside its declared guard",
    "JX11": "inconsistent lock-acquisition order (deadlock cycle)",
    "JX12": "blocking call while holding a lock",
    "JX13": "callback invoked under an undocumented lock",
    "JX14": "daemon thread touching JAX dispatch without the gate",
    "JXW1": "waiver without a written reason",
}

# drivers/tests own their blocking and their threads; guard discipline
# (JX10/JX11) is annotation-driven, so it applies tree-wide
_JX12_ALLOW_SEGMENTS = {"tests", "bench", "scripts"}
_JX14_ALLOW_SEGMENTS = {"tests", "bench", "scripts"}
_JX14_ALLOW_FILES = ("ops/pallas/gate.py",)  # the probe IS the gate

_WAIVER_RE = re.compile(
    r"#\s*racelint:\s*disable=([A-Z0-9]+(?:\s*,\s*[A-Z0-9]+)*)\s*(.*)")
_GUARD_RE = re.compile(r"#\s*guarded_by:\s*([A-Za-z_]\w*)")
_CALLED_UNDER_RE = re.compile(r"#\s*called_under:\s*([A-Za-z_]\w*)")
_HOLDS_RE = re.compile(r"#\s*racelint:\s*holds\s+([A-Za-z_]\w*)")

_LOCK_CTORS = {"Lock", "RLock", "Condition", "lock", "rlock", "condition"}
_BLOCKING = {"sleep", "fsync", "fdatasync", "sendall", "send", "sendto",
             "recv", "recv_into", "recvfrom", "accept", "connect",
             "block_until_ready", "device_get"}
_MUTATORS = {"append", "appendleft", "extend", "extendleft", "insert",
             "pop", "popleft", "remove", "discard", "clear", "add",
             "update", "setdefault", "sort"}
_HOOKISH = re.compile(r"^on_|(_hooks?|_callbacks?)$")
_JAX_ROOTS = {"jax", "jnp"}


@dataclasses.dataclass
class Finding:
    """One rule hit.  ``waived`` hits are kept for stats but do not fail
    the lint; ``reason`` carries the waiver's justification text."""

    path: str
    line: int
    code: str
    msg: str
    waived: bool = False
    reason: str = ""


@dataclasses.dataclass
class Report:
    """Tree-scan result: active findings, audited waivers, file count."""

    findings: List[Finding]
    waived: List[Finding]
    files: int

    def rules_fired(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for f in self.findings + self.waived:
            out[f.code] = out.get(f.code, 0) + 1
        return out

    def stats(self) -> dict:
        """The ``bench/RACELINT.json`` schema (same shape as
        JAXLINT.json so the ratchet tooling reads both)."""
        waivers: Dict[str, int] = {}
        for f in self.waived:
            waivers[f.code] = waivers.get(f.code, 0) + 1
        return {
            "tool": "racelint",
            "files_scanned": self.files,
            "rules_fired": self.rules_fired(),
            "unwaived_findings": len(self.findings),
            "waivers": waivers,
            "waiver_total": len(self.waived),
            "waiver_sites": sorted(
                f"{f.path}:{f.line} {f.code} {f.reason}" for f in self.waived),
            "rule_catalog": dict(ALL_RULES),
        }


# ---------------------------------------------------------------------------
# annotation + helper plumbing


def _attr_chain(node: ast.AST) -> List[str]:
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return parts[::-1]
    return []


def _is_lock_ctor(value: ast.AST) -> bool:
    """``threading.Lock()`` / ``lockdep.lock("...")`` / bare
    ``Condition()`` — anything whose callee bottoms out in a lock ctor
    name."""
    if not isinstance(value, ast.Call):
        return False
    chain = _attr_chain(value.func)
    return bool(chain) and chain[-1] in _LOCK_CTORS


def _first_param(fn: ast.FunctionDef) -> Optional[str]:
    args = fn.args.posonlyargs + fn.args.args
    return args[0].arg if args else None


@dataclasses.dataclass
class _ClassInfo:
    name: str
    locks: Set[str] = dataclasses.field(default_factory=set)
    guards: Dict[str, str] = dataclasses.field(default_factory=dict)
    called_under: Dict[str, str] = dataclasses.field(default_factory=dict)
    methods: Dict[str, ast.FunctionDef] = dataclasses.field(
        default_factory=dict)
    jax_methods: Set[str] = dataclasses.field(default_factory=set)


def _line_annotations(src: str):
    guards: Dict[int, str] = {}
    called: Dict[int, str] = {}
    holds: Dict[int, str] = {}
    waivers: Dict[int, Tuple[set, str]] = {}
    for i, line in enumerate(src.split("\n"), 1):
        m = _GUARD_RE.search(line)
        if m:
            guards[i] = m.group(1)
        m = _CALLED_UNDER_RE.search(line)
        if m:
            called[i] = m.group(1)
        m = _HOLDS_RE.search(line)
        if m:
            holds[i] = m.group(1)
        m = _WAIVER_RE.search(line)
        if m:
            codes = {c.strip() for c in m.group(1).split(",")}
            waivers[i] = (codes, m.group(2).strip())
    return guards, called, holds, waivers


def _mentions_jax(fn: ast.FunctionDef) -> bool:
    for sub in ast.walk(fn):
        if isinstance(sub, ast.Name) and sub.id in _JAX_ROOTS:
            return True
        if isinstance(sub, (ast.Import, ast.ImportFrom)):
            for alias in sub.names:
                if alias.name.split(".")[0] in _JAX_ROOTS:
                    return True
    return False


def _self_calls(fn: ast.FunctionDef, self_name: str) -> Set[str]:
    out: Set[str] = set()
    for sub in ast.walk(fn):
        if isinstance(sub, ast.Call) and isinstance(sub.func, ast.Attribute):
            v = sub.func.value
            if isinstance(v, ast.Name) and v.id == self_name:
                out.add(sub.func.attr)
    return out


def _collect_class(node: ast.ClassDef, guard_lines: Dict[int, str],
                   called_lines: Dict[int, str]) -> _ClassInfo:
    info = _ClassInfo(node.name)
    for stmt in node.body:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            info.methods[stmt.name] = stmt
            self_name = _first_param(stmt)
            for sub in ast.walk(stmt):
                targets: List[ast.AST] = []
                value = None
                if isinstance(sub, ast.Assign):
                    targets, value = list(sub.targets), sub.value
                elif isinstance(sub, ast.AnnAssign):
                    targets, value = [sub.target], sub.value
                for t in targets:
                    if isinstance(t, ast.Attribute) and isinstance(
                            t.value, ast.Name) and t.value.id in (
                                self_name, "self"):
                        if value is not None and _is_lock_ctor(value):
                            info.locks.add(t.attr)
                        g = guard_lines.get(sub.lineno)
                        if g:
                            info.guards[t.attr] = g
                        c = called_lines.get(sub.lineno)
                        if c:
                            info.called_under[t.attr] = c
        elif isinstance(stmt, (ast.Assign, ast.AnnAssign)):
            targets = stmt.targets if isinstance(stmt, ast.Assign) \
                else [stmt.target]
            for t in targets:
                if isinstance(t, ast.Name):
                    g = guard_lines.get(stmt.lineno)
                    if g:
                        info.guards[t.id] = g
    # transitive same-class jax taint for JX14 (fixpoint over self-calls)
    mentions = {name: _mentions_jax(fn)
                for name, fn in info.methods.items()}
    calls = {name: _self_calls(fn, _first_param(fn) or "self")
             for name, fn in info.methods.items()}
    changed = True
    while changed:
        changed = False
        for name in info.methods:
            if mentions[name]:
                continue
            if any(mentions.get(c, False) for c in calls[name]):
                mentions[name] = True
                changed = True
    info.jax_methods = {n for n, hit in mentions.items() if hit}
    return info


# ---------------------------------------------------------------------------
# the scanner


class _FileScanner:
    def __init__(self, rel: str, src: str) -> None:
        self.rel = (rel or "").replace(os.sep, "/")
        segs = set(self.rel.split("/")[:-1])
        base = os.path.basename(self.rel)
        is_test = base.startswith("test_") or base == "conftest.py"
        self.jx12_exempt = bool(segs & _JX12_ALLOW_SEGMENTS) or is_test
        self.jx13_exempt = is_test or bool(segs & {"tests"})
        self.jx14_exempt = bool(segs & _JX14_ALLOW_SEGMENTS) or is_test \
            or any(self.rel.endswith(f) for f in _JX14_ALLOW_FILES)
        (self.guard_lines, self.called_lines, self.holds_lines,
         self.waivers) = _line_annotations(src)
        self.raw: List[Tuple[int, int, str, str]] = []
        self.mod_locks: Set[str] = set()
        self.mod_guards: Dict[str, str] = {}
        self.mod_fn_jax: Dict[str, bool] = {}
        self.edges: List[Tuple[str, str, int, int]] = []  # a, b, line, end

    def _hit(self, node: ast.AST, code: str, msg: str) -> None:
        self.raw.append((node.lineno, getattr(node, "end_lineno",
                                              node.lineno), code, msg))

    # -- lock resolution ----------------------------------------------------

    def _resolve_lock(self, expr: ast.AST, cls: Optional[_ClassInfo],
                      self_name: Optional[str]) -> Optional[str]:
        if isinstance(expr, ast.Attribute) and isinstance(
                expr.value, ast.Name):
            if cls is not None and expr.value.id == self_name \
                    and expr.attr in cls.locks:
                return f"{cls.name}.{expr.attr}"
            return None
        if isinstance(expr, ast.Name) and expr.id in self.mod_locks:
            return f"<module>.{expr.id}"
        return None

    def _qualify_guard(self, guard: str, cls: Optional[_ClassInfo]) -> str:
        if cls is not None and guard in cls.locks:
            return f"{cls.name}.{guard}"
        if guard in self.mod_locks:
            return f"<module>.{guard}"
        # a guard naming a lock the scanner can't see (e.g. injected):
        # fall back to the raw name so `holds` annotations still match
        return guard

    # -- module scan --------------------------------------------------------

    def scan(self, tree: ast.Module) -> None:
        # module-level locks + guarded globals first (order-independent)
        for stmt in tree.body:
            targets = []
            value = None
            if isinstance(stmt, ast.Assign):
                targets, value = stmt.targets, stmt.value
            elif isinstance(stmt, ast.AnnAssign):
                targets, value = [stmt.target], stmt.value
            for t in targets:
                if isinstance(t, ast.Name):
                    if value is not None and _is_lock_ctor(value):
                        self.mod_locks.add(t.id)
                    g = self.guard_lines.get(stmt.lineno)
                    if g:
                        self.mod_guards[t.id] = g
        mod_fns = {s.name: s for s in tree.body
                   if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef))}
        self.mod_fn_jax = {n: _mentions_jax(fn) for n, fn in mod_fns.items()}
        for stmt in tree.body:
            if isinstance(stmt, ast.ClassDef):
                cls = _collect_class(stmt, self.guard_lines,
                                     self.called_lines)
                for m in cls.methods.values():
                    self._scan_function(m, cls)
            elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._scan_function(stmt, None)
            else:
                # module-level statements: with-blocks at import time
                self._walk_stmt(stmt, [], None, None, in_ctor=True)
        self._emit_jx11()

    def _emit_jx11(self) -> None:
        pairs = {(a, b) for a, b, _, _ in self.edges}

        def reachable(src: str, dst: str) -> bool:
            seen, stack = {src}, [src]
            while stack:
                n = stack.pop()
                if n == dst:
                    return True
                for (a, b) in pairs:
                    if a == n and b not in seen:
                        seen.add(b)
                        stack.append(b)
            return False

        for a, b, line, end in self.edges:
            if reachable(b, a):
                self.raw.append((
                    line, end, "JX11",
                    f"acquires {b} while holding {a}, but another path"
                    f" orders {b} before {a} — two threads on these paths"
                    " deadlock; pick one global order"))

    # -- function scan ------------------------------------------------------

    def _scan_function(self, fn: ast.FunctionDef,
                       cls: Optional[_ClassInfo]) -> None:
        self_name = _first_param(fn) if cls is not None else None
        held: List[str] = []
        h = self.holds_lines.get(fn.lineno)
        if h is None and fn.body:
            # decorated defs: the annotation may sit on the def line while
            # lineno points at the first decorator
            for cand in range(fn.lineno, fn.body[0].lineno):
                if cand in self.holds_lines:
                    h = self.holds_lines[cand]
                    break
        if h:
            held.append(self._qualify_guard(h, cls))
        in_ctor = cls is not None and fn.name in ("__init__", "__new__")
        for stmt in fn.body:
            self._walk_stmt(stmt, held, cls, self_name, in_ctor=in_ctor)

    def _walk_stmt(self, stmt: ast.stmt, held: List[str],
                   cls: Optional[_ClassInfo], self_name: Optional[str],
                   *, in_ctor: bool) -> None:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # a nested def runs later, under whatever locks its caller
            # holds — scan it with a clean slate (its own holds apply)
            self._scan_function(stmt, None)
            return
        if isinstance(stmt, ast.ClassDef):
            return
        if isinstance(stmt, ast.For):
            self._check_hook_loop(stmt, held, cls, self_name)
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            acquired: List[str] = []
            for item in stmt.items:
                self._check_expr(item.context_expr, held, cls, self_name,
                                 in_ctor=in_ctor, is_with_item=True)
                name = self._resolve_lock(item.context_expr, cls, self_name)
                if name is not None:
                    for outer in held + acquired:
                        if outer != name:
                            self.edges.append((outer, name,
                                               item.context_expr.lineno,
                                               stmt.lineno))
                    acquired.append(name)
            inner = held + acquired
            for s in stmt.body:
                self._walk_stmt(s, inner, cls, self_name, in_ctor=in_ctor)
            return
        # statement-level writes (JX10)
        if isinstance(stmt, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            targets = stmt.targets if isinstance(stmt, ast.Assign) \
                else [stmt.target]
            for t in targets:
                self._check_write(t, stmt, held, cls, self_name,
                                  in_ctor=in_ctor)
        if isinstance(stmt, ast.Delete):
            for t in stmt.targets:
                self._check_write(t, stmt, held, cls, self_name,
                                  in_ctor=in_ctor)
        # expressions within this statement (calls: JX12/13/14 + mutators)
        for child in ast.iter_child_nodes(stmt):
            if isinstance(child, ast.expr):
                self._check_expr(child, held, cls, self_name,
                                 in_ctor=in_ctor)
        # recurse into control-flow bodies with the same held set
        for field in ("body", "orelse", "finalbody"):
            for s in getattr(stmt, field, []) or []:
                if isinstance(s, ast.stmt):
                    self._walk_stmt(s, held, cls, self_name,
                                    in_ctor=in_ctor)
        for handler in getattr(stmt, "handlers", []) or []:
            for s in handler.body:
                self._walk_stmt(s, held, cls, self_name, in_ctor=in_ctor)

    # -- write + call checks ------------------------------------------------

    def _guard_of(self, target: ast.AST, cls: Optional[_ClassInfo],
                  self_name: Optional[str]
                  ) -> Optional[Tuple[str, str, str]]:
        """(attr_display, qualified_guard, raw_guard) when ``target`` is a
        guarded attribute reference."""
        if isinstance(target, ast.Attribute) and isinstance(
                target.value, ast.Name):
            if cls is not None and target.value.id == self_name \
                    and target.attr in cls.guards:
                raw = cls.guards[target.attr]
                return (f"self.{target.attr}",
                        self._qualify_guard(raw, cls), raw)
            return None
        if isinstance(target, ast.Name) and target.id in self.mod_guards:
            raw = self.mod_guards[target.id]
            return (target.id, self._qualify_guard(raw, None), raw)
        return None

    def _check_write(self, target: ast.AST, stmt: ast.stmt,
                     held: List[str], cls: Optional[_ClassInfo],
                     self_name: Optional[str], *, in_ctor: bool) -> None:
        if in_ctor:
            return
        base = target
        if isinstance(base, (ast.Subscript, ast.Starred)):
            base = base.value
        g = self._guard_of(base, cls, self_name)
        if g is None:
            return
        attr, qualified, raw = g
        if qualified in held or raw in held:
            return
        self._hit(stmt, "JX10",
                  f"write to {attr} (guarded_by: {raw}) without holding"
                  f" {raw}; wrap in `with ...{raw}:` or annotate the"
                  " method `# racelint: holds" f" {raw}`")

    def _check_expr(self, expr: ast.expr, held: List[str],
                    cls: Optional[_ClassInfo], self_name: Optional[str],
                    *, in_ctor: bool, is_with_item: bool = False) -> None:
        excluded: Set[int] = set()
        for sub in ast.walk(expr):
            if isinstance(sub, ast.Lambda):
                for leaf in ast.walk(sub):
                    if leaf is not sub:
                        excluded.add(id(leaf))
        for sub in ast.walk(expr):
            if id(sub) in excluded or not isinstance(sub, ast.Call):
                continue
            fn = sub.func
            attr = fn.attr if isinstance(fn, ast.Attribute) else (
                fn.id if isinstance(fn, ast.Name) else "")
            norm = attr.lstrip("_")
            # JX10 via mutator methods on guarded containers
            if isinstance(fn, ast.Attribute) and attr in _MUTATORS \
                    and not in_ctor:
                g = self._guard_of(fn.value, cls, self_name)
                if g is not None:
                    disp, qualified, raw = g
                    if qualified not in held and raw not in held:
                        self._hit(sub, "JX10",
                                  f".{attr}() on {disp} (guarded_by:"
                                  f" {raw}) without holding {raw}")
            # JX12 — blocking under a lock
            if held and norm in _BLOCKING and not self.jx12_exempt \
                    and not is_with_item:
                self._hit(sub, "JX12",
                          f"blocking call {attr}() while holding"
                          f" {held[-1]} stalls every thread queued on"
                          " it; move the wait outside the critical"
                          " section")
            # JX13 — hook under an undocumented lock
            if held and not self.jx13_exempt:
                hook_attr = None
                if isinstance(fn, ast.Attribute) and isinstance(
                        fn.value, ast.Name) and fn.value.id == self_name \
                        and _HOOKISH.search(attr):
                    hook_attr = attr
                if hook_attr is not None and cls is not None \
                        and hook_attr not in cls.called_under:
                    self._hit(sub, "JX13",
                              f"callback self.{hook_attr}(...) invoked"
                              f" while holding {held[-1]} but its"
                              " declaration does not document it; add"
                              " `# called_under:" f" {held[-1].split('.')[-1]}`"
                              " to the attribute or move the call out")
            # JX14 — thread creation with a jax-touching target
            chain = _attr_chain(fn)
            if chain and chain[-1] == "Thread" and not self.jx14_exempt:
                target_name, target_jax = None, False
                for kw in sub.keywords:
                    if kw.arg == "target":
                        v = kw.value
                        if isinstance(v, ast.Attribute) and isinstance(
                                v.value, ast.Name):
                            target_name = v.attr
                            if cls is not None and v.value.id == self_name:
                                target_jax = v.attr in cls.jax_methods
                        elif isinstance(v, ast.Name):
                            target_name = v.id
                            target_jax = self.mod_fn_jax.get(v.id, False)
                if target_jax:
                    self._hit(sub, "JX14",
                              f"thread target {target_name} reaches jax"
                              " dispatch from a background thread; route"
                              " it through the pallas gate or document"
                              " the owned executable in a waiver")

    def _check_hook_loop(self, stmt: ast.For, held: List[str],
                         cls: Optional[_ClassInfo],
                         self_name: Optional[str]) -> None:
        if not held or self.jx13_exempt or cls is None:
            return
        it = stmt.iter
        if isinstance(it, ast.Call) and isinstance(it.func, ast.Name) \
                and it.func.id in ("list", "tuple", "sorted") and it.args:
            it = it.args[0]
        if not (isinstance(it, ast.Attribute) and isinstance(
                it.value, ast.Name) and it.value.id == self_name):
            return
        attr = it.attr
        if not _HOOKISH.search(attr) or attr in cls.called_under:
            return
        if not isinstance(stmt.target, ast.Name):
            return
        var = stmt.target.id
        for sub in ast.walk(stmt):
            if isinstance(sub, ast.Call) and isinstance(sub.func, ast.Name) \
                    and sub.func.id == var:
                self._hit(sub, "JX13",
                          f"hook from self.{attr} invoked while holding"
                          f" {held[-1]} but the attribute's declaration"
                          " does not document it; add `# called_under:"
                          f" {held[-1].split('.')[-1]}` or call outside"
                          " the lock")


# ---------------------------------------------------------------------------
# entry points


def scan_source(src: str, path: str, rel: Optional[str] = None
                ) -> List[Finding]:
    """Scan one source string; returns all findings, waived ones marked.

    ``rel`` is the path relative to the scan root (used for the
    driver/test allowlists); defaults to ``path``."""
    try:
        tree = ast.parse(src, path)
    except SyntaxError as e:
        return [Finding(path, e.lineno or 0, "JX99",
                        f"unparseable: {e.msg}")]
    scanner = _FileScanner(rel if rel is not None else path, src)
    scanner.scan(tree)
    findings: List[Finding] = []
    waivers = scanner.waivers
    for line, end, code, msg in sorted(scanner.raw):
        waived, reason = False, ""
        for cand in (line, end):
            codes_reason = waivers.get(cand)
            if codes_reason and code in codes_reason[0]:
                waived, reason = True, codes_reason[1]
                break
        findings.append(Finding(path, line, code, msg, waived, reason))
    for line, (codes, reason) in sorted(waivers.items()):
        if not reason:
            findings.append(Finding(
                path, line, "JXW1",
                f"waiver for {','.join(sorted(codes))} has no written"
                " reason; justify it or fix the hazard"))
    return findings


def scan_file(path: str, root: Optional[str] = None) -> List[Finding]:
    with open(path, encoding="utf-8") as f:
        src = f.read()
    rel = os.path.relpath(path, root) if root else path
    return scan_source(src, path, rel)


def scan_tree(root: str) -> Report:
    """Walk ``root`` (skipping caches/VCS dirs) and aggregate a
    :class:`Report`."""
    skip = {".git", "__pycache__", ".claude", "node_modules", ".venv"}
    active: List[Finding] = []
    waived: List[Finding] = []
    files = 0
    base = root if os.path.isdir(root) else os.path.dirname(root) or "."
    paths = []
    if os.path.isdir(root):
        for dirpath, dirnames, filenames in os.walk(root):
            dirnames[:] = [d for d in dirnames if d not in skip]
            paths.extend(os.path.join(dirpath, fn)
                         for fn in filenames if fn.endswith(".py"))
    else:
        paths = [root]
    for path in sorted(paths):
        files += 1
        for f in scan_file(path, base):
            (waived if f.waived else active).append(f)
    return Report(active, waived, files)
