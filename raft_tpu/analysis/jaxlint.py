"""jaxlint — AST rules for the JAX/TPU hazard classes that CPU-pinned
tests never see.

The five rules (catalog with bad/good snippets: ``docs/jax_hygiene.md``):

* **JX01** host sync in library code — ``float()``/``int()``/``bool()``/
  ``.item()``/``.tolist()``/``np.asarray()``/``jax.device_get()`` applied
  to a value derived from ``jax``/``jnp``.  Each sync stalls the dispatch
  pipeline for a device round-trip; on TPU that is the difference between
  a saturated MXU and a host-bound loop.  Host-boundary modules
  (``serve/``, ``io/``, ``compat/`` and the ``core`` transfer helpers)
  are exempt: fetching results *is* their job.
* **JX02** recompilation hazard — Python ``if``/``while`` on a
  tracer-derived value inside a jitted function (concretization →
  retrace per value), ``jax.jit(f)(x)`` immediate invocation, or a
  ``jax.jit`` call inside a loop (a fresh jit wrapper per iteration has
  a fresh cache: every call compiles).
* **JX03** dtype hygiene — explicit ``float64``/``np.double`` requests
  that silently downcast to f32 with x64 off (and double memory traffic
  with it on).  Usages gated on ``jax_enable_x64`` are recognized and
  skipped.
* **JX04** impure host call inside jit — ``np.random``/``random``/
  ``time`` calls in a jitted function bake one sample/timestamp into the
  compiled program: correct-looking on the first call, frozen forever
  after.
* **JX05** blocking call — ``block_until_ready`` outside ``serve/``,
  ``bench/``, ``scripts/``: library code must stay async; only drivers
  and the serving dispatch own completion barriers.

Per-line waivers::

    res = float(residual)  # jaxlint: disable=JX01 one scalar sync per convergence check

The reason text is mandatory — a bare ``disable=`` is itself a finding
(**JXW0**, not waivable), so every exemption in the tree carries a
written justification a reviewer can audit.

Pure standard library (``ast``); importable without jax so lint tooling
stays accelerator-free.  Entry point: ``python scripts/mini_lint.py
--jax raft_tpu``.
"""

from __future__ import annotations

import ast
import dataclasses
import os
import re
from typing import Dict, List, Optional, Tuple

__all__ = ["ALL_RULES", "Finding", "Report", "scan_source", "scan_file",
           "scan_tree"]

ALL_RULES: Dict[str, str] = {
    "JX01": "host sync in library code",
    "JX02": "recompilation hazard",
    "JX03": "float64 dtype leak",
    "JX04": "impure host call inside jit",
    "JX05": "blocking call outside serve/bench/scripts",
    "JXW0": "waiver without a written reason",
}

# Directory segments / file suffixes whose job is the host boundary.
_JX01_ALLOW_SEGMENTS = {"serve", "io", "compat", "bench", "scripts", "tests"}
_JX01_ALLOW_FILES = (
    "core/array.py",       # to_numpy is the sanctioned fetch
    "core/copy.py",        # explicit H<->D copy API
    "core/serialize.py",   # serialization is a host format
    "core/host_memory.py",
    "core/buffer.py",      # memory_type dispatch spans host/device
    "core/memory.py",      # live-bytes accounting reads device stats
    "core/interruptible.py",  # sync points are its purpose
    "comms/selftest.py",   # diagnostic harness: verifying collectives on
                           # the host is the module's entire job
)
_JX05_ALLOW_SEGMENTS = {"serve", "bench", "scripts", "tests"}
_JX05_ALLOW_FILES = ("core/interruptible.py", "core/resources.py")

_WAIVER_RE = re.compile(
    r"#\s*jaxlint:\s*disable=([A-Z0-9]+(?:\s*,\s*[A-Z0-9]+)*)\s*(.*)")

_SYNC_BUILTINS = {"float", "int", "bool", "complex"}
_SYNC_METHODS = {"item", "tolist"}
_JAX_ROOTS = {"jax", "jnp"}
_TIME_ATTRS = {"time", "perf_counter", "monotonic", "process_time",
               "thread_time", "sleep", "perf_counter_ns", "time_ns",
               "monotonic_ns"}


@dataclasses.dataclass
class Finding:
    """One rule hit.  ``waived`` hits are kept for stats but do not fail
    the lint; ``reason`` carries the waiver's justification text."""

    path: str
    line: int
    code: str
    msg: str
    waived: bool = False
    reason: str = ""


@dataclasses.dataclass
class Report:
    """Tree-scan result: active findings, audited waivers, file count."""

    findings: List[Finding]
    waived: List[Finding]
    files: int

    def rules_fired(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for f in self.findings + self.waived:
            out[f.code] = out.get(f.code, 0) + 1
        return out

    def stats(self) -> dict:
        """The ``bench/JAXLINT.json`` schema — the artifact re-anchor
        reviewers watch for the waiver count trending to zero."""
        waivers: Dict[str, int] = {}
        for f in self.waived:
            waivers[f.code] = waivers.get(f.code, 0) + 1
        return {
            "tool": "jaxlint",
            "files_scanned": self.files,
            "rules_fired": self.rules_fired(),
            "unwaived_findings": len(self.findings),
            "waivers": waivers,
            "waiver_total": len(self.waived),
            "waiver_sites": sorted(
                f"{f.path}:{f.line} {f.code} {f.reason}" for f in self.waived),
            "rule_catalog": dict(ALL_RULES),
        }


# ---------------------------------------------------------------------------
# helpers


def _rel_segments(rel: Optional[str]) -> Tuple[set, str]:
    rel = (rel or "").replace(os.sep, "/")
    return set(rel.split("/")[:-1]), rel


_STATIC_ATTRS = {"ndim", "shape", "dtype", "size", "sharding", "itemsize",
                 "weak_type"}
# jax/jnp callables whose results are host values known at trace time —
# neither a sync hazard nor a retrace hazard
_STATIC_CALLS = {"issubdtype", "isdtype", "result_type", "promote_types",
                 "canonicalize_dtype", "dtype", "iinfo", "finfo",
                 "default_backend", "devices", "device_count",
                 "local_device_count", "local_devices", "process_index",
                 "process_count"}
# dtype-valued attributes (jnp.int8, np.float32, ...): static objects, not
# traced arrays — comparing against them must not taint a name
_DTYPE_ATTRS = {"float16", "float32", "bfloat16", "int8", "uint8", "int16",
                "uint16", "int32", "uint32", "int64", "uint64", "bool_",
                "complex64", "complex128", "integer", "floating", "inexact",
                "signedinteger", "unsignedinteger", "number", "generic"}


def _is_sync_sink(call: ast.Call) -> bool:
    """``float(x)`` / ``x.item()`` / ``np.asarray(x)`` / ``jax.device_get``
    — calls whose result lives on the host."""
    fn = call.func
    if isinstance(fn, ast.Name):
        return fn.id in _SYNC_BUILTINS
    if isinstance(fn, ast.Attribute):
        if fn.attr in _SYNC_METHODS or fn.attr == "device_get":
            return True
        chain = _attr_chain(fn)
        return bool(chain) and chain[0] in ("np", "numpy") \
            and chain[-1] in ("asarray", "array")
    return False


def _mentions_jax(node: ast.AST, tainted: set) -> bool:
    """True when the expression subtree references jax/jnp or a name
    assigned from such an expression (one-hop local dataflow).

    Accesses through static metadata (``x.shape[0]``, ``x.ndim``,
    ``x.dtype``, ``jnp.issubdtype(...)``, ``jax.default_backend()``) are
    *not* traced values — they are known at trace time and neither sync
    nor retrace — so their subtrees are excluded before the name check."""
    excluded: set = set()
    for sub in ast.walk(node):
        static = isinstance(sub, ast.Attribute) \
            and sub.attr in (_STATIC_ATTRS | _DTYPE_ATTRS)
        if isinstance(sub, ast.Call):
            fn = sub.func
            if isinstance(fn, ast.Attribute) and fn.attr in _STATIC_CALLS:
                static = True
            # a sync sink's RESULT is a host value: taint stops there (the
            # sink call itself is still checked by the JX01 visitor)
            elif _is_sync_sink(sub):
                static = True
        if static:
            for leaf in ast.walk(sub):
                excluded.add(id(leaf))
    for sub in ast.walk(node):
        if id(sub) in excluded:
            continue
        if isinstance(sub, ast.Name) and (sub.id in _JAX_ROOTS
                                          or sub.id in tainted):
            return True
    return False


def _attr_chain(node: ast.AST) -> List[str]:
    """``jax.random.fold_in`` -> ["jax", "random", "fold_in"]; [] when the
    chain does not bottom out in a Name."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return parts[::-1]
    return []


def _is_jit_expr(node: ast.AST) -> bool:
    """Matches ``jit`` / ``jax.jit`` / ``partial(jax.jit, ...)`` /
    ``functools.partial(jax.jit, ...)``."""
    if isinstance(node, ast.Name):
        return node.id == "jit"
    if isinstance(node, ast.Attribute):
        chain = _attr_chain(node)
        return bool(chain) and chain[-1] == "jit"
    if isinstance(node, ast.Call):
        chain = _attr_chain(node.func)
        if chain and chain[-1] == "partial" and node.args:
            return _is_jit_expr(node.args[0])
    return False


def _scope_nodes(body: List[ast.stmt]):
    """All nodes of a scope's own statements, descending through control
    flow but NOT into nested function/class/lambda scopes — their locals
    must not leak taint into this one."""
    stack: List[ast.AST] = list(body)
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef, ast.Lambda)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def _collect_taint(fn_body: List[ast.stmt]) -> set:
    """Names assigned from jax-derived expressions in this scope.  Two
    passes give one-hop transitivity (``y = f(x); z = y + 1``) without a
    fixpoint loop; nested scopes are excluded (their locals are not ours)."""
    tainted: set = set()
    for _ in range(2):
        for stmt in fn_body:
            for sub in _scope_nodes([stmt]):
                value = None
                targets: List[ast.AST] = []
                if isinstance(sub, ast.Assign):
                    value, targets = sub.value, list(sub.targets)
                elif isinstance(sub, ast.AnnAssign) and sub.value is not None:
                    value, targets = sub.value, [sub.target]
                elif isinstance(sub, ast.AugAssign):
                    value, targets = sub.value, [sub.target]
                if value is None or not _mentions_jax(value, tainted):
                    continue
                for t in targets:
                    # only plain-name bindings: `obj.attr = v` / `x[i] = v`
                    # must not taint `obj`/`x` (the container is unchanged
                    # as a name; attribute loads are checked at use sites)
                    for leaf in ast.walk(t):
                        if isinstance(leaf, (ast.Attribute, ast.Subscript)):
                            break
                    else:
                        for leaf in ast.walk(t):
                            if isinstance(leaf, ast.Name):
                                tainted.add(leaf.id)
    return tainted


class _Scanner(ast.NodeVisitor):
    def __init__(self, rel: str) -> None:
        self.rel = rel
        segs, relpath = _rel_segments(rel)
        base = os.path.basename(relpath)
        self.jx01_exempt = bool(segs & _JX01_ALLOW_SEGMENTS) or any(
            relpath.endswith(f) for f in _JX01_ALLOW_FILES) \
            or base.startswith("test_") or base == "conftest.py"
        self.jx05_exempt = bool(segs & _JX05_ALLOW_SEGMENTS) or any(
            relpath.endswith(f) for f in _JX05_ALLOW_FILES) \
            or base.startswith("test_") or base == "conftest.py"
        self.raw: List[Tuple[int, int, str, str]] = []  # (line, end, code, msg)
        self._jit_depth = 0
        self._loop_depth = 0
        self._x64_guard = 0
        self._taint: List[set] = [set()]

    # -- bookkeeping --------------------------------------------------------

    def _hit(self, node: ast.AST, code: str, msg: str) -> None:
        self.raw.append((node.lineno, getattr(node, "end_lineno",
                                              node.lineno), code, msg))

    def _tainted(self) -> set:
        return self._taint[-1]

    def visit_FunctionDef(self, node):  # noqa: N802 (ast API)
        jitted = any(_is_jit_expr(d) for d in node.decorator_list)
        self._jit_depth += 1 if jitted else 0
        scope = set(self._tainted())
        scope |= _collect_taint(node.body)
        self._taint.append(scope)
        for d in node.decorator_list:
            self.visit(d)
        for stmt in node.body:
            self.visit(stmt)
        self._taint.pop()
        self._jit_depth -= 1 if jitted else 0

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Module(self, node):  # noqa: N802
        self._taint[0] |= _collect_taint(node.body)
        self.generic_visit(node)

    def _loop(self, node):
        self._loop_depth += 1
        self.generic_visit(node)
        self._loop_depth -= 1

    # -- JX02a: tracer control flow / x64 gates -----------------------------

    def _test_mentions_x64(self, test: ast.AST) -> bool:
        return any(isinstance(s, (ast.Attribute, ast.Name))
                   and "enable_x64" in (getattr(s, "attr", "")
                                        or getattr(s, "id", ""))
                   for s in ast.walk(test))

    def _check_branch(self, node, kind: str) -> bool:
        """Returns True when the branch is an x64 gate (suppresses JX03
        inside)."""
        if self._test_mentions_x64(node.test):
            return True
        if self._jit_depth > 0 and not (
                isinstance(node.test, ast.Compare)
                and any(isinstance(op, (ast.Is, ast.IsNot))
                        for op in node.test.ops)):
            if _mentions_jax(node.test, self._tainted()):
                self._hit(node, "JX02",
                          f"Python `{kind}` on a traced value inside jit —"
                          " concretizes the tracer (retrace per value); use"
                          " lax.cond/while_loop or jnp.where")
        return False

    def visit_If(self, node):  # noqa: N802
        gate = self._check_branch(node, "if")
        self._x64_guard += 1 if gate else 0
        self.generic_visit(node)
        self._x64_guard -= 1 if gate else 0

    def visit_IfExp(self, node):  # noqa: N802
        gate = self._test_mentions_x64(node.test)
        static_none = isinstance(node.test, ast.Compare) and any(
            isinstance(op, (ast.Is, ast.IsNot)) for op in node.test.ops)
        if not gate and not static_none and self._jit_depth > 0 \
                and _mentions_jax(node.test, self._tainted()):
            self._hit(node, "JX02",
                      "conditional expression on a traced value inside jit;"
                      " use jnp.where")
        self._x64_guard += 1 if gate else 0
        self.generic_visit(node)
        self._x64_guard -= 1 if gate else 0

    def visit_While(self, node):  # noqa: N802
        self._check_branch(node, "while")
        self._loop(node)

    visit_For = _loop
    visit_AsyncFor = _loop

    # -- expression rules ---------------------------------------------------

    def visit_Attribute(self, node):  # noqa: N802
        chain = _attr_chain(node)
        if chain:
            dotted = ".".join(chain)
            if chain[-1] in ("float64", "double") \
                    and chain[0] in ("np", "numpy", "jnp", "jax") \
                    and self._x64_guard == 0:
                self._hit(node, "JX03",
                          f"{dotted}: silently downcasts to f32 with x64"
                          " off (or doubles memory traffic with it on);"
                          " request an explicit f32/bf16 dtype")
            if self._jit_depth > 0:
                # fire on the `np.random` node itself, not again on every
                # enclosing `np.random.<fn>` attribute above it
                if chain[:2] in (["np", "random"], ["numpy", "random"]) \
                        and len(chain) == 2:
                    self._hit(node, "JX04",
                              f"{dotted} inside jit bakes one sample into"
                              " the compiled program; thread a"
                              " jax.random key instead")
                elif chain[0] == "random" and len(chain) > 1:
                    self._hit(node, "JX04",
                              f"stdlib {dotted} inside jit bakes one sample"
                              " into the compiled program; thread a"
                              " jax.random key instead")
                elif chain[0] == "time" and chain[-1] in _TIME_ATTRS:
                    self._hit(node, "JX04",
                              f"{dotted} inside jit freezes one timestamp"
                              " into the compiled program; time on the"
                              " host, outside jit")
        self.generic_visit(node)

    def visit_Call(self, node):  # noqa: N802
        tainted = self._tainted()
        fn = node.func
        # JX01 — host syncs
        if not self.jx01_exempt:
            if isinstance(fn, ast.Name) and fn.id in _SYNC_BUILTINS \
                    and len(node.args) == 1 \
                    and _mentions_jax(node.args[0], tainted):
                self._hit(node, "JX01",
                          f"{fn.id}() on a jax value forces a blocking"
                          " device->host sync; keep it on-device"
                          " (jnp.where / lax.cond) or fetch once at the"
                          " API boundary")
            elif isinstance(fn, ast.Attribute):
                chain = _attr_chain(fn)
                if fn.attr in _SYNC_METHODS \
                        and _mentions_jax(fn.value, tainted):
                    self._hit(node, "JX01",
                              f".{fn.attr}() on a jax value is a blocking"
                              " device->host sync")
                elif chain and chain[0] in ("np", "numpy") \
                        and chain[-1] in ("asarray", "array") \
                        and node.args \
                        and _mentions_jax(node.args[0], tainted):
                    self._hit(node, "JX01",
                              "np.asarray/np.array on a jax value is a"
                              " blocking device->host transfer")
                elif chain and chain[0] == "jax" \
                        and chain[-1] == "device_get":
                    self._hit(node, "JX01",
                              "jax.device_get is a blocking device->host"
                              " transfer")
        # JX02b — jit misuse
        if isinstance(fn, ast.Call) and _is_jit_expr(fn.func):
            self._hit(node, "JX02",
                      "jax.jit(f)(args) compiles a fresh wrapper per call"
                      " (empty cache every time); jit once at def site or"
                      " cache the wrapper")
        if _is_jit_expr(fn) and self._loop_depth > 0:
            self._hit(node, "JX02",
                      "jax.jit inside a loop creates a new wrapper (and"
                      " empty compile cache) per iteration; hoist it out")
        # JX05 — completion barriers
        if not self.jx05_exempt:
            attr = fn.attr if isinstance(fn, ast.Attribute) else \
                (fn.id if isinstance(fn, ast.Name) else "")
            if attr == "block_until_ready":
                self._hit(node, "JX05",
                          "block_until_ready in library code serializes"
                          " the dispatch pipeline; only serve/, bench/,"
                          " scripts/ own completion barriers")
        self.generic_visit(node)


# ---------------------------------------------------------------------------
# waivers + entry points


def _waivers_by_line(src: str) -> Dict[int, Tuple[set, str]]:
    out: Dict[int, Tuple[set, str]] = {}
    for i, line in enumerate(src.split("\n"), 1):
        m = _WAIVER_RE.search(line)
        if m:
            codes = {c.strip() for c in m.group(1).split(",")}
            out[i] = (codes, m.group(2).strip())
    return out


def scan_source(src: str, path: str, rel: Optional[str] = None
                ) -> List[Finding]:
    """Scan one source string; returns all findings, waived ones marked.

    ``rel`` is the path relative to the scan root (used for the
    host-boundary allowlists); defaults to ``path``.
    """
    try:
        tree = ast.parse(src, path)
    except SyntaxError as e:
        return [Finding(path, e.lineno or 0, "JX99",
                        f"unparseable: {e.msg}")]
    scanner = _Scanner(rel if rel is not None else path)
    scanner.visit(tree)
    waivers = _waivers_by_line(src)
    findings: List[Finding] = []
    consumed: set = set()
    for line, end, code, msg in sorted(scanner.raw):
        waived, reason = False, ""
        for cand in (line, end):
            codes_reason = waivers.get(cand)
            if codes_reason and code in codes_reason[0]:
                waived, reason = True, codes_reason[1]
                consumed.add(cand)
                break
        findings.append(Finding(path, line, code, msg, waived, reason))
    for line, (codes, reason) in sorted(waivers.items()):
        if not reason:
            findings.append(Finding(
                path, line, "JXW0",
                f"waiver for {','.join(sorted(codes))} has no written"
                " reason; justify it or fix the hazard"))
    return findings


def scan_file(path: str, root: Optional[str] = None) -> List[Finding]:
    with open(path, encoding="utf-8") as f:
        src = f.read()
    rel = os.path.relpath(path, root) if root else path
    return scan_source(src, path, rel)


def scan_tree(root: str) -> Report:
    """Walk ``root`` (skipping caches/VCS dirs) and aggregate a
    :class:`Report`."""
    skip = {".git", "__pycache__", ".claude", "node_modules", ".venv"}
    active: List[Finding] = []
    waived: List[Finding] = []
    files = 0
    base = root if os.path.isdir(root) else os.path.dirname(root) or "."
    paths = []
    if os.path.isdir(root):
        for dirpath, dirnames, filenames in os.walk(root):
            dirnames[:] = [d for d in dirnames if d not in skip]
            paths.extend(os.path.join(dirpath, fn)
                         for fn in filenames if fn.endswith(".py"))
    else:
        paths = [root]
    for path in sorted(paths):
        files += 1
        for f in scan_file(path, base):
            (waived if f.waived else active).append(f)
    return Report(active, waived, files)
