"""Sparse subsystem — TPU-native parity with ``cpp/include/raft/sparse``
(SURVEY.md §2.5): COO/CSR containers, format conversions, sparse linalg
(SpMV/SpMM/SDDMM/masked-matmul/laplacian/symmetrize), structural ops,
text-statistics preprocessing, CSR top-k, and the solver family
(Lanczos, randomized SVD, MST) under :mod:`raft_tpu.sparse.solver`.
"""

from .types import COO, CSR
from .convert import (
    adj_to_csr,
    bitmap_to_csr,
    bitset_to_csr,
    coo_to_csr,
    coo_to_dense,
    csr_to_coo,
    csr_to_dense,
    dense_to_coo,
    dense_to_csr,
    sorted_coo_to_csr,
)
from .linalg import (
    compute_graph_laplacian,
    coo_degree,
    coo_symmetrize,
    csr_add,
    csr_row_norm,
    csr_row_normalize_l1,
    csr_row_normalize_max,
    csr_transpose,
    masked_matmul,
    sddmm,
    spmm,
    spmv,
)
from .ops import (
    coo_max_duplicates,
    coo_remove_scalar,
    coo_remove_zeros,
    coo_sort,
    coo_sum_duplicates,
    csr_diagonal,
    csr_row_op,
    csr_set_diagonal,
    csr_slice_rows,
)
from .preprocessing import encode_bm25, encode_tfidf
from .select_k import csr_select_k

__all__ = [
    "COO", "CSR",
    "adj_to_csr", "bitmap_to_csr", "bitset_to_csr", "coo_to_csr",
    "coo_to_dense", "csr_to_coo", "csr_to_dense", "dense_to_coo",
    "dense_to_csr", "sorted_coo_to_csr",
    "compute_graph_laplacian", "coo_degree", "coo_symmetrize", "csr_add",
    "csr_row_norm", "csr_row_normalize_l1", "csr_row_normalize_max",
    "csr_transpose", "masked_matmul", "sddmm", "spmm", "spmv",
    "coo_max_duplicates", "coo_remove_scalar", "coo_remove_zeros", "coo_sort",
    "coo_sum_duplicates", "csr_diagonal", "csr_row_op", "csr_set_diagonal",
    "csr_slice_rows",
    "encode_bm25", "encode_tfidf",
    "csr_select_k",
]
