"""CSR top-k — ``sparse/matrix/select_k.cuh`` parity.

The reference routes CSR rows through the same radix/warpsort machinery as the
dense ``matrix::select_k``.  The TPU formulation densifies the ragged rows
into a ``[n_rows, width]`` tile (width = longest row, padded with ±inf) and
reuses the dense select_k path — the MXU/VPU have no ragged layout, so this
is the layout the hardware wants anyway.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

from ..matrix.select_k import SelectAlgo, select_k as dense_select_k
from .types import CSR

__all__ = ["csr_select_k"]


def csr_select_k(
    csr: CSR,
    k: int,
    *,
    select_min: bool = True,
    algo: SelectAlgo = SelectAlgo.kAuto,
) -> Tuple[jax.Array, jax.Array]:
    """Top-k per CSR row → ``(values, column_indices)`` of ``[n_rows, k]``.

    Rows shorter than ``k`` are padded with ±inf values and ``-1`` indices
    (the reference's bounds contract for ``select_k``).
    """
    width = int(jnp.max(csr.row_lengths())) if csr.n_rows else 0  # jaxlint: disable=JX01 static pad width sizes the dense gather; must be a host int
    width = max(width, 1)
    pad = jnp.inf if select_min else -jnp.inf

    rid = csr.row_ids()
    valid = rid < csr.n_rows
    rid_c = jnp.minimum(rid, csr.n_rows - 1)
    pos = jnp.arange(csr.capacity, dtype=jnp.int32) - jnp.take(csr.indptr, rid_c)
    pos = jnp.clip(pos, 0, width - 1)

    dense_vals = jnp.full((csr.n_rows, width), pad, csr.data.dtype)
    dense_vals = dense_vals.at[rid_c, pos].set(
        jnp.where(valid, csr.data, pad), mode="drop"
    )
    dense_idx = jnp.full((csr.n_rows, width), -1, jnp.int32)
    dense_idx = dense_idx.at[rid_c, pos].set(
        jnp.where(valid, csr.indices, -1), mode="drop"
    )

    vals, pos_idx = dense_select_k(dense_vals, k, select_min=select_min, algo=algo)
    cols = jnp.take_along_axis(dense_idx, jnp.clip(pos_idx, 0, width - 1), axis=1)
    cols = jnp.where(pos_idx >= 0, cols, -1)
    # entries that selected padding report -1
    cols = jnp.where(jnp.isfinite(vals), cols, -1)
    return vals, cols
