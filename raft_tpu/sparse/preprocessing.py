"""Text statistics over CSR — ``sparse/matrix/preprocessing.cuh`` parity
(``encode_tfidf:28,63``, ``encode_bm25:~86``).

The CSR is the document-term matrix: rows = documents, columns = terms,
values = raw term counts.
"""

from __future__ import annotations

import jax.numpy as jnp

from .linalg import csr_row_norm
from .types import CSR

__all__ = ["encode_tfidf", "encode_bm25"]


def _doc_frequencies(csr: CSR):
    """Per-term document frequency and total docs with any term."""
    valid = jnp.arange(csr.capacity) < csr.nnz
    present = (valid & (csr.data != 0)).astype(jnp.float32)
    df = jnp.zeros((csr.n_cols,), jnp.float32).at[csr.indices].add(
        jnp.where(valid, present, 0)
    )
    return df


def encode_tfidf(csr: CSR) -> CSR:
    """TF-IDF re-weighting (``preprocessing.cuh`` ``encode_tfidf``):
    value := tf * log(1 + n_docs / (1 + df)), tf = raw count."""
    df = _doc_frequencies(csr)
    n_docs = jnp.float32(csr.n_rows)
    idf = jnp.log1p(n_docs / (1.0 + df))
    data = csr.data * jnp.take(idf, csr.indices)
    valid = jnp.arange(csr.capacity) < csr.nnz
    return CSR(csr.indptr, csr.indices, jnp.where(valid, data, 0),
               csr.shape, csr.nnz)


def encode_bm25(csr: CSR, k1: float = 1.6, b: float = 0.75) -> CSR:
    """Okapi BM25 re-weighting (``preprocessing.cuh`` ``encode_bm25``):
    value := idf * tf*(k1+1) / (tf + k1*(1 - b + b*len_d/avg_len))."""
    df = _doc_frequencies(csr)
    n_docs = jnp.float32(csr.n_rows)
    idf = jnp.log1p(n_docs / (1.0 + df))
    doc_len = csr_row_norm(csr, "l1")  # total term count per doc
    avg_len = jnp.mean(doc_len)
    rid = jnp.minimum(csr.row_ids(), csr.n_rows - 1)
    len_d = jnp.take(doc_len, rid)
    tf = csr.data
    denom = tf + k1 * (1.0 - b + b * len_d / jnp.maximum(avg_len, 1e-12))
    data = jnp.take(idf, csr.indices) * tf * (k1 + 1.0) / jnp.maximum(denom, 1e-12)
    valid = jnp.arange(csr.capacity) < csr.nnz
    return CSR(csr.indptr, csr.indices, jnp.where(valid, data, 0),
               csr.shape, csr.nnz)
