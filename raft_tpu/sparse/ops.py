"""Structural sparse ops — ``sparse/op/*.cuh`` parity.

nnz-changing ops (filter, dedup) can't produce dynamic shapes under XLA; the
convention here is **compact-in-place**: valid entries are moved to the prefix
(stable argsort on the keep-mask — the XLA replacement for the reference's
scan-compact kernels), pads carry sentinel coordinates, and the new nnz is
returned.  Host-eager callers get exact-size results via ``.trimmed()``.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp

from ..core.errors import expects
from .types import COO, CSR

__all__ = [
    "coo_sort",
    "coo_remove_scalar",
    "coo_remove_zeros",
    "coo_sum_duplicates",
    "coo_max_duplicates",
    "csr_row_op",
    "csr_slice_rows",
    "csr_diagonal",
    "csr_set_diagonal",
]


def coo_sort(coo: COO) -> COO:
    """Sort by (row, col), pads last (``sparse/op/sort.cuh`` coo_sort — cub
    radix sort role).  Lexicographic via two stable argsorts — overflow-safe
    for any shape, and XLA fuses both passes."""
    pad_rows = jnp.where(coo.pad_mask(), coo.rows, coo.shape[0])
    order = jnp.argsort(coo.cols, stable=True)
    order = order[jnp.argsort(pad_rows[order], stable=True)]
    return COO(coo.rows[order], coo.cols[order], coo.vals[order],
               coo.shape, coo.nnz)


def _compact(coo: COO, keep: jax.Array) -> COO:
    """Stable-partition kept entries to the prefix; returns new COO whose
    ``nnz`` is the kept count (host-side int when possible)."""
    keep = keep & coo.pad_mask()
    order = jnp.argsort(~keep, stable=True)  # kept first, stable
    rows = jnp.where(keep[order], coo.rows[order], coo.shape[0])
    cols = jnp.where(keep[order], coo.cols[order], coo.shape[1])
    vals = jnp.where(keep[order], coo.vals[order], 0)
    n_kept = int(jnp.sum(keep))  # jaxlint: disable=JX01 mirrors the reference's cudaMemcpy of the compacted count (detail/coo.cuh coo_remove_scalar)
    # cudaMemcpy of the compacted count (detail/coo.cuh coo_remove_scalar)
    return COO(rows, cols, vals, coo.shape, n_kept)


def coo_remove_scalar(coo: COO, scalar) -> COO:
    """Drop entries equal to ``scalar`` (``sparse/op/filter.cuh``
    ``coo_remove_scalar``)."""
    return _compact(coo, coo.vals != scalar)


def coo_remove_zeros(coo: COO) -> COO:
    return coo_remove_scalar(coo, 0)


def _dedup(coo: COO, combine: str) -> COO:
    """Merge duplicate (row, col) runs after sorting.

    ``sparse/op/reduce.cuh`` keeps the max dupe (``max_duplicates``);
    symmetrize wants sums.  Segment-combine over run ids keeps everything
    static-shaped: runs get ids via a prefix sum over "new key" flags.
    """
    s = coo_sort(coo)
    same = (s.rows[1:] == s.rows[:-1]) & (s.cols[1:] == s.cols[:-1]) & s.pad_mask()[1:]
    new_run = jnp.concatenate([jnp.ones((1,), bool), ~same])
    run_id = jnp.cumsum(new_run) - 1  # [cap]
    n_runs = s.capacity  # upper bound for segment ops
    if combine == "sum":
        merged = jax.ops.segment_sum(s.vals, run_id, num_segments=n_runs)
    else:
        merged = jax.ops.segment_max(s.vals, run_id, num_segments=n_runs)
    # representative entry of each run = first occurrence
    first_pos = jnp.where(new_run, jnp.arange(s.capacity), s.capacity)
    rep = jax.ops.segment_min(first_pos, run_id, num_segments=n_runs)
    rep_c = jnp.minimum(rep, s.capacity - 1)
    rows = jnp.where(rep < s.capacity, s.rows[rep_c], s.shape[0])
    cols = jnp.where(rep < s.capacity, s.cols[rep_c], s.shape[1])
    valid_run = (rep < s.capacity) & (rows < s.shape[0])
    vals = jnp.where(valid_run, merged, 0)
    out = COO(rows.astype(jnp.int32), cols.astype(jnp.int32), vals,
              s.shape, s.nnz)
    return _compact(out, valid_run)


def coo_sum_duplicates(coo: COO) -> COO:
    """Merge duplicates by summation (symmetrize contract,
    ``sparse/linalg/symmetrize.cuh``)."""
    return _dedup(coo, "sum")


def coo_max_duplicates(coo: COO) -> COO:
    """Keep max duplicate (``sparse/op/reduce.cuh`` ``max_duplicates``)."""
    return _dedup(coo, "max")


def csr_row_op(csr: CSR, fn: Callable) -> CSR:
    """Apply ``fn(row_id, values) -> values`` across rows
    (``sparse/op/row_op.cuh`` ``csr_row_op`` — per-row lambda kernel).
    Vectorized: fn receives the per-element row-id array and data."""
    rid = csr.row_ids()
    data = fn(jnp.minimum(rid, csr.n_rows - 1), csr.data)
    return CSR(csr.indptr, csr.indices, data, csr.shape, csr.nnz)


def csr_slice_rows(csr: CSR, start: int, stop: int) -> CSR:
    """Row-range slice (``sparse/op/slice.cuh`` ``csr_row_slice``).

    Static bounds (host ints) — the reference also computes the value range on
    the host before launching the copy.
    """
    expects(0 <= start <= stop <= csr.n_rows, "row slice out of range")
    lo = int(csr.indptr[start])
    hi = int(csr.indptr[stop])
    indptr = csr.indptr[start : stop + 1] - lo
    return CSR(indptr, csr.indices[lo:hi], csr.data[lo:hi],
               (stop - start, csr.n_cols), hi - lo)


def csr_diagonal(csr: CSR) -> jax.Array:
    """Extract the main diagonal (``sparse/matrix/diagonal.cuh``)."""
    rid, valid = csr.row_ids(), csr.row_ids() < csr.n_rows
    on_diag = valid & (rid == csr.indices)
    rid_c = jnp.minimum(rid, csr.n_rows - 1)
    return jnp.zeros((csr.n_rows,), csr.data.dtype).at[rid_c].add(
        jnp.where(on_diag, csr.data, 0)
    )


def csr_set_diagonal(csr: CSR, values) -> CSR:
    """Overwrite existing diagonal entries (``sparse/matrix/diagonal.cuh``
    ``set_diagonal`` — requires the diagonal to be present in the pattern)."""
    rid = csr.row_ids()
    on_diag = (rid < csr.n_rows) & (rid == csr.indices)
    rid_c = jnp.minimum(rid, csr.n_rows - 1)
    data = jnp.where(on_diag, jnp.take(values, rid_c), csr.data)
    return CSR(csr.indptr, csr.indices, data, csr.shape, csr.nnz)
