"""Sparse linear algebra — ``sparse/linalg/*.cuh`` parity.

The reference delegates SpMM/SDDMM to cuSPARSE (``linalg/spmm.hpp:51-78``,
``linalg/sddmm.hpp:59``) and hand-writes the rest.  On TPU there is no vendor
sparse library; the idiomatic formulations are:

* **SpMV/SpMM** — gather dense rows by column index, scale by values,
  ``segment_sum`` by row id.  XLA lowers gather+segment-sum onto the VPU with
  good HBM locality for the moderate-nnz matrices RAFT targets.
* **SDDMM / masked matmul** — compute only the sampled dot products:
  gather A-rows and B-cols at the nonzero coordinates and contract on the MXU
  as a batched dot.
* structure ops (symmetrize, laplacian, transpose) — index arithmetic + sort,
  no kernels.
"""

from __future__ import annotations


import jax
import jax.numpy as jnp

from ..core.errors import expects
from .convert import coo_to_csr, csr_to_coo
from .types import COO, CSR

__all__ = [
    "spmv",
    "spmm",
    "sddmm",
    "masked_matmul",
    "csr_add",
    "coo_degree",
    "csr_row_normalize_l1",
    "csr_row_normalize_max",
    "csr_row_norm",
    "csr_transpose",
    "coo_symmetrize",
    "compute_graph_laplacian",
]


def _expanded(csr: CSR):
    rid = csr.row_ids()
    valid = jnp.arange(csr.capacity) < csr.nnz
    return rid, valid


def spmv(csr: CSR, x, *, alpha: float = 1.0, beta: float = 0.0, y=None) -> jax.Array:
    """y = alpha * A @ x + beta * y (cuSPARSE SpMV role in the Lanczos loop,
    ``sparse/detail/cusparse_wrappers.h``)."""
    rid, valid = _expanded(csr)
    contrib = jnp.where(valid, csr.data * x[csr.indices], 0)
    out = jax.ops.segment_sum(contrib, rid, num_segments=csr.n_rows + 1)[: csr.n_rows]
    out = alpha * out
    if y is not None and beta != 0.0:
        out = out + beta * y
    return out


def spmm(csr: CSR, b, *, alpha: float = 1.0, beta: float = 0.0, c=None) -> jax.Array:
    """C = alpha * A @ B + beta * C (``sparse/linalg/spmm.hpp:51-78``).

    Gather B rows at the nonzero columns ([cap, n] slab), scale by values,
    segment-sum into C rows.  For tall B this is bandwidth-bound exactly like
    cuSPARSE's row-split SpMM.
    """
    expects(b.ndim == 2 and b.shape[0] == csr.n_cols, "spmm: B shape mismatch")
    rid, valid = _expanded(csr)
    gathered = jnp.take(b, csr.indices, axis=0)  # [cap, n]
    scaled = jnp.where(valid[:, None], csr.data[:, None] * gathered, 0)
    out = jax.ops.segment_sum(scaled, rid, num_segments=csr.n_rows + 1)[: csr.n_rows]
    out = alpha * out
    if c is not None and beta != 0.0:
        out = out + beta * c
    return out


def sddmm(a, b, mask: CSR, *, alpha: float = 1.0, beta: float = 0.0) -> CSR:
    """Sampled dense-dense matmul (``sparse/linalg/sddmm.hpp:59``):
    out.data[k] = alpha * <A[row_k], B[:, col_k]> + beta * mask.data[k].

    Only the sampled dots are computed: two gathers + a batched contraction —
    the MXU-friendly formulation of cuSPARSE SDDMM.
    """
    expects(a.shape[1] == b.shape[0], "sddmm: inner dims must match")
    rid, valid = _expanded(mask)
    rid_c = jnp.minimum(rid, a.shape[0] - 1)
    a_rows = jnp.take(a, rid_c, axis=0)          # [cap, k]
    b_cols = jnp.take(b.T, mask.indices, axis=0)  # [cap, k]
    dots = jnp.sum(a_rows * b_cols, axis=1)
    vals = jnp.where(valid, alpha * dots + beta * mask.data, 0)
    return CSR(mask.indptr, mask.indices, vals, mask.shape, mask.nnz)


def masked_matmul(a, b, mask: CSR) -> CSR:
    """(A @ B^T) sampled at mask positions (``linalg/masked_matmul.cuh``) —
    B given row-major as in the reference's bench suite."""
    return sddmm(a, b.T, mask, alpha=1.0, beta=0.0)


def csr_add(a: CSR, b: CSR) -> CSR:
    """C = A + B with duplicate merging (``sparse/linalg/add.cuh``).

    Concatenate entries, sort by (row, col), sum duplicate runs.  The result
    keeps capacity ``a.nnz + b.nnz`` with merged entries in the prefix (exact
    nnz recoverable host-side via ``trimmed_dedup`` semantics).
    """
    expects(a.shape == b.shape, "csr_add: shape mismatch")
    ra, va_ = a.row_ids(), a.data
    rb, vb_ = b.row_ids(), b.data
    rows = jnp.concatenate([ra[: a.nnz], rb[: b.nnz]])
    cols = jnp.concatenate([a.indices[: a.nnz], b.indices[: b.nnz]])
    vals = jnp.concatenate([va_[: a.nnz], vb_[: b.nnz]])
    coo = COO(rows, cols, vals, a.shape, rows.shape[0])
    from .ops import coo_sum_duplicates  # local import: ops depends on linalg types only

    return coo_to_csr(coo_sum_duplicates(coo))


def coo_degree(coo: COO) -> jax.Array:
    """Per-row nonzero count (``sparse/linalg/degree.cuh``)."""
    valid = coo.pad_mask()
    ones = jnp.where(valid, 1, 0).astype(jnp.int32)
    return jax.ops.segment_sum(ones, coo.rows,
                               num_segments=coo.shape[0] + 1)[: coo.shape[0]]


def csr_row_norm(csr: CSR, norm: str = "l2") -> jax.Array:
    """Row norms over a CSR (``sparse/linalg/norm.cuh`` rowNormCsr)."""
    rid, valid = _expanded(csr)
    if norm == "l1":
        v = jnp.abs(csr.data)
    elif norm == "l2":
        v = csr.data * csr.data
    elif norm == "linf" or norm == "max":
        v = jnp.abs(csr.data)
        seg = jax.ops.segment_max(jnp.where(valid, v, 0), rid,
                                  num_segments=csr.n_rows + 1)[: csr.n_rows]
        return seg
    else:
        raise ValueError(f"unknown norm {norm!r}")
    return jax.ops.segment_sum(jnp.where(valid, v, 0), rid,
                               num_segments=csr.n_rows + 1)[: csr.n_rows]


def _row_scale(csr: CSR, scale) -> CSR:
    rid, _ = _expanded(csr)
    rid_c = jnp.minimum(rid, csr.n_rows - 1)
    data = csr.data * jnp.take(scale, rid_c)
    return CSR(csr.indptr, csr.indices, data, csr.shape, csr.nnz)


def csr_row_normalize_l1(csr: CSR) -> CSR:
    """Rows scaled to unit L1 (``sparse/linalg/norm.cuh``
    ``csr_row_normalize_l1``); empty rows stay zero."""
    s = csr_row_norm(csr, "l1")
    return _row_scale(csr, jnp.where(s > 0, 1.0 / s, 0.0))


def csr_row_normalize_max(csr: CSR) -> CSR:
    s = csr_row_norm(csr, "max")
    return _row_scale(csr, jnp.where(s > 0, 1.0 / s, 0.0))


def csr_transpose(csr: CSR) -> CSR:
    """A^T (``sparse/linalg/transpose.cuh``, cusparse csr2csc role): swap
    coordinates and re-sort — index arithmetic only."""
    coo = csr_to_coo(csr)
    t = COO(coo.cols, jnp.where(coo.pad_mask(), coo.rows, csr.n_cols),
            coo.vals, (csr.n_cols, csr.n_rows), csr.nnz)
    # re-sort by new row (stable keeps column order within rows sorted if
    # original columns were sorted per row)
    order = jnp.argsort(jnp.where(t.pad_mask(), t.rows, csr.n_cols), stable=True)
    t = COO(t.rows[order], t.cols[order], t.vals[order], t.shape, t.nnz)
    from .convert import sorted_coo_to_csr

    return sorted_coo_to_csr(t)


def coo_symmetrize(coo: COO, reduce_op=None) -> COO:
    """Symmetrize a COO graph (``sparse/linalg/symmetrize.cuh``
    ``coo_symmetrize:29,48``): emit (i,j) and (j,i), combining duplicate
    edges with ``reduce_op`` (default: sum, the reference's behavior when
    edges exist both ways)."""
    import jax.numpy as jnp

    n = coo.nnz
    rows = jnp.concatenate([coo.rows[:n], coo.cols[:n]])
    cols = jnp.concatenate([coo.cols[:n], coo.rows[:n]])
    vals = jnp.concatenate([coo.vals[:n], coo.vals[:n]])
    sym = COO(rows, cols, vals, coo.shape, 2 * n)
    from .ops import coo_sum_duplicates

    out = coo_sum_duplicates(sym)
    if reduce_op is not None:
        return out  # custom reductions handled by caller on trimmed arrays
    return out


def compute_graph_laplacian(adj: CSR) -> CSR:
    """L = D - A (``sparse/linalg/laplacian.cuh`` ``compute_graph_laplacian:20``).

    Assumes a symmetric adjacency with no diagonal entries (the reference's
    contract).  Appends the diagonal as explicit entries.
    """
    deg = spmv(adj, jnp.ones((adj.n_cols,), adj.data.dtype))
    n = adj.nnz
    rid = adj.row_ids()
    rows = jnp.concatenate([rid[:n], jnp.arange(adj.n_rows, dtype=jnp.int32)])
    cols = jnp.concatenate([adj.indices[:n], jnp.arange(adj.n_rows, dtype=jnp.int32)])
    vals = jnp.concatenate([-adj.data[:n], deg])
    lap = COO(rows, cols, vals, adj.shape, rows.shape[0])
    return coo_to_csr(lap)
