"""Sparse containers — COO/CSR owning types.

Reference parity: ``sparse/coo.hpp`` (``COO<T>`` with RMM-backed rows/cols/vals
buffers), ``sparse/csr.hpp``, and the core owning types
(``core/sparse_types.hpp``, ``core/coo_matrix.hpp``, ``core/csr_matrix.hpp``).

TPU-native design: XLA requires static shapes, so a sparse matrix carries a
static element **capacity**; ``nnz`` is the valid prefix length (a static int
on the host path).  Padding lives at the tail: COO pad rows/cols are the
sentinel ``n_rows`` / ``n_cols`` (never a valid coordinate) with zero values,
CSR pad indices are zeros with zero data beyond ``indptr[-1]``, so segment-sum
kernels can run over full capacity without masking.  Both types are registered
pytrees — they pass through ``jit``/``shard_map`` boundaries like arrays.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..core.errors import expects

__all__ = ["COO", "CSR"]

Shape = Tuple[int, int]


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class COO:
    """Coordinate-format sparse matrix (``sparse/coo.hpp`` ``COO<T>``)."""

    rows: jax.Array  # [cap] int32
    cols: jax.Array  # [cap] int32
    vals: jax.Array  # [cap] T
    shape: Shape = dataclasses.field(metadata=dict(static=True))
    nnz: int = dataclasses.field(metadata=dict(static=True))

    def __post_init__(self):
        expects(self.rows.shape == self.cols.shape == self.vals.shape,
                "COO buffers must share shape")

    @property
    def capacity(self) -> int:
        return int(self.rows.shape[0])

    @classmethod
    def from_arrays(cls, rows, cols, vals, shape: Shape, nnz: Optional[int] = None) -> "COO":
        rows = jnp.asarray(rows, jnp.int32)
        cols = jnp.asarray(cols, jnp.int32)
        vals = jnp.asarray(vals)
        return cls(rows, cols, vals, (int(shape[0]), int(shape[1])),
                   int(nnz) if nnz is not None else int(rows.shape[0]))

    @classmethod
    def from_dense(cls, dense, *, tol: float = 0.0) -> "COO":
        """Host-eager densification inverse (``convert/coo.cuh`` role)."""
        d = np.asarray(dense)
        r, c = np.nonzero(np.abs(d) > tol)
        return cls.from_arrays(r, c, d[r, c], d.shape)

    def to_dense(self) -> jax.Array:
        """Scatter-add valid entries into a dense matrix (pads are no-ops
        because sentinel coordinates fall outside with mode='drop')."""
        out = jnp.zeros(self.shape, self.vals.dtype)
        return out.at[self.rows, self.cols].add(self.vals, mode="drop")

    def trimmed(self) -> "COO":
        """Drop padding (host-side; capacity becomes exact nnz)."""
        return COO(self.rows[: self.nnz], self.cols[: self.nnz],
                   self.vals[: self.nnz], self.shape, self.nnz)

    def pad_mask(self) -> jax.Array:
        """True for valid (non-pad) entries; usable under jit."""
        return jnp.arange(self.capacity) < self.nnz


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class CSR:
    """Compressed-sparse-row matrix (``sparse/csr.hpp``)."""

    indptr: jax.Array   # [n_rows+1] int32
    indices: jax.Array  # [cap] int32
    data: jax.Array     # [cap] T
    shape: Shape = dataclasses.field(metadata=dict(static=True))
    nnz: int = dataclasses.field(metadata=dict(static=True))

    @property
    def capacity(self) -> int:
        return int(self.indices.shape[0])

    @property
    def n_rows(self) -> int:
        return self.shape[0]

    @property
    def n_cols(self) -> int:
        return self.shape[1]

    @classmethod
    def from_arrays(cls, indptr, indices, data, shape: Shape, nnz: Optional[int] = None) -> "CSR":
        indptr = jnp.asarray(indptr, jnp.int32)
        indices = jnp.asarray(indices, jnp.int32)
        data = jnp.asarray(data)
        expects(indptr.shape[0] == shape[0] + 1, "indptr must have n_rows+1 entries")
        return cls(indptr, indices, data, (int(shape[0]), int(shape[1])),
                   int(nnz) if nnz is not None else int(indices.shape[0]))

    @classmethod
    def from_dense(cls, dense, *, tol: float = 0.0) -> "CSR":
        d = np.asarray(dense)
        r, c = np.nonzero(np.abs(d) > tol)
        indptr = np.zeros(d.shape[0] + 1, np.int32)
        np.add.at(indptr, r + 1, 1)
        indptr = np.cumsum(indptr).astype(np.int32)
        return cls.from_arrays(indptr, c, d[r, c], d.shape)

    def row_ids(self) -> jax.Array:
        """Expand indptr → per-element row id ([cap] int32); pads map to
        ``n_rows``.  The csr_to_coo expansion (``convert/coo.cuh``
        ``csr_to_coo``) as a searchsorted — one XLA op, no scatter."""
        pos = jnp.arange(self.capacity, dtype=jnp.int32)
        rid = jnp.searchsorted(self.indptr[1:], pos, side="right").astype(jnp.int32)
        return jnp.where(pos < self.nnz, rid, self.n_rows)

    def to_dense(self) -> jax.Array:
        rid = self.row_ids()
        out = jnp.zeros(self.shape, self.data.dtype)
        return out.at[rid, self.indices].add(self.data, mode="drop")

    def row_lengths(self) -> jax.Array:
        return self.indptr[1:] - self.indptr[:-1]

    def trimmed(self) -> "CSR":
        return CSR(self.indptr, self.indices[: self.nnz], self.data[: self.nnz],
                   self.shape, self.nnz)
