"""Minimum spanning tree / forest — Borůvka.

Parity with ``sparse/solver/mst.cuh:38`` ``mst()`` and the
``mst_solver.cuh`` Borůvka solver class (kernels ``detail/mst_kernels.cuh``,
``detail/mst_solver_inl.cuh``) — the basis of cuML's HDBSCAN/linkage.

TPU redesign: the reference's per-vertex kernels (min-edge-per-supervertex,
hooking, pointer-jumping) become whole-graph vectorized rounds:

* min outgoing edge per component — ``segment_min`` over edge keys,
* hooking + cycle break — pure index arithmetic,
* pointer jumping to collapse label trees — ``log n`` gather rounds.

Everything is fixed-shape; Borůvka needs at most ``ceil(log2 n)`` rounds.
Ties are broken by (weight, edge id) like the reference's
``alteration`` scheme, guaranteeing a unique MST even with equal weights.
"""

from __future__ import annotations

from typing import NamedTuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from ...core.errors import expects
from ..types import COO, CSR

__all__ = ["MstResult", "mst"]


class MstResult(NamedTuple):
    """``Graph_COO`` output parity (``mst_solver.cuh``)."""

    src: jax.Array      # [n-1] int32 (padded with -1 for forests)
    dst: jax.Array      # [n-1]
    weight: jax.Array   # [n-1]
    n_edges: int        # valid prefix length
    color: jax.Array    # [n] final component label per vertex


def _pointer_jump(parent):
    """Collapse label trees: parent = parent[parent] until fixpoint
    (``detail/mst_utils.cuh`` pointer jumping; log2(n) unrolled rounds)."""
    n = parent.shape[0]
    rounds = max(1, int(np.ceil(np.log2(max(n, 2)))))
    for _ in range(rounds):
        parent = parent[parent]
    return parent


def mst(g: Union[COO, CSR]) -> MstResult:
    """Minimum spanning forest of an undirected weighted graph.

    Input: symmetric COO/CSR (both (i,j) and (j,i) present, as the reference
    requires).  Returns up to ``n-1`` edges; for disconnected graphs the valid
    prefix covers each component's tree and ``n_edges < n-1``.
    """
    if isinstance(g, CSR):
        from ..convert import csr_to_coo

        g = csr_to_coo(g)
    n = g.shape[0]
    expects(g.shape[0] == g.shape[1], "mst: graph must be square")
    cap = g.capacity

    src = g.rows
    dst = g.cols
    w = g.vals
    valid_e = np.asarray(g.pad_mask())
    eid = jnp.arange(cap, dtype=jnp.int32)

    # order edges by (weight, id) for deterministic tie-breaks: rank array
    order = jnp.argsort(jnp.where(jnp.asarray(valid_e), w, jnp.inf), stable=True)
    rank_of = jnp.zeros((cap,), jnp.int32).at[order].set(eid)

    color = jnp.arange(n, dtype=jnp.int32)
    picked = jnp.zeros((cap,), bool)

    rounds = max(1, int(np.ceil(np.log2(max(n, 2)))))
    for _ in range(rounds):
        csrc = color[jnp.clip(src, 0, n - 1)]
        cdst = color[jnp.clip(dst, 0, n - 1)]
        cross = jnp.asarray(valid_e) & (csrc != cdst)
        # min outgoing edge per component, keyed by deterministic rank
        key = jnp.where(cross, rank_of, cap)
        best = jax.ops.segment_min(key, csrc, num_segments=n)  # [n] edge rank
        has_out = best < cap
        # translate rank back to edge id
        edge_at_rank = jnp.zeros((cap,), jnp.int32).at[rank_of].set(eid)
        best_eid = edge_at_rank[jnp.clip(best, 0, cap - 1)]
        # hooking: component c hooks onto color of the other endpoint
        to = jnp.where(
            has_out,
            jnp.where(color[jnp.clip(src[best_eid], 0, n - 1)] == jnp.arange(n),
                      color[jnp.clip(dst[best_eid], 0, n - 1)],
                      color[jnp.clip(src[best_eid], 0, n - 1)]),
            jnp.arange(n, dtype=jnp.int32),
        )
        # cycle breaking: mutual hooks a<->b keep the smaller label as root
        mutual = to[to] == jnp.arange(n)
        parent = jnp.where(mutual & (jnp.arange(n) < to), jnp.arange(n), to)
        # mark edges picked this round: one per hooking component that is not
        # the surviving root of a mutual pair (avoids double-adding a<->b)
        adds = has_out & ~(mutual & (jnp.arange(n) < to))
        # sentinel index `cap` drops non-adding components (a stale-read
        # write could otherwise clobber a concurrent True)
        picked = picked.at[jnp.where(adds, best_eid, cap)].set(True, mode="drop")
        # compose: vertices relabel through their component's new root
        color = _pointer_jump(parent)[color]

    # compact picked edges (dedup (a,b)/(b,a): keep src<dst orientation once)
    picked_np = np.asarray(picked)  # jaxlint: disable=JX01 one-time host compaction of the final forest after the device rounds (output is host-built)
    src_np, dst_np, w_np = np.asarray(src), np.asarray(dst), np.asarray(w)
    lo = np.minimum(src_np, dst_np)
    hi = np.maximum(src_np, dst_np)
    seen = {}
    out = []
    for e in np.nonzero(picked_np)[0]:
        kkey = (int(lo[e]), int(hi[e]))
        if kkey not in seen:
            seen[kkey] = True
            out.append(e)
    out_src = np.full((max(n - 1, 1),), -1, np.int32)
    out_dst = np.full((max(n - 1, 1),), -1, np.int32)
    out_w = np.zeros((max(n - 1, 1),), np.asarray(w).dtype)
    for i, e in enumerate(out[: n - 1]):
        out_src[i] = src_np[e]
        out_dst[i] = dst_np[e]
        out_w[i] = w_np[e]
    return MstResult(
        jnp.asarray(out_src), jnp.asarray(out_dst), jnp.asarray(out_w),
        len(out[: max(n - 1, 0)]), color,
    )
