"""Thick-restart Lanczos eigensolver.

Math parity with ``sparse/solver/detail/lanczos.cuh`` (``lanczos_smallest:402``
outer thick-restart loop, ``lanczos_aux:248`` tridiagonalization inner loop,
``lanczos_solve_ritz:129`` small dense eig) and the public API
``sparse/solver/lanczos.cuh:87`` ``lanczos_compute_eigenpairs`` +
``lanczos_types.hpp`` config.  Python driver parity:
``pylibraft/sparse/linalg/lanczos.pyx:100`` ``eigsh``.

TPU redesign notes:
* The inner loop's SpMV + dot + axpy + re-orth gemv sequence maps to our
  segment-sum :func:`~raft_tpu.sparse.linalg.spmv` plus MXU matmuls; full
  re-orthogonalization (``V[:i] @ u`` then subtract) is two skinny matmuls —
  exactly what the MXU wants — instead of the reference's per-vector gemv.
* The ncv×ncv Ritz problem uses ``jnp.linalg.eigh`` (cuSOLVER syevd role).
* One restart cycle is a single jitted function (static ncv unrolls the short
  inner loop); the outer while runs on the host like the reference's.
* f32 accumulation: the reference assumes f64 cuSOLVER; full re-orth each
  step keeps f32 stable (clamp guards mirror ``kernel_clamp_down:116``).
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Optional, Tuple, Union

import jax
import jax.numpy as jnp

from ...core.errors import expects
from ..linalg import spmv
from ..types import COO, CSR

__all__ = ["LanczosConfig", "lanczos_compute_eigenpairs", "eigsh"]


@dataclasses.dataclass
class LanczosConfig:
    """``lanczos_solver_config`` parity (``sparse/solver/lanczos_types.hpp``)."""

    n_components: int = 6
    max_iterations: int = 1000
    ncv: Optional[int] = None  # restartIter
    tolerance: float = 1e-9
    which: str = "SA"  # SA | LA | SM | LM
    seed: int = 42


def _matvec_of(a: Union[CSR, COO, Callable]) -> Tuple[Callable, int]:
    if callable(a):
        raise TypeError("pass a CSR/COO; for custom operators use eigsh(op, n=...)")
    if isinstance(a, COO):
        from ..convert import coo_to_csr

        a = coo_to_csr(a)
    expects(a.shape[0] == a.shape[1], "lanczos: matrix must be square")
    return (lambda x: spmv(a, x)), a.shape[0]


def _select_ritz(evals, which: str, k: int):
    """Pick k wanted Ritz pairs (order of ``lanczos_solve_ritz:129``)."""
    if which == "SA":
        idx = jnp.argsort(evals)[:k]
    elif which == "LA":
        idx = jnp.argsort(-evals)[:k]
    elif which == "SM":
        idx = jnp.argsort(jnp.abs(evals))[:k]
    elif which == "LM":
        idx = jnp.argsort(-jnp.abs(evals))[:k]
    else:
        raise ValueError(f"which must be SA/LA/SM/LM, got {which!r}")
    return jnp.sort(idx)  # keep ascending position order like the reference


def _lanczos_extend(matvec, V, alpha, beta, u, start: int, ncv: int, key=None):
    """Tridiagonalize from index ``start`` to ``ncv`` (``lanczos_aux:248``).

    V: [ncv, n] basis (rows < start valid); u: current residual vector.
    Full re-orthogonalization per step: two skinny MXU matmuls.

    Breakdown handling (beta → 0: Krylov space exhausted, common for graph
    Laplacians with few distinct eigenvalues): the residual is replaced by a
    fresh random vector orthogonalized against the basis — the standard
    deflation-restart, and the clamp guards of ``lanczos.cuh:386-390`` are
    its f32 analog.
    """
    n = V.shape[1]
    if key is None:
        key = jax.random.PRNGKey(0)
    for i in range(start, ncv):
        unrm = jnp.linalg.norm(u)
        breakdown = unrm < 1e-5
        repl = jax.random.normal(jax.random.fold_in(key, i), (n,), V.dtype)
        repl = repl - V.T @ (V @ repl)
        repl = repl - V.T @ (V @ repl)
        repl = repl / jnp.maximum(jnp.linalg.norm(repl), 1e-12)
        vi = jnp.where(breakdown, repl, u / jnp.maximum(unrm, 1e-12))
        V = V.at[i].set(vi)
        w = matvec(vi)
        a_i = jnp.dot(vi, w)
        alpha = alpha.at[i].set(a_i)
        # full re-orth against all basis rows (rows >= i+1 are zero)
        coeff = V @ w  # [ncv]
        w = w - V.T @ coeff
        # second pass for f32 robustness (CholeskyQR2-style twice-is-enough)
        coeff2 = V @ w
        w = w - V.T @ coeff2
        b_i = jnp.linalg.norm(w)
        beta = beta.at[i].set(b_i)
        u = w
    return V, alpha, beta, u


def _build_t(alpha, beta, beta_k, k: int, ncv: int):
    """Restart-form projected matrix: leading k×k diag of Ritz values with
    beta_k coupling to row/col k, tridiagonal beyond (thick-restart T)."""
    t = jnp.diag(alpha)
    off = jnp.zeros((ncv, ncv), alpha.dtype)
    i = jnp.arange(ncv - 1)
    off = off.at[i, i + 1].set(beta[:-1])
    t = t + off + off.T
    if beta_k is not None:
        t = t.at[k, :k].set(beta_k)
        t = t.at[:k, k].set(beta_k)
        # remove the spurious tridiagonal couplings inside the locked block
        blk = jnp.arange(k - 1) if k > 1 else jnp.arange(0)
        t = t.at[blk, blk + 1].set(0.0)
        t = t.at[blk + 1, blk].set(0.0)
    return t


def lanczos_compute_eigenpairs(
    a: Union[CSR, COO],
    config: LanczosConfig,
    v0=None,
) -> Tuple[jax.Array, jax.Array]:
    """Compute eigenpairs of a sparse symmetric matrix
    (``lanczos.cuh:87`` → ``detail::lanczos_compute_eigenpairs:757`` →
    ``lanczos_smallest:402``).

    Returns ``(eigenvalues[k], eigenvectors[n, k])``.
    """
    matvec, n = _matvec_of(a)
    k = config.n_components
    ncv = config.ncv or min(max(2 * k + 1, 20), n)
    ncv = min(ncv, n)
    expects(0 < k < ncv <= n, "need n_components < ncv <= n")
    dtype = a.data.dtype if isinstance(a, CSR) else a.vals.dtype

    if v0 is None:
        v0 = jax.random.normal(jax.random.PRNGKey(config.seed), (n,), dtype)
    v0 = jnp.asarray(v0, dtype)

    @jax.jit
    def first_cycle(u0, key):
        V = jnp.zeros((ncv, n), dtype)
        alpha = jnp.zeros((ncv,), dtype)
        beta = jnp.zeros((ncv,), dtype)
        V, alpha, beta, u = _lanczos_extend(matvec, V, alpha, beta, u0, 0, ncv, key)
        t = _build_t(alpha, beta, None, 0, ncv)
        evals, evecs = jnp.linalg.eigh(t)
        return V, alpha, beta, u, evals, evecs

    @jax.jit
    def restart_cycle(V, ritz_vals, ritz_vecs_small, beta_last, u, key):
        # Lock k Ritz vectors: V[:k] = (V^T @ s)^T  (gemm at lanczos.cuh:505)
        locked = (V.T @ ritz_vecs_small).T  # [k, n]
        Vn = jnp.zeros((ncv, n), dtype).at[:k].set(locked)
        alpha = jnp.zeros((ncv,), dtype).at[:k].set(ritz_vals)
        beta_k = beta_last * ritz_vecs_small[-1, :]  # [k] coupling
        # orthogonalize u against locked block (gemv pair, lanczos.cuh:556-580)
        uu = Vn[:k] @ u
        u = u - Vn[:k].T @ uu
        beta = jnp.zeros((ncv,), dtype)
        Vn, alpha, beta, u = _lanczos_extend(matvec, Vn, alpha, beta, u, k, ncv, key)
        t = _build_t(alpha, beta, beta_k, k, ncv)
        evals, evecs = jnp.linalg.eigh(t)
        return Vn, alpha, beta, u, evals, evecs

    @jax.jit
    def select_cycle(evals, evecs, beta_last):
        """Ritz selection + restart residual fused on-device: one
        executable instead of an eager argsort/gather/norm chain, and the
        residual stays on-device until the convergence check's single
        scalar sync (the former per-restart ``float(norm(...))`` forced a
        full dispatch+sync every iteration)."""
        sel = _select_ritz(evals, config.which, k)
        ritz_vals = evals[sel]
        s = evecs[:, sel]  # [ncv, k]
        res = jnp.linalg.norm(beta_last * s[-1, :])
        return ritz_vals, s, res

    key = jax.random.PRNGKey(config.seed + 1)
    V, alpha, beta, u, evals, evecs = first_cycle(v0, key)
    iters = ncv
    cycle = 0
    while True:
        ritz_vals, s, res = select_cycle(evals, evecs, beta[ncv - 1])
        # outer thick-restart loop runs on the host like the reference
        # (lanczos_smallest:402): exactly one scalar sync per check
        if float(res) <= config.tolerance or iters >= config.max_iterations:  # jaxlint: disable=JX01 host convergence check: one scalar sync per restart, the loop bound itself is host state
            break
        cycle += 1
        V, alpha, beta, u, evals, evecs = restart_cycle(
            V, ritz_vals, s, beta[ncv - 1], u, jax.random.fold_in(key, cycle)
        )
        iters += ncv - k

    vecs = V.T @ s  # [n, k] Ritz vectors
    # normalize (locked rows already unit, but restart products drift in f32)
    vecs = vecs / jnp.maximum(jnp.linalg.norm(vecs, axis=0, keepdims=True), 1e-12)
    return ritz_vals, vecs


def eigsh(
    a: Union[CSR, COO],
    k: int = 6,
    *,
    which: str = "SA",
    ncv: Optional[int] = None,
    maxiter: int = 1000,
    tol: float = 0.0,
    v0=None,
    seed: int = 42,
):
    """scipy-like driver (``pylibraft.sparse.linalg.eigsh``,
    ``sparse/linalg/lanczos.pyx:100``): returns ``(eigenvalues, eigenvectors)``.
    """
    cfg = LanczosConfig(
        n_components=k,
        max_iterations=maxiter,
        ncv=ncv,
        tolerance=tol if tol > 0 else 1e-9,
        which=which,
        seed=seed,
    )
    return lanczos_compute_eigenpairs(a, cfg, v0=v0)
