"""Randomized sparse SVD.

Parity with ``sparse/solver/randomized_svds.cuh`` + ``svds_config.hpp``
(impl ``detail/randomized_svds.cuh``; CholeskyQR2 orthonormalization
``detail/cholesky_qr.cuh``; deterministic sign fix
``detail/svds_sign_correction.cuh``) and the Python driver
``pylibraft/sparse/linalg/svds.pyx:73``.

TPU redesign: the sketch ``A @ Omega`` and the power iterations are
:func:`~raft_tpu.sparse.linalg.spmm` calls (segment-sum SpMM); the
orthonormalizations are CholeskyQR2 — two Cholesky solves of a k×k Gram
matrix, which beats Householder QR on the MXU for skinny panels and is the
same scheme the reference chose for the same reason (batched-friendly,
gemm-dominated).
"""

from __future__ import annotations

import dataclasses
from typing import Tuple, Union

import jax
import jax.numpy as jnp

from ...core.errors import expects
from ..linalg import spmm
from ..types import COO, CSR

__all__ = ["SvdsConfig", "randomized_svds", "svds"]


@dataclasses.dataclass
class SvdsConfig:
    """``svds_config.hpp`` parity."""

    k: int = 6
    p: int = 10  # oversampling
    n_iters: int = 4  # power iterations
    seed: int = 42
    sign_correction: bool = True


def _cholesky_qr2(y: jax.Array) -> jax.Array:
    """CholeskyQR2 (``detail/cholesky_qr.cuh``): Q = Y R^{-1}, run twice.

    One pass loses ~half the digits in f32; the second restores
    orthogonality (the 'twice is enough' result the reference relies on).
    """
    def one(y):
        g = y.T @ y
        # jitter for rank-deficient sketches
        g = g + 1e-7 * jnp.trace(g) / g.shape[0] * jnp.eye(g.shape[0], dtype=y.dtype)
        r = jnp.linalg.cholesky(g, upper=True)
        return jax.scipy.linalg.solve_triangular(r.T, y.T, lower=True).T

    return one(one(y))


def _sign_correct(u, v):
    """Deterministic signs (``detail/svds_sign_correction.cuh``): flip each
    component so the largest-magnitude entry of U's column is positive."""
    idx = jnp.argmax(jnp.abs(u), axis=0)
    signs = jnp.sign(u[idx, jnp.arange(u.shape[1])])
    signs = jnp.where(signs == 0, 1.0, signs)
    return u * signs[None, :], v * signs[None, :]


def randomized_svds(
    a: Union[CSR, COO], config: SvdsConfig
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Top-k SVD of a sparse matrix → ``(U[m,k], S[k], V[n,k])``
    (``randomized_svds.cuh`` driver shape)."""
    if isinstance(a, COO):
        from ..convert import coo_to_csr

        a = coo_to_csr(a)
    m, n = a.shape
    k = config.k
    sk = min(k + config.p, min(m, n))
    expects(k <= sk, "k + oversampling must fit the matrix")
    dtype = a.data.dtype

    from ..linalg import csr_transpose

    at = csr_transpose(a)

    key = jax.random.PRNGKey(config.seed)
    omega = jax.random.normal(key, (n, sk), dtype)

    y = spmm(a, omega)  # [m, sk] sketch
    q = _cholesky_qr2(y)
    for _ in range(config.n_iters):
        z = spmm(at, q)  # [n, sk]
        z = _cholesky_qr2(z)
        y = spmm(a, z)
        q = _cholesky_qr2(y)

    b = spmm(at, q).T  # [sk, n] projected matrix B = Q^T A
    ub, s, vt = jnp.linalg.svd(b, full_matrices=False)
    u = q @ ub  # [m, sk]
    u, s, v = u[:, :k], s[:k], vt[:k].T
    if config.sign_correction:
        u, v = _sign_correct(u, v)
    return u, s, v


def svds(a: Union[CSR, COO], k: int = 6, *, p: int = 10, n_iters: int = 4,
         seed: int = 42):
    """scipy-like driver (``pylibraft.sparse.linalg.svds``,
    ``sparse/linalg/svds.pyx:73``)."""
    return randomized_svds(a, SvdsConfig(k=k, p=p, n_iters=n_iters, seed=seed))
