"""Sparse solvers — ``raft/sparse/solver`` parity (SURVEY.md §2.5):
thick-restart Lanczos, randomized sparse SVD, Borůvka MST."""

from .lanczos import LanczosConfig, eigsh, lanczos_compute_eigenpairs
from .mst import MstResult, mst
from .randomized_svd import SvdsConfig, randomized_svds, svds

__all__ = [
    "LanczosConfig",
    "eigsh",
    "lanczos_compute_eigenpairs",
    "MstResult",
    "mst",
    "SvdsConfig",
    "randomized_svds",
    "svds",
]
