"""Format conversions — ``sparse/convert/{coo,csr,dense}.cuh`` parity.

All conversions are jit-compatible on fixed capacities; row-id expansion uses
``searchsorted`` over ``indptr`` and histogramming uses ``segment_sum`` — the
XLA-native replacements for the reference's scan/binary-search kernels.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.bitset import Bitset, Bitmap
from .types import COO, CSR

__all__ = [
    "coo_to_csr",
    "csr_to_coo",
    "dense_to_csr",
    "dense_to_coo",
    "csr_to_dense",
    "coo_to_dense",
    "adj_to_csr",
    "bitmap_to_csr",
    "bitset_to_csr",
    "sorted_coo_to_csr",
]


def sorted_coo_to_csr(coo: COO) -> CSR:
    """Row-sorted COO → CSR (``convert/csr.cuh`` ``sorted_coo_to_csr``).

    Builds indptr by counting rows with a one-hot segment sum; pad entries
    carry the sentinel row ``n_rows`` and fall off the histogram.
    """
    n_rows = coo.shape[0]
    counts = jax.ops.segment_sum(
        jnp.ones_like(coo.rows), coo.rows, num_segments=n_rows + 1
    )[:n_rows]
    indptr = jnp.concatenate([jnp.zeros((1,), jnp.int32),
                              jnp.cumsum(counts).astype(jnp.int32)])
    return CSR(indptr, coo.cols, coo.vals, coo.shape, coo.nnz)


def coo_to_csr(coo: COO) -> CSR:
    """General COO → CSR: stable row sort then count (``convert/csr.cuh``)."""
    order = jnp.argsort(coo.rows, stable=True)
    sorted_coo = COO(coo.rows[order], coo.cols[order], coo.vals[order],
                     coo.shape, coo.nnz)
    return sorted_coo_to_csr(sorted_coo)


def csr_to_coo(csr: CSR) -> COO:
    """CSR → COO (``convert/coo.cuh`` ``csr_to_coo``): indptr expansion via
    searchsorted, no kernel launch per row."""
    return COO(csr.row_ids(), csr.indices, csr.data, csr.shape, csr.nnz)


def dense_to_csr(dense, *, tol: float = 0.0) -> CSR:
    return CSR.from_dense(dense, tol=tol)


def dense_to_coo(dense, *, tol: float = 0.0) -> COO:
    return COO.from_dense(dense, tol=tol)


def csr_to_dense(csr: CSR) -> jax.Array:
    return csr.to_dense()


def coo_to_dense(coo: COO) -> jax.Array:
    return coo.to_dense()


def adj_to_csr(adj) -> CSR:
    """Boolean adjacency matrix → CSR with unit values
    (``convert/csr.cuh`` ``adj_to_csr``)."""
    a = np.asarray(adj).astype(bool)
    return CSR.from_dense(a.astype(np.float32))


def bitmap_to_csr(bitmap: Bitmap) -> CSR:
    """2-D bitmap view → CSR (``convert/csr.cuh`` ``bitmap_to_csr``)."""
    dense = np.asarray(bitmap.to_bool_array()).reshape(bitmap.rows, bitmap.cols)
    return CSR.from_dense(dense.astype(np.float32))


def bitset_to_csr(bitset: Bitset, n_rows: int) -> CSR:
    """Bitset repeated over rows → CSR (``convert/csr.cuh``
    ``bitset_to_csr``: every row shares the bitset's set columns)."""
    row = np.asarray(bitset.to_bool_array()).astype(np.float32)[None, :]
    dense = np.repeat(row, n_rows, axis=0)
    return CSR.from_dense(dense)
