"""Spectral graph analysis — ``raft/spectral`` parity (SURVEY.md §2.8)."""

from .analysis import analyze_modularity, analyze_partition, spectral_partition

__all__ = ["analyze_partition", "analyze_modularity", "spectral_partition"]
