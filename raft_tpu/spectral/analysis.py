"""Spectral partition/modularity analysis.

Parity: ``spectral/partition.cuh:38`` ``analyzePartition``,
``spectral/modularity_maximization.cuh:35`` ``analyzeModularity``
(impl ``spectral/detail/partition.hpp:52``,
``detail/modularity_maximization.hpp:48``; indicator construction
``detail/spectral_util.cuh:127``).

The reference loops clusters, building one indicator vector at a time and
hitting cuSPARSE SpMV per cluster.  The TPU formulation batches all clusters
at once: the one-hot membership matrix ``X [n, k]`` turns the per-cluster
quadratic forms into two SpMM + reductions on the MXU.

The full spectral *clustering* driver was removed from the reference with the
cuVS migration (SURVEY.md §2.8 note); :func:`spectral_partition` restores the
pre-migration capability (Laplacian eigenvectors → kmeans) from our own
Lanczos + kmeans pieces.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from ..sparse.linalg import spmm, spmv
from ..sparse.types import CSR

__all__ = ["analyze_partition", "analyze_modularity", "spectral_partition"]


def _one_hot(labels, k: int, dtype):
    return (labels[:, None] == jnp.arange(k)[None, :]).astype(dtype)


def analyze_partition(csr: CSR, n_clusters: int, labels) -> Tuple[jax.Array, jax.Array]:
    """Edge cut and balanced-cut cost of a partition
    (``detail/partition.hpp:78-91``: cost += xᵀLx/|c|, edgeCut += xᵀLx/2).
    """
    labels = jnp.asarray(labels)
    x = _one_hot(labels, n_clusters, csr.data.dtype)  # [n, k]
    deg = spmv(csr, jnp.ones((csr.n_cols,), csr.data.dtype))
    ax = spmm(csr, x)  # A X
    # xᶜᵀ L xᶜ = Σ_i∈c deg_i − xᶜᵀ A xᶜ
    quad = jnp.sum(x * (deg[:, None] - ax), axis=0)  # [k]
    sizes = jnp.sum(x, axis=0)
    safe = jnp.maximum(sizes, 1.0)
    nonempty = sizes > 0
    cost = jnp.sum(jnp.where(nonempty, quad / safe, 0.0))
    edge_cut = jnp.sum(jnp.where(nonempty, quad, 0.0)) / 2.0
    return edge_cut, cost


def analyze_modularity(csr: CSR, n_clusters: int, labels) -> jax.Array:
    """Newman modularity of a clustering
    (``detail/modularity_maximization.hpp:70-83``:
    Q = Σ_c xᶜᵀBxᶜ / ‖d‖₁ with B = A − d dᵀ/‖d‖₁)."""
    labels = jnp.asarray(labels)
    x = _one_hot(labels, n_clusters, csr.data.dtype)
    deg = spmv(csr, jnp.ones((csr.n_cols,), csr.data.dtype))
    two_m = jnp.sum(deg)  # ‖d‖₁ (2m for unweighted graphs)
    ax = spmm(csr, x)
    quad_a = jnp.sum(x * ax, axis=0)              # xᶜᵀ A xᶜ
    dx = x.T @ deg                                # [k] Σ_i∈c d_i
    quad_b = quad_a - dx * dx / jnp.maximum(two_m, 1e-12)
    return jnp.sum(quad_b) / jnp.maximum(two_m, 1e-12)


def spectral_partition(
    csr: CSR,
    n_clusters: int,
    *,
    n_eig: Optional[int] = None,
    seed: int = 42,
    kmeans_max_iter: int = 100,
):
    """Laplacian spectral clustering: smallest-eigenvector embedding + kmeans.

    Restores the pre-cuVS-migration driver (partition.cuh's removed half)
    from in-tree pieces: :func:`~raft_tpu.sparse.solver.eigsh` on L and
    :func:`~raft_tpu.cluster.kmeans_fit_predict`.
    Returns ``(labels, eigenvalues, embedding)``.
    """
    from ..cluster.kmeans import KMeansParams, kmeans_fit_predict
    from ..sparse.linalg import compute_graph_laplacian
    from ..sparse.solver import eigsh

    k = n_eig or n_clusters
    lap = compute_graph_laplacian(csr)
    vals, vecs = eigsh(lap, k=k, which="SA", tol=1e-6, seed=seed)
    params = KMeansParams(n_clusters=n_clusters, max_iter=kmeans_max_iter,
                          seed=seed)
    _, labels, _, _ = kmeans_fit_predict(vecs, params)
    return labels, vals, vecs
