"""Device-side segment utilities shared across subsystems.

The reference expresses these with CUB segmented primitives / atomics
(e.g. ``cpp/include/raft/util/reduction.cuh``); on TPU they are sort +
``segment_sum`` formulations usable inside ``jit``.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["within_group_rank"]


def within_group_rank(groups, scores, k: int):
    """Rank of each element among its group, ordered by ascending score.

    ``groups``: (n,) int32 in [0, k); ``scores``: (n,) sort key within the
    group (ties broken by position via the stable lexsort).  Returns (n,)
    int32 ranks.  Used by capacity-capped assignment
    (:func:`raft_tpu.cluster.kmeans.capped_assign`) and the CAGRA reverse-
    edge builder (:mod:`raft_tpu.neighbors.cagra`).
    """
    n = groups.shape[0]
    perm = jnp.lexsort((scores, groups))
    counts = jax.ops.segment_sum(jnp.ones((n,), jnp.int32), groups,
                                 num_segments=k)
    starts = jnp.cumsum(counts) - counts
    rank_sorted = jnp.arange(n, dtype=jnp.int32) - starts[groups[perm]]
    return jnp.zeros((n,), jnp.int32).at[perm].set(rank_sorted)
