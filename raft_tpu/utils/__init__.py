"""Host/device utility layer — the portable subset of
``cpp/include/raft/util`` (SURVEY.md §2.2).

Most of the reference's util layer is CUDA mechanics (warp shuffles,
cache-hinted loads, SM-arch dispatch) that has no TPU counterpart — XLA
and Mosaic own those decisions.  What transplants is the *host-side*
toolbox: power-of-two arithmetic (``util/pow2_utils.cuh``,
``util/integer_utils.hpp``), the prime seive (``util/seive.hpp``),
itertools helpers (``util/itertools.hpp``), dtype mapping
(``util/cuda_data_type.hpp`` → canonical JAX dtypes), and input
validation (``util/input_validation.hpp``).
"""

from .math import (
    bounded,
    ceildiv,
    is_pow2,
    next_pow2,
    prev_pow2,
    round_down_safe,
    round_up_safe,
)
from .seive import Seive, primes_up_to
from .itertools import product_of
from .dtype import canonical_dtype, dtype_code
from .validation import check_contiguous, check_finite

__all__ = [
    "ceildiv", "is_pow2", "next_pow2", "prev_pow2", "round_up_safe",
    "round_down_safe", "bounded",
    "Seive", "primes_up_to", "product_of",
    "canonical_dtype", "dtype_code",
    "check_contiguous", "check_finite",
]
