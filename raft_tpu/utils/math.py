"""Integer / power-of-two arithmetic — ``util/pow2_utils.cuh``,
``util/integer_utils.hpp`` parity (host-side: on device XLA constant-folds
these when shapes are static)."""

from __future__ import annotations

__all__ = ["ceildiv", "is_pow2", "next_pow2", "prev_pow2",
           "round_up_safe", "round_down_safe", "bounded"]


def ceildiv(a: int, b: int) -> int:
    """⌈a/b⌉ for non-negative ints (``raft::ceildiv``)."""
    return -(-a // b)


def is_pow2(x: int) -> bool:
    return x > 0 and (x & (x - 1)) == 0


def next_pow2(x: int) -> int:
    """Smallest power of two ≥ x (x ≥ 1)."""
    return 1 if x <= 1 else 1 << (x - 1).bit_length()


def prev_pow2(x: int) -> int:
    """Largest power of two ≤ x (x ≥ 1)."""
    return 1 << (x.bit_length() - 1)


def round_up_safe(x: int, multiple: int) -> int:
    """x rounded up to a multiple (``raft::round_up_safe``)."""
    return ceildiv(x, multiple) * multiple


def round_down_safe(x: int, multiple: int) -> int:
    return (x // multiple) * multiple


def bounded(x, lo, hi):
    """Clamp (``raft::bounded``-style helper)."""
    return max(lo, min(hi, x))
