"""Input validation — ``util/input_validation.hpp`` parity (the reference
checks mdspan layout/exhaustiveness; here: contiguity and finiteness of
host inputs before they enter jitted programs)."""

from __future__ import annotations

from typing import Any

import numpy as np

from ..core.errors import expects

__all__ = ["check_contiguous", "check_finite"]


def check_contiguous(x: Any, name: str = "array") -> None:
    """Reject non-contiguous host arrays (``is_row_major`` analog — device
    transfer of strided views silently copies; make the caller opt in)."""
    if isinstance(x, np.ndarray):
        expects(x.flags["C_CONTIGUOUS"] or x.flags["F_CONTIGUOUS"],
                f"{name} must be contiguous (got strides {x.strides})")


def check_finite(x: Any, name: str = "array") -> None:
    """Reject NaN/Inf in host inputs (cheap guard for build-time paths that
    would otherwise poison kmeans/top-k silently)."""
    arr = np.asarray(x)
    if arr.dtype.kind == "f":
        expects(bool(np.isfinite(arr).all()), f"{name} contains NaN/Inf")
