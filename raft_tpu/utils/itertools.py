"""Test/bench parameter-grid helpers — ``util/itertools.hpp`` parity
(the reference uses it to enumerate test-case structs from value lists)."""

from __future__ import annotations

import itertools
from typing import Any, Dict, Iterable, List

__all__ = ["product_of"]


def product_of(**axes: Iterable[Any]) -> List[Dict[str, Any]]:
    """Cartesian product of named value lists as a list of dicts.

    >>> cases = product_of(rows=[1, 2], k=[10])
    >>> cases == [{"rows": 1, "k": 10}, {"rows": 2, "k": 10}]
    True
    """
    names = list(axes)
    return [dict(zip(names, combo))
            for combo in itertools.product(*(list(axes[n]) for n in names))]
