"""Dtype mapping — ``util/cuda_data_type.hpp`` parity: the reference maps
C++ types ↔ ``cudaDataType_t`` for vendor-library calls; here the mapping
is arbitrary array-likes ↔ canonical JAX dtypes (+ short wire codes used
by the IO layer)."""

from __future__ import annotations

from typing import Any

import jax
import numpy as np

__all__ = ["canonical_dtype", "dtype_code"]

_CODES = {
    "float32": "f4", "float64": "f8", "float16": "f2", "bfloat16": "bf16",
    "int8": "i1", "int16": "i2", "int32": "i4", "int64": "i8",
    "uint8": "u1", "uint16": "u2", "uint32": "u4", "uint64": "u8",
    "bool": "b1",
}


def canonical_dtype(x: Any) -> np.dtype:
    """The JAX-canonical dtype for a value, dtype, or dtype name (respects
    x64 being disabled: float64 → float32, like the device-side promotion)."""
    if hasattr(x, "dtype"):
        x = x.dtype
    return np.dtype(jax.dtypes.canonicalize_dtype(np.dtype(x)))


def dtype_code(x: Any) -> str:
    """Short wire code for a dtype (``cudaDataType_t`` analog)."""
    if not isinstance(x, type) and hasattr(x, "dtype"):  # array-like instance
        dt = np.dtype(x.dtype)
    else:  # dtype object, scalar type, or name
        dt = np.dtype(x)
    try:
        return _CODES[dt.name]
    except KeyError:
        raise ValueError(f"no wire code for dtype {dt.name!r}") from None
