"""Prime seive — ``util/seive.hpp`` parity (Eratosthenes; the reference
uses it for hashing-related sizing downstream)."""

from __future__ import annotations

import numpy as np

__all__ = ["Seive", "primes_up_to"]


def primes_up_to(n: int) -> np.ndarray:
    """All primes ≤ n, vectorized Eratosthenes."""
    if n < 2:
        return np.empty(0, np.int64)
    mask = np.ones(n + 1, bool)
    mask[:2] = False
    for p in range(2, int(n ** 0.5) + 1):
        if mask[p]:
            mask[p * p:: p] = False
    return np.flatnonzero(mask).astype(np.int64)


class Seive:
    """Query object over a precomputed seive (``raft::common::Seive``)."""

    def __init__(self, n: int):
        self._n = n
        self._mask = np.zeros(n + 1, bool)
        self._mask[primes_up_to(n)] = True

    def is_prime(self, x: int) -> bool:
        if not 0 <= x <= self._n:
            raise ValueError(f"{x} outside seive range [0, {self._n}]")
        return bool(self._mask[x])
