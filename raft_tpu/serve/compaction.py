"""Background compaction scheduler (ROADMAP item 5, owned by ISSUE 7).

Watches the serving generation's registry stats and reclaims storage
when either trigger fires:

* **dead fraction** — tombstoned ids / stored rows ≥
  ``CompactionPolicy.dead_fraction`` (filtered search still pays for the
  dead rows' distances; compaction drops them);
* **overfull lists** — the fullest IVF list's occupancy ≥
  ``CompactionPolicy.overfull_fraction`` of ``list_cap`` (the next
  insert burst would hit the slab-growth slow path; compaction re-caps
  with ``headroom`` ×).

The actual work routes through ``SearchServer.swap_index(build=...)`` —
the PR-6 handoff primitive: the compacted generation builds off-thread
under the existing transient-fault :class:`~.admission.RetryPolicy`,
gets validated + pre-warmed while the old generation keeps serving, and
swaps in atomically (zero dropped requests).  With a
``neighbors.wal.DurableStore`` attached, the build is the store's
*durable* ``compact()`` — logged before it applies — so a crash
mid-compaction recovers to the old generation (record lost) or the new
one (record replayed), never a hybrid.

Compacted indexes are re-wrapped in a fresh all-live tombstone mask of
the SAME bit width by default: the searcher's keep-mask operand keeps
one shape across compactions (no recompile) and later deletes have
their headroom back.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Optional

from ..core.errors import expects

__all__ = ["CompactionPolicy", "CompactionScheduler"]


@dataclasses.dataclass(frozen=True)
class CompactionPolicy:
    """Trigger thresholds + pacing for :class:`CompactionScheduler`."""

    dead_fraction: float = 0.3
    overfull_fraction: float = 0.9
    headroom: float = 2.0
    min_interval_s: float = 0.0
    poll_interval_s: float = 0.05
    rewrap: bool = True

    def __post_init__(self):
        expects(0.0 < self.dead_fraction <= 1.0,
                "dead_fraction must lie in (0, 1]")
        expects(0.0 < self.overfull_fraction <= 1.0,
                "overfull_fraction must lie in (0, 1]")
        expects(self.headroom >= 1.0, "headroom must be >= 1.0")
        expects(self.min_interval_s >= 0, "min_interval_s must be >= 0")
        expects(self.poll_interval_s > 0, "poll_interval_s must be > 0")


class CompactionScheduler:
    """Polls one server's serving generation and compacts when due.

    Deterministic-test surface: ``stats()`` / ``due()`` / ``run_once()``
    need no thread (drive them inline with a fake clock);
    ``start()``/``stop()`` run the same loop on a daemon thread for real
    deployments.  ``store`` (optional ``neighbors.wal.DurableStore``)
    makes compactions durable — the WAL checkpoint is what turns a crash
    mid-compaction into a clean old-or-new recovery."""

    def __init__(self, server, policy: Optional[CompactionPolicy] = None, *,
                 store=None, clock=time.monotonic, sleep=time.sleep) -> None:
        self.server = server
        self.policy = policy or CompactionPolicy()
        self.store = store
        self.clock = clock
        self._sleep = sleep
        self._last_run = float("-inf")
        self.last_error: Optional[BaseException] = None
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    # -- triggers -----------------------------------------------------

    def stats(self) -> dict:
        """Registry-sampled trigger inputs for the CURRENT generation:
        ``rows``, ``dead`` (tombstoned ids), ``dead_fraction``, and
        ``occupancy`` (fullest IVF list / cap; 0 for list-less
        families).  Shares :func:`raft_tpu.neighbors.health.index_health`
        with the per-generation health gauges — a handful of explicit
        host scalars per poll, never on the dispatch path."""
        from ..neighbors.health import index_health

        h = index_health(self.server.index)
        return {"rows": h["rows"], "dead": int(h["dead"]),
                "dead_fraction": h["dead_fraction"],
                "occupancy": h.get("occupancy_max", 0.0)}

    def due(self, now: Optional[float] = None) -> Optional[str]:
        """The trigger that fires now ("dead_fraction" / "overfull"), or
        None — also None inside the ``min_interval_s`` cooldown."""
        now = self.clock() if now is None else now
        if now - self._last_run < self.policy.min_interval_s:
            return None
        s = self.stats()
        if s["dead_fraction"] >= self.policy.dead_fraction:
            return "dead_fraction"
        if s["occupancy"] >= self.policy.overfull_fraction:
            return "overfull"
        return None

    # -- the work -----------------------------------------------------

    def _build(self):
        """The compacted next generation (the ``swap_index(build=)``
        thunk — retried there under the server's RetryPolicy)."""
        from ..core.bitset import Bitset
        from ..neighbors import mutation

        p = self.policy
        if self.store is not None:
            return self.store.compact(headroom=p.headroom, rewrap=p.rewrap)
        index = self.server.index
        out = mutation.compact(index, headroom=p.headroom)
        if p.rewrap and isinstance(index, mutation.Tombstoned):
            out = mutation.Tombstoned(
                out, Bitset.create(index.keep.n_bits, True))
        return out

    def run_once(self, now: Optional[float] = None) -> Optional[str]:
        """Check triggers and, when due, compact + swap.  Returns the
        trigger that ran, or None.  Failures count
        ``compactions_failed``, park in ``last_error``, and start the
        cooldown (a failing compaction must not hot-loop) — the old
        generation keeps serving either way."""
        reason = self.due(now)
        if reason is None:
            return None
        metrics = self.server.metrics
        metrics.count("compactions_scheduled")
        self._last_run = self.clock() if now is None else now
        with self.server.recorder.span("serve.compaction",
                                       trigger=reason) as sp:
            try:
                self.server.swap_index(build=self._build)
            except Exception as exc:  # noqa: BLE001 — background loop survives
                metrics.count("compactions_failed")
                self.last_error = exc
                if sp is not None:
                    sp.attrs["status"] = "failed"
                return None
        metrics.count("compactions_completed")
        self.last_error = None
        return reason

    # -- background loop ----------------------------------------------

    def start(self) -> "CompactionScheduler":
        expects(self._thread is None, "scheduler already started")
        self._stop.clear()
        self._thread = threading.Thread(target=self._loop,
                                        name="raft-tpu-compaction",
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self, timeout: Optional[float] = 30.0) -> None:
        if self._thread is None:
            return
        self._stop.set()
        self._thread.join(timeout)
        self._thread = None

    def _loop(self) -> None:
        while not self._stop.is_set():
            self.run_once()
            self._stop.wait(self.policy.poll_interval_s)

    def __enter__(self) -> "CompactionScheduler":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()
