"""Shape-bucket ladder — the fixed set of query-batch shapes a server
ever dispatches.

TPU search programs jit-specialize on the query-batch shape; ragged
online traffic would recompile per distinct size.  The ladder quantizes
every batch up to the smallest bucket that fits (padding with zero rows —
all search impls are row-independent, so pads never perturb real rows),
bounding the executable population at ``len(ladder)`` per
(family, k, dtype, level) and keeping every dispatch MXU-shaped.

Sizing guidance lives in ``docs/serving_guide.md``: geometric ladders
(e.g. 1/8/64/512) cap padding waste at ~8× worst case while covering
single-query point lookups and bulk scoring with four executables.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import numpy as np

from ..core.errors import expects

__all__ = ["DEFAULT_LADDER", "normalize_ladder", "bucket_for",
           "split_rows", "pad_rows"]

DEFAULT_LADDER: Tuple[int, ...] = (1, 8, 64, 512)


def normalize_ladder(ladder: Sequence[int]) -> Tuple[int, ...]:
    """Validate + canonicalize: sorted, deduplicated, all >= 1."""
    expects(len(tuple(ladder)) > 0, "bucket ladder must not be empty")
    lad = tuple(sorted({int(b) for b in ladder}))
    expects(lad[0] >= 1, f"bucket sizes must be >= 1, got {lad}")
    return lad


def bucket_for(n: int, ladder: Sequence[int]) -> Optional[int]:
    """Smallest bucket holding ``n`` rows, or None when ``n`` exceeds the
    ladder (the caller splits via :func:`split_rows`)."""
    for b in ladder:
        if n <= b:
            return int(b)
    return None


def split_rows(n: int, max_bucket: int):
    """Greedy split of an oversized request into ``<= max_bucket``-row
    parts (all but the last full-sized, so they batch alone at perfect
    fill)."""
    expects(n >= 1, "need at least one row")
    out = []
    while n > 0:
        take = min(n, int(max_bucket))
        out.append(take)
        n -= take
    return out


def pad_rows(rows: np.ndarray, bucket: int) -> np.ndarray:
    """Zero-pad a host (n, d) block to ``(bucket, d)`` (no-op when full).
    Zero rows are safe: every search impl is per-row independent, and the
    server slices the first n result rows back out."""
    n, d = rows.shape
    expects(n <= bucket, f"{n} rows exceed bucket {bucket}")
    if n == bucket:
        return rows
    out = np.zeros((bucket, d), dtype=rows.dtype)
    out[:n] = rows
    return out
