"""AOT executable cache — one compiled program per (family, bucket, k,
dtype, degrade level).

Uses the ``jax.jit(fn).lower(spec, *operands).compile()`` discipline of
``tests/test_export_aot.py``: the searcher ``fn`` takes the index state
as *operands* (never closure constants), so every bucket executable
shares the same on-device slabs instead of baking per-bucket copies.

Counters (hits / misses / compiles) are the observability contract the
serve guard tests assert on: a mixed-shape workload must never compile
more than ``len(ladder)`` executables per (family, k, dtype, level).
"""

from __future__ import annotations

import time
from typing import Callable, Tuple

import jax

from ..core import lockdep, tracing

__all__ = ["ExecutableCache"]


class ExecutableCache:
    """Thread-safe compile-once cache of AOT-lowered search executables.

    ``get(key, builder)`` returns the compiled executable; ``builder`` is
    only invoked on a miss and must return ``(fn, operands, q_spec)``
    where ``fn(queries, *operands)`` is jit-traceable and ``q_spec`` is a
    ``jax.ShapeDtypeStruct`` for the padded query bucket.  Only the
    *compiled program* is cached — operands are generation state the
    server owns (``SearchServer._parts``), so an index swap to a
    same-shaped generation reuses every executable (the key carries the
    operand scope, shapes + dtypes, not the arrays).  Compilation
    happens under the cache lock — the single-writer discipline that makes
    the compile counter an exact recompilation census (the property the
    serve guard test asserts).
    """

    def __init__(self) -> None:
        self._lock = lockdep.lock("ExecutableCache._lock")
        self._entries: dict = {}  # guarded_by: _lock
        self.hits = 0             # guarded_by: _lock
        self.misses = 0           # guarded_by: _lock
        self.compiles = 0         # guarded_by: _lock
        self.compile_s = 0.0      # guarded_by: _lock

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def get(self, key, builder: Callable[[], Tuple]):
        with self._lock:
            entry = self._entries.get(key)
            if entry is not None:
                self.hits += 1
                return entry
            self.misses += 1
            fn, operands, q_spec = builder()
            t0 = time.perf_counter()
            with tracing.range("serve.compile(%s)", key):
                compiled = jax.jit(fn).lower(q_spec, *operands).compile()
            self.compile_s += time.perf_counter() - t0
            self.compiles += 1
            self._entries[key] = compiled
            return compiled

    def contains(self, key) -> bool:
        with self._lock:
            return key in self._entries

    def snapshot(self) -> dict:
        with self._lock:
            return {"entries": len(self._entries), "hits": self.hits,
                    "misses": self.misses, "compiles": self.compiles,
                    "compile_s": round(self.compile_s, 3)}
