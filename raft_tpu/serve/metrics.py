"""Serving metrics — counters, latency percentiles, JSON export.

One :class:`ServingMetrics` per server: submit/reject/timeout counters,
batch shape accounting (fill ratio = real rows / padded rows, the
padding-waste signal that tunes the bucket ladder), a bounded latency
reservoir for p50/p95/p99, and per-level degradation dispatch counts.
``snapshot()`` is the JSON schema documented in
``docs/serving_guide.md`` and consumed by ``bench/serve.py``.
"""

from __future__ import annotations

import json
import math
import threading
from collections import deque

__all__ = ["ServingMetrics", "percentile"]


def percentile(sorted_vals, q: float) -> float:
    """Nearest-rank percentile over an ascending list (0 < q <= 100)."""
    if not sorted_vals:
        return 0.0
    rank = max(0, min(len(sorted_vals) - 1,
                      math.ceil(q / 100.0 * len(sorted_vals)) - 1))
    return float(sorted_vals[rank])


class ServingMetrics:
    """Thread-safe counters + bounded latency reservoir."""

    def __init__(self, latency_window: int = 4096) -> None:
        self._lock = threading.Lock()
        self._lat_ms = deque(maxlen=int(latency_window))
        self.submitted = 0           # requests accepted into the queue
        self.completed = 0           # requests answered
        self.rejected_queue_full = 0
        self.rejected_deadline = 0   # expired while queued, never dispatched
        self.late_completions = 0    # answered, but past their deadline
        self.batches = 0
        self.real_rows = 0           # query rows carried by requests
        self.padded_rows = 0         # bucket rows dispatched (>= real_rows)
        self.swaps = 0               # generation handoffs completed
        self.failed_swaps = 0        # swaps rolled back (old gen kept)
        self.retries = 0             # dispatch retries after transient faults
        self.faulted_batches = 0     # batches rejected with retries exhausted
        self.wal_appends = 0         # durable mutations logged (neighbors.wal)
        self.wal_replayed = 0        # WAL records replayed during recovery
        self.snapshots = 0           # crash-consistent snapshots published
        self.quarantined_files = 0   # corrupt artifacts renamed aside
        self.recoveries = 0          # DurableStore.recover completions
        self.compactions_scheduled = 0  # scheduler trigger firings
        self.compactions_completed = 0  # compaction + swap succeeded
        self.compactions_failed = 0     # compaction attempts rolled back
        self.degrade_dispatches: dict = {}  # level -> batch count

    def count(self, field: str, n: int = 1) -> None:
        with self._lock:
            setattr(self, field, getattr(self, field) + n)

    def observe_batch(self, bucket: int, rows: int, level: int) -> None:
        with self._lock:
            self.batches += 1
            self.real_rows += int(rows)
            self.padded_rows += int(bucket)
            self.degrade_dispatches[level] = \
                self.degrade_dispatches.get(level, 0) + 1

    def observe_latency(self, ms: float, late: bool = False) -> None:
        with self._lock:
            self.completed += 1
            self._lat_ms.append(float(ms))
            if late:
                self.late_completions += 1

    def snapshot(self) -> dict:
        """Point-in-time metrics dict (the serving-guide JSON schema)."""
        with self._lock:
            lat = sorted(self._lat_ms)
            fill = (self.real_rows / self.padded_rows
                    if self.padded_rows else 0.0)
            return {
                "submitted": self.submitted,
                "completed": self.completed,
                "rejected_queue_full": self.rejected_queue_full,
                "rejected_deadline": self.rejected_deadline,
                "late_completions": self.late_completions,
                "batches": self.batches,
                "real_rows": self.real_rows,
                "padded_rows": self.padded_rows,
                "swaps": self.swaps,
                "failed_swaps": self.failed_swaps,
                "retries": self.retries,
                "faulted_batches": self.faulted_batches,
                "wal_appends": self.wal_appends,
                "wal_replayed": self.wal_replayed,
                "snapshots": self.snapshots,
                "quarantined_files": self.quarantined_files,
                "recoveries": self.recoveries,
                "compactions_scheduled": self.compactions_scheduled,
                "compactions_completed": self.compactions_completed,
                "compactions_failed": self.compactions_failed,
                "batch_fill_ratio": round(fill, 4),
                "degrade_dispatches": {str(k): v for k, v in
                                       sorted(self.degrade_dispatches.items())},
                "latency_ms": {
                    "count": len(lat),
                    "p50": round(percentile(lat, 50), 3),
                    "p95": round(percentile(lat, 95), 3),
                    "p99": round(percentile(lat, 99), 3),
                    "max": round(lat[-1], 3) if lat else 0.0,
                },
            }

    def to_json(self, path=None, extra=None) -> str:
        """Serialize ``snapshot()`` (+ optional extra keys, e.g. cache
        counters and queue depth from the server) to JSON; write to
        ``path`` when given."""
        snap = self.snapshot()
        if extra:
            snap.update(extra)
        text = json.dumps(snap, indent=2, sort_keys=True)
        if path:
            with open(path, "w") as f:
                f.write(text + "\n")
        return text
