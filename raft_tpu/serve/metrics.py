"""Serving metrics — registry-backed counters, histograms, JSON export.

One :class:`ServingMetrics` per server: submit/reject/timeout counters,
batch shape accounting (fill ratio = real rows / padded rows, the
padding-waste signal that tunes the bucket ladder), latency tracked BOTH
ways — a bounded reservoir for exact window p50/p95/p99 (the historical
JSON schema) and a fixed-boundary :class:`raft_tpu.obs.Histogram` whose
bucket counts are mergeable across replicas (the pod-scale story the
reservoir cannot serve) — and per-level degradation dispatch counts.

Every counter lives in a per-server :class:`raft_tpu.obs.MetricRegistry`
(ISSUE 9): ``snapshot()`` keeps the exact ``docs/serving_guide.md`` JSON
schema, and :meth:`prometheus_text` renders the same registry (plus the
process-global one, which carries Pallas gate fallbacks and tracing
diagnostics) as Prometheus text exposition.

``count()`` accepts **registered names only** and raises
:class:`UnknownCounter` otherwise — a typo'd counter name used to
surface as a confusing ``AttributeError`` deep in ``setattr`` math.
Subsystems with genuinely new counters declare them first with
:meth:`ServingMetrics.declare` (the documented dynamic-create path).
"""

from __future__ import annotations

import json
import math
from collections import deque
from typing import Optional, Sequence

from ..core import lockdep
from ..obs.metrics import (DEFAULT_LATENCY_BOUNDARIES_MS, MetricRegistry)

__all__ = ["ServingMetrics", "UnknownCounter", "percentile"]


class UnknownCounter(KeyError):
    """``count()`` was called with a name no one registered — almost
    always a typo; use :meth:`ServingMetrics.declare` for intentional
    dynamic counters."""


def percentile(sorted_vals, q: float) -> float:
    """Nearest-rank percentile over an ascending list (0 < q <= 100)."""
    if not sorted_vals:
        return 0.0
    rank = max(0, min(len(sorted_vals) - 1,
                      math.ceil(q / 100.0 * len(sorted_vals)) - 1))
    return float(sorted_vals[rank])


#: (field, help) — the registered counter set; the field name is both the
#: ``count()`` key and the ``snapshot()`` JSON key, the Prometheus name is
#: ``raft_serve_<field>_total``.
COUNTER_SPECS = (
    ("submitted", "requests accepted into the queue"),
    ("completed", "requests answered"),
    ("rejected_queue_full", "submits refused at queue capacity"),
    ("rejected_deadline", "requests expired while queued, never dispatched"),
    ("late_completions", "requests answered past their deadline"),
    ("batches", "accelerator dispatches"),
    ("real_rows", "query rows carried by requests"),
    ("padded_rows", "bucket rows dispatched (>= real_rows)"),
    ("swaps", "generation handoffs completed"),
    ("failed_swaps", "swaps rolled back (old generation kept)"),
    ("retries", "dispatch retries after transient faults"),
    ("faulted_batches", "batches rejected with retries exhausted"),
    ("stalls", "wedged dispatches detected by the stall watchdog"),
    ("wal_appends", "durable mutations logged (neighbors.wal)"),
    ("wal_replayed", "WAL records replayed during recovery"),
    ("wal_replicated", "shipped WAL records applied by a standby"),
    ("wal_pruned", "WAL records discarded by prune (snapshot + follower "
     "ack floor)"),
    ("fenced_writes", "writes rejected on a deposed primary (epoch fence)"),
    ("snapshots", "crash-consistent snapshots published"),
    ("quarantined_files", "corrupt artifacts renamed aside"),
    ("recoveries", "DurableStore.recover completions"),
    ("compactions_scheduled", "scheduler trigger firings"),
    ("compactions_completed", "compaction + swap succeeded"),
    ("compactions_failed", "compaction attempts rolled back"),
    ("quality_samples", "requests shadow-sampled for the recall oracle"),
    ("quality_sample_drops", "shadow samples dropped at the bounded queue"),
    ("quality_guard_overrides",
     "degradation levels refused by the recall guard"),
    ("stall_dumps_pruned", "quarantined stall dumps removed by retention"),
)


class ServingMetrics:
    """Thread-safe registry-backed counters + latency reservoir +
    mergeable latency histogram.

    Registered counters read as attributes (``metrics.completed``) for
    backward compatibility with the flat-field era; ``registry`` is the
    per-server :class:`~raft_tpu.obs.MetricRegistry` the Prometheus
    exposition renders."""

    def __init__(self, latency_window: int = 4096, *,
                 registry: Optional[MetricRegistry] = None,
                 latency_boundaries_ms: Sequence[float] =
                 DEFAULT_LATENCY_BOUNDARIES_MS) -> None:
        self._lock = lockdep.lock("ServingMetrics._lock")
        self._lat_ms = deque(maxlen=int(latency_window))  # guarded_by: _lock
        self.registry = registry if registry is not None else MetricRegistry()
        self._counters = {}
        for field, help_ in COUNTER_SPECS:
            self._counters[field] = self.registry.counter(
                f"raft_serve_{field}_total", help_)
        self.latency_hist = self.registry.histogram(
            "raft_serve_latency_ms",
            "request latency, submit to reply (fixed mergeable buckets)",
            latency_boundaries_ms)
        self._degrade = self.registry.counter(
            "raft_serve_degrade_dispatches_total",
            "batches dispatched per degradation level")

    # -- counters -----------------------------------------------------------

    def declare(self, field: str, help: str = "") -> None:
        """Register a new counter at runtime (the documented
        dynamic-create path — e.g. an embedding host's custom serve
        counter).  Idempotent; the field then works with :meth:`count`,
        attribute reads, ``snapshot()`` and the Prometheus exposition."""
        with self._lock:
            if field not in self._counters:
                self._counters[field] = self.registry.counter(
                    f"raft_serve_{field}_total", help)

    def count(self, field: str, n: int = 1) -> None:
        c = self._counters.get(field)
        if c is None:
            raise UnknownCounter(
                f"unknown serving counter {field!r} — registered: "
                f"{sorted(self._counters)}; use declare({field!r}) first "
                "for an intentional new counter")
        c.inc(n)

    def counter_value(self, field: str) -> int:
        c = self._counters.get(field)
        if c is None:
            raise UnknownCounter(f"unknown serving counter {field!r}")
        return int(c.value())

    def __getattr__(self, name: str):
        # only reached when normal attribute lookup fails: registered
        # counters read as plain ints (the flat-field era API)
        counters = self.__dict__.get("_counters")
        if counters is not None and name in counters:
            return int(counters[name].value())
        raise AttributeError(
            f"{type(self).__name__!r} object has no attribute {name!r}")

    # -- observations -------------------------------------------------------

    def observe_batch(self, bucket: int, rows: int, level: int) -> None:
        self._counters["batches"].inc()
        self._counters["real_rows"].inc(int(rows))
        self._counters["padded_rows"].inc(int(bucket))
        self._degrade.inc(level=str(int(level)))

    def observe_latency(self, ms: float, late: bool = False) -> None:
        self._counters["completed"].inc()
        self.latency_hist.observe(float(ms))
        with self._lock:
            self._lat_ms.append(float(ms))
        if late:
            self._counters["late_completions"].inc()

    # -- export -------------------------------------------------------------

    @property
    def degrade_dispatches(self) -> dict:
        """``{level: batch count}`` — derived from the labelled counter."""
        return {int(labels["level"]): int(v)
                for labels, v in self._degrade.samples()}

    def snapshot(self) -> dict:
        """Point-in-time metrics dict (the serving-guide JSON schema,
        backward-compatible) + the mergeable ``latency_hist`` block."""
        with self._lock:
            lat = sorted(self._lat_ms)
        snap = {field: int(c.value()) for field, c in self._counters.items()}
        fill = (snap["real_rows"] / snap["padded_rows"]
                if snap["padded_rows"] else 0.0)
        hist = self.latency_hist.samples()
        counts, total = (hist[0][1], hist[0][2]) if hist else ([], 0.0)
        snap.update({
            "batch_fill_ratio": round(fill, 4),
            "degrade_dispatches": {str(k): v for k, v in
                                   sorted(self.degrade_dispatches.items())},
            "latency_ms": {
                "count": len(lat),
                "p50": round(percentile(lat, 50), 3),
                "p95": round(percentile(lat, 95), 3),
                "p99": round(percentile(lat, 99), 3),
                "max": round(lat[-1], 3) if lat else 0.0,
            },
            "latency_hist": {
                "boundaries_ms": list(self.latency_hist.boundaries),
                "counts": list(counts),
                "sum_ms": round(float(total), 3),
            },
        })
        return snap

    def prometheus_text(self, extra_registries: Sequence = ()) -> str:
        """Prometheus text exposition of this server's registry, any
        ``extra_registries``, and the process-global one (gate fallbacks,
        tracing diagnostics) — one scrape body for the whole process."""
        from ..obs.metrics import registry as global_registry
        from ..obs.prometheus import render

        return render((self.registry, *extra_registries, global_registry()))

    def to_json(self, path=None, extra=None) -> str:
        """Serialize ``snapshot()`` (+ optional extra keys, e.g. cache
        counters and queue depth from the server) to JSON; write to
        ``path`` when given (atomically — a mid-write crash never leaves
        a torn metrics file)."""
        snap = self.snapshot()
        if extra:
            snap.update(extra)
        text = json.dumps(snap, indent=2, sort_keys=True)
        if path:
            from ..core.serialize import write_text_atomic

            write_text_atomic(path, text + "\n")
        return text
