"""Generation-aware copy-on-write index registry.

The zero-downtime-handoff primitive: the server dispatches against an
immutable :class:`Generation` snapshot while a replacement builds in the
background, then :meth:`IndexRegistry.swap` makes the new generation
current in one reference assignment.

Why this is already copy-on-write: every index here is a frozen pytree of
device arrays — "mutation" (extend/delete/compact) returns a NEW index
sharing unchanged slabs with the old one.  So a snapshot is just a
reference, and in-flight dispatches that captured the old generation's
operands keep its arrays alive until they resolve (the GC is the drain
barrier) — zero dropped requests, no locking on the dispatch path beyond
one attribute read.

Executable reuse across generations is the cache's job: bucket keys
include only the operand *scope* (shapes + dtypes), so a same-shaped new
generation reuses every compiled program — zero steady-state recompiles
across swaps (see ``SearchServer._compiled``).
"""

from __future__ import annotations

import dataclasses
from typing import Any

from ..core import lockdep

__all__ = ["Generation", "IndexRegistry"]


@dataclasses.dataclass(frozen=True)
class Generation:
    """One immutable snapshot: the index (or ``mutation.Tombstoned``
    view) plus its monotonically increasing generation number."""

    index: Any
    gen_id: int


class IndexRegistry:
    """Holds the current :class:`Generation`; swaps are atomic.

    ``current`` is a single attribute read (Python reference assignment
    is atomic), so the dispatch path never takes the lock — the lock only
    serializes writers, keeping ``gen_id`` strictly increasing when
    several background builders race."""

    def __init__(self, index, *, on_swap=None) -> None:
        self._lock = lockdep.lock("IndexRegistry._lock")
        self._current = Generation(index, 0)  # guarded_by: _lock  (reads are lock-free reference loads)
        self.swaps = 0                        # guarded_by: _lock
        #: optional callable invoked with each newly installed
        #: :class:`Generation`, outside the lock (the server hangs its
        #: index-health export here — see ``neighbors.health``)
        self.on_swap = on_swap

    @property
    def current(self) -> Generation:
        return self._current

    @property
    def gen_id(self) -> int:
        return self._current.gen_id

    def swap(self, new_index) -> Generation:
        """Install ``new_index`` as the next generation and return it.
        Validation belongs to the caller (``SearchServer.swap_index``
        checks family/dim/dtype compatibility and wraps failures in
        ``faults.SwapFailed`` *before* calling this)."""
        with self._lock:
            gen = Generation(new_index, self._current.gen_id + 1)
            self._current = gen
            self.swaps += 1
        cb = self.on_swap
        if cb is not None:       # outside the lock: the hook may be slow
            cb(gen)
        return gen
