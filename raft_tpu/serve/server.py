"""SearchServer — the online serving front-end over any built index.

Composition (one instance each): a FIFO request queue guarded by a
condition variable, the :mod:`.batcher` plan, the :mod:`.cache` of
AOT bucket executables, the :mod:`.admission` controller, and
:mod:`.metrics`.  A single dispatch thread owns the accelerator —
requests enter via ``submit()`` from any number of client threads and
resolve through ``concurrent.futures.Future``.

Determinism hooks for tests: construct with a fake ``clock``, skip
``start()``, and drive dispatches synchronously with ``step()``.
"""

from __future__ import annotations

import dataclasses
import random
import threading
import time
from concurrent.futures import Future
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..core import lockdep, tracing
from ..core.errors import expects
from ..core.logging import default_logger
from ..obs import spans as obs_spans
from .admission import (AdmissionController, AdmissionPolicy,
                        DeadlineExceeded, QueueFull, RetryPolicy,
                        ServeError)
from .batcher import Request, SplitSink, plan_batch
from .bucketing import DEFAULT_LADDER, normalize_ladder, pad_rows, split_rows
from .cache import ExecutableCache
from .faults import TRANSIENT_FAULTS, FaultInjector, SwapFailed
from .metrics import ServingMetrics
from .registry import IndexRegistry
from .searchers import (family_of, index_dim, index_size, make_searcher,
                        query_dtype_of)

__all__ = ["ServerConfig", "SearchServer"]


def _host_pool_stats() -> dict:
    """Process staging-pool stats, exported to the global registry
    gauges on every snapshot (``core.host_memory
    .export_host_pool_metrics``)."""
    from ..core.host_memory import export_host_pool_metrics

    return export_host_pool_metrics()


@dataclasses.dataclass(frozen=True)
class ServerConfig:
    """Serving knobs (see ``docs/serving_guide.md`` for sizing).

    ``ladder``: the shape buckets; ``max_wait_ms``: how long the batcher
    holds a non-full batch open for more arrivals; ``warm_levels``: how
    many degradation levels ``start()`` precompiles (level 0 is the
    bit-identical full-quality tier; deeper levels compile on first
    pressure unless warmed here); ``retry``: backoff schedule for
    transient dispatch faults (wedge/OOM — see :mod:`.faults`).
    """

    ladder: Tuple[int, ...] = DEFAULT_LADDER
    max_wait_ms: float = 2.0
    max_queue: int = 1024
    default_deadline_ms: float = 1000.0
    degrade_queue_fractions: Tuple[float, ...] = (0.5, 0.8)
    degrade_effort_scales: Tuple[float, ...] = (1.0, 0.5, 0.25)
    warm_levels: int = 1
    latency_window: int = 4096
    retry: RetryPolicy = RetryPolicy()

    def __post_init__(self):
        expects(len(self.degrade_effort_scales)
                == len(self.degrade_queue_fractions) + 1,
                "need one effort scale per degradation level (fractions"
                " define levels 1.., scales include level 0)")
        expects(self.degrade_effort_scales[0] == 1.0,
                "level 0 must be full quality (scale 1.0) — the serve"
                " bit-identity contract")
        expects(1 <= self.warm_levels <= len(self.degrade_effort_scales),
                "warm_levels out of range")
        expects(self.max_wait_ms >= 0, "max_wait_ms must be >= 0")


class SearchServer:
    """Micro-batching, deadline-aware serving wrapper around one index.

    ``index`` is any built index (IvfFlatIndex / IvfPqIndex / CagraIndex)
    or a raw (n, d) database array (brute force).  ``params`` is that
    family's SearchParams (``serve.searchers.BruteForceSearchParams`` for
    raw arrays).  Results are bit-identical to the family's direct
    ``search()`` at degradation level 0.

    ``clock`` (monotonic seconds) is injectable for deterministic tests;
    the dispatch thread's *waits* always use real time, so a fake clock
    only makes sense with manual ``step()`` driving.

    The index lives in a generation registry (:mod:`.registry`):
    :meth:`swap_index` installs a replacement with zero dropped requests
    and — for a same-shaped generation — zero recompiles (executable
    cache keys carry operand shapes, not the arrays).  ``faults`` is an
    optional :class:`.faults.FaultInjector` (default: armed from
    ``RAFT_SERVE_FAULTS`` if set, else inert); ``sleep`` injects the
    retry-backoff sleeper for deterministic fault tests.
    """

    def __init__(self, index, k: int = 10, params=None, *,
                 config: Optional[ServerConfig] = None,
                 clock=time.monotonic, seed: int = 0, res=None,
                 faults: Optional[FaultInjector] = None,
                 sleep=time.sleep, recorder=None) -> None:
        self._registry = IndexRegistry(index)
        self.family = family_of(index)
        expects(1 <= k <= index_size(index),
                f"k={k} out of range for index of {index_size(index)} rows")
        self.k = int(k)
        self.params = params
        self.config = config or ServerConfig()
        self.ladder = normalize_ladder(self.config.ladder)
        self.clock = clock
        self.seed = int(seed)
        self._dim = index_dim(index)
        self._qdtype = query_dtype_of(index)
        self.cache = ExecutableCache()
        self.metrics = ServingMetrics(self.config.latency_window)
        self.admission = AdmissionController(AdmissionPolicy(
            max_queue=self.config.max_queue,
            default_deadline_ms=self.config.default_deadline_ms,
            degrade_queue_fractions=self.config.degrade_queue_fractions))
        self.faults = faults if faults is not None \
            else FaultInjector.from_env(sleep=sleep)
        self._sleep = sleep
        # retry jitter draws from a seeded stream so fault tests replay
        # exactly; distinct replicas pass distinct seeds to decorrelate
        self._retry_rng = random.Random(self.seed ^ 0x9E3779B9)
        self.durable_store = None  # neighbors.wal.DurableStore, if adopted
        self.fence = None          # replication.EpochFence, if replicated
        self.replication = None    # LogShipper / StandbyReplica, if any
        # flight recorder: the process-wide ring unless the caller wires
        # its own (tests; multi-server hosts separating evidence)
        self.recorder = recorder if recorder is not None \
            else obs_spans.recorder()
        # _inflight is deliberately lock-free: a single tuple reference
        # swapped whole by the dispatch thread, read racily by observers
        self._inflight = None      # (site, t0) while a dispatch is on-device
        self._log = default_logger() if res is None else None
        self._cond = lockdep.condition("SearchServer._cond")
        self._parts_lock = lockdep.lock("SearchServer._parts_lock")
        self._searchers: dict = {}   # guarded_by: _parts_lock
        self._pending: list = []     # guarded_by: _cond
        self._thread: Optional[threading.Thread] = None
        self._running = False        # guarded_by: _cond
        # quality telemetry (opt-in via attach_quality); index-health
        # gauges are always on — recomputed for every swapped-in
        # generation so a bad compaction is visible in one scrape
        self.quality = None        # obs.quality.RecallEstimator
        self.slo = None            # obs.slo.SloEvaluator
        self._scan_kernel = str(
            getattr(self.params, "scan_kernel", None) or "xla")
        self._registry.on_swap = self._export_health
        self._export_health()

    @property
    def index(self):
        """The currently-serving generation's index (immutable snapshot —
        read it once per use; a concurrent swap replaces the reference,
        never the object)."""
        return self._registry.current.index

    # -- durability ---------------------------------------------------------

    def adopt_store(self, store) -> None:
        """Wire a ``neighbors.wal.DurableStore`` into this server: its
        accumulated counters (``wal_appends``/``wal_replayed``/
        ``quarantined_files``/``recoveries``/``snapshots``) transfer into
        the serving metrics, future store activity counts live, and the
        snapshot gains the WAL LSN watermark.  The store's index should
        be (or become, via :meth:`swap_index`) the serving generation."""
        self.durable_store = store
        for name, n in store.counters.items():
            self.metrics.count(name, n)
        store.metrics = self.metrics

    @classmethod
    def recover(cls, root, k: int = 10, params=None, *,
                store_config=None, **kw) -> "SearchServer":
        """Restore a crashed durable deployment and resume serving:
        ``DurableStore.recover(root)`` rebuilds the index (newest valid
        snapshot + WAL-tail replay, corrupt artifacts quarantined), the
        restored index becomes generation 0 of a fresh server, and the
        store is adopted (counters + watermark).  Remaining ``kw`` are
        :class:`SearchServer` constructor arguments; call ``start()`` (or
        drive ``step()``) on the result as usual."""
        from ..neighbors.wal import DurableStore

        store = DurableStore.recover(root, config=store_config,
                                     faults=kw.get("faults"))
        srv = cls(store.index, k, params, **kw)
        srv.adopt_store(store)
        return srv

    def attach_replication(self, role: str, transport=None, *,
                           config=None, node_id=None, root=None,
                           store_config=None, replica=None):
        """Wire WAL replication (:mod:`.replication`) onto this server.

        ``role="primary"`` hooks a :class:`.replication.LogShipper` onto
        the adopted :class:`~raft_tpu.neighbors.wal.DurableStore`: every
        committed mutation ships to the follower on ``transport``, acks
        flow back (``pump()`` manually or ``start()`` the background
        thread on the returned shipper), and the store + this server
        inherit the epoch fence — once deposed, appends and swaps raise
        :class:`.faults.FencedError`.

        ``role="standby"`` attaches a
        :class:`.replication.StandbyReplica` (pass ``root=`` for its
        durable directory, or a pre-built ``replica=``): applied records
        refresh the serving generation at the configured staleness
        bound, and ``replica.promote()`` fails this server over to
        primary.  Replication gauges/counters land on this server's
        metric registry, so ``prometheus_text()`` scrapes
        ``raft_replication_lag_{lsn,seconds}``,
        ``raft_replication_acks_total`` and ``raft_failovers_total``."""
        from .replication import LogShipper, StandbyReplica

        expects(role in ("primary", "standby"),
                f"role must be 'primary' or 'standby', got {role!r}")
        if role == "primary":
            expects(self.durable_store is not None,
                    "replicating a primary needs an adopted DurableStore "
                    "(SearchServer.recover or adopt_store first)")
            expects(transport is not None, "primary role needs a transport")
            shipper = LogShipper(self.durable_store, transport,
                                 config=config,
                                 node_id=node_id or "primary",
                                 registry=self.metrics.registry,
                                 faults=self.faults, clock=self.clock)
            self.fence = shipper.fence
            self.replication = shipper
            return shipper
        if replica is None:
            expects(transport is not None and root is not None,
                    "standby role needs transport= + root= "
                    "(or a pre-built replica=)")
            replica = StandbyReplica(root, transport, config=config,
                                     node_id=node_id or "standby",
                                     registry=self.metrics.registry,
                                     faults=self.faults, clock=self.clock,
                                     store_config=store_config)
        replica.attach_server(self)
        return replica

    @property
    def generation(self) -> int:
        return self._registry.gen_id

    # -- lifecycle ----------------------------------------------------------

    def warmup(self) -> int:
        """Precompile the bucket ladder (× ``warm_levels`` degradation
        tiers) for the default k and query dtype; returns the number of
        executables compiled.  Idempotent — the cache makes reruns free."""
        before = self.cache.compiles
        with tracing.range("serve.warmup(%s)", self.family):
            for level in range(self.config.warm_levels):
                for bucket in self.ladder:
                    self._compiled(bucket, self.k, self._qdtype, level)
        n = self.cache.compiles - before
        if self._log is not None and n:
            self._log.info(
                "serve warmup: %d executables (%s, ladder=%s, k=%d) in %.2fs",
                n, self.family, self.ladder, self.k, self.cache.compile_s)
        return n

    def start(self, warmup: bool = True) -> "SearchServer":
        """Warm the executable cache and start the dispatch thread."""
        expects(self._thread is None, "server already started")
        if warmup:
            self.warmup()
        with self._cond:
            self._running = True
        self._thread = threading.Thread(  # racelint: disable=JX14 dispatch thread owns its compiled executables (ExecutableCache built them under the pallas gate before serving)
            target=self._worker, name="raft-tpu-serve", daemon=True)
        self._thread.start()
        return self

    def stop(self, timeout: Optional[float] = 30.0) -> None:
        """Stop the dispatch thread; queued requests are drained first."""
        if self._thread is None:
            return
        with self._cond:
            self._running = False
            self._cond.notify_all()
        self._thread.join(timeout)
        self._thread = None

    def __enter__(self) -> "SearchServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- client surface -----------------------------------------------------

    def submit(self, queries, k: Optional[int] = None,
               deadline_ms: Optional[float] = None) -> Future:
        """Enqueue a search; returns a Future resolving to
        ``(distances, indices)`` numpy arrays of shape (rows, k).

        Raises :class:`QueueFull` when the bounded queue is at capacity
        (client backpressure); the Future raises
        :class:`DeadlineExceeded` when the deadline passes before
        dispatch.  Requests wider than the largest bucket are split and
        reassembled transparently."""
        q = np.asarray(queries)
        if q.ndim == 1:
            q = q[None, :]
        expects(q.ndim == 2, "queries must be 1-D or 2-D")
        expects(q.shape[0] >= 1, "empty query batch")
        expects(q.shape[1] == self._dim,
                f"query dim {q.shape[1]} != index dim {self._dim}")
        kk = self.k if k is None else int(k)
        expects(1 <= kk <= index_size(self.index),
                f"k={kk} out of range for index of "
                f"{index_size(self.index)} rows")
        now = self.clock()
        deadline = self.admission.deadline(now, deadline_ms)
        future: Future = Future()
        parts = split_rows(q.shape[0], self.ladder[-1])
        # the request's root span: opened here on the client thread,
        # finished by whichever thread resolves/rejects it — every later
        # lifecycle span (enqueue/batch-form/dispatch/device-exec/reply)
        # parents under it, forming one connected tree per request
        root = self.recorder.start("serve.request", rows=int(q.shape[0]),
                                   k=kk, parts=len(parts))
        t_enq = self.recorder.clock_ns() if root is not None else 0
        rejected_depth = None
        with self._cond:
            if not self.admission.admit(len(self._pending) + len(parts) - 1):
                rejected_depth = len(self._pending)
            else:
                if len(parts) == 1:
                    self._pending.append(Request(q, kk, deadline, now,
                                                 future=future, span=root))
                else:
                    sink = SplitSink(future, len(parts))
                    lo = 0
                    for i, rows in enumerate(parts):
                        self._pending.append(Request(q[lo:lo + rows], kk,
                                                     deadline, now, sink=sink,
                                                     part=i, span=root))
                        lo += rows
                self.metrics.count("submitted")
                self._cond.notify_all()
        if rejected_depth is not None:
            self.metrics.count("rejected_queue_full")
            self.recorder.finish(root, status="rejected_queue_full",
                                 queue_depth=rejected_depth)
            raise QueueFull(
                f"queue at capacity ({self.admission.policy.max_queue});"
                " retry with backoff or raise max_queue")
        if root is not None:
            self.recorder.record("serve.enqueue", t_enq,
                                 self.recorder.clock_ns(), parent=root,
                                 deadline_ms=round(1e3 * (deadline - now), 3))
        return future

    def search(self, queries, k: Optional[int] = None,
               deadline_ms: Optional[float] = None):
        """Synchronous convenience: ``submit()`` + wait.  Without a
        running dispatch thread this drives ``step()`` inline (the
        deterministic single-threaded mode the unit tests use)."""
        fut = self.submit(queries, k, deadline_ms)
        if self._thread is None:
            while not fut.done() and self.step():
                pass
        return fut.result(timeout=None if self._thread is None else
                          self.admission.policy.default_deadline_ms / 1e3
                          + 300.0)

    # -- dispatch -----------------------------------------------------------

    def step(self, now: Optional[float] = None) -> int:
        """Process one batch synchronously; returns the number of queue
        entries retired (0 = queue empty).  Expired entries are rejected
        before planning, so a step may retire requests without touching
        the accelerator."""
        if now is None:
            now = self.clock()
        t_plan = self.recorder.clock_ns() if self.recorder.enabled else 0
        with self._cond:
            expired = [r for r in self._pending if r.deadline < now]
            if expired:
                self._pending = [r for r in self._pending
                                 if r.deadline >= now]
            if not self._pending:
                batch = None
            else:
                depth = len(self._pending)
                batch, bucket = plan_batch(self._pending, self.ladder)
                chosen = set(map(id, batch))
                self._pending = [r for r in self._pending
                                 if id(r) not in chosen]
        for req in expired:
            self.metrics.count("rejected_deadline")
            self.recorder.finish(req.span, status="rejected_deadline")
            req.reject(DeadlineExceeded(
                f"deadline passed {1e3 * (now - req.deadline):.1f}ms before"
                " dispatch (queue wait exceeded the budget)"))
        if batch is None:
            return len(expired)
        if self.recorder.enabled:
            # post-hoc: planning ran under the queue lock; the span is
            # recorded after release, parented to the batch head's request
            self.recorder.record("serve.batch_form", t_plan,
                                 self.recorder.clock_ns(),
                                 parent=batch[0].span,
                                 n_requests=len(batch), queue_depth=depth)
        level = self.admission.guarded_level(
            depth, self._apply_quality_guard,
            max_level=len(self.config.degrade_effort_scales) - 1)
        self._execute(batch, bucket, level)
        return len(expired) + len(batch)

    def _apply_quality_guard(self, level: int) -> int:
        """Ask the SLO evaluator's recall guard before entering a ladder
        level; a refusal (guard picks a shallower level) is counted and
        recorded — the scrapeable trace of quality overriding load."""
        if self.slo is None:
            return level
        allowed = self.slo.quality_guard(level)
        if allowed != level:
            self.slo.overrides += 1
            self.metrics.count("quality_guard_overrides")
            self.recorder.event("serve.quality_guard",
                                requested=int(level), allowed=int(allowed))
        return allowed

    def _parts(self, k: int, level: int, gen=None):
        """(fn, operands) for one (generation, k, level) — memoized so the
        steady-state dispatch path never re-runs ``make_searcher`` (which
        would rebuild keep-mask/LUT operands per batch).  Older
        generations' entries are purged on first use of a newer one; any
        in-flight dispatch holds its own operand references, so the old
        arrays live exactly as long as requests that captured them."""
        gen = self._registry.current if gen is None else gen
        key = (gen.gen_id, int(k), int(level))
        with self._parts_lock:
            hit = self._searchers.get(key)
            if hit is not None:
                return hit
        scale = self.config.degrade_effort_scales[level]
        fn, operands = self._make_parts(gen.index, k, scale)
        with self._parts_lock:
            current = self._registry.gen_id
            for old in [kk for kk in self._searchers if kk[0] < current]:
                del self._searchers[old]
            self._searchers.setdefault(key, (fn, operands))
            return self._searchers[key]

    def _make_parts(self, index, k: int, scale: float):
        """Searcher-factory seam: build the ``(fn, operands)`` pair for
        one effort scale.  The fleet tier's per-replica servers override
        this with :func:`raft_tpu.serve.fleet.make_fleet_searcher` (the
        mesh-sharded fan-out) — everything else about dispatch (buckets,
        cache, admission, degradation) is topology-agnostic."""
        return make_searcher(index, k, self.params, effort_scale=scale,
                             seed=self.seed)

    def _stage_queries(self, qpad):
        """Host→device transfer seam for the padded query batch; fleet
        servers override to place the batch replicated over their mesh
        (an AOT executable's input sharding is part of its signature)."""
        return jax.device_put(qpad)

    def _query_spec(self, bucket: int, dtype):
        """The AOT lowering spec for one query bucket; fleet servers
        attach the replicated mesh sharding here so the compiled
        executable and :meth:`_stage_queries` agree."""
        return jax.ShapeDtypeStruct((bucket, self._dim), dtype)

    def queue_depth(self) -> int:
        """Requests waiting in the queue (lock-guarded read) — the
        router's load signal."""
        with self._cond:
            return len(self._pending)

    @staticmethod
    def _operand_scope(operands):
        """Shapes + dtypes of the searcher operands — the generation-
        INVARIANT part of an executable's identity.  Cache keys carry
        this instead of the arrays, so a swapped-in generation with the
        same slab shapes reuses every compiled program."""
        return tuple((tuple(a.shape), str(a.dtype)) for a in operands)

    def _compiled(self, bucket: int, k: int, dtype, level: int, gen=None):
        fn, operands = self._parts(k, level, gen)
        key = (self.family, int(bucket), int(k), str(jnp.dtype(dtype)),
               int(level), self._operand_scope(operands))

        def build():
            return fn, operands, self._query_spec(bucket, dtype)

        return self.cache.get(key, build), operands

    def _execute(self, batch, bucket: int, level: int) -> None:
        rows = sum(r.rows for r in batch)
        qpad = pad_rows(np.concatenate([r.queries for r in batch], axis=0)
                        if len(batch) > 1 else batch[0].queries, bucket)
        retry = self.config.retry
        backoffs = retry.start(self._retry_rng)
        attempt = 0
        # dispatch span: parented to the batch head's request (the other
        # requests are linked through `request_spans`); the in-flight
        # marker is what the stall watchdog polls — it stays set through
        # retries, so a wedge that burns backoff still reads as ONE stall
        dispatch = self.recorder.start(
            "serve.dispatch", parent=batch[0].span, bucket=int(bucket),
            level=int(level), n_requests=len(batch),
            request_spans=[r.span.span_id for r in batch
                           if r.span is not None])
        self._inflight = ("execute", self.clock())
        try:
            while True:
                try:
                    self.faults.fire("execute")
                    compiled, operands = self._compiled(bucket, batch[0].k,
                                                        qpad.dtype, level)
                    with self.recorder.span("serve.device_exec",
                                            parent=dispatch,
                                            attempt=attempt), \
                            tracing.range(
                                "serve.dispatch(%s,b=%d,k=%d,lvl=%d)",
                                self.family, bucket, batch[0].k, level):
                        # explicit transfers at the serving boundary:
                        # device_put / device_get pass
                        # ``jax.transfer_guard("disallow")``, so a
                        # TraceGuard-wrapped serve loop proves these are the
                        # ONLY host<->device crossings on the path
                        d, i = compiled(self._stage_queries(qpad), *operands)
                        d, i = jax.device_get((d, i))  # host fetch = completion barrier
                        d = np.asarray(d)
                        i = np.asarray(i)
                    break
                except TRANSIENT_FAULTS as exc:
                    attempt += 1
                    backoff = backoffs.next_s()
                    earliest = min(r.deadline for r in batch)
                    if attempt > retry.max_retries:
                        self.metrics.count("faulted_batches")
                        self.recorder.finish(dispatch, status="faulted",
                                             error=type(exc).__name__)
                        for req in batch:
                            self.recorder.finish(req.span, status="faulted")
                            req.reject(exc)
                        return
                    if self.clock() + backoff > earliest:
                        # deadline-aware retry budget: don't burn backoff on
                        # answers nobody will be waiting for
                        self.metrics.count("faulted_batches")
                        err = DeadlineExceeded(
                            f"transient fault ({exc!r}) and the next "
                            f"{1e3 * backoff:.1f}ms backoff outlives the "
                            "batch deadline")
                        self.recorder.finish(dispatch, status="faulted",
                                             error=type(exc).__name__)
                        for req in batch:
                            self.recorder.finish(req.span, status="faulted")
                            req.reject(err)
                        return
                    self.metrics.count("retries")
                    self.recorder.event("serve.retry", parent=dispatch,
                                        attempt=attempt,
                                        backoff_ms=round(1e3 * backoff, 3),
                                        error=type(exc).__name__)
                    self._sleep(backoff)
                except Exception as exc:  # noqa: BLE001 — fail the batch, not the server
                    self.recorder.finish(dispatch, status="error",
                                         error=type(exc).__name__)
                    for req in batch:
                        self.recorder.finish(req.span, status="error")
                        req.reject(ServeError(f"dispatch failed: {exc!r}"))
                    raise
        finally:
            self._inflight = None
        self.recorder.finish(dispatch, status="ok", attempts=attempt + 1)
        done = self.clock()
        self.metrics.observe_batch(bucket, rows, level)
        lo = 0
        for req in batch:
            hi = lo + req.rows
            reply_ns = self.recorder.clock_ns() if self.recorder.enabled else 0
            req.resolve(d[lo:hi], i[lo:hi])
            if self.quality is not None:
                # shadow-sampling hook: one hash per request; selected
                # requests copy onto the bounded oracle queue (overflow
                # drops) — the reply above is already on its way
                self.quality.maybe_sample(
                    req.queries, i[lo:hi], level=level,
                    generation=self._registry.gen_id,
                    scan_kernel=self._scan_kernel)
            if req.span is not None:
                self.recorder.record("serve.reply", reply_ns,
                                     self.recorder.clock_ns(),
                                     parent=req.span, part=req.part)
                self.recorder.finish(req.span, status="ok")
            self.metrics.observe_latency(1e3 * (done - req.t_submit),
                                         late=done > req.deadline)
            lo = hi

    # -- generation handoff -------------------------------------------------

    def swap_index(self, new_index=None, *, build=None):
        """Install a new index generation with zero dropped requests.

        Pass either a built ``new_index`` or a zero-arg ``build``
        callable (run here, with transient-fault retry — the
        OOM-on-extend recovery path).  The new generation is validated
        (family / dim / query dtype / size ≥ k) and its level-0 ladder
        pre-warmed **before** the atomic registry swap, so traffic never
        waits on a compile; a same-shaped generation reuses every cached
        executable (zero recompiles).  Any failure raises
        :class:`.faults.SwapFailed` and leaves the old generation
        serving.  In-flight batches that captured old-generation operands
        complete against them — the swap never interrupts a dispatch."""
        expects((new_index is None) != (build is None),
                "pass exactly one of new_index= or build=")
        if self.fence is not None:  # a deposed primary must not swap
            self.fence.check("swap", count=self.metrics.count)
        old = self._registry.current
        retry = self.config.retry
        try:
            if build is not None:
                attempt = 0
                backoffs = retry.start(self._retry_rng)
                while True:
                    try:
                        self.faults.fire("extend")
                        new_index = build()
                        break
                    except TRANSIENT_FAULTS:
                        attempt += 1
                        if attempt > retry.max_retries:
                            raise
                        self.metrics.count("retries")
                        self._sleep(backoffs.next_s())
            self.faults.fire("swap")
            expects(family_of(new_index) == self.family,
                    f"swap changes index family ({self.family} -> "
                    f"{family_of(new_index)})")
            expects(index_dim(new_index) == self._dim,
                    f"swap changes vector dim ({self._dim} -> "
                    f"{index_dim(new_index)})")
            expects(str(jnp.dtype(query_dtype_of(new_index)))
                    == str(jnp.dtype(self._qdtype)),
                    "swap changes the query dtype")
            expects(self.k <= index_size(new_index),
                    f"new generation has {index_size(new_index)} rows < "
                    f"k={self.k}")
            # pre-warm the prospective generation OUTSIDE the registry —
            # its compiles (zero, when shapes match) happen while the old
            # generation keeps serving
            prospective = type(old)(new_index, old.gen_id + 1)
            for level in range(self.config.warm_levels):
                for bucket in self.ladder:
                    self._compiled(bucket, self.k, self._qdtype, level,
                                   gen=prospective)
        except Exception as exc:
            self.metrics.count("failed_swaps")
            raise SwapFailed(
                f"swap aborted, generation {old.gen_id} still serving: "
                f"{exc}") from exc
        gen = self._registry.swap(new_index)
        with self._parts_lock:
            # re-key the pre-warmed parts under the REAL gen_id (a racing
            # swap may have bumped it past the prospective one)
            for (g, k, lvl) in list(self._searchers):
                if g == prospective.gen_id and g != gen.gen_id:
                    self._searchers[(gen.gen_id, k, lvl)] = \
                        self._searchers.pop((g, k, lvl))
        self.metrics.count("swaps")
        if self._log is not None:
            self._log.info("serve swap: generation %d -> %d (%s, %d rows)",
                           old.gen_id, gen.gen_id, self.family,
                           index_size(new_index))
        return gen

    def _worker(self) -> None:
        max_rows = self.ladder[-1]
        wait_s = self.config.max_wait_ms / 1e3
        while True:
            with self._cond:
                while self._running and not self._pending:
                    self._cond.wait(0.05)
                if not self._running and not self._pending:
                    return
                # batching window: hold for more arrivals until the
                # largest bucket fills or the window elapses (real time —
                # see the clock note in the class docstring)
                t0 = time.monotonic()
                while (self._running
                       and sum(r.rows for r in self._pending) < max_rows):
                    rem = t0 + wait_s - time.monotonic()
                    if rem <= 0:
                        break
                    self._cond.wait(rem)
            while self.step():
                pass

    # -- observability ------------------------------------------------------

    def dispatch_inflight(self):
        """``(site, t0)`` while a dispatch is executing on-device (server
        clock seconds), else ``None`` — the marker
        :class:`raft_tpu.obs.StallWatchdog` polls for the wedge failure
        mode.  Reads are lock-free: a Python tuple swap is atomic."""
        return self._inflight

    def _export_health(self, gen=None) -> dict:
        """Compute + export :func:`raft_tpu.neighbors.health.index_health`
        gauges for one generation (the ``IndexRegistry.on_swap`` hook;
        also runs at construction for generation 0).  Health telemetry
        must never take down serving, so failures degrade to an empty
        dict instead of raising out of a swap."""
        from ..neighbors.health import export_index_health

        gen = self._registry.current if gen is None else gen
        try:
            return export_index_health(self.metrics.registry, gen.index,
                                       generation=gen.gen_id)
        except Exception as exc:  # noqa: BLE001 — telemetry, not control
            self.recorder.event("serve.health_export_error",
                                generation=gen.gen_id,
                                error=type(exc).__name__)
            return {}

    def attach_quality(self, config=None, *, policy=None,
                       baseline_queries=None):
        """Wire the search-quality telemetry loop onto this server:
        a :class:`raft_tpu.obs.quality.RecallEstimator` shadow-sampling
        live requests (``config``: its ``QualityConfig``), an
        :class:`raft_tpu.obs.slo.SloEvaluator` over latency /
        availability / recall (``policy``: its ``SloPolicy``) whose
        recall guard the degradation ladder now consults, and — when
        ``baseline_queries`` is given — a
        :class:`raft_tpu.obs.drift.DriftDetector` fed from the sampled
        queries.  All metrics land in this server's registry, so
        :meth:`prometheus_text` carries them.

        Returns the estimator.  Call ``.start()`` on it for a background
        oracle worker, or drive ``.drain()`` inline in deterministic
        tests.  Attach before ``start()``; re-attaching replaces the
        previous wiring."""
        from ..obs.quality import RecallEstimator
        from ..obs.slo import SloEvaluator

        self.quality = RecallEstimator(
            self.index, self.k, config, registry=self.metrics.registry,
            metrics=self.metrics, recorder=self.recorder)
        if baseline_queries is not None:
            from ..obs.drift import DriftDetector

            self.quality.drift = DriftDetector.from_index(
                self.index, baseline_queries,
                registry=self.metrics.registry)
        self.slo = SloEvaluator(self.metrics, self.quality, policy,
                                recorder=self.recorder)
        return self.quality

    def attach_watchdog(self, quarantine_dir, **kw):
        """Construct (NOT start) a :class:`raft_tpu.obs.StallWatchdog`
        over this server's dispatch marker, flight recorder and metrics;
        kwargs forward (``stall_timeout_s``, ``poll_interval_s``,
        ``capture_s``...).  Call ``.start()`` on the result, or drive
        ``.check()`` inline in deterministic tests."""
        from ..obs.watchdog import StallWatchdog

        kw.setdefault("recorder", self.recorder)
        return StallWatchdog(self, quarantine_dir, **kw)

    def prometheus_text(self) -> str:
        """Prometheus text exposition for a scrape handler: the serving
        counters/histogram plus live gauges (queue depth/rows, degrade
        level, executable-cache and flight-recorder occupancy) and the
        process-global registry (Pallas gate fallbacks etc.)."""
        with self._cond:
            depth = len(self._pending)
            qrows = sum(r.rows for r in self._pending)
        reg = self.metrics.registry
        reg.gauge("raft_serve_queue_depth",
                  "requests waiting in the queue").set(depth)
        reg.gauge("raft_serve_queue_rows",
                  "query rows waiting in the queue").set(qrows)
        reg.gauge("raft_serve_degrade_level",
                  "current admission degradation level").set(
                      self.admission.level(depth))
        reg.gauge("raft_serve_generation",
                  "serving index generation").set(self._registry.gen_id)
        reg.gauge("raft_serve_index_rows",
                  "rows in the serving generation").set(
                      index_size(self.index))
        cache = self.cache.snapshot()
        reg.gauge("raft_serve_cache_hits", "executable cache hits").set(
            cache.get("hits", 0))
        reg.gauge("raft_serve_cache_compiles",
                  "executable cache compiles").set(cache.get("compiles", 0))
        rec = self.recorder.stats()
        reg.gauge("raft_obs_flight_recorder_spans",
                  "spans retained in the flight recorder").set(
                      rec["retained"])
        return self.metrics.prometheus_text()

    def metrics_snapshot(self) -> dict:
        """Serving metrics + live gauges + compile-cache counters (the
        ``docs/serving_guide.md`` schema).  ``host_pool`` surfaces the
        process staging-pool occupancy/hit-rate (the out-of-core tier's
        zero-alloc contract) and refreshes the
        ``raft_host_pool_{idle_bytes,hits,misses}`` gauges."""
        with self._cond:
            depth = len(self._pending)
            qrows = sum(r.rows for r in self._pending)
        snap = self.metrics.snapshot()
        snap.update({
            "queue_depth": depth,
            "queue_rows": qrows,
            "degrade_level": self.admission.level(depth),
            "cache": self.cache.snapshot(),
            "obs": self.recorder.stats(),
            "quality": (self.quality.stats()
                        if self.quality is not None else None),
            "slo": self.slo.stats() if self.slo is not None else None,
            "host_pool": _host_pool_stats(),
            "server": {"family": self.family, "k": self.k,
                       "ladder": list(self.ladder),
                       "index_rows": index_size(self.index),
                       "generation": self._registry.gen_id,
                       "wal_lsn": (self.durable_store.wal_lsn
                                   if self.durable_store is not None
                                   else None)},
        })
        return snap

    def dump_metrics(self, path=None) -> str:
        """JSON-serialize :meth:`metrics_snapshot` (optionally to a
        file) — the bench harness's ingestion format.  File writes use
        the ``core/serialize`` temp + fsync + atomic-rename discipline:
        a crash mid-dump leaves the previous complete file, never a torn
        one."""
        import json

        text = json.dumps(self.metrics_snapshot(), indent=2, sort_keys=True)
        if path:
            from ..core.serialize import write_text_atomic

            write_text_atomic(path, text + "\n")
        return text
