"""raft_tpu.serve — online query-serving runtime for the ANN index family.

Converts the library's one-shot ``search(queries, k)`` calls into the
inference-stack shape the north star demands (ROADMAP: "serves heavy
traffic from millions of users"): a :class:`SearchServer` wraps any built
index and owns

* a **micro-batcher** that coalesces concurrent ``submit()`` calls into
  padded batches drawn from a configurable **shape-bucket ladder**
  (:mod:`.bucketing`), so ragged traffic always dispatches one of a fixed
  set of shapes — TPU-KNN-style MXU batches, zero recompilation;
* a **shape-bucketed AOT executable cache** (:mod:`.cache`) keyed by
  (index family, bucket, k, dtype, degrade level) using the
  ``jax.jit(...).lower().compile()`` discipline of
  ``tests/test_export_aot.py``, warm-started over the whole ladder at
  server start;
* **deadline-aware admission control** (:mod:`.admission`): a bounded
  queue, per-request deadlines with timeout rejection, and graceful
  degradation — under queue pressure the effort knobs (``n_probes`` /
  ``itopk`` / shortlist width) shrink so overload degrades recall, not
  latency;
* **serving metrics** (:mod:`.metrics`): queue depth, batch-fill ratio,
  p50/p95/p99 latency, timeout/reject counts, compile-cache hits —
  JSON-dumpable for the bench harness (``bench/serve.py``) and annotated
  into profiler timelines via :mod:`raft_tpu.core.tracing`;
* a **generation registry** (:mod:`.registry`): dispatch reads an
  immutable copy-on-write snapshot and ``swap_index()`` publishes a
  replacement atomically — pre-warmed and validated first, so a handoff
  drops zero requests and (same-shaped generations) compiles nothing;
* a **fault-injection chaos harness** (:mod:`.faults`): wedge / slow /
  OOM / failed-swap faults armable per site (or via
  ``RAFT_SERVE_FAULTS``), recovered by deadline-aware retry-with-backoff
  (``ServerConfig.retry``) and transactional swap rollback — every
  failure mode has a deterministic test (``tests/test_serve_lifecycle``).

Served results are bit-identical to a direct index ``search()``: every
index family exposes a uniform ``searcher()`` entry point returning a
``(fn, operands)`` pair whose padded-bucket execution is row-independent,
so padding never perturbs real rows.

>>> import numpy as np
>>> from raft_tpu.serve import SearchServer, ServerConfig
>>> db = np.random.default_rng(0).standard_normal((256, 16)).astype(np.float32)
>>> srv = SearchServer(db, k=3, config=ServerConfig(ladder=(4, 16)))
>>> _ = srv.start()   # warms the ladder, starts the dispatch thread
>>> d, i = srv.search(db[:2])
>>> bool((np.asarray(i)[:, 0] == np.arange(2)).all())
True
>>> srv.stop()
"""

from .admission import (AdmissionController, AdmissionPolicy, Backoff,
                        DeadlineExceeded, QueueFull, RetryPolicy, ServeError)
from .bucketing import DEFAULT_LADDER, bucket_for, normalize_ladder
from .cache import ExecutableCache
from .compaction import CompactionPolicy, CompactionScheduler
from .faults import (CRASH_EXIT_CODE, TRANSIENT_FAULTS, DeviceOOM, FaultError,
                     FaultInjector, FencedError, Partitioned, SwapFailed,
                     WedgedDevice)
from .metrics import ServingMetrics, UnknownCounter
from .registry import Generation, IndexRegistry
from .replication import (EpochFence, EpochToken, LogShipper, QueuePair,
                          ReplicationConfig, SocketListener, SocketTransport,
                          StandbyReplica)
from .searchers import family_of, make_searcher, unwrap_tombstones
from .server import SearchServer, ServerConfig
from .fleet import (FleetDurability, FleetRouter, FleetServer, LocalReplica,
                    ReplicaDead, ShardDurability, make_fleet_searcher,
                    shard_sub_indexes)
from .placement import Assignment, PlacementPlan, plan_placement
from ..obs.watchdog import StallWatchdog

__all__ = [
    "SearchServer",
    "ServerConfig",
    "CompactionPolicy",
    "CompactionScheduler",
    "Backoff",
    "CRASH_EXIT_CODE",
    "ExecutableCache",
    "ServingMetrics",
    "StallWatchdog",
    "UnknownCounter",
    "AdmissionPolicy",
    "AdmissionController",
    "RetryPolicy",
    "ServeError",
    "QueueFull",
    "DeadlineExceeded",
    "FaultError",
    "WedgedDevice",
    "DeviceOOM",
    "SwapFailed",
    "Partitioned",
    "FencedError",
    "TRANSIENT_FAULTS",
    "FaultInjector",
    "EpochFence",
    "EpochToken",
    "LogShipper",
    "QueuePair",
    "ReplicationConfig",
    "SocketListener",
    "SocketTransport",
    "StandbyReplica",
    "Generation",
    "IndexRegistry",
    "DEFAULT_LADDER",
    "bucket_for",
    "normalize_ladder",
    "family_of",
    "make_searcher",
    "unwrap_tombstones",
    "FleetServer",
    "FleetRouter",
    "FleetDurability",
    "ShardDurability",
    "LocalReplica",
    "ReplicaDead",
    "make_fleet_searcher",
    "shard_sub_indexes",
    "Assignment",
    "PlacementPlan",
    "plan_placement",
]
