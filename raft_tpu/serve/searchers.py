"""Family dispatch — map any built index to its uniform ``searcher()``
entry point, with degradation-level effort scaling.

Every index family exposes ``searcher(index, k, params) -> (fn,
operands)`` where ``fn(queries, *operands)`` matches a direct
``search()`` call bit-for-bit and AOT-compiles with ``queries`` as the
only shape-varying input.  This module owns (a) the type→family mapping
and (b) the per-family *effort knob* a degradation level shrinks:

* ``ivf_flat`` / ``ivf_pq`` / ``ivf_rabitq`` — ``n_probes`` (fewer
  lists scanned),
* ``cagra`` — ``itopk_size`` (narrower beam; iterations follow),
* ``brute_force`` fast mode — ``cand`` (shorter shortlist); exact mode
  has no quality knob and degrades to itself.

Scaled knobs are floored so a degraded searcher still returns k valid
results (``n_probes >= 1``, ``itopk >= k``, ``cand >= k``).
"""

from __future__ import annotations

import dataclasses

import jax
import numpy as np

from ..core.errors import expects

__all__ = ["BruteForceSearchParams", "family_of", "make_searcher",
           "index_dim", "index_size", "query_dtype_of",
           "unwrap_tombstones"]


@dataclasses.dataclass(frozen=True)
class BruteForceSearchParams:
    """Search-time knobs for serving a raw (n, d) database with
    :func:`raft_tpu.neighbors.brute_force.knn` semantics (the family has
    no index object, so the params struct lives here)."""

    metric: str = "sqeuclidean"
    mode: str = "exact"          # exact | fast
    tile: int = 8192
    cand: int = 64               # fast-mode shortlist width
    cut: str = "exact"
    refine_precision: str = "highest"


def unwrap_tombstones(index):
    """Split a ``mutation.Tombstoned`` view into ``(index, keep_bitset)``
    — ``(index, None)`` for a plain index.  The serve layer does this at
    every entry point so tombstoned views serve transparently (the mask
    becomes the searcher's shared prefilter operand)."""
    from ..neighbors.mutation import Tombstoned

    if isinstance(index, Tombstoned):
        return index.index, index.keep
    return index, None


def family_of(index) -> str:
    """Index family name for cache keys / metrics labels."""
    from ..neighbors.cagra import CagraIndex
    from ..neighbors.ivf_flat import IvfFlatIndex
    from ..neighbors.ivf_pq import IvfPqIndex
    from ..neighbors.ivf_rabitq import IvfRabitqIndex
    from ..neighbors.ooc import OocIndex

    index, _ = unwrap_tombstones(index)
    if isinstance(index, IvfFlatIndex):
        return "ivf_flat"
    if isinstance(index, IvfPqIndex):
        return "ivf_pq"
    if isinstance(index, IvfRabitqIndex):
        return "ivf_rabitq"
    if isinstance(index, OocIndex):
        return "ooc"
    if isinstance(index, CagraIndex):
        return "cagra"
    if isinstance(index, (jax.Array, np.ndarray)) and index.ndim == 2:
        return "brute_force"
    raise TypeError(f"no serving searcher for {type(index).__name__}; "
                    "expected IvfFlatIndex/IvfPqIndex/IvfRabitqIndex/"
                    "OocIndex/CagraIndex, a mutation.Tombstoned view of "
                    "one, or a 2-D database array")


def index_dim(index) -> int:
    index, _ = unwrap_tombstones(index)
    return int(index.shape[1]) if family_of(index) == "brute_force" \
        else int(index.dim)


def index_size(index) -> int:
    index, _ = unwrap_tombstones(index)
    return int(index.shape[0]) if family_of(index) == "brute_force" \
        else int(index.size)


def query_dtype_of(index):
    """The dtype warm-up should precompile for — the dtype the stored
    vectors expect queries in (requests with another dtype compile their
    own bucket set on first use)."""
    index, _ = unwrap_tombstones(index)
    fam = family_of(index)
    if fam == "brute_force":
        return jax.numpy.asarray(index[:1]).dtype if isinstance(
            index, np.ndarray) else index.dtype
    if fam == "cagra":
        return index.dataset.dtype
    return index.centroids.dtype


def _scaled(value: int, scale: float, floor: int) -> int:
    return max(int(floor), int(round(value * float(scale))))


def make_searcher(index, k: int, params=None, *, effort_scale: float = 1.0,
                  seed: int = 0, filter=None):
    """Build the ``(fn, operands)`` searcher for ``index`` at one
    degradation point.  ``effort_scale`` in (0, 1] multiplies the
    family's effort knob; 1.0 reproduces direct ``search()`` exactly
    (the serve bit-identity contract).

    Only the effort knob is scaled — every other search param passes
    through unchanged.  In particular the IVF families' ``probe_block``
    (blocked probe scan; 0 = auto-tuned) reaches the baked executable
    as given: it changes wall-clock only, never results, so degradation
    ladders keep one blocking choice across all effort levels.

    A ``mutation.Tombstoned`` view is unwrapped here: its keep-mask
    becomes the family searcher's shared ``filter=`` operand (deleted
    ids report as −1/±inf sentinels, never as results), composed with an
    explicit ``filter`` by AND when both are present."""
    expects(0.0 < effort_scale <= 1.0,
            f"effort_scale must be in (0, 1], got {effort_scale}")
    index, keep = unwrap_tombstones(index)
    if keep is not None and filter is not None:
        from ..neighbors.mutation import _combined_keep

        filter = _combined_keep(keep, filter)
    elif keep is not None:
        filter = keep
    fam = family_of(index)
    if fam == "brute_force":
        from ..neighbors import brute_force

        p = params or BruteForceSearchParams()
        cand = _scaled(p.cand, effort_scale, k) if p.mode == "fast" \
            else p.cand
        return brute_force.searcher(
            index, k, metric=p.metric, mode=p.mode, tile=p.tile, cand=cand,
            cut=p.cut, refine_precision=p.refine_precision, filter=filter)
    if fam == "ivf_flat":
        from ..neighbors import ivf_flat

        p = params or ivf_flat.IvfFlatSearchParams()
        if effort_scale < 1.0:
            p = dataclasses.replace(
                p, n_probes=_scaled(min(p.n_probes, index.n_lists),
                                    effort_scale, 1))
        return ivf_flat.searcher(index, k, p, filter=filter)
    if fam == "ivf_pq":
        from ..neighbors import ivf_pq

        p = params or ivf_pq.IvfPqSearchParams()
        if effort_scale < 1.0:
            p = dataclasses.replace(
                p, n_probes=_scaled(min(p.n_probes, index.n_lists),
                                    effort_scale, 1))
        return ivf_pq.searcher(index, k, p, filter=filter)
    if fam == "ivf_rabitq":
        from ..neighbors import ivf_rabitq

        p = params or ivf_rabitq.IvfRabitqSearchParams()
        if effort_scale < 1.0:
            p = dataclasses.replace(
                p, n_probes=_scaled(min(p.n_probes, index.n_lists),
                                    effort_scale, 1))
        return ivf_rabitq.searcher(index, k, p, filter=filter)
    if fam == "ooc":
        from ..neighbors import ooc

        p = params or ooc.OocSearchParams()
        if effort_scale < 1.0:
            p = dataclasses.replace(
                p, n_probes=_scaled(min(p.n_probes, index.n_lists),
                                    effort_scale, 1))
        return ooc.searcher(index, k, p, filter=filter)
    from ..neighbors import cagra

    # resolve 0 = auto itopk/width from the tuned table FIRST — scaling
    # the raw params would multiply the auto sentinel, not the beam
    p = cagra.resolved_search_params(index, k, params)
    if effort_scale < 1.0:
        p = dataclasses.replace(
            p, itopk_size=_scaled(max(p.itopk_size, k), effort_scale, k))
    return cagra.searcher(index, k, p, seed=seed, filter=filter)
