"""Fault injection — deterministic chaos for the serving runtime.

The failure modes the bench rounds actually hit (BENCH_r04/r05: wedged
TPU probes; bench.py's FAKE_WEDGE tiers) become *injectable* here, so
every recovery path in :mod:`.server` has a deterministic test instead
of a bench anecdote:

* ``wedge``  — the accelerator call raises :class:`WedgedDevice`
  (transient: the dispatch retry loop backs off and re-issues);
* ``slow``   — the call completes after an injected delay (exercises
  per-request deadlines and late-completion accounting);
* ``oom``    — raises :class:`DeviceOOM` (transient — a background
  build/extend retries, a dispatch retries after backoff);
* ``fail``   — raises :class:`FaultError` (terminal: a generation swap
  wrapping it surfaces :class:`SwapFailed` and keeps the old
  generation);
* ``crash``  — SIGKILL-equivalent process abort (``os._exit(137)``, no
  atexit, no finally, no flushing) — the durability drill: armed at the
  ``wal_append``/``extend``/``snapshot``/``rename``/``compact`` sites it
  kills a subprocess mid-operation so ``tests/test_durability.py`` can
  prove ``DurableStore.recover`` restores a bit-identical index;
* ``corrupt`` — flips one byte of the file/directory the site passed to
  :meth:`FaultInjector.fire` (torn-write / bit-rot injection for the
  checksum + quarantine paths);
* ``partition`` — raises :class:`Partitioned` at the replication ship
  sites (``ship_send``/``ship_ack``): the message is *dropped*, not
  delivered late, exactly like a network partition.  Healing is
  deterministic: once the armed count is consumed the link carries
  traffic again, and the replication layer's resync (hello + watermark
  catch-up) repairs the gap.

A :class:`FaultInjector` is armed per *site* (serve dispatch:
``"execute"``, ``"swap"``, ``"extend"``; durability, fired by
``neighbors.wal.DurableStore``: ``"wal_append"``, ``"snapshot"``,
``"rename"``, ``"compact"``) with a finite fire count, so tests express
"the first two dispatches wedge, the third succeeds" exactly.  The
server calls :meth:`FaultInjector.fire` at each site; an unarmed
injector is a no-op (and the default), so production pays one dict
lookup per dispatch.

``RAFT_SERVE_FAULTS="site:kind[:times[:delay_ms]],..."`` arms an
injector from the environment — the chaos-smoke hook for
``bench/serve.py`` / ``scripts/tpu_jobs_*.sh``.
"""

from __future__ import annotations

import os
import time
from typing import Optional

from ..core import lockdep
from .admission import ServeError

__all__ = ["FaultError", "WedgedDevice", "DeviceOOM", "SwapFailed",
           "Partitioned", "FencedError", "TRANSIENT_FAULTS",
           "FaultInjector", "CRASH_EXIT_CODE"]


class FaultError(ServeError):
    """An injected (or injected-equivalent) runtime fault."""


class Partitioned(FaultError):
    """The replication link dropped this message (injected network
    partition at a ``ship_send``/``ship_ack`` site).  The sender counts
    the drop and moves on — delivery is repaired by watermark resync,
    never by blocking."""


class FencedError(ServeError):
    """A deposed primary tried to write after a newer epoch was observed
    (``EpochFence.check``).  Terminal for that node's write path: the
    split-brain guard — recover by rejoining as a standby."""


class WedgedDevice(FaultError):
    """The accelerator stopped answering (the BENCH_r04/r05 probe-timeout
    mode).  Transient: retry with backoff."""


class DeviceOOM(FaultError):
    """Device allocation failed (e.g. during a background extend).
    Transient: retry — the failed attempt's buffers are freed."""


class SwapFailed(ServeError):
    """A generation swap did not happen; the previous generation is still
    serving.  Raised by ``SearchServer.swap_index`` around validation or
    build failures — ``__cause__`` holds the original error."""


#: Fault types the dispatch/build retry loops may re-attempt.  Anything
#: else propagates immediately (retrying a logic error just burns the
#: deadline).
TRANSIENT_FAULTS = (WedgedDevice, DeviceOOM)

_KINDS = ("wedge", "slow", "oom", "fail", "crash", "corrupt", "partition")
_SITES = ("execute", "swap", "extend",
          "wal_append", "snapshot", "rename", "compact",
          "ship_send", "ship_ack")

#: the crash exit code (SIGKILL convention) the subprocess driver asserts
CRASH_EXIT_CODE = 137


def _corrupt_file(path: str) -> None:
    """Flip one byte in the middle of ``path`` (for a directory: its
    largest file — the slab, where a flip cannot hide).  Skips silently
    when the target is missing/empty: the fault fired too early to have
    anything to damage, which the test's fired-count assertion surfaces."""
    if path is None or not os.path.exists(path):
        return
    if os.path.isdir(path):
        files = [os.path.join(path, n) for n in os.listdir(path)]
        files = [f for f in files if os.path.isfile(f)]
        if not files:
            return
        path = max(files, key=os.path.getsize)
    size = os.path.getsize(path)
    if size == 0:
        return
    with open(path, "r+b") as f:
        f.seek(size // 2)
        byte = f.read(1)
        f.seek(size // 2)
        f.write(bytes([byte[0] ^ 0xFF]))


class FaultInjector:
    """Armable fault source, one per server; thread-safe.

    ``arm(site, kind, times=n, delay_ms=d)`` queues ``n`` firings of
    ``kind`` at ``site``; each :meth:`fire` consumes one.  ``fired``
    counts consumed faults per (site, kind) — tests assert recovery
    *happened* (e.g. 2 wedges fired AND the request completed), not just
    absence of a crash."""

    def __init__(self, sleep=time.sleep) -> None:
        self._lock = lockdep.lock("FaultInjector._lock")
        self._armed: dict = {}     # guarded_by: _lock  site -> [(kind, delay_ms)]
        self.fired: dict = {}      # guarded_by: _lock  (site, kind) -> count
        self._sleep = sleep

    @classmethod
    def from_env(cls, spec: Optional[str] = None, *,
                 sleep=time.sleep) -> "FaultInjector":
        """Build from ``RAFT_SERVE_FAULTS`` (or an explicit spec string):
        ``"execute:wedge:2,swap:fail"`` arms two wedges on dispatch and
        one failed swap.  Empty/missing spec → unarmed injector.
        Malformed entries fail loudly (``core.errors.expects``) — a chaos
        drill that silently arms nothing would report a vacuous pass."""
        from ..core.errors import expects

        inj = cls(sleep=sleep)
        spec = os.environ.get("RAFT_SERVE_FAULTS", "") if spec is None \
            else spec
        for part in filter(None, (p.strip() for p in spec.split(","))):
            bits = part.split(":")
            expects(2 <= len(bits) <= 4,
                    f"malformed fault spec {part!r} — want "
                    "site:kind[:times[:delay_ms]]")
            site, kind = bits[0].strip(), bits[1].strip()
            try:
                times = int(bits[2]) if len(bits) > 2 else 1
                delay = float(bits[3]) if len(bits) > 3 else 0.0
            except ValueError:
                from ..core.errors import RaftError

                raise RaftError(
                    f"malformed fault spec {part!r}: times must be an int "
                    "and delay_ms a float") from None
            inj.arm(site, kind, times=times, delay_ms=delay)
        return inj

    def arm(self, site: str, kind: str, *, times: int = 1,
            delay_ms: float = 0.0) -> "FaultInjector":
        from ..core.errors import expects

        expects(site in _SITES, f"unknown fault site {site!r} ({_SITES})")
        expects(kind in _KINDS, f"unknown fault kind {kind!r} ({_KINDS})")
        expects(times >= 1, "times must be >= 1")
        with self._lock:
            self._armed.setdefault(site, []).extend(
                [(kind, float(delay_ms))] * int(times))
        return self

    def disarm(self, site: Optional[str] = None) -> None:
        with self._lock:
            if site is None:
                self._armed.clear()
            else:
                self._armed.pop(site, None)

    def pending(self, site: str) -> int:
        with self._lock:
            return len(self._armed.get(site, ()))

    def fire(self, site: str, *, path: Optional[str] = None) -> None:
        """Consume and enact the next armed fault at ``site`` (no-op when
        unarmed).  ``slow`` sleeps through the injected ``sleep`` (a fake
        clock's sleep in tests); ``crash`` aborts the process like
        SIGKILL (``os._exit`` — nothing flushes, nothing unwinds);
        ``corrupt`` byte-flips ``path`` (the artifact the firing site is
        about to publish/append) and returns; the rest raise."""
        with self._lock:
            queue = self._armed.get(site)
            if not queue:
                return
            kind, delay_ms = queue.pop(0)
            key = (site, kind)
            self.fired[key] = self.fired.get(key, 0) + 1
        if kind == "slow":
            self._sleep(delay_ms / 1e3)
            return
        if kind == "crash":
            os._exit(CRASH_EXIT_CODE)
        if kind == "corrupt":
            _corrupt_file(path)
            return
        if kind == "wedge":
            raise WedgedDevice(f"injected wedge at {site!r}")
        if kind == "oom":
            raise DeviceOOM(f"injected OOM at {site!r}")
        if kind == "partition":
            raise Partitioned(f"injected partition at {site!r}")
        raise FaultError(f"injected failure at {site!r}")

    def fired_count(self, site: str, kind: Optional[str] = None) -> int:
        with self._lock:
            return sum(n for (s, kd), n in self.fired.items()
                       if s == site and (kind is None or kd == kind))
