"""Fleet placement policy — where shard primaries and their standbys go.

Pure host logic (no jax): the fleet's durability story is only as good
as its placement — a standby on its primary's host dies with it.  The
planner here implements the anti-affinity rule every replicated store
uses (HDFS rack-awareness, Cassandra NetworkTopologyStrategy): a shard's
follower NEVER lands on the host serving that shard's primary, and load
spreads round-robin so no host carries a disproportionate share of
either role.

:class:`PlacementPlan` is a frozen value object — the fleet bootstrap
computes it once, tests assert on it directly, and the runbook prints it
(``describe()``) so an operator can audit the topology before traffic.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Sequence, Tuple

from ..core.errors import expects

__all__ = ["Assignment", "PlacementPlan", "plan_placement"]


@dataclasses.dataclass(frozen=True)
class Assignment:
    """One shard's durability placement: the host that owns the primary
    ``DurableStore`` and the hosts holding its warm standbys."""

    shard: int
    primary: str
    standbys: Tuple[str, ...]


@dataclasses.dataclass(frozen=True)
class PlacementPlan:
    """The fleet's full shard→host assignment."""

    hosts: Tuple[str, ...]
    assignments: Tuple[Assignment, ...]

    def primaries_on(self, host: str) -> List[int]:
        return [a.shard for a in self.assignments if a.primary == host]

    def standbys_on(self, host: str) -> List[int]:
        return [a.shard for a in self.assignments if host in a.standbys]

    def validate(self) -> None:
        """Re-check the anti-affinity invariant (tests + startup gate)."""
        for a in self.assignments:
            expects(a.primary not in a.standbys,
                    f"shard {a.shard}: standby co-located with its "
                    f"primary on {a.primary!r}")
            expects(len(set(a.standbys)) == len(a.standbys),
                    f"shard {a.shard}: duplicate standby host")

    def describe(self) -> str:
        """Operator-facing table (the runbook prints this before the
        fleet takes traffic)."""
        lines = [f"{len(self.assignments)} shards over "
                 f"{len(self.hosts)} hosts"]
        for a in self.assignments:
            feet = ", ".join(a.standbys) if a.standbys else "-"
            lines.append(f"  shard {a.shard}: primary={a.primary} "
                         f"standbys=[{feet}]")
        return "\n".join(lines)


def plan_placement(n_shards: int, hosts: Sequence[str], *,
                   n_standbys: int = 1) -> PlacementPlan:
    """Assign each shard a primary host and ``n_standbys`` follower
    hosts under anti-affinity.

    Primaries round-robin over ``hosts`` (shard *i* → host ``i % H``);
    each standby then takes the least-loaded host that is neither the
    shard's primary nor one of its earlier standbys — ties break by host
    order, so the plan is deterministic.  Requires
    ``n_standbys < len(hosts)``: with H hosts at most H−1 distinct
    non-primary homes exist per shard.
    """
    hosts = tuple(str(h) for h in hosts)
    expects(len(hosts) >= 1, "placement needs at least one host")
    expects(len(set(hosts)) == len(hosts), "duplicate host names")
    expects(n_shards >= 1, "placement needs at least one shard")
    expects(0 <= n_standbys < max(len(hosts), 1) or n_standbys == 0,
            f"{n_standbys} standbys need at least {n_standbys + 1} "
            f"distinct hosts, have {len(hosts)}")
    load: Dict[str, int] = {h: 0 for h in hosts}  # standby count per host
    assignments: List[Assignment] = []
    for s in range(int(n_shards)):
        primary = hosts[s % len(hosts)]
        standbys: List[str] = []
        for _ in range(int(n_standbys)):
            candidates = [h for h in hosts
                          if h != primary and h not in standbys]
            # least standby load first, then host order: deterministic
            chosen = min(candidates, key=lambda h: (load[h],
                                                    hosts.index(h)))
            load[chosen] += 1
            standbys.append(chosen)
        assignments.append(Assignment(s, primary, tuple(standbys)))
    plan = PlacementPlan(hosts, tuple(assignments))
    plan.validate()
    return plan
