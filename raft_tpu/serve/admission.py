"""Admission control — bounded queue, per-request deadlines, graceful
degradation.

Overload policy (FusionANNS-style separation of admission from
accelerator-side search): a full queue rejects at ``submit()``
(:class:`QueueFull`, the client's backpressure signal); a request whose
deadline passes while still queued is rejected at dequeue
(:class:`DeadlineExceeded`) instead of wasting a dispatch on an answer
nobody is waiting for; and sustained queue pressure activates
*degradation levels* that shrink the search-effort knobs
(``n_probes`` / ``itopk`` / shortlist width, :mod:`.searchers`) so
overload costs recall instead of latency.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

from ..core.errors import RaftError, expects

__all__ = ["ServeError", "QueueFull", "DeadlineExceeded",
           "AdmissionPolicy", "AdmissionController", "RetryPolicy",
           "Backoff"]


class ServeError(RaftError):
    """Base class for serving-runtime errors."""


class QueueFull(ServeError):
    """Request rejected at submit: the bounded queue is at capacity."""


class DeadlineExceeded(ServeError):
    """Request rejected: its deadline passed before dispatch."""


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Backoff schedule for *transient* faults (``faults.TRANSIENT_FAULTS``:
    wedged device, device OOM).  Retries are deadline-aware — the server
    stops retrying a batch once the next backoff would outlive the
    earliest deadline in it, rejecting instead of burning the budget.

    ``jitter="decorrelated"`` (default) draws each sleep from
    ``uniform(backoff_ms, 3 × previous)`` clamped to
    ``[backoff_ms, max_backoff_ms]`` — the AWS decorrelated-jitter
    schedule, so a fleet of replicas retrying one shared fault spreads
    out instead of synchronizing into retry storms.  ``jitter="none"``
    keeps the deterministic exponential (``backoff_s``), for tests that
    pin exact sleeps.  ``max_backoff_ms`` is a HARD cap either way."""

    max_retries: int = 2
    backoff_ms: float = 5.0
    multiplier: float = 2.0
    max_backoff_ms: float = 100.0
    jitter: str = "decorrelated"

    def __post_init__(self):
        expects(self.max_retries >= 0, "max_retries must be >= 0")
        expects(self.backoff_ms >= 0, "backoff_ms must be >= 0")
        expects(self.multiplier >= 1.0, "multiplier must be >= 1.0")
        expects(self.max_backoff_ms >= self.backoff_ms,
                "max_backoff_ms must be >= backoff_ms")
        expects(self.jitter in ("none", "decorrelated"),
                f"jitter must be 'none' or 'decorrelated', "
                f"got {self.jitter!r}")

    def backoff_s(self, attempt: int) -> float:
        """Jitter-free sleep before retry ``attempt`` (0-based), seconds —
        the deterministic envelope :class:`Backoff` jitters inside."""
        ms = self.backoff_ms * (self.multiplier ** max(0, int(attempt)))
        return min(ms, self.max_backoff_ms) / 1e3

    def start(self, rng=None) -> "Backoff":
        """Fresh per-retry-sequence backoff state (one per faulted batch/
        build).  ``rng``: a ``random.Random`` for deterministic tests."""
        return Backoff(self, rng)


class Backoff:
    """Stateful backoff iterator for ONE retry sequence.

    Every sleep lies in ``[backoff_ms, max_backoff_ms]`` (the jitter-
    bounds contract ``tests/test_serve_lifecycle.py`` pins); under
    decorrelated jitter consecutive sleeps may shrink — that is the
    point, replicas desynchronize."""

    def __init__(self, policy: RetryPolicy, rng=None) -> None:
        import random

        self.policy = policy
        self._rng = rng if rng is not None else random.Random()
        self._attempt = 0
        self._prev_ms = policy.backoff_ms

    def next_s(self) -> float:
        """The next sleep, in seconds (caller enforces ``max_retries``
        and the deadline-aware refusal)."""
        p = self.policy
        if p.jitter == "none":
            ms = min(p.backoff_ms * (p.multiplier ** self._attempt),
                     p.max_backoff_ms)
        else:
            hi = max(p.backoff_ms, self._prev_ms * 3.0)
            ms = min(p.max_backoff_ms,
                     self._rng.uniform(p.backoff_ms, hi))
        self._attempt += 1
        self._prev_ms = ms
        return ms / 1e3


@dataclasses.dataclass(frozen=True)
class AdmissionPolicy:
    """Knobs for the bounded queue and the pressure→degradation map.

    ``degrade_queue_fractions`` are occupancy thresholds (of
    ``max_queue``): depth >= fraction_i activates degradation level i+1.
    The default (0.5, 0.8) gives three levels: full quality below half
    occupancy, level 1 above it, level 2 near saturation.
    """

    max_queue: int = 1024
    default_deadline_ms: float = 1000.0
    degrade_queue_fractions: Tuple[float, ...] = (0.5, 0.8)

    def __post_init__(self):
        expects(self.max_queue >= 1, "max_queue must be >= 1")
        expects(self.default_deadline_ms > 0,
                "default_deadline_ms must be > 0")
        expects(all(0.0 < f <= 1.0 for f in self.degrade_queue_fractions),
                "degrade_queue_fractions must lie in (0, 1]")
        expects(tuple(sorted(self.degrade_queue_fractions))
                == tuple(self.degrade_queue_fractions),
                "degrade_queue_fractions must be sorted ascending")


class AdmissionController:
    """Pure decision logic (no clock, no locks — the server owns both)."""

    def __init__(self, policy: AdmissionPolicy) -> None:
        self.policy = policy

    def admit(self, depth: int) -> bool:
        """May a new request enter a queue currently at ``depth``?"""
        return depth < self.policy.max_queue

    def level(self, depth: int) -> int:
        """Degradation level for the current queue depth (0 = full
        quality)."""
        lvl = 0
        for frac in self.policy.degrade_queue_fractions:
            if depth >= frac * self.policy.max_queue:
                lvl += 1
        return lvl

    def guarded_level(self, depth: int, guard=None,
                      max_level: Optional[int] = None) -> int:
        """:meth:`level`, clamped to ``max_level`` and then passed
        through ``guard`` (an int -> int callable — e.g.
        :meth:`raft_tpu.obs.slo.SloEvaluator.quality_guard` via the
        server — that may only *lower* the level; a guard asking for a
        deeper level than the ladder requested is a bug)."""
        lvl = self.level(depth)
        if max_level is not None:
            lvl = min(lvl, int(max_level))
        if guard is not None:
            guarded = int(guard(lvl))
            expects(0 <= guarded <= lvl,
                    f"quality guard returned level {guarded}, outside "
                    f"[0, {lvl}] — guards may only lower the level")
            lvl = guarded
        return lvl

    def deadline(self, now: float, deadline_ms=None) -> float:
        """Absolute deadline (server-clock seconds) for a request
        submitted at ``now``."""
        ms = self.policy.default_deadline_ms if deadline_ms is None \
            else float(deadline_ms)
        expects(ms > 0, "deadline_ms must be > 0")
        return now + ms / 1e3
