"""Pod-scale serving fleet: sharded fan-out, replica routing, durability.

Three layers compose here (ISSUE 16 / ROADMAP "pod-scale serving"):

1. **Sharded query fan-out** — :func:`make_fleet_searcher` builds the
   uniform ``(fn, operands)`` serving searcher whose executable is a
   ``shard_map`` over a device mesh: every shard scans ITS slice of the
   index through the same :mod:`~raft_tpu.ops.blocked_scan` core the
   single-device searchers use, folds a local top-k, and one
   ``all_gather`` + ranked ``select_k`` finishes the merge.  The result
   is **bit-identical** to the single-device searcher — values AND ids —
   because per-candidate scores never depend on slab partitioning
   (``slab_dots`` pins the block axis as a batch dim) and the global
   top-k of a union of per-shard top-ks equals the top-k of all
   candidates.  ``tests/test_fleet.py`` pins this across mesh widths.

2. **Replica groups + routing** — :class:`FleetServer` runs N
   :class:`_FleetReplicaServer` replicas (each a full
   :class:`~raft_tpu.serve.server.SearchServer`: micro-batching,
   deadline admission, per-replica degradation ladder + recall guard)
   behind a :class:`FleetRouter` that places each request on the
   least-loaded live replica, spills on ``QueueFull``, and sheds load
   from dead replicas to survivors within the request deadline.

3. **Fleet durability** — :meth:`FleetServer.attach_durability` slices
   the index into per-shard sub-indexes, gives each shard a
   :class:`~raft_tpu.neighbors.wal.DurableStore` + WAL and anti-affinity
   standbys (:mod:`.placement` — a shard's follower never lands on its
   primary's host), ships the log via the multi-follower
   :class:`~raft_tpu.serve.replication.LogShipper`, and promotes on
   lease expiry through the same
   :class:`~raft_tpu.serve.replication.EpochFence` tokens PR 15
   introduced.

Startup refuses to serve over a broken collective:
:func:`~raft_tpu.comms.bootstrap.verify_comms` runs the
:mod:`~raft_tpu.comms.selftest` battery before the first replica warms.
"""

from __future__ import annotations

import dataclasses
import os
import time
from functools import lru_cache
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..core import lockdep
from ..core.compat import shard_map
from ..core.errors import expects
from ..distance.pairwise import sq_l2
from ..matrix.select_k import select_k
from ..neighbors import brute_force as _bf
from ..neighbors import ivf_flat as _ivf
from ..neighbors import ivf_rabitq as _irq
from ..neighbors._packing import (as_keep_mask, blocked_probe_plan,
                                  check_filter_covers_ids, keep_lookup,
                                  resolve_probe_block,
                                  sentinel_filtered_ids)
from ..neighbors.wal import DurableStore
from ..obs import metrics as obs_metrics
from ..obs.prometheus import render, render_labeled
from ..ops import blocked_scan as _scan
from .admission import QueueFull, ServeError
from .placement import Assignment, PlacementPlan, plan_placement
from .replication import (LogShipper, QueuePair, ReplicationConfig,
                          StandbyReplica)
from .searchers import (BruteForceSearchParams, _scaled, family_of,
                        unwrap_tombstones)
from .server import SearchServer, ServerConfig

__all__ = ["make_fleet_searcher", "FleetServer", "FleetRouter",
           "LocalReplica", "ReplicaDead", "FleetDurability",
           "ShardDurability", "shard_sub_indexes"]


class ReplicaDead(ServeError):
    """The targeted replica is gone (process kill / transport closed);
    the router retries survivors within the deadline."""


# ---------------------------------------------------------------------------
# Sharded fan-out programs (one cached shard_map per static config)
# ---------------------------------------------------------------------------
#
# House rules for bit-identity with the single-device searchers:
#
# * per-candidate scores go through the SAME blocked_scan primitives
#   (slab_dots pins the block axis as batch dims, so a candidate's value
#   never depends on which slab/shard it was scored in);
# * non-owned gathers are CLIPPED into the local slab and masked invalid
#   (+inf) — never clamp-and-count, which would double-score the edge
#   lists of a shard;
# * the merge is one all_gather of the per-shard unsorted top-k carries
#   plus ONE ranked select_k — exactly the single searcher's ranked
#   exit over the same candidate multiset;
# * metric exit transforms (euclidean sqrt, inner-product sign) happen
#   once, after the merge, as in the single-device ``_search_impl``s.


@lru_cache(maxsize=32)
def _brute_fleet_program(mesh: Mesh, axis: str, k: int, metric: str,
                         tile: int, per: int):
    """shard_map'd brute-force fan-out: rows split contiguously, local
    exact scan via ``_knn_impl``, ids globalized, merged ranked."""

    def local(q, ysh, msh):
        shard = jax.lax.axis_index(axis)
        bv, bi = _bf._knn_impl(q, ysh, k, metric, tile, msh)
        if metric == "inner_product":
            bv = -bv                       # back to min-selectable
        gi = bi + shard * per              # local row -> global row id
        av = jax.lax.all_gather(bv, axis, tiled=False)   # [S, nq, k]
        ai = jax.lax.all_gather(gi, axis, tiled=False)
        av = jnp.moveaxis(av, 0, 1).reshape(q.shape[0], -1)
        ai = jnp.moveaxis(ai, 0, 1).reshape(q.shape[0], -1)
        dv, di = _scan.ranked_finish(av, ai, k)
        if metric == "inner_product":
            dv = -dv
        return dv, di

    return shard_map(local, mesh=mesh,
                     in_specs=(P(), P(axis), P(axis)),
                     out_specs=(P(), P()), check_vma=False)


@lru_cache(maxsize=32)
def _ivf_flat_fleet_program(mesh: Mesh, axis: str, k: int, n_probes: int,
                            metric: str, probe_block: int, lp: int,
                            has_keep: bool):
    """shard_map'd IVF-Flat fan-out: replicated (padded) centroid table
    ranks the SAME global probe list everywhere; each shard scans only
    the probed lists it owns (owned-mask, not clamp-and-count) and the
    merge is one all_gather + ranked finish."""

    def local(q, cen, data, ids, counts, norms, *rest):
        keep = rest[0] if has_keep else None
        nq = q.shape[0]
        cap = data.shape[1]
        qf = q.astype(jnp.float32)
        qn = _scan.row_sq_norms(qf)
        cd = sq_l2(q, cen)                       # [nq, L_pad] replicated
        _, probes = jax.lax.top_k(-cd, n_probes)  # pads rank last
        shard = jax.lax.axis_index(axis)
        lo = shard * lp
        lists_xs, pvalid = blocked_probe_plan(probes, probe_block)

        def score(inp):
            lists, pv = inp                       # GLOBAL lists [nq, B]
            ll = jnp.clip(lists - lo, 0, lp - 1)  # local slab rows
            owned = (lists >= lo) & (lists < lo + lp)
            bcap = lists.shape[1] * cap
            vecs = data[ll]
            vids = ids[ll].reshape(nq, bcap)
            valid = (jnp.arange(cap)[None, None, :]
                     < counts[ll][:, :, None]).reshape(nq, bcap)
            valid = valid & (vids >= 0) & jnp.repeat(pv, cap)[None, :]
            valid = valid & jnp.repeat(owned, cap, axis=1)
            if keep is not None:
                valid = valid & keep_lookup(keep, vids)
            dots = _scan.slab_dots(vecs, q).reshape(nq, -1)
            if metric == "inner_product":
                dist = -dots
            else:
                dist = norms[ll].reshape(nq, dots.shape[1]) - 2.0 * dots \
                    + qn[:, None]
                dist = jnp.maximum(dist, 0.0)
            return jnp.where(valid, dist, jnp.inf), vids

        def step(carry, inp):
            bv, bi = carry
            dist, vids = score(inp)
            return _scan.fold_topk(bv, bi, dist, vids, k,
                                   sorted=False), None

        (bv, bi), _ = jax.lax.scan(step, _scan.topk_carry(nq, k),
                                   (lists_xs, pvalid))
        av = jax.lax.all_gather(bv, axis, tiled=False)
        ai = jax.lax.all_gather(bi, axis, tiled=False)
        av = jnp.moveaxis(av, 0, 1).reshape(nq, -1)
        ai = jnp.moveaxis(ai, 0, 1).reshape(nq, -1)
        dv, di = _scan.ranked_finish(av, ai, k)
        if metric == "euclidean":
            dv = jnp.sqrt(jnp.maximum(dv, 0.0))
        elif metric == "inner_product":
            dv = -dv
        return dv, di

    specs = [P(), P()] + [P(axis)] * 4
    if has_keep:
        specs.append(P())                         # keep masks GLOBAL ids
    return shard_map(local, mesh=mesh, in_specs=tuple(specs),
                     out_specs=(P(), P()), check_vma=False)


@lru_cache(maxsize=32)
def _rabitq_fleet_program(mesh: Mesh, axis: str, k: int, n_probes: int,
                          rerank_k: int, metric: str, probe_block: int,
                          lp: int, has_keep: bool):
    """shard_map'd IVF-RaBitQ fan-out.  The estimator scan is local
    (owned lists only); the GLOBAL ``rerank_k`` survivor set is selected
    identically on every shard from the all-gathered estimator carries,
    each shard exact-rescores the survivors it owns (flat-slab pointers
    stay local — equal slab shapes make foreign pointers in-range
    garbage under the owner mask), and a ``pmin`` assembles the exact
    distances before the single ranked finish.  This mirrors the
    single-device estimate→rerank contract exactly: same survivor set,
    same rescore arithmetic (norm-free brute order), same final
    selection."""

    def local(q, cen, rot, codes, sabs, res_norms, code_cdots, data, ids,
              counts, *rest):
        keep = rest[0] if has_keep else None
        nq = q.shape[0]
        cap = codes.shape[1]
        qf = q.astype(jnp.float32)
        qn = _scan.row_sq_norms(qf)
        cd = sq_l2(q, cen)
        _, probes = jax.lax.top_k(-cd, n_probes)
        shard = jax.lax.axis_index(axis)
        lo = shard * lp
        lists_xs, pvalid = blocked_probe_plan(probes, probe_block)

        # hoisted query prep — identical on every shard (replicated rot)
        qrot = jnp.einsum("qd,ed->qe", qf, rot,
                          precision=jax.lax.Precision.HIGHEST)
        delta = jnp.max(jnp.abs(qrot), axis=1) / 127.0
        delta = jnp.where(delta > 0.0, delta, 1.0)
        q8 = jnp.round(qrot / delta[:, None]).astype(jnp.int8)
        qc = (jnp.einsum("qd,ld->ql", qf, cen.astype(jnp.float32),
                         precision=jax.lax.Precision.HIGHEST)
              if metric == "inner_product" else None)

        def score(inp):
            lists, pv = inp
            ll = jnp.clip(lists - lo, 0, lp - 1)
            owned = (lists >= lo) & (lists < lo + lp)
            bcap = lists.shape[1] * cap
            sq = _scan.slab_dots(codes[ll], q8,
                                 packed_sign=True).reshape(nq, bcap)
            sa = sabs[ll].reshape(nq, bcap)
            rn2 = res_norms[ll].reshape(nq, bcap)
            vids = ids[ll].reshape(nq, bcap)
            g = jnp.where(sa > 0.0, rn2 / sa, 0.0)
            sqf = delta[:, None] * sq
            if metric == "inner_product":
                qcl = jnp.repeat(jnp.take_along_axis(qc, lists, axis=1),
                                 cap, axis=1)
                est = -(qcl + g * sqf)
            else:
                cs = code_cdots[ll].reshape(nq, bcap)
                cdl = jnp.repeat(jnp.take_along_axis(cd, lists, axis=1),
                                 cap, axis=1)
                est = jnp.maximum(cdl + rn2 - 2.0 * g * (sqf - cs), 0.0)
            valid = (jnp.arange(cap)[None, None, :]
                     < counts[ll][:, :, None]).reshape(nq, bcap)
            valid = valid & (vids >= 0) & jnp.repeat(pv, cap)[None, :]
            valid = valid & jnp.repeat(owned, cap, axis=1)
            if keep is not None:
                valid = valid & keep_lookup(keep, vids)
            ptr = _scan.list_slab_ptr(ll, cap)    # LOCAL flat pointers
            return jnp.where(valid, est, jnp.inf), vids, ptr

        def step(carry, inp):
            bv, bi, bp = carry
            est, vids, ptr = score(inp)
            mv, mi, (mp,) = _scan.fold_topk_payload(
                bv, bi, (bp,), est, vids, (ptr,), rerank_k)
            return (mv, mi, mp), None

        bv0, bi0 = _scan.topk_carry(nq, rerank_k)
        bp0 = jnp.zeros((nq, rerank_k), jnp.int32)
        (bv, bi, bp), _ = jax.lax.scan(step, (bv0, bi0, bp0),
                                       (lists_xs, pvalid))

        # global survivor selection — replicated input, so every shard
        # computes the IDENTICAL (sv, spos) and agrees on ownership
        av = jnp.moveaxis(jax.lax.all_gather(bv, axis, tiled=False),
                          0, 1).reshape(nq, -1)
        ai = jnp.moveaxis(jax.lax.all_gather(bi, axis, tiled=False),
                          0, 1).reshape(nq, -1)
        ap = jnp.moveaxis(jax.lax.all_gather(bp, axis, tiled=False),
                          0, 1).reshape(nq, -1)
        pos = jnp.broadcast_to(jnp.arange(av.shape[1]), av.shape)
        sv, spos = select_k(av, rerank_k, in_idx=pos, select_min=True,
                            sorted=False)
        sids = jnp.take_along_axis(ai, spos, axis=1)
        sptr = jnp.take_along_axis(ap, spos, axis=1)
        sowner = spos // rerank_k
        rescore = _scan.l2_rescorer(data, None, q, qn, metric)
        dist = rescore(sptr, sids)
        mine = (sowner == shard) & jnp.isfinite(sv) & (sids >= 0)
        dist = jnp.where(mine, dist, jnp.inf)
        dist = jax.lax.pmin(dist, axis)           # owner's exact value
        dv, di = _scan.ranked_finish(dist, sids, k)
        if metric == "euclidean":
            dv = jnp.sqrt(jnp.maximum(dv, 0.0))
        elif metric == "inner_product":
            dv = -dv
        return dv, di

    specs = [P(), P(), P()] + [P(axis)] * 7
    if has_keep:
        specs.append(P())
    return shard_map(local, mesh=mesh, in_specs=tuple(specs),
                     out_specs=(P(), P()), check_vma=False)


# ---------------------------------------------------------------------------
# make_fleet_searcher — the sharded analog of searchers.make_searcher
# ---------------------------------------------------------------------------


def make_fleet_searcher(index, k: int, params=None, *, mesh: Mesh,
                        axis: str = "shard", effort_scale: float = 1.0,
                        seed: int = 0, filter=None, slices=None):
    """Build the sharded ``(fn, operands)`` serving searcher for
    ``index`` over ``mesh[axis]``.

    Same contract as :func:`.searchers.make_searcher` — bit-identical to
    the single-device searcher at ``effort_scale=1.0`` (values AND ids),
    one shape-varying input (queries, replicated), index state riding as
    operands (sharded/replicated ``NamedSharding``-committed arrays, so
    the AOT executables record matching input shardings).  A
    ``mutation.Tombstoned`` view unwraps to the shared prefilter, ANDed
    with an explicit ``filter``.

    ``slices``: pre-built ``fleet_slices`` for this exact index view
    (the replica server caches them so the degradation ladder's levels
    share device slabs instead of re-slicing per level).

    Fleet fan-out always dispatches the bit-exact ``"xla"`` blocked
    scan; ``brute_force`` ``mode="fast"`` is rejected — its approximate
    shortlist cannot be bit-pinned across shard boundaries.
    ``seed`` is accepted for signature parity (no stochastic family is
    fleet-enabled yet)."""
    del seed
    expects(0.0 < effort_scale <= 1.0,
            f"effort_scale must be in (0, 1], got {effort_scale}")
    expects(axis in mesh.axis_names, f"axis {axis!r} not in mesh")
    index, keep = unwrap_tombstones(index)
    if keep is not None and filter is not None:
        from ..neighbors.mutation import _combined_keep

        filter = _combined_keep(keep, filter)
    elif keep is not None:
        filter = keep
    fam = family_of(index)
    filtered = filter is not None

    if fam == "brute_force":
        p = params or BruteForceSearchParams()
        expects(p.mode == "exact",
                "fleet fan-out serves brute_force exact mode only — the "
                "fast shortlist is approximate and cannot be bit-pinned "
                "across shard boundaries")
        sl = slices if slices is not None else _bf.fleet_slices(
            index, mesh, axis=axis, filter=filter)
        t = int(min(p.tile, max(sl.per, 1)))
        prog = _brute_fleet_program(mesh, axis, int(k), p.metric, t,
                                    sl.per)
        if filtered:
            def fn(q, y, m):
                dv, di = prog(q, y, m)
                return dv, sentinel_filtered_ids(dv, di)
            return fn, (sl.data, sl.mask)
        return prog, (sl.data, sl.mask)

    rep = NamedSharding(mesh, P())
    if fam == "ivf_flat":
        p = params or _ivf.IvfFlatSearchParams()
        if effort_scale < 1.0:
            p = dataclasses.replace(
                p, n_probes=_scaled(min(p.n_probes, index.n_lists),
                                    effort_scale, 1))
        keep_arr = as_keep_mask(filter)
        if keep_arr is not None:
            expects(keep_arr.ndim == 1,
                    "fleet filters are shared bitsets (1-D)")
            check_filter_covers_ids(keep_arr, index.ids)
        sl = slices if slices is not None else _ivf.fleet_slices(
            index, mesh, axis=axis)
        n_probes = int(min(p.n_probes, index.n_lists))
        probe_block = resolve_probe_block(p.probe_block, n_probes,
                                          index.list_cap, "ivf_flat")
        prog = _ivf_flat_fleet_program(mesh, axis, int(k), n_probes,
                                       index.metric, probe_block,
                                       sl.lists_per, keep_arr is not None)
        ops = (sl.centroids, sl.data, sl.ids, sl.counts, sl.norms)
        if keep_arr is not None:
            kp = jax.device_put(keep_arr, rep)

            def fn(q, *operands):
                dv, di = prog(q, *operands)
                return dv, sentinel_filtered_ids(dv, di)
            return fn, ops + (kp,)
        return prog, ops

    if fam == "ivf_rabitq":
        p = params or _irq.IvfRabitqSearchParams()
        if effort_scale < 1.0:
            p = dataclasses.replace(
                p, n_probes=_scaled(min(p.n_probes, index.n_lists),
                                    effort_scale, 1))
        keep_arr = as_keep_mask(filter)
        if keep_arr is not None:
            expects(keep_arr.ndim == 1,
                    "fleet filters are shared bitsets (1-D)")
            check_filter_covers_ids(keep_arr, index.ids)
        # statics resolve on the UNSHARDED index — same n_probes /
        # rerank_k the single-device searcher would pick
        n_probes, probe_block, rerank_k, _ = _irq._resolved_static(
            index, k, p)
        sl = slices if slices is not None else _irq.fleet_slices(
            index, mesh, axis=axis)
        prog = _rabitq_fleet_program(mesh, axis, int(k), n_probes,
                                     rerank_k, index.metric, probe_block,
                                     sl.lists_per, keep_arr is not None)
        ops = (sl.centroids, sl.rotation, sl.codes, sl.sabs, sl.res_norms,
               sl.code_cdots, sl.data, sl.ids, sl.counts)
        if keep_arr is not None:
            kp = jax.device_put(keep_arr, rep)

            def fn(q, *operands):
                dv, di = prog(q, *operands)
                return dv, sentinel_filtered_ids(dv, di)
            return fn, ops + (kp,)
        return prog, ops

    raise NotImplementedError(
        f"no fleet fan-out for family {fam!r} yet — supported: "
        "brute_force (exact), ivf_flat, ivf_rabitq (ROADMAP: ivf_pq / "
        "cagra fan-out)")


def _fleet_slices_for(index, mesh: Mesh, axis: str):
    """Family-dispatched ``fleet_slices`` for a (possibly Tombstoned)
    index view — the brute family folds the tombstone mask into its
    sharded validity mask; the IVF families carry it replicated."""
    base, keep = unwrap_tombstones(index)
    fam = family_of(base)
    if fam == "brute_force":
        return _bf.fleet_slices(base, mesh, axis=axis, filter=keep)
    if fam == "ivf_flat":
        return _ivf.fleet_slices(base, mesh, axis=axis)
    if fam == "ivf_rabitq":
        return _irq.fleet_slices(base, mesh, axis=axis)
    raise NotImplementedError(f"no fleet fan-out for family {fam!r}")


# ---------------------------------------------------------------------------
# Replica server: a SearchServer whose searchers fan out over the mesh
# ---------------------------------------------------------------------------


class _FleetReplicaServer(SearchServer):
    """A :class:`SearchServer` whose executables are mesh fan-outs.

    Overrides exactly the three seams the base class exposes:
    ``_make_parts`` (build the sharded searcher), ``_query_spec`` /
    ``_stage_queries`` (AOT executables record a replicated query
    sharding, and dispatch must stage queries with the SAME sharding —
    a plain ``device_put`` would commit to device 0 and miss the
    executable's layout).  Everything else — batching, admission,
    deadlines, the degradation ladder, metrics — is inherited, which is
    what makes per-replica degradation "the PR 10 ladder, per replica"
    rather than new machinery."""

    def __init__(self, index, k: int = 10, params=None, *, mesh: Mesh,
                 axis: str = "shard", name: str = "r0", **kw) -> None:
        self.mesh = mesh
        self.axis = axis
        self.name = str(name)
        self._slice_cache: Dict[int, Tuple[Any, Any]] = {}
        super().__init__(index, k, params, **kw)

    def _make_parts(self, index, k: int, scale: float):
        return make_fleet_searcher(index, k, self.params, mesh=self.mesh,
                                   axis=self.axis, effort_scale=scale,
                                   seed=self.seed,
                                   slices=self._slices(index))

    def _slices(self, index):
        # one slicing per generation view: ladder levels and k values
        # share the device slabs (the cache holds a strong ref, so the
        # id key stays valid while cached; two entries cover the
        # swap-prewarm window where old + new generations coexist)
        key = id(index)
        hit = self._slice_cache.get(key)
        if hit is not None and hit[0] is index:
            return hit[1]
        sl = _fleet_slices_for(index, self.mesh, self.axis)
        if len(self._slice_cache) >= 2:
            self._slice_cache.pop(next(iter(self._slice_cache)))
        self._slice_cache[key] = (index, sl)
        return sl

    def _stage_queries(self, qpad):
        return jax.device_put(qpad, NamedSharding(self.mesh, P()))

    def _query_spec(self, bucket: int, dtype):
        return jax.ShapeDtypeStruct(
            (bucket, self._dim), dtype,
            sharding=NamedSharding(self.mesh, P()))


# ---------------------------------------------------------------------------
# Router: least-loaded live replica, QueueFull spill, dead shedding
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class LocalReplica:
    """In-process replica handle: the router's duck type (``name`` /
    ``alive`` / ``load()`` / ``submit()`` / ``search()``).  The
    multi-process bench driver implements the same surface over a
    socket."""

    name: str
    server: SearchServer
    alive: bool = True

    def load(self) -> int:
        return self.server.queue_depth()

    def submit(self, queries, k=None, deadline_ms=None):
        if not self.alive:
            raise ReplicaDead(f"replica {self.name} is dead")
        return self.server.submit(queries, k, deadline_ms)

    def search(self, queries, k=None, deadline_ms=None):
        if not self.alive:
            raise ReplicaDead(f"replica {self.name} is dead")
        return self.server.search(queries, k, deadline_ms)


class FleetRouter:
    """Load-balanced request placement over a replica group.

    Placement is least-queued-first over LIVE replicas;  a replica at
    queue capacity spills the request to the next candidate instead of
    rejecting it (``QueueFull`` reaches the caller only when EVERY live
    replica is saturated).  A replica that dies mid-request is marked
    dead, counted (``raft_fleet_reroutes_total``), and the request
    retries on survivors — the replica-kill drill pins "zero dropped
    in-deadline requests" on exactly this path."""

    def __init__(self, replicas: Sequence[Any], *, registry=None,
                 clock=time.monotonic) -> None:
        expects(len(replicas) >= 1, "router needs at least one replica")
        self.replicas: List[Any] = list(replicas)
        self.clock = clock
        self._lock = lockdep.lock("FleetRouter._lock")
        reg = registry if registry is not None else obs_metrics.registry()
        self.registry = reg
        self._routed = reg.counter(
            "raft_fleet_routed_total",
            "requests placed on a replica by the fleet router")
        self._spills = reg.counter(
            "raft_fleet_queue_spills_total",
            "requests spilled to another replica on QueueFull")
        self._reroutes = reg.counter(
            "raft_fleet_reroutes_total",
            "requests rerouted off a dead replica to a survivor")
        self._depth_g = reg.gauge(
            "raft_fleet_replica_queue_depth",
            "per-replica pending queue depth at last export")
        self._live_g = reg.gauge("raft_fleet_replicas_live",
                                 "replicas currently routable")

    def live(self) -> List[Any]:
        return [r for r in self.replicas if r.alive]

    def mark_dead(self, name: str) -> None:
        with self._lock:
            for r in self.replicas:
                if r.name == name:
                    r.alive = False

    def export_gauges(self) -> None:
        for r in self.replicas:
            try:
                depth = float(r.load()) if r.alive else 0.0
            except Exception:
                depth = 0.0
            self._depth_g.set(depth, replica=r.name)
        self._live_g.set(float(len(self.live())))

    def _candidates(self) -> List[Any]:
        live = self.live()
        if not live:
            raise ReplicaDead("no live replicas")
        # snapshot loads once so one placement sorts one consistent view
        return sorted(live, key=lambda r: (r.load(), r.name))

    def submit(self, queries, k=None, deadline_ms=None):
        """Place one request; returns ``(future, replica)``.  Spills on
        ``QueueFull``, sheds dead replicas, raises ``QueueFull`` only
        when every live replica is saturated."""
        saturated = None
        for r in self._candidates():
            try:
                fut = r.submit(queries, k, deadline_ms)
                self._routed.inc(replica=r.name)
                return fut, r
            except QueueFull as e:
                saturated = e
                self._spills.inc(replica=r.name)
                continue
            except ReplicaDead:
                self.mark_dead(r.name)
                self._reroutes.inc(replica=r.name)
                continue
        if saturated is not None:
            raise saturated
        raise ReplicaDead("no live replicas")

    def search(self, queries, k=None, deadline_ms=None):
        """Synchronous search with dead-replica retry: each attempt runs
        on the current least-loaded live replica; a replica that dies
        mid-flight is marked dead and the request retries on a survivor
        (each attempt re-spans the full deadline — the caller's deadline
        governs queue wait within a replica, not the retry budget)."""
        last: Optional[Exception] = None
        for _ in range(max(1, len(self.replicas))):
            saturated = None
            placed = False
            for r in self._candidates():
                try:
                    out = r.search(queries, k, deadline_ms)
                    placed = True
                except QueueFull as e:
                    saturated = e
                    self._spills.inc(replica=r.name)
                    continue
                except ReplicaDead as e:
                    self.mark_dead(r.name)
                    self._reroutes.inc(replica=r.name)
                    last = e
                    break                      # re-sort and retry
                self._routed.inc(replica=r.name)
                return out
            if not placed and saturated is not None:
                raise saturated
            if not placed and last is None:
                raise ReplicaDead("no live replicas")
        raise last if last is not None else ReplicaDead("no live replicas")


# ---------------------------------------------------------------------------
# Fleet durability: per-shard stores, anti-affinity standbys, promotion
# ---------------------------------------------------------------------------


def shard_sub_indexes(index, n_shards: int) -> List[Any]:
    """Slice an index into ``n_shards`` host-side sub-indexes matching
    the fan-out's contiguous layout — shard *s* of the serving mesh owns
    exactly ``sub_indexes[s]``'s rows/lists.  These are what each
    shard's :class:`~raft_tpu.neighbors.wal.DurableStore` snapshots: a
    shard recovers (or a standby promotes) from state that maps 1:1 onto
    its slice of the serving operands."""
    index, _ = unwrap_tombstones(index)
    fam = family_of(index)
    n_shards = int(n_shards)
    expects(n_shards >= 1, "need at least one shard")

    if fam == "brute_force":
        y = np.asarray(index)
        n = y.shape[0]
        expects(n >= n_shards,
                f"{n} rows cannot populate {n_shards} shards")
        per = (n + n_shards - 1) // n_shards
        return [y[s * per:min(n, (s + 1) * per)] for s in range(n_shards)]

    def _pad(x, fill, pad):
        x = np.asarray(x)
        if not pad:
            return x
        shape = (pad,) + x.shape[1:]
        return np.concatenate([x, np.full(shape, fill, x.dtype)], axis=0)

    # IVF families: each shard's store is a SELF-CONTAINED sub-index over
    # its own list slice — centroids included (the build_sharded model:
    # shard s owns lists [s*lp, (s+1)*lp)).  A durable extend on a shard
    # therefore assigns into that shard's lists only, which is exactly
    # what the contiguous fan-out layout expects back at reslice time.
    # The list-axis pad (far-but-finite centroid, empty list) rides into
    # the last shard's sub-index as a never-chosen empty list.
    L = index.n_lists
    lp = (L + n_shards - 1) // n_shards
    pad = lp * n_shards - L
    cen = _pad(index.centroids, _ivf._FLEET_CENTROID_PAD, pad)
    sl = lambda x, s: x[s * lp:(s + 1) * lp]
    if fam == "ivf_flat":
        data = _pad(index.data, 0, pad)
        ids = _pad(index.ids, -1, pad)
        counts = _pad(index.counts, 0, pad)
        norms = _pad(index.norms, 0, pad)
        return [
            _ivf.IvfFlatIndex(sl(cen, s), sl(data, s), sl(ids, s),
                              sl(counts, s), sl(norms, s), index.metric)
            for s in range(n_shards)]
    if fam == "ivf_rabitq":
        rot = np.asarray(index.rotation)
        codes = _pad(index.codes, 0, pad)
        sabs = _pad(index.sabs, 0, pad)
        rn = _pad(index.res_norms, 0, pad)
        cdots = _pad(index.code_cdots, 0, pad)
        data = _pad(index.data, 0, pad)
        ids = _pad(index.ids, -1, pad)
        counts = _pad(index.counts, 0, pad)
        return [
            _irq.IvfRabitqIndex(sl(cen, s), rot, sl(codes, s), sl(sabs, s),
                                sl(rn, s), sl(cdots, s), sl(data, s),
                                sl(ids, s), sl(counts, s), index.metric)
            for s in range(n_shards)]
    raise NotImplementedError(
        f"no per-shard durability slicing for family {fam!r}")


@dataclasses.dataclass
class ShardDurability:
    """One shard's durability column: primary store + WAL, the
    multi-follower shipper, and its anti-affinity standbys."""

    shard: int
    assignment: Assignment
    store: DurableStore
    shipper: Optional[LogShipper]
    standbys: Tuple[StandbyReplica, ...]


class FleetDurability:
    """The PR 15 durability stack, fleet-wide.

    Each shard gets a primary :class:`DurableStore` (own WAL + snapshot
    lineage under ``<root>/shardNNN/primary``) and one
    :class:`LogShipper` fanning its log out to the shard's standbys —
    placed by :func:`.placement.plan_placement` so no standby shares a
    host with its primary.  :meth:`pump` drives heartbeats, shipping,
    and standby applies deterministically (tests; a deployment calls
    ``start()`` on the shippers/standbys instead); :meth:`promote_expired`
    is the fleet-level failover sweep — any shard whose primary lease
    expired promotes its first standby through the shared
    :class:`~raft_tpu.serve.replication.EpochFence` protocol."""

    def __init__(self, sub_indexes: Sequence[Any], root, *,
                 plan: PlacementPlan,
                 config: Optional[ReplicationConfig] = None,
                 registry=None, clock=time.monotonic) -> None:
        expects(len(sub_indexes) == len(plan.assignments),
                f"{len(sub_indexes)} sub-indexes for "
                f"{len(plan.assignments)} placement assignments")
        plan.validate()
        self.plan = plan
        self.root = os.fspath(root)
        self.config = config or ReplicationConfig()
        self.clock = clock
        self.promoted: List[int] = []
        shards: List[ShardDurability] = []
        for a in plan.assignments:
            base = os.path.join(self.root, f"shard{a.shard:03d}")
            store = DurableStore.create(os.path.join(base, "primary"),
                                        sub_indexes[a.shard], clock=clock)
            links: List[Any] = []
            standbys: List[StandbyReplica] = []
            for host in a.standbys:
                t_primary, t_standby = QueuePair.create()
                links.append(t_primary)
                standbys.append(StandbyReplica(
                    os.path.join(base, f"standby-{host}"), t_standby,
                    config=self.config, registry=registry,
                    node_id=f"shard{a.shard}-{host}", clock=clock))
            shipper = LogShipper(store, links, config=self.config,
                                 node_id=f"shard{a.shard}-primary",
                                 registry=registry,
                                 clock=clock) if links else None
            shards.append(ShardDurability(a.shard, a, store, shipper,
                                          tuple(standbys)))
        self.shards = shards
        self.pump()           # serve the hellos: snapshot bootstraps

    def pump(self, timeout: float = 0.0) -> int:
        """One deterministic replication round for every shard:
        heartbeat + ship + standby apply + ack collection.  Returns the
        number of messages processed."""
        n = 0
        for sh in self.shards:
            if sh.shipper is not None:
                sh.shipper.beat()
                n += sh.shipper.pump(timeout)
            for sb in sh.standbys:
                n += sb.poll()
        for sh in self.shards:   # collect the acks the applies produced
            if sh.shipper is not None:
                n += sh.shipper.pump(0.0)
        return n

    def promote_expired(self, now: Optional[float] = None) -> List[int]:
        """Fleet failover sweep: every shard whose primary lease has
        expired promotes its first (placement-ordered) bootstrapped
        standby.  Returns the shard ids promoted this sweep."""
        done: List[int] = []
        for sh in self.shards:
            for sb in sh.standbys:
                if sb.store is None or sb.promoted:
                    continue
                if not sb.primary_alive(now):
                    sb.promote()
                    done.append(sh.shard)
                break            # only the first standby per sweep
        self.promoted.extend(done)
        return done

    def lag(self) -> Dict[int, Dict[str, int]]:
        """Per-shard follower watermark lag (primary lsn − acked)."""
        out: Dict[int, Dict[str, int]] = {}
        for sh in self.shards:
            lsn = sh.store.wal_lsn
            out[sh.shard] = {fid: max(0, lsn - acked)
                             for fid, acked in sh.store.followers().items()}
        return out

    def stop(self) -> None:
        for sh in self.shards:
            if sh.shipper is not None:
                sh.shipper.stop()
            for sb in sh.standbys:
                sb.stop()


# ---------------------------------------------------------------------------
# FleetServer: bootstrap + replicas + router + durability, one object
# ---------------------------------------------------------------------------


class FleetServer:
    """Pod-scale serving: a replica group of mesh fan-out servers.

    Bootstrap: pass a ``mesh`` (tests), or let the constructor call
    :func:`~raft_tpu.comms.bootstrap.init_distributed` (which validates
    ``axis_shape`` against the visible devices).  Unless
    ``selftest=False``, the :mod:`~raft_tpu.comms.selftest` battery runs
    over the bootstrapped communicator first and a broken collective
    REFUSES to serve — a fleet that merges top-k through a faulty
    all-gather would return wrong neighbors with healthy-looking
    latency.

    ``n_replicas`` full :class:`SearchServer` replicas share the mesh
    (time-multiplexed on one process's devices here; one process per
    replica in the multi-process bench driver).  Each replica keeps its
    own admission controller, degradation ladder, and metrics registry —
    degradation is per-replica state, so one overloaded replica degrades
    while its peers keep serving at full effort.  The
    :class:`FleetRouter` in front places requests least-loaded-first and
    sheds from dead replicas to survivors.

    Durability (:meth:`attach_durability`) slices the index per shard
    and runs the PR 15 store/WAL/standby stack under an anti-affinity
    placement; :meth:`promote_expired` is the lease-expiry failover
    sweep.
    """

    def __init__(self, index, k: int = 10, params=None, *,
                 mesh: Optional[Mesh] = None, axis: str = "shard",
                 n_replicas: int = 1,
                 config: Optional[ServerConfig] = None,
                 comms=None, selftest: bool = True, seed: int = 0,
                 clock=time.monotonic, **server_kw) -> None:
        from ..comms import Comms
        from ..comms.bootstrap import init_distributed, verify_comms

        if mesh is None:
            if comms is None:
                comms = init_distributed(axis_names=(axis,))
            mesh = comms.mesh
        elif comms is None:
            comms = Comms(mesh, axis)
        expects(axis in mesh.axis_names,
                f"axis {axis!r} not in mesh axes {mesh.axis_names}")
        self.mesh = mesh
        self.axis = axis
        self.comms = comms
        self.n_shards = int(mesh.shape[axis])
        expects(n_replicas >= 1, "need at least one replica")
        # startup gate: don't take traffic over a broken collective
        self.selftest_results = verify_comms(comms) if selftest else None
        self._index = index
        self.k = int(k)
        self.params = params
        self.registry = obs_metrics.MetricRegistry()
        self.registry.gauge("raft_fleet_shards",
                            "index shards in the fan-out").set(
                                float(self.n_shards))
        self.replicas: List[LocalReplica] = []
        for r in range(int(n_replicas)):
            name = f"r{r}"
            srv = _FleetReplicaServer(index, k, params, mesh=mesh,
                                      axis=axis, name=name, config=config,
                                      seed=seed + r, clock=clock,
                                      **server_kw)
            self.replicas.append(LocalReplica(name, srv))
        self.router = FleetRouter(self.replicas, registry=self.registry,
                                  clock=clock)
        self.durability: Optional[FleetDurability] = None

    # -- lifecycle ----------------------------------------------------

    def start(self, warmup: bool = True) -> "FleetServer":
        for r in self.replicas:
            r.server.start(warmup=warmup)
        return self

    def stop(self, timeout: Optional[float] = 30.0) -> None:
        for r in self.replicas:
            r.server.stop(timeout=timeout)
        if self.durability is not None:
            self.durability.stop()

    def warmup(self) -> int:
        return sum(r.server.warmup() for r in self.replicas)

    def __enter__(self) -> "FleetServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- serving ------------------------------------------------------

    def submit(self, queries, k=None, deadline_ms=None):
        fut, _ = self.router.submit(queries, k, deadline_ms)
        return fut

    def search(self, queries, k=None, deadline_ms=None):
        return self.router.search(queries, k, deadline_ms)

    def step(self, now: Optional[float] = None) -> int:
        """Manual-drive mode: one batch step on every live replica."""
        return sum(r.server.step(now) for r in self.router.live())

    def kill_replica(self, name: str) -> None:
        """Drill hook: mark a replica dead (the router sheds to
        survivors) and stop its dispatch thread."""
        self.router.mark_dead(name)
        for r in self.replicas:
            if r.name == name:
                r.server.stop(timeout=5.0)

    # -- durability ---------------------------------------------------

    def attach_durability(self, root, hosts: Sequence[str], *,
                          n_standbys: int = 1,
                          config: Optional[ReplicationConfig] = None
                          ) -> FleetDurability:
        """Give every shard a durable store + WAL and ``n_standbys``
        warm standbys placed under anti-affinity over ``hosts``."""
        plan = plan_placement(self.n_shards, hosts,
                              n_standbys=n_standbys)
        subs = shard_sub_indexes(self._index, self.n_shards)
        self.durability = FleetDurability(
            subs, root, plan=plan, config=config, registry=self.registry,
            clock=self.replicas[0].server.clock)
        return self.durability

    def promote_expired(self, now: Optional[float] = None) -> List[int]:
        expects(self.durability is not None,
                "attach_durability() first — nothing to promote")
        return self.durability.promote_expired(now)

    # -- observability ------------------------------------------------

    def prometheus_text(self) -> str:
        """One scrape body: fleet-level families (router counters,
        shard/replica gauges) plus every replica's serving families
        disambiguated by an injected ``replica`` label."""
        self.router.export_gauges()
        per_replica = {r.name: r.server.metrics.registry
                       for r in self.replicas}
        return render(self.registry) + render_labeled(per_replica,
                                                      label="replica")

    def metrics_snapshot(self) -> dict:
        return {
            "shards": self.n_shards,
            "replicas_live": len(self.router.live()),
            "replicas": {r.name: r.server.metrics_snapshot()
                         for r in self.replicas},
        }

    def describe(self) -> str:
        """Operator-facing topology summary (runbook output)."""
        lines = [f"fleet: {self.n_shards} shards over mesh "
                 f"{dict(self.mesh.shape)} (axis {self.axis!r}), "
                 f"{len(self.replicas)} replicas "
                 f"({len(self.router.live())} live)"]
        for r in self.replicas:
            state = "live" if r.alive else "dead"
            lines.append(f"  replica {r.name}: {state}, "
                         f"queue={r.load() if r.alive else '-'}")
        if self.durability is not None:
            lines.append(self.durability.plan.describe())
        return "\n".join(lines)
