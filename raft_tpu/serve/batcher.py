"""Micro-batcher — coalesce pending requests into one bucket-shaped
dispatch.

Pure planning logic over the server's FIFO queue (no locks, no clock):
take the head request, then extend with successors sharing its
(k, dtype) cache coordinates while the running row total still fits the
largest ladder bucket.  FIFO order is preserved — a same-shape request
never overtakes an older incompatible one (which would starve it under
sustained mixed traffic).

Oversized requests are split at submit into ≤ max-bucket parts sharing
one :class:`SplitSink`, so a 10k-row bulk query streams through the
ladder's largest executable at full fill instead of demanding its own
shape.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence, Tuple

import numpy as np

from ..core import lockdep
from .bucketing import bucket_for

__all__ = ["Request", "SplitSink", "plan_batch"]


class SplitSink:
    """Aggregates the parts of a split request back into one future.

    Parts complete in submission order (FIFO queue, single dispatch
    thread), but the sink tolerates any order; the first failing part
    fails the whole request."""

    def __init__(self, future, n_parts: int) -> None:
        self.future = future
        self._lock = lockdep.lock("SplitSink._lock")
        self._parts: List = [None] * n_parts  # guarded_by: _lock
        self._missing = n_parts               # guarded_by: _lock

    def deliver(self, part: int, dist: np.ndarray, idx: np.ndarray) -> None:
        with self._lock:
            if self.future.done():
                return
            self._parts[part] = (dist, idx)
            self._missing -= 1
            done = self._missing == 0
        if done:
            d = np.concatenate([p[0] for p in self._parts], axis=0)
            i = np.concatenate([p[1] for p in self._parts], axis=0)
            self.future.set_result((d, i))

    def fail(self, exc: BaseException) -> None:
        with self._lock:
            if self.future.done():
                return
            self.future.set_exception(exc)


@dataclasses.dataclass
class Request:
    """One queue entry (a whole request, or one part of a split one)."""

    queries: np.ndarray          # (rows, d) host block
    k: int
    deadline: float              # absolute server-clock seconds
    t_submit: float
    future: object = None        # set for unsplit requests
    sink: Optional[SplitSink] = None   # set for split parts
    part: int = 0
    span: object = None          # obs root span (serve.request), if recording

    @property
    def rows(self) -> int:
        return int(self.queries.shape[0])

    @property
    def dtype_key(self) -> str:
        return str(self.queries.dtype)

    def resolve(self, dist: np.ndarray, idx: np.ndarray) -> None:
        if self.sink is not None:
            self.sink.deliver(self.part, dist, idx)
        elif not self.future.done():
            self.future.set_result((dist, idx))

    def reject(self, exc: BaseException) -> None:
        if self.sink is not None:
            self.sink.fail(exc)
        elif not self.future.done():
            self.future.set_exception(exc)


def plan_batch(pending: Sequence[Request],
               ladder: Sequence[int]) -> Tuple[List[Request], int]:
    """Pick the next dispatch from the FIFO queue.

    Returns ``(requests, bucket)``; callers pop exactly those entries.
    Greedy FIFO-prefix fill: head first, then later entries with the
    head's (k, dtype) while total rows still fit the largest bucket —
    skipped (incompatible) entries keep their queue position for the
    next plan."""
    head = pending[0]
    take = [head]
    total = head.rows
    max_bucket = ladder[-1]
    for req in list(pending)[1:]:
        if req.k != head.k or req.dtype_key != head.dtype_key:
            continue
        if total + req.rows > max_bucket:
            break
        take.append(req)
        total += req.rows
    return take, bucket_for(total, ladder)
