"""Replicated durability — WAL shipping, warm standby, fenced failover
(ISSUE 15 tentpole).

PR 7 made one node crash-recoverable: every mutation is a WAL record,
``DurableStore._apply`` is the ONLY mutation path (live and replay), so
recovery is a deterministic fold.  This module turns that contract into
high availability: a :class:`LogShipper` on the primary streams the same
CRC-framed records to a :class:`StandbyReplica`, which appends them to
its own WAL *at the primary's LSNs* and applies them through the same
fold — a promoted standby is bit-identical (values AND ids) to the
primary by construction, not by comparison.

Pieces:

* **Transport seam** — messages are ``kind + arrays + static`` reusing
  the WAL payload codec, framed ``magic | version | crc32 | len``.
  :meth:`QueuePair.create` wires two in-process endpoints (deterministic
  tests, single-host benches); :class:`SocketListener` /
  :class:`SocketTransport` carry the same frames over localhost TCP
  (the subprocess SIGKILL drill).  Both tolerate drops: delivery is
  repaired by watermark resync, never by blocking retry.
* **Ack modes** — ``async`` ships and moves on (loss window bounded by
  ``ReplicationConfig.ship_queue`` unacked records: the publisher blocks
  once the standby falls further behind); ``semi_sync`` extends the
  group-commit contract across the wire — the mutator's return waits for
  the standby ack (or degrades to async for that write after
  ``ack_timeout_s``, counted).
* **Catch-up** — a follower says hello with its ack watermark; the
  primary replies with the WAL tail past it, or a snapshot bootstrap
  (newest published checkpoint, shipped file-by-file) when the follower
  is cold or pruned-past.  ``DurableStore.prune_wal`` never discards
  records a registered follower has not acked, so catch-up from any
  live watermark always finds its tail.
* **Failure detection** — heartbeats carry ``(epoch, lsn, primary
  clock)``; :meth:`StandbyReplica.primary_alive` is a lease check over
  them, and lag is exported as ``raft_replication_lag_lsn`` /
  ``raft_replication_lag_seconds`` (primary-clock arithmetic: no
  cross-host clock comparison).
* **Fenced promotion** — epochs are ``(epoch, node_id)`` tokens ordered
  lexicographically.  :meth:`StandbyReplica.promote` drains the ship
  queue, claims ``max_seen + 1``, persists it, announces it; a deposed
  primary observing the higher token has every subsequent append / swap
  / snapshot rejected (:class:`.faults.FencedError`, counted as
  ``fenced_writes``).  The double-promotion race converges because the
  token order is total: exactly one claimant stays unfenced.

Chaos drills: the ``ship_send`` / ``ship_ack`` fault sites accept the
``partition`` kind (message dropped, deterministic heal when the armed
count is consumed), and every loss path above is exercised in
``tests/test_replication.py`` — including a subprocess SIGKILL failover
in the style of ``tests/_durability_driver.py``.
"""

from __future__ import annotations

import dataclasses
import os
import queue
import shutil
import socket
import struct
import threading
import time
import zlib
from typing import Any, Dict, List, Optional

import numpy as np

from ..core import lockdep
from ..core.errors import expects
from ..core.serialize import CorruptArtifact, fsync_dir, write_text_atomic
from ..neighbors.serialize import index_manifest
from ..neighbors.wal import (DurableStore, WalRecord, _decode_payload,
                             _encode_payload, read_wal)
from ..obs import metrics as obs_metrics
from ..obs import spans as obs_spans
from .faults import FencedError, Partitioned

__all__ = ["Message", "encode_message", "decode_message",
           "QueuePair", "SocketListener", "SocketTransport",
           "EpochToken", "EpochFence", "ReplicationConfig",
           "LogShipper", "StandbyReplica"]

_MSG_MAGIC = b"RTRM"
_MSG_VERSION = 1
_MSG_HEADER = struct.Struct("<4sBIQ")  # magic, version, crc32, payload_len
_EPOCH_FILE = "epoch"


# -- message framing ----------------------------------------------------


@dataclasses.dataclass(frozen=True)
class Message:
    """One decoded replication message: ``kind`` routes it, ``arrays``
    carry bulk payloads (WAL record operands, snapshot file bytes),
    ``static`` the JSON-able metadata."""

    kind: str
    arrays: Dict[str, np.ndarray]
    static: Dict[str, Any]


def encode_message(kind: str, arrays: Optional[Dict[str, Any]] = None,
                   **static) -> bytes:
    """Frame one message: the WAL payload codec (json head + npy
    streams) under a ``magic | version | crc32 | length`` header — the
    same torn/corrupt self-detection the on-disk log has, on the wire."""
    payload = _encode_payload(kind, arrays or {}, static)
    return _MSG_HEADER.pack(_MSG_MAGIC, _MSG_VERSION, zlib.crc32(payload),
                            len(payload)) + payload


def decode_message(blob: bytes) -> Message:
    """Parse + verify one framed message (raises
    :class:`core.serialize.CorruptArtifact` on any mismatch — a mangled
    frame must never half-apply)."""
    if len(blob) < _MSG_HEADER.size:
        raise CorruptArtifact(
            f"short replication frame ({len(blob)} bytes)")
    magic, version, crc, plen = _MSG_HEADER.unpack_from(blob)
    if magic != _MSG_MAGIC or version != _MSG_VERSION:
        raise CorruptArtifact(
            f"bad replication frame header ({magic!r} v{version})")
    payload = blob[_MSG_HEADER.size:_MSG_HEADER.size + plen]
    if len(payload) != plen or zlib.crc32(payload) != crc:
        raise CorruptArtifact("replication frame length/crc mismatch")
    rec = _decode_payload(0, payload)
    return Message(rec.op, rec.arrays, rec.static)


# -- transports ---------------------------------------------------------


class QueueTransport:
    """One endpoint of an in-process :meth:`QueuePair.create` link.
    Bytes round-trip through the full encode/decode (CRC verified), so
    in-process tests exercise the same framing the socket path does."""

    def __init__(self, inbox: "queue.Queue", outbox: "queue.Queue") -> None:
        self._inbox = inbox
        self._outbox = outbox
        self.closed = False

    def send(self, blob: bytes) -> None:
        self._outbox.put(bytes(blob))

    def recv(self, timeout: float = 0.0) -> Optional[Message]:
        try:
            if timeout and timeout > 0:
                blob = self._inbox.get(timeout=timeout)
            else:
                blob = self._inbox.get_nowait()
        except queue.Empty:
            return None
        return decode_message(blob)

    def pending(self) -> int:
        """Messages delivered but not yet received — the in-flight ship
        queue the async-mode loss bound is measured against."""
        return self._inbox.qsize()

    def close(self) -> None:
        self.closed = True


class QueuePair:
    """Factory for a bidirectional in-process link."""

    @staticmethod
    def create(maxsize: int = 0):
        """``(a, b)`` endpoints: whatever ``a`` sends, ``b`` receives,
        and vice versa, in order."""
        ab: "queue.Queue" = queue.Queue(maxsize)
        ba: "queue.Queue" = queue.Queue(maxsize)
        return QueueTransport(ba, ab), QueueTransport(ab, ba)


class SocketTransport:
    """Localhost TCP carrier for the same frames; partial reads are
    buffered so a frame split across segments reassembles, and a dead
    peer turns into ``closed=True`` + ``recv() -> None`` (never an
    unhandled exception on the serving path)."""

    def __init__(self, sock: socket.socket) -> None:
        self._sock = sock
        try:
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        except OSError:
            pass
        self._buf = b""
        self._send_lock = lockdep.lock("SocketTransport._send_lock")
        self.closed = False

    @classmethod
    def connect(cls, host: str, port: int,
                timeout: float = 30.0) -> "SocketTransport":
        return cls(socket.create_connection((host, port), timeout=timeout))

    def send(self, blob: bytes) -> None:
        with self._send_lock:
            self._sock.sendall(blob)  # racelint: disable=JX12 the send IS this lock's job: frames must hit the wire whole, and _send_lock is a per-connection leaf nothing else nests under it

    def _parse(self) -> Optional[Message]:
        if len(self._buf) < _MSG_HEADER.size:
            return None
        plen = _MSG_HEADER.unpack_from(self._buf)[3]
        total = _MSG_HEADER.size + plen
        if len(self._buf) < total:
            return None
        blob = self._buf[:total]
        self._buf = self._buf[total:]
        return decode_message(blob)

    def recv(self, timeout: float = 0.0) -> Optional[Message]:
        msg = self._parse()
        if msg is not None:
            return msg
        deadline = time.monotonic() + max(float(timeout), 0.0)
        while not self.closed:
            remaining = deadline - time.monotonic()
            if remaining < 0:
                return None
            self._sock.settimeout(max(remaining, 0.001))
            try:
                chunk = self._sock.recv(1 << 20)
            except socket.timeout:
                continue
            except OSError:
                self.closed = True
                return None
            if not chunk:  # orderly peer close
                self.closed = True
                return None
            self._buf += chunk
            msg = self._parse()
            if msg is not None:
                return msg
        return None

    def pending(self) -> int:
        """Complete frames buffered locally (in-flight kernel bytes are
        invisible — the socket loss bound is asserted via watermarks)."""
        n, off = 0, 0
        while len(self._buf) - off >= _MSG_HEADER.size:
            plen = _MSG_HEADER.unpack_from(self._buf, off)[3]
            if len(self._buf) - off < _MSG_HEADER.size + plen:
                break
            off += _MSG_HEADER.size + plen
            n += 1
        return n

    def close(self) -> None:
        self.closed = True
        try:
            self._sock.close()
        except OSError:
            pass


class SocketListener:
    """Accept side of the socket transport (the standby in the failover
    drill listens; the primary child process connects)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0) -> None:
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(4)
        self.host, self.port = self._sock.getsockname()[:2]

    def accept(self, timeout: float = 30.0) -> SocketTransport:
        self._sock.settimeout(timeout)
        conn, _ = self._sock.accept()
        return SocketTransport(conn)

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass


# -- epochs + fencing ---------------------------------------------------


@dataclasses.dataclass(frozen=True, order=True)
class EpochToken:
    """Totally-ordered promotion claim: epochs compare first, node ids
    break ties — two standbys racing ``promote()`` at the same epoch
    resolve deterministically, no coordinator needed."""

    epoch: int
    node_id: str


class EpochFence:
    """Monotonic split-brain guard threaded through ``DurableStore``
    (append/snapshot) and ``SearchServer.swap_index``.

    ``writer=True`` marks the node as claiming primaryship: its writes
    raise :class:`.faults.FencedError` the moment a higher token has
    been observed.  A standby keeps ``writer=False`` (it must checkpoint
    and refresh its serving generation while *correctly* observing the
    primary's higher epoch) until :meth:`advance` promotes it.  The
    current and max-seen tokens persist to ``<root>/epoch`` so a
    restarted deposed primary stays deposed."""

    def __init__(self, node_id: str, epoch: int = 0, *,
                 root: Optional[str] = None, writer: bool = False) -> None:
        self.node_id = str(node_id)
        self.epoch = int(epoch)
        self.writer = bool(writer)
        self.root = os.fspath(root) if root is not None else None
        self._lock = lockdep.lock("EpochFence._lock")
        self._max_seen = EpochToken(self.epoch, self.node_id)  # guarded_by: _lock

    @property
    def token(self) -> EpochToken:
        return EpochToken(self.epoch, self.node_id)

    @property
    def max_seen(self) -> EpochToken:
        with self._lock:
            return self._max_seen

    @property
    def fenced(self) -> bool:
        """True when a strictly newer claim than ours has been observed."""
        with self._lock:
            return self._max_seen > EpochToken(self.epoch, self.node_id)

    def observe(self, epoch: int, node_id: str = "") -> bool:
        """Fold a remote token into ``max_seen``; returns the (possibly
        new) fenced state."""
        tok = EpochToken(int(epoch), str(node_id))
        with self._lock:
            newly = tok > self._max_seen
            if newly:
                self._max_seen = tok
        if newly and self.root is not None:
            self._persist()
        return self.fenced

    def advance(self) -> int:
        """Claim the next epoch (promotion): strictly greater than every
        claim this node has observed, persisted before it is announced."""
        with self._lock:
            self.epoch = self._max_seen.epoch + 1
            self.writer = True
            self._max_seen = EpochToken(self.epoch, self.node_id)
        if self.root is not None:
            self._persist()
        return self.epoch

    def check(self, site: str, count=None) -> None:
        """Raise :class:`.faults.FencedError` when a deposed writer tries
        to write at ``site``; ``count`` (a counter callable) records the
        rejection as ``fenced_writes``."""
        if self.writer and self.fenced:
            if count is not None:
                count("fenced_writes")
            obs_spans.recorder().event("replication.fenced_write",
                                       site=site, node=self.node_id,
                                       epoch=self.epoch)
            raise FencedError(
                f"node {self.node_id!r} epoch {self.epoch} deposed by "
                f"{self.max_seen} — write at {site!r} rejected")

    def _persist(self) -> None:
        seen = self.max_seen
        write_text_atomic(
            os.path.join(self.root, _EPOCH_FILE),
            f"{self.epoch} {self.node_id}\n{seen.epoch} {seen.node_id}\n")

    @classmethod
    def load(cls, root, node_id: str, *, writer: bool = False) -> "EpochFence":
        """Restore a fence from ``<root>/epoch`` (fresh roots start at
        epoch 0)."""
        self = cls(node_id, root=root, writer=writer)
        path = os.path.join(self.root, _EPOCH_FILE)
        if os.path.exists(path):
            with open(path) as f:
                lines = f.read().splitlines()
            own = lines[0].split()
            if own[0].lstrip("-").isdigit() and own[1:] == [self.node_id]:
                self.epoch = int(own[0])
            seen = lines[1].split(None, 1) if len(lines) > 1 else own
            self._max_seen = max(EpochToken(self.epoch, self.node_id),
                                 EpochToken(int(seen[0]),
                                            seen[1] if len(seen) > 1 else ""))
        return self


# -- configuration ------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class ReplicationConfig:
    """Replication policy knobs.

    ``ack_mode``: ``"async"`` (ship and continue; loss window bounded by
    ``ship_queue`` unacked records) or ``"semi_sync"`` (the mutator's
    return waits for the standby ack, extending the group-commit
    durability contract across the wire; a wait past ``ack_timeout_s``
    degrades that one write to async and counts
    ``raft_replication_ack_timeouts_total``).  ``heartbeat_interval_s`` /
    ``lease_s``: failure-detection cadence — the standby declares the
    primary dead after ``lease_s`` without traffic.  ``refresh_every``:
    the standby's bounded-staleness serve refresh — swap the serving
    generation every N applied records."""

    ack_mode: str = "async"
    ack_timeout_s: float = 5.0
    ship_queue: int = 256
    heartbeat_interval_s: float = 1.0
    lease_s: float = 3.0
    refresh_every: int = 1


_ACK_MODES = ("async", "semi_sync")


# -- primary: the log shipper ------------------------------------------


class LogShipper:
    """Streams a primary :class:`DurableStore`'s WAL to followers.

    Hooks ``store.on_commit`` so every committed mutation ships in LSN
    order (under the store lock — ordering is structural, not
    best-effort).  Incoming traffic (``hello`` / ``ack`` / ``fence``) is
    consumed by :meth:`pump`, either manually (deterministic tests) or
    from :meth:`start`'s background thread.  Follower watermarks live on
    the store itself (``register_follower`` / ``follower_acked``) so
    ``DurableStore.prune_wal`` sees them without knowing this class.

    ``transport`` is one endpoint or a sequence of them — one per
    follower (the fleet tier places several anti-affinity standbys per
    shard).  Records and heartbeats broadcast to every link; catch-up
    replies go back on the link the ``hello`` arrived on; the semi-sync
    ack wait and the async loss bound both measure against
    ``DurableStore.follower_floor()`` — the SLOWEST follower — so the
    durability guarantee is fleet-wide, not per-link.  Per-follower lag
    is exported as ``raft_replication_follower_lag_lsn{follower=...}``
    next to the floor-level ``raft_replication_lag_*`` pair."""

    def __init__(self, store: DurableStore, transport, *,
                 config: Optional[ReplicationConfig] = None,
                 node_id: str = "primary", registry=None, faults=None,
                 clock=time.monotonic) -> None:
        self.store = store
        if isinstance(transport, (list, tuple)):
            expects(len(transport) >= 1, "LogShipper needs >= 1 transport")
            self.transports: List[Any] = list(transport)
        else:
            self.transports = [transport]
        self.config = config or ReplicationConfig()
        expects(self.config.ack_mode in _ACK_MODES,
                f"unknown ack_mode {self.config.ack_mode!r} ({_ACK_MODES})")
        self.node_id = str(node_id)
        self.clock = clock
        self.faults = faults if faults is not None \
            else getattr(store, "faults", None)
        reg = registry if registry is not None else obs_metrics.registry()
        self.metrics = reg
        self._acks = reg.counter("raft_replication_acks_total",
                                 "standby acks processed by the primary")
        self._shipped = reg.counter("raft_replication_records_total",
                                    "WAL records shipped to followers")
        self._drops = reg.counter(
            "raft_replication_drops_total",
            "replication messages dropped (partition / link down)")
        self._ack_timeouts = reg.counter(
            "raft_replication_ack_timeouts_total",
            "semi-sync ack waits that timed out (that write degraded "
            "to async)")
        self._resyncs = reg.counter(
            "raft_replication_resyncs_total",
            "follower catch-up streams served (hello / gap resync)")
        self._lag_lsn = reg.gauge(
            "raft_replication_lag_lsn",
            "primary WAL lsn minus the slowest follower's acked lsn")
        self._lag_s = reg.gauge(
            "raft_replication_lag_seconds",
            "seconds since the slowest follower's last ack "
            "(primary clock)")
        self._follower_lag = reg.gauge(
            "raft_replication_follower_lag_lsn",
            "primary WAL lsn minus one follower's acked lsn")
        fence = getattr(store, "fence", None)
        self.fence = fence if fence is not None \
            else EpochFence.load(store.root, self.node_id, writer=True)
        self.fence.writer = True
        store.fence = self.fence
        if self.fence.epoch == 0 and not self.fence.fenced:
            # epoch 0 is the unclaimed era (every fresh node holds it):
            # a primary's authority must outrank all unclaimed tokens,
            # so shipping starts by claiming epoch 1
            self.fence.advance()
        # _ack_t / _follower_link / _last_beat are owned by whichever
        # single thread drives pump()/beat() — the heartbeat loop or a
        # test harness, never both at once — so they stay unguarded
        self._ack_t: Dict[str, float] = {}  # follower -> clock at last ack
        self._follower_link: Dict[str, Any] = {}  # follower -> hello's link
        self._cond = lockdep.condition("LogShipper._cond")
        self._last_beat = float("-inf")
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        store.on_commit.append(self._on_commit)

    # -- outbound ------------------------------------------------------

    @property
    def transport(self):
        """The first (historically only) follower link — kept for the
        single-follower call sites; multi-follower code iterates
        ``transports``."""
        return self.transports[0]

    @transport.setter
    def transport(self, value) -> None:
        """Replace the sole follower link (restart-with-new-socket path).
        Stale per-follower reply links die with the old endpoint; the
        follower's next hello re-registers over the new one."""
        expects(len(self.transports) == 1,
                "transport setter is single-follower only; "
                "mutate `transports` for a fan-out shipper")
        self.transports = [value]
        self._follower_link.clear()

    def _send(self, blob: bytes, *, what: str, transport=None) -> bool:
        """Send on one link (``transport``) or broadcast to every
        follower link; True when at least one delivery succeeded."""
        if self.faults is not None:
            try:
                self.faults.fire("ship_send")
            except Partitioned:
                self._drops.inc()
                obs_spans.recorder().event("replication.drop",
                                           site="ship_send", what=what)
                return False
        links = self.transports if transport is None else [transport]
        ok = False
        for link in links:
            try:
                link.send(blob)
                ok = True
            except OSError as exc:
                self._drops.inc()
                obs_spans.recorder().event("replication.drop",
                                           site="ship_send", what=what,
                                           error=type(exc).__name__)
        return ok

    def _record_blob(self, lsn: int, op: str, arrays, static) -> bytes:
        return encode_message("record", arrays, lsn=int(lsn), op=str(op),
                              record_static=static, node=self.node_id,
                              epoch=self.fence.epoch, t=self.clock())

    def _on_commit(self, lsn: int, op: str, arrays, static) -> None:
        # runs under the store lock: records enter the wire in LSN order
        if self._send(self._record_blob(lsn, op, arrays, static),
                      what=f"record:{lsn}"):
            self._shipped.inc()
        floor = self.store.follower_floor()
        if floor is None:
            return  # nobody registered yet — hello catch-up will resync
        if self.config.ack_mode == "semi_sync":
            self._await_floor(lsn, self.config.ack_timeout_s)
        else:
            window = max(0, int(self.config.ship_queue))
            if lsn - floor > window:  # async backpressure = loss bound
                self._await_floor(lsn - window, self.config.ack_timeout_s)

    def _await_floor(self, target: int, timeout_s: float) -> bool:
        deadline = self.clock() + timeout_s
        while True:
            floor = self.store.follower_floor()
            if floor is None or floor >= target:
                return True
            remaining = deadline - self.clock()
            if remaining <= 0:
                self._ack_timeouts.inc()
                obs_spans.recorder().event("replication.ack_timeout",
                                           target=target, floor=floor)
                return False
            if self._thread is not None and self._thread.is_alive():
                with self._cond:  # the pump thread notifies on acks
                    self._cond.wait(min(remaining, 0.05))
            else:
                self.pump(min(remaining, 0.05))

    def beat(self, force: bool = False) -> None:
        """Heartbeat: ``(epoch, lsn, primary clock)`` — the standby's
        lease and lag-seconds source.  Rate-limited to
        ``heartbeat_interval_s`` unless forced."""
        now = self.clock()
        if not force and now - self._last_beat \
                < self.config.heartbeat_interval_s:
            return
        self._last_beat = now
        self._send(encode_message("heartbeat", None, node=self.node_id,
                                  lsn=self.store.wal_lsn,
                                  epoch=self.fence.epoch, t=now),
                   what="heartbeat")
        self._update_lag()

    # -- inbound -------------------------------------------------------

    def pump(self, timeout: float = 0.0) -> int:
        """Process pending follower traffic (every link); returns
        messages handled.  The blocking ``timeout`` applies to the first
        link only — subsequent links drain whatever is already pending,
        so a silent follower never starves the others."""
        n = 0
        t = timeout
        for link in list(self.transports):
            while True:
                msg = link.recv(t)
                t = 0.0
                if msg is None:
                    break
                self._handle(msg, link)
                n += 1
        return n

    def _handle(self, msg: Message, transport=None) -> None:
        s = msg.static
        if "epoch" in s and self.fence.observe(s.get("epoch", 0),
                                               s.get("node", "")):
            obs_spans.recorder().event("replication.deposed",
                                       node=self.node_id,
                                       by=str(self.fence.max_seen))
        if msg.kind == "hello":
            fid = str(s["node"])
            ack = int(s["ack_lsn"])
            self.store.register_follower(fid, ack)
            self._ack_t[fid] = self.clock()
            if transport is not None:
                self._follower_link[fid] = transport
            self._catch_up(fid, ack, cold=bool(s.get("cold")),
                           transport=transport)
        elif msg.kind == "ack":
            fid = str(s["node"])
            self.store.follower_acked(fid, int(s["lsn"]))
            self._acks.inc()
            self._ack_t[fid] = self.clock()
            if transport is not None:
                self._follower_link.setdefault(fid, transport)
            self._update_lag()
            with self._cond:
                self._cond.notify_all()
        # fence messages need no handler beyond the observe above

    def _update_lag(self) -> None:
        floor = self.store.follower_floor()
        if floor is None:
            return
        lsn = self.store.wal_lsn
        for fid, acked in self.store.followers().items():
            self._follower_lag.set(float(max(0, lsn - acked)),
                                   follower=fid)
        lag = max(0, lsn - floor)
        self._lag_lsn.set(float(lag))
        if lag == 0 or not self._ack_t:
            self._lag_s.set(0.0)
        else:
            self._lag_s.set(max(0.0,
                                self.clock() - min(self._ack_t.values())))

    # -- catch-up ------------------------------------------------------

    def _catch_up(self, fid: str, from_lsn: int, cold: bool,
                  transport=None) -> None:
        # replies ride the link the hello arrived on: a broadcast resync
        # would re-deliver (harmless duplicates, re-acked) but waste the
        # other followers' bandwidth on records they already hold
        rec = obs_spans.recorder()
        with rec.span("replication.catch_up", follower=fid,
                      from_lsn=from_lsn, cold=cold):
            self._resyncs.inc()
            records: List[WalRecord] = []
            if os.path.exists(self.store.wal.path):
                self.store.wal.sync()
                records, _, _ = read_wal(self.store.wal.path)
            base = records[0].lsn - 1 if records else self.store.wal_lsn
            if cold or from_lsn < base:
                # the tail alone cannot reach the follower's watermark:
                # bootstrap from the newest published snapshot
                watermark = self._ship_snapshot(transport)
                from_lsn = max(from_lsn, watermark)
            for r in records:
                if r.lsn > from_lsn:
                    if not self._send(self._record_blob(r.lsn, r.op,
                                                        r.arrays, r.static),
                                      what=f"catchup:{r.lsn}",
                                      transport=transport):
                        break  # partitioned: the follower will re-hello
            self.beat(force=True)

    def _ship_snapshot(self, transport=None) -> int:
        snaps = self.store.snapshots()
        if not snaps:
            self.store.snapshot()
            snaps = self.store.snapshots()
        name = snaps[-1]
        path = os.path.join(self.store.snap_dir, name)
        watermark = int(index_manifest(path).get("wal_lsn", 0))
        files: List[str] = []
        for walk_root, _, fns in os.walk(path):
            files += [os.path.relpath(os.path.join(walk_root, fn), path)
                      for fn in fns]
        files.sort()
        arrays = {f"f{i:04d}": np.fromfile(os.path.join(path, rel),
                                           dtype=np.uint8)
                  for i, rel in enumerate(files)}
        self._send(encode_message("snapshot", arrays, name=name,
                                  watermark=watermark, files=files,
                                  node=self.node_id,
                                  epoch=self.fence.epoch, t=self.clock()),
                   what=f"snapshot:{name}", transport=transport)
        return watermark

    # -- lifecycle -----------------------------------------------------

    @property
    def followers(self) -> Dict[str, int]:
        return self.store.followers()

    def start(self) -> "LogShipper":
        """Background pump: follower traffic + heartbeats."""
        expects(self._thread is None, "shipper already started")
        self._stop.clear()
        self._thread = threading.Thread(target=self._run,
                                        name="raft-log-shipper", daemon=True)
        self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                self.pump(0.05)
                self.beat()
            except Exception as exc:  # noqa: BLE001 — keep shipping
                obs_spans.recorder().event("replication.pump_error",
                                           error=type(exc).__name__)

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None


# -- standby ------------------------------------------------------------


class StandbyReplica:
    """Warm follower: applies shipped records through the store's own
    ``_apply`` fold (bit-identity by construction), acks watermarks,
    serves bounded-staleness reads via an attached server, and promotes
    with a fenced epoch claim.

    A fresh root bootstraps cold (hello → snapshot ship → records); a
    root with prior state recovers locally and catches up from its
    watermark.  Drive it manually with :meth:`poll` (deterministic
    tests) or :meth:`start` a background thread."""

    def __init__(self, root, transport, *,
                 config: Optional[ReplicationConfig] = None,
                 node_id: str = "standby", registry=None, faults=None,
                 clock=time.monotonic, store_config=None,
                 hello: bool = True) -> None:
        self.root = os.fspath(root)
        os.makedirs(self.root, exist_ok=True)
        self.transport = transport
        self.config = config or ReplicationConfig()
        self.node_id = str(node_id)
        self.clock = clock
        self.faults = faults
        self.store_config = store_config
        reg = registry if registry is not None else obs_metrics.registry()
        self.metrics = reg
        self._applied_c = reg.counter("raft_replication_applied_total",
                                      "records applied by this standby")
        self._gaps = reg.counter(
            "raft_replication_gaps_total",
            "out-of-sequence ship messages (each triggers a resync)")
        self._stale = reg.counter(
            "raft_replication_stale_epoch_total",
            "messages from a deposed epoch, dropped")
        self._drops = reg.counter(
            "raft_replication_drops_total",
            "replication messages dropped (partition / link down)")
        self._failovers = reg.counter("raft_failovers_total",
                                      "standby promotions completed")
        self._lag_lsn = reg.gauge(
            "raft_replication_lag_lsn",
            "primary WAL lsn minus the slowest follower's acked lsn")
        self._lag_s = reg.gauge(
            "raft_replication_lag_seconds",
            "seconds since the slowest follower's last ack "
            "(primary clock)")
        self.fence = EpochFence.load(self.root, self.node_id, writer=False)
        self.store: Optional[DurableStore] = None
        if self._has_local_state():
            self.store = DurableStore.recover(self.root,
                                              config=store_config,
                                              faults=faults, clock=clock)
            self.store.fence = self.fence
        self.applied = self._local_watermark()
        self.applied_t: Optional[float] = None  # primary clock, last apply
        self.primary_lsn = self.applied
        self.primary_t: Optional[float] = None  # primary clock, last beat
        self.last_beat: Optional[float] = None  # local clock, last traffic
        self.promoted = False
        self.server = None
        self._refreshed = -1
        self._resync_at = -1
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        if hello:
            self.hello()

    # -- local state ---------------------------------------------------

    def _has_local_state(self) -> bool:
        snap_dir = os.path.join(self.root, "snapshots")
        if os.path.isdir(snap_dir) and any(
                n.startswith("snap-") and "." not in n
                for n in os.listdir(snap_dir)):
            return True
        wal_path = os.path.join(self.root, "wal.log")
        return os.path.exists(wal_path) and os.path.getsize(wal_path) > 0

    def _local_watermark(self) -> int:
        if self.store is None:
            return 0
        w = self.store.wal_lsn
        snaps = self.store.snapshots()
        if snaps:
            manifest = index_manifest(
                os.path.join(self.store.snap_dir, snaps[-1]))
            w = max(w, int(manifest.get("wal_lsn", 0)))
        return w

    # -- outbound ------------------------------------------------------

    def _send(self, blob: bytes, *, what: str) -> bool:
        if self.faults is not None:
            try:
                self.faults.fire("ship_ack")
            except Partitioned:
                self._drops.inc()
                obs_spans.recorder().event("replication.drop",
                                           site="ship_ack", what=what)
                return False
        try:
            self.transport.send(blob)
        except OSError as exc:
            self._drops.inc()
            obs_spans.recorder().event("replication.drop", site="ship_ack",
                                       what=what, error=type(exc).__name__)
            return False
        return True

    def hello(self) -> None:
        """(Re)introduce this follower: the primary registers the ack
        watermark and streams the missing tail (or a snapshot)."""
        self._send(encode_message("hello", None, node=self.node_id,
                                  ack_lsn=self.applied,
                                  cold=self.store is None,
                                  epoch=self.fence.epoch, t=self.clock()),
                   what="hello")

    def _ack(self, lsn: int) -> None:
        self._send(encode_message("ack", None, node=self.node_id,
                                  lsn=int(lsn), epoch=self.fence.epoch,
                                  t=self.clock()),
                   what=f"ack:{lsn}")

    def _request_resync(self) -> None:
        if self._resync_at == self.applied:
            return  # one outstanding request per watermark
        self._resync_at = self.applied
        self.hello()

    # -- inbound -------------------------------------------------------

    def poll(self, timeout: float = 0.0, max_messages: int = 0) -> int:
        """Apply pending ship traffic; returns messages handled."""
        n = 0
        t = timeout
        while True:
            msg = self.transport.recv(t)
            if msg is None:
                return n
            self._handle(msg)
            n += 1
            if max_messages and n >= max_messages:
                return n
            t = 0.0

    def _handle(self, msg: Message) -> None:
        s = msg.static
        sender = EpochToken(int(s.get("epoch", 0)), str(s.get("node", "")))
        if msg.kind in ("record", "snapshot", "heartbeat") \
                and sender < self.fence.token:
            # a deposed primary's leftovers: never apply (split brain)
            self._stale.inc()
            obs_spans.recorder().event("replication.stale_epoch",
                                       kind=msg.kind, sender=str(sender))
            return
        if self.fence.observe(sender.epoch, sender.node_id) \
                and self.promoted:
            self.promoted = False  # outranked after our own promotion
            obs_spans.recorder().event("replication.deposed",
                                       node=self.node_id,
                                       by=str(self.fence.max_seen))
        if msg.kind == "record":
            self.last_beat = self.clock()
            self._on_record(msg)
        elif msg.kind == "snapshot":
            self.last_beat = self.clock()
            self._bootstrap(msg)
        elif msg.kind == "heartbeat":
            self.last_beat = self.clock()
            self.primary_lsn = max(self.primary_lsn, int(s.get("lsn", 0)))
            self.primary_t = float(s.get("t", 0.0))
            if self.primary_lsn > self.applied:
                self._request_resync()  # records were dropped on the wire
            self._update_lag()
        elif msg.kind == "fence":
            pass  # the observe above did the work

    def _on_record(self, msg: Message) -> None:
        s = msg.static
        lsn = int(s["lsn"])
        self.primary_lsn = max(self.primary_lsn, lsn)
        self.primary_t = float(s.get("t", 0.0))
        if self.store is None:
            self._request_resync()  # cold: need the snapshot first
            return
        if lsn <= self.applied:
            self._ack(self.applied)  # duplicate from a resync: re-ack
        elif lsn == self.applied + 1:
            rec = WalRecord(lsn, str(s["op"]), msg.arrays,
                            dict(s.get("record_static") or {}))
            self.store.apply_replicated(rec)
            self.applied = lsn
            self.applied_t = float(s.get("t", 0.0))
            self._applied_c.inc()
            self._ack(lsn)
            self._refresh_server()
        else:
            self._gaps.inc()
            obs_spans.recorder().event("replication.gap", got=lsn,
                                       want=self.applied + 1)
            self._request_resync()
        self._update_lag()

    def _bootstrap(self, msg: Message) -> None:
        s = msg.static
        watermark = int(s["watermark"])
        if self.store is not None and self.applied >= watermark:
            return  # already warm past this checkpoint
        rec = obs_spans.recorder()
        with rec.span("replication.bootstrap", watermark=watermark):
            snap_dir = os.path.join(self.root, "snapshots")
            os.makedirs(snap_dir, exist_ok=True)
            tmp = os.path.join(snap_dir, f"bootstrap-{os.getpid()}.tmp")
            shutil.rmtree(tmp, ignore_errors=True)
            for i, rel in enumerate(s["files"]):
                data = msg.arrays[f"f{i:04d}"]
                fp = os.path.join(tmp, rel)
                os.makedirs(os.path.dirname(fp), exist_ok=True)
                with open(fp, "wb") as f:
                    f.write(data.tobytes())
                    f.flush()
                    os.fsync(f.fileno())
            final = os.path.join(snap_dir, str(s["name"]))
            shutil.rmtree(final, ignore_errors=True)
            os.rename(tmp, final)
            fsync_dir(snap_dir)
            # any local WAL predates this checkpoint (the primary only
            # bootstraps when the tail cannot reach our watermark):
            # subsumed, and its lsn base could not continue the stream
            wal_path = os.path.join(self.root, "wal.log")
            if self.store is not None:
                self.store.close()
            if os.path.exists(wal_path):
                os.unlink(wal_path)
            self.store = DurableStore.recover(self.root,
                                              config=self.store_config,
                                              faults=self.faults,
                                              clock=self.clock)
            self.store.fence = self.fence
            self.applied = self._local_watermark()
            self._ack(self.applied)
            if self.server is not None:
                self.server.adopt_store(self.store)
            self._refresh_server(force=True)
            self._update_lag()

    def _update_lag(self) -> None:
        lag = max(0, self.primary_lsn - self.applied)
        self._lag_lsn.set(float(lag))
        if lag == 0 or self.applied_t is None or self.primary_t is None:
            self._lag_s.set(0.0)
        else:
            # primary-clock arithmetic on both operands: no cross-host
            # clock comparison sneaks in
            self._lag_s.set(max(0.0, self.primary_t - self.applied_t))

    def lag(self) -> Dict[str, float]:
        """Current replication lag: ``{"lsn": ..., "seconds": ...}``."""
        self._update_lag()
        return {"lsn": float(self._lag_lsn.value()),
                "seconds": float(self._lag_s.value())}

    def primary_alive(self, now: Optional[float] = None) -> bool:
        """Lease check: any primary traffic within ``lease_s``?"""
        if self.last_beat is None:
            return False
        now = self.clock() if now is None else now
        return (now - self.last_beat) <= self.config.lease_s

    # -- serving -------------------------------------------------------

    def attach_server(self, server) -> "StandbyReplica":
        """Serve bounded-staleness reads from this standby: the server's
        generation is swapped every ``refresh_every`` applied records,
        and the server inherits the fence (its ``swap_index`` stays
        permitted — ``writer=False`` — until promotion flips it)."""
        self.server = server
        server.fence = self.fence
        server.replication = self
        if self.store is not None:
            server.adopt_store(self.store)
            self._refresh_server(force=True)
        return self

    def _refresh_server(self, force: bool = False) -> None:
        if self.server is None or self.store is None:
            return
        every = max(1, int(self.config.refresh_every))
        if not force and self.applied - self._refreshed < every:
            return
        if self.store.index is not self.server.index:
            self.server.swap_index(self.store.index)
        self._refreshed = self.applied

    @property
    def is_serving(self) -> bool:
        """Promoted and not outranked — the double-promotion drill
        asserts exactly one node in the fleet reports True."""
        return self.promoted and not self.fence.fenced

    # -- promotion -----------------------------------------------------

    def promote(self, drain_timeout_s: float = 0.25) -> DurableStore:
        """Fail over: drain the ship queue (every delivered record
        applies before the epoch turns), claim + persist + announce the
        next epoch, fsync the WAL, and swap the freshest generation into
        the attached server.  Returns the now-primary store."""
        rec = obs_spans.recorder()
        span = rec.start("replication.promote", node=self.node_id,
                         applied=self.applied)
        # 1) drain: keep pulling until the link stays silent
        while self.poll(drain_timeout_s):
            pass
        expects(self.store is not None,
                "nothing to promote — this standby never bootstrapped")
        # 2) claim the next epoch (persisted before it is announced)
        epoch = self.fence.advance()
        self.promoted = True
        self._failovers.inc()
        self.store.wal.sync()
        # 3) announce: the deposed primary (if alive) and racing peers
        #    fence themselves on this token
        self._send(encode_message("fence", None, node=self.node_id,
                                  epoch=epoch, t=self.clock()),
                   what="fence")
        # 4) serve
        self._refresh_server(force=True)
        self._update_lag()
        rec.finish(span, epoch=epoch, lsn=self.applied)
        return self.store

    # -- lifecycle -----------------------------------------------------

    def start(self) -> "StandbyReplica":
        """Background poll loop (apply + ack + lease bookkeeping)."""
        expects(self._thread is None, "standby already started")
        self._stop.clear()
        self._thread = threading.Thread(target=self._run,
                                        name="raft-standby", daemon=True)
        self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                self.poll(0.05)
            except Exception as exc:  # noqa: BLE001 — keep following
                obs_spans.recorder().event("replication.poll_error",
                                           error=type(exc).__name__)

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
