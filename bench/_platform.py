"""Backend pinning shared by every bench entry point.

The rule lives exactly once here: ``RAFT_BENCH_PLATFORM`` (e.g. ``cpu``
for smoke runs and scaling probes) must be applied with a programmatic
``jax.config.update`` BEFORE backend initialization — a ``JAX_PLATFORMS``
env var alone is not enough because the axon PJRT plugin's sitecustomize
``register()`` overrides it.  (``bench.py``'s subprocess probe carries an
inlined copy in ``_PROBE_SRC``: it must stay self-contained source text.)
"""

from __future__ import annotations

import os


def pin_backend(argv=None) -> None:
    """Apply ``RAFT_BENCH_PLATFORM`` (or a ``--cpu`` alias in ``argv``).

    Call immediately after ``import jax`` and before anything touches a
    backend.  ``--cpu`` in ``argv`` wins over the env var.
    """
    platform = os.environ.get("RAFT_BENCH_PLATFORM")
    if argv and "--cpu" in argv:
        platform = "cpu"
    if platform:
        import jax

        jax.config.update("jax_platforms", platform)
