"""Offline CAGRA search tuning — pick ``(itopk_size, search_width)`` per
``(k, n)`` bucket by measurement, the trained-heuristic pattern of
``bench/tune_probe_block.py`` with one crucial difference: **this knob
changes results**, so the tuner is RECALL-GATED — a config only competes
on QPS after clearing the recall floor (default 0.95 @ k=10 against
exact ground truth).  Run on the target backend:

    python bench/tune_cagra.py [--quick] [--cpu]

Writes ``raft_tpu/neighbors/_cagra_search_table.json`` keyed
``cagra:{k.bit_length()}:{n.bit_length()}`` →  ``[itopk, width]`` —
``resolve_cagra_search``'s 0 (auto) consults it at call time with EXACT
bucket match only; absent entries fall back to the historical (64, 4).

Also writes the frontier A/B acceptance artifact
``bench/CAGRA_FRONTIER_<BACKEND>.json``: the frontier engine vs the
per-parent reference at the frontier-bound grid point (widest frontier).
The engines are bit-identical (tests/test_cagra_frontier.py), so the A/B
compares pure wall-clock at equal recall.
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

# persistent XLA executable cache (shared with bench.py): repeat runs
# on the same machine skip recompilation
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                      os.path.expanduser("~/.cache/raft_tpu_jax"))

import jax

from _platform import pin_backend

# MUST precede any backend use (see tune_select_k.py: the axon plugin's
# sitecustomize overrides a bare JAX_PLATFORMS env var)
pin_backend(sys.argv)

import numpy as np

from ann import ground_truth, make_clustered, measure_qps
from raft_tpu.neighbors import cagra
from raft_tpu.neighbors._packing import resolve_cagra_search
from raft_tpu.stats import neighborhood_recall

DIM, NQ, K = 64, 256, 10
RECALL_FLOOR = 0.95
ITOPK_GRID = [32, 64, 128]
WIDTH_GRID = [1, 2, 4, 8]
N_GRID = [40_000]
QUICK_N_GRID = [8_000]
# frontier-bound grid point: at the LARGE beam the per-parent engine's
# width ranked merges + O(itopk²) membership product dominate the
# iteration, which is exactly the cost the frontier fold deletes (at
# itopk=64 the distance einsum dominates and the engines tie)
AB_POINT = (128, 8)


def bucket_key(k: int, n: int) -> str:
    """Must mirror ``resolve_cagra_search``'s table key scheme exactly."""
    return f"cagra:{k.bit_length()}:{n.bit_length()}"


def kernel_sha() -> str:
    """Hash of the search-engine sources the measurements depend on —
    recorded in the sidecar (stale-table detection) and scoping the
    resume checkpoint."""
    import hashlib

    root = os.path.join(os.path.dirname(os.path.abspath(__file__)), "..")
    h = hashlib.sha256()
    for rel in ("raft_tpu/neighbors/cagra.py",
                "raft_tpu/neighbors/_packing.py",
                "raft_tpu/matrix/select_k.py",
                "raft_tpu/ops/pallas/select_k.py"):
        with open(os.path.join(root, rel), "rb") as f:
            h.update(f.read())
    return h.hexdigest()[:16]


def _measure(index, q, gt, itopk: int, width: int, impl: str) -> dict:
    sp = cagra.CagraSearchParams(itopk_size=itopk, search_width=width,
                                 search_impl=impl)
    run = lambda: cagra.search(index, q, K, sp)
    ids = np.asarray(run()[1])
    rec = float(neighborhood_recall(ids, gt))
    qps = measure_qps(run, int(q.shape[0]))
    _, _, iters, _ = cagra._resolve_search(sp, K, index.size)
    return {"itopk": itopk, "width": width, "iterations": iters,
            "recall": round(rec, 4), "qps": round(qps, 1)}


def main() -> None:
    quick = "--quick" in sys.argv
    n_grid = QUICK_N_GRID if quick else N_GRID
    sha = kernel_sha()
    backend = jax.default_backend()

    # resume checkpoint: decided buckets flush immediately and a re-run
    # under the SAME backend + kernel sources skips them
    ckpt_path = os.path.join(
        "/tmp", f"tune_cagra.{backend}.u{os.getuid()}.partial.json")
    table: dict = {}
    curves: dict = {}
    try:
        with open(ckpt_path) as f:
            prior = json.load(f)
        if prior.get("backend") == backend and prior.get("kernel_sha") == sha:
            table = prior.get("table", {})
            curves = prior.get("curves", {})
            print(f"resuming: {len(table)} buckets from checkpoint",
                  file=sys.stderr)
    except (OSError, ValueError):
        pass

    warned = []

    def flush_ckpt():
        try:
            with open(ckpt_path + ".tmp", "w") as f:
                json.dump({"backend": backend, "kernel_sha": sha,
                           "table": table, "curves": curves}, f)
            os.replace(ckpt_path + ".tmp", ckpt_path)
        except OSError as e:
            if not warned:
                warned.append(True)
                print(f"WARN: checkpoint flush failing ({e}); a mid-run "
                      f"kill will lose progress", file=sys.stderr)

    ab = None
    for n in n_grid:
        key = bucket_key(K, n)
        if key in table and key + ":ab" in curves:
            ab = curves[key + ":ab"]
            continue
        data = make_clustered(n + NQ, DIM, max(64, n // 200), seed=3,
                              scale=2.0)
        db, q = data[:n], data[n:]
        gt = ground_truth(q, db, K)
        index = cagra.build(db, cagra.CagraIndexParams(
            intermediate_graph_degree=64, graph_degree=32))
        points = []
        for itopk in ITOPK_GRID:
            for width in WIDTH_GRID:
                pt = _measure(index, q, gt, itopk, width, "frontier")
                points.append(pt)
                print(f"n={n} itopk={itopk:4d} w={width} "
                      f"→ recall={pt['recall']:.4f} qps={pt['qps']:.1f}")
        # recall gate first, QPS second; if nothing clears the floor the
        # most accurate config wins (auto must never silently pick a
        # fast-but-useless beam)
        cleared = [p for p in points if p["recall"] >= RECALL_FLOOR]
        pool = cleared or [max(points, key=lambda p: p["recall"])]
        best = max(pool, key=lambda p: p["qps"])
        table[key] = [best["itopk"], best["width"]]
        curves[key] = {"n": n, "k": K, "recall_floor": RECALL_FLOOR,
                       "points": points, "chosen": best}
        print(f"bucket {key} → itopk={best['itopk']} width={best['width']} "
              f"(recall {best['recall']}, {best['qps']} qps)")

        # frontier A/B at the frontier-bound point, same index + gt
        it_ab, w_ab = AB_POINT
        front = _measure(index, q, gt, it_ab, w_ab, "frontier")
        perp = _measure(index, q, gt, it_ab, w_ab, "per_parent")
        ab = {"rows": n, "dim": DIM, "nq": NQ, "k": K,
              "itopk_size": it_ab, "search_width": w_ab,
              "iterations": front["iterations"],
              "frontier": {"recall": front["recall"], "qps": front["qps"]},
              "per_parent": {"recall": perp["recall"], "qps": perp["qps"]},
              "speedup": round(front["qps"] / perp["qps"], 3)}
        curves[key + ":ab"] = ab
        flush_ckpt()
        print(f"A/B @ itopk={it_ab} w={w_ab}: frontier {front['qps']:.1f} "
              f"qps vs per_parent {perp['qps']:.1f} qps "
              f"({ab['speedup']:.2f}x, recall {front['recall']} vs "
              f"{perp['recall']})")

    out = os.path.join(os.path.dirname(os.path.abspath(__file__)), "..",
                       "raft_tpu", "neighbors", "_cagra_search_table.json")
    if backend != "tpu" and "--force" not in sys.argv:
        # an off-TPU run must never clobber the table the TPU search
        # paths consult (same rule as the probe_block tuner)
        out = out.replace(".json", f".{backend}.json")
        print(f"non-TPU backend: writing to {os.path.basename(out)} "
              f"(--force overrides)", file=sys.stderr)
    with open(out, "w") as f:
        json.dump(table, f, indent=1, sort_keys=True)
        f.write("\n")

    import datetime

    with open(out.replace(".json", ".meta.json"), "w") as f:
        json.dump({"backend": backend,
                   "date": datetime.date.today().isoformat(),
                   "kernel_sha": sha,
                   "recall_floor": RECALL_FLOOR,
                   "n_entries": len(table),
                   "curves": curves}, f, indent=1, sort_keys=True)
        f.write("\n")

    ab_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           f"CAGRA_FRONTIER_{backend.upper()}.json")
    with open(ab_path, "w") as f:
        json.dump({"backend": backend, "kernel_sha": sha,
                   "date": datetime.date.today().isoformat(),
                   "note": "frontier-blocked vs per-parent engine at the "
                           "frontier-bound grid point; bit-identical "
                           "results by construction "
                           "(tests/test_cagra_frontier.py)",
                   "ab": ab}, f, indent=1, sort_keys=True)
        f.write("\n")
    try:
        os.remove(ckpt_path)  # spent: the final table supersedes it
    except OSError:
        pass
    print(f"wrote {len(table)} entries → {os.path.normpath(out)}")
    print(f"A/B artifact → {os.path.normpath(ab_path)}")
    # the auto path must be able to see what we just measured
    it, w = resolve_cagra_search(0, 0, K, n_grid[-1])
    assert it >= K and w >= 1


if __name__ == "__main__":
    main()
