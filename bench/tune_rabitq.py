"""Offline RaBitQ search tuning — pick ``(rerank_k, probe_block)`` per
``(k, n_probes, list cap)`` bucket, the recall-gated sibling of
``bench/tune_probe_block.py``.

Unlike ``probe_block`` (bit-identical at every value), ``rerank_k``
changes RESULTS: it gates which candidates reach the exact rerank, so
the knob must be tuned against a recall target, not wall-clock alone
(the ``resolve_cagra_search`` model).  Per bucket:

1. measure the bucket's recall *ceiling* — ``rerank_k`` = everything
   probed (the estimator then only orders the exact rerank's input, so
   the ceiling is the probe-coverage recall);
2. pick the smallest power-of-two-ish ``rerank_k`` whose recall is
   within ``GATE`` of that ceiling (coverage losses don't count against
   the estimator);
3. at that ``rerank_k``, pick ``probe_block`` by pure wall-clock.

Run on the target backend (real TPU for production numbers):

    python bench/tune_rabitq.py [--quick] [--cpu]

Writes ``raft_tpu/neighbors/_rabitq_tune_table.json`` (or the
``.{backend}.json`` variant off-TPU) keyed
``ivf_rabitq:k.bit_length():n_probes.bit_length():cap.bit_length()``
with ``{"rerank_k": R, "probe_block": B}`` entries —
``resolve_rerank_k`` / ``_resolve_probe_block`` consult it at call time
(``kernel_sha``-scoped: a table measured against older scan sources is
ignored).  A ``.meta.json`` sidecar records provenance.
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                      os.path.expanduser("~/.cache/raft_tpu_jax"))

import jax

from _platform import pin_backend

# MUST precede any backend use (see tune_select_k.py: the axon plugin's
# sitecustomize overrides a bare JAX_PLATFORMS env var)
pin_backend(sys.argv)

import numpy as np

from _timing import timeit as _time
from ann import ground_truth, make_clustered
from raft_tpu.neighbors import ivf_rabitq
from raft_tpu.ops.blocked_scan import scan_kernel_sha
from raft_tpu.stats import neighborhood_recall

ROWS, DIM, NQ, K = 120_000, 64, 256, 10
QUICK_ROWS = 30_000                       # smoke the machinery, not the numbers
BLOCK_CANDIDATES = [1, 2, 4, 8, 16]
# smallest rerank_k within GATE of the bucket's own probe-coverage
# ceiling wins — an absolute floor would conflate estimator quality with
# how many lists the bucket probes
GATE = 0.005
# rerank everything probed IS the ceiling definition, but past a few
# thousand rows the estimator's ordering is long since saturated and the
# exact-gather cost explodes (64 probes × cap 1407 ≈ 90k rows/query) —
# cap the ceiling measurement where the curve is provably flat
CEILING_CAP = 4096
CONFIGS = [(512, [8, 16, 64]), (128, [8, 16]), (32, [8, 16])]
QUICK_CONFIGS = [(512, [16, 64]), (128, [64])]


def bucket_key(k: int, n_probes: int, cap: int) -> str:
    """Must mirror ``ivf_rabitq._tune_entry``'s key scheme exactly."""
    return f"ivf_rabitq:{k.bit_length()}:{n_probes.bit_length()}" \
           f":{cap.bit_length()}"


def _rerank_grid(k: int, total: int):
    out, r = [], max(32, 2 * k)
    while r < total:
        out.append(r)
        r *= 2
    out.append(total)
    return out


def main() -> None:
    quick = "--quick" in sys.argv
    configs = QUICK_CONFIGS if quick else CONFIGS
    rows = QUICK_ROWS if quick else ROWS
    sha = scan_kernel_sha()
    backend = jax.default_backend()

    rng = np.random.default_rng(0)
    x = jax.device_put(np.asarray(make_clustered(
        rows, DIM, max(64, rows // 1000), seed=0, scale=2.0)))
    q = jax.device_put(np.asarray(make_clustered(
        NQ, DIM, max(64, rows // 1000), seed=0, scale=2.0, point_seed=1)))
    del rng
    gt = ground_truth(q, x, K)

    entries: dict = {}
    timings: dict = {}
    for n_lists, probe_grid in configs:
        index = ivf_rabitq.build(x, ivf_rabitq.IvfRabitqIndexParams(
            n_lists=n_lists, list_cap_ratio=1.5,
            kmeans_trainset_fraction=0.05, seed=0))
        cap = index.list_cap
        for n_probes in probe_grid:
            total = min(n_probes * cap, CEILING_CAP)

            def recall_at(rk: int) -> float:
                p = ivf_rabitq.IvfRabitqSearchParams(
                    n_probes=n_probes, rerank_k=rk)
                _, ids = ivf_rabitq.search(index, q, K, p)
                return float(neighborhood_recall(np.asarray(ids), gt))

            ceiling = recall_at(total)
            grid = _rerank_grid(K, total)
            chosen, curve = total, {}
            for rk in grid:
                r = recall_at(rk)
                curve[str(rk)] = round(r, 4)
                if r >= ceiling - GATE:
                    chosen = rk
                    break
            best_b, best_t, tcurve = 1, float("inf"), {}
            for pb in BLOCK_CANDIDATES:
                if pb > n_probes:
                    continue
                p = ivf_rabitq.IvfRabitqSearchParams(
                    n_probes=n_probes, rerank_k=chosen, probe_block=pb)
                t = _time(lambda p=p: ivf_rabitq.search(index, q, K, p))
                tcurve[str(pb)] = t
                if t < best_t:
                    best_b, best_t = pb, t
            key = bucket_key(K, n_probes, cap)
            entries[key] = {"rerank_k": int(chosen), "probe_block": best_b}
            timings[key] = {"n_lists": n_lists, "cap": cap,
                            "n_probes": n_probes, "ceiling": round(ceiling, 4),
                            "recall_curve": curve, "block_curve_s": tcurve}
            print(f"n_lists={n_lists:4d} cap={cap:5d} p={n_probes:3d} → "
                  f"rerank_k={chosen} (ceiling {ceiling:.4f}) "
                  f"B={best_b} ({best_t * 1e3:.1f} ms)")

    out = os.path.join(os.path.dirname(os.path.abspath(__file__)), "..",
                       "raft_tpu", "neighbors", "_rabitq_tune_table.json")
    if backend != "tpu" and "--force" not in sys.argv:
        # an off-TPU run must never clobber the table the TPU search
        # paths consult (same rule as the probe_block tuner)
        out = out.replace(".json", f".{backend}.json")
        print(f"non-TPU backend: writing to {os.path.basename(out)} "
              f"(--force overrides)", file=sys.stderr)
    with open(out, "w") as f:
        json.dump({"kernel_sha": sha, "entries": entries}, f,
                  indent=1, sort_keys=True)
        f.write("\n")

    import datetime

    with open(out.replace(".json", ".meta.json"), "w") as f:
        json.dump({"backend": backend,
                   "date": datetime.date.today().isoformat(),
                   "kernel_sha": sha,
                   "gate": GATE,
                   "rows": rows, "dim": DIM, "nq": NQ, "k": K,
                   "n_entries": len(entries),
                   "timings": timings}, f, indent=1, sort_keys=True)
        f.write("\n")
    print(f"wrote {len(entries)} entries → {os.path.normpath(out)}")

    # the auto path must be able to see what we just measured
    ivf_rabitq._rabitq_tune_table.cache_clear()
    r = ivf_rabitq.resolve_rerank_k(0, K, 64, 512)
    assert r >= K


if __name__ == "__main__":
    main()
