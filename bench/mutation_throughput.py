"""Mutation throughput A/B — online ``extend()`` vs rebuild-from-scratch.

The mutable-lifecycle question: when a batch of new rows arrives, is the
online insert path (fused slab-donating chunk steps, one executable per
index shape) actually cheaper than rebuilding the index?  Measured per
IVF family over a grid of insert-batch sizes:

* **extend** — steady-state ``extend(index, batch)`` wall time (the
  executable is pre-warmed by the timing harness; bit-identical results
  are asserted in ``tests/test_mutation.py``, so this is pure
  wall-clock);
* **rebuild** — ``build()`` over the union corpus, the only alternative
  an immutable index offers;
* **delete** — ``mutation.delete`` of 1k ids (tombstone mask update;
  O(mask), slab-free) and **compact** — rewriting the slabs after
  tombstoning 30% of the corpus (the reclaim path a background
  ``swap_index(build=...)`` runs).

    python bench/mutation_throughput.py [--quick] [--cpu]

Writes ``bench/MUTATION_<BACKEND>.json``.
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                      os.path.expanduser("~/.cache/raft_tpu_jax"))

import jax

from _platform import pin_backend

# MUST precede any backend use (see _platform.py)
pin_backend(sys.argv)

import time

import numpy as np

from _timing import sync, timeit
from raft_tpu.neighbors import ivf_flat, ivf_pq, mutation

QUICK = "--quick" in sys.argv
ROWS = 20_000 if QUICK else 200_000
DIM = 64
N_LISTS = max(16, int(np.sqrt(ROWS)))
BATCHES = (1024, 16384)
REPS = 3


def _build(family, x):
    if family == "ivf_flat":
        return ivf_flat.build(x, ivf_flat.IvfFlatIndexParams(
            n_lists=N_LISTS, kmeans_n_iters=4))
    return ivf_pq.build(x, ivf_pq.IvfPqIndexParams(
        n_lists=N_LISTS, pq_dim=16, pq_bits=4, kmeans_n_iters=4,
        store_recon=False))


def _extend(family, idx, batch, ids):
    mod = ivf_flat if family == "ivf_flat" else ivf_pq
    return mod.extend(idx, batch, ids)


def run() -> dict:
    rng = np.random.default_rng(0)
    x = rng.standard_normal((ROWS, DIM)).astype(np.float32)
    results = []
    for family in ("ivf_flat", "ivf_pq"):
        idx = _build(family, x)
        sync(idx.counts)
        for b in BATCHES:
            batch = rng.standard_normal((b, DIM)).astype(np.float32)
            ids = np.arange(ROWS, ROWS + b)
            # steady state: extend returns a NEW index (the caller's
            # slabs survive via the COW first chunk), so repeated calls
            # on the same base index time the same work
            ext_s = timeit(lambda: _extend(family, idx, batch, ids),
                           reps=REPS)
            union = np.concatenate([x, batch], axis=0)
            reb_s = timeit(lambda: _build(family, union), reps=REPS)
            results.append({
                "family": family, "rows": ROWS, "dim": DIM,
                "n_lists": N_LISTS, "batch": b,
                "extend_s": round(ext_s, 4),
                "extend_rows_per_s": int(b / ext_s),
                "rebuild_s": round(reb_s, 4),
                "speedup_vs_rebuild": round(reb_s / ext_s, 1),
            })
            print(json.dumps(results[-1]), flush=True)
        # tombstone + compact: mask update is O(mask); compact rewrites
        # the slabs — cost swept over the dead fraction (the trigger knob)
        dead = rng.permutation(ROWS)
        sync(mutation.delete(idx, [0]).keep.words)  # warm the mask ops
        t0 = time.perf_counter()
        view = mutation.delete(idx, np.sort(dead[:1024]).astype(np.int32))
        sync(view.keep.words)
        delete_s = time.perf_counter() - t0
        for frac in (0.1, 0.3, 0.5):
            view = mutation.delete(
                idx, np.sort(dead[:int(ROWS * frac)]).astype(np.int32))
            sync(view.keep.words)
            compact_s = timeit(lambda: mutation.compact(view), reps=REPS)
            results.append({
                "family": family, "rows": ROWS,
                "delete_1k_s": round(delete_s, 4),
                "tombstoned_frac": frac,
                "compact_s": round(compact_s, 4),
                "compact_rows_per_s": int(ROWS * (1 - frac) / compact_s),
            })
            print(json.dumps(results[-1]), flush=True)
    out = {
        "bench": "mutation_throughput",
        "backend": jax.default_backend(),
        "mode": "quick" if QUICK else "full",
        "reps": REPS,
        "note": "extend is the online-insert path (COW-first/donate-rest"
                " fused chunk steps; bit-identical to rebuild per"
                " tests/test_mutation.py); rebuild is the immutable"
                " alternative; compact rewrites slabs after tombstoning"
                " 30% of rows",
        "results": results,
    }
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        f"MUTATION_{jax.default_backend().upper()}.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=2)
        f.write("\n")
    print(f"wrote {path}", flush=True)
    return out


if __name__ == "__main__":
    run()
