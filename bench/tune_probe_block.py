"""Offline probe_block tuning — pick the probe-block size B per
``(family, n_probes, list cap)`` bucket by measurement, the same
trained-heuristic pattern as ``bench/tune_select_k.py``.

Blocked and per-probe scans return bit-identical results (pinned by
``tests/test_probe_block.py``), so this tuner compares pure wall-clock —
no recall gate.  Run on the target backend (real TPU for production
numbers):

    python bench/tune_probe_block.py [--quick] [--cpu]

Writes ``raft_tpu/neighbors/_probe_block_table.json`` keyed by
``family:n_probes.bit_length():cap.bit_length()`` —
``resolve_probe_block``'s ``probe_block=0`` (auto) consults it at call
time; absent entries fall back to the candidates-per-merge heuristic.
Also writes the probe-bound A/B acceptance artifact
``bench/PROBE_BLOCK_<BACKEND>.json`` (per-probe vs blocked wall-clock at
the highest-probe config of the grid).
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

# persistent XLA executable cache (shared with bench.py): repeat runs
# on the same machine skip recompilation
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                      os.path.expanduser("~/.cache/raft_tpu_jax"))

import jax

from _platform import pin_backend

# MUST precede any backend use (see tune_select_k.py: the axon plugin's
# sitecustomize overrides a bare JAX_PLATFORMS env var)
pin_backend(sys.argv)

import numpy as np

from _timing import timeit as _time
from raft_tpu.neighbors import ivf_flat, ivf_pq
from raft_tpu.neighbors._packing import resolve_probe_block

ROWS, DIM, NQ, K = 120_000, 64, 256, 10
BLOCK_CANDIDATES = [1, 2, 4, 8, 16, 32]
# (n_lists, n_probes grid): spans cap buckets ~2800 (32 lists) down to
# ~350 (512 lists), and the shortlist-bound -> probe-bound probe range
CONFIGS = [(512, [8, 16, 64]), (128, [8, 16, 64]), (32, [8, 16])]
QUICK_CONFIGS = [(512, [16, 64]), (128, [64])]


def bucket_key(family: str, n_probes: int, cap: int) -> str:
    """Must mirror ``resolve_probe_block``'s table key scheme exactly."""
    return f"{family}:{n_probes.bit_length()}:{cap.bit_length()}"


def kernel_sha() -> str:
    """Hash of the scan + merge sources the timings depend on — recorded
    in the sidecar (stale-table detection) and scoping the resume
    checkpoint."""
    import hashlib

    root = os.path.join(os.path.dirname(os.path.abspath(__file__)), "..")
    h = hashlib.sha256()
    for rel in ("raft_tpu/neighbors/ivf_flat.py",
                "raft_tpu/neighbors/ivf_pq.py",
                "raft_tpu/neighbors/_packing.py",
                "raft_tpu/neighbors/brute_force.py",
                "raft_tpu/matrix/select_k.py"):
        with open(os.path.join(root, rel), "rb") as f:
            h.update(f.read())
    return h.hexdigest()[:16]


def _build_indexes(n_lists: int, x):
    fi = ivf_flat.build(x, ivf_flat.IvfFlatIndexParams(
        n_lists=n_lists, list_cap_ratio=1.5, kmeans_trainset_fraction=0.05,
        seed=0))
    pi = ivf_pq.build(x, ivf_pq.IvfPqIndexParams(
        n_lists=n_lists, pq_dim=16, list_cap_ratio=1.5,
        kmeans_trainset_fraction=0.05, seed=0))
    return {"ivf_flat": fi, "ivf_pq": pi}


def _searcher(family: str, index, q, n_probes: int, pb: int):
    if family == "ivf_flat":
        p = ivf_flat.IvfFlatSearchParams(n_probes=n_probes, probe_block=pb)
        return lambda: ivf_flat.search(index, q, K, p)
    p = ivf_pq.IvfPqSearchParams(n_probes=n_probes, mode="lut",
                                 probe_block=pb)
    return lambda: ivf_pq.search(index, q, K, p)


def main() -> None:
    quick = "--quick" in sys.argv
    configs = QUICK_CONFIGS if quick else CONFIGS
    sha = kernel_sha()
    backend = jax.default_backend()

    # resume checkpoint: decided buckets flush immediately and a re-run
    # under the SAME backend + kernel sources skips them (tunnel-wedge
    # recovery, same story as tune_select_k.py)
    ckpt_path = os.path.join(
        "/tmp", f"tune_probe_block.{backend}.u{os.getuid()}.partial.json")
    table: dict = {}
    timings: dict = {}
    try:
        with open(ckpt_path) as f:
            prior = json.load(f)
        if prior.get("backend") == backend and prior.get("kernel_sha") == sha:
            table = prior.get("table", {})
            timings = prior.get("timings", {})
            print(f"resuming: {len(table)} buckets from checkpoint",
                  file=sys.stderr)
    except (OSError, ValueError):
        pass

    warned = []

    def flush_ckpt():
        try:
            with open(ckpt_path + ".tmp", "w") as f:
                json.dump({"backend": backend, "kernel_sha": sha,
                           "table": table, "timings": timings}, f)
            os.replace(ckpt_path + ".tmp", ckpt_path)
        except OSError as e:
            if not warned:
                warned.append(True)
                print(f"WARN: checkpoint flush failing ({e}); a mid-run "
                      f"kill will lose progress", file=sys.stderr)

    rng = np.random.default_rng(0)
    x = (rng.standard_normal((ROWS, DIM))
         + 3 * rng.standard_normal((256, DIM))[rng.integers(0, 256, ROWS)]
         ).astype(np.float32)
    q = jax.device_put(x[:NQ] + 0.1)

    # resume identity is (family, n_lists, n_probes) — the bucket key
    # alone can't gate the build loop since cap is unknown until built
    decided = {(k.split(":")[0], t["n_lists"], t["n_probes"])
               for k, t in timings.items()}
    for n_lists, probe_grid in configs:
        if all((family, n_lists, p) in decided
               for family in ("ivf_flat", "ivf_pq") for p in probe_grid):
            continue
        indexes = _build_indexes(n_lists, x)
        for family, index in indexes.items():
            cap = index.list_cap
            for n_probes in probe_grid:
                key = bucket_key(family, n_probes, cap)
                if (family, n_lists, n_probes) in decided:
                    continue
                best_b, best_t, curve = None, float("inf"), {}
                for pb in BLOCK_CANDIDATES:
                    if pb > n_probes:
                        continue
                    t = _time(_searcher(family, index, q, n_probes, pb))
                    curve[str(pb)] = t
                    if t < best_t:
                        best_b, best_t = pb, t
                table[key] = best_b
                timings[key] = {"n_lists": n_lists, "cap": cap,
                                "n_probes": n_probes, "curve_s": curve}
                flush_ckpt()
                print(f"{family:9s} n_lists={n_lists:4d} cap={cap:5d} "
                      f"p={n_probes:3d} → B={best_b} "
                      f"({best_t * 1e3:.1f} ms; B=1 "
                      f"{curve.get('1', float('nan')) * 1e3:.1f} ms)")

    out = os.path.join(os.path.dirname(os.path.abspath(__file__)), "..",
                       "raft_tpu", "neighbors", "_probe_block_table.json")
    if backend != "tpu" and "--force" not in sys.argv:
        # an off-TPU run must never clobber the table the TPU search
        # paths consult (same rule as the select_k tuner)
        out = out.replace(".json", f".{backend}.json")
        print(f"non-TPU backend: writing to {os.path.basename(out)} "
              f"(--force overrides)", file=sys.stderr)
    with open(out, "w") as f:
        json.dump(table, f, indent=1, sort_keys=True)

    import datetime

    with open(out.replace(".json", ".meta.json"), "w") as f:
        json.dump({"backend": backend,
                   "date": datetime.date.today().isoformat(),
                   "kernel_sha": sha,
                   "n_entries": len(table)}, f)
        f.write("\n")

    # probe-bound A/B acceptance artifact: per-probe vs blocked at the
    # highest-probe bucket measured (>= 64 probes unless --quick trimmed
    # the grid) — the headline "blocked beats per-probe" number
    ab = {}
    for key, t in timings.items():
        family = key.split(":")[0]
        p = t["n_probes"]
        if p < max(pg for _, g in configs for pg in g):
            continue
        curve = t["curve_s"]
        best_b = str(table[key])
        if "1" in curve and best_b in curve:
            ab[key] = {
                "n_lists": t["n_lists"], "cap": t["cap"], "n_probes": p,
                "nq": NQ, "k": K, "rows": ROWS, "dim": DIM,
                "per_probe_s": curve["1"],
                "blocked_s": curve[best_b], "probe_block": table[key],
                "speedup": curve["1"] / curve[best_b],
                "curve_s": curve,
            }
    ab_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           f"PROBE_BLOCK_{backend.upper()}.json")
    with open(ab_path, "w") as f:
        json.dump({"backend": backend, "kernel_sha": sha,
                   "note": "per-probe (B=1) vs blocked wall-clock at the "
                           "probe-bound grid point; bit-identical results "
                           "by construction (tests/test_probe_block.py)",
                   "configs": ab}, f, indent=1, sort_keys=True)
        f.write("\n")
    try:
        os.remove(ckpt_path)  # spent: the final table supersedes it
    except OSError:
        pass
    print(f"wrote {len(table)} entries → {os.path.normpath(out)}")
    print(f"A/B artifact → {os.path.normpath(ab_path)}")
    # the auto path must be able to see what we just measured
    r = resolve_probe_block(0, 64, 512, "ivf_flat")
    assert r >= 1


if __name__ == "__main__":
    main()
