"""CAGRA graph-quality study at ≥1M rows (VERDICT r2 next #6).

Measures recall@fixed-effort of three search substrates over the same
dataset and query set:

* the **optimized** graph (rank-merge forward/reverse union — the CAGRA
  detour-pruning stand-in, ``neighbors.cagra.optimize_graph``),
* the **raw kNN** graph it was built from (same degree),
* **brute force** (recall 1.0 by construction — the QPS denominator).

Run on the target backend:  ``python bench/cagra_quality.py [--rows N]``
Writes ``bench/CAGRA_QUALITY.json`` (committed each round) with the table;
the companion gate lives in ``tests/test_cagra.py``
(``test_graph_quality_1m_rows``, slow-marked).
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

# persistent XLA executable cache (shared with bench.py): repeat runs
# on the same machine skip recompilation
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                      os.path.expanduser("~/.cache/raft_tpu_jax"))

import jax

from _platform import pin_backend

pin_backend(sys.argv)

import jax.numpy as jnp
import numpy as np

from ann import ground_truth, make_clustered, measure_qps

OUT = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                   "CAGRA_QUALITY.json")


def main() -> None:
    global OUT
    rows = 1_000_000
    if "--rows" in sys.argv:
        rows = int(sys.argv[sys.argv.index("--rows") + 1])
    if "--out" in sys.argv:  # scaling probes must not clobber the artifact
        OUT = sys.argv[sys.argv.index("--out") + 1]
    d, nq, k = 96, 2000, 10
    n_clusters = max(64, rows // 1000)

    from raft_tpu.neighbors import cagra

    t0 = time.time()
    data = make_clustered(rows + nq, d, n_clusters, seed=3, scale=2.0)
    db, q = data[:rows], data[rows:]
    gt = ground_truth(q, db, k)
    print(f"data+gt: {time.time() - t0:.1f}s", file=sys.stderr)

    t0 = time.time()
    p = cagra.CagraIndexParams(
        intermediate_graph_degree=64, graph_degree=32,
        build_algo="ivf" if rows > 200_000 else "brute_force")  # routers auto
    idx = cagra.build(db, p)
    build_s = time.time() - t0
    print(f"build: {build_s:.1f}s", file=sys.stderr)

    # raw-graph baseline: same beam search over the UNoptimized kNN graph,
    # truncated to the same degree (isolates the optimize step's value)
    from raft_tpu.neighbors import ivf_flat
    ip = ivf_flat.IvfFlatIndexParams(
        n_lists=max(16, int(np.sqrt(rows))), seed=p.seed)
    fidx = ivf_flat.build(db, ip)
    _, raw_nbrs = ivf_flat.search(
        fidx, db, p.graph_degree + 1,
        ivf_flat.IvfFlatSearchParams(n_probes=16))
    raw_graph = cagra._drop_self(jnp.asarray(raw_nbrs), p.graph_degree)
    raw_idx = cagra.CagraIndex(idx.dataset, raw_graph, idx.router_centroids,
                               idx.router_nodes, idx.metric)

    import datetime

    # full search scope in the artifact header: a recall@effort point is
    # meaningless without the engine and iteration budget that produced it
    results = {"rows": rows, "dim": d, "k": k, "build_s": round(build_s, 1),
               "backend": jax.default_backend(),
               "search_impl": cagra.CagraSearchParams().search_impl,
               "date": datetime.date.today().isoformat(), "points": []}
    for itopk, width in [(32, 4), (64, 4), (64, 8), (128, 8)]:
        sp = cagra.CagraSearchParams(itopk_size=itopk, search_width=width)
        _, _, iters, _ = cagra._resolve_search(sp, k, rows)
        row = {"itopk_size": itopk, "search_width": width,
               "iterations": iters}
        for name, ix in (("optimized", idx), ("raw_knn", raw_idx)):
            run = lambda: cagra.search(ix, q, k, sp)
            from ann import _fetch
            ids = _fetch(run())[1]
            from raft_tpu.stats import neighborhood_recall
            rec = float(neighborhood_recall(np.asarray(ids), gt))
            qps = measure_qps(run, nq)
            row[name] = {"recall": round(rec, 4), "qps": round(qps, 1)}
        results["points"].append(row)
        print(json.dumps(row))

    with open(OUT, "w") as f:
        json.dump(results, f, indent=1)
        f.write("\n")
    print(f"wrote {OUT}", file=sys.stderr)


if __name__ == "__main__":
    main()
