"""Build-throughput A/B — pipelined chunk engine vs the per-op loop.

Measures the streaming phase of ``build_chunked`` (training excluded —
it is byte-identical in both engines) as rows/s, plus end-to-end
time-to-index, for the three IVF families (ivf_flat, ivf_pq,
ivf_rabitq — the RaBitQ encode is codebook-free, so its stream is the
flat pipeline plus one rotation einsum + sign-pack per chunk):

* **perop** — the pre-pipelining reference loop kept verbatim as
  ``_stream_perop`` / ``_pq_stream_perop``: blocking ``jnp.asarray``
  H2D, separate assign / residual / encode / scatter dispatches, tail
  chunk at its own shape (one extra compile).
* **pipelined** — the PR 4 engine: fixed-shape padded chunks, one fused
  slab-donating jitted program per chunk
  (``_flat_chunk_step`` / ``_pq_chunk_step``), chunk t+1 staged with a
  non-blocking ``device_put`` while chunk t computes.

Both engines produce BIT-identical indexes
(tests/test_chunked_builds.py), so this is pure wall-clock — no recall
gate.  The acceptance grid point is 1M rows; on CPU the win comes from
collapsing per-chunk dispatch overhead and letting XLA fuse the whole
chunk program (single-stream backend — the H2D overlap is free but
empty); on TPU the overlap additionally hides the PCIe chunk copy.

    python bench/build_throughput.py [--quick] [--cpu]

Writes ``bench/BUILD_THROUGHPUT_<BACKEND>.json``.
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                      os.path.expanduser("~/.cache/raft_tpu_jax"))

import jax

from _platform import pin_backend

# MUST precede any backend use (see _platform.py: the axon plugin's
# sitecustomize overrides a bare JAX_PLATFORMS env var)
pin_backend(sys.argv)

import time

import numpy as np

from _timing import sync, timeit
from raft_tpu.neighbors import ivf_flat, ivf_pq, ivf_rabitq, ooc

# (family, rows, dim, n_lists, chunk_rows): the 1M acceptance point runs
# at a small chunk size — the dispatch-bound regime the fusion targets
# (the default 65536-row chunks amortize dispatch so well that both
# engines converge to the same compute bound) — plus one default-chunk
# point per family so the artifact also records the compute-bound end.
# ivf_pq runs 4-bit codebooks (the pack_codes deployment shape): with the
# encode compute small, the per-op loop's eager residual gather/subtract
# and extra dispatches dominate, which is exactly what the fusion removes.
GRID = [
    ("ivf_flat", 1_000_000, 64, 64, 128),
    ("ivf_flat", 1_000_000, 64, 64, 65536),
    ("ivf_pq", 1_000_000, 64, 64, 128),
    ("ivf_pq", 1_000_000, 64, 64, 65536),
    ("ivf_rabitq", 1_000_000, 64, 64, 128),
    ("ivf_rabitq", 1_000_000, 64, 64, 65536),
    # ooc = the rabitq device stream + shard writes riding the staging
    # thread; the A/B prices whether the disk write hides behind compute
    ("ooc", 1_000_000, 64, 64, 128),
    ("ooc", 1_000_000, 64, 64, 65536),
]
QUICK_GRID = [("ivf_flat", 100_000, 64, 64, 128),
              ("ivf_pq", 100_000, 64, 64, 128),
              ("ivf_rabitq", 100_000, 64, 64, 128),
              ("ooc", 100_000, 64, 64, 128)]
# training is byte-identical in both engines and excluded from the
# timings — keep it short so the bench spends its budget on the streams
TRAIN_FRACTION, TRAIN_ITERS = 0.02, 5
REPS = 3


def _params(family: str, n_lists: int):
    if family == "ivf_flat":
        return ivf_flat.IvfFlatIndexParams(
            n_lists=n_lists, kmeans_trainset_fraction=TRAIN_FRACTION,
            kmeans_n_iters=TRAIN_ITERS, seed=0)
    if family == "ivf_rabitq":
        return ivf_rabitq.IvfRabitqIndexParams(
            n_lists=n_lists, kmeans_trainset_fraction=TRAIN_FRACTION,
            kmeans_n_iters=TRAIN_ITERS, seed=0)
    if family == "ooc":
        return ooc.OocIndexParams(
            n_lists=n_lists, kmeans_trainset_fraction=TRAIN_FRACTION,
            kmeans_n_iters=TRAIN_ITERS, seed=0)
    return ivf_pq.IvfPqIndexParams(
        n_lists=n_lists, pq_dim=16, pq_bits=4,
        kmeans_trainset_fraction=TRAIN_FRACTION,
        kmeans_n_iters=TRAIN_ITERS, pq_kmeans_n_iters=5, seed=0)


def _streams(family: str, x, p, chunk_rows: int):
    """Return zero-arg thunks (perop, pipelined) over a shared trained
    quantizer — streaming only, training off the clock."""
    n, d = x.shape
    if family == "ivf_flat":
        cap = max(1, int(np.ceil(p.list_cap_ratio * n / p.n_lists)))
        cents = ivf_flat._coarse_train_chunked(x, p, n)
        sync(cents)
        dt = cents.dtype
        perop = lambda: ivf_flat._stream_perop(
            x, cents, p, n, cap, chunk_rows, None, dt)
        pipe = lambda: ivf_flat._stream_pipelined(
            x, cents, p, n, cap, chunk_rows, None, dt)
        return perop, pipe
    if family == "ivf_rabitq":
        cap = max(1, int(np.ceil(p.list_cap_ratio * n / p.n_lists)))
        cents = ivf_flat._coarse_train_chunked(x, p, n)
        rot = ivf_rabitq._rotation(d, p.seed)
        sync((cents, rot))
        dt = cents.dtype
        perop = lambda: ivf_rabitq._stream_perop(
            x, cents, rot, p, n, cap, chunk_rows, None, dt)
        pipe = lambda: ivf_rabitq._stream_pipelined(
            x, cents, rot, p, n, cap, chunk_rows, None, dt)
        return perop, pipe
    if family == "ooc":
        import shutil
        import tempfile

        from raft_tpu.io.shards import ShardWriter

        cap = max(1, int(np.ceil(p.list_cap_ratio * n / p.n_lists)))
        cents = ivf_flat._coarse_train_chunked(x, p, n)
        rot = ivf_rabitq._rotation(d, p.seed)
        sync((cents, rot))
        dt = cents.dtype

        def _with_writer(stream):
            # fresh shard dir per rep: the stream writes the store as a
            # side effect, so reps must not append to the same shards
            def run():
                root = tempfile.mkdtemp(prefix="ooc_bt_")
                try:
                    w = ShardWriter(os.path.join(root, "s"), d,
                                    np.dtype("float32"), p.rows_per_shard)
                    out = stream(x, cents, rot, p, n, cap, chunk_rows, w, dt)
                    w.close()
                    return out
                finally:
                    shutil.rmtree(root, ignore_errors=True)
            return run

        return (_with_writer(ooc._stream_perop),
                _with_writer(ooc._stream_pipelined))
    m = p.pq_dim
    cap = max(1, int(np.ceil(p.list_cap_ratio * n / p.n_lists)))
    cents, cbs = ivf_pq._pq_train_chunked(x, p, n, m, 1 << p.pq_bits)
    sync((cents, cbs))
    perop = lambda: ivf_pq._pq_stream_perop(
        x, cents, cbs, p, n, m, cap, chunk_rows, None)
    pipe = lambda: ivf_pq._pq_stream_pipelined(
        x, cents, cbs, p, n, m, cap, chunk_rows, None)
    return perop, pipe


def main() -> None:
    quick = "--quick" in sys.argv
    grid = QUICK_GRID if quick else GRID
    backend = jax.default_backend()
    rng = np.random.default_rng(0)
    results = []
    x_cache = {}
    for family, rows, dim, n_lists, chunk_rows in grid:
        if x_cache.get("shape") != (rows, dim):
            x_cache = {"shape": (rows, dim),
                       "x": rng.standard_normal((rows, dim)).astype(np.float32)}
        x = x_cache["x"]
        p = _params(family, n_lists)
        perop, pipe = _streams(family, x, p, chunk_rows)
        t_perop = timeit(perop, REPS)
        t_pipe = timeit(pipe, REPS)
        if family == "ooc":
            import shutil
            import tempfile

            root = tempfile.mkdtemp(prefix="ooc_bt_")
            t0 = time.perf_counter()
            sync(ooc.build_chunked(x, p, store_path=os.path.join(root, "s"),
                                   chunk_rows=chunk_rows).counts)
            tti = time.perf_counter() - t0
            shutil.rmtree(root, ignore_errors=True)
        else:
            build = {"ivf_flat": ivf_flat.build_chunked,
                     "ivf_pq": ivf_pq.build_chunked,
                     "ivf_rabitq": ivf_rabitq.build_chunked}[family]
            t0 = time.perf_counter()
            sync(build(x, p, chunk_rows=chunk_rows))
            tti = time.perf_counter() - t0
        entry = {
            "family": family, "rows": rows, "dim": dim,
            "n_lists": n_lists, "chunk_rows": chunk_rows,
            "perop_s": round(t_perop, 4),
            "pipelined_s": round(t_pipe, 4),
            "perop_rows_per_s": round(rows / t_perop),
            "pipelined_rows_per_s": round(rows / t_pipe),
            "speedup": round(t_perop / t_pipe, 3),
            "time_to_index_s": round(tti, 4),
        }
        if family == "ivf_pq":
            entry["pq_dim"], entry["pq_bits"] = p.pq_dim, p.pq_bits
        results.append(entry)
        print(json.dumps(entry), flush=True)

    out = {
        "bench": "build_throughput",
        "backend": backend,
        "mode": "quick" if quick else "full",
        "reps": REPS,
        "note": ("streaming-phase rows/s (training excluded — identical "
                 "in both engines); time_to_index_s is end-to-end "
                 "build_chunked incl. training at trainset_fraction="
                 f"{TRAIN_FRACTION}; results bit-identical across engines "
                 "(tests/test_chunked_builds.py)"),
        "results": results,
    }
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        f"BUILD_THROUGHPUT_{backend.upper()}.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=2)
        f.write("\n")
    print(f"wrote {path}")


if __name__ == "__main__":
    main()
